"""Flight recorder + trigger bus (utils/trace.py): always-on retention
at full fidelity regardless of the head sample, anomaly triggers →
incident bundles (breaker trip, shed spike, watch resume storm, pinned-
path recompile), cooldown rate-limiting, and the zero-configuration
end-to-end loop through ``with_telemetry(incident_dir=...)``."""

import json
import os
import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_latency_mode,
    with_telemetry,
)
from gochugaru_tpu.utils import faults, metrics, trace
from gochugaru_tpu.utils.admission import AdmissionConfig, CircuitBreaker
from gochugaru_tpu.utils.context import background

SCHEMA = """
definition user {}
definition doc { relation reader: user  permission read = reader }
"""


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


def _recorder(**kw):
    kw.setdefault("grace_s", 0.0)
    kw.setdefault("cooldown_s", 0.0)
    return trace.install_recorder(trace.FlightRecorder(**kw))


def _doc_client(*opts):
    c = new_tpu_evaluator(with_latency_mode(), *opts)
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    for i in range(16):
        txn.create(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i}"))
    c.write(ctx, txn)
    rs = [rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i}")
          for i in range(8)]
    return c, ctx, rs


# ---------------------------------------------------------------------------
# always-on retention
# ---------------------------------------------------------------------------


def test_flight_ring_retains_unsampled_at_full_fidelity():
    """sample_rate=0 head-drops every request from the export ring, but
    with a recorder installed the full span TREE still builds and lands
    in the flight ring — the 'regardless of the sample rate' contract."""
    tr = trace.configure(sample_rate=0.0, slow_threshold_s=None)
    rec = _recorder(capacity=8)
    c, ctx, rs = _doc_client()
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8
    assert tr.traces() == [], "export ring must stay head-sampled"
    flight = [t for t in rec.traces() if t["name"] == "check"]
    assert flight, "flight ring retained nothing"
    t = flight[-1]
    assert t["flight_only"] is True
    names = {sp["name"] for sp in t["spans"]}
    # full fidelity: the dispatch subtree, not a root-only stub
    assert {"check", "dispatch"} <= names
    assert metrics.default.counter("trace.flight_kept") > 0


def test_flight_ring_bounded_and_sampled_traces_ride_both_rings():
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=64)
    rec = _recorder(capacity=4)
    for i in range(10):
        trace.root_span("probe", i=i).end()
    assert len(rec.traces()) == 4  # ring bound
    assert [t["spans"][0]["attrs"]["i"] for t in rec.traces()] == [6, 7, 8, 9]
    assert len([t for t in tr.traces() if t["name"] == "probe"]) == 10
    assert all("flight_only" not in t for t in rec.traces())


def test_flight_only_slow_trace_exports_full_tree():
    """A flight-only trace that blows the slow threshold exports its
    FULL tree to /traces — strictly better than the root-only tail-kept
    stub the recorder-less path produces."""
    tr = trace.configure(sample_rate=0.0, slow_threshold_s=0.0)
    _recorder()
    sp = trace.root_span("check", batch=1)
    sp.child("dispatch").end()
    sp.end()
    kept = tr.traces()
    assert len(kept) == 1
    assert len(kept[0]["spans"]) == 2  # full tree, not root-only
    # the documented flag rides along: /traces consumers filtering on
    # tail_kept must see flight-only slow trees too
    assert kept[0]["tail_kept"] is True and kept[0]["flight_only"] is True
    assert metrics.default.counter("trace.tail_kept") > 0


def test_no_recorder_means_noop_unsampled_path():
    trace.configure(sample_rate=0.0, slow_threshold_s=None)
    n0 = trace.spans_created()
    assert trace.root_span("check") is trace.NOOP
    assert trace.spans_created() == n0


# ---------------------------------------------------------------------------
# the trigger bus
# ---------------------------------------------------------------------------


def test_trigger_captures_bundle_with_traces_metrics_context(tmp_path):
    m = metrics.Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    rec = _recorder(incident_dir=str(tmp_path), registry=m)
    rec.add_context("cost_model", lambda: {"overall_s": 0.001})
    rec.add_context("broken", lambda: 1 / 0)
    m.inc("checks.requested", 7)
    m.observe("checks.dispatch", 0.003)
    m.observe_hist("serve.request_latency", 0.02, (0.01, 0.1),
                   trace_id="tid-x")
    with trace.root_span("check", batch=2) as sp:
        sp.child("dispatch").set_attr("error", "UnavailableError").end()
    iid = trace.trigger_incident("breaker.trip", consecutive=3)
    assert iid is not None
    rec.flush()
    files = [f for f in os.listdir(tmp_path) if f.startswith("incident_")]
    assert len(files) == 1 and "breaker.trip" in files[0]
    lines = [json.loads(ln)
             for ln in (tmp_path / files[0]).read_text().splitlines()]
    head = lines[0]
    assert head["kind"] == "incident" and head["trigger"] == "breaker.trip"
    assert head["info"] == {"consecutive": 3}
    assert head["context"]["cost_model"] == {"overall_s": 0.001}
    # a broken provider records itself, never loses the bundle
    assert head["context"]["broken"] == {"provider_error": "ZeroDivisionError"}
    trs = [ln for ln in lines if ln["kind"] == "trace"]
    assert len(trs) == 1 and trs[0]["trace_id"] in head["trace_ids"]
    assert any("error" in (sp.get("attrs") or {})
               for sp in trs[0]["spans"])
    mt = next(ln for ln in lines if ln["kind"] == "metrics")
    assert mt["counters"]["checks.requested"] == 7
    assert "p99_s" in mt["timers"]["checks.dispatch"]
    hs = next(ln for ln in lines if ln["kind"] == "hists")
    assert hs["hists"]["serve.request_latency"]["exemplars"][1][0] == "tid-x"
    # the in-memory bundle serves identically (the /debug/incidents path)
    assert rec.bundle(iid) == (tmp_path / files[0]).read_text()
    idx = rec.incident_index()
    assert idx[-1]["state"] == "captured" and idx[-1]["traces"] == 1


def test_trigger_cooldown_rate_limits(tmp_path):
    m = metrics.Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    clock = [0.0]
    rec = trace.install_recorder(trace.FlightRecorder(
        incident_dir=str(tmp_path), grace_s=0.0, cooldown_s=30.0,
        registry=m, clock=lambda: clock[0],
    ))
    assert rec.trigger("breaker.trip") is not None
    assert rec.trigger("breaker.trip") is None  # suppressed
    assert m.counter("incidents.suppressed") == 1
    # a DIFFERENT trigger class is not suppressed
    assert rec.trigger("slo.burn") is not None
    clock[0] += 31.0
    assert rec.trigger("breaker.trip") is not None
    rec.flush()
    assert m.counter("incidents.captured") == 3


def test_note_spike_detector():
    m = metrics.Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    clock = [0.0]
    rec = trace.install_recorder(trace.FlightRecorder(
        grace_s=0.0, cooldown_s=0.0, registry=m,
        spike_threshold=5, spike_window_s=1.0, clock=lambda: clock[0],
    ))
    for _ in range(4):
        trace.note_anomaly("shed")
    assert not rec.incident_index()  # under threshold: no incident
    clock[0] += 2.0  # window expires — old notes must not count
    for _ in range(4):
        trace.note_anomaly("shed")
    assert not rec.incident_index()
    trace.note_anomaly("shed")  # 5th inside the window → spike
    rec.flush()
    idx = rec.incident_index()
    assert len(idx) == 1 and idx[0]["trigger"] == "shed.spike"
    assert idx[0]["info"]["count"] == 5


def test_trigger_freezes_ring_against_post_trigger_flood():
    """The freeze is synchronous: traces retained at trigger time must
    survive however much post-anomaly traffic floods the ring during
    the capture grace — they are the incident's evidence."""
    trace.configure(sample_rate=1.0, slow_threshold_s=None)
    rec = trace.install_recorder(trace.FlightRecorder(
        capacity=8, grace_s=0.2, cooldown_s=0.0,
    ))
    for i in range(8):
        trace.root_span("pre", i=i).end()
    assert rec.trigger("breaker.trip") is not None
    # flood: far more than the ring holds, all before the grace expires
    for i in range(100):
        trace.root_span("post", i=i).end()
    rec.flush()
    names = [t["name"] for t in
             [json.loads(ln) for ln in
              rec.bundle(rec.incident_index()[0]["id"]).splitlines()]
             if t["kind"] == "trace"]
    assert names.count("pre") == 8, names
    # late-finishing roots ride along AFTER the frozen evidence
    assert names.index("post") > names.index("pre")


def test_max_incidents_prunes_oldest_files(tmp_path):
    trace.configure(sample_rate=1.0, slow_threshold_s=None)
    rec = _recorder(incident_dir=str(tmp_path), max_incidents=2)
    for i in range(4):
        rec.trigger(f"t{i}")
        rec.flush()
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 2
    assert "t2" in files[0] and "t3" in files[1]


# ---------------------------------------------------------------------------
# anomaly-site wiring
# ---------------------------------------------------------------------------


def test_breaker_trip_fires_incident():
    m = metrics.Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    rec = _recorder(registry=m)
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, registry=m)
    br.record_failure()
    assert not rec.incident_index()
    br.record_failure()  # trips
    rec.flush()
    idx = rec.incident_index()
    assert len(idx) == 1 and idx[0]["trigger"] == "breaker.trip"
    assert idx[0]["info"] == {"consecutive": 2, "threshold": 2}


def test_gate_shed_burst_fires_spike():
    from gochugaru_tpu.utils.admission import DispatchGate
    from gochugaru_tpu.utils.errors import ShedError

    m = metrics.Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    rec = trace.install_recorder(trace.FlightRecorder(
        grace_s=0.0, cooldown_s=0.0, registry=m, spike_threshold=8,
    ))
    gate = DispatchGate(max_inflight=1, registry=m)
    with gate.admit():
        for _ in range(8):
            with pytest.raises(ShedError):
                with gate.admit():
                    pass
    rec.flush()
    assert [i["trigger"] for i in rec.incident_index()] == ["shed.spike"]


def test_watch_resume_storm_fires_incident():
    trace.configure(sample_rate=1.0, slow_threshold_s=None)
    rec = _recorder()
    c, ctx, _ = _doc_client()
    from gochugaru_tpu.rel.update import UpdateFilter

    wctx = ctx.with_cancel()
    stream = c.updates_since_revision(wctx, UpdateFilter(), "")
    got = []

    def consume():
        try:
            got.append(next(stream))
        except StopIteration:
            pass

    # every delivery attempt faults for 8 consecutive resumes — storm
    # threshold — then the stream recovers and delivers
    faults.arm("watch.stream", times=c.WATCH_STORM_RESUMES)
    t = threading.Thread(target=consume, daemon=True)
    t.start()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:storm", "reader", "user:u0"))
    c.write(ctx, txn)
    t.join(timeout=30)
    wctx.cancel()
    assert got, "stream never recovered"
    rec.flush()
    storms = [i for i in rec.incident_index()
              if i["trigger"] == "watch.resume_storm"]
    assert len(storms) == 1
    assert storms[0]["info"]["no_progress"] == c.WATCH_STORM_RESUMES


def test_latency_retrace_detection_fires_incident():
    """A fresh compile for a (slots, tier, qctx) combo this path already
    served warm means a pinned executable was LOST — the runtime alarm
    for the no-retrace invariant.  Forced here by evicting the pin
    caches under the path."""
    trace.configure(sample_rate=1.0, slow_threshold_s=None)
    rec = _recorder()
    c, ctx, rs = _doc_client()
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8  # warm
    engine = c._engine
    snap = c._store.snapshot_for(consistency.full())
    dsnap = c._dsnap_for(engine, snap)
    lp = engine.latency_path(dsnap)
    assert lp.dispatch_count > 0 and lp._served_keys
    with engine._latency_pins_lock:
        engine._latency_pins.clear()
    lp._local.clear()
    assert c.check(ctx, consistency.full(), *rs) == [True] * 8  # recompiles
    rec.flush()
    idx = [i for i in rec.incident_index()
           if i["trigger"] == "latency.retrace"]
    assert len(idx) == 1
    assert metrics.default.counter("latency.retraces") == 1.0


# ---------------------------------------------------------------------------
# end-to-end: the acceptance loop (zero config beyond incident_dir)
# ---------------------------------------------------------------------------


def test_fault_storm_produces_bundle_with_failing_dispatch_traces(tmp_path):
    """The ISSUE's acceptance criterion: armed chaos sites trip the
    breaker under traffic and an incident bundle appears — containing
    the failing dispatch spans — with no configuration beyond
    ``with_telemetry(incident_dir=...)``."""
    c, ctx, rs = _doc_client(
        with_admission_control(AdmissionConfig(
            breaker_threshold=2, breaker_cooldown_s=60.0,
        )),
        with_telemetry(port=0, incident_dir=str(tmp_path)),
    )
    try:
        # zero-config wiring: tracer (0% head sample) + recorder + SLO
        assert trace.enabled() and trace.recorder() is c.recorder
        assert c.slo is not None and c.telemetry is not None
        assert c.check(ctx, consistency.full(), *rs) == [True] * 8  # warm
        faults.arm("latency.dispatch", times=2)
        # the retry envelope absorbs both injected faults; the second
        # consecutive failure trips the breaker mid-request
        assert c.check(ctx, consistency.full(), *rs) == [True] * 8
        assert metrics.default.counter("breaker.trips") >= 1
        deadline = time.time() + 20
        bundle = None
        while bundle is None and time.time() < deadline:
            c.recorder.flush()
            hits = [f for f in os.listdir(tmp_path)
                    if "breaker.trip" in f]
            if hits:
                bundle = tmp_path / hits[0]
                break
            time.sleep(0.1)
        assert bundle is not None, "no incident bundle appeared"
        lines = [json.loads(ln)
                 for ln in bundle.read_text().splitlines()]
        head = lines[0]
        traces = [ln for ln in lines if ln["kind"] == "trace"]
        offending = [
            t["trace_id"] for t in traces
            if any("error" in (sp.get("attrs") or {}) for sp in t["spans"])
        ]
        assert offending, "bundle lacks the failing dispatch traces"
        assert set(offending) <= set(head["trace_ids"])
        # providers are keyed per telemetry client on the shared
        # recorder (first client bare, later ones #N-suffixed)
        ctx_keys = head["context"]
        adm_key = next(k for k in ctx_keys if k.startswith("admission"))
        assert any(k.startswith("cost_model") for k in ctx_keys)
        assert ctx_keys[adm_key]["breaker_state"] == 2
    finally:
        if c.slo is not None:
            c.slo.close()
        c.telemetry.close()


def test_with_telemetry_shares_one_slo_engine_and_overrides_incident_dir(
    tmp_path,
):
    """Two with_telemetry clients in one process must share ONE SLO
    engine (they write the same slo.* gauges — two evaluators would
    fight and double-fire breach edges), and a later explicit
    incident_dir must WIN over the shared recorder's earlier one."""
    from gochugaru_tpu.utils import slo as _slo

    c1 = new_tpu_evaluator(
        with_telemetry(port=0, incident_dir=str(tmp_path / "a"))
    )
    c2 = new_tpu_evaluator(
        with_telemetry(port=0, incident_dir=str(tmp_path / "b"))
    )
    try:
        assert c1.slo is c2.slo and c2.slo is _slo.get_engine()
        assert c1.recorder is c2.recorder
        # the later caller's explicit dir took over
        assert c2.recorder.incident_dir == str(tmp_path / "b")
        # each client's context providers coexist on the shared
        # recorder (suffixed keys) — c2 must not clobber c1's
        adm_keys = [k for k in c1.recorder._context
                    if k.startswith("admission")]
        assert len(adm_keys) == 2
        # slos=() DISABLES: the shared engine actually stops
        eng = c1.slo
        c3 = new_tpu_evaluator(with_telemetry(port=0, slos=()))
        try:
            assert c3.slo is None and _slo.get_engine() is None
            assert eng._stop.is_set(), "disable must close the engine"
            # ...and a closed engine clears its slo.* gauges (a stale
            # breached=1 would page forever on /metrics)
            from gochugaru_tpu.utils import metrics as _m

            assert not any(
                k.startswith("slo.") for k in _m.default._gauges
            )
        finally:
            c3.telemetry.close()
    finally:
        _slo.install_engine(None)
        c1.telemetry.close()
        c2.telemetry.close()

"""Workload-adaptive self-tuning (gochugaru_tpu/tune/): the offline
tuner's fixed-point and JSON round-trip contracts, the no-retrace and
parity invariants on tuned NON-pow2 tier ladders, and the online
controller's safety envelope — hysteresis, cooldown, bounded-move
convergence, the oscillation tripwire (flight-recorder incident), and
one-call revert to preset."""

from dataclasses import replace

import numpy as np
import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_engine_config,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.latency import tier_for
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.serve import ServeConfig
from gochugaru_tpu.tune import (
    OnlineController,
    TuneDiff,
    TuneTarget,
    apply_diff,
    collect_snapshot,
    propose,
)
from gochugaru_tpu.utils import metrics, perf, trace
from gochugaru_tpu.utils.context import background

from tests.test_latency_path import EPOCH, build_rbac_world, _random_queries

#: a ladder the offline tuner could emit: nothing pow2-aligned
TUNED_TIERS = (192, 576, 1344)


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


def _synthetic_registry():
    """A registry describing a workload with an oversized 1024 tier,
    clock-bound flushes, and near-zero duplicate checks."""
    m = metrics.Metrics()
    for _ in range(40):
        m.observe_hist(
            "serve.occupancy.t1024", 120.0, (64, 128, 256, 512, 1024)
        )
        m.inc("serve.flush_maxhold")
    for _ in range(4):
        m.inc("serve.flush_full")
    m.inc("serve.checks", 1000)
    m.inc("serve.unique_checks", 990)
    return m


# ---------------------------------------------------------------------------
# offline tuner
# ---------------------------------------------------------------------------

def test_propose_fixed_point_and_json_roundtrip():
    """Applying a proposed diff and re-proposing against the SAME
    snapshot yields the empty diff (fixed point), and the diff survives
    JSON serialization bit-for-bit."""
    m = _synthetic_registry()
    eng = EngineConfig(latency_tiers=(256, 1024, 4096))
    srv = ServeConfig()
    snap = collect_snapshot(m, engine_config=eng, serve_config=srv)
    target = TuneTarget(engine=eng, serve=srv, cache_bytes=None)
    diff = propose(snap, target)
    assert diff, "the synthetic workload must produce proposals"
    knobs = {k.knob for k in diff.knobs}
    assert "latency_tiers" in knobs and "hold_max_s" in knobs
    for k in diff.knobs:
        assert k.evidence, f"{k.knob} proposal carries no evidence"
        assert k.predicted, f"{k.knob} proposal carries no prediction"
    tuned = apply_diff(target, diff)
    assert not propose(snap, tuned), "re-propose after apply must be empty"
    rt = TuneDiff.from_json(diff.to_json())
    assert rt == diff


def test_propose_quiet_on_thin_evidence():
    """An empty registry (no samples anywhere) proposes nothing — the
    tuner never moves a knob without measured evidence."""
    m = metrics.Metrics()
    snap = collect_snapshot(
        m, engine_config=EngineConfig(), serve_config=ServeConfig()
    )
    assert not propose(
        snap,
        TuneTarget(engine=EngineConfig(), serve=ServeConfig(),
                   cache_bytes=None),
    )


def test_pallas_rule_proposes_from_byte_model_and_fixed_point():
    """The pallas knob follows the flat_packed discipline: evidence is
    the one-pass byte model prepare publishes (gauges in the measured
    registry), the proposal carries the saved fraction, and applying it
    reaches the fixed point (re-propose is empty because the tuned
    target resolves to the proposed backend)."""
    from gochugaru_tpu.engine import pallas as P

    if not P.available():  # pragma: no cover - env without pallas
        pytest.skip("jax.experimental.pallas unavailable")
    m = metrics.Metrics()
    m.set_gauge("perf.pallas.bytes_per_check", 300.0)
    m.set_gauge("perf.pallas.bytes_saved_per_check", 900.0)  # 75% saved
    eng = EngineConfig(pallas=False)
    snap = collect_snapshot(m, engine_config=eng, serve_config=ServeConfig())
    assert snap["config"]["pallas_resolved"] is False
    target = TuneTarget(engine=eng, serve=ServeConfig(), cache_bytes=None)
    diff = propose(snap, target)
    kd = next(k for k in diff.knobs if k.knob == "pallas")
    assert kd.layer == "engine" and kd.proposed is True
    assert "byte model" in kd.evidence
    assert kd.predicted["bytes_per_check_frac"] == pytest.approx(-0.75)
    tuned = apply_diff(target, diff)
    assert tuned.engine.pallas is True
    assert not propose(snap, tuned), "re-propose after apply must be empty"


def test_pallas_rule_vetoes_on_degrade_and_silent_without_model():
    """A runtime degrade (pallas.degraded counter) vetoes the backend
    even when the model looks great; with no fused prepare measured the
    rule stays silent rather than guessing."""
    m = metrics.Metrics()
    m.set_gauge("perf.pallas.bytes_per_check", 300.0)
    m.set_gauge("perf.pallas.bytes_saved_per_check", 900.0)
    m.inc("pallas.degraded")
    eng = EngineConfig(pallas=True)
    snap = collect_snapshot(m, engine_config=eng, serve_config=ServeConfig())
    target = TuneTarget(engine=eng, serve=ServeConfig(), cache_bytes=None)
    diff = propose(snap, target)
    kd = next(k for k in diff.knobs if k.knob == "pallas")
    assert kd.proposed is False and "vetoed" in kd.evidence
    assert apply_diff(target, diff).engine.pallas is False
    # no fused prepare measured (gauges unset): silent on the knob
    m2 = metrics.Metrics()
    snap2 = collect_snapshot(
        m2, engine_config=EngineConfig(), serve_config=ServeConfig()
    )
    assert not any(
        k.knob == "pallas"
        for k in propose(
            snap2,
            TuneTarget(engine=EngineConfig(), serve=ServeConfig(),
                       cache_bytes=None),
        ).knobs
    )


def test_tiers_rule_emits_non_pow2():
    """The ladder rule quantizes to 64-lane multiples, not powers of
    two: a tier whose p90 occupancy is 131 proposes 320 (p90 × 2.0
    burst headroom, rounded up to the 64-lane quantum)."""
    m = metrics.Metrics()
    for _ in range(32):
        m.observe_hist(
            "serve.occupancy.t1024", 131.0,
            (64, 131, 256, 512, 1024),
        )
    eng = EngineConfig(latency_tiers=(1024, 4096))
    snap = collect_snapshot(m, engine_config=eng, serve_config=ServeConfig())
    diff = propose(
        snap, TuneTarget(engine=eng, serve=ServeConfig(), cache_bytes=None)
    )
    kd = diff.get("latency_tiers")
    assert kd is not None
    assert 320 in kd.proposed, kd.proposed
    assert "131" in kd.evidence  # the measured number is in the story


def test_tiers_rule_inserts_below_shared_tier():
    """When the pad ledger shows non-batcher dispatches (direct calls,
    coalesced-answer sampling) still filling a rung the batcher leaves
    near-empty, the rule INSERTS the small tier instead of replacing —
    the ladder serves every dispatch path, not just the batcher's."""
    m = metrics.Metrics()
    for _ in range(32):
        m.observe_hist(
            "serve.occupancy.t1024", 20.0, (64, 131, 256, 512, 1024)
        )
    # 40 non-batcher dispatches at ~800 live lanes on the same tier
    for _ in range(40):
        perf.record_pad(1024, 800, m)
    # and the batcher's own 32 dispatches flow through the ledger too
    for _ in range(32):
        perf.record_pad(1024, 20, m)
    eng = EngineConfig(latency_tiers=(1024, 4096))
    snap = collect_snapshot(m, engine_config=eng, serve_config=ServeConfig())
    diff = propose(
        snap, TuneTarget(engine=eng, serve=ServeConfig(), cache_bytes=None)
    )
    kd = diff.get("latency_tiers")
    assert kd is not None
    assert kd.proposed == (128, 1024, 4096), kd.proposed
    assert "insert" in kd.evidence and "stays" in kd.evidence


# ---------------------------------------------------------------------------
# tuned non-pow2 ladders keep the latency-path contracts
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tuned_world():
    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(
        cs, EngineConfig.for_schema(cs, latency_tiers=TUNED_TIERS)
    )
    dsnap = engine.prepare(snap)
    return engine, dsnap, snap, users, repos, slot


def test_nonpow2_tier_for_routing():
    assert tier_for(TUNED_TIERS, 1) == 192
    assert tier_for(TUNED_TIERS, 192) == 192
    assert tier_for(TUNED_TIERS, 193) == 576
    assert tier_for(TUNED_TIERS, 1344) == 1344
    assert tier_for(TUNED_TIERS, 1345) is None


def test_nonpow2_ladder_no_retrace_and_parity(tuned_world):
    """110 warm dispatches on a tuned (192, 576, 1344) ladder pay zero
    additional compiles and zero ``latency.retraces``, with answers
    identical to the throughput path."""
    engine, dsnap, snap, users, repos, slot = tuned_world
    lp = engine.latency_path(dsnap)
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 500, seed=23)
    retr0 = metrics.default.counter("latency.retraces")
    out = lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    assert out is not None
    assert lp.last_budget.tier == 576
    warm = lp.compile_count
    for i in range(110):
        d, p, o = lp.dispatch_columns(
            np.roll(q_res, i), q_perm, np.roll(q_subj, i), now_us=EPOCH
        )
        if i % 37 == 0:
            dd, pp, oo = engine.check_columns(
                dsnap, np.roll(q_res, i), q_perm, np.roll(q_subj, i),
                now_us=EPOCH,
            )
            assert (d == dd).all() and (p == pp).all() and (o == oo).all()
    assert lp.compile_count == warm, "non-pow2 ladder retraced"
    assert metrics.default.counter("latency.retraces") == retr0
    # a second tier of the tuned ladder also pins and stays warm
    lp.dispatch_columns(q_res[:100], q_perm[:100], q_subj[:100], now_us=EPOCH)
    assert lp.last_budget.tier == 192
    warm2 = lp.compile_count
    lp.dispatch_columns(q_res[:150], q_perm[:150], q_subj[:150], now_us=EPOCH)
    assert lp.compile_count == warm2


def test_nonpow2_ladder_pin_reuse_across_prepares(tuned_world):
    """Re-preparing the same geometry re-pins tuned-tier executables
    from the engine-wide cache with zero new compiles."""
    engine, dsnap, snap, users, repos, slot = tuned_world
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 150, seed=29)
    lp = engine.latency_path(dsnap)
    lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    dsnap2 = engine.prepare(snap)
    lp2 = engine.latency_path(dsnap2)
    out = lp2.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    assert out is not None
    assert lp2.compile_count == 0, "tuned-tier pins were not shared"


def test_serving_on_tuned_ladder_parity_and_occupancy():
    """A serving handle over a tuned non-pow2 ladder answers exactly
    like the host oracle, records per-tier occupancy histograms for the
    tuned tiers, and never retraces."""
    cfg = replace(EngineConfig(), latency_tiers=(48, 192, 576))
    c = new_tpu_evaluator(with_latency_mode(), with_engine_config(cfg))
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """)
    txn = rel.Txn()
    for i in range(40):
        txn.touch(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i % 9}"))
    c.write(ctx, txn)
    oracle = new_tpu_evaluator(
        with_host_only_evaluation(), with_store(c.store)
    )
    from gochugaru_tpu import consistency
    cs = consistency.full()
    rng = np.random.default_rng(31)
    retr0 = metrics.default.counter("latency.retraces")
    with c.with_serving() as h:
        for _ in range(12):
            qs = [
                rel.must_from_triple(
                    f"doc:d{rng.integers(40)}", "read",
                    f"user:u{rng.integers(9)}",
                )
                for _ in range(6)
            ]
            assert list(h.check(ctx, *qs)) == list(oracle.check(ctx, cs, *qs))
    assert metrics.default.counter("latency.retraces") == retr0
    occ = [
        n for n in metrics.default.hist_snapshot()
        if n.startswith("serve.occupancy.t")
    ]
    assert "serve.occupancy.t48" in occ, occ


# ---------------------------------------------------------------------------
# online controller
# ---------------------------------------------------------------------------

class FakeBatcher:
    def __init__(self, **kw):
        self.config = ServeConfig(**kw)
        self._top = 4096
        self.applies = 0

    def apply_config(self, cfg):
        self.config = cfg
        self.applies += 1


class FakeVcache:
    def __init__(self, max_bytes):
        self.max_bytes = max_bytes

    def set_max_bytes(self, n):
        self.max_bytes = int(n)


def _deadline_window(m, n=10):
    for _ in range(n):
        m.inc("serve.flush_deadline")


def test_controller_hysteresis_dead_band():
    """Mid-band signals (no watermark crossed) move nothing, tick after
    tick — the controller holds still on ambiguous evidence."""
    m = metrics.Metrics()
    b = FakeBatcher()
    c = OnlineController(b, registry=m, cooldown_steps=0)
    for _ in range(5):
        # 50% maxhold / 20% deadline at 40% fill: inside every dead band
        for _ in range(5):
            m.inc("serve.flush_maxhold")
        for _ in range(2):
            m.inc("serve.flush_deadline")
        for _ in range(3):
            m.inc("serve.flush_full")
        for _ in range(4):
            m.observe_hist(
                "serve.occupancy.t1024", 410.0, (64, 128, 256, 512, 1024)
            )
        assert c.step() == 0
    assert b.applies == 0 and b.config == ServeConfig()


def test_controller_cooldown_blocks_next_move():
    m = metrics.Metrics()
    b = FakeBatcher()
    c = OnlineController(b, registry=m, cooldown_steps=1)
    _deadline_window(m)
    assert c.step() == 1 and b.config.hold_max_s == 0.001
    _deadline_window(m)
    assert c.step() == 0, "cooldown must block the very next tick"
    _deadline_window(m)
    assert c.step() == 1 and b.config.hold_max_s == 0.0005


def test_controller_converges_bounded_under_load_shift():
    """A sustained deadline-heavy shift walks hold down the ladder one
    bounded step per eligible tick, stops at the clamp, and never moves
    again under the same signal — convergence, not hunting."""
    m = metrics.Metrics()
    b = FakeBatcher()
    c = OnlineController(b, registry=m, cooldown_steps=0,
                         hold_bounds=(0.0005, 0.008))
    trajectory = [b.config.hold_max_s]
    for _ in range(8):
        _deadline_window(m)
        c.step()
        trajectory.append(b.config.hold_max_s)
    # monotone, bounded steps (each move is one ladder rung), clamped
    assert trajectory[0] == 0.002
    assert all(a >= z for a, z in zip(trajectory, trajectory[1:]))
    assert trajectory[-1] == 0.0005
    assert c.moves == 2  # 0.002 -> 0.001 -> 0.0005, then parked
    assert m.counter("tune.moves") == 2
    assert m.gauge("tune.hold_max_s") == 0.0005
    assert "hold_max_s" not in c._frozen


def test_controller_cache_knob_grow_shrink_clamped():
    m = metrics.Metrics()
    b = FakeBatcher()
    vc = FakeVcache(32 << 20)
    c = OnlineController(b, vcache=vc, registry=m, cooldown_steps=0,
                         cache_bounds=(16 << 20, 64 << 20))
    # hot + full + evicting -> grow x2
    m.inc("cache.hits", 50)
    m.inc("cache.misses", 50)
    m.inc("cache.evicted_revisions", 2)
    m.set_gauge("cache.bytes", float(int(0.9 * (32 << 20))))
    assert c.step() == 1 and vc.max_bytes == 64 << 20
    # still hot + full -> clamped at the ceiling, no further move
    m.inc("cache.hits", 50)
    m.inc("cache.misses", 50)
    m.inc("cache.evicted_revisions", 2)
    m.set_gauge("cache.bytes", float(int(0.9 * (64 << 20))))
    assert c.step() == 0
    # cold + idle -> shrink toward (and clamp at) the floor
    for _ in range(3):
        m.inc("cache.misses", 100)
        m.set_gauge("cache.bytes", 1024.0)
        c.step()
    assert vc.max_bytes == 16 << 20
    assert m.gauge("tune.vcache_bytes") == float(16 << 20)


def test_controller_dedup_off_only_on_measured_uniqueness():
    m = metrics.Metrics()
    b = FakeBatcher()
    c = OnlineController(b, registry=m, cooldown_steps=0)
    # heavy duplication: dedup stays on
    m.inc("serve.checks", 1000)
    m.inc("serve.unique_checks", 700)
    assert c.step() == 0 and b.config.dedup is True
    # near-total uniqueness: dedup turns off (and cannot turn back on)
    m.inc("serve.checks", 1000)
    m.inc("serve.unique_checks", 999)
    assert c.step() == 1 and b.config.dedup is False
    m.inc("serve.checks", 1000)  # no unique counting once off
    assert c.step() == 0 and b.config.dedup is False


def test_controller_oscillation_trips_incident_and_freezes():
    """Alternating raise/lower pressure flips the hold knob until the
    tripwire freezes it and captures a flight-recorder incident."""
    m = metrics.Metrics()
    rec = trace.install_recorder(
        trace.FlightRecorder(grace_s=0.0, cooldown_s=0.0)
    )
    b = FakeBatcher()
    c = OnlineController(b, registry=m, cooldown_steps=0, osc_flips=3)
    for i in range(12):
        if "hold_max_s" in c._frozen:
            break
        if i % 2 == 0:
            _deadline_window(m)  # pressure down
        else:  # pressure up: maxhold-bound at high fill
            for _ in range(10):
                m.inc("serve.flush_maxhold")
            for _ in range(5):
                m.observe_hist(
                    "serve.occupancy.t1024", 900.0,
                    (64, 128, 256, 512, 1024),
                )
        c.step()
    assert "hold_max_s" in c._frozen
    assert m.counter("tune.oscillations") >= 1
    assert m.gauge("tune.frozen_knobs") == 1.0
    assert any(
        i["trigger"] == "tune.oscillation" for i in rec.incident_index()
    )
    # frozen means frozen: the same pressure moves nothing
    held = b.config.hold_max_s
    _deadline_window(m)
    assert c.step() == 0 and b.config.hold_max_s == held


def test_controller_revert_restores_preset():
    m = metrics.Metrics()
    b = FakeBatcher()
    vc = FakeVcache(32 << 20)
    c = OnlineController(b, vcache=vc, registry=m, cooldown_steps=0)
    _deadline_window(m)
    c.step()
    m.inc("serve.checks", 1000)
    m.inc("serve.unique_checks", 999)
    c.step()
    for _ in range(3):
        m.inc("cache.misses", 100)
        m.set_gauge("cache.bytes", 1024.0)
        c.step()
    c._frozen.add("hold_max_s")
    assert b.config.hold_max_s != 0.002 or not b.config.dedup
    c.revert()
    assert b.config == ServeConfig()
    assert vc.max_bytes == 32 << 20
    assert c._frozen == set()
    assert m.counter("tune.reverts") == 1
    assert m.gauge("tune.hold_max_s") == 0.002
    assert m.gauge("tune.dedup") == 1.0
    # after revert the controller may move again (history cleared)
    _deadline_window(m)
    assert c.step() == 1

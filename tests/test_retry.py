"""The retry envelope itself (utils/retry.py): jitter bounds, interval
cap, deadline clamping, cancellation honesty, and PermanentError
unwrapping — all with an injected fake sleep so no test actually waits.

The envelope mirrors the reference exactly (client/client.go:205-210,
cenkalti/backoff defaults: initial 50 ms, multiplier 1.5, randomization
0.5, max 2 s, bounded by the context deadline); these tests pin the
numbers so a refactor cannot silently drift them.
"""

import time

import pytest

from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    CancelledError,
    DeadlineExceededError,
    PermanentError,
    UnavailableError,
)
from gochugaru_tpu.utils.retry import (
    INITIAL_INTERVAL,
    MAX_INTERVAL,
    MULTIPLIER,
    RANDOMIZATION_FACTOR,
    retry_retriable_errors,
)


def _failing_fn(failures: int):
    """A fn that raises UnavailableError ``failures`` times, then returns."""
    state = {"calls": 0}

    def fn():
        state["calls"] += 1
        if state["calls"] <= failures:
            raise UnavailableError(f"transient #{state['calls']}")
        return "ok"

    return fn, state


def test_jitter_stays_within_randomization_band():
    """Every pause lies in [interval·(1−RF), interval·(1+RF)] for the
    unclamped ladder interval of its attempt."""
    pauses = []
    fn, _ = _failing_fn(8)
    assert retry_retriable_errors(background(), fn, sleep=pauses.append) == "ok"
    assert len(pauses) == 8
    interval = INITIAL_INTERVAL
    for p in pauses:
        lo = interval * (1 - RANDOMIZATION_FACTOR)
        hi = interval * (1 + RANDOMIZATION_FACTOR)
        assert lo <= p <= hi, (p, lo, hi)
        interval = min(interval * MULTIPLIER, MAX_INTERVAL)


def test_interval_caps_at_max_interval():
    """Deep ladders stop growing: late pauses are bounded by
    MAX_INTERVAL·(1+RF) and the underlying interval by MAX_INTERVAL."""
    pauses = []
    fn, _ = _failing_fn(25)
    retry_retriable_errors(background(), fn, sleep=pauses.append)
    # by attempt k the unclamped interval is INITIAL·MULT^k capped at MAX
    assert max(pauses) <= MAX_INTERVAL * (1 + RANDOMIZATION_FACTOR)
    # the tail attempts must actually reach the cap region
    assert max(pauses[-5:]) > MAX_INTERVAL * (1 - RANDOMIZATION_FACTOR) * 0.9


def test_backoff_never_sleeps_past_deadline():
    """With a context deadline, every pause is clamped to the remaining
    budget at the moment it is computed."""
    budget = 0.12
    ctx = background().with_timeout(budget)
    t0 = time.monotonic()
    pauses = []

    def sleep(p):
        pauses.append((p, time.monotonic()))
        time.sleep(p)  # real (short) sleep so the deadline advances

    fn, _ = _failing_fn(100)
    with pytest.raises(DeadlineExceededError):
        retry_retriable_errors(ctx, fn, sleep=sleep)
    dl = t0 + budget
    for p, at in pauses:
        assert p <= max(dl - at, 0.0) + 0.01, (p, dl - at)
    # and the whole envelope respected the deadline (+ small scheduling slop)
    assert time.monotonic() - t0 <= budget + 0.2


def test_zero_length_pause_is_skipped(monkeypatch):
    """A deadline clamp producing pause == 0 must not call sleep at all
    (an injected fake sleep observes no zero-length pauses).  The
    envelope's clock is steered so the deadline check sees remaining
    budget but the clamp sees exactly none — the racy instant the
    satellite fix covers."""
    import types

    import gochugaru_tpu.utils.retry as retry_mod

    ctx = background().with_timeout(10.0)
    dl = ctx.deadline()
    # retry's own clock: first call (the deadline check) still inside the
    # budget, second call (the clamp) exactly at the deadline → pause 0.
    seq = iter([dl - 1.0, dl])
    fake = types.SimpleNamespace(monotonic=lambda: next(seq, dl))
    monkeypatch.setattr(retry_mod, "time", fake)

    pauses = []
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] == 1:
            raise UnavailableError("transient")
        return "ok"

    assert retry_retriable_errors(ctx, fn, sleep=pauses.append) == "ok"
    assert pauses == []  # the zero-length pause never reached sleep
    assert calls["n"] == 2


def test_cancellation_after_pause_surfaces_before_next_attempt():
    """A cancellation landing during the backoff pause raises before
    fn() is attempted again."""
    ctx = background().with_cancel()
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise UnavailableError("transient")

    def sleep(p):
        ctx.cancel()  # cancellation arrives mid-backoff

    with pytest.raises(CancelledError):
        retry_retriable_errors(ctx, fn, sleep=sleep)
    assert calls["n"] == 1  # no second attempt after the cancelled pause


def test_default_pause_is_context_aware():
    """Without an injected sleep, the pause is ctx.wait — a cancellation
    from another thread interrupts the backoff instead of waiting it
    out.  Uses a failure deep enough in the ladder that the pause would
    be ~2 s if not interrupted."""
    import threading

    ctx = background().with_cancel()
    fn, state = _failing_fn(100)
    threading.Timer(0.15, ctx.cancel).start()
    t0 = time.monotonic()
    with pytest.raises(CancelledError):
        retry_retriable_errors(ctx, fn)
    # the ladder reaches ~0.17s pauses by try 4; an uninterruptible sleep
    # chain would overshoot well past the cancel point
    assert time.monotonic() - t0 < 1.0


def test_permanent_error_unwrap_preserves_cause_chain():
    """PermanentError unwraps to its __cause__, and that cause keeps its
    own __cause__ chain intact."""
    root = KeyError("root")
    mid = ValueError("mid")
    mid.__cause__ = root

    def fn():
        raise PermanentError("wrapped") from mid

    with pytest.raises(ValueError) as ei:
        retry_retriable_errors(background(), fn, sleep=lambda s: None)
    assert ei.value is mid
    assert ei.value.__cause__ is root


def test_max_tries_bounds_retries():
    fn, state = _failing_fn(100)
    with pytest.raises(UnavailableError):
        retry_retriable_errors(
            background(), fn, sleep=lambda s: None, max_tries=4
        )
    assert state["calls"] == 4

"""Latency-mode execution path (engine/latency.py): differential parity
against the host oracle, the no-retrace pin invariant, tier routing, and
the budget-breakdown smoke the CI tier runs so the path can't silently
rot between bench runs."""

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.rel.txn import Txn
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot_from_columns
from gochugaru_tpu.utils import metrics
from gochugaru_tpu.utils.context import background

RBAC_SCHEMA = """
definition user {}
definition team { relation member: user }
definition org {
    relation admin: user
    relation member: user | team#member
}
definition repo {
    relation org: org
    relation maintainer: user | team#member
    relation reader: user
    permission admin = org->admin + maintainer
    permission read = reader + admin + org->member
}
"""

EPOCH = 1_700_000_000_000_000


def build_rbac_world(n_users=40, n_teams=4, n_orgs=3, n_repos=25, seed=7):
    cs = compile_schema(parse_schema(RBAC_SCHEMA))
    interner = Interner()
    rng = np.random.default_rng(seed)
    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    teams = np.array([interner.node("team", f"t{i}") for i in range(n_teams)], np.int64)
    orgs = np.array([interner.node("org", f"o{i}") for i in range(n_orgs)], np.int64)
    repos = np.array([interner.node("repo", f"r{i}") for i in range(n_repos)], np.int64)
    slot = cs.slot_of_name
    res, rel_s, subj, srel = [], [], [], []

    def add(r, rl, s, sr):
        res.append(r); rel_s.append(rl); subj.append(s); srel.append(sr)

    for t in teams:
        for u in rng.choice(users, 6, replace=False):
            add(t, slot["member"], u, -1)
    for o in orgs:
        add(o, slot["admin"], rng.choice(users), -1)
        add(o, slot["member"], rng.choice(teams), slot["member"])
        for u in rng.choice(users, 3, replace=False):
            add(o, slot["member"], u, -1)
    for r in repos:
        add(r, slot["org"], rng.choice(orgs), -1)
        add(r, slot["maintainer"], rng.choice(teams), slot["member"])
        add(r, slot["reader"], rng.choice(users), -1)
    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=np.asarray(res, np.int64), rel=np.asarray(rel_s, np.int64),
        subj=np.asarray(subj, np.int64), srel=np.asarray(srel, np.int64),
        epoch_us=EPOCH,
    )
    return cs, snap, users, repos, slot


@pytest.fixture(scope="module")
def rbac_world():
    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    return engine, dsnap, snap, users, repos, slot


def _random_queries(users, repos, slot, B, seed):
    rng = np.random.default_rng(seed)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(
        np.array([slot["read"], slot["admin"]], np.int32), B
    )
    q_subj = rng.choice(users, B).astype(np.int32)
    return q_res, q_perm, q_subj


def test_latency_path_parity_rbac(rbac_world):
    """Latency-path planes == throughput-path planes == oracle verdicts
    on the RBAC world."""
    engine, dsnap, snap, users, repos, slot = rbac_world
    from gochugaru_tpu.engine.oracle import SnapshotOracle, T

    oracle = SnapshotOracle(snap, {})
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 200, seed=3)
    d0, p0, o0 = engine.check_columns(
        dsnap, q_res, q_perm, q_subj, now_us=EPOCH
    )
    d1, p1, o1 = engine.check_columns_latency(
        dsnap, q_res, q_perm, q_subj, now_us=EPOCH
    )
    assert (d0 == d1).all() and (p0 == p1).all() and (o0 == o1).all()
    # and against ground truth, resolving the possible plane like the
    # client does (no caveats in this schema: d is the verdict when the
    # device didn't overflow)
    perm_name = {slot["read"]: "read", slot["admin"]: "admin"}
    for i in range(q_res.shape[0]):
        if o1[i] or (p1[i] and not d1[i]):
            continue  # host-resolved slice: not the device's verdict
        rtype, rid = snap.interner.key_of(int(q_res[i]))
        stype, sid = snap.interner.key_of(int(q_subj[i]))
        r = rel.must_from_triple(
            f"{rtype}:{rid}", perm_name[int(q_perm[i])], f"{stype}:{sid}"
        )
        assert bool(d1[i]) == (oracle.check_relationship(r) == T), r


def test_latency_mode_client_parity_founders():
    """A with_latency_mode client answers the founders-world checks
    exactly like a host-only (oracle) client sharing the same store."""
    lat_client = new_tpu_evaluator(with_latency_mode())
    ctx = background()
    lat_client.write_schema(ctx, """
    definition user {}
    definition document {
        relation founder: user
        permission view = founder
    }
    """)
    txn = Txn()
    for name in ("jake", "joey", "jimmy"):
        txn.touch(rel.must_from_triple("document:readme", "founder", f"user:{name}"))
    lat_client.write(ctx, txn)
    oracle_client = new_tpu_evaluator(
        with_host_only_evaluation(), with_store(lat_client.store)
    )
    cs = consistency.full()
    checks = [
        rel.must_from_triple("document:readme", "view", f"user:{n}")
        for n in ("jake", "joey", "jimmy", "judas", "jeb")
    ] + [rel.must_from_triple("document:readme", "founder", "user:jake")]
    before = metrics.default.counter("latency.dispatches")
    got = lat_client.check(ctx, cs, *checks)
    want = oracle_client.check(ctx, cs, *checks)
    assert got == want == [True, True, True, False, False, True]
    assert metrics.default.counter("latency.dispatches") > before, (
        "latency mode was configured but the latency path never ran"
    )


def test_latency_path_no_retrace_warm(rbac_world):
    """≥100 warm dispatches at one tier with VARYING query contents pay
    ZERO additional compiles — the pinned-executable invariant."""
    engine, dsnap, snap, users, repos, slot = rbac_world
    lp = engine.latency_path(dsnap)
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 700, seed=11)
    d_ref, p_ref, o_ref = engine.check_columns(
        dsnap, q_res, q_perm, q_subj, now_us=EPOCH
    )
    out = lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    assert out is not None
    warm_compiles = lp.compile_count
    assert warm_compiles >= 1
    for i in range(110):
        d, p, o = lp.dispatch_columns(
            np.roll(q_res, i), q_perm, np.roll(q_subj, i), now_us=EPOCH
        )
        assert lp.last_budget.tier == lp.tier_for(700)
        if i % 37 == 0:  # spot-check answers stay right while warm
            dd, pp, oo = engine.check_columns(
                dsnap, np.roll(q_res, i), q_perm, np.roll(q_subj, i),
                now_us=EPOCH,
            )
            assert (d == dd).all() and (p == pp).all() and (o == oo).all()
    assert lp.compile_count == warm_compiles, (
        f"latency path retraced: {lp.compile_count - warm_compiles} extra"
        " compiles across 110 warm same-tier dispatches"
    )
    # same-tier, different batch size: still the same pinned kernel
    lp.dispatch_columns(q_res[:500], q_perm[:500], q_subj[:500], now_us=EPOCH)
    assert lp.compile_count == warm_compiles


def test_latency_path_tier_routing(rbac_world):
    """Batches beyond the top tier return None from the path and fall
    back (check_columns_latency still answers, identically)."""
    engine, dsnap, snap, users, repos, slot = rbac_world
    top = max(engine.config.latency_tiers)
    B = top + 1
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, B, seed=13)
    lp = engine.latency_path(dsnap)
    assert lp.tier_for(B) is None
    assert lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH) is None
    d0, p0, o0 = engine.check_columns(dsnap, q_res, q_perm, q_subj, now_us=EPOCH)
    d1, p1, o1 = engine.check_columns_latency(
        dsnap, q_res, q_perm, q_subj, now_us=EPOCH
    )
    assert (d0 == d1).all() and (p0 == p1).all() and (o0 == o1).all()


def test_latency_pins_shared_across_prepares(rbac_world):
    """A re-prepared snapshot with identical geometry re-pins from the
    engine-wide cache: zero new XLA compiles."""
    engine, dsnap, snap, users, repos, slot = rbac_world
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 200, seed=17)
    lp = engine.latency_path(dsnap)
    lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    dsnap2 = engine.prepare(snap)
    lp2 = engine.latency_path(dsnap2)
    assert lp2 is not lp
    out = lp2.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    assert out is not None
    assert lp2.compile_count == 0, "identical geometry should reuse pins"
    assert lp2.pin_count == 1


def test_latency_budget_smoke(rbac_world):
    """Fast CI smoke: the latency path end-to-end on a tiny world, with
    the host/H2D/kernel/D2H budget populated in last_budget AND in the
    metrics registry (counts, totals, live p50/p99)."""
    engine, dsnap, snap, users, repos, slot = rbac_world
    reg = metrics.default
    before = reg.counter("latency.dispatches")
    q_res, q_perm, q_subj = _random_queries(users, repos, slot, 64, seed=19)
    lp = engine.latency_path(dsnap)
    for i in range(5):
        out = lp.dispatch_columns(np.roll(q_res, i), q_perm, q_subj, now_us=EPOCH)
        assert out is not None
    b = lp.last_budget
    assert b is not None and b.batch == 64 and b.tier == lp.tier_for(64)
    for stage in ("host_lower_s", "h2d_s", "kernel_s", "d2h_s"):
        assert getattr(b, stage) >= 0.0
    assert b.total_s >= b.host_lower_s + b.h2d_s  # stages nest inside total
    assert not b.compiled, "5th dispatch must be warm"
    snapm = reg.snapshot()
    assert reg.counter("latency.dispatches") >= before + 5
    for stage in ("host_lower", "h2d", "kernel", "d2h", "dispatch"):
        assert snapm[f"latency.{stage}_s.count"] >= 1
        assert f"latency.{stage}_s.p99_s" in snapm
        assert f"latency.{stage}_s.p50_s" in snapm
    assert reg.percentile("latency.dispatch_s", 99) is not None

"""Tests for the bucketed hash indexes (engine/hash.py): host/device hash
agreement, exact probes, range probes, duplicates, and empties."""

import numpy as np

import jax.numpy as jnp

from gochugaru_tpu.engine.hash import (
    build_hash,
    build_range_hash,
    mix32,
    probe_range,
    probe_rows,
)


def test_mix32_host_device_agree():
    rng = np.random.default_rng(0)
    cols = [rng.integers(-(2**31), 2**31 - 1, 257).astype(np.int32) for _ in range(4)]
    hn = mix32(cols, np)
    hj = np.asarray(mix32([jnp.asarray(c) for c in cols], jnp))
    np.testing.assert_array_equal(hn, hj)


def _probe_host(idx, key_cols, q_cols):
    dev = [jnp.asarray(c) for c in key_cols]
    q = [jnp.asarray(c) for c in q_cols]
    return np.asarray(
        probe_rows(
            jnp.asarray(idx.off), jnp.asarray(idx.rows), dev, q, idx.cap, idx.n
        )
    )


def test_exact_probe_hits_and_misses():
    rng = np.random.default_rng(1)
    n = 5000
    k1 = rng.permutation(n).astype(np.int32)
    k2 = rng.integers(0, 50, n).astype(np.int32)
    k3 = rng.integers(-5, 5, n).astype(np.int32)
    idx = build_hash([k1, k2, k3])
    assert idx.cap <= 4 or idx.size >= 2 * n
    # every present key found at its own row
    got = _probe_host(idx, [k1, k2, k3], [k1, k2, k3])
    np.testing.assert_array_equal(got, np.arange(n))
    # absent keys miss
    qa = (k1 + np.int32(n)).astype(np.int32)  # k1 values all < n, so +n misses
    got = _probe_host(idx, [k1, k2, k3], [qa, k2, k3])
    assert (got == -1).all()


def test_duplicate_keys_probe_returns_a_matching_row():
    k1 = np.asarray([7, 7, 7, 3], np.int32)
    k2 = np.asarray([1, 1, 1, 2], np.int32)
    idx = build_hash([k1, k2])
    got = _probe_host(idx, [k1, k2], [np.asarray([7, 3], np.int32),
                                      np.asarray([1, 2], np.int32)])
    assert k1[got[0]] == 7 and k2[got[0]] == 1
    assert got[1] == 3


def test_empty_table_probes_miss():
    idx = build_hash([])
    got = _probe_host(
        idx,
        [np.zeros(1, np.int32)],
        [np.asarray([5, 0, -1], np.int32)],
    )
    assert (got == -1).all()


def test_probe_broadcast_shapes():
    k1 = np.arange(100, dtype=np.int32)
    k2 = (np.arange(100) % 7).astype(np.int32)
    idx = build_hash([k1, k2])
    q1 = np.arange(12, dtype=np.int32).reshape(3, 4)
    q2 = (np.arange(12) % 7).astype(np.int32).reshape(3, 4)
    got = _probe_host(idx, [k1, k2], [q1, q2])
    assert got.shape == (3, 4)
    ok = (np.arange(12) % 7) == (np.arange(12) % 7)  # by construction all hit
    assert (got.ravel()[ok] == np.arange(12)[ok]).all()


def test_range_index_matches_searchsorted():
    rng = np.random.default_rng(3)
    G, reps = 200, 6
    k = np.repeat(rng.choice(100000, G, replace=False), reps)
    k = np.sort(k).astype(np.int32)
    ri = build_range_hash(k)
    assert ri.max_run == reps
    arrays = {
        "gk": jnp.asarray(ri.gk),
        "glo": jnp.asarray(ri.glo), "ghi": jnp.asarray(ri.ghi),
        "off": jnp.asarray(ri.index.off), "rows": jnp.asarray(ri.index.rows),
    }
    # probe every distinct key + some misses
    q = np.concatenate([ri.gk, np.asarray([123456789, -7], np.int32)])
    lo, hi = probe_range(arrays, ri.index.cap, ri.index.n, jnp.asarray(q))
    lo, hi = np.asarray(lo), np.asarray(hi)
    for i in range(len(ri.gk)):
        assert lo[i] == np.searchsorted(k, q[i], "left")
        assert hi[i] == np.searchsorted(k, q[i], "right")
    assert (lo[-2:] == 0).all() and (hi[-2:] == 0).all()


def test_range_index_empty():
    ri = build_range_hash(np.zeros(0, np.int32))
    assert ri.max_run == 0
    arrays = {
        "gk": jnp.asarray(np.zeros(1, np.int32)),
        "glo": jnp.asarray(np.zeros(1, np.int32)),
        "ghi": jnp.asarray(np.zeros(1, np.int32)),
        "off": jnp.asarray(ri.index.off), "rows": jnp.asarray(ri.index.rows),
    }
    lo, hi = probe_range(arrays, ri.index.cap, ri.index.n,
                         jnp.asarray([3], dtype=jnp.int32))
    assert int(lo[0]) == 0 and int(hi[0]) == 0

"""Tests for the bucketed hash indexes (engine/hash.py): host/device hash
agreement, exact probes, range probes, duplicates, and empties."""

import numpy as np

import jax.numpy as jnp

from gochugaru_tpu.engine.hash import (
    build_hash,
    build_range_hash,
    mix32,
    probe_range,
    probe_rows,
)


def test_mix32_host_device_agree():
    rng = np.random.default_rng(0)
    cols = [rng.integers(-(2**31), 2**31 - 1, 257).astype(np.int32) for _ in range(4)]
    hn = mix32(cols, np)
    hj = np.asarray(mix32([jnp.asarray(c) for c in cols], jnp))
    np.testing.assert_array_equal(hn, hj)


def _probe_host(idx, key_cols, q_cols):
    dev = [jnp.asarray(c) for c in key_cols]
    q = [jnp.asarray(c) for c in q_cols]
    return np.asarray(
        probe_rows(
            jnp.asarray(idx.off), jnp.asarray(idx.rows), dev, q, idx.cap, idx.n
        )
    )


def test_exact_probe_hits_and_misses():
    rng = np.random.default_rng(1)
    n = 5000
    k1 = rng.permutation(n).astype(np.int32)
    k2 = rng.integers(0, 50, n).astype(np.int32)
    k3 = rng.integers(-5, 5, n).astype(np.int32)
    idx = build_hash([k1, k2, k3])
    assert idx.cap <= 4 or idx.size >= 2 * n
    # every present key found at its own row
    got = _probe_host(idx, [k1, k2, k3], [k1, k2, k3])
    np.testing.assert_array_equal(got, np.arange(n))
    # absent keys miss
    qa = (k1 + np.int32(n)).astype(np.int32)  # k1 values all < n, so +n misses
    got = _probe_host(idx, [k1, k2, k3], [qa, k2, k3])
    assert (got == -1).all()


def test_duplicate_keys_probe_returns_a_matching_row():
    k1 = np.asarray([7, 7, 7, 3], np.int32)
    k2 = np.asarray([1, 1, 1, 2], np.int32)
    idx = build_hash([k1, k2])
    got = _probe_host(idx, [k1, k2], [np.asarray([7, 3], np.int32),
                                      np.asarray([1, 2], np.int32)])
    assert k1[got[0]] == 7 and k2[got[0]] == 1
    assert got[1] == 3


def test_empty_table_probes_miss():
    idx = build_hash([])
    got = _probe_host(
        idx,
        [np.zeros(1, np.int32)],
        [np.asarray([5, 0, -1], np.int32)],
    )
    assert (got == -1).all()


def test_probe_broadcast_shapes():
    k1 = np.arange(100, dtype=np.int32)
    k2 = (np.arange(100) % 7).astype(np.int32)
    idx = build_hash([k1, k2])
    q1 = np.arange(12, dtype=np.int32).reshape(3, 4)
    q2 = (np.arange(12) % 7).astype(np.int32).reshape(3, 4)
    got = _probe_host(idx, [k1, k2], [q1, q2])
    assert got.shape == (3, 4)
    ok = (np.arange(12) % 7) == (np.arange(12) % 7)  # by construction all hit
    assert (got.ravel()[ok] == np.arange(12)[ok]).all()


def test_range_index_matches_searchsorted():
    rng = np.random.default_rng(3)
    G, reps = 200, 6
    k = np.repeat(rng.choice(100000, G, replace=False), reps)
    k = np.sort(k).astype(np.int32)
    ri = build_range_hash(k)
    assert ri.max_run == reps
    arrays = {
        "gk": jnp.asarray(ri.gk),
        "glo": jnp.asarray(ri.glo), "ghi": jnp.asarray(ri.ghi),
        "off": jnp.asarray(ri.index.off), "rows": jnp.asarray(ri.index.rows),
    }
    # probe every distinct key + some misses
    q = np.concatenate([ri.gk, np.asarray([123456789, -7], np.int32)])
    lo, hi = probe_range(arrays, ri.index.cap, ri.index.n, jnp.asarray(q))
    lo, hi = np.asarray(lo), np.asarray(hi)
    for i in range(len(ri.gk)):
        assert lo[i] == np.searchsorted(k, q[i], "left")
        assert hi[i] == np.searchsorted(k, q[i], "right")
    assert (lo[-2:] == 0).all() and (hi[-2:] == 0).all()


def test_range_index_empty():
    ri = build_range_hash(np.zeros(0, np.int32))
    assert ri.max_run == 0
    arrays = {
        "gk": jnp.asarray(np.zeros(1, np.int32)),
        "glo": jnp.asarray(np.zeros(1, np.int32)),
        "ghi": jnp.asarray(np.zeros(1, np.int32)),
        "off": jnp.asarray(ri.index.off), "rows": jnp.asarray(ri.index.rows),
    }
    lo, hi = probe_range(arrays, ri.index.cap, ri.index.n,
                         jnp.asarray([3], dtype=jnp.int32))
    assert int(lo[0]) == 0 and int(hi[0]) == 0


def test_probe_block_matches_probe_rows():
    """The block-slice probe must find exactly the rows the scattered
    probe finds, across random tables and query mixes."""
    import numpy as np

    from gochugaru_tpu.engine.hash import (
        build_hash, interleave_buckets, probe_block, probe_rows,
    )

    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 500):
        k1 = rng.integers(0, 200, n).astype(np.int32)
        k2 = rng.integers(0, 50, n).astype(np.int32)
        payload = np.arange(n, dtype=np.int32)
        h = build_hash([k1, k2])
        tbl = interleave_buckets(h, [k1, k2, payload])
        q1 = rng.integers(-1, 220, 64).astype(np.int32)
        q2 = rng.integers(-1, 60, 64).astype(np.int32)
        import jax.numpy as jnp

        blk = np.asarray(
            probe_block(
                jnp.asarray(h.off), jnp.asarray(tbl), max(h.cap, 1),
                (jnp.asarray(q1), jnp.asarray(q2)),
            )
        )
        hit = (
            (blk[..., 0] == q1[:, None])
            & (blk[..., 1] == q2[:, None])
            & (q1 >= 0)[:, None]
            & (q2 >= 0)[:, None]
        )
        got = np.where(hit.any(1), blk[..., 2].max(1, initial=-1, where=hit), -1)
        if n == 0:
            assert (got == -1).all()
            continue
        row = np.asarray(
            probe_rows(h.off, h.rows, (k1, k2), (q1, q2), max(h.cap, 1), h.n)
        )
        want = np.where(row >= 0, payload[np.clip(row, 0, max(n - 1, 0))], -1)
        np.testing.assert_array_equal(got, want)


def test_slice_blocks_never_shifts_within_pad():
    """A slice starting at any real offset must return exactly the rows
    at [start, start+cap) — the pad guarantees no clamp shift."""
    import numpy as np

    from gochugaru_tpu.engine.hash import interleave_rows, slice_blocks

    vals = np.arange(100, dtype=np.int32)
    tbl = interleave_rows([vals, vals * 2], pad=16)
    starts = np.asarray([0, 1, 57, 99, 100], np.int32)
    import jax.numpy as jnp

    blk = np.asarray(slice_blocks(jnp.asarray(tbl), jnp.asarray(starts), 8))
    for i, s in enumerate(starts):
        for j in range(8):
            want = s + j if s + j < 100 else -1
            assert blk[i, j, 0] == want, (s, j)


def test_stack_point_and_range_cover_all_rows():
    """Bucket-sharded stacking: every row lands on exactly one shard, at
    the local offset its (normalized) bucket table says."""
    import numpy as np

    from gochugaru_tpu.engine.hash import build_hash, mix32
    from gochugaru_tpu.engine.flat import _stack_point

    rng = np.random.default_rng(3)
    n, M = 300, 4
    k = rng.integers(0, 10_000, n).astype(np.int32)
    payload = np.arange(n, dtype=np.int32)
    h = build_hash([k], min_size=M)
    off, tbl = _stack_point(h, [k, payload], M)
    bpd = (off.shape[0] // M) - 1
    tbl3 = tbl.reshape(M, -1, 2)
    off2 = off.reshape(M, bpd + 1)
    seen = []
    for i in range(n):
        b = int(mix32([k[i : i + 1]])[0] & np.uint32(h.size - 1))
        s = b // bpd
        lo, hi = off2[s, b % bpd], off2[s, b % bpd + 1]
        rows = tbl3[s, lo:hi]
        match = rows[(rows[:, 0] == k[i]) & (rows[:, 1] == payload[i])]
        assert match.shape[0] == 1, i
        seen.append(int(match[0, 1]))
    assert sorted(seen) == list(range(n))

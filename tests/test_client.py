"""Client integration tests — the local mirror of the reference's
dockerized integration suite (client/client_test.go).  Each test builds a
fresh client (the analogue of `serve-testing`'s per-token isolated
datastore) and exercises the full surface."""

import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    Client,
    new_plaintext,
    new_tpu_evaluator,
    new_with_opts,
    with_host_only_evaluation,
    with_overlap_required,
)
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    OverlapKeyMissingError,
    PreconditionFailedError,
)

# the example schema from client/client_test.go:23-32
EXAMPLE_SCHEMA = """
definition user {}
definition document {
    relation writer: user
    relation reader: user

    permission edit = writer
    permission view = reader + edit
}
"""


def make_client(*opts):
    ctx = background()
    c = new_tpu_evaluator(*opts)
    c.write_schema(ctx, EXAMPLE_SCHEMA)
    return ctx, c


# -- ExampleClient_ReadRelationships (client/client_test.go:73-105) --------

def test_read_relationships_example():
    ctx, c = make_client()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:README", "reader", "user:jimmy"))
    c.write(ctx, txn)

    got = [
        str(r)
        for r in c.read_relationships(
            ctx, consistency.min_latency(), rel.new_filter("document", "", "")
        )
    ]
    assert got == ["document:README#reader@user:jimmy"]


# -- TestClient_LookupResources (client/client_test.go:107-139) ------------

def test_lookup_resources():
    ctx, c = make_client()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:check_test1", "writer", "user:alice"))
    txn.create(rel.must_from_triple("document:check_test1", "reader", "user:bob"))
    txn.create(rel.must_from_triple("document:check_test1", "writer", "user:charlie"))
    txn.create(rel.must_from_triple("document:check_test2", "writer", "user:charlie"))
    c.write(ctx, txn)

    ids = list(c.lookup_resources(ctx, consistency.full(), "document#writer", "user:alice"))
    assert ids == ["check_test1"]
    ids = sorted(
        c.lookup_resources(ctx, consistency.full(), "document#writer", "user:charlie")
    )
    assert ids == ["check_test1", "check_test2"]


# -- TestClient_Check (client/client_test.go:141-216) ----------------------

@pytest.fixture(params=["device", "host"])
def check_client(request):
    opts = () if request.param == "device" else (with_host_only_evaluation(),)
    ctx, c = make_client(*opts)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:check_test1", "writer", "user:alice"))
    txn.create(rel.must_from_triple("document:check_test1", "reader", "user:bob"))
    txn.create(rel.must_from_triple("document:check_test2", "writer", "user:charlie"))
    c.write(ctx, txn)
    return ctx, c


def test_check_single_has_permission(check_client):
    ctx, c = check_client
    results = c.check(
        ctx, consistency.min_latency(),
        rel.must_from_triple("document:check_test1", "edit", "user:alice"),
    )
    assert results == [True]


def test_check_single_no_permission(check_client):
    ctx, c = check_client
    results = c.check(
        ctx, consistency.min_latency(),
        rel.must_from_triple("document:check_test1", "edit", "user:bob"),
    )
    assert results == [False]


def test_check_multiple(check_client):
    ctx, c = check_client
    results = c.check(
        ctx, consistency.min_latency(),
        rel.must_from_triple("document:check_test1", "edit", "user:alice"),
        rel.must_from_triple("document:check_test1", "view", "user:bob"),
        rel.must_from_triple("document:check_test2", "edit", "user:charlie"),
        rel.must_from_triple("document:check_test2", "view", "user:alice"),
    )
    assert results == [True, True, True, False]


def test_check_consistency_strategies(check_client):
    ctx, c = check_client
    for strategy in (consistency.min_latency(), consistency.full()):
        results = c.check(
            ctx, strategy,
            rel.must_from_triple("document:check_test1", "edit", "user:alice"),
        )
        assert results == [True]


def test_check_empty(check_client):
    ctx, c = check_client
    assert c.check(ctx, consistency.min_latency()) == []


def test_check_nonexistent_resource(check_client):
    ctx, c = check_client
    results = c.check(
        ctx, consistency.min_latency(),
        rel.must_from_triple("document:nonexistent", "edit", "user:alice"),
    )
    assert results == [False]


# -- README founders example (README.md:64-89) -----------------------------

def test_readme_founders_check_all():
    ctx, c = make_client()
    c.write_schema(
        ctx,
        "definition user {}\ndefinition company { relation founder: user }",
    )
    txn = rel.Txn()
    founders = [
        rel.from_triple("company:authzed", "founder", "user:" + f)
        for f in ("jake", "joey", "jimmy")
    ]
    for f in founders:
        txn.touch(f)
    c.write(ctx, txn)

    assert c.check_all(ctx, consistency.min_latency(), *founders)
    assert not c.check_all(
        ctx, consistency.min_latency(), *founders,
        rel.must_from_triple("company:authzed", "founder", "user:impostor"),
    )
    assert c.check_any(
        ctx, consistency.min_latency(),
        rel.must_from_triple("company:authzed", "founder", "user:impostor"),
        rel.must_from_triple("company:authzed", "founder", "user:jake"),
    )
    assert c.check_one(ctx, consistency.min_latency(), founders[0])


# -- check_iter batching (client/client.go:164-180) ------------------------

def test_check_iter():
    ctx, c = make_client()
    txn = rel.Txn()
    for i in range(0, 10, 2):
        txn.create(rel.must_from_triple(f"document:d{i}", "reader", "user:amy"))
    c.write(ctx, txn)
    checks = [
        rel.must_from_triple(f"document:d{i}", "view", "user:amy") for i in range(10)
    ]
    got = list(c.check_iter(ctx, consistency.full(), checks, chunk_size=3))
    assert got == [i % 2 == 0 for i in range(10)]


# -- read-after-write with at_least (consistency/consistency.go:54-62) -----

def test_read_after_write_at_least():
    ctx, c = make_client()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:new", "reader", "user:amy"))
    rev = c.write(ctx, txn)
    assert c.check_one(
        ctx, consistency.at_least(rev),
        rel.must_from_triple("document:new", "view", "user:amy"),
    )


# -- writes with preconditions (README.md:101-111) -------------------------

def test_write_precondition_flow():
    ctx, c = make_client()
    c.write_schema(
        ctx,
        "definition user {}\ndefinition module {"
        " relation creator: user relation maintainer: user }",
    )
    txn = rel.Txn()
    for rival in ("joey", "jake"):
        txn.must_not_match(
            rel.must_from_triple("module:gochugaru", "creator", "user:" + rival).filter()
        )
    txn.touch(rel.must_from_triple("module:gochugaru", "creator", "user:jimmy"))
    rev = c.write(ctx, txn)
    assert rev

    # now a rival exists → precondition fails
    t2 = rel.Txn()
    t2.touch(rel.must_from_triple("module:gochugaru", "creator", "user:joey"))
    c.write(ctx, t2)
    with pytest.raises(PreconditionFailedError):
        c.write(ctx, txn)


# -- deletes (client/client.go:317-358) ------------------------------------

def test_delete_and_delete_atomic():
    ctx, c = make_client()
    txn = rel.Txn()
    for i in range(7):
        txn.create(rel.must_from_triple(f"document:d{i}", "reader", "user:amy"))
    c.write(ctx, txn)

    pf = rel.new_preconditioned_filter(rel.new_filter("document", "d0", ""))
    rev = c.delete_atomic(ctx, pf)
    assert rev
    remaining = list(
        c.read_relationships(ctx, consistency.full(), rel.new_filter("document", "", ""))
    )
    assert len(remaining) == 6

    c.delete(ctx, rel.new_preconditioned_filter(rel.new_filter("document", "", "")))
    assert (
        list(
            c.read_relationships(
                ctx, consistency.full(), rel.new_filter("document", "", "")
            )
        )
        == []
    )


# -- import/export (client/client.go:436-499) ------------------------------

def test_import_and_export():
    ctx, c = make_client()
    rs = [
        rel.must_from_triple(f"document:d{i}", "reader", f"user:u{i % 3}")
        for i in range(10)
    ]
    c.import_relationships(ctx, iter(rs))
    # importing again hits AlreadyExists and falls back to TOUCH
    c.import_relationships(ctx, iter(rs))
    _, rev = c.read_schema(ctx)
    # pin the export at the current head by materializing it
    c.check_one(
        ctx, consistency.full(),
        rel.must_from_triple("document:d0", "view", "user:u0"),
    )
    exported = sorted(str(r) for r in c.export_relationships(ctx, rev))
    assert len(exported) == 10
    assert exported[0].startswith("document:d0#reader@")


# -- watch (client/client.go:360-413) --------------------------------------

def test_updates_stream_and_resume():
    ctx, c = make_client()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:amy"))
    rev1 = c.write(ctx, txn)

    seen = []
    wctx = ctx.with_cancel()

    def consume():
        # subscribes at head: the historical CREATE must NOT replay
        # (Watch with no cursor starts at head, client/client.go:379-387)
        for u in c.updates(wctx, rel.UpdateFilter()):
            seen.append(u)
            if len(seen) >= 2:
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    t2 = rel.Txn()
    t2.delete(rel.must_from_triple("document:a", "reader", "user:amy"))
    c.write(ctx, t2)
    t3 = rel.Txn()
    t3.touch(rel.must_from_triple("document:b", "reader", "user:amy"))
    c.write(ctx, t3)
    t.join(timeout=5)
    assert not t.is_alive()
    assert [u.update_type for u in seen] == [rel.UpdateType.DELETE, rel.UpdateType.TOUCH]

    # resume from rev1: the historical delete+touch replay in order
    resumed = []
    for u in c.updates_since_revision(wctx, rel.UpdateFilter(), rev1):
        resumed.append(u)
        if len(resumed) >= 2:
            break
    assert [u.update_type for u in resumed] == [
        rel.UpdateType.DELETE,
        rel.UpdateType.TOUCH,
    ]

    # cancellation ends the stream
    wctx.cancel()
    assert list(c.updates(wctx, rel.UpdateFilter())) == []


def test_updates_filters():
    ctx, c = make_client()
    c.write_schema(
        ctx,
        "definition user {}\ndefinition doc { relation viewer: user }\n"
        "definition folder { relation viewer: user }",
    )
    _, rev0 = c.read_schema(ctx)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:a", "viewer", "user:amy"))
    txn.create(rel.must_from_triple("folder:f", "viewer", "user:amy"))
    c.write(ctx, txn)

    wctx = ctx.with_cancel()
    got = []
    f = rel.UpdateFilter(object_types=["doc"])
    for u in c.updates_since_revision(wctx, f, rev0):
        got.append(u)
        break
    assert [u.relationship.resource_type for u in got] == ["doc"]
    with pytest.raises(ValueError):
        next(
            c.updates(
                wctx,
                rel.UpdateFilter(
                    object_types=["doc"],
                    relationship_filters=[rel.new_filter("doc", "", "")],
                ),
            )
        )


# -- lookup_subjects (client/client.go:554-599) ----------------------------

def test_lookup_subjects():
    ctx, c = make_client()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:README", "writer", "user:alice"))
    txn.create(rel.must_from_triple("document:README", "reader", "user:bob"))
    c.write(ctx, txn)
    subjects = sorted(
        c.lookup_subjects(ctx, consistency.full(), "document:README", "view", "user")
    )
    assert subjects == ["alice", "bob"]


# -- TestMissingOverlapPanic (client/client_test.go:218-277) ---------------

def test_missing_overlap_raises():
    ctx = background()
    c = new_with_opts(with_overlap_required())
    c.write_schema(ctx, EXAMPLE_SCHEMA)  # schema ops are exempt, as in the ref

    pf = rel.new_preconditioned_filter(rel.new_filter("document", "", ""))
    cases = [
        lambda: next(
            c.read_relationships(ctx, consistency.full(), rel.new_filter("document", "", "")),
            None,
        ),
        lambda: next(c.export_relationships(ctx, "gtz1.1"), None),
        lambda: c.check_one(
            ctx, consistency.full(),
            rel.must_from_triple("document:README", "view", "user:bot"),
        ),
        lambda: c.delete_atomic(ctx, pf),
        lambda: c.delete(ctx, pf),
        lambda: next(iter(c.updates(ctx, rel.UpdateFilter())), None),
        lambda: next(
            c.lookup_resources(ctx, consistency.full(), "document#writer", "user:alice"),
            None,
        ),
        lambda: next(
            c.lookup_subjects(ctx, consistency.full(), "document:x", "view", "user"),
            None,
        ),
    ]
    for i, case in enumerate(cases):
        with pytest.raises(OverlapKeyMissingError):
            case()

    # provided overlap key doesn't raise
    okctx = consistency.with_overlap_key(ctx, "test")
    c.check_one(
        okctx, consistency.full(),
        rel.must_from_triple("document:README", "view", "user:bot"),
    )


def test_constructor_parity():
    # the reference's constructors exist and return working local clients
    ctx = background()
    for c in (new_plaintext("127.0.0.1:50051", "key"), new_with_opts()):
        c.write_schema(ctx, EXAMPLE_SCHEMA)
        txn = rel.Txn()
        txn.create(rel.must_from_triple("document:x", "reader", "user:u"))
        c.write(ctx, txn)
        assert c.check_one(
            ctx, consistency.full(),
            rel.must_from_triple("document:x", "view", "user:u"),
        )

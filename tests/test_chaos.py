"""Chaos soak: a mixed Check/Write/Watch workload under seeded random
fault injection (utils/faults.py), asserting the system's end-to-end
robustness contract:

- every returned check result matches the host oracle exactly;
- no watch event is lost or duplicated across injected stream breaks;
- every failure that surfaces is a classified ``AuthzError`` — never a
  raw JAX traceback;
- no hang: every round completes within its context deadline or sheds
  with ``UnavailableError``.

Deterministic by construction: the workload RNG and every fault policy
RNG are seeded from ``GOCHUGARU_CHAOS_SEED`` (default 20260803), so a
failure reproduces with the same command.  ``scripts/chaos_smoke.sh``
runs exactly this file with the fixed seed under the tier-1 timeout.
"""

import os
import random
import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils.admission import AdmissionConfig
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    AuthzError,
    DeadlineExceededError,
    UnavailableError,
)

SEED = int(os.environ.get("GOCHUGARU_CHAOS_SEED", "20260803"))
ROUNDS = int(os.environ.get("GOCHUGARU_CHAOS_ROUNDS", "30"))

SCHEMA = """
definition user {}
definition team { relation member: user }
definition doc {
    relation owner: user
    relation reader: user | team#member
    relation banned: user
    permission read = reader + owner - banned
}
"""

#: fault sites the check phase randomly arms each round (watch.stream is
#: armed separately, for the whole stream's life)
CHAOS_SITES = (
    "device.dispatch",
    "latency.dispatch",
    "device.prepare",
    "store.snapshot_for",
    "store.materialize",
    "snapshot.finish",
    "explain.walk",
    "lookup.dispatch",
    "spmm.dispatch",
)


def _fixed_world(c):
    """A static base world so early rounds have something to check."""
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    for i in range(8):
        txn.touch(rel.must_from_triple(f"doc:base{i}", "owner", f"user:own{i % 3}"))
        txn.touch(rel.must_from_triple(f"doc:base{i}", "reader", f"user:rd{i % 5}"))
    txn.touch(rel.must_from_triple("team:core", "member", "user:tm1"))
    txn.touch(rel.must_from_tuple("doc:base0#reader", "team:core#member"))
    txn.touch(rel.must_from_triple("doc:base1", "banned", "user:rd1"))
    c.write(ctx, txn)


def _key(update_type: str, r) -> tuple:
    return (
        update_type,
        r.resource_type, r.resource_id, r.resource_relation,
        r.subject_type, r.subject_id,
    )


def test_chaos_soak():
    rng = random.Random(SEED)
    m = _metrics.default

    chaos = new_tpu_evaluator(
        with_latency_mode(),
        with_admission_control(
            AdmissionConfig(
                max_inflight=8, breaker_threshold=3, breaker_cooldown_s=0.2
            )
        ),
    )
    _fixed_world(chaos)
    oracle = new_tpu_evaluator(
        with_store(chaos.store), with_host_only_evaluation()
    )

    # ---- watch consumer: alive for the whole soak, faulted throughout --
    watch_ctx = background().with_cancel()
    collected = []
    watch_err = {}
    # cursor = head NOW (after the fixed world, before any soak write)
    stream = chaos.updates(watch_ctx, rel.UpdateFilter())

    def consume():
        try:
            for u in stream:
                collected.append(_key(u.update_type.name, u.relationship))
        except BaseException as e:  # a surfaced error must be classified
            watch_err["e"] = e

    watcher = threading.Thread(target=consume, daemon=True)
    watcher.start()
    # persistent low-probability stream breaker: every break exercises the
    # cursor-resume path; progress resets the resume budget, so the
    # stream recovers rather than surfacing
    faults.arm("watch.stream", probability=0.10, seed=SEED ^ 0xBEEF)

    expected_updates = []  # every applied update, in log order
    live = []  # (resource_id, subject_id) of soak-written reader rels
    users = [f"user:cu{i}" for i in range(6)]
    mismatches = []
    sheds = 0
    unclassified = []

    for rnd in range(ROUNDS):
        # ---- write phase: fresh touches + an occasional delete ---------
        txn = rel.Txn()
        for w in range(rng.randint(1, 3)):
            r = rel.must_from_triple(
                f"doc:r{rnd}w{w}", "reader", rng.choice(users)
            )
            txn.touch(r)
            expected_updates.append(_key("TOUCH", r))
            live.append((r.resource_id, r.subject_id))
        if live and rng.random() < 0.3:
            rid, sid = live.pop(rng.randrange(len(live)))
            d = rel.must_from_triple(f"doc:{rid}", "reader", f"user:{sid}")
            txn.delete(d)
            expected_updates.append(_key("DELETE", d))
        chaos.write(background(), txn)

        # ---- arm a random subset of sites for the check phase ----------
        armed = []
        for site in CHAOS_SITES:
            if rng.random() < 0.35:
                faults.arm(
                    site,
                    probability=1.0,
                    times=rng.randint(1, 2),
                    seed=rng.randrange(1 << 30),
                )
                armed.append(site)

        # ---- check phase under faults ----------------------------------
        queries = [
            rel.must_from_triple(
                rng.choice([f"doc:base{rng.randrange(8)}", f"doc:r{rnd}w0"]),
                "read",
                rng.choice(users + ["user:own0", "user:rd1", "user:tm1"]),
            )
            for _ in range(rng.randint(2, 6))
        ]
        ctx = background().with_timeout(30.0)
        result = None
        explained = None
        looked_up = None
        lookup_subj = rng.choice(users + ["user:own0", "user:tm1"])
        try:
            result = chaos.check(ctx, consistency.full(), *queries)
            if rnd % 4 == 1:
                # lookup under the same armed faults: the fused SpMM
                # dispatch (spmm.dispatch) and the looped hop dispatch
                # (lookup.dispatch) both classify into the retry envelope
                looked_up = sorted(chaos.lookup_resources(
                    ctx, consistency.full(), "doc#read", lookup_subj
                ))
            if rnd % 3 == 0:
                # explain under the same armed faults: the explain.walk
                # site (and any armed dispatch/prepare site the witness
                # extraction hits) classifies into the retry envelope
                explained = chaos.explain(
                    ctx, consistency.full(), queries[0]
                )
        except (UnavailableError, DeadlineExceededError):
            sheds += 1  # allowed: a classified shed, within the deadline
        except BaseException as e:
            if not isinstance(e, AuthzError):
                unclassified.append((rnd, repr(e)))
        finally:
            for site in armed:
                faults.disarm(site)

        # ---- oracle comparison (faults disarmed, same head) ------------
        if result is not None:
            want = oracle.check(background(), consistency.full(), *queries)
            if result != want:
                mismatches.append((rnd, result, want))
        if looked_up is not None:
            want_lu = sorted(oracle.lookup_resources(
                background(), consistency.full(), "doc#read", lookup_subj
            ))
            if looked_up != want_lu:
                mismatches.append((rnd, "lookup", looked_up, want_lu))
        if explained is not None:
            # no torn trees: a returned tree is complete (popped root)
            # and verdict-exact against the oracle at the same head
            w0 = oracle.check(
                background(), consistency.full(), queries[0]
            )[0]
            if (explained["result"] == "allowed") != w0:
                mismatches.append((rnd, "explain", explained["result"], w0))
            if explained["tree"] is None or "verdict" not in explained["tree"]:
                mismatches.append((rnd, "torn explain tree"))

    # ---- drain + verify the watch stream -------------------------------
    drain = background().with_timeout(20.0)
    while (
        len(collected) < len(expected_updates)
        and not drain.done()
        and "e" not in watch_err
    ):
        time.sleep(0.05)
    watch_ctx.cancel()
    watcher.join(5.0)

    assert not unclassified, f"unclassified exceptions: {unclassified}"
    assert not mismatches, f"oracle mismatches: {mismatches[:3]}"
    assert "e" not in watch_err, f"watch surfaced: {watch_err.get('e')!r}"
    # exactly-once, in-order delivery across injected stream breaks
    assert collected == expected_updates
    # the soak must actually have injected faults and exercised recovery
    assert m.counter("faults.injected") > 0
    assert m.counter("retry.retries") > 0
    # sheds are allowed but must be the exception, not the rule
    assert sheds <= ROUNDS // 3, f"{sheds}/{ROUNDS} rounds shed"


FLEET_ROUNDS = int(os.environ.get("GOCHUGARU_CHAOS_FLEET_ROUNDS", "10"))

#: the four fleet fault sites, armed for the whole soak at seeded
#: probabilities.  replica.kill is the interesting one: it turns ANY
#: served op (including health probes) into a crash, so the soak's
#: supervisor loop is constantly re-bootstrapping replicas.
FLEET_SITES = (
    ("router.dispatch", 0.15),
    ("router.health", 0.05),
    ("replica.apply", 0.20),
    ("replica.kill", 0.01),
)


def test_fleet_chaos_soak():
    """Fleet soak: router + 2 replicas under all four fleet fault sites,
    with a deterministic mid-soak replica kill and supervised restarts.

    Contract (the single-process soak's, one layer up):

    - every returned verdict matches the host oracle at the router head;
    - zookie read-your-writes holds every round, through faults;
    - killed replicas are detected, evicted, and restarted replicas
      catch up and rejoin — zero lost or duplicated answers;
    - every surfaced failure is a classified ``AuthzError``; no hangs.
    """
    from dataclasses import replace as _replace

    from gochugaru_tpu.client import with_verdict_cache
    from gochugaru_tpu.fleet import FleetConfig, FleetRouter, Replica
    from gochugaru_tpu.fleet import wire as fwire
    from gochugaru_tpu.fleet import zookie

    rng = random.Random(SEED ^ 0xF1EE7)
    m = _metrics.default
    faults.reset()  # the single-process soak leaves watch.stream armed

    cfg = _replace(
        FleetConfig(),
        probe_interval_s=0.05,
        probe_timeout_s=0.5,
        freshness_wait_s=3.0,
        freshness_poll_s=0.02,
        heartbeat_s=0.05,
    )
    router = FleetRouter(config=cfg)
    _fixed_world(router)
    oracle = new_tpu_evaluator(
        with_store(router.store), with_host_only_evaluation()
    )

    def spawn(rid):
        return Replica(
            ("127.0.0.1", router.port),
            replica_id=rid,
            config=cfg,
            client_options=(with_verdict_cache(), with_host_only_evaluation()),
        )

    reps = {}
    for i in range(2):
        r = spawn(f"f{i}")
        reps[i] = r
        router.add_replica(r.host, r.port, wait_ready_s=10.0)

    users = [f"user:fu{i}" for i in range(5)]
    mismatches = []
    unclassified = []
    sheds = 0
    restarts = 0
    injected_before = m.counter("faults.injected")
    deaths_before = m.counter("fleet.replica_deaths")

    try:
        import zlib

        for site, p in FLEET_SITES:
            faults.arm(
                site, probability=p, seed=SEED ^ zlib.crc32(site.encode())
            )

        for rnd in range(FLEET_ROUNDS):
            # ---- write through the authority, mint a zookie ------------
            txn = rel.Txn()
            fresh = rel.must_from_triple(
                f"doc:fr{rnd}", "reader", rng.choice(users)
            )
            txn.touch(fresh)
            zk = router.write(background(), txn)

            # ---- deterministic mid-soak crash --------------------------
            if rnd == FLEET_ROUNDS // 2:
                victim = reps[0]
                conn = fwire.Conn((victim.host, victim.port))
                try:
                    with pytest.raises(ConnectionError):
                        conn.request({"op": "kill"})
                finally:
                    conn.close()

            # ---- checks under faults: zookie RYW + full parity ---------
            queries = [
                rel.must_from_triple(
                    rng.choice(
                        [f"doc:base{rng.randrange(8)}", f"doc:fr{rnd}"]
                    ),
                    "read",
                    rng.choice(users + ["user:own0", "user:rd1", "user:tm1"]),
                )
                for _ in range(rng.randint(2, 5))
            ]
            ryw = rel.must_from_triple(
                fresh.resource_type + ":" + fresh.resource_id,
                "read",
                fresh.subject_type + ":" + fresh.subject_id,
            )
            ctx = background().with_timeout(15.0)
            try:
                got_ryw = router.check(
                    ctx, consistency.min_latency(), ryw, zookie=zk
                )
                if got_ryw != [True]:
                    mismatches.append((rnd, "zookie-ryw", got_ryw))
                got = router.check(ctx, consistency.full(), *queries)
                want = oracle.check(
                    background(), consistency.full(), *queries
                )
                if got != want:
                    mismatches.append((rnd, got, want))
            except (UnavailableError, DeadlineExceededError):
                sheds += 1
            except BaseException as e:
                if not isinstance(e, AuthzError):
                    unclassified.append((rnd, repr(e)))

            # ---- supervisor: restart anything the kill site took -------
            for i, r in list(reps.items()):
                if r._dead:
                    r.close()
                    nr = spawn(f"f{i}g{rnd}")
                    try:
                        router.add_replica(nr.host, nr.port, wait_ready_s=10.0)
                        reps[i] = nr
                        restarts += 1
                    except AuthzError:
                        nr.close()  # killed during admission; next round
    finally:
        for site, _ in FLEET_SITES:
            faults.disarm(site)

    # with faults quiet, a surviving fleet must converge and agree
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not router.status()["ring"]:
        time.sleep(0.05)
    final_q = [
        rel.must_from_triple(f"doc:fr{r}", "read", "user:fu0")
        for r in range(FLEET_ROUNDS)
    ]
    got = router.check(
        background().with_timeout(20.0), consistency.full(), *final_q
    )
    want = oracle.check(background(), consistency.full(), *final_q)

    try:
        assert not unclassified, f"unclassified exceptions: {unclassified}"
        assert not mismatches, f"oracle mismatches: {mismatches[:3]}"
        assert got == want
        assert m.counter("faults.injected") > injected_before
        # the deterministic kill was detected and survived
        assert m.counter("fleet.replica_deaths") > deaths_before
        assert restarts >= 1
        assert router.status()["ring"], "fleet never recovered"
        assert sheds <= max(1, FLEET_ROUNDS // 3), (
            f"{sheds}/{FLEET_ROUNDS} rounds shed"
        )
    finally:
        router.close()
        for r in reps.values():
            r.close()

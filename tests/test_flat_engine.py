"""Differential tests for the flat hash-probe engine (engine/flat.py).

Contract (engine/flat.py docstring): on worlds without caveated MEMBERSHIP
edges the flat engine is device-exact (definite == oracle T, possible ==
oracle ≥ U, modulo overflow flags); with caveated membership edges it is a
sound bracket (definite ⇒ T, T ⇒ possible) and the client cascade resolves
the gap on the host oracle."""

import random

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import F, Oracle, T, U
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000


def world(schema, rels, **cfg_overrides):
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    # small recursion budget: CPU XLA compile time grows with the unrolled
    # depth, and 3 levels exercise every code path the default 8 would
    cfg_overrides.setdefault("flat_recursion", 3)
    cfg_overrides.setdefault("flat_max_width", 32)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, **cfg_overrides))
    assert engine.config.use_flat
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is not None
    return engine, dsnap, oracle


def assert_exact(engine, dsnap, oracle, checks):
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not ovf[i], f"unexpected overflow for {q}"
        assert bool(d[i]) == (want == T), f"{q}: d={d[i]} oracle={want}"
        assert bool(p[i]) == (want != F), f"{q}: p={p[i]} oracle={want}"


def assert_sound_cascade(engine, dsnap, oracle, checks):
    """The client-cascade result (device definite, host for the rest) must
    equal the oracle truth, and definite must never overclaim."""
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not d[i] or want == T, f"unsound definite for {q}"
        if not ovf[i]:
            assert p[i] or want == F, f"possible misses oracle {want} for {q}"
        final = bool(d[i]) or (
            bool((p[i] and not d[i]) or ovf[i]) and want == T
        )
        assert final == (want == T)


FEATURES = """
caveat tier(t int, min int) { t >= min }
definition user {}
definition group {
    relation member: user | user:* | group#member
    relation admin: user
}
definition folder {
    relation parent: folder
    relation owner: user | group#member
    permission view = owner + parent->view
}
definition doc {
    relation folder: folder
    relation reader: user | user:* | group#member | user with tier
    relation banned: user
    permission read = (reader - banned) + folder->view
    permission audit = reader & banned
}
"""


def build_feature_world(rng, n_users=10, n_groups=5, n_folders=6, n_docs=10):
    import datetime as dt

    rels = []

    def expiring(r, secs):
        return r.with_expiration(
            dt.datetime.fromtimestamp(NOW / 1e6 + secs, tz=dt.timezone.utc)
        )

    for g in range(n_groups):
        for u in rng.sample(range(n_users), 3):
            r = rel.must_from_tuple(f"group:g{g}#member", f"user:u{u}")
            if rng.random() < 0.2:
                r = expiring(r, rng.choice([-100, 500]))
            rels.append(r)
    rels.append(rel.must_from_tuple("group:g0#member", "user:*"))
    for g in range(1, n_groups):
        if rng.random() < 0.6:
            rels.append(
                rel.must_from_tuple(
                    f"group:g{g}#member", f"group:g{rng.randrange(g)}#member"
                )
            )
    for f in range(1, n_folders):
        rels.append(
            rel.must_from_tuple(f"folder:f{f}#parent", f"folder:f{rng.randrange(f)}")
        )
    for f in range(n_folders):
        if rng.random() < 0.7:
            rels.append(
                rel.must_from_tuple(
                    f"folder:f{f}#owner", f"group:g{rng.randrange(n_groups)}#member"
                )
            )
        else:
            rels.append(
                rel.must_from_tuple(f"folder:f{f}#owner", f"user:u{rng.randrange(n_users)}")
            )
    for dd in range(n_docs):
        rels.append(
            rel.must_from_tuple(f"doc:d{dd}#folder", f"folder:f{rng.randrange(n_folders)}")
        )
        for u in rng.sample(range(n_users), 2):
            r = rel.must_from_tuple(f"doc:d{dd}#reader", f"user:u{u}")
            if rng.random() < 0.3:
                r = r.with_caveat("tier", {"min": rng.randint(1, 9)})
            elif rng.random() < 0.2:
                r = expiring(r, rng.choice([-50, 1000]))
            rels.append(r)
        if rng.random() < 0.3:
            rels.append(rel.must_from_tuple(f"doc:d{dd}#reader", "user:*"))
        if rng.random() < 0.4:
            rels.append(
                rel.must_from_tuple(f"doc:d{dd}#banned", f"user:u{rng.randrange(n_users)}")
            )
    return rels


def make_checks(rng, n_users, n_docs, n=80):
    checks = []
    for _ in range(n):
        perm = rng.choice(["read", "audit", "reader", "banned"])
        q = rel.must_from_triple(
            f"doc:d{rng.randrange(n_docs)}", perm, f"user:u{rng.randrange(n_users + 2)}"
        )
        if rng.random() < 0.5:
            q = q.with_caveat("", {"t": rng.randint(0, 10)})
        checks.append(q)
    # userset subjects + group/folder-level checks + nonsense
    checks += [
        rel.must_from_tuple("doc:d0#read", "group:g1#member"),
        rel.must_from_tuple("group:g2#member", "group:g0#member"),
        rel.must_from_tuple("group:g2#member", "group:g2#member"),
        rel.must_from_triple("folder:f1", "view", "user:u0"),
        rel.must_from_triple("doc:nope", "read", "user:u0"),
        rel.must_from_triple("doc:d0", "ghost", "user:u0"),
    ]
    return checks


def test_feature_world_exact_no_membership_caveats():
    # recursion present (folder parent chains) but no caveats on
    # membership edges → flat must be device-exact
    rng = random.Random(11)
    rels = build_feature_world(rng)
    engine, dsnap, oracle = world(FEATURES, rels)
    assert_exact(engine, dsnap, oracle, make_checks(rng, 10, 10))


def test_feature_world_many_seeds():
    # soundness bracket only: the tuned-down flat_recursion (3) makes
    # deep folder chains legitimately fall back to the host, so exactness
    # is asserted separately on the seed-11 world whose chains fit
    for seed in (1, 2, 3):
        rng = random.Random(seed)
        rels = build_feature_world(rng)
        engine, dsnap, oracle = world(FEATURES, rels)
        assert_sound_cascade(engine, dsnap, oracle, make_checks(rng, 10, 10, n=48))


def test_flat_matches_legacy_on_caveat_free_world():
    rng = random.Random(5)
    rels = [r for r in build_feature_world(rng) if not r.caveat_name]
    engine, dsnap, oracle = world(FEATURES, rels)
    cs = compile_schema(parse_schema(FEATURES))
    legacy = DeviceEngine(cs, EngineConfig.for_schema(cs, use_flat=False))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    ldsnap = legacy.prepare(snap)
    checks = [c for c in make_checks(rng, 10, 10) if not c.caveat_context]
    fd, fp, fovf = engine.check_batch(dsnap, checks, now_us=NOW)
    ld, lp, lovf = legacy.check_batch(ldsnap, checks, now_us=NOW)
    for i in range(len(checks)):
        if not fovf[i] and not lovf[i]:
            assert bool(fd[i]) == bool(ld[i]), checks[i]
            assert bool(fp[i]) == bool(lp[i]), checks[i]


def _deep_chain_world(chain=14, **cfg):
    rels = [rel.must_from_tuple("folder:f0#owner", "user:deep")]
    for i in range(1, chain):
        rels.append(rel.must_from_tuple(f"folder:f{i}#parent", f"folder:f{i-1}"))
    rels.append(rel.must_from_tuple("doc:d#folder", f"folder:f{chain-1}"))
    engine, dsnap, oracle = world(FEATURES, rels, flat_recursion=4, **cfg)
    checks = [
        rel.must_from_triple("doc:d", "read", "user:deep"),
        rel.must_from_triple("doc:d", "read", "user:other"),
        rel.must_from_triple("folder:f1", "view", "user:deep"),
    ]
    return engine, dsnap, oracle, checks


def test_deep_recursion_beyond_budget_falls_back_not_wrong():
    # folder chain deeper than the recursion budget, with the flattened
    # ancestor index AND the permission fold DISABLED: queries needing the
    # deep walk must surface as possible/overflow (host fallback), and
    # shallow queries stay exact
    engine, dsnap, oracle, checks = _deep_chain_world(
        flat_rc_index=False, flat_fold=False
    )
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    # never a wrong definite
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not d[i] or want == T
    # the deep grant is beyond the budget: must be flagged for the host,
    # not silently denied
    assert (p[0] and not d[0]) or ovf[0]
    # shallow view query is exact
    assert bool(d[2]) == (oracle.check_relationship(checks[2]) == T)


def test_deep_recursion_folded_exact_on_device():
    # with the permission fold (default) and the rc index off, the SAME
    # deep chain resolves exactly at the root probe pair
    engine, dsnap, oracle, checks = _deep_chain_world(flat_rc_index=False)
    assert dsnap.flat_meta.fold_pairs, "permissions should be folded"
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    from gochugaru_tpu.engine.oracle import F

    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not ovf[i]
        assert bool(d[i]) == (want == T), q
        assert bool(p[i]) == (want != F), q


def test_deep_recursion_flattened_exact_on_device():
    # with the resource-side Leopard index and the fold disabled, the
    # SAME deep chain resolves exactly through the walked rc lattice —
    # no host fallback, no overflow
    engine, dsnap, oracle, checks = _deep_chain_world(flat_fold=False)
    assert dsnap.flat_meta.rc_slots, "hierarchy should be flattened"
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    from gochugaru_tpu.engine.oracle import F

    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not ovf[i]
        assert bool(d[i]) == (want == T), q
        assert bool(p[i]) == (want != F), q


def test_arrow_fanout_overflow_flags():
    # a resource with more arrow children than the cap must flag overflow
    rels = [rel.must_from_tuple(f"doc:d#folder", f"folder:f{i}") for i in range(9)]
    rels.append(rel.must_from_tuple("folder:f8#owner", "user:u"))
    engine, dsnap, oracle = world(FEATURES, rels, arrow_fanout=2)
    checks = [rel.must_from_triple("doc:d", "read", "user:u")]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert ovf[0] or bool(d[0]) == (oracle.check_relationship(checks[0]) == T)
    assert ovf[0]  # 9 children > cap 2


def test_userset_fanout_overflow_flags():
    rels = [
        rel.must_from_tuple("doc:d#reader", f"group:g{i}#member") for i in range(12)
    ]
    rels.append(rel.must_from_tuple("group:g11#member", "user:u"))
    checks = [rel.must_from_triple("doc:d", "read", "user:u")]
    # the T-index has no per-(slot, resource) fanout cap: 12 userset edges
    # answer exactly in one probe
    engine, dsnap, oracle = world(FEATURES, rels, us_leaf_cap=4)
    assert dsnap.flat_meta.has_tindex
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert bool(d[0]) and not ovf[0]
    # the KU probe path must flag the capped fanout instead
    engine, dsnap, oracle = world(
        FEATURES, rels, us_leaf_cap=4, flat_tindex=False
    )
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert ovf[0]


def test_closure_source_overflow_routes_to_host():
    # u belongs to more USED groups than closure_source_cap (membership
    # edges into groups never used as subjects don't index) → overflow
    # flag on queries that touch userset probes
    n = 40
    rels = [rel.must_from_tuple(f"group:g{i}#member", "user:u") for i in range(n)]
    rels += [
        rel.must_from_tuple(f"doc:x{i}#reader", f"group:g{i}#member")
        for i in range(n)
    ]
    rels += [
        rel.must_from_tuple(f"doc:d#reader", f"group:g{n-1}#member"),
        rel.must_from_tuple(f"doc:e#reader", "user:u"),
    ]
    engine, dsnap, oracle = world(FEATURES, rels, closure_source_cap=8)
    checks = [
        rel.must_from_triple("doc:d", "read", "user:u"),
        rel.must_from_triple("doc:e", "read", "user:u"),  # no userset probe hit
    ]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert ovf[0]
    # the direct grant is decided without the closure: exact, no fallback
    assert bool(d[1]) and not ovf[1]


def test_permission_valued_userset_flat_possible_only():
    schema = """
    definition user {}
    definition team {
        relation lead: user
        permission heads = lead
    }
    definition doc {
        relation reader: team#heads
        permission read = reader
    }
    """
    rels = [
        rel.must_from_tuple("team:t#lead", "user:u"),
        rel.must_from_tuple("doc:d#reader", "team:t#heads"),
    ]
    engine, dsnap, oracle = world(schema, rels)
    checks = [
        rel.must_from_triple("doc:d", "read", "user:u"),
        rel.must_from_triple("doc:d", "read", "user:v"),
    ]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    # membership through a permission fixpoint: possible-only, host decides
    assert not d[0] and p[0]
    assert oracle.check_relationship(checks[0]) == T
    assert not d[1]


def test_batch_slot_spill_falls_back_to_legacy():
    # more distinct permissions than flat_max_slots → legacy path answers
    schema = "definition user {}\ndefinition d {\n" + "\n".join(
        f"    relation r{i}: user" for i in range(10)
    ) + "\n" + "\n".join(
        f"    permission p{i} = r{i}" for i in range(10)
    ) + "\n}"
    rels = [rel.must_from_tuple(f"d:x#r{i}", "user:u") for i in range(10)]
    engine, dsnap, oracle = world(schema, rels, flat_max_slots=4)
    checks = [rel.must_from_triple("d:x", f"p{i}", "user:u") for i in range(10)]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert all(d)


def test_empty_world_and_empty_batch():
    engine, dsnap, oracle = world(FEATURES, [])
    assert engine.check_batch(dsnap, [], now_us=NOW)[0].shape == (0,)
    checks = [rel.must_from_triple("doc:d", "read", "user:u")]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert not d[0] and not p[0] and not ovf[0]


def test_tindex_matches_ku_path_and_oracle():
    """The T-index (userset edges ⋈ closure) must answer identically to
    the KU probe path on eligible worlds."""
    rng = random.Random(21)
    rels = [r for r in build_feature_world(rng) if not r.caveat_name]
    checks = [c for c in make_checks(rng, 10, 10)]
    eng_t, ds_t, oracle = world(FEATURES, rels)
    assert ds_t.flat_meta.has_tindex
    eng_k, ds_k, _ = world(FEATURES, rels, flat_tindex=False)
    assert not ds_k.flat_meta.has_tindex
    td, tp, tovf = eng_t.check_batch(ds_t, checks, now_us=NOW)
    kd, kp, kovf = eng_k.check_batch(ds_k, checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert bool(td[i]) == bool(kd[i]), q
        assert bool(tp[i]) == bool(kp[i]), q
    assert_sound_cascade(eng_t, ds_t, oracle, checks)


def test_tindex_ineligible_slots_fall_back():
    # a caveated userset row makes its slot ineligible; a permission-
    # valued userset slot likewise — answers stay correct via KU/pus
    schema = """
    caveat c(x int) { x > 0 }
    definition user {}
    definition team {
        relation lead: user
        permission heads = lead
    }
    definition group { relation member: user }
    definition doc {
        relation reader: group#member with c
        relation auditor: team#heads
        relation viewer: group#member
        permission read = reader
        permission audit = auditor
        permission view = viewer
    }
    """
    rels = [
        rel.must_from_tuple("group:g#member", "user:u"),
        rel.must_from_tuple("doc:d#reader", "group:g#member").with_caveat("c", {"x": 1}),
        rel.must_from_tuple("team:t#lead", "user:v"),
        rel.must_from_tuple("doc:d#auditor", "team:t#heads"),
        rel.must_from_tuple("doc:d#viewer", "group:g#member"),
    ]
    engine, dsnap, oracle = world(schema, rels)
    meta = dsnap.flat_meta
    viewer = engine.compiled.slot_of_name["viewer"]
    reader = engine.compiled.slot_of_name["reader"]
    auditor = engine.compiled.slot_of_name["auditor"]
    if meta.has_tindex:
        assert viewer in meta.t_slots
        assert reader not in meta.t_slots
        assert auditor not in meta.t_slots
    checks = [
        rel.must_from_triple("doc:d", "view", "user:u"),
        rel.must_from_triple("doc:d", "read", "user:u").with_caveat("", {"x": 5}),
        rel.must_from_triple("doc:d", "audit", "user:v"),
        rel.must_from_triple("doc:d", "audit", "user:u"),
    ]
    assert_sound_cascade(engine, dsnap, oracle, checks)
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert d[0]  # T-index slot decides on device


def test_blockslice_scatter_parity():
    """The interleaved block-slice layout (flat_blockslice=True, the
    default) and the scattered 1-D probe layout must agree plane-for-plane
    on identical worlds/queries — both layouts stay covered by CI."""
    for seed in (7, 8):
        rng = random.Random(seed)
        rels = build_feature_world(rng)
        checks = make_checks(rng, 10, 10, n=48)
        engine_b, dsnap_b, _ = world(FEATURES, rels)
        assert engine_b.config.flat_blockslice
        assert dsnap_b.flat_meta.blockslice
        engine_s, dsnap_s, _ = world(FEATURES, rels, flat_blockslice=False)
        assert not dsnap_s.flat_meta.blockslice
        db, pb, ob = engine_b.check_batch(dsnap_b, checks, now_us=NOW)
        ds, ps, osc = engine_s.check_batch(dsnap_s, checks, now_us=NOW)
        for i, q in enumerate(checks):
            assert bool(db[i]) == bool(ds[i]), f"definite differs for {q}"
            assert bool(pb[i]) == bool(ps[i]), f"possible differs for {q}"
            assert bool(ob[i]) == bool(osc[i]), f"overflow differs for {q}"


RC_GATED = """
caveat tier(t int, min int) { t >= min }
definition user {}
definition folder {
    relation parent: folder | folder with tier
    relation owner: user
    permission view = owner + parent->view
}
"""


def _gated_chain(chain=12):
    """A deep parent chain with a caveated edge and an expired edge mid-
    chain: flattened ancestor paths must fold per-edge admissibility
    through the closure semiring (definite only via caveat-free live
    paths)."""
    import datetime as dt

    rels = [rel.must_from_tuple("folder:f0#owner", "user:root")]
    for i in range(1, chain):
        r = rel.must_from_tuple(f"folder:f{i}#parent", f"folder:f{i-1}")
        if i == chain // 2:
            r = r.with_caveat("tier", {"min": 5})
        if i == chain - 2:
            r = r.with_expiration(
                dt.datetime.fromtimestamp(
                    NOW / 1e6 - 50, tz=dt.timezone.utc
                )
            )
        rels.append(r)
    # a second branch with fully-live edges into the middle of the chain
    rels.append(rel.must_from_tuple("folder:side#parent", "folder:f3"))
    rels.append(rel.must_from_tuple(f"folder:f{chain//2}#owner", "user:mid"))
    return rels


def test_rc_index_folds_caveats_and_expiry():
    rels = _gated_chain()
    engine, dsnap, oracle = world(RC_GATED, rels, flat_recursion=3)
    assert dsnap.flat_meta.rc_slots, "deep gated chain should be flattened"
    checks = [
        # below the caveated edge: root grant is conditional, mid definite
        rel.must_from_triple("folder:f7", "view", "user:root"),
        rel.must_from_triple("folder:f7", "view", "user:mid"),
        # above the caveated edge: root grant stays definite
        rel.must_from_triple("folder:f4", "view", "user:root"),
        # beyond the EXPIRED edge: nothing flows through it
        rel.must_from_triple("folder:f11", "view", "user:root"),
        rel.must_from_triple("folder:f11", "view", "user:mid"),
        # the side branch re-enters mid-chain below the caveat
        rel.must_from_triple("folder:side", "view", "user:root"),
        rel.must_from_triple("folder:side", "view", "user:mid"),
    ]
    assert_sound_cascade(engine, dsnap, oracle, checks)
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert bool(d[1]) and bool(d[2])  # definite along clean paths
    assert bool(p[0]) and not bool(d[0])  # conditional through the caveat
    assert not bool(p[3]) and not bool(p[4])  # dead past the expiry


def test_rc_index_sharded_deep_chain():
    import jax
    import pytest as _pytest

    if len(jax.devices()) < 8:
        _pytest.skip("needs 8 virtual devices")
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rels = _gated_chain()
    cs = compile_schema(parse_schema(RC_GATED))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    cfg = EngineConfig.for_schema(cs, flat_recursion=3)
    single = DeviceEngine(cs, cfg)
    sds = single.prepare(snap)
    assert sds.flat_meta.rc_slots
    checks = [
        rel.must_from_triple(f"folder:f{i}", "view", u)
        for i in range(12)
        for u in ("user:root", "user:mid")
    ]
    sd, sp, sovf = single.check_batch(sds, checks, now_us=NOW)
    eng = ShardedEngine(cs, make_mesh(2, 4), cfg)
    ds = eng.prepare(snap)
    assert ds.flat_meta.sharded and ds.flat_meta.rc_slots
    d, p, ovf = eng.check_batch(ds, checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert bool(d[i]) == bool(sd[i]), f"definite differs: {q}"
        assert bool(p[i]) == bool(sp[i]), f"possible differs: {q}"
        assert bool(ovf[i]) == bool(sovf[i]), f"ovf differs: {q}"

"""bench.py backend-probe verdict cache: a standalone bench run must not
re-pay the 75 s hung-TPU probe timeout when a previous run on the same
jaxlib/TPU environment already learned the answer (the on-disk
counterpart of run_all.py's GOCHUGARU_BACKEND_PROBED parent-inherit)."""

import json
import subprocess

import pytest


@pytest.fixture()
def bench_mod(monkeypatch, tmp_path):
    import bench

    # neutralize every probe-skipping env short-circuit so the cache
    # path itself is what's under test
    for k in ("JAX_PLATFORMS", "GOCHUGARU_FORCE_CPU",
              "GOCHUGARU_BACKEND_PROBED", "GOCHUGARU_PROBE_CACHE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setattr(bench, "PROBE_CACHE_PATH", str(tmp_path / "probe.json"))
    monkeypatch.setattr(bench, "_PROBE_VERDICT", [])
    return bench


def test_probe_failure_verdict_is_cached(bench_mod, monkeypatch):
    bench = bench_mod
    calls = []

    def fake_run(*a, **kw):
        calls.append(1)
        raise subprocess.TimeoutExpired(cmd="probe", timeout=75)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    reason = bench._probe_backend()
    assert reason and "timed out" in reason
    assert len(calls) == 1
    with open(bench.PROBE_CACHE_PATH) as f:
        blob = json.load(f)
    assert "timed out" in blob["reason"]

    # a fresh process (memo cleared) reads the cache: no subprocess
    monkeypatch.setattr(bench, "_PROBE_VERDICT", [])
    reason2 = bench._probe_backend()
    assert len(calls) == 1, "cached verdict did not skip the probe"
    assert "cached verdict" in reason2


def test_probe_success_verdict_is_cached(bench_mod, monkeypatch):
    bench = bench_mod
    calls = []

    class R:
        returncode = 0
        stdout = "1 tpu\n"
        stderr = ""

    def fake_run(*a, **kw):
        calls.append(1)
        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._probe_backend() is None
    monkeypatch.setattr(bench, "_PROBE_VERDICT", [])
    assert bench._probe_backend() is None  # from cache
    assert len(calls) == 1


def test_probe_cache_keyed_by_environment(bench_mod, monkeypatch):
    """A stale verdict from a different jaxlib/TPU env must NOT be
    reused — the key mismatch forces a fresh probe."""
    bench = bench_mod
    with open(bench.PROBE_CACHE_PATH, "w") as f:
        json.dump({"key": "jaxlib=0.0.0;stale", "reason": "old failure"}, f)
    calls = []

    class R:
        returncode = 0
        stdout = "1 tpu\n"
        stderr = ""

    def fake_run(*a, **kw):
        calls.append(1)
        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._probe_backend() is None
    assert len(calls) == 1, "stale cache was trusted"


def test_probe_cache_disabled(bench_mod, monkeypatch):
    bench = bench_mod
    monkeypatch.setenv("GOCHUGARU_PROBE_CACHE", "0")
    calls = []

    def fake_run(*a, **kw):
        calls.append(1)
        raise subprocess.TimeoutExpired(cmd="probe", timeout=75)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench._probe_backend()
    monkeypatch.setattr(bench, "_PROBE_VERDICT", [])
    bench._probe_backend()
    assert len(calls) == 2, "cache engaged despite GOCHUGARU_PROBE_CACHE=0"
    import os

    assert not os.path.exists(bench.PROBE_CACHE_PATH)
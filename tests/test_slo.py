"""SLO burn-rate engine (utils/slo.py): exact over-objective counting,
multi-window burn math, breach edge-triggering into the flight-recorder
bus, gauge export, and the declarative constructors."""

import time

import pytest

from gochugaru_tpu.utils import trace
from gochugaru_tpu.utils.metrics import Metrics
from gochugaru_tpu.utils.slo import (
    SLOEngine,
    default_slos,
    latency_slo,
    ratio_slo,
)


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


def _engine(m, clock, slos=None, **kw):
    kw.setdefault("windows", (10.0, 60.0))
    kw.setdefault("burn_threshold", 2.0)
    kw.setdefault("tick_s", 1.0)
    return SLOEngine(
        slos=slos if slos is not None else [
            latency_slo("req", "t_s", objective_ms=10.0),
            ratio_slo("shed", bad=("sheds",), total=("reqs",), budget=0.05),
        ],
        registry=m, clock=lambda: clock[0], start=False, **kw,
    )


def test_constructors_validate():
    s = latency_slo("a", "t_s", objective_ms=20.0, quantile=99.0)
    assert s.kind == "latency" and s.objective_s == 0.02
    assert s.budget == pytest.approx(0.01)
    r = ratio_slo("b", bad=("x",), total=("y",), budget=0.05)
    assert r.kind == "ratio" and r.budget == 0.05
    with pytest.raises(ValueError):
        latency_slo("a", "t_s", objective_ms=1.0, quantile=100.0)
    with pytest.raises(ValueError):
        ratio_slo("b", bad=("x",), total=("y",), budget=0.0)
    with pytest.raises(ValueError):
        SLOEngine(slos=[s], windows=(), start=False)


def test_latency_over_objective_counts_are_exact():
    m = Metrics()
    clock = [0.0]
    eng = _engine(m, clock)
    # the engine armed the threshold at construction
    for _ in range(97):
        m.observe("t_s", 0.001)
    for _ in range(3):
        m.observe("t_s", 0.5)
    n, over = m.timer_counts("t_s")
    assert (n, over) == (100, 3)
    clock[0] += 1.0
    rep = eng.tick()
    row = next(s for s in rep["slos"] if s["name"] == "req")
    w = row["windows"]["10s"]
    # 3 bad of 100 against a 1% budget = burn 3.0 — exact, not estimated
    assert w["bad"] == 3 and w["total"] == 100
    assert w["burn"] == pytest.approx(3.0)


def test_ratio_burn_and_gauges():
    m = Metrics()
    clock = [0.0]
    eng = _engine(m, clock)
    # 70 ticks: past the 60s long window's warm-up, so the sustained
    # burn is confirmable in BOTH windows
    for _ in range(70):
        clock[0] += 1.0
        m.inc("reqs", 100)
        m.inc("sheds", 20)  # 20% shed vs 5% budget → burn 4
        eng.tick()
    assert m.gauge("slo.shed.burn_10s") == pytest.approx(4.0, rel=0.05)
    assert m.gauge("slo.shed.burn_60s") == pytest.approx(4.0, rel=0.05)
    assert m.gauge("slo.shed.breached") == 1.0
    assert m.gauge("slo.breached") >= 1.0
    rep = eng.report()
    assert "shed" in rep["breached"] and not rep["healthy"]


def test_multi_window_and_rule_denoises_short_spikes():
    """A burst confined to the short window must NOT breach: the long
    window has to confirm the burn is sustained (the standard
    multi-window AND)."""
    m = Metrics()
    clock = [0.0]
    eng = _engine(m, clock)
    # 55 clean ticks fill the long window with healthy history
    for _ in range(55):
        clock[0] += 1.0
        m.inc("reqs", 100)
        eng.tick()
    # 3 bad ticks: short-window burn blows past the threshold...
    for _ in range(3):
        clock[0] += 1.0
        m.inc("reqs", 100)
        m.inc("sheds", 50)
        rep = eng.tick()
    row = next(s for s in rep["slos"] if s["name"] == "shed")
    assert row["windows"]["10s"]["burn"] > 2.0
    # ...but the 60s window dilutes it below, so no breach
    assert row["windows"]["60s"]["burn"] < 2.0
    assert not row["breached"] and rep["healthy"]


def test_cold_start_blip_cannot_breach_while_warming():
    """Until history covers a window, that window is WARMING and cannot
    confirm a breach: with a short history every window computes the
    same delta off the oldest sample, so without the gate a cold-start
    compile blip (first dispatches way over objective) would page
    instantly — the exact thing the multi-window AND exists to stop."""
    m = Metrics()
    clock = [0.0]
    eng = _engine(m, clock)
    # an immediate 100%-bad storm, but only 5 ticks of history
    for _ in range(5):
        clock[0] += 1.0
        m.inc("reqs", 10)
        m.inc("sheds", 10)
        rep = eng.tick()
    row = next(s for s in rep["slos"] if s["name"] == "shed")
    assert row["windows"]["10s"]["burn"] > 2.0  # burn reported...
    assert row["windows"]["10s"]["warming"] is True  # ...but warming
    assert not row["breached"] and rep["healthy"]
    # once the windows warm, the (still sustained) burn breaches
    for _ in range(65):
        clock[0] += 1.0
        m.inc("reqs", 10)
        m.inc("sheds", 10)
        rep = eng.tick()
    row = next(s for s in rep["slos"] if s["name"] == "shed")
    assert "warming" not in row["windows"]["60s"]
    assert row["breached"]


def test_idle_process_is_healthy_not_breached():
    m = Metrics()
    clock = [0.0]
    eng = _engine(m, clock)
    for _ in range(30):
        clock[0] += 1.0
        rep = eng.tick()
    assert rep["healthy"] and not rep["breached"]
    # zero-traffic windows report burn 0, not NaN/inf
    row = rep["slos"][0]
    assert row["windows"]["10s"]["burn"] == 0.0


def test_breach_edge_fires_one_incident():
    m = Metrics()
    clock = [0.0]
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    rec = trace.install_recorder(
        trace.FlightRecorder(cooldown_s=0.0, grace_s=0.0, registry=m)
    )
    eng = _engine(m, clock)
    # sustained burn across BOTH windows
    for _ in range(70):
        clock[0] += 1.0
        m.inc("reqs", 100)
        m.inc("sheds", 30)
        eng.tick()
    rec.flush()
    slo_burns = [i for i in rec.incident_index()
                 if i["trigger"] == "slo.burn"]
    # edge-triggered: ONE incident for the whole excursion, not per tick
    assert len(slo_burns) == 1
    assert slo_burns[0]["info"]["slo"] == "shed"
    assert m.counter("slo.breaches") == 1.0
    # recovery then re-breach fires a second edge
    for _ in range(80):
        clock[0] += 1.0
        m.inc("reqs", 100)
        eng.tick()
    assert eng.report()["healthy"]
    for _ in range(70):
        clock[0] += 1.0
        m.inc("reqs", 100)
        m.inc("sheds", 30)
        eng.tick()
    rec.flush()
    assert m.counter("slo.breaches") == 2.0


def test_default_slos_cover_the_serving_surfaces():
    names = {s.name for s in default_slos()}
    assert {"check.dispatch", "serve.request", "latency.dispatch",
            "shed", "transient_faults"} <= names
    # latency objectives arm timer thresholds on construction
    m = Metrics()
    eng = SLOEngine(registry=m, start=False)
    m.observe("serve.request_s", 10.0)  # way over any objective
    assert m.timer_counts("serve.request_s") == (1, 1)
    assert eng.report()["ticks"] >= 1  # constructor tick


def test_background_thread_ticks_and_closes():
    m = Metrics()
    eng = SLOEngine(
        slos=[ratio_slo("shed", bad=("sheds",), total=("reqs",),
                        budget=0.05)],
        registry=m, tick_s=0.02, start=True,
    )
    t0 = time.time()
    while eng.report()["ticks"] < 5 and time.time() - t0 < 5.0:
        time.sleep(0.02)
    assert eng.report()["ticks"] >= 5
    eng.close()
    ticks = eng.report()["ticks"]
    time.sleep(0.1)
    assert eng.report()["ticks"] == ticks  # really stopped

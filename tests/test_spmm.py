"""Unified masked-SpMM sparse core (engine/spmm.py) — property-fuzz
parity against BOTH oracles plus the fused-dispatch contracts.

The parity discipline: with ``EngineConfig.spmm`` on (the default) the
fused K-hop programs serve multi-hop lookups in ONE device dispatch and
the T-index join runs through the generic semiring product; with it off
the looped spmv path and the bespoke ``t_join_core`` serve byte-for-byte
as before.  Every fuzzed world here is answered three ways — fused,
legacy-looped, host walker oracle — and all three must agree exactly,
including caveats (conditional-by-construction omitted), expirations,
wildcards, recursive groups, and exclusion/intersection rewrites.

Dispatch contracts asserted on counters, not logs:
- a ≥2-hop LookupResources completes in exactly 1 ``spmm.dispatches``
  with 0 looped ``lookup.dispatches``;
- 100 fused dispatches on one snapshot trace the program exactly once
  (the pinned-executable discipline);
- the ``spmm.dispatch`` fault site classifies into the client retry
  envelope (same contract as ``lookup.dispatch``) and survives a seeded
  probabilistic soak with every answer still exact.
"""

import dataclasses
import random

import numpy as np
import pytest

import test_lookup as tl
from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine import lookup as lm
from gochugaru_tpu.engine import spmv
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.fold import t_join_core
from gochugaru_tpu.engine.oracle import Oracle
from gochugaru_tpu.engine.spmm import masked_semiring_spmm, tjoin_spmm
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils.metrics import default as _m

NOW = tl.NOW


def dual_world(schema, rels):
    """(fused engine+dsnap, legacy engine+dsnap, oracle) over one
    snapshot — the two engines differ ONLY in ``config.spmm``."""
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    eng_on = DeviceEngine(cs)
    assert eng_on.config.spmm, "spmm must default on"
    eng_off = DeviceEngine(
        cs, dataclasses.replace(eng_on.config, spmm=False)
    )
    return (eng_on, eng_on.prepare(snap)), (eng_off, eng_off.prepare(snap)), oracle


def assert_res_parity(on, off, oracle, rtype, perm, s):
    stype, _, rest = s.partition(":")
    sid, _, srel = rest.partition("#")
    fused = lm.lookup_resources_device(
        on[0], on[1], rtype, perm, stype, sid, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    legacy = lm.lookup_resources_device(
        off[0], off[1], rtype, perm, stype, sid, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_resources(rtype, perm, stype, sid, srel))
    assert fused == legacy == want, (
        f"resources({rtype}#{perm}, {s}): fused={fused} legacy={legacy} "
        f"oracle={want}"
    )


def assert_subj_parity(on, off, oracle, rtype, rid, perm, subj):
    stype, _, srel = subj.partition("#")
    fused = lm.lookup_subjects_device(
        on[0], on[1], rtype, rid, perm, stype, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    legacy = lm.lookup_subjects_device(
        off[0], off[1], rtype, rid, perm, stype, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_subjects(rtype, rid, perm, stype, srel))
    assert fused == legacy == want, (
        f"subjects({rtype}:{rid}#{perm}, {subj}): fused={fused} "
        f"legacy={legacy} oracle={want}"
    )


# ---------------------------------------------------------------------------
# property fuzz: fused == legacy == walker on randomized worlds
# ---------------------------------------------------------------------------

FUZZ_SCHEMA = """
caveat lim(v int, cap int) { v <= cap }
definition user {}
definition group {
    relation member: user | group#member | user:*
}
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition proj {
    relation parent: folder
    relation owner: user | group#member
    relation writer: user | group#member | user with lim
    relation banned: user
    permission write = (owner + writer + parent->view) - banned
    permission manage = owner & writer
}
"""


def fuzz_world(seed):
    """Randomized world exercising every gate the semiring multiplies:
    caveats (definite / failing / conditional-by-construction),
    expirations (live and lapsed), wildcards, recursive usersets, arrow
    chains, exclusion and intersection."""
    import datetime as dt

    rng = random.Random(seed)
    users = [f"user:u{i}" for i in range(12)]
    groups = [f"group:g{i}" for i in range(5)]
    folders = [f"folder:f{i}" for i in range(6)]
    projs = [f"proj:p{i}" for i in range(8)]
    past = dt.datetime.fromtimestamp(
        (NOW - 5_000_000) / 1e6, tz=dt.timezone.utc
    )
    future = dt.datetime.fromtimestamp(
        (NOW + 3_600_000_000) / 1e6, tz=dt.timezone.utc
    )
    rels = []

    def maybe_expire(r):
        p = rng.random()
        if p < 0.15:
            return r.with_expiration(past)  # lapsed: grants nothing
        if p < 0.3:
            return r.with_expiration(future)  # live window
        return r

    for g in groups:
        for u in rng.sample(users, 3):
            rels.append(maybe_expire(rel.must_from_tuple(f"{g}#member", u)))
        if rng.random() < 0.5:
            rels.append(rel.must_from_tuple(
                f"{g}#member", f"{rng.choice(groups)}#member"
            ))
        if rng.random() < 0.3:
            rels.append(rel.must_from_tuple(f"{g}#member", "user:*"))
    for i, f in enumerate(folders):
        if i and rng.random() < 0.6:
            rels.append(rel.must_from_tuple(
                f"{f}#parent", folders[rng.randrange(i)]
            ))
        if rng.random() < 0.7:
            rels.append(maybe_expire(
                rel.must_from_tuple(f"{f}#viewer", rng.choice(users))
            ))
        if rng.random() < 0.4:
            rels.append(rel.must_from_tuple(
                f"{f}#viewer", f"{rng.choice(groups)}#member"
            ))
    for p in projs:
        if rng.random() < 0.7:
            rels.append(rel.must_from_tuple(f"{p}#parent", rng.choice(folders)))
        rels.append(rel.must_from_tuple(f"{p}#owner", rng.choice(users)))
        if rng.random() < 0.7:
            rels.append(rel.must_from_tuple(
                f"{p}#owner", f"{rng.choice(groups)}#member"
            ))
        for u in rng.sample(users, 2):
            r = rel.must_from_tuple(f"{p}#writer", u)
            if rng.random() < 0.4:
                r = r.with_caveat(
                    "lim",
                    {"v": rng.randint(0, 9), "cap": 5}
                    if rng.random() < 0.7 else {},
                )
            rels.append(maybe_expire(r))
        if rng.random() < 0.4:
            rels.append(rel.must_from_tuple(f"{p}#banned", rng.choice(users)))
    return rels, users, groups, projs


@pytest.mark.parametrize("seed", [1, 2, 7])
def test_spmm_fuzz_parity(seed):
    rng = random.Random(seed * 31)
    rels, users, groups, projs = fuzz_world(seed)
    on, off, oracle = dual_world(FUZZ_SCHEMA, rels)
    d0 = _m.counter("spmm.dispatches")
    for u in rng.sample(users, 5) + ["user:stranger"]:
        for perm in ("write", "manage"):
            assert_res_parity(on, off, oracle, "proj", perm, u)
    for g in groups:
        assert_res_parity(on, off, oracle, "proj", "write", f"{g}#member")
    for p in rng.sample(projs, 4):
        pid = p.split(":")[1]
        for perm in ("write", "manage"):
            assert_subj_parity(on, off, oracle, "proj", pid, perm, "user")
        assert_subj_parity(
            on, off, oracle, "proj", pid, "write", "group#member"
        )
    # the fused path actually served (not silently falling back)
    assert _m.counter("spmm.dispatches") > d0


# ---------------------------------------------------------------------------
# T-join: the generic semiring product is bitwise the bespoke kernel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_tjoin_spmm_bitwise_parity(seed):
    rng = np.random.RandomState(seed)
    n_us = int(rng.randint(1, 200))
    n_cl = int(rng.randint(1, 300))
    k1 = rng.randint(0, 50, n_us).astype(np.int64)
    pe = rng.randint(0, 40, n_us).astype(np.int64)
    w = rng.randint(1, 1000, n_us).astype(np.int32)
    cl_k1 = rng.randint(0, 60, n_cl).astype(np.int64)
    cl_k2 = rng.randint(0, 40, n_cl).astype(np.int64)
    c_d = rng.randint(0, 1000, n_cl).astype(np.int32)
    c_p = rng.randint(0, 1000, n_cl).astype(np.int32)
    # plenty / tight / guaranteed closure-overflow caps: the size gate
    # must agree too (None == None)
    for cap in (1 << 30, n_us + n_cl // 2, 1):
        a = t_join_core(k1, pe, w, cl_k1, cl_k2, c_d, c_p, cap)
        b = tjoin_spmm(k1, pe, w, cl_k1, cl_k2, c_d, c_p, cap)
        if a is None:
            assert b is None
            continue
        assert b is not None
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert x.dtype == y.dtype
            np.testing.assert_array_equal(x, y)


def test_masked_semiring_identity_term():
    # one A row, empty B: the product is exactly A's identity rows
    got = masked_semiring_spmm(
        np.asarray([7], np.int64), np.asarray([3], np.int64),
        np.asarray([9], np.int32),
        np.empty(0, np.int64), np.empty(0, np.int64),
        (np.empty(0, np.int32), np.empty(0, np.int32)), 16,
    )
    assert got is not None
    np.testing.assert_array_equal(got[0], [7])
    np.testing.assert_array_equal(got[1], [3])
    np.testing.assert_array_equal(got[2], [9])
    np.testing.assert_array_equal(got[3], [9])


# ---------------------------------------------------------------------------
# dispatch contracts: one dispatch, one trace, exact cursors
# ---------------------------------------------------------------------------


def rbac_dual():
    rels, users, teams, orgs, repos = tl.rbac_world()
    on, off, oracle = dual_world(tl.RBAC, rels)
    return on, off, oracle, users, teams, repos


def test_multihop_lookup_is_one_device_dispatch():
    """A LookupResources crossing ≥2 hops (reader + org->admin arrow)
    drains its whole candidate fixpoint in exactly ONE fused dispatch —
    counter-asserted, 0 looped dispatches."""
    on, off, oracle, users, teams, repos = rbac_dual()
    engine, dsnap = on
    st = spmv.state_for(engine, dsnap)
    assert st._spmm is not None, "fused server must be eligible here"
    snap = dsnap.snapshot
    rtid = snap.interner.type_lookup("repo")
    # a user who reaches repos through the 2-hop org->admin arrow
    admin_uid = next(
        u for u in users
        if oracle.lookup_resources("repo", "admin", "user", u.split(":")[1], "")
    )
    un = snap.interner.lookup("user", admin_uid.split(":")[1])
    d0 = _m.counter("spmm.dispatches")
    l0 = _m.counter("lookup.dispatches")
    blocks = list(st.resource_candidates(rtid, un, -1, -1, NOW))
    assert _m.counter("spmm.dispatches") - d0 == 1
    assert _m.counter("lookup.dispatches") - l0 == 0
    cands = set()
    for b in blocks:
        cands.update(int(x) for x in b)
    want = {
        snap.interner.lookup("repo", r)
        for r in oracle.lookup_resources(
            "repo", "admin", "user", admin_uid.split(":")[1], ""
        )
    }
    assert want <= cands, "fused candidates must be a superset"


def test_no_retrace_across_100_fused_dispatches():
    on, off, oracle, users, teams, repos = rbac_dual()
    engine, dsnap = on
    st = spmv.state_for(engine, dsnap)
    assert st._spmm is not None
    snap = dsnap.snapshot
    rtid = snap.interner.type_lookup("repo")
    kern = st._spmm.kern
    t0 = dict(kern.traces)
    d0 = _m.counter("spmm.dispatches")
    rng = random.Random(11)
    for i in range(100):
        u = rng.choice(users).split(":")[1]
        un = snap.interner.lookup("user", u)
        list(st.resource_candidates(rtid, un, -1, -1, NOW))
    assert _m.counter("spmm.dispatches") - d0 == 100
    # the pinned path: ONE trace serves all 100 dispatches
    assert kern.traces["res"] - t0.get("res", 0) == 1


def test_cursor_resume_across_fused_dispatch():
    """Paged draining over the fused path: cursors round-trip through
    their string encoding, and an evicted stream recompute-resumes to
    the identical continuation (the fused program is deterministic)."""
    on, off, oracle, users, teams, repos = rbac_dual()
    engine, dsnap = on
    full = {}
    for u in users[:4]:
        sid = u.split(":")[1]
        full[u] = lm.lookup_resources_device(
            engine, dsnap, "repo", "read", "user", sid, "",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
    for u in users[:4]:
        sid = u.split(":")[1]
        out, cursor, pages = [], None, 0
        while True:
            ids, cursor = lm.lookup_resources_page(
                engine, dsnap, "repo", "read", "user", sid, "",
                page_size=2, cursor=cursor, now_us=NOW,
                oracle_factory=lambda: oracle,
            )
            out.extend(ids)
            pages += 1
            if cursor is None:
                break
            cursor = spmv.LookupCursor.decode(cursor.encode())
            # evict the live stream: the next page exercises the
            # deterministic recompute-and-skip across a fused dispatch
            if pages % 2 == 1:
                dsnap.__dict__.get("_lookup_streams", {}).clear()
        assert sorted(out) == full[u]
        assert len(out) == len(set(out)), "no duplicates across pages"


def test_spmm_parity_survives_overflow_fallback():
    """Force every fused capacity to overflow: answers must still be
    exact (the looped path serves), with fallbacks counted."""
    rels, users, teams, orgs, repos = tl.rbac_world()
    cs = compile_schema(parse_schema(tl.RBAC))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    oracle = Oracle(cs, rels, {}, now_us=NOW)
    eng = DeviceEngine(cs)
    eng = DeviceEngine(cs, dataclasses.replace(
        eng.config, spmm_rounds=1, spmm_candidates=2,
    ))
    dsnap = eng.prepare(snap)
    f0 = _m.counter("spmm.fallbacks")
    for u in users[:4]:
        sid = u.split(":")[1]
        got = lm.lookup_resources_device(
            eng, dsnap, "repo", "read", "user", sid, "",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        want = sorted(oracle.lookup_resources("repo", "read", "user", sid, ""))
        assert got == want
    assert _m.counter("spmm.fallbacks") > f0


# ---------------------------------------------------------------------------
# the spmm.dispatch fault site: retry envelope + seeded soak
# ---------------------------------------------------------------------------


def _client_world():
    from gochugaru_tpu import new_tpu_evaluator
    from gochugaru_tpu.rel.txn import Txn
    from gochugaru_tpu.utils.context import background

    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, tl.RBAC)
    rels, users, teams, orgs, repos = tl.rbac_world(
        seed=3, n_users=10, n_repos=6
    )
    txn = Txn()
    for r in rels:
        txn.create(r)
    rev = c.write(ctx, txn)
    return c, ctx, rev, users


def test_client_envelope_retries_spmm_dispatch_fault():
    from gochugaru_tpu import consistency
    from gochugaru_tpu.utils.metrics import default as m

    c, ctx, rev, users = _client_world()
    cs = consistency.at_least(rev)
    base_retries = m.counter("retry.retries")
    with faults.default.armed("spmm.dispatch", times=1) as spec:
        got = sorted(c.lookup_resources(ctx, cs, "repo#read", users[0]))
    assert spec.fired == 1
    assert m.counter("retry.retries") >= base_retries + 1
    snap = c.store.snapshot_for(cs)
    oracle = c._oracle_for(snap)
    stype, sid = users[0].split(":")
    assert got == sorted(oracle.lookup_resources("repo", "read", stype, sid, ""))


def test_spmm_dispatch_chaos_soak():
    """Seeded probabilistic faulting of the fused dispatch across a
    burst of client lookups: every call either retries to the exact
    answer or sheds classified — never a wrong answer, never a raw
    traceback."""
    from gochugaru_tpu import consistency
    from gochugaru_tpu.utils.errors import AuthzError, UnavailableError

    c, ctx, rev, users = _client_world()
    cs = consistency.at_least(rev)
    snap = c.store.snapshot_for(cs)
    oracle = c._oracle_for(snap)
    rng = random.Random(20260806)
    sheds = 0
    faults.arm("spmm.dispatch", probability=0.35, seed=20260806)
    try:
        for i in range(25):
            u = rng.choice(users)
            stype, sid = u.split(":")
            try:
                got = sorted(c.lookup_resources(ctx, cs, "repo#read", u))
            except UnavailableError:
                sheds += 1  # classified shed after exhausted retries: ok
                continue
            except BaseException as e:
                assert isinstance(e, AuthzError), f"unclassified: {e!r}"
                raise
            assert got == sorted(
                oracle.lookup_resources("repo", "read", stype, sid, "")
            )
    finally:
        faults.disarm("spmm.dispatch")
    assert faults.default.spec("spmm.dispatch") is None

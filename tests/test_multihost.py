"""Multi-host (multi-process) skeleton: jax.distributed over CPU.

The 2-process dryrun is the moral equivalent of the reference's
serve-testing container (SURVEY.md §4): real process boundaries, real
collectives (Gloo), the same sharded kernel.  It closes SURVEY §5's
"distributed communication backend" item — ICI/DCN selection is XLA's
job once the mesh spans processes (parallel/multihost.py docstring maps
the v5e-16 deployment).
"""

from gochugaru_tpu.parallel.multihost import dryrun_multihost


def test_two_process_dryrun():
    # spawns 2 CPU processes × 4 virtual devices joined by
    # jax.distributed; every process verifies its addressable result
    # shards and the parent asserts full batch coverage
    dryrun_multihost(n_processes=2, n_devices=8)


def test_four_process_dryrun():
    # 4 CPU processes x 2 virtual devices each over one 8-device global
    # mesh: the v5e-16 two-slice shape's process count, halved devices
    # (VERDICT r04 item 9).  Every process must verify its shard rows
    # against the host oracle.
    dryrun_multihost(n_processes=4, n_devices=8, timeout_s=900)

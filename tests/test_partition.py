"""Unit parity of the partition-first stacking primitives
(engine/partition.py) against the build-full-then-stack reference
(engine/flat.py _stack_point/_stack_range over engine/hash.py
build_hash/build_range_hash): the partitioned build must be BITWISE
identical — offsets, group tables, row tables, pads — across empty /
tiny / duplicate-heavy / native-threshold-crossing inputs, and the
owned-subset (ShardSlices) form must equal the corresponding slices of
the full arrays."""

import numpy as np
import pytest

from gochugaru_tpu.engine.flat import _stack_point, _stack_range
from gochugaru_tpu.engine.hash import build_hash, build_range_hash
from gochugaru_tpu.engine.partition import (
    _hash_cols,
    gather_cols,
    point_geom,
    range_geom,
    shard_order,
    stack_point,
    stack_range,
)
from gochugaru_tpu.native.sort import sorted_runs


def _keys(rng, n, dup_frac):
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    k1 = rng.integers(0, max(int(n * (1 - dup_frac)), 2), n).astype(np.int32)
    k2 = rng.integers(0, 1 << 20, n).astype(np.int32)
    return k1, k2


@pytest.mark.parametrize("n", [0, 1, 37, 5_000, 80_000])
@pytest.mark.parametrize("M", [1, 2, 8])
def test_stack_point_bitwise(n, M):
    rng = np.random.default_rng(n * 31 + M)
    k1, k2 = _keys(rng, n, dup_frac=0.3)
    pay = rng.integers(-1, 1 << 15, n).astype(np.int32)
    cols = [k1, k2, pay]
    ms = max(8, M)

    h = build_hash([k1, k2], min_size=ms)
    ref_off, ref_tbl = _stack_point(h, cols, M)

    h_full = _hash_cols([k1, k2])
    geom = point_geom(h_full, M, min_size=ms)
    assert (geom.size, geom.cap, geom.n) == (h.size, h.cap, h.n)
    got_off, got_tbl = stack_point(h_full, gather_cols(cols), geom, len(cols))
    assert got_off.dtype == ref_off.dtype and np.array_equal(got_off, ref_off)
    assert got_tbl.shape == ref_tbl.shape
    assert np.array_equal(got_tbl, ref_tbl)

    # owned-subset slices == the full arrays' corresponding blocks
    owned = [0, M - 1] if M > 1 else [0]
    so, st = stack_point(h_full, gather_cols(cols), geom, len(cols), owned=owned)
    for s in owned:
        assert np.array_equal(
            so.blocks[s], ref_off[s * (geom.bpd + 1) : (s + 1) * (geom.bpd + 1)]
        )
        assert np.array_equal(
            st.blocks[s], ref_tbl[s * geom.R_pad : (s + 1) * geom.R_pad]
        )


@pytest.mark.parametrize("n", [0, 1, 53, 7_000, 80_000])
@pytest.mark.parametrize("M", [2, 4])
def test_stack_range_bitwise(n, M):
    rng = np.random.default_rng(n * 13 + M)
    # a sorted group-key column with skewed run lengths + payload rows
    k = np.sort(rng.integers(0, max(n // 6, 2), n)).astype(np.int32)
    r1 = rng.integers(0, 1 << 20, n).astype(np.int32)
    r2 = rng.integers(-1, 9, n).astype(np.int32)
    ms = max(8, M)
    fan_pad = 64

    ri = build_range_hash(k, min_size=ms)
    ref_goff, ref_gtbl, ref_rows, ref_cap = _stack_range(ri, [r1, r2], M, fan_pad)

    if n:
        starts = sorted_runs(k)
        ends = np.concatenate([starts[1:], np.asarray([n])])
        gk = np.ascontiguousarray(k[starts], np.int32)
        glo, lens = starts, ends - starts
    else:
        gk = np.zeros(0, np.int32)
        glo = lens = np.zeros(0, np.int64)
    h_g = _hash_cols([gk])
    geom = range_geom(gk, lens, h_g, M, min_size=ms, fan_pad=fan_pad)
    assert geom.cap == ref_cap
    assert geom.max_run == ri.max_run
    got_goff, got_gtbl, got_rows = stack_range(
        gk, glo, lens, h_g, gather_cols([r1, r2]), geom, 2
    )
    assert np.array_equal(got_goff, ref_goff)
    assert got_gtbl.shape == ref_gtbl.shape
    assert np.array_equal(got_gtbl, ref_gtbl)
    assert got_rows.shape == ref_rows.shape
    assert np.array_equal(got_rows, ref_rows)

    owned = [1]
    so, sg, sr = stack_range(
        gk, glo, lens, h_g, gather_cols([r1, r2]), geom, 2, owned=owned
    )
    bpd = geom.gh.bpd
    for s in owned:
        assert np.array_equal(
            so.blocks[s], ref_goff[s * (bpd + 1) : (s + 1) * (bpd + 1)]
        )
        assert np.array_equal(
            sg.blocks[s], ref_gtbl[s * geom.G_pad : (s + 1) * geom.G_pad]
        )
        assert np.array_equal(
            sr.blocks[s], ref_rows[s * geom.R_pad : (s + 1) * geom.R_pad]
        )


def test_point_geom_frozen_growth_branch():
    """Past 2^24 entries build_hash freezes table growth and point_geom
    switches to per-shard histograms (no O(size) int64 histogram): the
    geometry must equal the direct global-histogram computation."""
    from gochugaru_tpu.engine.hash import _ceil_pow2

    n = (1 << 24) + 11
    h = np.random.default_rng(0).integers(0, 1 << 32, n, dtype=np.uint32)
    M = 8
    g = point_geom(h, M, min_size=8)
    assert g.size == _ceil_pow2(2 * n, 8)  # frozen: no growth
    counts = np.bincount(
        (h & np.uint32(g.size - 1)).astype(np.int64), minlength=g.size
    )
    assert g.cap == int(counts.max())
    shard_rows = counts.reshape(M, g.size // M).sum(axis=1)
    assert g.R_pad == _ceil_pow2(int(shard_rows.max()) + max(64, g.cap))


def test_stack_point_precomputed_order_bitwise():
    """stack_point(order=...) — the frozen-geometry reuse path (>16M
    rows hands point_geom's own (order, starts) back in) — must equal
    the self-computed partition bitwise, full and owned-subset."""
    rng = np.random.default_rng(5)
    k1, k2 = _keys(rng, 20_000, dup_frac=0.4)
    pay = rng.integers(-1, 1 << 15, 20_000).astype(np.int32)
    cols = [k1, k2, pay]
    M = 8
    h_full = _hash_cols([k1, k2])
    geom = point_geom(h_full, M, min_size=M)
    ord_starts = shard_order(h_full, geom.size, M)
    ref_off, ref_tbl = stack_point(h_full, gather_cols(cols), geom, len(cols))
    got_off, got_tbl = stack_point(
        h_full, gather_cols(cols), geom, len(cols), order=ord_starts
    )
    assert np.array_equal(got_off, ref_off)
    assert np.array_equal(got_tbl, ref_tbl)
    so, st = stack_point(
        h_full, gather_cols(cols), geom, len(cols),
        owned=[1, 6], order=ord_starts,
    )
    for s in (1, 6):
        assert np.array_equal(
            st.blocks[s], ref_tbl[s * geom.R_pad : (s + 1) * geom.R_pad]
        )


def test_point_geom_return_order_matches_shard_order():
    """return_order=True: None on the histogram branch; on the frozen
    branch (>2^24 rows) exactly shard_order's (order, starts)."""
    rng = np.random.default_rng(6)
    h_small = rng.integers(0, 1 << 32, 4_096, dtype=np.uint32)
    g, os_ = point_geom(h_small, 4, min_size=8, return_order=True)
    assert os_ is None and g.n == 4_096

    n = (1 << 24) + 7
    h_big = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    g, os_ = point_geom(h_big, 8, min_size=8, return_order=True)
    assert os_ is not None
    order, starts = os_
    ref_order, ref_starts = shard_order(h_big, g.size, 8)
    assert np.array_equal(order, ref_order)
    assert np.array_equal(starts, ref_starts)


def test_shard_order_is_stable_partition():
    rng = np.random.default_rng(2)
    h = rng.integers(0, 1 << 32, 10_000, dtype=np.uint32)
    size, M = 1 << 12, 8
    order, starts = shard_order(h, size, M)
    bpd = size // M
    for s in range(M):
        rows = order[starts[s] : starts[s + 1]]
        assert np.all(np.diff(rows) > 0)  # original order preserved
        assert np.all((h[rows] & (size - 1)) // bpd == s)
    assert starts[-1] == h.shape[0]

"""Performance-attribution subsystem (gochugaru_tpu/utils/perf.py):
the gathered-bytes model's closure (per-level == per-table == total)
and recursion-depth coverage, cost_analysis capture at pin time plus
the graceful decline when a backend refuses it, pad-waste accounting,
the bandwidth microbench's fingerprint cache, the wall-time ledger's
priority attribution and its 100%±ε closure under a chaos soak (armed
``latency.dispatch``/``batcher.form`` faults — retry/backoff time
attributed, not lost), the /perf telemetry endpoint, and the
bench_compare direction registry for the new perf columns."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.utils import faults, metrics, perf
from gochugaru_tpu.utils.context import background

CS = consistency.full()
EPOCH = 1_700_000_000_000_000


def _store_world():
    c = new_tpu_evaluator(with_latency_mode())
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    rng = np.random.default_rng(11)
    txn = rel.Txn()
    for i in range(150):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{rng.integers(40)}"
        ))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 4}"))
    for o in range(4):
        txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
        txn.touch(rel.must_from_triple(
            f"org:o{o}", "member", f"user:u{o + 8}"
        ))
    c.write(ctx, txn)
    oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))
    return c, oracle


@pytest.fixture(scope="module")
def world():
    return _store_world()


def _dsnap_of(c):
    snap = c.store.snapshot_for(CS)
    eng = c._engine_for(snap)
    return eng, c._dsnap_for(eng, snap)


def _rand_checks(rng, n):
    return [
        rel.must_from_triple(
            f"repo:r{rng.integers(150)}", "read", f"user:u{rng.integers(40)}"
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# gathered-bytes model
# ---------------------------------------------------------------------------

def test_bytes_model_closes_and_covers_levels(world):
    """total == Σ per_level == Σ per_table, and the arrow-bearing world
    contributes recursion levels BEYOND the root dispatch (the old
    est_bytes_per_check docstring admitted it excluded them)."""
    c, _ = world
    _, ds = _dsnap_of(c)
    model = perf.gathered_bytes_model(ds)
    assert model.total > 0
    assert abs(sum(model.per_level) - model.total) < 1e-6
    assert abs(sum(model.per_table.values()) - model.total) < 1e-6
    # repo->org arrows: deeper levels must be modeled (level 1+ nonzero)
    assert len(model.per_level) > 1 and model.per_level[1] > 0
    # every charged table is a real device array
    assert set(model.per_table) <= set(ds.arrays)


def test_common_delegates_to_ledger(world):
    """benchmarks/common keeps ONE implementation: the ledger's."""
    from benchmarks.common import est_bytes_per_check, table_bytes

    c, _ = world
    _, ds = _dsnap_of(c)
    assert est_bytes_per_check(ds) == perf.est_bytes_per_check(ds)
    assert table_bytes(ds) == perf.table_bytes(ds)
    assert table_bytes(ds) == sum(
        int(getattr(v, "nbytes", 0)) for v in ds.arrays.values()
    )


def test_model_published_at_prepare(world):
    c, _ = world
    _, ds = _dsnap_of(c)
    perf.publish_model(ds)
    m = metrics.default
    assert m.gauge("perf.bytes_per_check") == perf.est_bytes_per_check(ds)
    assert m.gauge("perf.bytes_per_check.level0") > 0
    assert perf.last_model() is not None


# ---------------------------------------------------------------------------
# cost ledger
# ---------------------------------------------------------------------------

class _FakeCompiled:
    """Stands in for jax.stages.Compiled across backend behaviors."""

    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca

    def memory_analysis(self):
        raise RuntimeError("no memory stats either")


def test_record_cost_normalizes_backends():
    perf.reset_cost_ledger()
    e = perf.record_cost(
        "t", "list", _FakeCompiled([{"flops": 10.0, "bytes accessed": 4.0}])
    )
    assert e["flops"] == 10.0 and e["bytes_accessed"] == 4.0
    e = perf.record_cost("t", "dict", _FakeCompiled({"flops": 3.0}))
    assert e["flops"] == 3.0
    perf.reset_cost_ledger()


def test_cost_analysis_unavailable_degrades_to_meta_model(world):
    """Satellite regression: a backend whose cost_analysis returns None
    or raises must not error — the entry records 'unavailable', the
    ``perf.cost_analysis_unavailable`` gauge counts it, and the roofline
    columns still come from the meta model."""
    perf.reset_cost_ledger()
    m = metrics.default
    base = m.gauge("perf.cost_analysis_unavailable", 0.0)
    e1 = perf.record_cost("t", "none", _FakeCompiled(None))
    e2 = perf.record_cost("t", "raise", _FakeCompiled(RuntimeError("nope")))
    assert e1["unavailable"] and e2["unavailable"]
    assert m.gauge("perf.cost_analysis_unavailable") == base + 2
    # the meta model is untouched by the decline: roofline columns work
    c, _ = world
    _, ds = _dsnap_of(c)
    cols = perf.roofline_columns(1e6, dsnap=ds)
    assert cols["bytes_per_check"] > 0
    assert cols["achieved_gbps"] > 0
    assert cols["roofline_frac"] > 0
    perf.reset_cost_ledger()


def test_thunk_failure_is_graceful():
    """A lazy thunk that blows up on realization records an
    'unavailable' entry instead of breaking cost_entries()."""
    perf.reset_cost_ledger()

    def boom():
        raise RuntimeError("lowering exploded")

    perf.register_cost_thunk("t", "boom", boom)
    ents = perf.cost_entries(realize=True)
    hit = next(e for e in ents if e["key"] == "boom")
    assert hit["unavailable"] and "lowering exploded" in hit["error"]
    perf.reset_cost_ledger()


def test_latency_pin_captures_cost_and_pad(world):
    """A pinned-tier dispatch records its executable's cost analysis at
    pin time (free: the Compiled is in hand) and feeds the pad ledger
    live-vs-padded lanes."""
    c, _ = world
    eng, ds = _dsnap_of(c)
    lp = eng.latency_path(ds)
    m = metrics.default
    snap = c.store.snapshot_for(CS)
    it = snap.interner
    slot = snap.compiled.slot_of_name
    B = 33
    q_res = np.array([it.node("repo", f"r{i}") for i in range(B)], np.int32)
    q_perm = np.full(B, slot["read"], np.int32)
    q_subj = np.array([it.node("user", f"u{i % 40}") for i in range(B)],
                      np.int32)
    live0 = m.counter("perf.pad.live_lanes")
    total0 = m.counter("perf.pad.total_lanes")
    out = lp.dispatch_columns(q_res, q_perm, q_subj, now_us=EPOCH)
    assert out is not None
    pins = [e for e in perf.cost_entries() if e["kind"] == "latency_pin"]
    assert pins, "pin-time capture missing"
    assert all(e.get("flops") or e.get("unavailable") for e in pins)
    assert m.counter("perf.pad.live_lanes") - live0 == B
    assert m.counter("perf.pad.total_lanes") - total0 == lp.last_budget.tier
    stats = perf.pad_stats()
    assert 0 <= stats["pad_fraction"] < 1
    assert str(lp.last_budget.tier) in stats["per_tier"]


def test_batch_path_registers_lazy_thunk(world):
    """The throughput path registers a LAZY cost capture at kernel-cache
    time (no compile on the serving path) that realizes on demand."""
    c, _ = world
    eng, ds = _dsnap_of(c)
    perf.reset_cost_ledger()
    rng = np.random.default_rng(3)
    snap = c.store.snapshot_for(CS)
    it = snap.interner
    slot = snap.compiled.slot_of_name
    B = 64
    q_res = np.array([it.node("repo", f"r{i}") for i in range(B)], np.int32)
    q_perm = np.full(B, slot["read"], np.int32)
    q_subj = np.array(
        [it.node("user", f"u{rng.integers(40)}") for _ in range(B)], np.int32
    )
    eng.check_columns(ds, q_res, q_perm, q_subj, now_us=EPOCH)
    pend = [e for e in perf.cost_entries() if e["kind"] == "batch"]
    assert pend and pend[0].get("pending"), pend
    ents = perf.cost_entries(realize=True)
    got = [e for e in ents if e["kind"] == "batch"]
    assert got and not any(e.get("pending") for e in got)
    assert got[0].get("flops") or got[0].get("unavailable")
    perf.reset_cost_ledger()


# ---------------------------------------------------------------------------
# roofline meter
# ---------------------------------------------------------------------------

def test_bandwidth_cache_fingerprint(tmp_path, monkeypatch):
    """The microbench measures once per backend fingerprint; a second
    read serves the cached verdict, a refresh re-measures, a stale
    fingerprint re-measures."""
    p = tmp_path / "roofline.json"
    monkeypatch.setattr(perf, "ROOFLINE_CACHE_PATH", str(p))
    bw = perf.measure_bandwidth(size_mb=2, reps=2)
    assert bw["gbps"] > 0 and not bw["cached"]
    bw2 = perf.measure_bandwidth(size_mb=2, reps=2)
    assert bw2["cached"] and bw2["gbps"] == bw["gbps"]
    # stale fingerprint → the cached verdict no longer stands
    blob = json.loads(p.read_text())
    blob["fingerprint"] = "jaxlib=other;backend=tpu;kind=v6e;n=8"
    p.write_text(json.dumps(blob))
    bw3 = perf.measure_bandwidth(size_mb=2, reps=2)
    assert not bw3["cached"]
    assert metrics.default.gauge("perf.roofline_gbps") == bw3["gbps"]


def test_roofline_columns_math(tmp_path, monkeypatch):
    p = tmp_path / "roofline.json"
    monkeypatch.setattr(perf, "ROOFLINE_CACHE_PATH", str(p))
    perf.measure_bandwidth(size_mb=2, reps=2)
    # fresh registry: the pallas byte-model gauges are process-global,
    # and an earlier test's fused prepare would otherwise override the
    # XLA bytes_per_check as the "active backend" traffic
    cols = perf.roofline_columns(
        2_000_000.0, bytes_per_check=100.0, registry=metrics.Metrics()
    )
    assert cols["bytes_per_check"] == 100.0
    assert cols["bytes_accessed_per_check"] == 100.0
    assert "pallas_bytes_saved_per_check" not in cols
    assert cols["achieved_gbps"] == round(100.0 * 2e6 / 1e9, 3)
    assert cols["roofline_frac"] == round(
        cols["achieved_gbps"] / cols["roofline_gbps"], 4
    )


# ---------------------------------------------------------------------------
# wall-time ledger
# ---------------------------------------------------------------------------

def test_wall_attribution_priority_and_closure():
    """Synthetic intervals: overlap resolves by priority (kernel beats
    filter beats queue_wait), uncovered time is idle, and the buckets
    sum to the window EXACTLY — the closure property by construction."""
    w = perf.WallLedger()
    w.start()
    t0 = w.t_start
    # filter spans [0, 10]; kernel overlays [2, 5]; queue_wait [8, 14]
    w._report("filter", t0 + 0.0, t0 + 0.010)
    w._report("kernel", t0 + 0.002, t0 + 0.005)
    w._report("queue_wait", t0 + 0.008, t0 + 0.014)
    while time.perf_counter() < t0 + 0.016:
        time.sleep(0.001)
    res = w.stop()
    s = res["seconds"]
    assert abs(s["kernel"] - 0.003) < 1e-9
    assert abs(s["filter"] - 0.007) < 1e-9  # 10ms minus the kernel overlay
    assert abs(s["queue_wait"] - 0.004) < 1e-9  # [10, 14]: filter wins [8,10]
    assert s["idle"] > 0
    assert abs(sum(s.values()) - res["window_s"]) < 1e-4
    # closure comes from the UNROUNDED sums: exact by construction even
    # on a sub-100µs window (where µs-rounded bucket seconds would read
    # percent-level noise)
    assert res["closure_frac"] == 1.0
    assert 0 < res["named_frac"] < 1


def test_wall_report_noop_without_window():
    """No armed window → report_wall is a no-op (and cheap)."""
    assert perf._WALL is None
    perf.report_wall("kernel", 0.0, 1.0)  # must not raise or leak


def test_wall_interval_bound():
    w = perf.WallLedger()
    old = perf.WALL_INTERVAL_MAX
    try:
        perf.WALL_INTERVAL_MAX = 4
        w.start()
        t0 = w.t_start
        for i in range(10):
            w._report("filter", t0, t0 + 0.001)
        res = w.stop()
        assert res["intervals"] == 4 and res["dropped"] == 6
        assert res["closure_frac"] >= 0.99
    finally:
        perf.WALL_INTERVAL_MAX = old
        perf._WALL = None


def test_wall_ledger_closes_under_serving(world):
    """Real serving traffic: the window's buckets account ≈100% of wall
    time and the device stages appear (the bench9 row block's
    contract)."""
    c, oracle = world
    ctx = background()
    rng = np.random.default_rng(5)
    w = perf.WallLedger().start()
    with c.with_serving() as h:
        futs = []
        for k in range(48):
            futs.append(h.submit(ctx, *_rand_checks(rng, 8),
                                 client_id=k % 4))
        got = [f.result(timeout=60.0) for f in futs]
    res = w.stop()
    # closure is structural (idle is the residual) — the accounting's
    # teeth are zero drops + the expected named buckets being nonzero
    assert res["closure_frac"] >= 0.95, res
    assert res["dropped"] == 0, res
    assert res["named_frac"] > 0, res
    assert res["seconds"]["kernel"] > 0, res
    assert res["seconds"]["host_prep"] > 0, res
    assert perf.last_wall() is res or perf.last_wall() == res
    m = metrics.default
    assert m.gauge("perf.wall.closure_frac") >= 0.95
    # spot-check answers stayed correct under the window
    want = oracle.check(ctx, CS, *_rand_checks(np.random.default_rng(5), 8))
    assert len(want) == 8 and len(got) == 48


def test_wall_ledger_closure_under_chaos(world):
    """Satellite: with ``latency.dispatch`` and ``batcher.form`` armed
    at seeded probabilities the ledger STILL closes to 100%±ε, and the
    retry/backoff + form-retry time is attributed (nonzero buckets),
    not lost to idle."""
    c, oracle = world
    ctx = background()
    rng = np.random.default_rng(9)
    m = metrics.default
    r0 = m.counter("retry.retries")
    w = perf.WallLedger().start()
    with faults.default.armed("latency.dispatch", probability=0.25, seed=4), \
         faults.default.armed("batcher.form", probability=0.25, seed=5):
        with c.with_serving() as h:
            errors = []

            def worker(k):
                lr = np.random.default_rng(k)
                for _ in range(6):
                    qs = _rand_checks(lr, 5)
                    try:
                        got = h.check(ctx.with_timeout(60.0), *qs,
                                      client_id=k)
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        return
                    want = oracle.check(ctx, CS, *qs)
                    if list(got) != list(want):
                        errors.append((got, want))

            ts = [threading.Thread(target=worker, args=(k,))
                  for k in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
    res = w.stop()
    assert not errors, errors[:3]
    assert res["closure_frac"] >= 0.95, res
    assert res["dropped"] == 0, res
    retried = m.counter("retry.retries") - r0
    assert retried > 0, "chaos never engaged the retry envelope"
    # attributed, not lost: the backoff pauses and the former's fault
    # retries show up as named buckets
    assert res["seconds"]["backoff"] > 0, res
    assert res["seconds"]["form"] > 0, res


# ---------------------------------------------------------------------------
# /perf endpoint + incident context
# ---------------------------------------------------------------------------

def test_perf_endpoint_serves_ledger(world, tmp_path, monkeypatch):
    from gochugaru_tpu.utils.telemetry import TelemetryServer

    monkeypatch.setattr(
        perf, "ROOFLINE_CACHE_PATH", str(tmp_path / "roofline.json")
    )
    c, _ = world
    _, ds = _dsnap_of(c)
    perf.publish_model(ds)
    srv = TelemetryServer(port=0)
    try:
        def get(path):
            with urllib.request.urlopen(srv.url + path, timeout=30) as r:
                return json.loads(r.read().decode())

        rep = get("/perf")
        assert rep["bytes_model"]["total"] == round(
            perf.est_bytes_per_check(ds), 1
        )
        assert rep["bytes_model"]["per_table"]
        assert "pad" in rep and "cost" in rep
        assert rep["roofline"] is None  # fresh cache path, no bench ask
        rep2 = get("/perf?bench=1")
        assert rep2["roofline"] and rep2["roofline"]["gbps"] > 0
        rep3 = get("/perf")  # now cached
        assert rep3["roofline"]["gbps"] == rep2["roofline"]["gbps"]
    finally:
        srv.close()


def test_context_state_is_cheap_and_complete(world):
    c, _ = world
    _, ds = _dsnap_of(c)
    perf.publish_model(ds)
    st = perf.context_state()
    assert st["bytes_per_check"] == round(perf.est_bytes_per_check(ds), 1)
    assert "pad" in st and "cost_entries" in st and "wall" in st
    json.dumps(st)  # bundle-serializable


# ---------------------------------------------------------------------------
# bench_compare direction registry (satellite)
# ---------------------------------------------------------------------------

def _bench_compare():
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "bench_compare.py",
    )
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_compare_perf_column_directions():
    bc = _bench_compare()
    # higher-is-better: a drop must read as regression
    assert not bc.lower_is_better("serve_openloop_goodput.roofline_frac", "")
    assert not bc.lower_is_better(
        "rbac_2hop_bulk_check_throughput.achieved_gbps", "checks/sec/chip"
    )
    # lower-is-better: pad share shrinking is the win
    assert bc.lower_is_better("serve_openloop_goodput.pad_fraction",
                              "checks/sec")
    # the perf columns are promoted off headline rows from round one
    for fld in ("achieved_gbps", "roofline_frac", "pad_fraction"):
        assert fld in bc._PROMOTED_FIELDS


def test_bench_compare_cache_column_directions():
    """The verdict-cache bench columns are direction-aware from round
    one: hit_rate/dedup_frac falling is a regression (same pattern as
    the PR-12 achieved_gbps fix — ``dedup_frac`` must not fall into any
    lower-better suffix bucket, and ``cache_hit_rate`` ends with
    ``hit_rate`` so headline and sweep rows both resolve)."""
    bc = _bench_compare()
    assert not bc.lower_is_better("serve_openloop_goodput.cache_hit_rate", "")
    assert not bc.lower_is_better("serve_cache_ab.hit_rate", "checks/sec")
    assert not bc.lower_is_better("serve_openloop_goodput.dedup_frac", "")
    assert not bc.lower_is_better("serve_cache_ab.cache_speedup", "x")
    assert "cache_hit_rate" in bc._PROMOTED_FIELDS
    # dedup_frac is direction-registered but deliberately NOT promoted
    # (workload-noise-sized absolute values would flap the trajectory)
    assert "dedup_frac" not in bc._PROMOTED_FIELDS
    # direction actually drives the verdict
    old = {"h.cache_hit_rate": {"value": 0.9, "unit": "", "platform": ""}}
    new = {"h.cache_hit_rate": {"value": 0.5, "unit": "", "platform": ""}}
    rows, regressions = bc.compare(old, new, "r01", "r02", 0.10)
    assert regressions == 1 and "REGRESSED" in "\n".join(rows)


def test_bench_compare_flags_roofline_regression():
    bc = _bench_compare()
    old = {
        "h.roofline_frac": {"value": 0.5, "unit": "checks/sec", "platform": ""},
        "h.pad_fraction": {"value": 0.5, "unit": "checks/sec", "platform": ""},
    }
    new = {
        "h.roofline_frac": {"value": 0.3, "unit": "checks/sec", "platform": ""},
        "h.pad_fraction": {"value": 0.3, "unit": "checks/sec", "platform": ""},
    }
    rows, regressions = bc.compare(old, new, "r01", "r02", 0.10)
    assert regressions == 1  # roofline_frac fell; pad_fraction improved
    table = "\n".join(rows)
    assert "REGRESSED" in table and "improved" in table


def test_bench_compare_extracts_promoted_perf_fields(tmp_path):
    bc = _bench_compare()
    doc = {"tail": json.dumps({
        "metric": "m", "value": 1.0, "unit": "checks/sec",
        "achieved_gbps": 1.5, "roofline_frac": 0.2, "pad_fraction": 0.1,
    })}
    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps(doc))
    got = bc.metrics_of(str(p))
    assert got["m.achieved_gbps"]["value"] == 1.5
    assert got["m.roofline_frac"]["value"] == 0.2
    assert got["m.pad_fraction"]["value"] == 0.1


def test_bench_compare_provenance_column_directions():
    """The decision-provenance columns are direction-aware from round
    one: explain_overhead_frac growing means the zero-cost contract is
    eroding, decisions_dropped growing is an audit-trail hole — both
    lower-better and promoted off headline rows (the PR-12/13 pattern)."""
    bc = _bench_compare()
    assert bc.lower_is_better("explain_smoke.explain_overhead_frac", "ok")
    assert bc.lower_is_better("explain_smoke.decisions_dropped", "ok")
    # the trailing "_frac" must not read as higher-better via the
    # roofline_frac rule
    assert not bc.lower_is_better("serve_openloop_goodput.roofline_frac", "")
    for fld in ("explain_overhead_frac", "decisions_dropped"):
        assert fld in bc._PROMOTED_FIELDS


def test_bench_compare_spmm_column_directions():
    """The fused-SpMM bench columns are direction-aware from round one:
    ``mixed_users_rate`` (bench8's 48-random-user candidate rate, the
    dispatch-floor workload the fused path exists for) falling is a
    regression; ``dispatches_per_lookup`` growing means the K-hop fusion
    is regressing to per-hop loops.  Both promoted off headline rows."""
    bc = _bench_compare()
    assert not bc.lower_is_better(
        "lookup_fused_vs_looped.mixed_users_rate", "x"
    )
    assert not bc.lower_is_better(
        "lookup_candidates_per_s.mixed_users_rate", "candidates/sec/chip"
    )
    assert bc.lower_is_better(
        "lookup_fused_vs_looped.dispatches_per_lookup", "x"
    )
    assert bc.lower_is_better(
        "lookup_candidates_per_s.dispatches_per_lookup",
        "candidates/sec/chip",
    )
    for fld in ("mixed_users_rate", "dispatches_per_lookup"):
        assert fld in bc._PROMOTED_FIELDS
    # direction actually drives the verdict both ways
    old = {
        "l.mixed_users_rate": {"value": 9e5, "unit": "x", "platform": ""},
        "l.dispatches_per_lookup": {"value": 1.0, "unit": "x",
                                    "platform": ""},
    }
    new = {
        "l.mixed_users_rate": {"value": 3e5, "unit": "x", "platform": ""},
        "l.dispatches_per_lookup": {"value": 3.9, "unit": "x",
                                    "platform": ""},
    }
    rows, regressions = bc.compare(old, new, "r05", "r06", 0.10)
    assert regressions == 2 and "REGRESSED" in "\n".join(rows)


def test_bench_compare_host_bound_escape():
    """A higher-better row measuring at its OWN host's bandwidth ceiling
    (``roofline_frac`` within tolerance of 1.0) flags ``host-bound``
    instead of failing: software can't beat the memory wall, so the
    round-over-round drop is the container, not the code.  Lower-better
    rows get no such escape, and a row below the ceiling still fails."""
    bc = _bench_compare()
    old = {"t": {"value": 12.6e6, "unit": "checks/sec/chip",
                 "platform": "cpu"}}
    at_ceiling = {"t": {"value": 5.8e6, "unit": "checks/sec/chip",
                        "platform": "cpu", "roofline_frac": 0.958}}
    rows, regressions = bc.compare(old, at_ceiling, "r05", "r06", 0.10)
    assert regressions == 0 and "host-bound" in "\n".join(rows)
    below_ceiling = {"t": {"value": 5.8e6, "unit": "checks/sec/chip",
                           "platform": "cpu", "roofline_frac": 0.55}}
    rows, regressions = bc.compare(old, below_ceiling, "r05", "r06", 0.10)
    assert regressions == 1 and "REGRESSED" in "\n".join(rows)
    # no escape for latency rows: at-ceiling bandwidth doesn't excuse a
    # p99 that tripled
    old_ms = {"t_p99_ms": {"value": 9.0, "unit": "ms", "platform": "cpu"}}
    new_ms = {"t_p99_ms": {"value": 30.0, "unit": "ms", "platform": "cpu",
                           "roofline_frac": 0.958}}
    rows, regressions = bc.compare(old_ms, new_ms, "r05", "r06", 0.10)
    assert regressions == 1
    # promoted companions inherit the parent row's roofline_frac
    import json as _json
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "BENCH_r06.json")
        row = {"metric": "m", "value": 1.0, "unit": "checks/sec/chip",
               "true_rate": 0.9, "roofline_frac": 0.97, "platform": "cpu"}
        with open(p, "w") as f:
            _json.dump({"tail": _json.dumps(row), "parsed": None}, f)
        mets = bc.metrics_of(p)
    assert mets["m"]["roofline_frac"] == 0.97
    assert mets["m.true_rate"]["roofline_frac"] == 0.97


def test_bench_compare_pallas_column_directions():
    """The pallas ledger columns are direction-aware from round one:
    modeled HBM bytes per check shrinking is the fused kernel's whole
    point, while MORE VMEM-resident hot state is the win — its raw
    ``_bytes`` suffix must not fall into the lower-better unit bucket."""
    bc = _bench_compare()
    assert bc.lower_is_better(
        "rbac_2hop_bulk_check_throughput.bytes_accessed_per_check", ""
    )
    assert not bc.lower_is_better("vmem_resident_bytes", "bytes")
    assert not bc.lower_is_better(
        "pallas_smoke_bytes_saved_frac", "fraction of XLA bytes/check"
    )
    for fld in ("bytes_accessed_per_check", "vmem_resident_bytes"):
        assert fld in bc._PROMOTED_FIELDS

"""Fleet serving (gochugaru_tpu/fleet): replicated processes behind the
consistent-hash router.

In-process topology for tier-1 speed: the router and replicas live in
this process as objects, but every byte between them crosses real
localhost sockets through the framed wire protocol — the same path the
subprocess deployment (scripts/fleetd.py, benchmarks/bench10_fleet.py)
uses.  Covered here:

- bootstrap + streamed coherence: replica verdicts match the host
  oracle for every consistency strategy;
- zookie read-your-writes through the router (including blocking for
  catchup — never serving stale);
- failover: seeded replica kill mid-traffic with zero lost/duplicated
  answers, ring eviction, `fleet.failover` incident, rejoin;
- the four fleet fault sites (router.dispatch, router.health,
  replica.apply, replica.kill);
- satellites: WatchConfig resume budget, transport-error
  classification, replica identity on decision log entries.
"""

import threading
import time
from dataclasses import replace

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    WatchConfig,
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_store,
    with_verdict_cache,
)
from gochugaru_tpu.fleet import FleetConfig, FleetRouter, HashRing, Replica
from gochugaru_tpu.fleet import wire as fwire
from gochugaru_tpu.fleet import zookie
from gochugaru_tpu.utils import decisions as _decisions
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils import trace
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    DeadlineExceededError,
    UnavailableError,
    classify_dispatch_exception,
)

SCHEMA = """
definition user {}
definition team { relation member: user }
definition doc {
    relation owner: user
    relation reader: user | team#member
    relation banned: user
    permission read = reader + owner - banned
}
"""

#: test posture: sub-100ms failure detection, short freshness waits
CFG = replace(
    FleetConfig(),
    probe_interval_s=0.05,
    probe_timeout_s=0.5,
    freshness_wait_s=3.0,
    freshness_poll_s=0.02,
    heartbeat_s=0.05,
)


@pytest.fixture(autouse=True)
def _hygiene():
    faults.reset()
    yield
    faults.reset()
    trace.install_recorder(None)
    _decisions.set_identity(None)


def _world(router):
    ctx = background()
    router.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    for i in range(16):
        txn.touch(rel.must_from_triple(f"doc:d{i}", "owner", f"user:u{i % 5}"))
        txn.touch(rel.must_from_triple(f"doc:d{i}", "reader", f"user:r{i % 7}"))
    txn.touch(rel.must_from_triple("team:core", "member", "user:tm"))
    txn.touch(rel.must_from_tuple("doc:d0#reader", "team:core#member"))
    txn.touch(rel.must_from_triple("doc:d1", "banned", "user:r1"))
    router.write(ctx, txn)


def _replica(router, rid, cfg=CFG):
    return Replica(
        ("127.0.0.1", router.port),
        replica_id=rid,
        config=cfg,
        client_options=(with_verdict_cache(), with_host_only_evaluation()),
    )


@pytest.fixture
def fleet():
    router = FleetRouter(config=CFG)
    _world(router)
    reps = [_replica(router, f"r{i}") for i in range(3)]
    for r in reps:
        router.add_replica(r.host, r.port, wait_ready_s=5.0)
    yield router, reps
    router.close()
    for r in reps:
        r.close()


def _queries():
    qs = [
        rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i % 5}")
        for i in range(8)
    ]
    qs += [
        rel.must_from_triple(f"doc:d{i}", "read", f"user:r{i % 7}")
        for i in range(8)
    ]
    qs.append(rel.must_from_triple("doc:d0", "read", "user:tm"))
    qs.append(rel.must_from_triple("doc:d1", "read", "user:r1"))  # banned
    qs.append(rel.must_from_triple("doc:d2", "read", "user:nobody"))
    return qs


# -- hash ring --------------------------------------------------------------


def test_ring_stability_and_spread():
    ring = HashRing(vnodes=32)
    for m in ("a", "b", "c"):
        ring.add(m)
    keys = [f"doc:d{i}" for i in range(500)]
    owners = {k: ring.owner(k) for k in keys}
    spread = {m: sum(1 for o in owners.values() if o == m) for m in "abc"}
    # virtual nodes keep the split rough-thirds, not degenerate
    assert all(50 < n < 450 for n in spread.values()), spread
    # removing one member must not move keys between survivors
    ring.remove("b")
    for k in keys:
        if owners[k] != "b":
            assert ring.owner(k) == owners[k]
    assert ring.owner("anything") in {"a", "c"}
    ring.remove("a")
    ring.remove("c")
    assert ring.owner("anything") is None


# -- wire codecs ------------------------------------------------------------


def test_wire_rel_roundtrip_preserves_caveat_and_expiration():
    import datetime as dt

    r = rel.must_from_triple("doc:d1", "reader", "user:u1").with_caveat(
        "tod", {"hour": 9}
    ).with_expiration(
        dt.datetime(2030, 1, 1, tzinfo=dt.timezone.utc)
    )
    back = fwire.rel_from_wire(fwire.rel_to_wire(r))
    assert back == r
    u = rel.Update(rel.UpdateType.DELETE, r)
    bu = fwire.update_from_wire(fwire.update_to_wire(u))
    assert bu.update_type == rel.UpdateType.DELETE
    assert bu.relationship == r


def test_wire_strategy_roundtrip():
    for cs in (
        consistency.full(),
        consistency.min_latency(),
        consistency.at_least("gtz1.5"),
        consistency.snapshot("gtz1.9"),
    ):
        assert fwire.strategy_from_wire(fwire.strategy_to_wire(cs)) == cs


def test_policy_for_mapping():
    assert consistency.policy_for(consistency.full()) == ("head", None)
    assert consistency.policy_for(consistency.min_latency()) == ("any", None)
    assert consistency.policy_for(consistency.at_least("gtz1.3")) == (
        "at_least", "gtz1.3",
    )
    assert consistency.policy_for(consistency.snapshot("gtz1.3")) == (
        "exact", "gtz1.3",
    )


# -- coherence --------------------------------------------------------------


def test_replica_parity_all_strategies(fleet):
    router, _ = fleet
    ctx = background()
    oracle = new_tpu_evaluator(
        with_store(router.store), with_host_only_evaluation()
    )
    qs = _queries()
    want = oracle.check(ctx, consistency.full(), *qs)
    at = consistency.at_least(
        zookie.revision_token(zookie.mint(router.head_revision))
    )
    for cs in (consistency.min_latency(), consistency.full(), at):
        assert router.check(ctx, cs, *qs) == want, cs


def test_streamed_write_reaches_replicas_exactly_once(fleet):
    router, reps = fleet
    ctx = background()
    for n in range(6):
        txn = rel.Txn()
        txn.touch(rel.must_from_triple(f"doc:w{n}", "reader", "user:wr"))
        router.write(ctx, txn)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if all(r.head == router.head_revision for r in reps):
            break
        time.sleep(0.02)
    for r in reps:
        assert r.head == router.head_revision
        # content parity, not just head parity
        assert (
            sorted(map(str, r._store.live_relationships()))
            == sorted(map(str, router.store.live_relationships()))
        )


def test_replica_apply_advances_serving_and_invalidates_vcache():
    """Staleness regression: applying watch deltas must ADVANCE what
    MIN_LATENCY serves (apply_replicated alone never materializes, so a
    replica would keep answering from its bootstrap-era generation and
    that generation's cached verdicts forever), and verdict-cache shards
    for store generations the LRU retired must drop, counted as
    ``fleet.vcache_invalidations``."""
    m = _metrics.default
    router = FleetRouter(config=CFG)
    _world(router)
    r = _replica(router, "rv-fresh")
    router.add_replica(r.host, r.port, wait_ready_s=5.0)
    try:
        ctx = background()
        q = rel.must_from_triple("doc:fresh", "read", "user:fu")
        # warm the replica's verdict cache on the stale (False) verdict
        assert router.check(ctx, consistency.min_latency(), q) == [False]
        inv0 = m.counter("fleet.vcache_invalidations")
        # first write flips the verdict; the rest churn generations past
        # the store's keep_generations LRU so shard retirement is visible
        for n in range(6):
            txn = rel.Txn()
            txn.touch(rel.must_from_triple("doc:fresh", "reader", "user:fu"))
            txn.touch(rel.must_from_triple(f"doc:churn{n}", "reader", "user:cu"))
            router.write(ctx, txn)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and r.head != router.head_revision:
            time.sleep(0.02)
        assert r.head == router.head_revision
        # a MIN_LATENCY check must serve the applied write — the fresh
        # keyspace, not the bootstrap generation's cached False
        assert router.check(ctx, consistency.min_latency(), q) == [True]
        assert m.counter("fleet.vcache_invalidations") > inv0
        # residency report stays coherent: every cached shard's revision
        # is a generation the store still keeps
        h = r.health()
        assert set(h["cache"]["revisions"]) <= set(h["resident"])
    finally:
        router.close()
        r.close()


def test_zookie_read_your_writes(fleet):
    router, _ = fleet
    ctx = background()
    for n in range(5):
        txn = rel.Txn()
        q = rel.must_from_triple(f"doc:ryw{n}", "reader", "user:me")
        txn.touch(q)
        zk = router.write(ctx, txn)
        got = router.check(
            ctx, consistency.min_latency(),
            rel.must_from_triple(f"doc:ryw{n}", "read", "user:me"),
            zookie=zk,
        )
        assert got == [True], n


def test_future_zookie_blocks_for_catchup_never_stale():
    m = _metrics.default
    router = FleetRouter(config=CFG)
    _world(router)
    r0 = _replica(router, "lagger")
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        ctx = background()
        r0.pause_tail()
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:late", "reader", "user:lw"))
        zk = router.write(ctx, txn)
        waits_before = m.counter("fleet.fresh_waits")
        # un-pause only after the dispatch has started waiting
        t = threading.Timer(0.3, r0.resume_tail)
        t.start()
        got = router.check(
            background().with_timeout(10.0), consistency.min_latency(),
            rel.must_from_triple("doc:late", "read", "user:lw"),
            zookie=zk,
        )
        t.join()
        assert got == [True]
        assert m.counter("fleet.fresh_waits") > waits_before
    finally:
        router.close()
        r0.close()


def test_no_fresh_replica_sheds_classified_not_stale():
    cfg = replace(CFG, freshness_wait_s=0.3)
    router = FleetRouter(config=cfg)
    _world(router)
    r0 = _replica(router, "stuck", cfg)
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        ctx = background().with_timeout(1.5)
        r0.pause_tail()
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:never", "reader", "user:nv"))
        zk = router.write(background(), txn)
        with pytest.raises((UnavailableError, DeadlineExceededError)):
            router.check(
                ctx, consistency.min_latency(),
                rel.must_from_triple("doc:never", "read", "user:nv"),
                zookie=zk,
            )
    finally:
        router.close()
        r0.close()


def test_invalid_zookie_fails_before_dispatch(fleet):
    router, _ = fleet
    with pytest.raises(zookie.InvalidZookieError):
        router.check(
            background(), consistency.min_latency(),
            rel.must_from_triple("doc:d0", "read", "user:u0"),
            zookie="zk1.999.forgedforgedforged00",
        )


# -- failover ---------------------------------------------------------------


def test_replica_kill_failover_and_rejoin(fleet, tmp_path):
    router, reps = fleet
    m = _metrics.default
    rec = trace.install_recorder(trace.FlightRecorder(
        incident_dir=str(tmp_path), grace_s=0.0, cooldown_s=0.0,
    ))
    ctx = background()
    oracle = new_tpu_evaluator(
        with_store(router.store), with_host_only_evaluation()
    )
    qs = _queries()
    want = oracle.check(ctx, consistency.full(), *qs)
    kills_before = m.counter("fleet.kill_detections")

    # kill one replica the way the chaos soak does: over the wire
    conn = fwire.Conn((reps[1].host, reps[1].port))
    with pytest.raises(ConnectionError):
        conn.request({"op": "kill"})
    conn.close()

    # traffic through the kill window: every answer exact, none lost
    for _ in range(25):
        got = router.check(
            background().with_timeout(15.0), consistency.full(), *qs
        )
        assert got == want

    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if sorted(router.status()["ring"]) == ["r0", "r2"]:
            break
        time.sleep(0.02)
    assert sorted(router.status()["ring"]) == ["r0", "r2"]
    assert m.counter("fleet.kill_detections") > kills_before
    rec.flush()
    assert any(
        e["trigger"] == "fleet.failover" and e["info"]["replica"] == "r1"
        for e in rec.incident_index()
    )

    # a restarted replica bootstraps, catches up, and rejoins the ring
    r1b = _replica(router, "r1b")
    reps.append(r1b)
    router.add_replica(r1b.host, r1b.port, wait_ready_s=5.0)
    assert sorted(router.status()["ring"]) == ["r0", "r1b", "r2"]
    assert router.check(ctx, consistency.full(), *qs) == want


def test_router_dispatch_fault_reroutes(fleet):
    router, _ = fleet
    m = _metrics.default
    ctx = background().with_timeout(15.0)
    qs = _queries()[:6]
    oracle = new_tpu_evaluator(
        with_store(router.store), with_host_only_evaluation()
    )
    want = oracle.check(background(), consistency.full(), *qs)
    before = m.counter("fleet.reroutes")
    with faults.armed("router.dispatch", times=2, seed=7):
        assert router.check(ctx, consistency.full(), *qs) == want
    assert m.counter("fleet.reroutes") >= before + 2


def test_router_health_fault_storm_evicts_then_rejoins():
    router = FleetRouter(config=CFG)
    _world(router)
    r0 = _replica(router, "flappy")
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        with faults.armed("router.health", probability=1.0, times=6, seed=3):
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if not router.status()["ring"]:
                    break
                time.sleep(0.02)
            assert not router.status()["ring"]
        # probes recover → the replica re-enters on its next ready probe
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if router.status()["ring"] == ["flappy"]:
                break
            time.sleep(0.02)
        assert router.status()["ring"] == ["flappy"]
    finally:
        router.close()
        r0.close()


def test_replica_apply_fault_tail_resumes_exactly_once():
    m = _metrics.default
    router = FleetRouter(config=CFG)
    _world(router)
    r0 = _replica(router, "applier")
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        ctx = background()
        with faults.armed("replica.apply", probability=0.5, seed=11):
            for n in range(12):
                txn = rel.Txn()
                txn.touch(
                    rel.must_from_triple(f"doc:af{n}", "reader", "user:af")
                )
                router.write(ctx, txn)
            deadline = time.monotonic() + 8.0
            while time.monotonic() < deadline:
                if r0.head == router.head_revision:
                    break
                time.sleep(0.02)
        assert r0.head == router.head_revision
        # exactly-once: full content parity after faulted redelivery
        assert (
            sorted(map(str, r0._store.live_relationships()))
            == sorted(map(str, router.store.live_relationships()))
        )
        assert m.counter("fleet.tail_resumes") > 0
    finally:
        router.close()
        r0.close()


def test_not_ready_replica_drained_without_failover_alarm():
    cfg = replace(CFG, ready_lag=2)
    m = _metrics.default
    router = FleetRouter(config=cfg)
    _world(router)
    r0 = _replica(router, "slowpoke", cfg)
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        kills_before = m.counter("fleet.kill_detections")
        r0.pause_tail()
        ctx = background()
        for n in range(6):  # push it past ready_lag
            txn = rel.Txn()
            txn.touch(rel.must_from_triple(f"doc:nr{n}", "reader", "user:x"))
            router.write(ctx, txn)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if not router.status()["ring"]:
                break
            time.sleep(0.02)
        # drained from the ring — but this is backpressure, not a death:
        # no kill detection, no failover incident
        assert not router.status()["ring"]
        assert m.counter("fleet.kill_detections") == kills_before
        r0.resume_tail()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if router.status()["ring"] == ["slowpoke"]:
                break
            time.sleep(0.02)
        assert router.status()["ring"] == ["slowpoke"]
    finally:
        router.close()
        r0.close()


# -- group commit over the wire ---------------------------------------------


def test_group_commit_replicates_as_one_entry():
    """A router.write_group is ONE log entry: every transaction's zookie
    resolves, but the replica tail sees exactly one applied frame whose
    revision jumps base→base+k (counted as fleet.group_applies)."""
    m = _metrics.default
    router = FleetRouter(config=CFG)
    _world(router)
    r0 = _replica(router, "grouped")
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    try:
        ctx = background()
        applied_before = m.counter("fleet.applied_entries")
        groups_before = m.counter("fleet.write_groups")
        gapplies_before = m.counter("fleet.group_applies")
        base = router.head_revision
        txns = []
        for n in range(8):
            txn = rel.Txn()
            txn.touch(rel.must_from_triple(f"doc:gc{n}", "reader", "user:gw"))
            txns.append(txn)
        zks = router.write_group(ctx, txns)
        assert not any(isinstance(z, BaseException) for z in zks)
        # dense zookies base+1..base+8, head at base+8
        assert [zookie.parse(z) for z in zks] == [base + 1 + i for i in range(8)]
        assert router.head_revision == base + 8
        assert m.counter("fleet.write_groups") == groups_before + 1
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and r0.head != router.head_revision:
            time.sleep(0.02)
        assert r0.head == router.head_revision
        # the whole group crossed the wire as ONE applied entry
        assert m.counter("fleet.applied_entries") == applied_before + 1
        assert m.counter("fleet.group_applies") == gapplies_before + 1
        # read-your-writes through the group's last zookie
        got = router.check(
            ctx, consistency.min_latency(),
            rel.must_from_triple("doc:gc7", "read", "user:gw"),
            zookie=zks[-1],
        )
        assert got == [True]
        # a per-slot ejection stays per-slot across the wire
        dup = rel.Txn()
        dup.create(rel.must_from_triple("doc:gc0", "reader", "user:gw"))
        ok = rel.Txn()
        ok.touch(rel.must_from_triple("doc:gc8", "reader", "user:gw"))
        out = router.write_group(ctx, [dup, ok])
        assert isinstance(out[0], BaseException)
        assert zookie.parse(out[1]) == base + 9
    finally:
        router.close()
        r0.close()


def test_group_commit_replica_kill_replays_without_double_apply():
    """Replica killed mid-group-stream: groups committed while it is
    dead replay to a restarted replica from its bootstrap cursor, with
    full content parity — the dup guard makes redelivery exactly-once
    even when each redelivered entry spans a whole group."""
    m = _metrics.default
    router = FleetRouter(config=CFG)
    _world(router)
    r0 = _replica(router, "gk0")
    router.add_replica(r0.host, r0.port, wait_ready_s=5.0)
    r0b = None
    try:
        ctx = background()

        def _group(tag, k=6):
            txns = []
            for n in range(k):
                txn = rel.Txn()
                txn.touch(
                    rel.must_from_triple(f"doc:{tag}{n}", "reader", "user:gk")
                )
                txns.append(txn)
            return txns

        zks = router.write_group(ctx, _group("gka"))
        assert not any(isinstance(z, BaseException) for z in zks)

        # kill the replica the way the chaos soak does: over the wire
        conn = fwire.Conn((r0.host, r0.port))
        with pytest.raises(ConnectionError):
            conn.request({"op": "kill"})
        conn.close()

        # two more groups land while no replica is alive to stream them
        for tag in ("gkb", "gkc"):
            zks = router.write_group(ctx, _group(tag))
            assert not any(isinstance(z, BaseException) for z in zks)

        # a restarted replica bootstraps past some groups and tails the
        # rest; any redelivered prefix must be a no-op (no double-apply)
        r0b = _replica(router, "gk0b")
        router.add_replica(r0b.host, r0b.port, wait_ready_s=5.0)
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            if r0b.head == router.head_revision:
                break
            time.sleep(0.02)
        assert r0b.head == router.head_revision
        assert (
            sorted(map(str, r0b._store.live_relationships()))
            == sorted(map(str, router.store.live_relationships()))
        )
        # group zookies minted before the kill resolve on the rejoined
        # replica — revision numbering survived the replay
        got = router.check(
            ctx, consistency.min_latency(),
            rel.must_from_triple("doc:gkc5", "read", "user:gk"),
            zookie=zks[-1],
        )
        assert got == [True]
    finally:
        router.close()
        r0.close()
        if r0b is not None:
            r0b.close()


# -- satellites -------------------------------------------------------------


def test_transport_errors_classify_retriable():
    import socket

    for e in (
        ConnectionError("boom"),
        ConnectionResetError("reset"),
        BrokenPipeError("pipe"),
        socket.timeout("slow"),
        TimeoutError("slow"),
        fwire.WireClosed("closed mid-frame"),
    ):
        c = classify_dispatch_exception(e)
        assert isinstance(c, UnavailableError), e
        assert c.__cause__ is e
    assert classify_dispatch_exception(ValueError("nope")) is None


def test_watch_config_storm_threshold_and_cursor(tmp_path):
    """Satellite: the resume-storm threshold is a WatchConfig knob and
    the storm incident carries the cursor position."""
    c = new_tpu_evaluator(with_host_only_evaluation())
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:w0", "reader", "user:w"))
    c.write(ctx, txn)
    rec = trace.install_recorder(trace.FlightRecorder(
        incident_dir=str(tmp_path), grace_s=0.0, cooldown_s=0.0,
    ))
    watch_ctx = background().with_cancel()
    stream = c.updates_since_revision(
        watch_ctx, rel.UpdateFilter(), "gtz1.1",
        config=WatchConfig(max_resumes=16, storm_resumes=3),
    )
    seen = [next(stream)]  # cursor advances past the first update
    # every subsequent delivery faults: no-progress resumes accumulate
    with faults.armed("watch.stream", probability=1.0, times=4, seed=1):
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:w1", "reader", "user:w"))
        c.write(ctx, txn)
        seen.append(next(stream))
    watch_ctx.cancel()
    rec.flush()
    storms = [
        e for e in rec.incident_index()
        if e["trigger"] == "watch.resume_storm"
    ]
    assert storms, "configured storm threshold (3) never fired"
    # the incident carries the full cursor: revision AND raw offset
    assert storms[0]["info"]["no_progress"] == 3
    assert storms[0]["info"]["cursor_rev"] == 2
    assert "cursor_offset" in storms[0]["info"]
    assert [u.relationship.resource_id for u in seen] == ["w0", "w1"]


def test_watch_config_max_resumes_surfaces():
    c = new_tpu_evaluator(with_host_only_evaluation())
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:m0", "reader", "user:m"))
    c.write(ctx, txn)
    watch_ctx = background().with_cancel()
    stream = c.updates_since_revision(
        watch_ctx, rel.UpdateFilter(), "gtz1.1",
        config=WatchConfig(max_resumes=2, storm_resumes=99),
    )
    with faults.armed("watch.stream", probability=1.0, seed=2):
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:m1", "reader", "user:m"))
        c.write(ctx, txn)
        with pytest.raises(UnavailableError):
            next(stream)
    watch_ctx.cancel()


def test_decision_log_carries_replica_identity():
    from gochugaru_tpu.utils.decisions import DecisionLog

    log = _decisions.install(DecisionLog())
    _decisions.set_identity("replica-test-7")
    try:
        c = new_tpu_evaluator(with_host_only_evaluation())
        ctx = background()
        c.write_schema(ctx, SCHEMA)
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:dl", "reader", "user:dl"))
        c.write(ctx, txn)
        c.check(
            ctx, consistency.full(),
            rel.must_from_triple("doc:dl", "read", "user:other"),
        )
        entries = log.tail(10)
        assert entries, "no decision entries recorded"
        assert all(e["replica"] == "replica-test-7" for e in entries)
    finally:
        _decisions.set_identity(None)
        _decisions.install(None)

"""Store tests: write semantics, preconditions, revisions/consistency,
reads, deletes, import, watch, and snapshot materialization."""

import datetime as dt
import threading

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.schema.compiler import SchemaValidationError
from gochugaru_tpu.store.store import Store, parse_revision
from gochugaru_tpu.utils.errors import (
    AlreadyExistsError,
    PreconditionFailedError,
    RevisionUnavailableError,
)

EXAMPLE = """
definition user {}
definition document {
    relation writer: user
    relation reader: user

    permission edit = writer
    permission view = reader + edit
}
"""


def make_store():
    s = Store()
    s.write_schema(EXAMPLE)
    return s


def test_write_returns_increasing_revisions():
    s = make_store()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    r1 = s.write(txn)
    txn2 = rel.Txn()
    txn2.touch(rel.must_from_triple("document:a", "writer", "user:jim"))
    r2 = s.write(txn2)
    assert parse_revision(r2) > parse_revision(r1)


def test_create_fails_on_duplicate_touch_upserts():
    s = make_store()
    r = rel.must_from_triple("document:a", "reader", "user:jim")
    txn = rel.Txn()
    txn.create(r)
    s.write(txn)
    dup = rel.Txn()
    dup.create(r)
    with pytest.raises(AlreadyExistsError):
        s.write(dup)
    up = rel.Txn()
    up.touch(r)
    s.write(up)  # idempotent
    assert len(s) == 1


def test_delete_removes_and_is_idempotent():
    s = make_store()
    r = rel.must_from_triple("document:a", "reader", "user:jim")
    txn = rel.Txn()
    txn.create(r)
    s.write(txn)
    d = rel.Txn()
    d.delete(r)
    s.write(d)
    assert len(s) == 0
    s.write(d)  # deleting nonexistent is a no-op
    assert len(s) == 0


def test_write_validates_against_schema():
    s = make_store()
    bad = rel.Txn()
    bad.create(rel.must_from_triple("document:a", "ghost", "user:jim"))
    with pytest.raises(SchemaValidationError):
        s.write(bad)
    perm = rel.Txn()
    perm.create(rel.must_from_triple("document:a", "view", "user:jim"))
    with pytest.raises(SchemaValidationError):
        s.write(perm)  # cannot write to a permission


def test_preconditions():
    s = make_store()
    guard = rel.must_from_triple("document:a", "writer", "user:amy").filter()
    txn = rel.Txn()
    txn.must_match(guard)
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    with pytest.raises(PreconditionFailedError):
        s.write(txn)  # nothing matches yet — atomic, nothing applied
    assert len(s) == 0

    setup = rel.Txn()
    setup.create(rel.must_from_triple("document:a", "writer", "user:amy"))
    s.write(setup)
    s.write(txn)  # now the precondition holds
    assert len(s) == 2

    neg = rel.Txn()
    neg.must_not_match(guard)
    neg.touch(rel.must_from_triple("document:b", "reader", "user:jim"))
    with pytest.raises(PreconditionFailedError):
        s.write(neg)


def test_schema_change_protects_live_relationships():
    s = make_store()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    s.write(txn)
    with pytest.raises(SchemaValidationError):
        s.write_schema("definition user {}\ndefinition document { relation writer: user }")
    # original schema still live
    text, _ = s.read_schema()
    assert "reader" in text


def test_read_with_filters():
    s = make_store()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    txn.create(rel.must_from_triple("document:a", "writer", "user:amy"))
    txn.create(rel.must_from_triple("document:b", "reader", "user:amy"))
    s.write(txn)

    all_docs = list(s.read(consistency.full(), rel.new_filter("document", "", "")))
    assert len(all_docs) == 3
    a_only = list(s.read(consistency.full(), rel.new_filter("document", "a", "")))
    assert {str(r) for r in a_only} == {
        "document:a#reader@user:jim",
        "document:a#writer@user:amy",
    }
    readers = list(s.read(consistency.full(), rel.new_filter("document", "", "reader")))
    assert len(readers) == 2
    f = rel.new_filter("document", "", "")
    f.with_subject_filter("user", "amy")
    amy = list(s.read(consistency.full(), f))
    assert len(amy) == 2


def test_consistency_strategies_pick_generations():
    s = make_store()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    rev1 = s.write(txn)
    snap1 = s.snapshot_for(consistency.full())
    assert snap1.revision == parse_revision(rev1)

    txn2 = rel.Txn()
    txn2.create(rel.must_from_triple("document:b", "reader", "user:jim"))
    rev2 = s.write(txn2)

    # min_latency returns the stale materialized generation
    assert s.snapshot_for(consistency.min_latency()).revision == parse_revision(rev1)
    # at_least forces a fresh one
    assert s.snapshot_for(consistency.at_least(rev2)).revision == parse_revision(rev2)
    # snapshot pins an exact cached generation
    assert s.snapshot_for(consistency.snapshot(rev1)).revision == parse_revision(rev1)
    with pytest.raises(RevisionUnavailableError):
        s.snapshot_for(consistency.snapshot("gtz1.99999"))
    with pytest.raises(RevisionUnavailableError):
        s.snapshot_for(consistency.at_least("gtz1.99999"))


def test_delete_by_filter():
    s = make_store()
    txn = rel.Txn()
    for i in range(5):
        txn.create(rel.must_from_triple(f"document:d{i}", "reader", "user:jim"))
    txn.create(rel.must_from_triple("document:keep", "writer", "user:amy"))
    s.write(txn)

    pf = rel.new_preconditioned_filter(rel.new_filter("document", "", "reader"))
    _, complete = s.delete_by_filter(pf, limit=3)
    assert not complete and len(s) == 3
    _, complete = s.delete_by_filter(pf, limit=3)
    assert complete and len(s) == 1


def test_import_raises_already_exists():
    s = make_store()
    rs = [rel.must_from_triple("document:a", "reader", "user:jim")]
    s.import_relationships(rs)
    with pytest.raises(AlreadyExistsError):
        s.import_relationships(rs)


def test_expired_relationships_hidden_from_reads():
    s = make_store()
    past = dt.datetime.now(dt.timezone.utc) - dt.timedelta(hours=1)
    future = dt.datetime.now(dt.timezone.utc) + dt.timedelta(hours=1)
    txn = rel.Txn()
    txn.create(
        rel.must_from_triple("document:a", "reader", "user:old").with_expiration(past)
    )
    txn.create(
        rel.must_from_triple("document:a", "reader", "user:new").with_expiration(future)
    )
    s.write(txn)
    got = {r.subject_id for r in s.read(consistency.full(), rel.new_filter("document", "", ""))}
    assert got == {"new"}


def test_watch_replay_and_live():
    s = make_store()
    txn = rel.Txn()
    txn.create(rel.must_from_triple("document:a", "reader", "user:jim"))
    s.write(txn)

    stop = threading.Event()
    seen = []

    def consume():
        for rev, u in s.updates_since(0, stop=stop, poll_interval=0.01):
            seen.append((rev, u))
            if len(seen) >= 2:
                return

    t = threading.Thread(target=consume)
    t.start()
    txn2 = rel.Txn()
    txn2.delete(rel.must_from_triple("document:a", "reader", "user:jim"))
    s.write(txn2)
    t.join(timeout=5)
    stop.set()
    assert not t.is_alive()
    assert [u.update_type for _, u in seen] == [rel.UpdateType.CREATE, rel.UpdateType.DELETE]
    assert seen[0][0] < seen[1][0]


def test_snapshot_columnar_views():
    s = Store()
    s.write_schema(
        """
        definition user {}
        definition group { relation member: user | group#member }
        definition folder { relation parent: folder relation owner: user
                            permission view = owner + parent->view }
        """
    )
    txn = rel.Txn()
    txn.create(rel.must_from_triple("group:eng", "member", "user:amy"))
    txn.create(rel.must_from_tuple("group:all#member", "group:eng#member"))
    txn.create(rel.must_from_tuple("group:sup#member", "group:all#member"))
    txn.create(rel.must_from_triple("folder:root", "owner", "user:amy"))
    txn.create(rel.must_from_triple("folder:sub", "parent", "folder:root"))
    s.write(txn)
    snap = s.snapshot_for(consistency.full())

    assert snap.num_edges == 5
    # sorted lex by (rel, res)
    k = snap.e_rel.astype(np.int64) * snap.num_nodes + snap.e_res
    assert np.all(np.diff(k) >= 0)
    # two userset edges (all#member@eng#member, sup#member@all#member)
    assert snap.us_rel.shape[0] == 2
    # membership seed: user:amy ∈ group:eng#member ((eng,member) is used as
    # a subject).  Propagation: the group:all edge targets (all,member),
    # which is itself used as a subject (by the group:sup edge); the
    # group:sup edge targets (sup,member), which nothing references → pruned.
    assert snap.ms_subj.shape[0] == 1
    assert snap.mp_subj.shape[0] == 1
    # arrow edge: folder:sub --parent--> folder:root
    assert snap.ar_rel.shape[0] == 1
    child_type, child_id = snap.interner.key_of(int(snap.ar_child[0]))
    assert (child_type, child_id) == ("folder", "root")
    # round-trip decode
    rels = {str(r) for r in snap.iter_relationships()}
    assert "folder:sub#parent@folder:root" in rels
    assert "group:all#member@group:eng#member" in rels

"""Client cache-pinning (round-2 Weak #5) and the jax.profiler escape
hatch (SURVEY.md §5 tracing/profiling)."""

import os

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import Client, with_profiling
from gochugaru_tpu.utils import metrics
from gochugaru_tpu.utils.context import background

SCHEMA = """
definition user {}
definition doc {
    relation reader: user
    permission view = reader
}
"""


def seeded_client():
    c = Client()
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:d", "reader", "user:u"))
    rev = c.write(ctx, txn)
    return c, ctx, rev


def test_snapshot_pinned_reader_survives_head_writes():
    c, ctx, rev = seeded_client()
    pinned = consistency.snapshot(rev)
    assert c.check_one(ctx, pinned, rel.must_from_triple("doc:d", "view", "user:u"))
    snap = c._store.snapshot_for(pinned)
    held = c._dsnap_cache[snap.revision]
    for i in range(10):
        txn = rel.Txn()
        txn.create(rel.must_from_triple(f"doc:w{i}", "reader", f"user:x{i}"))
        c.write(ctx, txn)
        # a head reader churns the cache with fresh revisions…
        assert c.check_one(
            ctx, consistency.full(),
            rel.must_from_triple(f"doc:w{i}", "view", f"user:x{i}"),
        )
        # …but the pinned generation stays warm: same prepared object
        assert c.check_one(
            ctx, pinned, rel.must_from_triple("doc:d", "view", "user:u")
        )
        assert c._dsnap_cache.get(snap.revision) is held, (
            f"pinned generation evicted after write {i}"
        )
    assert len(c._dsnap_cache) <= Client.SNAPSHOT_CACHE_MAX


def test_lowest_revision_not_preferentially_evicted():
    c, ctx, rev = seeded_client()
    pinned = consistency.snapshot(rev)
    c.check_one(ctx, pinned, rel.must_from_triple("doc:d", "view", "user:u"))
    snap = c._store.snapshot_for(pinned)
    for i in range(6):
        txn = rel.Txn()
        txn.create(rel.must_from_triple(f"doc:y{i}", "reader", "user:u"))
        c.write(ctx, txn)
        c.check_one(
            ctx, consistency.full(),
            rel.must_from_triple(f"doc:y{i}", "view", "user:u"),
        )
        c.check_one(ctx, pinned, rel.must_from_triple("doc:d", "view", "user:u"))
    # the oracle cache follows the same LRU policy
    assert snap.revision in c._dsnap_cache


def test_profiling_option_writes_trace_and_metric(tmp_path):
    trace_dir = str(tmp_path / "trace")
    c = Client(with_profiling(trace_dir))
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:d", "reader", "user:u"))
    rev = c.write(ctx, txn)
    before = metrics.default.snapshot().get("checks.device_time_s.count", 0)
    assert c.check_one(
        ctx, consistency.at_least(rev),
        rel.must_from_triple("doc:d", "view", "user:u"),
    )
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "profiler trace directory is empty"
    after = metrics.default.snapshot().get("checks.device_time_s.count", 0)
    assert after > before


def test_client_takes_incremental_device_path():
    """Consecutive write→check revisions through the public Client must
    advance the device snapshot incrementally (base tables reused, delta
    overlay only) — the Watch-driven re-index path, BASELINE config 5."""
    c, ctx, rev = seeded_client()
    full = consistency.full()
    assert c.check_one(ctx, full, rel.must_from_triple("doc:d", "view", "user:u"))
    incremental = 0
    for i in range(4):
        txn = rel.Txn()
        txn.touch(rel.must_from_triple("doc:d", "reader", f"user:w{i}"))
        c.write(ctx, txn)
        assert c.check_one(
            ctx, full, rel.must_from_triple("doc:d", "view", f"user:w{i}")
        )
        snap = c._store.snapshot_for(full)
        ds = c._dsnap_cache.get(snap.revision)
        if (
            ds is not None
            and ds.flat_meta is not None
            and ds.flat_meta.delta is not None
        ):
            incremental += 1
    assert incremental >= 3, f"incremental prepares: {incremental}/4"
    # deletes ride the same path (tombstone overlay)
    txn = rel.Txn()
    txn.delete(rel.must_from_triple("doc:d", "reader", "user:w0"))
    c.write(ctx, txn)
    assert not c.check_one(
        ctx, full, rel.must_from_triple("doc:d", "view", "user:w0")
    )
    snap = c._store.snapshot_for(full)
    ds = c._dsnap_cache.get(snap.revision)
    assert ds is not None and ds.flat_meta.delta is not None
    assert ds.flat_meta.delta.has_tombs

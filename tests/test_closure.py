"""Differential tests for the flattened membership closure
(store/closure.py) against a brute-force max-min path evaluator."""

import numpy as np
import pytest

from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.closure import NEVER, NO_EXP, build_closure
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.rel.relationship import Relationship
import datetime as dt

EPOCH_US = 1_700_000_000_000_000


def rel(res, rl, subj, srel="", caveat="", exp_s=0):
    rt, rid = res.split(":")
    st, sid = subj.split(":")
    expiration = None
    if exp_s:
        expiration = dt.datetime.fromtimestamp(
            (EPOCH_US / 1_000_000) + exp_s, tz=dt.timezone.utc
        )
    return Relationship(
        resource_type=rt, resource_id=rid, resource_relation=rl,
        subject_type=st, subject_id=sid, subject_relation=srel,
        caveat_name=caveat, caveat_context={},
        expiration=expiration,
    )


SCHEMA = """
caveat c1(x int) { x > 0 }
definition user {}
definition group {
    relation member: user | user:* | group#member | group#other with c1
    relation other: user | group#member
}
definition doc {
    relation reader: user | group#member | group#other
    permission view = reader
}
"""


def brute_closure(snap):
    """Max-min path values over the ms/mp membership graph, per plane."""
    S1 = snap.num_slots + 1
    edges = []  # (src_key, dst_key, dval, pval)
    for i in range(snap.ms_subj.shape[0]):
        w = NO_EXP if snap.ms_exp[i] == 0 else int(snap.ms_exp[i])
        d = w if snap.ms_caveat[i] == 0 else int(NEVER)
        edges.append(
            (
                int(snap.ms_subj[i]) * S1,
                int(snap.ms_res[i]) * S1 + int(snap.ms_rel[i]) + 1,
                d,
                w,
            )
        )
    for i in range(snap.mp_subj.shape[0]):
        w = NO_EXP if snap.mp_exp[i] == 0 else int(snap.mp_exp[i])
        d = w if snap.mp_caveat[i] == 0 else int(NEVER)
        edges.append(
            (
                int(snap.mp_subj[i]) * S1 + int(snap.mp_srel[i]) + 1,
                int(snap.mp_res[i]) * S1 + int(snap.mp_rel[i]) + 1,
                d,
                w,
            )
        )
    best = {}  # (src, dst) -> [d, p]
    sources = {e[0] for e in edges}
    # Bellman-Ford-style relaxation from each source
    for s in sources:
        vals = {s: (NO_EXP, NO_EXP)}  # node -> (d, p) best value from s
        changed = True
        while changed:
            changed = False
            for (a, b, d, p) in edges:
                if a in vals:
                    nd = min(vals[a][0], d)
                    np_ = min(vals[a][1], p)
                    od, op = vals.get(b, (NEVER, NEVER))
                    if nd > od or np_ > op:
                        vals[b] = (max(nd, od), max(np_, op))
                        changed = True
        for dst, (d, p) in vals.items():
            if dst != s:
                best[(s, dst)] = (d, p)
    return best


def closure_dict(idx, num_slots):
    S1 = num_slots + 1
    out = {}
    for i in range(idx.num_pairs):
        src = int(idx.c_src[i]) * S1 + int(idx.c_srel1[i])
        dst = int(idx.c_g[i]) * S1 + int(idx.c_grel[i]) + 1
        out[(src, dst)] = (int(idx.c_d_until[i]), int(idx.c_p_until[i]))
    return out


def check_world(rels, schema=SCHEMA, **kw):
    cs = compile_schema(parse_schema(schema))
    from gochugaru_tpu.store.interner import Interner

    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=EPOCH_US)
    idx = build_closure(snap, **kw)
    assert idx.ovf_src.shape[0] == 0 or kw, "unexpected overflow"
    got = closure_dict(idx, snap.num_slots)
    want = brute_closure(snap)
    assert got == want
    return idx, snap


def test_direct_membership():
    check_world(
        [
            rel("group:eng", "member", "user:alice"),
            rel("group:eng", "member", "user:bob"),
            rel("doc:d1", "reader", "group:eng", "member"),
        ]
    )


def test_nested_groups_three_deep():
    check_world(
        [
            rel("group:a", "member", "user:u1"),
            rel("group:b", "member", "group:a", "member"),
            rel("group:c", "member", "group:b", "member"),
            rel("doc:d", "reader", "group:c", "member"),
        ]
    )


def test_cyclic_groups_terminate():
    check_world(
        [
            rel("group:a", "member", "user:u1"),
            rel("group:b", "member", "group:a", "member"),
            rel("group:a", "member", "group:b", "member"),
            rel("doc:d", "reader", "group:b", "member"),
            rel("doc:d", "reader", "group:a", "member"),
        ]
    )


def test_caveated_edge_definite_never():
    idx, snap = check_world(
        [
            rel("group:a", "other", "user:u1", caveat="c1"),
            rel("group:b", "member", "group:a", "other"),
            rel("doc:d", "reader", "group:b", "member"),
        ]
    )
    # the caveated seed makes every pair from u1 possible-only
    S1 = snap.num_slots + 1
    d = closure_dict(idx, snap.num_slots)
    u1 = snap.interner.lookup("user", "u1") * S1
    vals = [v for (s, _), v in d.items() if s == u1]
    assert vals and all(dv == NEVER and pv == NO_EXP for dv, pv in vals)


def test_expiring_edge_semiring():
    idx, snap = check_world(
        [
            rel("group:a", "member", "user:u1", exp_s=500),
            rel("group:b", "member", "group:a", "member", exp_s=1000),
            # second, longer-lived path to b
            rel("group:c", "member", "user:u1"),
            rel("group:b", "member", "group:c", "member", exp_s=800),
            rel("doc:d", "reader", "group:b", "member"),
        ]
    )
    S1 = snap.num_slots + 1
    d = closure_dict(idx, snap.num_slots)
    u1 = snap.interner.lookup("user", "u1") * S1
    b = snap.interner.lookup("group", "b")
    member = snap.compiled.slot_of_name["member"]
    # path via a: min(500, 1000) = 500; via c: min(inf, 800) = 800 → max 800
    assert d[(u1, b * S1 + member + 1)] == (800, 800)


def test_wildcard_subject_is_ordinary_source():
    idx, snap = check_world(
        [
            rel("group:a", "member", "user:*"),
            rel("group:b", "member", "group:a", "member"),
            rel("doc:d", "reader", "group:b", "member"),
        ]
    )
    S1 = snap.num_slots + 1
    d = closure_dict(idx, snap.num_slots)
    wc = snap.interner.lookup("user", "*") * S1
    b = snap.interner.lookup("group", "b")
    member = snap.compiled.slot_of_name["member"]
    assert d[(wc, b * S1 + member + 1)] == (int(NO_EXP), int(NO_EXP))


def test_per_source_cap_overflow():
    rels = [rel("group:big", "member", "user:u0")]
    # u0 belongs to 40 groups transitively; cap at 8 → u0 overflows
    for i in range(40):
        rels.append(rel(f"group:g{i}", "member", "group:big", "member"))
        rels.append(rel("doc:d", "reader", f"group:g{i}", "member"))
    rels.append(rel("doc:d", "reader", "group:big", "member"))
    cs = compile_schema(parse_schema(SCHEMA))
    from gochugaru_tpu.store.interner import Interner

    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=EPOCH_US)
    idx = build_closure(snap, per_source_cap=8)
    S1 = snap.num_slots + 1
    ovf = {
        int(idx.ovf_src[i]) * S1 + int(idx.ovf_srel1[i])
        for i in range(idx.ovf_src.shape[0])
    }
    u0 = snap.interner.lookup("user", "u0") * S1
    big = snap.interner.lookup("group", "big")
    member = snap.compiled.slot_of_name["member"]
    assert u0 in ovf  # user inherits the overflow
    assert big * S1 + member + 1 in ovf  # the pair source itself
    # no partial rows for overflowed sources survive
    d = closure_dict(idx, snap.num_slots)
    assert not any(s in ovf for (s, _) in d)


def test_random_worlds_match_brute_force():
    rng = np.random.default_rng(7)
    for trial in range(8):
        n_users, n_groups = 6, 8
        rels = []
        for g in range(n_groups):
            for u in rng.choice(n_users, 2, replace=False):
                kw = {}
                r = int(rng.integers(0, 4))
                if r == 1:
                    kw["caveat"] = "c1"
                    rels.append(
                        rel(f"group:g{g}", "other", f"user:u{u}", **kw)
                    )
                    continue
                if r == 2:
                    kw["exp_s"] = int(rng.integers(1, 1000))
                rels.append(rel(f"group:g{g}", "member", f"user:u{u}", **kw))
        for _ in range(6):
            a, b = rng.choice(n_groups, 2, replace=False)
            kw = {}
            if rng.integers(0, 3) == 0:
                kw["exp_s"] = int(rng.integers(1, 1000))
            rels.append(
                rel(f"group:g{a}", "member", f"group:g{b}", "member", **kw)
            )
        for g in range(n_groups):
            rels.append(rel("doc:d", "reader", f"group:g{g}", "member"))
            rels.append(rel("doc:d", "reader", f"group:g{g}", "other"))
        check_world(rels)


def test_empty_membership_graph():
    idx, snap = check_world([rel("doc:d", "reader", "user:u1")])
    assert idx.num_pairs == 0
    assert idx.ovf_src.shape[0] == 0


def test_self_loop_edge_no_reflexive_row():
    # group:a#member @ group:a#member is writable; the closure must not
    # store the reflexive pair (probes test identity directly)
    idx, snap = check_world(
        [
            rel("group:a", "member", "group:a", "member"),
            rel("group:a", "member", "user:u1"),
            rel("doc:d", "reader", "group:a", "member"),
        ]
    )
    S1 = snap.num_slots + 1
    a = snap.interner.lookup("group", "a")
    member = snap.compiled.slot_of_name["member"]
    key = a * S1 + member + 1
    d = closure_dict(idx, snap.num_slots)
    assert (key, key) not in d


def test_max_hops_exhaustion_overflows_not_silently_wrong():
    # 5-deep chain with max_hops=1: unconverged sources must land in the
    # overflow set (host-oracle fallback), never silently miss pairs
    rels = [rel("group:g0", "member", "user:u0")]
    for i in range(1, 6):
        rels.append(rel(f"group:g{i}", "member", f"group:g{i-1}", "member"))
        rels.append(rel("doc:d", "reader", f"group:g{i}", "member"))
    rels.append(rel("doc:d", "reader", "group:g0", "member"))
    cs = compile_schema(parse_schema(SCHEMA))
    from gochugaru_tpu.store.interner import Interner

    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=EPOCH_US)
    idx = build_closure(snap, max_hops=1)
    S1 = snap.num_slots + 1
    ovf = {
        int(idx.ovf_src[i]) * S1 + int(idx.ovf_srel1[i])
        for i in range(idx.ovf_src.shape[0])
    }
    got = closure_dict(idx, snap.num_slots)
    want = brute_closure(snap)
    # every missing or divergent pair belongs to an overflowed source
    for (s, dsts), v in want.items():
        if got.get((s, dsts)) != v:
            assert s in ovf, (s, dsts, v, got.get((s, dsts)))
    # and no overflowed source has partial rows
    assert not any(s in ovf for (s, _) in got)


# ---------------------------------------------------------------------------
# incremental maintenance: advance_closure ≡ build_closure, bitwise
# ---------------------------------------------------------------------------

from gochugaru_tpu.store.closure import advance_closure, build_closure_state
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import _exp_to_rel32
from gochugaru_tpu.rel.relationship import expiration_micros


def _random_member_edge(rng, n_users=12, n_groups=8):
    g = rng.integers(0, n_groups)
    rl = "member" if rng.random() < 0.7 else "other"
    caveat = "c1" if (rl == "member" and rng.random() < 0.2) else ""
    exp_s = int(rng.integers(1, 1000)) if rng.random() < 0.3 else 0
    if rng.random() < 0.5:
        subj, srel = f"user:u{rng.integers(0, n_users)}", ""
    else:
        srel = "member" if rng.random() < 0.7 else "other"
        subj = f"group:g{rng.integers(0, n_groups)}"
    r = rel(f"group:g{g}", rl, subj, srel, caveat, exp_s)
    return {(f"group:g{g}", rl, subj, srel): r}


def _pack_identity(snap, r):
    """(packed src, packed dst, srel1) of one membership row."""
    S1 = snap.num_slots + 1
    slot = snap.compiled.slot_of_name
    subj = snap.interner.lookup(r.subject_type, r.subject_id)
    res = snap.interner.lookup(r.resource_type, r.resource_id)
    srel1 = slot[r.subject_relation] + 1 if r.subject_relation else 0
    return (
        subj * S1 + srel1,
        res * S1 + slot[r.resource_relation] + 1,
        srel1,
    )


def _index_equal(a, b):
    for f in ("c_src", "c_srel1", "c_g", "c_grel", "c_d_until", "c_p_until",
              "ovf_src", "ovf_srel1"):
        x, y = getattr(a, f), getattr(b, f)
        if x.shape != y.shape or not np.array_equal(x, y):
            return f
    return None


def _run_delta_sequence(seed, cap=4096, steps=10):
    """Random membership-edge delta sequence: the incrementally-advanced
    closure must equal a from-scratch rebuild BITWISE at every step."""
    rng = np.random.default_rng(seed)
    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rels = {}
    # doc anchors keep every group userset "used" (deleting the last use
    # shrinks the membership views — the engine bails there rather than
    # advancing, so the property holds over a stable used set)
    for g in range(8):
        rels[("doc:d0", "reader", f"group:g{g}", "member")] = rel(
            "doc:d0", "reader", f"group:g{g}", "member")
        rels[("doc:d1", "reader", f"group:g{g}", "other")] = rel(
            "doc:d1", "reader", f"group:g{g}", "other")
    for _ in range(30):
        rels.update(_random_member_edge(rng))
    snap = build_snapshot(1, cs, interner, list(rels.values()),
                          epoch_us=EPOCH_US)
    st = build_closure_state(
        snap, build_closure(snap, per_source_cap=cap), per_source_cap=cap
    )
    used = set(snap.us_used_keys.tolist())
    num_slots = snap.num_slots
    slot = cs.slot_of_name

    for step in range(steps):
        adds, dels = {}, {}
        keys = [k for k in rels if not k[0].startswith("doc:")]
        for _ in range(rng.integers(1, 5)):
            if keys and rng.random() < 0.4:
                k = keys[rng.integers(0, len(keys))]
                dels[k] = rels[k]
            else:
                adds.update(_random_member_edge(rng))
        for k in dels:
            rels.pop(k, None)
        prev_rels = dict(rels)
        rels.update(adds)
        nsnap = build_snapshot(step + 2, cs, interner, list(rels.values()),
                               epoch_us=EPOCH_US)
        pair_add, seed_add, pair_del, seed_del = [], [], [], []
        for k, r in adds.items():
            res = interner.lookup(r.resource_type, r.resource_id)
            if res * num_slots + slot[r.resource_relation] not in used:
                continue
            src, dst, srel1 = _pack_identity(nsnap, r)
            exp_us = expiration_micros(r.expiration) if r.has_expiration() else 0
            exp32 = int(_exp_to_rel32(np.array([exp_us], np.int64), EPOCH_US)[0])
            cav = cs.caveat_ids[r.caveat_name] if r.caveat_name else 0
            (pair_add if srel1 > 0 else seed_add).append((src, dst, cav, exp32))
            if k in prev_rels and k not in dels:  # upsert = delete + add
                osrc, odst, osrel1 = _pack_identity(nsnap, prev_rels[k])
                (pair_del if osrel1 > 0 else seed_del).append((osrc, odst))
        for k, r in dels.items():
            res = interner.lookup(r.resource_type, r.resource_id)
            if res * num_slots + slot[r.resource_relation] not in used:
                continue
            src, dst, srel1 = _pack_identity(nsnap, r)
            (pair_del if srel1 > 0 else seed_del).append((src, dst))

        def c4(rows):
            if not rows:
                return None
            a = np.array(rows, np.int64)
            return (a[:, 0], a[:, 1], a[:, 2].astype(np.int32),
                    a[:, 3].astype(np.int32))

        def c2(rows):
            if not rows:
                return None
            a = np.array(rows, np.int64)
            return a[:, 0], a[:, 1]

        got = advance_closure(
            st, nsnap.revision,
            pair_add=c4(pair_add), pair_del=c2(pair_del),
            seed_add=c4(seed_add), seed_del=c2(seed_del),
        )
        assert got is not None, f"seed={seed} step={step}: advance bailed"
        st = got.state
        want = build_closure(nsnap, per_source_cap=cap)
        bad = _index_equal(st.cl, want)
        assert bad is None, f"seed={seed} step={step}: field {bad} differs"


def test_advance_closure_bitwise_equal_property():
    for seed in range(6):
        _run_delta_sequence(seed)


def test_advance_closure_bitwise_equal_under_overflow():
    # per_source_cap=4 exercises overflow creation, propagation to user
    # sources, and un-overflow on deletes — all must match the rebuild
    for seed in range(4):
        _run_delta_sequence(seed + 100, cap=4)


def test_advance_closure_empty_delta_is_identity():
    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rels = [
        rel("group:g0", "member", "user:u0"),
        rel("group:g1", "member", "group:g0", "member"),
        rel("doc:d", "reader", "group:g1", "member"),
        rel("doc:d", "reader", "group:g0", "member"),
    ]
    snap = build_snapshot(1, cs, interner, rels, epoch_us=EPOCH_US)
    cl = build_closure(snap)
    st = build_closure_state(snap, cl)
    got = advance_closure(st, 2)
    assert got is not None
    assert got.state is st  # no work → same state object
    assert got.changed_dsts.shape[0] == 0


def test_advance_closure_value_change_reports_changed_group():
    # replacing an expiring member edge with a longer-lived one changes
    # the VALUE of existing closure rows: the touched groups must be
    # reported (they drive the engine's T-index dirty set)
    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rels = [
        rel("group:g0", "member", "user:u0", exp_s=100),
        rel("group:g1", "member", "group:g0", "member"),
        rel("doc:d", "reader", "group:g1", "member"),
        rel("doc:d", "reader", "group:g0", "member"),
    ]
    snap = build_snapshot(1, cs, interner, rels, epoch_us=EPOCH_US)
    cl = build_closure(snap)
    st = build_closure_state(snap, cl)
    S1 = snap.num_slots + 1
    member = cs.slot_of_name["member"]
    u0 = interner.lookup("user", "u0")
    g0 = interner.lookup("group", "g0")
    g1 = interner.lookup("group", "g1")
    # upsert: same identity, exp 100 → no expiration
    got = advance_closure(
        st, 2,
        seed_add=(np.array([u0 * S1]), np.array([g0 * S1 + member + 1]),
                  np.array([0], np.int32), np.array([0], np.int32)),
        seed_del=(np.array([u0 * S1]), np.array([g0 * S1 + member + 1])),
    )
    assert got is not None
    changed = set(got.changed_dsts.tolist())
    assert g0 * S1 + member + 1 in changed
    assert g1 * S1 + member + 1 in changed  # downstream value also moved
    d = closure_dict(got.state.cl, snap.num_slots)
    assert d[(u0 * S1, g1 * S1 + member + 1)] == (int(NO_EXP), int(NO_EXP))

"""Zookie token coverage (fleet/zookie.py): roundtrip, tamper/garbage
rejection, stale-token behavior per consistency strategy, and token
survival through the serving handle's coalesced batches."""

import threading

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_host_only_evaluation,
    with_store,
)
from gochugaru_tpu.fleet import zookie
from gochugaru_tpu.fleet.zookie import InvalidZookieError
from gochugaru_tpu.store.store import RevisionToken, parse_revision
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import RevisionUnavailableError

SCHEMA = """
definition user {}
definition doc {
    relation owner: user
    relation reader: user
    permission read = reader + owner
}
"""


def _client():
    c = new_tpu_evaluator(with_host_only_evaluation())
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    for i in range(4):
        txn.touch(rel.must_from_triple(f"doc:d{i}", "owner", f"user:u{i}"))
    c.write(ctx, txn)
    return c


# -- encode/decode ----------------------------------------------------------


def test_roundtrip_from_int_and_token():
    assert zookie.parse(zookie.mint(42)) == 42
    assert zookie.parse(zookie.mint(RevisionToken(7))) == 7
    assert zookie.revision_token(zookie.mint(7)) == RevisionToken(7)


def test_strategy_is_at_least():
    cs = zookie.strategy(zookie.mint(9))
    assert cs.requirement == consistency.Requirement.AT_LEAST
    assert parse_revision(cs.revision) == 9


def test_tamper_rejected():
    token = zookie.mint(5)
    prefix, revision, mac = token.split(".")
    # revision bumped, mac unchanged: the forged-freshness vector
    with pytest.raises(InvalidZookieError):
        zookie.parse(f"{prefix}.{int(revision) + 1}.{mac}")
    # mac flipped
    bad_mac = ("0" if mac[0] != "0" else "1") + mac[1:]
    with pytest.raises(InvalidZookieError):
        zookie.parse(f"{prefix}.{revision}.{bad_mac}")


def test_wrong_key_rejected():
    token = zookie.mint(5, key=b"other-deployment")
    with pytest.raises(InvalidZookieError):
        zookie.parse(token)
    assert zookie.parse(token, key=b"other-deployment") == 5


@pytest.mark.parametrize(
    "garbage",
    ["", "zk1", "zk1.", "zk1.x.deadbeef", "zk2.5.deadbeef", "zk1.-1.x",
     "gtz1.5", "zk1.5", None, 42],
)
def test_garbage_rejected(garbage):
    with pytest.raises(InvalidZookieError):
        zookie.parse(garbage)


# -- stale-token behavior per strategy -------------------------------------


def test_at_least_future_zookie_never_serves_stale():
    """A zookie from the future (beyond the store head) must surface as
    RevisionUnavailableError — block-or-redirect semantics; the one
    thing it may never do is silently serve an older world."""
    c = _client()
    future = zookie.mint(c.store.head_revision + 10)
    with pytest.raises(RevisionUnavailableError):
        c.check(
            background(), zookie.strategy(future),
            rel.must_from_triple("doc:d0", "read", "user:u0"),
        )


def test_at_least_current_zookie_serves():
    c = _client()
    ctx = background()
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:fresh", "reader", "user:new"))
    zk = zookie.mint(c.write(ctx, txn))
    got = c.check(
        ctx, zookie.strategy(zk),
        rel.must_from_triple("doc:fresh", "read", "user:new"),
    )
    assert got == [True]


def test_old_zookie_still_valid():
    """A stale (old) zookie only sets a freshness FLOOR: reads evaluate
    at that revision or newer, so verdicts reflect the newer world."""
    c = _client()
    ctx = background()
    old = zookie.mint(c.store.head_revision)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:later", "reader", "user:l8r"))
    c.write(ctx, txn)
    got = c.check(
        ctx, zookie.strategy(old),
        rel.must_from_triple("doc:later", "read", "user:l8r"),
    )
    assert got == [True]


def test_snapshot_pins_exact_revision():
    """SNAPSHOT ignores freshness floors entirely: it evaluates at
    exactly its revision — a write after the pinned revision must not
    leak in."""
    c = _client()
    ctx = background()
    pinned = RevisionToken(c.store.head_revision)
    c.store.snapshot_for(consistency.snapshot(pinned))  # materialize
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:d0", "reader", "user:pinned"))
    c.write(ctx, txn)
    q = rel.must_from_triple("doc:d0", "read", "user:pinned")
    assert c.check(ctx, consistency.snapshot(pinned), q) == [False]
    assert c.check(ctx, consistency.full(), q) == [True]


# -- survival through the serving handle's coalesced batches ---------------


def test_zookie_through_serving_handle_coalesced_batches():
    """A handle pinned to a zookie's strategy serves read-your-writes
    for every coalesced submitter: concurrent checks — including ones
    for the relationship the zookie's write just created — coalesce
    into shared formed batches and still see the written world."""
    c = _client()
    ctx = background()
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:coal", "reader", "user:rw"))
    zk = zookie.mint(c.write(ctx, txn))

    results = {}
    errors = []
    queries = [
        ("fresh", rel.must_from_triple("doc:coal", "read", "user:rw"), True),
        ("base0", rel.must_from_triple("doc:d0", "read", "user:u0"), True),
        ("deny", rel.must_from_triple("doc:d1", "read", "user:u0"), False),
        ("base2", rel.must_from_triple("doc:d2", "read", "user:u2"), True),
    ]
    with c.with_serving(cs=zookie.strategy(zk)) as handle:
        def worker(name, q):
            try:
                results[name] = handle.check(
                    background().with_timeout(20.0), q
                )[0]
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append((name, e))

        threads = [
            threading.Thread(target=worker, args=(n, q))
            for n, q, _ in queries
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    assert not errors, errors
    for name, _, want in queries:
        assert results[name] is want, (name, results)

"""Mesh-sharded engine tests on the 8-virtual-device CPU mesh (conftest
forces XLA_FLAGS=--xla_force_host_platform_device_count=8) — the moral
equivalent of the reference's dockerized cluster test (SURVEY.md §4)."""

import random

import jax
import numpy as np
import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import T, U, Oracle
from gochugaru_tpu.parallel import ShardedEngine, make_mesh
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

SCHEMA = """
definition user {}
definition team { relation member: user }
definition org {
    relation admin: user
    relation member: user | team#member
}
definition repo {
    relation org: org
    relation maintainer: user | team#member
    relation reader: user
    permission admin = org->admin + maintainer
    permission read = reader + admin + org->member
}
"""


def build_world(seed=7):
    rng = random.Random(seed)
    triples = []
    users = [f"user:u{i}" for i in range(40)]
    teams = [f"team:t{i}" for i in range(6)]
    orgs = [f"org:o{i}" for i in range(3)]
    repos = [f"repo:r{i}" for i in range(20)]
    for t in teams:
        for u in rng.sample(users, 8):
            triples.append((f"{t}#member", u))
    for o in orgs:
        triples.append((f"{o}#admin", rng.choice(users)))
        for t in rng.sample(teams, 2):
            triples.append((f"{o}#member", f"{t}#member"))
    for r in repos:
        triples.append((f"{r}#org", rng.choice(orgs)))
        triples.append((f"{r}#maintainer", f"{rng.choice(teams)}#member"))
        for u in rng.sample(users, 3):
            triples.append((f"{r}#reader", u))
    rels = [rel.must_from_tuple(*t) for t in triples]
    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=1_700_000_000_000_000)
    oracle = Oracle(cs, rels, now_us=1_700_000_000_000_000)
    queries = []
    rng2 = random.Random(seed + 1)
    for r in [f"repo:r{i}" for i in range(20)]:
        for u in rng2.sample(users, 8):
            queries.append(rel.must_from_triple(r, rng2.choice(["read", "admin"]), u))
    return cs, snap, oracle, queries


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_matches_oracle_and_single_device(shape):
    data, model = shape
    cs, snap, oracle, queries = build_world()
    mesh = make_mesh(data, model)
    sharded = ShardedEngine(cs, mesh)
    dsnap = sharded.prepare(snap)
    d, p, ovf = sharded.check_batch(dsnap, queries, now_us=1_700_000_000_000_000)

    single = DeviceEngine(cs)
    sd, sp, sovf = single.check_batch(
        single.prepare(snap), queries, now_us=1_700_000_000_000_000
    )
    np.testing.assert_array_equal(d, sd)
    np.testing.assert_array_equal(p, sp)
    for i, q in enumerate(queries):
        tri = oracle.check_relationship(q)
        assert not ovf[i]
        assert d[i] == (tri == T), f"{q}: sharded={d[i]} oracle={tri}"


def test_edge_sharded_folder_recursion():
    # recursion + arrows across edge shards: children live on any shard
    schema = """
    definition user {}
    definition folder {
        relation parent: folder
        relation owner: user
        permission view = owner + parent->view
    }
    """
    triples = [("folder:f0#owner", "user:root")]
    for i in range(1, 6):
        triples.append((f"folder:f{i}#parent", f"folder:f{i-1}"))
    rels = [rel.must_from_tuple(*t) for t in triples]
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=1_700_000_000_000_000)
    mesh = make_mesh(2, 4)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    qs = [
        rel.must_from_triple("folder:f5", "view", "user:root"),
        rel.must_from_triple("folder:f3", "view", "user:root"),
        rel.must_from_triple("folder:f5", "view", "user:other"),
    ]
    d, p, ovf = eng.check_batch(dsnap, qs, now_us=1_700_000_000_000_000)
    assert list(d) == [True, True, False]
    assert not ovf.any()


def test_array_keys_match_host_arrays():
    # ShardedEngine derives its shard_map specs from
    # DeviceEngine.ARRAY_COLUMN_KEYS; _host_arrays must emit exactly that
    # column set or the in_specs pytree desyncs (silent drift hazard)
    cs = compile_schema(parse_schema(SCHEMA))
    rels = [rel.must_from_tuple("repo:r#reader", "user:u")]
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=1_700_000_000_000_000)
    eng = DeviceEngine(cs)
    host = eng._host_arrays(snap)
    assert set(host) == set(DeviceEngine.ARRAY_COLUMN_KEYS)


def test_sharded_check_columns_matches_check_batch():
    cs, snap, oracle, queries = build_world(seed=3)
    mesh = make_mesh(4, 2)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    checks = queries[:48]
    d0, p0, o0 = eng.check_batch(dsnap, checks, now_us=1_700_000_000_000_000)
    interner = snap.interner
    slot = cs.slot_of_name
    q_res = np.array(
        [interner.lookup(x.resource_type, x.resource_id) for x in checks], np.int32
    )
    q_perm = np.array([slot[x.resource_relation] for x in checks], np.int32)
    q_subj = np.array(
        [interner.lookup(x.subject_type, x.subject_id) for x in checks], np.int32
    )
    d1, p1, o1 = eng.check_columns(
        dsnap, q_res, q_perm, q_subj, now_us=1_700_000_000_000_000
    )
    assert list(d0) == list(np.asarray(d1))
    assert list(p0) == list(np.asarray(p1))


def test_sharded_check_columns_reflexive_self():
    cs, snap, oracle, queries = build_world(seed=5)
    mesh = make_mesh(4, 2)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    interner = snap.interner
    slot = cs.slot_of_name
    # team:t0#member checked against itself → reflexive True
    t0 = interner.lookup("team", "t0")
    q_res = np.array([t0], np.int32)
    q_perm = np.array([slot["member"]], np.int32)
    q_subj = np.array([t0], np.int32)
    q_srel = np.array([slot["member"]], np.int32)
    d, p, o = eng.check_columns(
        dsnap, q_res, q_perm, q_subj, q_srel=q_srel,
        now_us=1_700_000_000_000_000,
    )
    assert bool(np.asarray(d)[0])


def test_sharded_flat_slot_chunking():
    """More distinct permissions in one batch than flat_max_slots: the
    sharded flat dispatch must chunk the slot set (bounded compiles) and
    still answer every query exactly."""
    cs, snap, oracle, queries = build_world()
    mesh = make_mesh(2, 4)
    from gochugaru_tpu.engine.plan import EngineConfig

    eng = ShardedEngine(cs, mesh, EngineConfig.for_schema(cs, flat_max_slots=1))
    dsnap = eng.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded
    # queries mix 'read'/'admin' (2 slots) + relation slots via tuples
    mixed = queries[:48] + [
        rel.must_from_tuple("repo:r1#reader", "user:u1"),
        rel.must_from_tuple("team:t0#member", "user:u0"),
    ]
    d, p, ovf = eng.check_batch(dsnap, mixed, now_us=1_700_000_000_000_000)
    single = DeviceEngine(cs)
    sd, sp, sovf = single.check_batch(
        single.prepare(snap), mixed, now_us=1_700_000_000_000_000
    )
    np.testing.assert_array_equal(d, sd)
    np.testing.assert_array_equal(p, sp)
    np.testing.assert_array_equal(ovf, sovf)


def test_sharded_meta_kernel_mismatch_raises():
    """A bucket-sharded FlatMeta must not silently build a single-chip
    kernel (and vice versa) — the geometry is incompatible."""
    cs, snap, oracle, queries = build_world()
    mesh = make_mesh(2, 4)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    from gochugaru_tpu.engine.flat import make_flat_fn

    with pytest.raises(ValueError):
        make_flat_fn(
            eng.compiled, eng.plan, eng.config, dsnap.flat_meta, (),
            caveat_plan=eng.caveat_plan,
        )


def test_sharded_flat_features_world():
    """Caveats, expirations, wildcards, nested groups, and folder
    recursion under the bucket-sharded flat kernel: every plane must
    match the single-chip flat engine exactly (the CEL VM runs on
    replicated context tables; gates ride the sharded blocks)."""
    import test_flat_engine as tfe

    rng = random.Random(4)
    rels = tfe.build_feature_world(rng)
    cs = compile_schema(parse_schema(tfe.FEATURES))
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=tfe.NOW)
    checks = tfe.make_checks(rng, 10, 10, n=64)
    from gochugaru_tpu.engine.plan import EngineConfig

    cfg = EngineConfig.for_schema(cs, flat_recursion=3, flat_max_width=32)
    single = DeviceEngine(cs, cfg)
    sd, sp, sovf = single.check_batch(
        single.prepare(snap), checks, now_us=tfe.NOW
    )
    for shape in [(4, 2), (1, 8)]:
        mesh = make_mesh(*shape)
        eng = ShardedEngine(cs, mesh, cfg)
        dsnap = eng.prepare(snap)
        assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded
        d, p, ovf = eng.check_batch(dsnap, checks, now_us=tfe.NOW)
        for i, q in enumerate(checks):
            assert bool(d[i]) == bool(sd[i]), f"{shape} definite differs: {q}"
            assert bool(p[i]) == bool(sp[i]), f"{shape} possible differs: {q}"
            assert bool(ovf[i]) == bool(sovf[i]), f"{shape} ovf differs: {q}"

"""On-device CEL caveat evaluation (caveats/device.py) — differential
tests against the host oracle.

The contract under test: for every query, the device's (definite,
possible) planes bracket the oracle's tri-state — definite == (oracle==T)
whenever the device had what it needed, and any query where the device
can't be exact surfaces as possible&~definite (→ host fallback in the
client), never as a wrong definite answer.
"""

import random

import numpy as np
import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.caveats.device import build_caveat_plan, encode_contexts
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import F, Oracle, T, U
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000


def world(schema, rels, config=None):
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    engine = DeviceEngine(cs, config)
    dsnap = engine.prepare(snap)
    return cs, engine, dsnap, oracle


def run_and_compare(engine, dsnap, oracle, checks, expect_no_fallback=True,
                    strict=True):
    """``strict`` asserts the device decides exactly where it can (the
    legacy engine resolves membership-edge caveats on device with query
    context).  ``strict=False`` asserts the cascade-soundness bracket the
    flat engine guarantees instead: definite ⇒ oracle T, oracle ≥ U ⇒
    possible — any conservative gap surfaces as possible&~definite, which
    the client resolves on the host oracle (never a wrong answer)."""
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        if strict:
            assert bool(d[i]) == (want == T), f"definite mismatch on {q}: {want}"
        else:
            assert not d[i] or want == T, f"unsound definite on {q}: {want}"
        if not ovf[i]:
            if strict:
                assert bool(p[i]) == (want != F), f"possible mismatch on {q}: {want}"
            else:
                assert p[i] or want == F, f"possible misses oracle {want} on {q}"
        if expect_no_fallback and want != U and strict:
            assert not (p[i] and not d[i]) or want == T, q
    return d, p, ovf


SCHEMA_BASIC = """
caveat tier_at_least(tier int, minimum int) { tier >= minimum }
caveat ip_allowed(ip string) { ip in ['10.0.0.1', '10.0.0.2'] }
caveat weekday(is_weekday bool) { is_weekday }
definition user {}
definition doc {
    relation viewer: user | user with tier_at_least | user with ip_allowed | user with weekday
    permission view = viewer
}
"""


def test_int_comparison_definite_on_device():
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "tier_at_least", {"minimum": 5}
        ),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_BASIC, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"tier": 7}),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"tier": 3}),
        rel.must_from_triple("doc:a", "view", "user:u1"),  # missing → U
    ]
    d, p, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [True, False, False]
    assert list(p) == [True, False, True]


def test_string_membership_and_unknown_strings():
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "ip_allowed", {}
        ),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_BASIC, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"ip": "10.0.0.2"}),
        # string the snapshot has never seen — must get a fresh negative id
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"ip": "8.8.8.8"}),
    ]
    d, p, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [True, False]
    assert list(p) == [True, False]


def test_bool_param_and_stored_context_wins():
    rels = [
        # stored context pins is_weekday=False; query context must NOT
        # override it (oracle.py: stored wins)
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "weekday", {"is_weekday": False}
        ),
        rel.must_from_triple("doc:b", "viewer", "user:u1").with_caveat("weekday", {}),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_BASIC, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"is_weekday": True}
        ),
        rel.must_from_triple("doc:b", "view", "user:u1").with_caveat(
            "", {"is_weekday": True}
        ),
    ]
    d, p, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [False, True]


SCHEMA_ARITH = """
caveat quota(used int, limit int) { used + used * 2 < limit && limit % 2 == 0 }
definition user {}
definition doc {
    relation viewer: user with quota
    permission view = viewer
}
"""


def test_int_arithmetic_with_division_semantics():
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat("quota", {}),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_ARITH, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"used": 3, "limit": 10}
        ),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"used": 4, "limit": 10}
        ),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"used": 1, "limit": 9}
        ),
    ]
    d, _, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [True, False, False]


def test_out_of_bound_int_falls_back_to_host():
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat("quota", {}),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_ARITH, rels)
    # huge value: device must flag host (row bound), not overflow silently
    big = 2**40
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"used": 1, "limit": big}
        ),
    ]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert not d[0]  # device cannot be definite
    assert p[0]  # → conditional, host resolves
    assert oracle.check_relationship(checks[0]) == T


SCHEMA_HOSTONLY = """
caveat complex_one(m map<string>) { m.owner == 'alice' }
definition user {}
definition doc {
    relation viewer: user with complex_one
    permission view = viewer
}
"""


def test_host_only_caveat_stays_conditional():
    plan_schema = compile_schema(parse_schema(SCHEMA_HOSTONLY))
    plan = build_caveat_plan(plan_schema)
    cid = plan_schema.caveat_ids["complex_one"]
    assert plan.host_only[cid]
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "complex_one", {"m": {"owner": "alice"}}
        ),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_HOSTONLY, rels)
    checks = [rel.must_from_triple("doc:a", "view", "user:u1")]
    d, p, _ = engine.check_batch(dsnap, checks, now_us=NOW)
    assert not d[0] and p[0]  # device defers
    assert oracle.check_relationship(checks[0]) == T  # host resolves


SCHEMA_DOUBLE = """
caveat score_ok(score double) { score >= 0.5 }
definition user {}
definition doc {
    relation viewer: user with score_ok
    permission view = viewer
}
"""


def test_double_comparison_f32_exact():
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat("score_ok", {}),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_DOUBLE, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"score": 0.75}),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"score": 0.25}),
        # not exactly representable in f32 → host fallback, not a wrong answer
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"score": 0.1}),
    ]
    d, p, _ = engine.check_batch(dsnap, checks, now_us=NOW)
    assert list(d)[:2] == [True, False]
    assert not d[2] and p[2]
    assert oracle.check_relationship(checks[2]) == F


SCHEMA_GROUPS = """
caveat on_call(level int) { level > 3 }
definition user {}
definition team {
    relation member: user | team#member | user with on_call
}
definition doc {
    relation org: team
    relation reader: user | team#member with on_call
    permission view = reader + org->member
}
"""


def _membership_caveat_world():
    rels = [
        # caveated direct membership (ms view)
        rel.must_from_tuple("team:t1#member", "user:u1").with_caveat("on_call", {}),
        # nested team, caveated propagation edge (mp view)
        rel.must_from_tuple("team:t2#member", "team:t1#member").with_caveat(
            "on_call", {"level": 9}
        ),
        # caveated userset grant (us view)
        rel.must_from_tuple("doc:d1#reader", "team:t2#member").with_caveat(
            "on_call", {}
        ),
        # caveated arrow edge (ar view)
        rel.must_from_tuple("doc:d2#org", "team:t1").with_caveat("on_call", {"level": 5}),
        rel.must_from_tuple("team:t1#member", "user:u2"),
    ]
    checks = [
        rel.must_from_triple("doc:d1", "view", "user:u1").with_caveat("", {"level": 7}),
        rel.must_from_triple("doc:d1", "view", "user:u1").with_caveat("", {"level": 1}),
        rel.must_from_triple("doc:d2", "view", "user:u2").with_caveat("", {"level": 9}),
        rel.must_from_triple("doc:d2", "view", "user:u2"),
        rel.must_from_triple("doc:d1", "view", "user:u2").with_caveat("", {"level": 7}),
    ]
    return rels, checks


def test_caveats_on_membership_userset_and_arrow_edges_legacy_exact():
    # the legacy two-phase engine resolves membership-edge caveats on
    # device with query context — strict equality with the oracle
    from gochugaru_tpu.engine.plan import EngineConfig

    rels, checks = _membership_caveat_world()
    _, engine, dsnap, oracle = world(
        SCHEMA_GROUPS, rels, config=EngineConfig.for_schema(
            compile_schema(parse_schema(SCHEMA_GROUPS)), use_flat=False
        )
    )
    run_and_compare(engine, dsnap, oracle, checks, expect_no_fallback=False)


def test_caveats_on_membership_userset_and_arrow_edges_flat_bracket():
    # the flat engine precomputes the closure without query context, so
    # caveated membership edges answer possible-only (host resolves);
    # leaf and arrow caveats stay device-exact
    rels, checks = _membership_caveat_world()
    _, engine, dsnap, oracle = world(SCHEMA_GROUPS, rels)
    d, p, ovf = run_and_compare(
        engine, dsnap, oracle, checks, expect_no_fallback=False, strict=False
    )
    # queries decided by leaf/arrow caveats alone remain exact: d2's grant
    # rides a caveated ARROW edge + non-caveated membership
    assert bool(d[2]) == (oracle.check_relationship(checks[2]) == T)


def test_randomized_differential_with_caveats():
    rng = random.Random(42)
    schema = """
    caveat lim(v int, cap int) { v < cap }
    caveat tag_ok(tag string) { tag in ['a', 'b', 'c'] }
    definition user {}
    definition group { relation member: user | group#member | user with lim }
    definition res {
        relation parent: group
        relation writer: user | user with tag_ok | group#member
        relation banned: user
        permission write = (writer - banned) + parent->member
    }
    """
    users = [f"user:u{i}" for i in range(12)]
    groups = [f"group:g{i}" for i in range(4)]
    ress = [f"res:r{i}" for i in range(8)]
    rels = []
    for g in groups:
        for u in rng.sample(users, 4):
            r = rel.must_from_tuple(f"{g}#member", u)
            if rng.random() < 0.4:
                r = r.with_caveat("lim", {"cap": rng.randint(1, 10)} if rng.random() < 0.7 else {})
            rels.append(r)
    for g in groups[1:]:
        rels.append(rel.must_from_tuple(f"{g}#member", f"{groups[0]}#member"))
    for rs in ress:
        rels.append(rel.must_from_tuple(f"{rs}#parent", rng.choice(groups)))
        for u in rng.sample(users, 3):
            r = rel.must_from_tuple(f"{rs}#writer", u)
            if rng.random() < 0.5:
                r = r.with_caveat("tag_ok", {"tag": rng.choice(["a", "x"])} if rng.random() < 0.5 else {})
            rels.append(r)
        if rng.random() < 0.5:
            rels.append(rel.must_from_tuple(f"{rs}#banned", rng.choice(users)))
    _, engine, dsnap, oracle = world(schema, rels)
    checks = []
    for _ in range(64):
        q = rel.must_from_triple(rng.choice(ress), "write", rng.choice(users))
        ctx = {}
        if rng.random() < 0.6:
            ctx["v"] = rng.randint(0, 10)
        if rng.random() < 0.6:
            ctx["tag"] = rng.choice(["a", "b", "x"])
        if ctx:
            q = q.with_caveat("", ctx)
        checks.append(q)
    # flat engine: sound bracket (caveated MEMBERSHIP edges resolve on the
    # host per query); leaf caveats stay device-exact
    run_and_compare(
        engine, dsnap, oracle, checks, expect_no_fallback=False, strict=False
    )
    # legacy engine: device-exact everywhere it has context
    from gochugaru_tpu.engine.plan import EngineConfig

    _, leg_engine, leg_dsnap, _ = world(
        schema, rels,
        config=EngineConfig.for_schema(
            compile_schema(parse_schema(schema)), use_flat=False
        ),
    )
    run_and_compare(leg_engine, leg_dsnap, oracle, checks, expect_no_fallback=False)


def test_encode_contexts_wrong_type_flags_host():
    cs = compile_schema(parse_schema(SCHEMA_BASIC))
    plan = build_caveat_plan(cs)
    strings = dict(plan.base_strings)
    table = encode_contexts(plan, [{"tier": "not-an-int"}], strings)
    cid = cs.caveat_ids["tier_at_least"]
    assert table.host[0, cid]
    # but the same row is fine for caveats that don't declare `tier`
    assert not table.host[0, cs.caveat_ids["ip_allowed"]]


# ---------------------------------------------------------------------------
# review regressions: f32 promotion exactness + CEL '%' semantics
# ---------------------------------------------------------------------------

SCHEMA_F32 = """
caveat f32risk(a int, lim double) { a + 99999999 > lim }
caveat f32safe(a int, lim double) { a > lim }
caveat inrisk(lim double) { lim in [100000001, 5.0] }
definition user {}
definition doc {
    relation viewer: user with f32risk | user with f32safe | user with inrisk
    permission view = viewer
}
"""


def test_compound_int_in_double_compare_is_host_only():
    """A compound int expression promoted to f32 can exceed 2^24 while
    passing the i32 overflow check; such caveats must be evicted to the
    host, never evaluated inexactly on device."""
    cs = compile_schema(parse_schema(SCHEMA_F32))
    plan = build_caveat_plan(cs)
    assert plan.host_only[cs.caveat_ids["f32risk"]]
    # a big int literal inside an 'in' list with a double needle likewise
    assert plan.host_only[cs.caveat_ids["inrisk"]]
    # but a bare-var double compare stays on device with a bounded range
    cid = cs.caveat_ids["f32safe"]
    assert not plan.host_only[cid]
    assert plan.int_bound[cid] <= 2**24


def test_f32risk_falls_back_not_wrong_definite():
    """The advisor's concrete miscompare: a=2, lim=1e8 → 100000001 > 1e8
    is TRUE exactly but FALSE after f32 rounding.  The device must emit
    possible-without-definite (host fallback), not a wrong definite."""
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "f32risk", {"lim": 1.0e8}
        ),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_F32, rels)
    q = rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"a": 2})
    assert oracle.check_relationship(q) == T
    d, p, _ = engine.check_batch(dsnap, [q], now_us=NOW)
    assert not bool(d[0]) and bool(p[0])  # → client resolves on host → True


SCHEMA_MOD = """
caveat modc(a int) { a % 3 == 2 }
caveat modn(a int, b int) { a % b == -1 }
definition user {}
definition doc {
    relation viewer: user with modc | user with modn
    permission view = viewer
}
"""


def test_modulo_truncates_toward_zero_host_and_device_agree():
    """CEL '%' is the truncated remainder (sign of the dividend).  For
    a=-7: -7 % 3 == -1, so 'a % 3 == 2' is FALSE — Python's floored '%'
    would say 2 (TRUE).  Host oracle and device must agree on CEL
    semantics."""
    prog = compile_cel("modc", {"a": "int"}, "a % 3 == 2")
    assert prog.evaluate({"a": -7}) is False
    assert prog.evaluate({"a": 5}) is True
    progn = compile_cel("modn", {"a": "int", "b": "int"}, "a % b == -1")
    assert progn.evaluate({"a": -7, "b": 3}) is True  # truncated: r = -1
    assert progn.evaluate({"a": 7, "b": -3}) is False  # truncated: r = 1

    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat("modc", {}),
        rel.must_from_triple("doc:b", "viewer", "user:u1").with_caveat("modn", {}),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_MOD, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"a": -7}),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat("", {"a": 5}),
        rel.must_from_triple("doc:b", "view", "user:u1").with_caveat(
            "", {"a": -7, "b": 3}
        ),
        rel.must_from_triple("doc:b", "view", "user:u1").with_caveat(
            "", {"a": 7, "b": -3}
        ),
    ]
    d, p, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [False, True, True, False]


# ---------------------------------------------------------------------------
# timestamp / duration device lowering (exact-µs i32 limb pairs)
# ---------------------------------------------------------------------------

SCHEMA_TIME = """
caveat before_expiry(access_at timestamp, expires_at timestamp) {
  access_at < expires_at
}
caveat in_window(at timestamp, start timestamp, grace duration) {
  at >= start && at < start + grace
}
caveat long_enough(d duration, lim duration) {
  d - lim >= duration("0s") || d == duration("90m")
}
caveat fancy(at timestamp, g duration) {
  (at > timestamp("2024-06-01T00:00:00Z") ? at - g : at + g)
    <= timestamp("2030-01-01T00:00:00Z")
}
definition user {}
definition doc {
    relation viewer: user with before_expiry | user with in_window | user with long_enough | user with fancy
    permission view = viewer
}
"""


def test_time_caveats_lower_on_device_not_host_only():
    """The Timestamp/Duration algebra (compare, ts±dur, ts−ts, dur±dur,
    folded constructor literals) lowers onto the device as exact-µs i32
    limb pairs — none of these caveats may fall back to _HostOnly."""
    from gochugaru_tpu.caveats.device import TIME_MAX_US

    cs = compile_schema(parse_schema(SCHEMA_TIME))
    plan = build_caveat_plan(cs)
    for name, cid in cs.caveat_ids.items():
        assert not plan.host_only[cid], f"{name} leaked to host-only"
        assert 0 < plan.time_bound[cid] < TIME_MAX_US, name
    # each timed param owns TWO slots (hi + lo companion)
    timed = sum(t in ("timestamp", "duration") for t in plan.slot_type)
    lo = sum(t == "time_lo" for t in plan.slot_type)
    assert timed == 9 and lo == 9, (timed, lo)


def test_dynamic_timestamp_constructor_stays_host_only():
    """Only literal constructor forms fold; ``timestamp(x)`` over a
    string param is the documented host-only remainder."""
    cs = compile_schema(parse_schema("""
    caveat dyn(x string) { timestamp(x) < timestamp("2030-01-01T00:00:00Z") }
    definition user {}
    definition doc {
        relation viewer: user with dyn
        permission view = viewer
    }
    """))
    plan = build_caveat_plan(cs)
    assert plan.host_only[cs.caveat_ids["dyn"]]


def test_time_engine_differential_mixed_coercions():
    """Stored + query contexts in every accepted spelling (Timestamp,
    ISO-8601 string, numeric seconds, Duration, '90m' strings) must give
    device answers equal to the host oracle, with now_us pinned."""
    from gochugaru_tpu.caveats.cel import Duration, Timestamp

    day = 86_400_000_000
    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "before_expiry", {"expires_at": Timestamp(NOW + day)}
        ),
        rel.must_from_triple("doc:b", "viewer", "user:u1").with_caveat(
            "in_window",
            {"start": "2023-11-14T00:00:00Z", "grace": "48h"},
        ),
        rel.must_from_triple("doc:c", "viewer", "user:u1").with_caveat(
            "long_enough", {"lim": Duration(30 * 60 * 1_000_000)}
        ),
        rel.must_from_triple("doc:d", "viewer", "user:u1").with_caveat(
            "fancy", {"g": "1h30m"}
        ),
    ]
    _, engine, dsnap, oracle = world(SCHEMA_TIME, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"access_at": NOW / 1e6}  # numeric seconds
        ),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"access_at": Timestamp(NOW + 2 * day)}  # past expiry
        ),
        rel.must_from_triple("doc:b", "view", "user:u1").with_caveat(
            "", {"at": "2023-11-15T12:00:00Z"}  # inside the 48h window
        ),
        rel.must_from_triple("doc:b", "view", "user:u1").with_caveat(
            "", {"at": "2023-11-17T00:00:00Z"}  # past it
        ),
        rel.must_from_triple("doc:c", "view", "user:u1").with_caveat(
            "", {"d": "45m"}
        ),
        rel.must_from_triple("doc:c", "view", "user:u1").with_caveat(
            "", {"d": Duration(90 * 60 * 1_000_000)}  # == escape hatch
        ),
        rel.must_from_triple("doc:c", "view", "user:u1").with_caveat(
            "", {"d": "10m"}
        ),
        rel.must_from_triple("doc:d", "view", "user:u1").with_caveat(
            "", {"at": Timestamp(NOW)}
        ),
        rel.must_from_triple("doc:d", "view", "user:u1").with_caveat("", {}),
    ]
    d, p, _ = run_and_compare(engine, dsnap, oracle, checks)
    assert list(d) == [True, False, True, False, True, True, False, True,
                       False]
    # the missing-context row is conditional, not denied
    assert bool(p[8]) and not bool(d[8])


def test_time_out_of_bound_or_uncoercible_falls_back_not_wrong():
    """A µs magnitude past the caveat's proven bound — or a value the
    coercion table rejects — must surface as possible&~definite (host
    fallback), never as a wrong definite."""
    from gochugaru_tpu.caveats.cel import Timestamp

    rels = [
        rel.must_from_triple("doc:a", "viewer", "user:u1").with_caveat(
            "before_expiry", {"expires_at": Timestamp(NOW)}
        ),
    ]
    from gochugaru_tpu.caveats.cel import Timestamp

    _, engine, dsnap, oracle = world(SCHEMA_TIME, rels)
    checks = [
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"access_at": Timestamp(1 << 60)}  # beyond TIME_MAX_US
        ),
        rel.must_from_triple("doc:a", "view", "user:u1").with_caveat(
            "", {"access_at": "not-a-timestamp"}
        ),
    ]
    d, p, _ = engine.check_batch(dsnap, checks, now_us=NOW)
    for i in range(2):
        assert not bool(d[i]) and bool(p[i]), i


def test_time_randomized_differential():
    """Fuzz the tri-state evaluator over all four timed caveats with
    mixed coercion spellings, missing params, and junk values: every
    device-definite row must equal the host result; rows with a full
    well-typed context must BE device-definite (no gratuitous U)."""
    import datetime as dt

    import jax.numpy as jnp

    from gochugaru_tpu.caveats import device as cdev
    from gochugaru_tpu.caveats.cel import UNKNOWN, Duration, Timestamp

    cs = compile_schema(parse_schema(SCHEMA_TIME))
    plan = build_caveat_plan(cs)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    tri_fn = cdev.make_tri_fn(plan)
    rng = random.Random(0)

    def rand_val(ptype, clean):
        if rng.random() < 0.1:
            return None
        if not clean and rng.random() < 0.15:
            return rng.choice(["junk", 3.5e18, True])
        if ptype == "timestamp":
            us = NOW + rng.randint(-10**13, 10**13)
            style = rng.random()
            if style < 0.4:
                return Timestamp(us)
            if style < 0.7:
                return dt.datetime.fromtimestamp(
                    us / 1e6, dt.timezone.utc
                ).isoformat()
            return us / 1e6
        us = rng.randint(-10**10, 10**10)
        style = rng.random()
        if style < 0.4:
            return Duration(us)
        if style < 0.7:
            return (f"{us}us") if us >= 0 else f"-{-us}us"
        return us / 1e6

    rows, expect = [], []
    for trial in range(160):
        name = rng.choice(sorted(progs))
        prog = progs[name]
        clean = trial % 4 == 0
        ctx = {}
        for pname, ptype in prog.params.items():
            v = rand_val(ptype, clean)
            while clean and v is None:
                v = rand_val(ptype, True)
            if v is not None:
                ctx[pname] = v
        rows.append(ctx)
        expect.append((cs.caveat_ids[name], prog, ctx, clean))

    strings = dict(plan.base_strings)
    table = encode_contexts(plan, rows, strings)
    P = table.vi.shape[1]
    tables = {
        "ectx_vi": np.asarray(table.vi),
        "ectx_vf": np.asarray(table.vf),
        "ectx_pr": np.asarray(table.present),
        "ectx_host": np.asarray(table.host),
        "qctx_vi": np.zeros((1, P), np.int32),
        "qctx_vf": np.zeros((1, P), np.float32),
        "qctx_pr": np.zeros((1, P), bool),
        "qctx_host": np.zeros((1, plan.num_caveats + 1), bool),
    }
    cav = jnp.asarray(np.array([c for c, _, _, _ in expect], np.int32))
    eidx = jnp.asarray(np.arange(len(expect), dtype=np.int32))
    qidx = jnp.asarray(np.full(len(expect), -1, np.int32))
    out = np.asarray(tri_fn(cav, eidx, qidx, tables))

    n_definite = 0
    for k, (cid, prog, ctx, clean) in enumerate(expect):
        dev = int(out[k])
        if dev == int(U):
            assert not clean, (
                f"full well-typed context must be device-definite: "
                f"{prog.name} {ctx}"
            )
            continue
        n_definite += 1
        host = prog.evaluate(ctx)
        want = U if host is UNKNOWN else (T if host else F)
        assert dev == int(want), (prog.name, ctx, dev, want)
    assert n_definite >= 80  # the fuzz must actually exercise the device

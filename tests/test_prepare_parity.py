"""Parity + budget tests for the vectorized cold-start prepare pipeline.

The first-prepare rebuild (round 9) moved every hot loop onto fused
native kernels (native/ingest.cpp: parallel radix, counting-sort hash
index, interleaved gathers) with pure-numpy fallbacks.  The contract is
the round-8 incremental-closure guarantee: the accelerated builder's
output tables are BITWISE-identical to the reference (numpy) builder on
randomized worlds — usersets, nested groups, caveats with contexts,
expirations, wildcards, and closure overflow all exercised.

Plus a CI-safe budget smoke: a fixed small world's first prepare must
stay inside a generous wall-clock envelope, and the staged pipeline must
publish its ``prepare.*`` stage timers (the bench-output decomposition
contract of benchmarks/bench_import.py).
"""

import random
import time

import numpy as np
import pytest

from gochugaru_tpu import native, rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.flat import (
    build_flat_arrays,
    build_flat_arrays_sharded,
)
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils import metrics

NOW = 1_700_000_000_000_000

SCHEMA = """
caveat on_tuesday(day string) { day == "tuesday" }
definition user {}
definition team {
    relation member: user | team#member | user:*
    permission everyone = member
}
definition doc {
    relation reader: user | user:* | team#member | team#everyone
    relation writer: user | team#member
    permission edit = writer
    permission view = reader + edit
}
"""


def _random_world(seed: int, n_edges: int):
    """Randomized relationships hitting every table the builder emits:
    direct / wildcard / userset / permission-valued-userset subjects,
    caveats (with and without context), expirations, nested team chains
    deep enough to overflow a small closure cap."""
    rng = random.Random(seed)
    n_docs = max(n_edges // 8, 8)
    n_users = max(n_edges // 16, 8)
    n_teams = 48
    rels = []
    # nested teams: a few long chains (closure overflow at small caps)
    # plus random nesting
    for t in range(1, n_teams):
        parent = t - 1 if t % 7 else rng.randrange(t)
        rels.append(rel.Relationship(
            resource_type="team", resource_id=f"t{parent}",
            resource_relation="member",
            subject_type="team", subject_id=f"t{t}",
            subject_relation="member",
        ))
    for t in range(n_teams):
        for _ in range(rng.randrange(1, 4)):
            r = rel.Relationship(
                resource_type="team", resource_id=f"t{t}",
                resource_relation="member",
                subject_type="user", subject_id=f"u{rng.randrange(n_users)}",
            )
            if rng.random() < 0.2:
                r = rel.Relationship(
                    **{**r.__dict__, "caveat_name": "on_tuesday",
                       "caveat_context": {"day": "tuesday"}},
                )
            rels.append(r)
    # one wildcard team member + wildcard doc readers
    rels.append(rel.Relationship(
        resource_type="team", resource_id="t3", resource_relation="member",
        subject_type="user", subject_id="*",
    ))
    for i in range(n_edges):
        d = f"d{rng.randrange(n_docs)}"
        kind = rng.random()
        kw = dict(resource_type="doc", resource_id=d,
                  resource_relation="reader" if rng.random() < 0.8 else "writer",
                  subject_type="user", subject_id=f"u{rng.randrange(n_users)}")
        if kind < 0.08:
            kw.update(subject_type="team",
                      subject_id=f"t{rng.randrange(n_teams)}",
                      subject_relation="member")
        elif kind < 0.11:
            kw.update(subject_type="team",
                      subject_id=f"t{rng.randrange(n_teams)}",
                      subject_relation="everyone")
            kw["resource_relation"] = "reader"
        elif kind < 0.13:
            kw.update(subject_id="*")
            kw["resource_relation"] = "reader"
        r = rel.Relationship(**kw)
        if rng.random() < 0.1:
            r = rel.Relationship(
                **{**r.__dict__, "caveat_name": "on_tuesday",
                   "caveat_context": {"day": "tuesday"} if rng.random() < 0.5
                   else {}},
            )
        if rng.random() < 0.07:
            import datetime as dt

            r = rel.Relationship(
                **{**r.__dict__,
                   "expiration": dt.datetime.fromtimestamp(
                       (NOW + rng.randrange(-10**9, 10**12)) / 1e6,
                       tz=dt.timezone.utc,
                   )},
            )
        rels.append(r)
    return rels


SNAP_COLS = [
    "node_type", "wildcard_node_of_type",
    "e_rel", "e_res", "e_subj", "e_srel1", "e_caveat", "e_ctx", "e_exp",
    "e_exp_us",
    "us_rel", "us_res", "us_subj", "us_srel", "us_caveat", "us_ctx",
    "us_exp", "us_perm", "pus_n", "pus_r",
    "ms_subj", "ms_res", "ms_rel", "ms_caveat", "ms_ctx", "ms_exp",
    "mp_subj", "mp_srel", "mp_res", "mp_rel", "mp_caveat", "mp_ctx",
    "mp_exp",
    "ar_rel", "ar_res", "ar_child", "ar_caveat", "ar_ctx", "ar_exp",
]


def _build(rels, native_on: bool, *, sharded: bool = False, M: int = 2, **cfg):
    """One full pipeline run (snapshot + flat tables) with the native
    layer forced on/off.  Fresh interner per run: the two runs must not
    share any state.  Restores the PRIOR enabled state afterwards (a
    GOCHUGARU_NATIVE=0 session must stay numpy-only past these tests)."""
    prior = native.enabled()
    native.set_enabled(native_on)
    try:
        cs = compile_schema(parse_schema(SCHEMA))
        snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
        engine = DeviceEngine(cs, EngineConfig.for_schema(cs, **cfg))
        if sharded:
            built = build_flat_arrays_sharded(
                snap, engine.config, M, plan=engine.plan
            )
        else:
            built = build_flat_arrays(snap, engine.config, plan=engine.plan)
        assert built is not None
        arrays, meta, _fstate, _cstate = built
        return snap, arrays, meta
    finally:
        native.set_enabled(prior)


def _assert_same(sa, aa, ma, sb, ab, mb):
    for col in SNAP_COLS:
        va, vb = getattr(sa, col), getattr(sb, col)
        assert va.dtype == vb.dtype and np.array_equal(va, vb), (
            f"snapshot column {col} differs"
        )
    assert sa.us_used_keys.shape == sb.us_used_keys.shape
    assert np.array_equal(sa.us_used_keys, sb.us_used_keys)
    assert set(aa) == set(ab), (
        f"table sets differ: {set(aa) ^ set(ab)}"
    )
    for k in sorted(aa):
        assert aa[k].shape == ab[k].shape, f"{k} shape differs"
        assert np.array_equal(aa[k], ab[k]), f"table {k} differs"
    assert ma == mb, "FlatMeta differs"


@pytest.mark.skipif(not native.available(), reason="no native library")
@pytest.mark.parametrize("seed", [7, 23])
def test_vectorized_builder_bitwise_parity(seed):
    """Native-accelerated build == reference numpy build, bitwise, on a
    randomized world (the world is sized past the native engagement
    threshold so the fused kernels actually run)."""
    rels = _random_world(seed, 80_000)
    sa, aa, ma = _build(rels, native_on=False)
    sb, ab, mb = _build(rels, native_on=True)
    _assert_same(sa, aa, ma, sb, ab, mb)


@pytest.mark.skipif(not native.available(), reason="no native library")
def test_parity_with_closure_overflow_and_small_caps():
    """Small closure cap forces overflow sources; small fold/T budgets
    flip the optional tables — the parity must hold on every layout."""
    rels = _random_world(3, 70_000)
    kw = dict(closure_source_cap=12)
    sa, aa, ma = _build(rels, False, **kw)
    sb, ab, mb = _build(rels, True, **kw)
    _assert_same(sa, aa, ma, sb, ab, mb)


@pytest.mark.skipif(not native.available(), reason="no native library")
def test_parity_sharded_stacked_layout():
    """The bucket-sharded (stacked) builder: batched stacking + native
    kernels vs the pure-numpy reference, bitwise."""
    rels = _random_world(11, 70_000)
    sa, aa, ma = _build(rels, False, sharded=True)
    sb, ab, mb = _build(rels, True, sharded=True)
    _assert_same(sa, aa, ma, sb, ab, mb)


@pytest.mark.parametrize("M", [2, 4])
def test_partition_first_equals_build_full_then_stack(M):
    """The partition-first stacked build (engine/partition.py, the
    default) vs the legacy build-full-then-stack path, bitwise — on a
    randomized world with usersets, caveats, wildcards, expirations,
    closure overflow, folds, and the T-index all engaged."""
    rels = _random_world(5, 70_000)
    sa, aa, ma = _build(
        rels, native.available(), sharded=True, M=M,
        flat_partition_build=True, flat_partition_chunk=1 << 14,
    )
    sb, ab, mb = _build(
        rels, native.available(), sharded=True, M=M,
        flat_partition_build=False,
    )
    _assert_same(sa, aa, ma, sb, ab, mb)


# ---------------------------------------------------------------------------
# piecewise parity of the pure-numpy rewrites (no native involvement):
# the rewritten expressions must equal the idioms they replaced
# ---------------------------------------------------------------------------


def test_feeds_searchsorted_equals_isin():
    rng = np.random.default_rng(5)
    edge_key = rng.integers(0, 5000, 200_000)
    used = np.unique(rng.integers(0, 5000, 300))
    pos = np.clip(np.searchsorted(used, edge_key), 0, used.shape[0] - 1)
    assert np.array_equal(used[pos] == edge_key, np.isin(edge_key, used))


def test_uniq_small_equals_np_unique():
    from gochugaru_tpu.engine.flat import _uniq_small

    rng = np.random.default_rng(6)
    parts = [rng.integers(0, 40, 10_000).astype(np.int32),
             np.zeros(0, np.int32),
             rng.integers(0, 40, 7).astype(np.int32)]
    ref = np.unique(np.concatenate(parts).astype(np.int64))
    got = _uniq_small(parts, 40)
    assert got.dtype == ref.dtype and np.array_equal(got, ref)


def test_dedup_rows_sorted_fast_path_is_exact():
    """The strict-sorted passthrough of fold._dedup_rows must equal the
    full sort+reduce on inputs that qualify AND on ones that don't."""
    from gochugaru_tpu.engine.fold import _Rows, _dedup_rows

    rng = np.random.default_rng(8)

    def ref(r):
        o = np.lexsort((r.e_ctx, r.e_cav, r.e_k2, r.e_res))
        er, ek, ec, ex, eu = (
            r.e_res[o], r.e_k2[o], r.e_cav[o], r.e_ctx[o], r.e_until[o]
        )
        first = np.ones(er.shape[0], bool)
        first[1:] = (
            (er[1:] != er[:-1]) | (ek[1:] != ek[:-1])
            | (ec[1:] != ec[:-1]) | (ex[1:] != ex[:-1])
        )
        st = np.nonzero(first)[0]
        return (er[first], ek[first], ec[first], ex[first],
                np.maximum.reduceat(eu, st))

    z = np.zeros(0, np.int32)
    for case in ("sorted-unique", "random"):
        n = 5_000
        if case == "sorted-unique":
            res = np.sort(rng.choice(100_000, n, replace=False)).astype(np.int32)
            k2 = rng.integers(0, 2**40, n)
        else:
            res = rng.integers(0, 50, n).astype(np.int32)
            k2 = rng.integers(0, 10, n)
        r = _Rows(
            res, k2.astype(np.int64),
            rng.integers(0, 3, n).astype(np.int32),
            rng.integers(-1, 5, n).astype(np.int32),
            rng.integers(1, 100, n).astype(np.int32),
            z, z, z, z,
        )
        got = _dedup_rows(r)
        want = ref(r)
        for g, w in zip((got.e_res, got.e_k2, got.e_cav, got.e_ctx,
                         got.e_until), want):
            assert np.array_equal(g, w), case


# ---------------------------------------------------------------------------
# budget smoke + stage-timer presence (CI-safe)
# ---------------------------------------------------------------------------


def test_first_prepare_budget_and_stage_timers():
    """First prepare of a fixed 150k-edge world: generous wall-clock
    envelope (regression tripwire, not a benchmark) and every pipeline
    stage must have published its ``prepare.*`` timer — the decomposition
    benchmarks/bench_import.py reports."""
    rels = _random_world(1, 150_000)
    cs = compile_schema(parse_schema(SCHEMA))
    metrics.default.reset()
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs))
    t0 = time.perf_counter()
    dsnap = engine.prepare(snap)
    wall = time.perf_counter() - t0
    assert dsnap.flat_meta is not None
    got = metrics.default.snapshot()
    for stage in ("prepare.closure_s", "prepare.pack_s", "prepare.hash_s",
                  "prepare.tindex_s", "prepare.h2d_s", "prepare.total_s",
                  "prepare.snapshot_s"):
        assert f"{stage}.count" in got, f"missing stage timer {stage}"
    # ~1.5 s measured on a 2-core CI box; 20 s is the don't-regress bar
    assert wall < 20.0, f"first prepare took {wall:.1f}s at 150k edges"

"""Regression tests for code-review findings (round 1, batch 2)."""

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.oracle import F, T, Oracle
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.store import Store
from gochugaru_tpu.utils.errors import AlreadyExistsError


def test_write_is_atomic_on_create_conflict():
    s = Store()
    s.write_schema("definition user {}\ndefinition doc { relation viewer: user }")
    t1 = rel.Txn()
    t1.create(rel.must_from_triple("doc:a", "viewer", "user:alice"))
    s.write(t1)
    head = s.head_revision

    t2 = rel.Txn()
    t2.touch(rel.must_from_triple("doc:b", "viewer", "user:bob"))
    t2.create(rel.must_from_triple("doc:a", "viewer", "user:alice"))  # conflict
    with pytest.raises(AlreadyExistsError):
        s.write(t2)
    # nothing applied, no revision minted
    assert len(s) == 1
    assert s.head_revision == head


def test_delete_then_create_same_key_in_one_txn():
    s = Store()
    s.write_schema("definition user {}\ndefinition doc { relation viewer: user }")
    r = rel.must_from_triple("doc:a", "viewer", "user:alice")
    t1 = rel.Txn()
    t1.create(r)
    s.write(t1)
    t2 = rel.Txn()
    t2.delete(r)
    t2.create(r)
    s.write(t2)  # legal: in-txn sequencing
    assert len(s) == 1


def test_read_filter_uses_interner_type_ids():
    # Interner assigns type ids in first-seen order, schema sorts them —
    # filters must translate through the interner's table.
    s = Store()
    s.write_schema(
        "definition user {}\ndefinition zz_doc { relation viewer: user }\n"
        "definition aa_doc { relation viewer: user }"
    )
    txn = rel.Txn()
    txn.create(rel.must_from_triple("zz_doc:z", "viewer", "user:u"))
    txn.create(rel.must_from_triple("aa_doc:a", "viewer", "user:u"))
    s.write(txn)
    got = list(s.read(consistency.full(), rel.new_filter("zz_doc", "", "")))
    assert [r.resource_type for r in got] == ["zz_doc"]
    f = rel.new_filter("zz_doc", "", "")
    f.with_subject_filter("user", "u")
    assert len(list(s.read(consistency.full(), f))) == 1


def test_oracle_does_not_memoize_cycle_cut_values():
    # grp1#member = {grp2#member, user:u}; grp2#member = {grp1#member};
    # view = ra & rc where ra → grp1#member, rc → grp2#member.
    # Both memberships are T; a stale cycle-cut memo made the & return F.
    schema = """
    definition user {}
    definition grp { relation member: user | grp#member }
    definition doc {
        relation ra: grp#member
        relation rc: grp#member
        permission view = ra & rc
    }
    """
    o = Oracle(
        compile_schema(parse_schema(schema)),
        [
            rel.must_from_tuple("grp:1#member", "grp:2#member"),
            rel.must_from_tuple("grp:1#member", "user:u"),
            rel.must_from_tuple("grp:2#member", "grp:1#member"),
            rel.must_from_tuple("doc:d#ra", "grp:1#member"),
            rel.must_from_tuple("doc:d#rc", "grp:2#member"),
        ],
    )
    assert o.check("grp", "1", "member", "user", "u") == T
    assert o.check("grp", "2", "member", "user", "u") == T
    assert o.check("doc", "d", "view", "user", "u") == T


def test_import_rejects_intra_batch_duplicates_and_returns_token():
    s = Store()
    s.write_schema("definition user {}\ndefinition doc { relation viewer: user }")
    r = rel.must_from_triple("doc:a", "viewer", "user:alice")
    with pytest.raises(AlreadyExistsError):
        s.import_relationships([r, r.with_caveat("", {})])
    assert len(s) == 0
    token = s.import_relationships([r])
    assert token.startswith("gtz1.")


def test_caveat_body_with_brace_in_string():
    s = parse_schema(
        'caveat c(s string) { s == "}" }\ndefinition user {}'
    )
    assert s.caveats["c"].expression == 's == "}"'
    assert "user" in s.definitions


def test_cel_string_escapes():
    prog = compile_cel("c", {"s": "string"}, r's == "a\nb"')
    assert prog.evaluate({"s": "a\nb"}) is True
    assert prog.evaluate({"s": "anb"}) is False
    prog2 = compile_cel("c", {"s": "string"}, r's == "A"')
    assert prog2.evaluate({"s": "A"}) is True


def test_naive_expiration_consistent_between_paths():
    import datetime as dt

    from gochugaru_tpu.rel.relationship import expiration_micros

    naive = dt.datetime(2030, 1, 1, 12, 0, 0)
    aware = naive.replace(tzinfo=dt.timezone.utc)
    assert expiration_micros(naive) == expiration_micros(aware)

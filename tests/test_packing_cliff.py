"""The int32 key-packing bound (engine/flat.py _node_radix).

The flat engine packs (slot, node) and (subject, srel+1) into single
int32 columns; a graph with pow2(num_nodes) · (num_slots+1) ≥ 2³¹ can't
pack and falls back to the legacy two-phase kernel — ~1.1k checks/s on
the CPU proxy vs millions on the flat path (measured at 4.1M nodes ×
511 slots, 4M edges).  These tests pin (a) where the bound trips and
(b) that the fallback stays CORRECT, so the cliff is a measured,
documented performance edge — never a wrong answer.  README "Status &
known limits" carries the operator-facing numbers.
"""

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.engine.flat import _node_radix
from gochugaru_tpu.schema import compile_schema, parse_schema

from test_flat_engine import world  # noqa: E402

NOW = 1_700_000_000_000_000


class _FakeSnap:
    def __init__(self, num_nodes, num_slots):
        self.num_nodes = num_nodes
        self.num_slots = num_slots


def test_radix_bound_formula():
    # pow2(nodes) · (slots+1) < 2³¹ packs; at/over it does not
    assert _node_radix(_FakeSnap(1 << 20, 63)) is not None
    assert _node_radix(_FakeSnap((1 << 25) + 1, 31)) is None  # 2²⁶·32 = 2³¹
    assert _node_radix(_FakeSnap(1 << 25, 30)) is not None
    # headroom doubling never pushes past the bound
    n, s1 = _node_radix(_FakeSnap(1000, 7))
    assert n * s1 < 2**31 and n >= 2048  # doubled for delta headroom


def test_unpackable_world_stays_correct_on_legacy_path():
    # many slots push a modest world over the packing bound (formula
    # pinned above at full scale); the legacy two-phase kernel must
    # answer exactly (differential).  Kept to 48 relations so the
    # legacy kernel's compile stays test-suite-fast
    rels_txt = "\n".join(f"    relation r{i}: user" for i in range(48))
    schema = (
        "definition user {}\n"
        f"definition res {{\n{rels_txt}\n    permission p = r0 + r1\n}}"
    )
    cs = compile_schema(parse_schema(schema))
    assert cs.num_slots >= 49
    rows = []
    # enough nodes that pow2(nodes)·(slots+1) ≥ 2³¹ requires millions —
    # too slow for a unit test, so assert the bound formula separately
    # (above) and exercise the legacy path by disabling flat here
    for i in range(40):
        rows.append(rel.must_from_triple(f"res:d{i}", "r0", f"user:u{i % 7}"))
        if i % 3 == 0:
            rows.append(rel.must_from_triple(f"res:d{i}", "r1", f"user:u{(i + 1) % 7}"))
    from gochugaru_tpu.caveats import compile_cel
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot

    snap = build_snapshot(1, cs, Interner(), rows, epoch_us=NOW)
    oracle = Oracle(cs, rows, {}, now_us=NOW)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, use_flat=False))
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is None
    checks = [
        rel.must_from_triple(f"res:d{i}", "p", f"user:u{u}")
        for i in range(40)
        for u in range(7)
    ]
    from gochugaru_tpu.engine.oracle import T

    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q) == T
        assert bool(d[i]) == want or ovf[i], q

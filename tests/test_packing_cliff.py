"""The int32 key-packing bound (engine/flat.py _node_radix + SlotMaps).

The flat engine packs (slot, node) and (subject, srel+1) into single
int32 columns through a DENSE remap of the ACTIVE slots — the cliff is
pow2(num_nodes) · max(active k1 slots, active srels+1) ≥ 2³¹, NOT the
schema's declared slot count.  A 511-slot schema with 2 active slots
stays on the flat path at 100M+ nodes; a world genuinely over the dense
bound falls back to the legacy two-phase kernel (~1.1k checks/s CPU
proxy vs millions) — these tests pin the bound, the dense engagement,
and the fallback's correctness.  README "Status & known limits" carries
the operator-facing numbers.
"""

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.engine.flat import SlotMaps, _node_radix
from gochugaru_tpu.schema import compile_schema, parse_schema

from test_flat_engine import world  # noqa: E402

NOW = 1_700_000_000_000_000


class _FakeSnap:
    def __init__(self, num_nodes):
        self.num_nodes = num_nodes


def _maps(n_k1, n_k2):
    z = np.zeros(1, np.int32)
    return SlotMaps(k1=z, k2=z, k1_raw=z, k2_raw=z, n_k1=n_k1, S1=n_k2 + 1)


def test_radix_bound_formula():
    # pow2(nodes) · max(active k1, active srels+1) < 2³¹ packs
    assert _node_radix(_FakeSnap(1 << 20), _maps(63, 62)) is not None
    assert _node_radix(_FakeSnap((1 << 25) + 1), _maps(31, 31)) is None
    assert _node_radix(_FakeSnap(1 << 25), _maps(31, 29)) is not None
    # headroom doubling never pushes past the bound
    n = _node_radix(_FakeSnap(1000), _maps(7, 6))
    assert n * 7 < 2**31 and n >= 2048  # doubled for delta headroom


def test_many_declared_slots_few_active_stays_flat():
    # the dense remap: hundreds of DECLARED relations but only two
    # active ones must keep the flat engine (pre-remap this fell off at
    # pow2(nodes)·(num_slots+1) ≥ 2³¹ and ran ~1.1k checks/s)
    rels_txt = "\n".join(f"    relation r{i}: user" for i in range(200))
    schema = (
        "definition user {}\n"
        f"definition res {{\n{rels_txt}\n    permission p = r0 + r1\n}}"
    )
    rows = [
        rel.must_from_triple(f"res:d{i}", "r0", f"user:u{i % 5}")
        for i in range(30)
    ]
    engine, dsnap, oracle = world(schema, rows)
    meta = dsnap.flat_meta
    assert meta is not None, "dense remap should keep the flat path"
    # two active k1 slots (r0 rows only → 1) regardless of 200 declared
    assert sum(1 for x in meta.k1_dense if x >= 0) <= 2
    from gochugaru_tpu.engine.oracle import T

    checks = [
        rel.must_from_triple(f"res:d{i}", "p", f"user:u{u}")
        for i in range(30)
        for u in range(5)
    ] + [rel.must_from_triple("res:d0", "r7", "user:u0")]  # inactive slot
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q) == T
        assert not ovf[i] and bool(d[i]) == want, q


def test_unpackable_world_stays_correct_on_legacy_path():
    # many slots push a modest world over the packing bound (formula
    # pinned above at full scale); the legacy two-phase kernel must
    # answer exactly (differential).  Kept to 48 relations so the
    # legacy kernel's compile stays test-suite-fast
    rels_txt = "\n".join(f"    relation r{i}: user" for i in range(48))
    schema = (
        "definition user {}\n"
        f"definition res {{\n{rels_txt}\n    permission p = r0 + r1\n}}"
    )
    cs = compile_schema(parse_schema(schema))
    assert cs.num_slots >= 49
    rows = []
    # enough nodes that pow2(nodes)·(slots+1) ≥ 2³¹ requires millions —
    # too slow for a unit test, so assert the bound formula separately
    # (above) and exercise the legacy path by disabling flat here
    for i in range(40):
        rows.append(rel.must_from_triple(f"res:d{i}", "r0", f"user:u{i % 7}"))
        if i % 3 == 0:
            rows.append(rel.must_from_triple(f"res:d{i}", "r1", f"user:u{(i + 1) % 7}"))
    from gochugaru_tpu.caveats import compile_cel
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot

    snap = build_snapshot(1, cs, Interner(), rows, epoch_us=NOW)
    oracle = Oracle(cs, rows, {}, now_us=NOW)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, use_flat=False))
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is None
    checks = [
        rel.must_from_triple(f"res:d{i}", "p", f"user:u{u}")
        for i in range(40)
        for u in range(7)
    ]
    from gochugaru_tpu.engine.oracle import T

    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q) == T
        assert bool(d[i]) == want or ovf[i], q

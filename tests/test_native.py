"""Native ingest layer (C++ via ctypes): differential tests against the
pure-Python/numpy paths it replaces.  If the library can't build on a
platform, the whole module is skipped — the framework works identically
without it, just slower at scale."""

import numpy as np
import pytest

from gochugaru_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native ingest library not available"
)


def test_interner_matches_python_reference():
    from gochugaru_tpu.native.interner import NativeInterner
    from gochugaru_tpu.store.interner import Interner

    nat, ref = NativeInterner(), Interner()
    pairs = [
        ("user", "alice"), ("user", "bob"), ("doc", "alice"), ("user", "alice"),
        ("team", "eng"), ("doc", ""), ("user", "ünïcode-οκ"), ("team", "eng"),
    ]
    for t, i in pairs:
        assert nat.node(t, i) == ref.node(t, i)
    assert len(nat) == len(ref)
    assert nat.num_types == ref.num_types
    for n in range(len(ref)):
        assert nat.key_of(n) == ref.key_of(n)
    assert (nat.node_type_array() == ref.node_type_array()).all()
    assert nat.lookup("user", "bob") == ref.lookup("user", "bob")
    assert nat.lookup("user", "nope") == -1
    assert nat.lookup("ghost", "x") == -1


def test_interner_batch_equivalence_and_growth():
    from gochugaru_tpu.native.interner import NativeInterner

    it = NativeInterner()
    ids = [f"id{i}" for i in range(200_000)]  # forces several table growths
    nodes = it.node_batch("user", ids)
    assert nodes.dtype == np.int32
    assert len(np.unique(nodes)) == len(ids)
    # re-interning returns identical ids; singles agree with batch
    assert (it.node_batch("user", ids[:1000]) == nodes[:1000]).all()
    assert it.node("user", "id500") == nodes[500]
    found = it.lookup_batch("user", ["id0", "missing", "id199999"])
    assert found[0] == nodes[0] and found[1] == -1 and found[2] == nodes[-1]


def test_sorts_match_numpy():
    from gochugaru_tpu.native.sort import argsort1, lexsort2, lexsort4

    rng = np.random.default_rng(7)
    n = 100_000
    a = rng.integers(0, 50, n).astype(np.int32)
    b = rng.integers(-1, 40, n).astype(np.int32)
    c = rng.integers(0, 2**31 - 1, n).astype(np.int32)
    d = rng.integers(0, 5, n).astype(np.int32)
    k = np.stack([a, b, c, d])
    got = k[:, lexsort4(a, b, c, d)]
    want = k[:, np.lexsort((d, c, b, a))]
    assert (got == want).all()
    got2 = k[:2, lexsort2(a, b)]
    want2 = k[:2, np.lexsort((b, a))]
    assert (got2 == want2).all()
    assert (a[argsort1(a)] == np.sort(a)).all()


def test_snapshot_build_native_vs_python_interner():
    """The same world through both interners produces equivalent snapshots
    (column-for-column after node-id translation is identity, since both
    assign ids in first-intern order)."""
    from gochugaru_tpu import rel
    from gochugaru_tpu.native.interner import NativeInterner
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot

    schema = """
    definition user {}
    definition team { relation member: user | team#member }
    definition repo {
        relation owner: team
        relation reader: user
        permission read = reader + owner->member
    }
    """
    cs = compile_schema(parse_schema(schema))
    rels = [
        rel.must_from_tuple("team:eng#member", "user:alice"),
        rel.must_from_tuple("team:all#member", "team:eng#member"),
        rel.must_from_tuple("repo:core#owner", "team:all"),
        rel.must_from_tuple("repo:core#reader", "user:bob"),
    ]
    s_py = build_snapshot(1, cs, Interner(), rels, epoch_us=0)
    s_nat = build_snapshot(1, cs, NativeInterner(), rels, epoch_us=0)
    for col in ("e_rel", "e_res", "e_subj", "e_srel1", "ms_subj", "mp_subj",
                "ar_rel", "ar_res", "ar_child", "us_rel", "us_res"):
        assert (getattr(s_py, col) == getattr(s_nat, col)).all(), col
    assert (s_py.node_type == s_nat.node_type).all()


def test_store_uses_available_interner():
    from gochugaru_tpu.native.interner import make_interner
    from gochugaru_tpu.store.store import Store

    s = Store()
    it = make_interner()
    assert type(s.interner) is type(it)

"""CEL-subset caveat compiler/evaluator tests."""

import pytest

from gochugaru_tpu.caveats import UNKNOWN, CelCompileError, compile_cel


def ev(src, params, ctx):
    return compile_cel("t", params, src).evaluate(ctx)


def test_comparisons_and_logic():
    p = {"day": "string", "n": "int"}
    assert ev('day == "tuesday"', p, {"day": "tuesday"}) is True
    assert ev('day == "tuesday"', p, {"day": "monday"}) is False
    assert ev('day == "tuesday" || n > 3', p, {"day": "monday", "n": 5}) is True
    assert ev('day == "tuesday" && n > 3', p, {"day": "tuesday", "n": 1}) is False
    assert ev("!(n >= 10)", p, {"n": 3}) is True


def test_unknown_propagation():
    p = {"a": "int", "b": "int"}
    assert ev("a > 1", p, {}) is UNKNOWN
    # Kleene: T || U = T, F && U = F
    assert ev("a > 1 || b > 1", p, {"a": 5}) is True
    assert ev("a > 1 && b > 1", p, {"a": 0}) is False
    assert ev("a > 1 && b > 1", p, {"a": 5}) is UNKNOWN


def test_arithmetic_and_ternary():
    p = {"x": "int", "y": "int"}
    assert ev("x + y * 2 == 7", p, {"x": 1, "y": 3}) is True
    assert ev("x % 2 == 0 ? y > 0 : y < 0", p, {"x": 4, "y": 1}) is True
    assert ev("-x < 0", p, {"x": 3}) is True
    # CEL int division truncates toward zero
    assert ev("x / y == -1", p, {"x": -3, "y": 2}) is True


def test_in_and_lists():
    p = {"region": "string", "allowed": "list"}
    assert ev('region in ["us", "eu"]', p, {"region": "eu"}) is True
    assert ev('region in ["us", "eu"]', p, {"region": "ap"}) is False
    assert ev("region in allowed", p, {"region": "us", "allowed": ["us"]}) is True


def test_member_access():
    p = {"req": "map"}
    assert ev('req.ip == "10.0.0.1"', p, {"req": {"ip": "10.0.0.1"}}) is True
    assert ev('req.ip == "10.0.0.1"', p, {"req": {}}) is UNKNOWN


def test_compile_errors():
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "a ==")  # truncated
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "b > 1")  # undeclared ident
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "wat"}, "a > 1")  # unknown type
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "a @ 1")  # bad char


def test_timestamp_duration_host_evaluation():
    """timestamp()/duration() constructors compute on the host: the CEL
    time algebra (ts − ts = dur, ts ± dur = ts, dur ± dur = dur) plus
    comparisons, with declared params coerced from RFC 3339 / Go
    duration strings, datetimes, and numeric seconds."""
    import datetime as dt

    p = {"at": "timestamp"}
    assert ev('at < timestamp("2024-06-01T00:00:00Z")', p,
              {"at": "2024-01-01T00:00:00Z"}) is True
    assert ev('at < timestamp("2024-06-01T00:00:00Z")', p,
              {"at": "2024-12-01T00:00:00Z"}) is False
    assert ev('at < timestamp("2024-06-01T00:00:00Z")', p, {}) is UNKNOWN
    # datetime and epoch-seconds coercion
    t0 = dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc)
    assert ev('at >= timestamp("2024-01-01T00:00:00Z")', p, {"at": t0}) is True
    assert ev('at == timestamp("2024-01-01T00:00:00Z")', p,
              {"at": t0.timestamp()}) is True
    # offsets (RFC 3339 with numeric zone)
    assert ev('at == timestamp("2024-01-01T02:00:00+02:00")', p,
              {"at": t0}) is True

    d = {"age": "duration"}
    assert ev('age <= duration("1h30m")', d, {"age": "45m"}) is True
    assert ev('age <= duration("1h30m")', d, {"age": "2h"}) is False
    assert ev('age == duration("90s")', d, {"age": 90}) is True
    assert ev('age == duration("-2m")', d, {"age": "-2m"}) is True
    assert ev('age == duration("1.5s")', d, {"age": 1.5}) is True

    # the algebra
    both = {"start": "timestamp", "now": "timestamp"}
    expr = 'now - start < duration("30m") && now >= start'
    assert ev(expr, both, {
        "start": t0, "now": t0 + dt.timedelta(minutes=10)}) is True
    assert ev(expr, both, {
        "start": t0, "now": t0 + dt.timedelta(hours=1)}) is False
    assert ev(expr, both, {"start": t0}) is UNKNOWN
    assert ev('timestamp("2024-01-01T01:00:00Z")'
              ' - timestamp("2024-01-01T00:00:00Z") == duration("1h")',
              {}, {}) is True
    assert ev('timestamp("2024-01-01T00:00:00Z") + duration("1h")'
              ' == timestamp("2024-01-01T01:00:00Z")', {}, {}) is True
    assert ev('duration("1h") - duration("30m") == duration("30m")',
              {}, {}) is True


def test_timestamp_duration_compile_errors():
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'frobnicate("x")')
    # literal constructor arguments validate at COMPILE time (schema
    # write), not on the first live check
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'timestamp("not a time")')
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'duration("3 parsecs")')
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, "timestamp() == timestamp()")  # arity
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'duration("1h", "2h") == duration("1h")')
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'timestamp("a" "b") < timestamp("c")')
    with pytest.raises(CelCompileError):
        compile_cel("t", {}, 'duration(5) == duration("5s")')  # non-str
    with pytest.raises(CelCompileError):
        # comparing a timestamp against a bare number is a type error
        compile_cel(
            "t", {"at": "timestamp"}, "at < 5"
        ).evaluate({"at": "2024-01-01T00:00:00Z"})


def test_duration_literal_strictness():
    """Go/CEL reject bare signs and interior-signed parts — a malformed
    stored context must ERROR, never coerce to a grantable zero."""
    from gochugaru_tpu.caveats.cel import parse_duration

    assert parse_duration("0").us == 0
    assert parse_duration("-1h30m").us == -5_400_000_000
    for bad in ("-", "+", "", "1h-30m", "-1h-30m", "1h+30m", "h", "1x"):
        with pytest.raises(CelCompileError):
            parse_duration(bad)
    # through the evaluator: a declared duration param with a malformed
    # value raises instead of silently comparing as zero
    with pytest.raises(CelCompileError):
        ev('age <= duration("1h")', {"age": "duration"}, {"age": "-"})
    # bool is an int subtype but a True/False time value is garbage —
    # must ERROR, never coerce to the epoch / zero duration
    with pytest.raises(CelCompileError):
        ev('age <= duration("1h")', {"age": "duration"}, {"age": False})
    with pytest.raises(CelCompileError):
        ev('at < timestamp("2024-06-01T00:00:00Z")',
           {"at": "timestamp"}, {"at": False})


def test_timestamp_caveat_lowers_on_device():
    """Caveats computing with timestamps lower to the typed i64-µs
    device VM (round 25 closed the carried ROADMAP item — this test
    used to pin the host-first decline); only dynamic constructors
    over non-literal arguments still resolve through the host
    oracle (tests/test_device_caveats.py)."""
    from gochugaru_tpu.caveats.device import build_caveat_plan
    from gochugaru_tpu.schema import compile_schema, parse_schema

    cs = compile_schema(parse_schema("""
    caveat not_expired(deadline timestamp, now timestamp) {
        now < deadline
    }
    definition user {}
    definition doc {
        relation reader: user with not_expired
        permission view = reader
    }
    """))
    plan = build_caveat_plan(cs)
    assert plan.has_device_programs
    assert not plan.host_only[cs.caveat_ids["not_expired"]]

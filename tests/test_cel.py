"""CEL-subset caveat compiler/evaluator tests."""

import pytest

from gochugaru_tpu.caveats import UNKNOWN, CelCompileError, compile_cel


def ev(src, params, ctx):
    return compile_cel("t", params, src).evaluate(ctx)


def test_comparisons_and_logic():
    p = {"day": "string", "n": "int"}
    assert ev('day == "tuesday"', p, {"day": "tuesday"}) is True
    assert ev('day == "tuesday"', p, {"day": "monday"}) is False
    assert ev('day == "tuesday" || n > 3', p, {"day": "monday", "n": 5}) is True
    assert ev('day == "tuesday" && n > 3', p, {"day": "tuesday", "n": 1}) is False
    assert ev("!(n >= 10)", p, {"n": 3}) is True


def test_unknown_propagation():
    p = {"a": "int", "b": "int"}
    assert ev("a > 1", p, {}) is UNKNOWN
    # Kleene: T || U = T, F && U = F
    assert ev("a > 1 || b > 1", p, {"a": 5}) is True
    assert ev("a > 1 && b > 1", p, {"a": 0}) is False
    assert ev("a > 1 && b > 1", p, {"a": 5}) is UNKNOWN


def test_arithmetic_and_ternary():
    p = {"x": "int", "y": "int"}
    assert ev("x + y * 2 == 7", p, {"x": 1, "y": 3}) is True
    assert ev("x % 2 == 0 ? y > 0 : y < 0", p, {"x": 4, "y": 1}) is True
    assert ev("-x < 0", p, {"x": 3}) is True
    # CEL int division truncates toward zero
    assert ev("x / y == -1", p, {"x": -3, "y": 2}) is True


def test_in_and_lists():
    p = {"region": "string", "allowed": "list"}
    assert ev('region in ["us", "eu"]', p, {"region": "eu"}) is True
    assert ev('region in ["us", "eu"]', p, {"region": "ap"}) is False
    assert ev("region in allowed", p, {"region": "us", "allowed": ["us"]}) is True


def test_member_access():
    p = {"req": "map"}
    assert ev('req.ip == "10.0.0.1"', p, {"req": {"ip": "10.0.0.1"}}) is True
    assert ev('req.ip == "10.0.0.1"', p, {"req": {}}) is UNKNOWN


def test_compile_errors():
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "a ==")  # truncated
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "b > 1")  # undeclared ident
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "wat"}, "a > 1")  # unknown type
    with pytest.raises(CelCompileError):
        compile_cel("t", {"a": "int"}, "a @ 1")  # bad char

"""Tests for consistency strategies, Context, and the retry envelope."""

import pytest

from gochugaru_tpu import consistency
from gochugaru_tpu.utils import (
    Context,
    DeadlineExceededError,
    UnavailableError,
    background,
    retry_retriable_errors,
)
from gochugaru_tpu.utils.errors import PermanentError, is_retriable


def test_strategies():
    assert consistency.full().requirement == consistency.Requirement.FULL
    assert consistency.min_latency().requirement == consistency.Requirement.MIN_LATENCY
    s = consistency.at_least("r42")
    assert (s.requirement, s.revision) == (consistency.Requirement.AT_LEAST, "r42")
    s = consistency.snapshot("r42")
    assert (s.requirement, s.revision) == (consistency.Requirement.SNAPSHOT, "r42")


def test_overlap_key_in_context():
    ctx = background()
    assert ctx.value(consistency.OVERLAP_KEY) is None
    ctx2 = consistency.with_overlap_key(ctx, "tenant-7")
    assert ctx2.value(consistency.OVERLAP_KEY) == "tenant-7"
    # parent untouched
    assert ctx.value(consistency.OVERLAP_KEY) is None


def test_context_cancel_propagates():
    parent = background().with_cancel()
    child = parent.with_value("k", "v")
    assert not child.done()
    parent.cancel()
    assert child.done()
    assert child.err() is not None


def test_retry_succeeds_after_transient():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise UnavailableError("try later")
        return "ok"

    assert retry_retriable_errors(background(), fn, sleep=lambda s: None) == "ok"
    assert len(calls) == 3


def test_retry_permanent_raises_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("bad input")

    with pytest.raises(ValueError):
        retry_retriable_errors(background(), fn, sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_compat_strings_are_retriable():
    # SpiceDB < v1.30 compat strings (client/client.go:197)
    assert is_retriable(RuntimeError("a retryable error happened"))
    assert is_retriable(RuntimeError("try restarting transaction"))
    assert not is_retriable(RuntimeError("boom"))
    assert not is_retriable(PermanentError("nope"))


def test_retry_respects_deadline():
    ctx = background().with_timeout(-1)  # already expired
    with pytest.raises(DeadlineExceededError):
        retry_retriable_errors(ctx, lambda: "never", sleep=lambda s: None)

"""End-to-end tests for the columnar bulk-import path THROUGH the Client
(round-2 Weak #3: the client API never reached the store's columnar
threshold, so segments were dead code).  Every product surface is
exercised against imported segments: check, read, delete-by-filter,
watch replay, schema slot remap, export round-trip, TOUCH recovery."""

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import Client
from gochugaru_tpu.rel.filter import Filter, PreconditionedFilter
from gochugaru_tpu.rel.update import UpdateFilter, UpdateType
from gochugaru_tpu.store.store import COLUMNAR_IMPORT_MIN
from gochugaru_tpu.utils.context import background

SCHEMA = """
definition user {}
definition group { relation member: user }
definition doc {
    relation reader: user | group#member
    relation owner: user
    permission view = reader + owner
}
"""

N = COLUMNAR_IMPORT_MIN + 2_000  # one columnar flush + headroom


def bulk(n=N):
    for i in range(n):
        yield rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i % 97}")


def make_client():
    c = Client()
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    c.import_relationships(ctx, bulk())
    return c, ctx


def test_client_import_lands_columnar_segments():
    c, ctx = make_client()
    assert len(c.store._segments) >= 1
    seg_rows = sum(s.live_count for s in c.store._segments)
    assert seg_rows == N  # nothing fell into the per-object dict
    assert len(c.store._live) == 0


def test_checks_see_segment_rows():
    c, ctx = make_client()
    cs = consistency.full()
    got = c.check(
        ctx, cs,
        rel.must_from_triple("doc:d5", "view", "user:u5"),
        rel.must_from_triple("doc:d5", "view", "user:u6"),
        rel.must_from_triple(f"doc:d{N-1}", "view", f"user:u{(N-1) % 97}"),
    )
    assert got == [True, False, True]


def test_touch_reimport_through_client():
    c, ctx = make_client()
    # re-importing the same data must recover via TOUCH, not raise
    c.import_relationships(ctx, bulk())
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d1", "view", "user:u1"))


def test_delete_by_filter_kills_segment_rows():
    c, ctx = make_client()
    f = PreconditionedFilter(Filter("doc", optional_resource_id="d7"))
    c.delete(ctx, f)
    cs = consistency.full()
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:d7", "view", "user:u7"))
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d8", "view", "user:u8"))
    seg_rows = sum(s.live_count for s in c.store._segments)
    assert seg_rows == N - 1


def test_watch_replays_columnar_import_lazily():
    c, ctx = make_client()
    # resume from revision 1 (the schema write): the import must replay
    count = 0
    first = None
    cctx = ctx.with_cancel()
    for u in c.updates_since_revision(cctx, UpdateFilter(), "gtz1.1"):
        if first is None:
            first = u
        count += 1
        if count >= N:
            cctx.cancel()
            break
    assert count == N
    assert first.update_type == UpdateType.CREATE
    assert first.relationship.resource_type == "doc"


def test_write_schema_remaps_segment_slots():
    c, ctx = make_client()
    # adding a relation that sorts before "reader" renumbers every slot;
    # segment columns must be remapped in place
    c.write_schema(ctx, SCHEMA.replace(
        'relation reader:', 'relation archive: user\n    relation reader:'
    ))
    cs = consistency.full()
    assert c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "view", "user:u3"))
    assert not c.check_one(ctx, cs, rel.must_from_triple("doc:d3", "archive", "user:u3"))


def test_export_round_trips_segments():
    c, ctx = make_client()
    _, rev = c.read_schema(ctx)
    rows = list(c.export_relationships(ctx, rev))
    assert len(rows) == N
    keys = {(r.resource_id, r.subject_id) for r in rows}
    assert ("d5", "u5") in keys
    # restore into a fresh client and compare a spot check
    c2 = Client()
    ctx2 = background()
    c2.write_schema(ctx2, SCHEMA)
    c2.import_relationships(ctx2, rows)
    assert c2.check_one(
        ctx2, consistency.full(),
        rel.must_from_triple("doc:d5", "view", "user:u5"),
    )
    assert len(c2.store._segments) >= 1


def test_mixed_userset_segment_world_checks():
    c = Client()
    ctx = background()
    c.write_schema(ctx, SCHEMA)

    def gen():
        for i in range(COLUMNAR_IMPORT_MIN):
            yield rel.must_from_triple(f"doc:m{i}", "reader", "group:g#member")
        yield rel.must_from_triple("group:g", "member", "user:alice")

    c.import_relationships(ctx, gen())
    cs = consistency.full()
    got = c.check(
        ctx, cs,
        rel.must_from_triple("doc:m0", "view", "user:alice"),
        rel.must_from_triple(f"doc:m{COLUMNAR_IMPORT_MIN-1}", "view", "user:alice"),
        rel.must_from_triple("doc:m0", "view", "user:bob"),
    )
    assert got == [True, True, False]

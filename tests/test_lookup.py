"""Device-backed LookupResources/LookupSubjects (engine/lookup.py) —
differential tests against the host oracle's exhaustive scans on
deterministic and randomized worlds.

Contract: lookup_*_device returns exactly sorted(oracle.lookup_*) — the
reverse candidate expansion is a superset by construction, and the
batched device forward check (itself differentially tested) filters it
exactly, with oracle re-checks for overflowed candidates."""

import random

import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.lookup import (
    lookup_resources_device,
    lookup_subjects_device,
)
from gochugaru_tpu.engine.oracle import Oracle
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000


def world(schema, rels):
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    return cs, engine, dsnap, oracle


def assert_lookup_resources_match(engine, dsnap, oracle, rtype, perm, s):
    stype, _, rest = s.partition(":")
    sid, _, srel = rest.partition("#")
    got = lookup_resources_device(
        engine, dsnap, rtype, perm, stype, sid, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_resources(rtype, perm, stype, sid, srel))
    assert got == want, f"lookup_resources({rtype}#{perm}, {s}): {got} != {want}"


def assert_lookup_subjects_match(engine, dsnap, oracle, rtype, rid, perm, subj):
    stype, _, srel = subj.partition("#")
    got = lookup_subjects_device(
        engine, dsnap, rtype, rid, perm, stype, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_subjects(rtype, rid, perm, stype, srel))
    assert got == want, (
        f"lookup_subjects({rtype}:{rid}#{perm}, {subj}): {got} != {want}"
    )


RBAC = """
definition user {}
definition team { relation member: user }
definition org {
    relation admin: user
    relation member: user | team#member
}
definition repo {
    relation org: org
    relation maintainer: user | team#member
    relation reader: user
    permission admin = org->admin + maintainer
    permission read = reader + admin + org->member
}
"""


def rbac_world(seed=3, n_users=20, n_teams=4, n_orgs=2, n_repos=10):
    rng = random.Random(seed)
    users = [f"user:u{i}" for i in range(n_users)]
    teams = [f"team:t{i}" for i in range(n_teams)]
    orgs = [f"org:o{i}" for i in range(n_orgs)]
    repos = [f"repo:r{i}" for i in range(n_repos)]
    rels = []
    for t in teams:
        for u in rng.sample(users, 5):
            rels.append(rel.must_from_tuple(f"{t}#member", u))
    for o in orgs:
        rels.append(rel.must_from_tuple(f"{o}#admin", rng.choice(users)))
        rels.append(
            rel.must_from_tuple(f"{o}#member", f"{rng.choice(teams)}#member")
        )
    for r in repos:
        rels.append(rel.must_from_tuple(f"{r}#org", rng.choice(orgs)))
        rels.append(
            rel.must_from_tuple(f"{r}#maintainer", f"{rng.choice(teams)}#member")
        )
        for u in rng.sample(users, 2):
            rels.append(rel.must_from_tuple(f"{r}#reader", u))
    return rels, users, teams, orgs, repos


def test_lookup_resources_rbac_matches_oracle():
    rels, users, teams, orgs, repos = rbac_world()
    _, engine, dsnap, oracle = world(RBAC, rels)
    for u in users[:8]:
        for perm in ("read", "admin"):
            assert_lookup_resources_match(engine, dsnap, oracle, "repo", perm, u)
    # userset subjects: which repos can team members read?
    for t in teams:
        assert_lookup_resources_match(
            engine, dsnap, oracle, "repo", "read", f"{t}#member"
        )
    # at least one user has results through the 2-hop arrow path
    any_results = any(
        lookup_resources_device(
            engine, dsnap, "repo", "read", "user", u.split(":")[1], "",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        for u in users
    )
    assert any_results


def test_lookup_subjects_rbac_matches_oracle():
    rels, users, teams, orgs, repos = rbac_world()
    _, engine, dsnap, oracle = world(RBAC, rels)
    for r in repos[:6]:
        rid = r.split(":")[1]
        for perm in ("read", "admin"):
            assert_lookup_subjects_match(
                engine, dsnap, oracle, "repo", rid, perm, "user"
            )
    # userset-subject lookups: which team usersets hold read?
    for r in repos[:4]:
        assert_lookup_subjects_match(
            engine, dsnap, oracle, "repo", r.split(":")[1], "read", "team#member"
        )


WILD = """
definition user {}
definition doc {
    relation viewer: user | user:*
    relation blocked: user
    permission view = viewer - blocked
}
"""


def test_lookup_with_wildcards_and_exclusion():
    rels = [
        rel.must_from_tuple("doc:pub#viewer", "user:*"),
        rel.must_from_tuple("doc:priv#viewer", "user:alice"),
        rel.must_from_tuple("doc:pub#blocked", "user:eve"),
        rel.must_from_tuple("doc:other#viewer", "user:bob"),
    ]
    _, engine, dsnap, oracle = world(WILD, rels)
    for u in ("alice", "bob", "eve", "stranger"):
        assert_lookup_resources_match(engine, dsnap, oracle, "doc", "view", f"user:{u}")
    for d in ("pub", "priv", "other"):
        assert_lookup_subjects_match(engine, dsnap, oracle, "doc", d, "view", "user")
    # stranger (not interned) gets pub via the wildcard
    got = lookup_resources_device(
        engine, dsnap, "doc", "view", "user", "stranger", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    assert got == ["pub"]
    # wildcard widening: every subject appearing anywhere is a candidate
    got = lookup_subjects_device(
        engine, dsnap, "doc", "pub", "view", "user", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    assert "bob" in got and "eve" not in got


NESTED = """
definition user {}
definition group {
    relation member: user | group#member
}
definition folder {
    relation parent: folder
    relation owner: user | group#member
    permission own = owner + parent->own
}
"""


def test_lookup_recursive_groups_and_folders():
    rels = [
        rel.must_from_tuple("group:root#member", "user:a"),
        rel.must_from_tuple("group:mid#member", "group:root#member"),
        rel.must_from_tuple("group:leaf#member", "group:mid#member"),
        rel.must_from_tuple("folder:top#owner", "group:leaf#member"),
        rel.must_from_tuple("folder:c1#parent", "folder:top"),
        rel.must_from_tuple("folder:c2#parent", "folder:c1"),
        rel.must_from_tuple("folder:c3#parent", "folder:c2"),
        rel.must_from_tuple("folder:solo#owner", "user:b"),
    ]
    _, engine, dsnap, oracle = world(NESTED, rels)
    for u in ("a", "b", "nobody"):
        assert_lookup_resources_match(
            engine, dsnap, oracle, "folder", "own", f"user:{u}"
        )
    for f in ("top", "c1", "c2", "c3", "solo"):
        assert_lookup_subjects_match(engine, dsnap, oracle, "folder", f, "own", "user")
    # deep arrow chain: a owns everything under top
    got = lookup_resources_device(
        engine, dsnap, "folder", "own", "user", "a", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    assert got == ["c1", "c2", "c3", "top"]


CAVEATED = """
caveat tier(t int, minimum int) { t >= minimum }
definition user {}
definition doc {
    relation viewer: user | user with tier
    permission view = viewer
}
"""


def test_lookup_caveats_conditional_omitted():
    import datetime as dt

    exp = dt.datetime.fromtimestamp((NOW - 5_000_000) / 1e6, tz=dt.timezone.utc)
    rels = [
        rel.must_from_tuple("doc:a#viewer", "user:u"),
        # stored context fully determines the caveat: definite on device
        rel.must_from_triple("doc:b", "viewer", "user:u").with_caveat(
            "tier", {"t": 9, "minimum": 5}
        ),
        rel.must_from_triple("doc:c", "viewer", "user:u").with_caveat(
            "tier", {"t": 1, "minimum": 5}
        ),
        # missing params -> conditional -> omitted from lookups
        rel.must_from_triple("doc:d", "viewer", "user:u").with_caveat("tier", {}),
        # expired edge grants nothing
        rel.must_from_tuple("doc:e#viewer", "user:u").with_expiration(exp),
    ]
    _, engine, dsnap, oracle = world(CAVEATED, rels)
    assert_lookup_resources_match(engine, dsnap, oracle, "doc", "view", "user:u")
    got = lookup_resources_device(
        engine, dsnap, "doc", "view", "user", "u", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    assert got == ["a", "b"]
    for d in ("a", "b", "c", "d", "e"):
        assert_lookup_subjects_match(engine, dsnap, oracle, "doc", d, "view", "user")


FUZZ_SCHEMA = """
caveat lim(v int, cap int) { v <= cap }
definition user {}
definition group {
    relation member: user | group#member | user:*
}
definition proj {
    relation parent: proj
    relation owner: user | group#member
    relation writer: user | group#member | user with lim
    relation banned: user
    permission write = (owner + writer + parent->write) - banned
    permission manage = owner & writer
}
"""


@pytest.mark.parametrize("seed", [1, 2, 5])
def test_lookup_fuzz_matches_oracle(seed):
    rng = random.Random(seed)
    users = [f"user:u{i}" for i in range(12)]
    groups = [f"group:g{i}" for i in range(5)]
    projs = [f"proj:p{i}" for i in range(8)]
    rels = []
    for g in groups:
        for u in rng.sample(users, 3):
            r = rel.must_from_tuple(f"{g}#member", u)
            rels.append(r)
        if rng.random() < 0.5:
            rels.append(
                rel.must_from_tuple(f"{g}#member", f"{rng.choice(groups)}#member")
            )
        if rng.random() < 0.3:
            rels.append(rel.must_from_tuple(f"{g}#member", "user:*"))
    for p in projs:
        if rng.random() < 0.6:
            rels.append(rel.must_from_tuple(f"{p}#parent", rng.choice(projs)))
        rels.append(rel.must_from_tuple(f"{p}#owner", rng.choice(users)))
        if rng.random() < 0.7:
            rels.append(
                rel.must_from_tuple(f"{p}#owner", f"{rng.choice(groups)}#member")
            )
        for u in rng.sample(users, 2):
            r = rel.must_from_tuple(f"{p}#writer", u)
            if rng.random() < 0.4:
                r = r.with_caveat(
                    "lim",
                    {"v": rng.randint(0, 9), "cap": 5} if rng.random() < 0.7 else {},
                )
            rels.append(r)
        if rng.random() < 0.4:
            rels.append(rel.must_from_tuple(f"{p}#banned", rng.choice(users)))
    _, engine, dsnap, oracle = world(FUZZ_SCHEMA, rels)
    for u in rng.sample(users, 5) + ["user:stranger"]:
        for perm in ("write", "manage"):
            assert_lookup_resources_match(engine, dsnap, oracle, "proj", perm, u)
    for p in rng.sample(projs, 4):
        pid = p.split(":")[1]
        for perm in ("write", "manage"):
            assert_lookup_subjects_match(
                engine, dsnap, oracle, "proj", pid, perm, "user"
            )
        assert_lookup_subjects_match(
            engine, dsnap, oracle, "proj", pid, "write", "group#member"
        )
    for g in groups:
        assert_lookup_resources_match(
            engine, dsnap, oracle, "proj", "write", f"{g}#member"
        )


def test_lookup_unknowns_and_empty():
    rels = [rel.must_from_tuple("doc:a#viewer", "user:u")]
    schema = """
    definition user {}
    definition doc { relation viewer: user  permission view = viewer }
    """
    _, engine, dsnap, oracle = world(schema, rels)
    # unknown permission / type / subject -> empty, no error
    assert lookup_resources_device(
        engine, dsnap, "doc", "nope", "user", "u", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    ) == []
    assert lookup_resources_device(
        engine, dsnap, "nope", "view", "user", "u", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    ) == []
    assert lookup_resources_device(
        engine, dsnap, "doc", "view", "user", "ghost", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    ) == []
    assert lookup_subjects_device(
        engine, dsnap, "doc", "ghost", "view", "user", "",
        now_us=NOW, oracle_factory=lambda: oracle,
    ) == []
    # unknown subject_relation slots
    assert lookup_resources_device(
        engine, dsnap, "doc", "view", "user", "u", "bogus",
        now_us=NOW, oracle_factory=lambda: oracle,
    ) == []


def test_client_lookup_uses_device_path():
    """The Client routes lookups through the device pipeline when the
    engine is available, with identical results to the oracle scans."""
    from gochugaru_tpu import consistency, new_tpu_evaluator
    from gochugaru_tpu.rel.txn import Txn
    from gochugaru_tpu.utils import background

    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, RBAC)
    rels, users, teams, orgs, repos = rbac_world(seed=9, n_users=10, n_repos=6)
    txn = Txn()
    for r in rels:
        txn.create(r)
    rev = c.write(ctx, txn)
    cs = consistency.at_least(rev)
    from gochugaru_tpu.utils.metrics import default as m

    base = m.counter("lookups.resources_device")
    got = sorted(c.lookup_resources(ctx, cs, "repo#read", users[0]))
    assert m.counter("lookups.resources_device") == base + 1
    snap = c.store.snapshot_for(cs)
    oracle = c._oracle_for(snap)
    stype, sid = users[0].split(":")
    assert got == sorted(oracle.lookup_resources("repo", "read", stype, sid, ""))
    rid = repos[0].split(":")[1]
    got = sorted(c.lookup_subjects(ctx, cs, repos[0], "read", "user"))
    assert got == sorted(oracle.lookup_subjects("repo", rid, "read", "user", ""))


def test_lookup_index_advances_through_lsm_chain(monkeypatch):
    """A chained (deferred) LSM snapshot whose BASE carries a lookup
    index must answer lookups by ADVANCING that index with the chain's
    accumulated overlay/tombstones — never by a full rebuild
    (engine/lookup.py lookup_index chain-advance; VERDICT r04 item 4)."""
    from gochugaru_tpu.engine import lookup as lookup_mod
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.store.delta import apply_delta

    rels, users, teams, orgs, repos = rbac_world()
    cs, engine, dsnap, oracle = world(RBAC, rels)
    snap = dsnap.snapshot
    # this test pins the HOST walker's index-advance machinery — the
    # serving path for layouts without the reverse-CSR index — so the
    # device frontier path (which never touches the transposed index)
    # is disabled for it
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_rev_index=False))
    dsnap = engine.prepare(snap)
    # plant the base index the way the prepare-time prewarm does
    lookup_mod.lookup_index(snap, mark_used=False)
    assert getattr(snap, "_lookup_index", None) is not None
    assert not getattr(snap, "_lookup_used", False)

    # chain several deferred revisions: adds, an upsert-replace, deletes
    # (incl. deleting a row added earlier in the chain)
    cur, cur_rels = snap, list(rels)
    deltas = [
        ([rel.must_from_tuple("repo:r0#reader", "user:u19")], []),
        ([rel.must_from_tuple("repo:r1#reader", "user:u18")],
         [rel.must_from_tuple("repo:r0#reader", "user:u19")]),
        ([rel.must_from_tuple("repo:r2#reader", "user:u17")],
         [cur_rels[-1]]),
    ]
    revision = 2
    for adds, dels in deltas:
        cur = apply_delta(cur, revision, adds, dels,
                          interner=snap.interner, defer=True)
        for d in dels:
            cur_rels = [r for r in cur_rels if str(r) != str(d)]
        cur_rels += adds
        revision += 1
    assert getattr(cur, "_lookup_index", None) is None

    # any full rebuild now is the bug this test pins
    def _no_rebuild(s):
        raise AssertionError("full lookup-index rebuild on a chained snap")

    monkeypatch.setattr(lookup_mod, "_build_lookup_index", _no_rebuild)

    ds2 = engine.prepare(cur, prev=dsnap)
    oracle2 = Oracle(cs, cur_rels, {}, now_us=NOW)
    for u in ("user:u19", "user:u18", "user:u17", "user:u0"):
        got = lookup_resources_device(
            engine, ds2, "repo", "read", "user", u.split(":")[1], "",
            now_us=NOW, oracle_factory=lambda: oracle2,
        )
        want = sorted(oracle2.lookup_resources("repo", "read", "user",
                                               u.split(":")[1], ""))
        assert got == want, f"{u}: {got} != {want}"
    # the advanced index landed on the tip snapshot
    assert getattr(cur, "_lookup_index", None) is not None


def test_stash_redeems_across_chain_hops(monkeypatch):
    """A mid-chain materialization while the index is still UNUSED
    stashes the O(D) advance inputs; when a NEW chain hops off that
    materialized tip and a lookup finally happens, the lineage redeems
    (base stash first, then the new chain's carry) — never a full
    rebuild (store/delta.py _materialize_locked carry block)."""
    from gochugaru_tpu.engine import lookup as lookup_mod
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.store.delta import apply_delta

    rels, users, teams, orgs, repos = rbac_world()
    cs, engine, dsnap, oracle = world(RBAC, rels)
    snap = dsnap.snapshot
    # walker-forced engine: this test pins the stash-redeem machinery of
    # the transposed host index (see the chain-advance test above)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_rev_index=False))
    dsnap = engine.prepare(snap)
    lookup_mod.lookup_index(snap, mark_used=False)  # prewarm-style
    cur_rels = list(rels)

    # chain 1: two deferred revisions, then force a materialization
    # WITHOUT any lookup (an export does this)
    adds1 = [rel.must_from_tuple("repo:r0#reader", "user:u19")]
    r2 = apply_delta(snap, 2, adds1, [], interner=snap.interner, defer=True)
    adds2 = [rel.must_from_tuple("repo:r1#reader", "user:u18")]
    r3 = apply_delta(r2, 3, adds2, [], interner=snap.interner, defer=True)
    cur_rels += adds1 + adds2
    _ = r3.e_rel  # lazy materialize; index unused -> stash, not advance
    assert getattr(r3, "_lookup_index", None) is None
    assert r3.__dict__.get("_lookup_chain_stash") is not None

    # chain 2 hops off the materialized, stash-carrying tip
    adds3 = [rel.must_from_tuple("repo:r2#reader", "user:u17")]
    r4 = apply_delta(r3, 4, adds3, [], interner=snap.interner, defer=True)
    cur_rels += adds3

    def _no_rebuild(s):
        raise AssertionError("full rebuild despite stash lineage")

    monkeypatch.setattr(lookup_mod, "_build_lookup_index", _no_rebuild)
    oracle2 = Oracle(cs, cur_rels, {}, now_us=NOW)
    ds4 = engine.prepare(r4, prev=dsnap)
    for u in ("user:u19", "user:u18", "user:u17"):
        got = lookup_resources_device(
            engine, ds4, "repo", "read", "user", u.split(":")[1], "",
            now_us=NOW, oracle_factory=lambda: oracle2,
        )
        want = sorted(oracle2.lookup_resources(
            "repo", "read", "user", u.split(":")[1], ""))
        assert got == want, f"{u}: {got} != {want}"

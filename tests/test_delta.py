"""Incremental (delta) materialization == full rebuild, by construction.

BASELINE config 5's Watch-driven re-index: each new revision advances the
previous snapshot via store/delta.py's sorted merge.  These tests drive a
randomized update stream through the Store twice — once forcing full
rebuilds, once through the delta path — and require the primary and
derived columns to be bit-identical (contexts are index-mapped, so e_ctx
is compared through the decoded relationships instead)."""

import dataclasses
import datetime as dt
import random

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.rel.filter import Filter, PreconditionedFilter
from gochugaru_tpu.rel.txn import Txn
from gochugaru_tpu.store.delta import apply_delta
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.store.store import Store

SCHEMA = """
caveat ip_ok(allowed int) { allowed == 1 }
definition user {}
definition team { relation member: user | team#member }
definition doc {
    relation owner: team
    relation reader: user | user with ip_ok | user:* | team#member
    permission view = reader + owner->member
}
"""

COLS = [
    "e_rel", "e_res", "e_subj", "e_srel1", "e_caveat", "e_exp", "e_exp_us",
    "us_rel", "us_res", "us_subj", "us_srel", "us_caveat", "us_exp",
    "ms_subj", "ms_res", "ms_rel", "ms_caveat", "ms_exp",
    "mp_subj", "mp_srel", "mp_res", "mp_rel", "mp_caveat", "mp_exp",
    "ar_rel", "ar_res", "ar_child", "ar_caveat", "ar_exp",
]


def _assert_snapshots_equal(got, want):
    for c in COLS:
        np.testing.assert_array_equal(
            getattr(got, c), getattr(want, c), err_msg=f"column {c}"
        )
    got_rels = sorted(str(got.decode_edge(i)) for i in range(got.num_edges))
    want_rels = sorted(str(want.decode_edge(i)) for i in range(want.num_edges))
    assert got_rels == want_rels


def _random_rel(rng, with_caveat=True):
    r = rel.must_from_tuple(
        f"doc:d{rng.randrange(20)}#{rng.choice(['owner', 'reader'])}",
        rng.choice(
            [
                f"user:u{rng.randrange(30)}",
                f"team:t{rng.randrange(5)}#member",
                "user:*",
            ]
        ),
    )
    if r.resource_relation == "owner":
        r = rel.must_from_tuple(
            f"doc:{r.resource_id}#owner", f"team:t{rng.randrange(5)}"
        )
    elif (
        with_caveat
        and r.subject_type == "user"
        and not r.subject_relation
        and r.subject_id != "*"
        and rng.random() < 0.4
    ):
        r = r.with_caveat("ip_ok", {"allowed": rng.randrange(2)})
    if rng.random() < 0.2:
        r = r.with_expiration(
            dt.datetime(2030, 1, 1, tzinfo=dt.timezone.utc)
            + dt.timedelta(days=rng.randrange(100))
        )
    return r


def test_apply_delta_matches_full_build():
    rng = random.Random(3)
    store = Store()
    store.write_schema(SCHEMA)
    base_rels = [_random_rel(rng) for _ in range(60)]
    txn = Txn()
    seen = set()
    for r in base_rels:
        if r.key() not in seen:
            txn.touch(r)
            seen.add(r.key())
    store.write(txn)
    full = consistency.full()
    base = store.snapshot_for(full)  # first materialization: full build

    # a batch of touches (some replacing), creates, and deletes
    live = store.live_relationships()
    adds = [_random_rel(rng) for _ in range(25)]
    dels = rng.sample(live, 10)
    add_keys = {r.key() for r in adds}
    dels = [r for r in dels if r.key() not in add_keys]
    t2 = Txn()
    done = set()
    for r in adds:
        if r.key() not in done:
            t2.touch(r)
            done.add(r.key())
    for r in dels:
        t2.delete(r)
    store.write(t2)

    got = store.snapshot_for(full)
    assert got.revision > base.revision
    want = build_snapshot(
        got.revision,
        store.compiled_schema,
        store.interner,
        store.live_relationships(),
        epoch_us=got.epoch_us,
    )
    _assert_snapshots_equal(got, want)


def test_delta_stream_many_revisions():
    rng = random.Random(11)
    store = Store()
    store.write_schema(SCHEMA)
    full = consistency.full()
    for step in range(12):
        t = Txn()
        done = set()
        for _ in range(rng.randrange(1, 12)):
            r = _random_rel(rng)
            if r.key() in done:
                continue
            done.add(r.key())
            if rng.random() < 0.25:
                t.delete(r)
            else:
                t.touch(r)
        store.write(t)
        if rng.random() < 0.3:
            store.delete_by_filter(
                PreconditionedFilter(Filter("doc", f"d{rng.randrange(20)}", ""))
            )
        got = store.snapshot_for(full)
        want = build_snapshot(
            got.revision,
            store.compiled_schema,
            store.interner,
            store.live_relationships(),
            epoch_us=got.epoch_us,
        )
        _assert_snapshots_equal(got, want)


def test_delta_contexts_do_not_accumulate():
    """Touching the same caveated tuple revision after revision must keep
    the contexts list bounded: identical context dicts are deduplicated at
    lowering, and once the list outgrows the compaction floor the dead
    fraction is renumbered away (flagged so the device delta-prepare does
    a full rebuild rather than reading stale ctx ids)."""
    store = Store()
    store.write_schema(SCHEMA)
    full = consistency.full()
    r = rel.must_from_tuple("doc:d0#reader", "user:u0")
    for i in range(30):
        store.write(Txn().touch(r.with_caveat("ip_ok", {"allowed": i % 2})))
        snap = store.snapshot_for(full)
    assert snap.num_edges == 1
    # value-dedup: the 30 touches alternate between exactly two dicts
    assert len(snap.contexts) <= 2
    assert snap.decode_edge(0).caveat_context == {"allowed": 1}


def test_delta_contexts_compact_past_floor(monkeypatch):
    """Past the compaction floor, dead contexts are renumbered away and
    the delta is flagged contexts_renumbered (the device delta-prepare
    must not trust its baked-in ctx ids afterwards)."""
    from gochugaru_tpu.store import delta as delta_mod

    monkeypatch.setattr(delta_mod, "CTX_COMPACT_MIN", 4)
    store = Store()
    store.write_schema(SCHEMA)
    full = consistency.full()
    r = rel.must_from_tuple("doc:d0#reader", "user:u0")
    renumbered_ever = False
    for i in range(12):
        store.write(Txn().touch(r.with_caveat("ip_ok", {"allowed": i})))
        snap = store.snapshot_for(full)
        di = getattr(snap, "delta_info", None)
        if di is not None and di.contexts_renumbered:
            renumbered_ever = True
    assert snap.num_edges == 1
    assert len(snap.contexts) <= 5
    assert renumbered_ever
    assert snap.decode_edge(0).caveat_context == {"allowed": 11}


def test_delta_checks_agree_with_oracle():
    """End-to-end: checks evaluated on a delta-materialized snapshot match
    the host oracle built from the live set."""
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.oracle import Oracle, T

    rng = random.Random(7)
    store = Store()
    store.write_schema(SCHEMA)
    full = consistency.full()
    t = Txn()
    done = set()
    for _ in range(40):
        r = _random_rel(rng, with_caveat=False)
        if r.key() not in done:
            t.touch(r)
            done.add(r.key())
    store.write(t)
    store.snapshot_for(full)
    t2 = Txn()
    done2 = set()
    for _ in range(15):
        r = _random_rel(rng, with_caveat=False)
        if r.key() not in done2:
            t2.touch(r)
            done2.add(r.key())
    store.write(t2)
    snap = store.snapshot_for(full)

    now_us = 1_700_000_000_000_000
    from gochugaru_tpu.engine.plan import EngineConfig

    cfg = EngineConfig.for_schema(snap.compiled)
    # a doc may own several teams; widen the arrow subgraph past the
    # schema-depth default so no query needs the host-fallback path here
    cfg = dataclasses.replace(cfg, subgraph_nodes=16, arrow_fanout=8)
    engine = DeviceEngine(snap.compiled, cfg)
    dsnap = engine.prepare(snap)
    oracle = Oracle(snap.compiled, store.live_relationships(), now_us=now_us)
    checks = [
        rel.must_from_triple(f"doc:d{rng.randrange(20)}", "view", f"user:u{rng.randrange(30)}")
        for _ in range(48)
    ]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=now_us)
    assert not ovf.any()
    for i, q in enumerate(checks):
        assert bool(d[i]) == (oracle.check_relationship(q) == T), str(q)


def test_lookup_index_carried_across_delta():
    """round-2 Weak #4: apply_delta must advance the previous revision's
    LookupIndex incrementally, never forcing a full O(E log E) rebuild,
    and the carried index must equal a from-scratch build bit for bit."""
    from gochugaru_tpu.engine.lookup import lookup_index
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner

    rng = random.Random(9)
    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rels = list({ _random_rel(rng).key(): _random_rel(rng) for _ in range(120) }.values())
    prev = build_snapshot(1, cs, interner, rels, epoch_us=1_700_000_000_000_000)
    lookup_index(prev)  # force the index on the base revision

    adds = [
        rel.must_from_tuple("doc:dX#reader", "user:zed"),
        rels[0],  # upsert of an existing identity
        rel.must_from_tuple("doc:d0#owner", "team:t9"),  # arrow row
    ]
    deletes = [rels[3], rels[7]]
    adds = [a for a in adds if a.key() not in {d.key() for d in deletes}]
    nxt = apply_delta(prev, 2, adds, deletes, interner=interner)

    carried = getattr(nxt, "_lookup_index", None)
    assert carried is not None, "delta did not carry the lookup index"

    # equality with a from-scratch build on the same snapshot
    del nxt._lookup_index
    fresh = lookup_index(nxt)
    for field in ("rs_key", "rs_res", "rs_rel", "ra_child", "ra_res",
                  "er_res", "er_rel", "er_subj", "er_srel1",
                  "e_relres", "ar_relres"):
        np.testing.assert_array_equal(
            getattr(carried, field), getattr(fresh, field), err_msg=field
        )

    # chained delta: the carried index advances again, staying consistent
    nxt._lookup_index = carried
    adds2 = [rel.must_from_tuple("doc:dY#reader", "user:amy")]
    deletes2 = [rels[11]]
    n2 = apply_delta(nxt, 3, adds2, deletes2, interner=interner)
    carried2 = getattr(n2, "_lookup_index", None)
    assert carried2 is not None
    del n2._lookup_index
    fresh2 = lookup_index(n2)
    np.testing.assert_array_equal(carried2.rs_key, fresh2.rs_key)
    np.testing.assert_array_equal(carried2.rs_res, fresh2.rs_res)
    np.testing.assert_array_equal(carried2.ra_child, fresh2.ra_child)
    np.testing.assert_array_equal(carried2.er_res, fresh2.er_res)


def test_delta_interning_new_type_grows_perm_table():
    """Review regression: a delta adding the first node of a schema type
    must not leave a stale undersized perm_table on the carried index."""
    from gochugaru_tpu.engine.lookup import lookup_index
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    base = [rel.must_from_tuple("doc:d0#reader", "user:u0")]
    prev = build_snapshot(1, cs, interner, base, epoch_us=1_700_000_000_000_000)
    lookup_index(prev)
    # first team node ever: grows the interner's type space
    adds = [rel.must_from_tuple("doc:d0#owner", "team:t0")]
    nxt = apply_delta(prev, 2, adds, [], interner=interner)
    carried = nxt._lookup_index
    assert carried.perm_table.shape[0] >= nxt.interner.num_types
    del nxt._lookup_index
    fresh = lookup_index(nxt)
    np.testing.assert_array_equal(carried.perm_table, fresh.perm_table)

"""Feed-partition parity (the multihost O(E/M) host-RSS path): the
stacked tables ``engine/partition.py partition_feed`` prepares from a
RAW bucket-partitioned store feed must be BITWISE-identical — array for
array, plus FlatMeta equality — to the pre-PR build-full-then-stack
reference (``build_flat_arrays_sharded`` over the fully-sorted
snapshot) at the same feed, on randomized worlds exercising usersets,
caveats with contexts, expirations, wildcards, and closure overflow.
The reference passes ``plan=None``: the feed path declines the
permission fold / rc flattening (their inputs are the full per-edge
views), so the walked kernel evaluates — parity is against the same
contract.

Owned-subset runs must produce exactly the owned slices of the full
arrays, and the bucket-filtered Snapshot must hold only the owned rows
of each O(E) view while keeping the membership subgraph whole."""

import numpy as np
import pytest

from test_prepare_parity import NOW, SCHEMA, _random_world

from gochugaru_tpu.engine.flat import build_flat_arrays_sharded
from gochugaru_tpu.engine.partition import ShardSlices, partition_feed
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import (
    build_snapshot_from_columns,
    relationships_to_raw_columns,
)


def _world(seed: int, n_edges: int):
    rels = _random_world(seed, n_edges)
    cs = compile_schema(parse_schema(SCHEMA))
    itn = Interner()
    raw, contexts = relationships_to_raw_columns(cs, itn, rels)
    return cs, itn, raw, contexts


def _reference(cs, itn, raw, contexts, M):
    snap = build_snapshot_from_columns(
        1, cs, itn, contexts=contexts, epoch_us=NOW,
        **{k: v.copy() for k, v in raw.items()},
    )
    cfg = EngineConfig.for_schema(cs)
    # the reference is the PRE-PR build-full-then-stack path: with the
    # partition-first default both sides would share engine/partition.py
    # and a shared bug would cancel out of the parity comparison.
    # flat_rev_index=False: the feed declines the reverse lookup index
    # (rv ownership is keyed by the subject hash, not the primary
    # bucket the owned feed rows are keyed by), so the reference
    # builds without it too
    legacy = EngineConfig.for_schema(
        cs, flat_partition_build=False, flat_rev_index=False
    )
    built = build_flat_arrays_sharded(snap, legacy, M, plan=None)
    assert built is not None
    arrays, meta, _f, _c = built
    return snap, arrays, meta, cfg


def _as_full(v):
    return v.to_full() if isinstance(v, ShardSlices) else v


@pytest.mark.parametrize("seed,M", [(7, 2), (23, 4)])
def test_feed_partition_bitwise_parity(seed, M):
    cs, itn, raw, contexts = _world(seed, 60_000)
    ref_snap, ref_arrays, ref_meta, cfg = _reference(cs, itn, raw, contexts, M)

    part = partition_feed(
        1, cs, itn, {k: v.copy() for k, v in raw.items()}, cfg, M,
        contexts=contexts, epoch_us=NOW,
    )
    assert part is not None
    assert set(part.arrays) == set(ref_arrays), (
        set(part.arrays) ^ set(ref_arrays)
    )
    for k in sorted(ref_arrays):
        got = _as_full(part.arrays[k])
        assert got.shape == ref_arrays[k].shape, k
        assert np.array_equal(got, ref_arrays[k]), f"table {k} differs"
    assert part.meta == ref_meta, "FlatMeta differs"

    # full ownership reproduces the full per-edge views too
    assert np.array_equal(np.sort(part.snapshot.e_res), np.sort(ref_snap.e_res))
    assert part.snapshot.us_rel.shape == ref_snap.us_rel.shape


def test_feed_partition_owned_subset_slices():
    M = 4
    cs, itn, raw, contexts = _world(3, 40_000)
    _snap, ref_arrays, ref_meta, cfg = _reference(cs, itn, raw, contexts, M)

    owned = (1, 3)
    part = partition_feed(
        1, cs, itn, {k: v.copy() for k, v in raw.items()}, cfg, M,
        owned=owned, contexts=contexts, epoch_us=NOW,
    )
    assert part is not None
    assert part.meta == ref_meta  # geometry is global: identical everywhere
    for k, v in part.arrays.items():
        if not isinstance(v, ShardSlices):
            # globally-small tables build whole on every process
            assert np.array_equal(v, ref_arrays[k]), k
            continue
        assert sorted(v.blocks) == list(owned), k
        for s in owned:
            ref_blk = ref_arrays[k][s * v.per : (s + 1) * v.per]
            assert np.array_equal(v.blocks[s], ref_blk), (k, s)

    # the bucket-filtered snapshot holds only the owned partitions of the
    # O(E) views, and the membership subgraph whole
    full = partition_feed(
        1, cs, itn, {k: v.copy() for k, v in raw.items()}, cfg, M,
        contexts=contexts, epoch_us=NOW,
    )
    assert part.snapshot.e_rel.shape[0] < full.snapshot.e_rel.shape[0]
    assert part.snapshot.us_rel.shape[0] < full.snapshot.us_rel.shape[0]
    assert np.array_equal(full.snapshot.ms_subj, part.snapshot.ms_subj)
    assert np.array_equal(full.snapshot.mp_subj, part.snapshot.mp_subj)
    assert part.snapshot.partition_owned == owned


def test_prepare_partitioned_dispatch_matches_oracle():
    """End-to-end: a FeedPartition through ShardedEngine.prepare_
    partitioned (ShardSlices → jax.make_array_from_callback) must serve
    real sharded check dispatches that agree with the host oracle."""
    import random

    from gochugaru_tpu import rel as relmod
    from gochugaru_tpu.caveats import compile_cel
    from gochugaru_tpu.engine.oracle import Oracle, T
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rels = _random_world(9, 4_000)
    cs = compile_schema(parse_schema(SCHEMA))
    itn = Interner()
    raw, contexts = relationships_to_raw_columns(cs, itn, rels)
    cfg = EngineConfig.for_schema(cs)
    part = partition_feed(
        1, cs, itn, raw, cfg, 4, contexts=contexts, epoch_us=NOW
    )
    assert part is not None
    eng = ShardedEngine(cs, make_mesh(2, 4), cfg)
    dsnap = eng.prepare_partitioned(part)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded

    rng = random.Random(1)
    checks = [
        relmod.must_from_triple(
            f"doc:d{rng.randrange(500)}",
            rng.choice(["view", "edit"]),
            f"user:u{rng.randrange(250)}",
        )
        for _ in range(64)
    ]
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    d, p, ovf = eng.check_batch(dsnap, checks, now_us=NOW)
    verified = 0
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        if ovf[i]:
            continue
        if d[i]:
            # definite device grant must be a true grant
            assert want == T, q
            verified += 1
        elif not p[i]:
            # definite device no: the oracle must not grant
            assert want != T, q
            verified += 1
        # else possible-only (caveats without query context, permission-
        # valued usersets): the client resolves these on the host
    assert verified >= len(checks) // 2

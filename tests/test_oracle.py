"""Oracle evaluator tests: the full check-semantics matrix the device
engine will be differentially tested against."""

import datetime as dt

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.oracle import F, T, U, Oracle
from gochugaru_tpu.schema import compile_schema, parse_schema


def make_oracle(schema_text, triples, caveats=None, now_us=None):
    cs = compile_schema(parse_schema(schema_text))
    programs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    rels = [t if isinstance(t, rel.Relationship) else rel.must_from_tuple(*t) for t in triples]
    return Oracle(cs, rels, programs, now_us=now_us)


EXAMPLE = """
definition user {}
definition document {
    relation writer: user
    relation reader: user
    permission edit = writer
    permission view = reader + edit
}
"""


def test_reference_check_matrix():
    # Mirrors TestClient_Check fixtures (client/client_test.go:141-216)
    o = make_oracle(
        EXAMPLE,
        [
            ("document:check_test1#writer", "user:alice"),
            ("document:check_test1#reader", "user:bob"),
            ("document:check_test2#writer", "user:charlie"),
        ],
    )
    assert o.check("document", "check_test1", "edit", "user", "alice") == T
    assert o.check("document", "check_test1", "edit", "user", "bob") == F
    assert o.check("document", "check_test1", "view", "user", "bob") == T
    assert o.check("document", "check_test2", "edit", "user", "charlie") == T
    assert o.check("document", "check_test2", "view", "user", "alice") == F
    # transitive: writer ⇒ edit ⇒ view
    assert o.check("document", "check_test1", "view", "user", "alice") == T
    # nonexistent resource → F, not an error
    assert o.check("document", "nonexistent", "edit", "user", "alice") == F
    # nonexistent permission → F
    assert o.check("document", "check_test1", "ghost", "user", "alice") == F


NESTED_GROUPS = """
definition user {}
definition group {
    relation member: user | group#member
}
definition document {
    relation viewer: group#member
    permission view = viewer
}
"""


def test_nested_groups_recursion():
    o = make_oracle(
        NESTED_GROUPS,
        [
            ("group:leaf#member", "user:amy"),
            ("group:mid#member", "group:leaf#member"),
            ("group:top#member", "group:mid#member"),
            ("document:d#viewer", "group:top#member"),
        ],
    )
    assert o.check("document", "d", "view", "user", "amy") == T
    assert o.check("document", "d", "view", "user", "bob") == F
    # membership at each level
    assert o.check("group", "top", "member", "user", "amy") == T
    assert o.check("group", "leaf", "member", "user", "amy") == T


def test_group_cycle_terminates():
    o = make_oracle(
        NESTED_GROUPS,
        [
            ("group:a#member", "group:b#member"),
            ("group:b#member", "group:a#member"),
            ("document:d#viewer", "group:a#member"),
        ],
    )
    assert o.check("document", "d", "view", "user", "amy") == F


def test_userset_self_identity():
    o = make_oracle(NESTED_GROUPS, [("document:d#viewer", "group:g#member")])
    # a userset is a member of itself
    assert o.check("group", "g", "member", "group", "g", "member") == T
    assert o.check("document", "d", "view", "group", "g", "member") == T


FOLDERS = """
definition user {}
definition folder {
    relation parent: folder
    relation owner: user
    permission view = owner + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user
    relation banned: user
    permission view = (viewer + folder->view) - banned
}
"""


def test_arrow_recursion_deep_chain():
    triples = [("folder:f0#owner", "user:root")]
    for i in range(1, 6):
        triples.append((f"folder:f{i}#parent", f"folder:f{i-1}"))
    triples.append(("document:d#folder", "folder:f5"))
    o = make_oracle(FOLDERS, triples)
    # 5-hop recursive arrow chain (BASELINE config 3 shape)
    assert o.check("document", "d", "view", "user", "root") == T
    assert o.check("folder", "f5", "view", "user", "root") == T
    assert o.check("document", "d", "view", "user", "other") == F


def test_exclusion():
    o = make_oracle(
        FOLDERS,
        [
            ("document:d#viewer", "user:amy"),
            ("document:d#viewer", "user:bob"),
            ("document:d#banned", "user:bob"),
        ],
    )
    assert o.check("document", "d", "view", "user", "amy") == T
    assert o.check("document", "d", "view", "user", "bob") == F


def test_intersection():
    o = make_oracle(
        """
        definition user {}
        definition vault {
            relation manager: user
            relation auditor: user
            permission open = manager & auditor
        }
        """,
        [
            ("vault:v#manager", "user:amy"),
            ("vault:v#auditor", "user:amy"),
            ("vault:v#manager", "user:bob"),
        ],
    )
    assert o.check("vault", "v", "open", "user", "amy") == T
    assert o.check("vault", "v", "open", "user", "bob") == F


def test_wildcard():
    o = make_oracle(
        """
        definition user {}
        definition doc {
            relation viewer: user | user:*
            permission view = viewer
        }
        """,
        [("doc:public#viewer", "user:*"), ("doc:private#viewer", "user:amy")],
    )
    assert o.check("doc", "public", "view", "user", "anyone") == T
    assert o.check("doc", "private", "view", "user", "anyone") == F
    # wildcard does not satisfy userset-subject queries
    assert o.check("doc", "public", "view", "group", "g", "member") == F


CAVEATED = """
caveat on_weekday(day string) {
    day != "saturday" && day != "sunday"
}
definition user {}
definition doc {
    relation viewer: user with on_weekday
    permission view = viewer
}
"""


def test_caveats_tri_state():
    r = rel.must_from_triple("doc:d", "viewer", "user:amy").with_caveat("on_weekday", {})
    o = make_oracle(CAVEATED, [r])
    assert o.check("doc", "d", "view", "user", "amy", context={"day": "monday"}) == T
    assert o.check("doc", "d", "view", "user", "amy", context={"day": "sunday"}) == F
    # missing context → conditional
    assert o.check("doc", "d", "view", "user", "amy") == U


def test_caveat_stored_context_wins():
    r = rel.must_from_triple("doc:d", "viewer", "user:amy").with_caveat(
        "on_weekday", {"day": "monday"}
    )
    o = make_oracle(CAVEATED, [r])
    # stored day=monday beats query day=sunday
    assert o.check("doc", "d", "view", "user", "amy", context={"day": "sunday"}) == T


def test_conditional_exclusion_stays_conditional():
    # banned-with-caveat: if the ban is conditional, the grant is conditional
    o = make_oracle(
        """
        caveat c(flag bool) { flag }
        definition user {}
        definition doc {
            relation viewer: user
            relation banned: user with c
            permission view = viewer - banned
        }
        """,
        [
            rel.must_from_triple("doc:d", "viewer", "user:amy"),
            rel.must_from_triple("doc:d", "banned", "user:amy").with_caveat("c", {}),
        ],
    )
    assert o.check("doc", "d", "view", "user", "amy") == U
    assert o.check("doc", "d", "view", "user", "amy", context={"flag": True}) == F
    assert o.check("doc", "d", "view", "user", "amy", context={"flag": False}) == T


def test_expiration():
    now = dt.datetime.now(dt.timezone.utc)
    now_us = int(now.timestamp() * 1_000_000)
    o = make_oracle(
        """
        use expiration
        definition user {}
        definition door { relation opener: user with expiration
                          permission open = opener }
        """,
        [
            rel.must_from_triple("door:front", "opener", "user:old").with_expiration(
                now - dt.timedelta(hours=1)
            ),
            rel.must_from_triple("door:front", "opener", "user:new").with_expiration(
                now + dt.timedelta(hours=1)
            ),
        ],
        now_us=now_us,
    )
    assert o.check("door", "front", "open", "user", "old") == F
    assert o.check("door", "front", "open", "user", "new") == T


def test_lookup_resources_and_subjects():
    o = make_oracle(
        EXAMPLE,
        [
            ("document:check_test1#writer", "user:alice"),
            ("document:check_test1#reader", "user:bob"),
            ("document:check_test1#writer", "user:charlie"),
            ("document:check_test2#writer", "user:charlie"),
        ],
    )
    # mirrors TestClient_LookupResources (client/client_test.go:107-139)
    assert list(o.lookup_resources("document", "writer", "user", "alice")) == ["check_test1"]
    assert list(o.lookup_resources("document", "writer", "user", "charlie")) == [
        "check_test1",
        "check_test2",
    ]
    assert list(o.lookup_subjects("document", "check_test1", "view", "user")) == [
        "alice",
        "bob",
        "charlie",
    ]


def test_arrow_ignores_userset_and_wildcard_subjects():
    o = make_oracle(
        """
        definition user {}
        definition team { relation member: user }
        definition folder { relation owner: user permission view = owner }
        definition doc {
            relation parent: folder | team#member
            permission view = parent->view
        }
        """,
        [
            ("doc:d#parent", "team:t#member"),
            ("folder:f#owner", "user:amy"),
        ],
    )
    # the userset parent edge is skipped by the arrow; no folder edge exists
    assert o.check("doc", "d", "view", "user", "amy") == F

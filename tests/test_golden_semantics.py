"""Golden semantics corpus: the oracle validated against SpiceDB's
DOCUMENTED behavior, not against itself (VERDICT r04 item 7).

Every expectation below is hand-derived from the SpiceDB public
documentation and schema-language reference — NOT from running this
repo's code — so the differential-testing ground truth
(engine/oracle.py) is itself pinned.  Sources per group (authzed.com
docs paths, stable topics):

- [UNION/INTER/EXCL]  "Schema Language > Permissions": ``+`` union,
  ``&`` intersection, ``-`` exclusion; permissionship combines as
  HAS > CONDITIONAL > NO_PERMISSION (Kleene: OR=max, AND=min,
  NOT flips HAS/NO and keeps CONDITIONAL).
- [WILDCARD]  "Schema Language > Wildcards": ``user:*`` grants every
  individual user; wildcards apply ONLY to direct subjects — a userset
  subject (team#member) is not matched by ``user:*``, and wildcards do
  not expand transitively through usersets used as subjects elsewhere.
- [USERSET]  "Subject Relations": ``team:eng#member`` as a subject
  grants all members of that relation, transitively; a userset is
  always a member of itself.
- [ARROW]  "Schema Language > Arrows": ``parent->view`` evaluates
  ``view`` on every object related via ``parent`` (direct subjects
  only — arrows do not walk usersets or wildcards on the tupleset).
- [CAVEAT]  "Caveats": stored context is merged over request context
  with STORED winning on conflicts; a caveat that evaluates true →
  HAS, false → NO, missing parameters → CONDITIONAL (the gRPC
  CheckPermission result CONDITIONAL, collapsed to false by clients
  that only ask for a bool — reference collapse at
  /root/reference/client/client.go:277).
- [EXPIRE]  "Expiring Relationships": an expired relationship grants
  nothing (as if deleted); expiration composes with every operator.
- [MISSING]  Checks on nonexistent resources, relations, or subjects
  return NO_PERMISSION, never an error (reference test
  /root/reference/client/client_test.go:209-215).

Each case is asserted against the oracle tri-state, and the whole
corpus is ALSO dispatched through the device engine, whose (definite,
possible) planes must bracket the golden value — so both evaluators are
grounded in the documented semantics.
"""

import datetime as dt

import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import F, Oracle, T, U
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000

SCHEMA = """
caveat ip_allowlist(allowed string, ip string) { allowed == ip }
caveat min_tier(tier int, need int) { tier >= need }

definition user {}

definition team {
    relation member: user | team#member
}

definition org {
    relation admin: user
    relation banned: user | user:*
}

definition folder {
    relation parent: folder
    relation owner: user
    permission view = owner + parent->view
}

definition doc {
    relation org: org
    relation folder: folder
    relation reader: user | user:* | team#member | user with ip_allowlist
    relation editor: user | user with min_tier
    relation banned: user | user:* | team#member
    relation auditor: user
    permission edit = editor
    permission read = (reader - banned) + folder->view
    permission audit = reader & auditor
    permission admin_read = read & org->admin
    permission never = reader - reader
}
"""


def _expire(r, secs):
    return r.with_expiration(
        dt.datetime.fromtimestamp(NOW / 1e6 + secs, tz=dt.timezone.utc)
    )


def _world():
    R = rel.must_from_tuple
    rels = [
        # teams (nested)
        R("team:eng#member", "user:alice"),
        R("team:eng#member", "team:core#member"),
        R("team:core#member", "user:dave"),
        # org
        R("org:acme#admin", "user:alice"),
        R("org:acme#banned", "user:mallory"),
        # folders (2-level chain)
        R("folder:root#owner", "user:root_owner"),
        R("folder:sub#parent", "folder:root"),
        # docs
        R("doc:plain#reader", "user:bob"),
        R("doc:plain#org", "org:acme"),
        R("doc:plain#auditor", "user:bob"),
        R("doc:plain#editor", "user:bob"),
        # wildcard reader doc
        R("doc:open#reader", "user:*"),
        R("doc:open#banned", "user:mallory"),
        # userset reader doc
        R("doc:team#reader", "team:eng#member"),
        R("doc:team#banned", "team:core#member"),
        # exclusion with wildcard ban
        R("doc:lockdown#reader", "user:bob"),
        R("doc:lockdown#banned", "user:*"),
        # arrow fallback
        R("doc:filed#folder", "folder:sub"),
        # caveated edges
        R("doc:gated#reader", "user:carol").with_caveat(
            "ip_allowlist", {"allowed": "10.0.0.1"}
        ),
        R("doc:gated#banned", "user:carol").with_caveat(
            "ip_allowlist", {"allowed": "10.9.9.9"}
        ),
        R("doc:tiered#editor", "user:erin").with_caveat(
            "min_tier", {"need": 3}
        ),
        # expiring edges
        _expire(R("doc:expiring#reader", "user:frank"), +3600),
        _expire(R("doc:expired#reader", "user:frank"), -3600),
        _expire(R("team:temp#member", "user:gina"), -60),
        R("doc:tmpteam#reader", "team:temp#member"),
        # caveated reader on an audit doc (intersection with conditional)
        R("doc:caudit#reader", "user:henk").with_caveat(
            "ip_allowlist", {"allowed": "10.1.1.1"}
        ),
        R("doc:caudit#auditor", "user:henk"),
    ]
    cs = compile_schema(parse_schema(SCHEMA))
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    return cs, rels, oracle


# (name, resource, permission, subject[, srel], context, golden)
CASES = [
    # -- [UNION] / plain relations --------------------------------------
    ("union: direct reader has read", "doc:plain", "read", "user:bob", "", None, T),
    ("union: permission via alias edit=editor", "doc:plain", "edit", "user:bob", "", None, T),
    ("union: stranger has nothing", "doc:plain", "read", "user:nobody", "", None, F),
    ("relation checked directly", "doc:plain", "reader", "user:bob", "", None, T),
    # -- [INTER] ---------------------------------------------------------
    ("inter: reader AND auditor", "doc:plain", "audit", "user:bob", "", None, T),
    ("inter: reader only is not audit", "doc:open", "audit", "user:bob", "", None, F),
    ("inter over arrow: read & org->admin", "doc:plain", "admin_read", "user:alice", "", None, F),
    # alice is org admin but NOT a reader of doc:plain → min(F, T) = F
    ("inter over arrow: admin but no read", "doc:plain", "admin_read", "user:bob", "", None, F),
    # bob reads but is not org admin → min(T, F) = F
    # -- [EXCL] ----------------------------------------------------------
    ("excl: reader minus absent ban", "doc:plain", "read", "user:bob", "", None, T),
    ("excl: self-exclusion is empty", "doc:plain", "never", "user:bob", "", None, F),
    ("excl: banned wildcard kills direct reader", "doc:lockdown", "read", "user:bob", "", None, F),
    ("excl: userset ban hits transitive member", "doc:team", "read", "user:dave", "", None, F),
    # dave ∈ core ⊆ eng → reader, but banned: team:core#member
    ("excl: member outside banned subset keeps read", "doc:team", "read", "user:alice", "", None, T),
    # alice ∈ eng directly, not ∈ core
    # -- [WILDCARD] ------------------------------------------------------
    ("wildcard grants any individual user", "doc:open", "read", "user:anyone", "", None, T),
    ("wildcard + direct ban excludes that user", "doc:open", "read", "user:mallory", "", None, F),
    ("wildcard does NOT match userset subjects", "doc:open", "read", "team:eng", "member", None, F),
    # team:eng#member as the CHECKED subject is a userset: user:* does not
    # cover it ([WILDCARD]: wildcards apply to individual subjects only)
    # -- [USERSET] -------------------------------------------------------
    ("userset: direct member reads", "doc:team", "read", "user:alice", "", None, T),
    ("userset: identity — the userset itself", "doc:team", "reader", "team:eng", "member", None, T),
    ("userset: nested member via team in team", "doc:team", "reader", "user:dave", "", None, T),
    ("userset: non-member excluded", "doc:team", "read", "user:bob", "", None, F),
    ("userset: sibling relation is not member", "doc:team", "read", "team:eng", "admin", None, F),
    # -- [ARROW] ---------------------------------------------------------
    ("arrow: folder owner reads filed doc via 2-level chain",
     "doc:filed", "read", "user:root_owner", "", None, T),
    ("arrow: recursive folder view up the chain",
     "folder:sub", "view", "user:root_owner", "", None, T),
    ("arrow: owner of nothing", "folder:sub", "view", "user:bob", "", None, F),
    ("arrow: doc without folder has no fallback", "doc:plain", "read", "user:root_owner", "", None, F),
    # -- [CAVEAT] --------------------------------------------------------
    ("caveat true -> HAS", "doc:gated", "reader", "user:carol", "",
     {"ip": "10.0.0.1"}, T),
    ("caveat false -> NO", "doc:gated", "reader", "user:carol", "",
     {"ip": "192.168.0.1"}, F),
    ("caveat missing context -> CONDITIONAL", "doc:gated", "reader", "user:carol", "",
     None, U),
    ("caveat: stored context wins over request context", "doc:gated", "reader",
     "user:carol", "", {"allowed": "192.168.0.1", "ip": "10.0.0.1"}, T),
    # stored {"allowed": "10.0.0.1"} overrides the request's allowed
    ("caveat int param true", "doc:tiered", "edit", "user:erin", "",
     {"tier": 5}, T),
    ("caveat int param false", "doc:tiered", "edit", "user:erin", "",
     {"tier": 1}, F),
    ("caveat int param missing -> CONDITIONAL", "doc:tiered", "edit",
     "user:erin", "", None, U),
    # -- [CAVEAT x EXCL] -------------------------------------------------
    ("excl: caveated reader minus caveated ban, both satisfied",
     "doc:gated", "read", "user:carol", "", {"ip": "10.0.0.1"}, T),
    # reader caveat true (allowed=10.0.0.1), ban caveat false
    # (ban stored allowed=10.9.9.9 != ip) → T - F = T
    ("excl: caveated reader minus caveated ban at the ban's ip",
     "doc:gated", "read", "user:carol", "", {"ip": "10.9.9.9"}, F),
    # reader caveat false → F regardless of ban
    ("excl: conditional reader minus conditional ban -> CONDITIONAL",
     "doc:gated", "read", "user:carol", "", None, U),
    # -- [CAVEAT x INTER] ------------------------------------------------
    ("inter: conditional reader & definite auditor -> CONDITIONAL",
     "doc:caudit", "audit", "user:henk", "", None, U),
    ("inter: satisfied reader & auditor -> HAS",
     "doc:caudit", "audit", "user:henk", "", {"ip": "10.1.1.1"}, T),
    ("inter: failed reader & auditor -> NO",
     "doc:caudit", "audit", "user:henk", "", {"ip": "10.2.2.2"}, F),
    # -- [EXPIRE] --------------------------------------------------------
    ("future expiry still grants", "doc:expiring", "read", "user:frank", "", None, T),
    ("past expiry grants nothing", "doc:expired", "read", "user:frank", "", None, F),
    ("expired membership breaks userset grant", "doc:tmpteam", "read", "user:gina", "", None, F),
    # -- [MISSING] -------------------------------------------------------
    ("nonexistent resource -> NO, not an error", "doc:ghost", "read", "user:bob", "", None, F),
    ("nonexistent subject -> NO", "doc:plain", "read", "user:ghost", "", None, F),
    ("nonexistent resource TYPE -> NO", "widget:x", "read", "user:bob", "", None, F),
    ("permission not on type -> NO", "doc:plain", "view", "user:bob", "", None, F),
    # view is a folder permission, not a doc permission
    ("relation not on subject type -> NO", "doc:team", "read", "org:acme", "member", None, F),
]


@pytest.fixture(scope="module")
def world():
    return _world()


@pytest.mark.parametrize(
    "name,res,perm,subj,srel,ctx,want",
    CASES,
    ids=[c[0] for c in CASES],
)
def test_golden_oracle(world, name, res, perm, subj, srel, ctx, want):
    _, _, oracle = world
    rtype, rid = res.split(":")
    stype, sid = subj.split(":")
    got = oracle.check(rtype, rid, perm, stype, sid, srel, context=ctx)
    assert got == want, f"{name}: oracle={got} golden={want}"


def test_golden_device_brackets(world):
    """The device engine's (definite, possible) planes must bracket every
    golden value: definite ⇒ golden == T, golden != F ⇒ possible (or the
    overflow flag routes the query to the host)."""
    cs, rels, _ = world
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    checks = []
    for (name, res, perm, subj, srel, ctx, want) in CASES:
        r = rel.Relationship(
            resource_type=res.split(":")[0], resource_id=res.split(":")[1],
            resource_relation=perm,
            subject_type=subj.split(":")[0], subject_id=subj.split(":")[1],
            subject_relation=srel,
            caveat_context=dict(ctx) if ctx else {},
        )
        checks.append(r)
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, (name, *_, want) in enumerate(CASES):
        assert not d[i] or want == T, f"{name}: device definite but golden {want}"
        if not ovf[i]:
            assert p[i] or want == F, f"{name}: device impossible but golden {want}"


def test_corpus_size():
    assert len(CASES) >= 40, len(CASES)

"""Bulk-Check per-item error parity.

The reference's Check maps CheckBulkPermissions pairs in order and, on a
per-item error, aborts returning the results accumulated so far plus the
error (/root/reference/client/client.go:279-283).  Locally the per-item
work is the host-oracle resolution of conditional/overflowed items — an
exception there must surface as BulkCheckItemError carrying the partial
prefix, and must NOT be retried (the reference retries the RPC, not the
mapping loop).
"""

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import Client
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import BulkCheckItemError

SCHEMA = """
caveat tier(t int, min int) { t >= min }
definition user {}
definition doc {
    relation reader: user | user with tier
    permission read = reader
}
"""


def _client() -> Client:
    c = Client()
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "reader", "user:u1"))
    # caveated rows force host-oracle resolution (conditional plane)
    txn.touch(
        rel.must_from_triple("doc:b", "reader", "user:u2").with_caveat(
            "tier", {"min": 3}
        )
    )
    txn.touch(
        rel.must_from_triple("doc:c", "reader", "user:u3").with_caveat(
            "tier", {"min": 3}
        )
    )
    c.write(ctx, txn)
    return c


def test_per_item_error_returns_partials():
    c = _client()
    ctx = background()
    cs = consistency.full()
    checks = [
        rel.must_from_triple("doc:a", "read", "user:u1"),  # definite T
        # no query context: the device CEL VM yields UNKNOWN → host
        rel.must_from_triple("doc:b", "read", "user:u2"),
        rel.must_from_triple("doc:c", "read", "user:u3"),  # made to fail
        rel.must_from_triple("doc:a", "read", "user:u9"),  # never reached
    ]
    # baseline: conditional items resolve (to not-granted) on the host
    assert c.check(ctx, cs, *checks) == [True, False, False, False]

    # fail the SECOND host resolution (item index 2)
    real_oracle_for = c._oracle_for
    boom = RuntimeError("caveat evaluation exploded")

    def failing_oracle_for(snap):
        oracle = real_oracle_for(snap)

        class Wrapper:
            def __init__(self):
                self.calls = 0

            def check_relationship(self, r):
                self.calls += 1
                if self.calls == 2:
                    raise boom
                return oracle.check_relationship(r)

        return Wrapper()

    c._oracle_for = failing_oracle_for
    with pytest.raises(BulkCheckItemError) as ei:
        c.check(ctx, cs, *checks)
    err = ei.value
    # results up to (not including) the failing item, reference order
    assert err.index == 2
    assert err.results == [True, False]
    assert err.__cause__ is boom


def test_per_item_error_not_retried():
    c = _client()
    ctx = background()
    cs = consistency.full()
    check = rel.must_from_triple("doc:b", "read", "user:u2")
    calls = {"n": 0}
    real_oracle_for = c._oracle_for

    def failing_oracle_for(snap):
        class Wrapper:
            def check_relationship(self, r):
                calls["n"] += 1
                raise RuntimeError("always fails")

        return Wrapper()

    c._oracle_for = failing_oracle_for
    with pytest.raises(BulkCheckItemError):
        c.check(ctx, cs, check)
    assert calls["n"] == 1, "per-item mapping errors must not be retried"
    c._oracle_for = real_oracle_for
    assert c.check(ctx, cs, check) == [False]


def test_pipelined_subbatch_matches_monolithic():
    """check_batch with flat_pipeline_batch splits big batches into
    queued sub-dispatches; results must be identical to the monolithic
    dispatch (VERDICT r04 item 8)."""
    import dataclasses

    import numpy as np

    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot
    from gochugaru_tpu import rel

    cs = compile_schema(parse_schema("""
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """))
    rels = [
        rel.must_from_tuple(f"doc:d{i % 40}#reader", f"user:u{i % 9}")
        for i in range(120)
    ]
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=1_700_000_000_000_000)
    checks = [
        rel.must_from_triple(f"doc:d{i % 50}", "read", f"user:u{i % 11}")
        for i in range(100)
    ]
    eng_m = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_pipeline_batch=0))
    eng_p = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_pipeline_batch=16))
    dm = eng_m.prepare(snap)
    dp = eng_p.prepare(snap)
    NOW = 1_700_000_000_000_000
    d0, p0, o0 = eng_m.check_batch(dm, checks, now_us=NOW)
    d1, p1, o1 = eng_p.check_batch(dp, checks, now_us=NOW)
    assert np.array_equal(np.asarray(d0), np.asarray(d1))
    assert np.array_equal(np.asarray(p0), np.asarray(p1))
    assert np.array_equal(np.asarray(o0), np.asarray(o1))

    # the generator form: per-sub-batch windows in order, same planes
    queries, _, _qc = eng_p._lower_queries(snap, checks, dp.strings)
    got = list(eng_p.check_columns_pipelined(
        dp, queries["q_res"], queries["q_perm"], queries["q_subj"],
        now_us=NOW, sub_batch=16,
    ))
    assert [g[0] for g in got] == list(range(0, 100, 16))
    dcat = np.concatenate([g[2] for g in got])
    assert np.array_equal(dcat, np.asarray(d0))

"""Shard-local fold/rc derivations + owner-routed serving.

Part 1 — bitwise parity: with a DevicePlan, ``partition_feed`` now
derives the permission fold (pfx / pfu / csr) and the rc ancestor
closures from the raw feed (full views through a stub; the derivations
are canonical, so the unsorted feed order yields the same rows as the
sorted reference snapshot) and stacks each owned shard's slice
independently.  The merged result must be BITWISE-identical — array for
array plus FlatMeta equality — to the full build-then-stack derivation
(``build_flat_arrays_sharded`` with the legacy path) on randomized
worlds with caveats, wildcards, closure overflow, and the T-join
engaged.

Part 2 — owner-routed serving: a ``serve="routed"`` feed through
``ShardedEngine.prepare_partitioned`` keeps only the primary/fold point
tables model-split (O(E/M) per device) and dispatches owner-routed
batches with no collectives; results must match the single-chip engine
exactly and the host oracle.  Batches whose slot set is not routable
(walked programs, wildcard worlds) fall back to the psum path on the
same snapshot and must match too."""

import random

import numpy as np
import pytest

from test_prepare_parity import NOW, SCHEMA, _random_world

from gochugaru_tpu import rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.flat import build_flat_arrays_sharded
from gochugaru_tpu.engine.partition import (
    ShardSlices,
    partition_feed,
    snapshot_raw_columns,
)
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import (
    build_snapshot,
    build_snapshot_from_columns,
    relationships_to_raw_columns,
)

NOWUS = NOW


def _as_full(v):
    return v.to_full() if isinstance(v, ShardSlices) else v


def _build_both(rels, cs, M, **cfg_kw):
    """(partition_feed arrays/meta, legacy reference arrays/meta) at the
    same feed, both WITH the device plan (fold/rc engaged)."""
    itn = Interner()
    raw, contexts = relationships_to_raw_columns(cs, itn, rels)
    snap = build_snapshot_from_columns(
        1, cs, itn, contexts=contexts, epoch_us=NOW,
        **{k: v.copy() for k, v in raw.items()},
    )
    eng = DeviceEngine(cs, EngineConfig.for_schema(cs, **cfg_kw))
    # flat_rev_index=False: the FEED cannot build the reverse lookup
    # index (rv ownership is keyed by the subject hash, not the primary
    # bucket a process's owned feed rows are keyed by — engine/rev.py),
    # so the apples-to-apples reference builds without it too
    legacy = EngineConfig.for_schema(
        cs, flat_partition_build=False, flat_rev_index=False, **cfg_kw
    )
    built = build_flat_arrays_sharded(snap, legacy, M, plan=eng.plan)
    assert built is not None
    ref_arrays, ref_meta, _f, _c = built
    cfg = EngineConfig.for_schema(cs, **cfg_kw)
    part = partition_feed(
        1, cs, itn, raw, cfg, M, contexts=contexts, epoch_us=NOW,
        plan=eng.plan,
    )
    assert part is not None
    return part, ref_arrays, ref_meta


def _assert_bitwise(part, ref_arrays, ref_meta):
    assert set(part.arrays) == set(ref_arrays), (
        set(part.arrays) ^ set(ref_arrays)
    )
    for k in sorted(ref_arrays):
        got = _as_full(part.arrays[k])
        assert got.shape == ref_arrays[k].shape, k
        assert np.array_equal(got, ref_arrays[k]), f"table {k} differs"
    assert part.meta == ref_meta, "FlatMeta differs"


@pytest.mark.parametrize("seed,M", [(7, 2), (23, 4)])
def test_fold_partition_bitwise_parity(seed, M):
    """Randomized world (caveats with contexts, wildcards, userset
    chains, expirations, the T-join): the partitioned fold tables merge
    bitwise-identical to the full derivation."""
    rels = _random_world(seed, 50_000)
    cs = compile_schema(parse_schema(SCHEMA))
    part, ref_arrays, ref_meta = _build_both(rels, cs, M)
    assert ref_meta.fold_pairs, "world must actually fold something"
    assert any(k.startswith("pf") for k in ref_arrays)
    _assert_bitwise(part, ref_arrays, ref_meta)


def test_fold_partition_parity_with_closure_overflow():
    """Small closure cap: overflow sources disable the fold (the
    builders must agree on the decline, and the ovf tables still merge
    bitwise)."""
    rels = _random_world(3, 40_000)
    cs = compile_schema(parse_schema(SCHEMA))
    part, ref_arrays, ref_meta = _build_both(
        rels, cs, 2, closure_source_cap=12
    )
    assert ref_meta.has_ovf
    _assert_bitwise(part, ref_arrays, ref_meta)


RC_SCHEMA = """
definition user {}
definition folder {
    relation parent: folder
    relation viewer: user
    permission view = viewer + parent->view
}
"""


def _folder_world(depth: int, chains: int, seed: int = 5):
    rng = random.Random(seed)
    rels = []
    for c in range(chains):
        for d in range(1, depth):
            rels.append(rel.Relationship(
                resource_type="folder", resource_id=f"c{c}f{d}",
                resource_relation="parent",
                subject_type="folder", subject_id=f"c{c}f{d - 1}",
            ))
        for _ in range(6):
            rels.append(rel.Relationship(
                resource_type="folder",
                resource_id=f"c{c}f{rng.randrange(depth)}",
                resource_relation="viewer",
                subject_type="user", subject_id=f"u{rng.randrange(40)}",
            ))
    return rels


@pytest.mark.parametrize("M", [2, 4])
def test_rc_partition_bitwise_parity(M):
    """Deep recursive folder hierarchy past the unroll budget: the rc
    ancestor-closure tables (fold disabled so the rc path is the one
    being compared) merge bitwise-identical to the full derivation."""
    rels = _folder_world(depth=14, chains=40)
    cs = compile_schema(parse_schema(RC_SCHEMA))
    part, ref_arrays, ref_meta = _build_both(rels, cs, M, flat_fold=False)
    assert ref_meta.rc_slots, "world must engage the rc index"
    _assert_bitwise(part, ref_arrays, ref_meta)


def test_fold_partition_owned_subset_slices():
    """Owned-subset runs materialize exactly the owned slices of the
    fold/rc stacked tables."""
    M = 4
    rels = _random_world(9, 30_000)
    cs = compile_schema(parse_schema(SCHEMA))
    itn = Interner()
    raw, contexts = relationships_to_raw_columns(cs, itn, rels)
    eng = DeviceEngine(cs, EngineConfig.for_schema(cs))
    cfg = EngineConfig.for_schema(cs)
    full = partition_feed(
        1, cs, itn, {k: v.copy() for k, v in raw.items()}, cfg, M,
        contexts=contexts, epoch_us=NOW, plan=eng.plan,
    )
    owned = (0, 2)
    part = partition_feed(
        1, cs, itn, {k: v.copy() for k, v in raw.items()}, cfg, M,
        owned=owned, contexts=contexts, epoch_us=NOW, plan=eng.plan,
    )
    assert full.meta == part.meta
    assert full.meta.fold_pairs
    saw_fold_slices = False
    for k, v in part.arrays.items():
        ref = full.arrays[k]
        if not isinstance(v, ShardSlices):
            assert np.array_equal(v, ref), k
            continue
        assert sorted(v.blocks) == list(owned), k
        if k.startswith(("pf", "rc")):
            saw_fold_slices = True
        reff = _as_full(ref)
        for s in owned:
            assert np.array_equal(
                v.blocks[s], reff[s * v.per : (s + 1) * v.per]
            ), (k, s)
    assert saw_fold_slices, "fold tables must be owned-sliced"


# ---------------------------------------------------------------------------
# owner-routed serving
# ---------------------------------------------------------------------------

ROUTED_SCHEMA = """
caveat on_tuesday(day string) { day == "tuesday" }
definition user {}
definition team { relation member: user }
definition org {
    relation admin: user
    relation member: user | team#member
}
definition repo {
    relation org: org
    relation maintainer: user | team#member
    relation reader: user with on_tuesday
    permission admin = org->admin + maintainer
    permission read = reader + admin + org->member
}
definition audit {
    relation auditor: user
    relation owner: user
    permission both = auditor & owner
}
"""


def _routed_world(seed: int = 3, n_repos: int = 600, n_users: int = 200):
    rng = random.Random(seed)
    rels = []
    for t in range(12):
        for _ in range(8):
            rels.append(rel.Relationship(
                resource_type="team", resource_id=f"t{t}",
                resource_relation="member",
                subject_type="user", subject_id=f"u{rng.randrange(n_users)}",
            ))
    for o in range(4):
        rels.append(rel.Relationship(
            resource_type="org", resource_id=f"o{o}",
            resource_relation="admin",
            subject_type="user", subject_id=f"u{rng.randrange(n_users)}",
        ))
        for t in rng.sample(range(12), 2):
            rels.append(rel.Relationship(
                resource_type="org", resource_id=f"o{o}",
                resource_relation="member",
                subject_type="team", subject_id=f"t{t}",
                subject_relation="member",
            ))
    for r in range(n_repos):
        rels.append(rel.Relationship(
            resource_type="repo", resource_id=f"r{r}",
            resource_relation="org",
            subject_type="org", subject_id=f"o{rng.randrange(4)}",
        ))
        rels.append(rel.Relationship(
            resource_type="repo", resource_id=f"r{r}",
            resource_relation="maintainer",
            subject_type="team", subject_id=f"t{rng.randrange(12)}",
            subject_relation="member",
        ))
        for _ in range(2):
            kw = dict(
                resource_type="repo", resource_id=f"r{r}",
                resource_relation="reader",
                subject_type="user", subject_id=f"u{rng.randrange(n_users)}",
            )
            if rng.random() < 0.2:
                kw.update(caveat_name="on_tuesday",
                          caveat_context={"day": "tuesday"})
            rels.append(rel.Relationship(**kw))
    for a in range(40):
        rels.append(rel.Relationship(
            resource_type="audit", resource_id=f"a{a}",
            resource_relation="auditor",
            subject_type="user", subject_id=f"u{rng.randrange(60)}",
        ))
        rels.append(rel.Relationship(
            resource_type="audit", resource_id=f"a{a}",
            resource_relation="owner",
            subject_type="user", subject_id=f"u{rng.randrange(60)}",
        ))
    return rels


def _routed_fixture(M=4):
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rels = _routed_world()
    cs = compile_schema(parse_schema(ROUTED_SCHEMA))
    itn = Interner()
    snap = build_snapshot(1, cs, itn, rels, epoch_us=NOW)
    cfg = EngineConfig.for_schema(cs)
    eng = ShardedEngine(cs, make_mesh(1, M), cfg)
    raw = snapshot_raw_columns(snap, copy=True)
    part = partition_feed(
        snap.revision, cs, snap.interner, raw, cfg, M,
        contexts=snap.contexts, epoch_us=snap.epoch_us, plan=eng.plan,
        serve="routed",
    )
    assert part is not None and part.meta.part_serve
    assert part.meta.fold_pairs, "read/admin must fold"
    return rels, cs, snap, cfg, eng, eng.prepare_partitioned(part)


def test_routed_dispatch_matches_single_chip_and_oracle():
    """Owner-routed dispatch over the partitioned-serve snapshot: the
    routed kernel (no collectives) must agree with the single-chip
    engine bit-for-bit and with the host oracle, on a fold-bearing
    batch mixing folded permissions and relation leaves."""
    from gochugaru_tpu.caveats import compile_cel
    from gochugaru_tpu.engine.oracle import Oracle, T

    rels, cs, snap, cfg, eng, dsnap = _routed_fixture()
    single = DeviceEngine(cs, cfg)
    ds_single = single.prepare(snap)

    slot = cs.slot_of_name
    rng = np.random.default_rng(7)
    B = 2048
    names = [f"u{i}" for i in range(200)]
    res_names = [f"r{i}" for i in range(600)]
    q_res = np.array(
        [snap.interner.lookup("repo", rng.choice(res_names)) for _ in range(B)],
        np.int32,
    )
    q_perm = rng.choice(
        np.array([slot["read"], slot["admin"], slot["reader"]], np.int32), B
    )
    q_subj = np.array(
        [snap.interner.lookup("user", rng.choice(names)) for _ in range(B)],
        np.int32,
    )
    d0, p0, o0 = single.check_columns(
        ds_single, q_res, q_perm, q_subj, now_us=NOW
    )
    d1, p1, o1 = eng.check_columns(dsnap, q_res, q_perm, q_subj, now_us=NOW)
    assert np.array_equal(d0, d1)
    assert np.array_equal(p0, p1)
    assert np.array_equal(o0, o1)
    assert 0 < int(d1.sum()) < B

    # oracle spot-check through the relationship path (check_batch)
    checks = [
        rel.must_from_triple(
            f"repo:r{rng.integers(600)}",
            str(rng.choice(["read", "admin"])),
            f"user:u{rng.integers(200)}",
        )
        for _ in range(96)
    ]
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    d, p, ovf = eng.check_batch(dsnap, checks, now_us=NOW)
    verified = 0
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        if ovf[i]:
            continue
        if d[i]:
            assert want == T, q
            verified += 1
        elif not p[i]:
            assert want != T, q
            verified += 1
    assert verified >= len(checks) // 2


def test_unroutable_batch_falls_back_to_psum_path():
    """A batch touching the walked (intersection) permission is not
    routable: it must dispatch through the psum path on the SAME
    partitioned-serve snapshot and still match the single-chip engine."""
    rels, cs, snap, cfg, eng, dsnap = _routed_fixture()
    assert not eng._routable(
        dsnap.flat_meta, [cs.slot_of_name["both"]]
    )
    assert eng._routable(
        dsnap.flat_meta, [cs.slot_of_name["read"], cs.slot_of_name["reader"]]
    )
    single = DeviceEngine(cs, cfg)
    ds_single = single.prepare(snap)
    rng = np.random.default_rng(11)
    B = 512
    q_res = np.array(
        [snap.interner.lookup("audit", f"a{rng.integers(40)}")
         for _ in range(B)],
        np.int32,
    )
    q_perm = np.full(B, cs.slot_of_name["both"], np.int32)
    # mix in folded-slot queries so the fallback covers mixed batches
    q_perm[: B // 4] = cs.slot_of_name["read"]
    q_subj = np.array(
        [snap.interner.lookup("user", f"u{rng.integers(60)}")
         for _ in range(B)],
        np.int32,
    )
    d0, p0, o0 = single.check_columns(
        ds_single, q_res, q_perm, q_subj, now_us=NOW
    )
    d1, p1, o1 = eng.check_columns(dsnap, q_res, q_perm, q_subj, now_us=NOW)
    assert np.array_equal(d0, d1)
    assert np.array_equal(p0, p1)
    assert np.array_equal(o0, o1)


def test_routed_per_device_tables_are_disjoint_and_small():
    """The routed snapshot's O(E)-scale point tables are genuinely
    model-split (each device holds 1/M of ehx/pfx/tx); the membership
    tables are whole per device."""
    _rels, _cs, _snap, _cfg, _eng, dsnap = _routed_fixture()
    M = 4
    for name in ("ehx", "eh_off", "pfx", "pfh_off", "tx", "th_off"):
        arr = dsnap.arrays[name]
        total = int(arr.nbytes)
        per = {}
        for s in arr.addressable_shards:
            per.setdefault(s.device.id, 0)
            per[s.device.id] += int(np.asarray(s.data).nbytes)
        assert len(per) == M
        for dev, got in per.items():
            assert got == total // M, (name, dev, got, total)
    usx = dsnap.arrays["usx"]
    for s in usx.addressable_shards:
        assert int(np.asarray(s.data).nbytes) == int(usx.nbytes)


def test_t_slot_batch_falls_back_to_psum_and_matches():
    """A T-probing slot (userset leaf, e.g. ``maintainer``) is NOT
    routable — the T join is model-split under part-serve and its
    bucket geometry differs from the routing geometry — so the batch
    dispatches through the psum path, whose ownership-mask T probe over
    the sharded tx must still match the single-chip engine exactly."""
    rels, cs, snap, cfg, eng, dsnap = _routed_fixture()
    m_slot = cs.slot_of_name["maintainer"]
    assert m_slot in dsnap.flat_meta.t_slots, "maintainer must T-index"
    assert not eng._routable(dsnap.flat_meta, [m_slot])
    single = DeviceEngine(cs, cfg)
    ds_single = single.prepare(snap)
    rng = np.random.default_rng(13)
    B = 1024
    q_res = np.array(
        [snap.interner.lookup("repo", f"r{rng.integers(600)}")
         for _ in range(B)],
        np.int32,
    )
    q_perm = np.full(B, m_slot, np.int32)
    # mix folded slots in so the fallback covers the mixed case too
    q_perm[: B // 4] = cs.slot_of_name["read"]
    q_subj = np.array(
        [snap.interner.lookup("user", f"u{rng.integers(200)}")
         for _ in range(B)],
        np.int32,
    )
    d0, p0, o0 = single.check_columns(
        ds_single, q_res, q_perm, q_subj, now_us=NOW
    )
    d1, p1, o1 = eng.check_columns(dsnap, q_res, q_perm, q_subj, now_us=NOW)
    assert np.array_equal(d0, d1)
    assert np.array_equal(p0, p1)
    assert np.array_equal(o0, o1)
    assert 0 < int(d1.sum()) < B


def test_client_with_mesh_partitioned_serves_folds_and_traces_routing():
    """client.with_mesh(mesh, partitioned=True): fold-bearing schemas
    serve through the partitioned feed (the PR-5 decline is gone), the
    dispatch owner-routes, and the request trace attributes the routing
    (per-shard batch sizes + exchange bytes on the sharded.dispatch
    span) with dispatch.route_s / partition.owned_rows metrics live."""
    from gochugaru_tpu import consistency
    from gochugaru_tpu.client import new_tpu_evaluator, with_mesh
    from gochugaru_tpu.parallel import make_mesh
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils import trace
    from gochugaru_tpu.utils.context import background

    c = new_tpu_evaluator(with_mesh(make_mesh(1, 4), partitioned=True))
    ctx = background()
    c.write_schema(ctx, ROUTED_SCHEMA)
    txn = rel.Txn()
    rng = random.Random(2)
    for r in range(60):
        txn.touch(rel.must_from_triple(f"repo:r{r}", "org", "org:o0"))
        txn.touch(rel.Relationship(
            resource_type="repo", resource_id=f"r{r}",
            resource_relation="reader",
            subject_type="user", subject_id=f"u{rng.randrange(30)}",
            caveat_name="on_tuesday",
            caveat_context={"day": "tuesday"},
        ))
    txn.touch(rel.must_from_triple("org:o0", "admin", "user:u0"))
    c.write(ctx, txn)

    _metrics.default.reset()
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=32)
    try:
        got = c.check(
            ctx, consistency.full(),
            *[rel.must_from_triple(f"repo:r{r}", "read", "user:u0")
              for r in range(32)],
        )
        assert all(got), "org admin u0 must read every repo"
        got2 = c.check(
            ctx, consistency.full(),
            rel.must_from_triple("repo:r0", "read", "user:u29"),
            rel.must_from_triple("repo:r1", "admin", "user:u1"),
        )
        assert got2[1] is False
    finally:
        traces = [t for t in tr.traces() if t["name"] == "check"]
        trace.disable()
    evs = [
        e
        for t in traces
        for sp in t["spans"]
        if sp["name"] == "sharded.dispatch"
        for e in sp.get("events", ())
    ]
    routes = [e for e in evs if e["name"] == "route"]
    assert routes, "owner-routed dispatch must record its route event"
    r0 = routes[0]
    assert len(r0["shard_batches"]) == 4
    assert sum(r0["shard_batches"]) == 32
    assert r0["exchange_bytes"] > 0
    m = _metrics.default.snapshot()
    assert m.get("dispatch.route_s.count", 0) >= 1
    assert m.get("partition.owned_rows", 0) > 0


def test_partitioned_client_keeps_fold_across_delta_prepares():
    """Regression: ``prepare_partitioned`` must carry the feed's armed
    FoldState onto the DeviceSnapshot.  Without it the FIRST incremental
    prepare finds ``fold_state=None`` and sticky-downgrades the fold
    (DeltaMeta.pf_off), which silently drops every folded slot off the
    owner-routed path onto the psum fallback for the rest of the chain."""
    from gochugaru_tpu import consistency
    from gochugaru_tpu.client import new_tpu_evaluator, with_mesh
    from gochugaru_tpu.parallel import make_mesh
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils.context import background

    c = new_tpu_evaluator(with_mesh(make_mesh(1, 4), partitioned=True))
    ctx = background()
    c.write_schema(ctx, ROUTED_SCHEMA)
    txn = rel.Txn()
    for r in range(48):
        txn.touch(rel.must_from_triple(f"repo:r{r}", "org", "org:o0"))
    txn.touch(rel.must_from_triple("org:o0", "admin", "user:u0"))
    # seed the maintainer slot so the delta below stays dense-mappable
    # (a fresh relation first used mid-chain is a legitimate full-prepare
    # bail — not what this test is about)
    txn.touch(rel.must_from_triple("repo:r1", "maintainer", "user:u5"))
    rev1 = c.write(ctx, txn)
    assert c.check(
        ctx, consistency.at_least(rev1),
        rel.must_from_triple("repo:r0", "read", "user:u0"),
    ) == [True]
    ds1 = c._dsnap_cache[max(c._dsnap_cache)]
    assert ds1.flat_meta.fold_pairs, "world must fold"
    assert ds1.fold_state is not None, "feed must arm the fold state"

    # a plain leaf write advances the chain through the incremental
    # prepare; the fold must stay engaged (no pf_off) and the next
    # batch must still owner-route
    txn2 = rel.Txn()
    txn2.touch(rel.must_from_triple("repo:r0", "maintainer", "user:u7"))
    rev2 = c.write(ctx, txn2)
    _metrics.default.reset()
    got = c.check(
        ctx, consistency.at_least(rev2),
        rel.must_from_triple("repo:r0", "read", "user:u7"),
        rel.must_from_triple("repo:r1", "read", "user:u7"),
        rel.must_from_triple("repo:r1", "read", "user:u0"),
    )
    assert got == [True, False, True]
    ds2 = c._dsnap_cache[max(c._dsnap_cache)]
    assert ds2.flat_meta.delta is not None, "chain must ride the delta path"
    assert not ds2.flat_meta.delta.pf_off, "fold downgraded on first delta"
    assert ds2.fold_state is not None
    m = _metrics.default.snapshot()
    assert m.get("dispatch.route_s.count", 0) >= 1, (
        "post-delta folded batch must still owner-route"
    )

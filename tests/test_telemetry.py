"""Telemetry export (utils/telemetry.py) and the metrics satellites:
Prometheus rendering, the shared nearest-rank percentile math
(p50/p90/p99/p999 in one sorted pass), the explicit sample-ring write
cursor, and the HTTP endpoint end-to-end."""

import json
import urllib.error
import urllib.request

import pytest

from gochugaru_tpu.utils import metrics, trace
from gochugaru_tpu.utils.metrics import Metrics, nearest_rank, quantile_suffix
from gochugaru_tpu.utils.telemetry import (
    TelemetryServer,
    prom_name,
    render_prometheus,
    render_traces,
)


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


# ---------------------------------------------------------------------------
# metrics satellites
# ---------------------------------------------------------------------------


def test_percentile_and_snapshot_share_one_definition():
    m = Metrics()
    for i in range(100):
        m.observe("t_s", (i + 1) / 1000.0)
    snap = m.snapshot()
    for q in (50.0, 90.0, 99.0, 99.9):
        assert snap[f"t_s.{quantile_suffix(q)}"] == m.percentile("t_s", q), q
    # the new quantiles ride the same pass as the old ones
    assert snap["t_s.p90_s"] == pytest.approx(0.090, abs=0.002)
    assert snap["t_s.p99_s"] == pytest.approx(0.099, abs=0.002)
    assert snap["t_s.p999_s"] == pytest.approx(0.100, abs=0.002)
    assert quantile_suffix(99.9) == "p999_s"


def test_nearest_rank_edges():
    assert nearest_rank([5.0], 99.0) == 5.0
    assert nearest_rank([1.0, 2.0], 0.0) == 1.0
    assert nearest_rank([1.0, 2.0], 100.0) == 2.0


def test_ring_cursor_wraps_in_order():
    m = Metrics()
    cap = Metrics.SAMPLE_CAP
    for i in range(cap + 5):
        m.observe("t_s", float(i))
    # the 5 oldest samples (0..4) were overwritten in ring order
    s = m._samples["t_s"]
    assert len(s) == cap
    assert s[:5] == [float(cap), float(cap + 1), float(cap + 2),
                     float(cap + 3), float(cap + 4)]
    assert s[5] == 5.0
    assert m._scursor["t_s"] == 5


def test_ring_cursor_survives_reset_race():
    """The regression the explicit cursor fixes: deriving the write slot
    from the timing COUNT lets an in-flight timer that observed across a
    reset() recreate _timings out of step with _samples.  The cursor
    lives and dies with its ring, so post-reset writes always restart at
    slot 0 / append mode."""
    m = Metrics()
    cap = Metrics.SAMPLE_CAP
    for i in range(cap + 7):
        m.observe("t_s", float(i))
    assert m._scursor["t_s"] == 7
    m.reset()
    # racing in-flight timer lands after the reset: the old code would
    # have indexed by the recreated count (slot n-1) against a ring that
    # may or may not exist — now it's a plain append with cursor 0
    m.observe("t_s", 42.0)
    assert m._samples["t_s"] == [42.0]
    assert m._scursor["t_s"] == 0
    # refill: wrap starts from slot 0 again, not an inherited offset
    for i in range(cap):
        m.observe("t_s", float(i))
    assert m._samples["t_s"][0] == float(cap - 1)  # 42.0 was slot 0 … then
    # cursor advanced exactly once past the wrap boundary
    assert m._scursor["t_s"] == 1


# ---------------------------------------------------------------------------
# prometheus rendering
# ---------------------------------------------------------------------------


def test_render_prometheus_types_and_quantiles():
    m = Metrics()
    m.inc("checks.requested", 41)
    m.inc("checks.requested")
    m.set_gauge("breaker.state", 2)
    for i in range(200):
        m.observe("checks.dispatch", (i + 1) / 1000.0)
    text = render_prometheus(m)
    lines = text.splitlines()
    assert "# TYPE gochugaru_checks_requested_total counter" in lines
    assert "gochugaru_checks_requested_total 42.0" in lines
    assert "# TYPE gochugaru_breaker_state gauge" in lines
    assert "gochugaru_breaker_state 2.0" in lines
    assert "# TYPE gochugaru_checks_dispatch_seconds summary" in lines
    for q in ("0.5", "0.9", "0.99", "0.999"):
        assert any(
            ln.startswith(f'gochugaru_checks_dispatch_seconds{{quantile="{q}"}} ')
            for ln in lines
        ), q
    assert "gochugaru_checks_dispatch_seconds_count 200" in lines
    # quantile values equal the registry's own percentile math
    p99 = m.percentile("checks.dispatch", 99.0)
    assert f'gochugaru_checks_dispatch_seconds{{quantile="0.99"}} {p99!r}' in lines
    # '_s'-suffixed timer names normalize to _seconds, not _s_seconds
    m2 = Metrics()
    m2.observe("latency.kernel_s", 0.001)
    assert "gochugaru_latency_kernel_seconds_count 1" in render_prometheus(m2)
    assert prom_name("a.b-c", "_total") == "gochugaru_a_b_c_total"


def test_render_traces_follows_global_tracer():
    assert render_traces() == ""  # disabled → empty, not an error
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None)
    trace.root_span("probe", k="v").end()
    out = render_traces()
    assert json.loads(out.splitlines()[0])["name"] == "probe"
    assert render_traces(tr) == out


# ---------------------------------------------------------------------------
# the HTTP endpoint
# ---------------------------------------------------------------------------


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_telemetry_server_endpoints():
    m = Metrics()
    m.inc("checks.requested", 7)
    m.observe("checks.dispatch", 0.003)
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None)
    trace.root_span("check", batch=1).end()
    srv = TelemetryServer(port=0, registry=m)
    try:
        assert srv.port > 0
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        assert json.loads(body)["tracing"] is True
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        assert "gochugaru_checks_requested_total 7.0" in body
        assert 'gochugaru_checks_dispatch_seconds{quantile="0.99"}' in body
        code, body = _get(srv.url + "/traces")
        assert code == 200
        assert json.loads(body.splitlines()[0])["name"] == "check"
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.url + "/nope")
        # the gauge advertises the bound port on the default registry
        assert metrics.default.gauge("telemetry.port") == srv.port
    finally:
        srv.close()


def test_with_telemetry_client_option():
    from gochugaru_tpu.client import new_tpu_evaluator, with_telemetry

    c = new_tpu_evaluator(
        with_telemetry(port=0, trace_sample_rate=1.0, trace_slow_ms=None)
    )
    try:
        assert c.telemetry is not None and c.telemetry.port > 0
        assert trace.enabled(), "with_telemetry(trace_sample_rate=) installs tracer"
        code, body = _get(c.telemetry.url + "/metrics")
        assert code == 200 and "gochugaru_" in body
    finally:
        c.telemetry.close()

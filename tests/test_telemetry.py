"""Telemetry export (utils/telemetry.py) and the metrics satellites:
Prometheus rendering, the shared nearest-rank percentile math
(p50/p90/p99/p999 in one sorted pass), the explicit sample-ring write
cursor, and the HTTP endpoint end-to-end."""

import json
import urllib.error
import urllib.request

import pytest

from gochugaru_tpu.utils import metrics, trace
from gochugaru_tpu.utils.metrics import Metrics, nearest_rank, quantile_suffix
from gochugaru_tpu.utils.telemetry import (
    TelemetryServer,
    prom_name,
    render_prometheus,
    render_traces,
)


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


# ---------------------------------------------------------------------------
# metrics satellites
# ---------------------------------------------------------------------------


def test_percentile_and_snapshot_share_one_definition():
    m = Metrics()
    for i in range(100):
        m.observe("t_s", (i + 1) / 1000.0)
    snap = m.snapshot()
    for q in (50.0, 90.0, 99.0, 99.9):
        assert snap[f"t_s.{quantile_suffix(q)}"] == m.percentile("t_s", q), q
    # the new quantiles ride the same pass as the old ones
    assert snap["t_s.p90_s"] == pytest.approx(0.090, abs=0.002)
    assert snap["t_s.p99_s"] == pytest.approx(0.099, abs=0.002)
    assert snap["t_s.p999_s"] == pytest.approx(0.100, abs=0.002)
    assert quantile_suffix(99.9) == "p999_s"


def test_nearest_rank_edges():
    assert nearest_rank([5.0], 99.0) == 5.0
    assert nearest_rank([1.0, 2.0], 0.0) == 1.0
    assert nearest_rank([1.0, 2.0], 100.0) == 2.0


def test_ring_cursor_wraps_in_order():
    m = Metrics()
    cap = Metrics.SAMPLE_CAP
    for i in range(cap + 5):
        m.observe("t_s", float(i))
    # the 5 oldest samples (0..4) were overwritten in ring order
    s = m._samples["t_s"]
    assert len(s) == cap
    assert s[:5] == [float(cap), float(cap + 1), float(cap + 2),
                     float(cap + 3), float(cap + 4)]
    assert s[5] == 5.0
    assert m._scursor["t_s"] == 5


def test_ring_cursor_survives_reset_race():
    """The regression the explicit cursor fixes: deriving the write slot
    from the timing COUNT lets an in-flight timer that observed across a
    reset() recreate _timings out of step with _samples.  The cursor
    lives and dies with its ring, so post-reset writes always restart at
    slot 0 / append mode."""
    m = Metrics()
    cap = Metrics.SAMPLE_CAP
    for i in range(cap + 7):
        m.observe("t_s", float(i))
    assert m._scursor["t_s"] == 7
    m.reset()
    # racing in-flight timer lands after the reset: the old code would
    # have indexed by the recreated count (slot n-1) against a ring that
    # may or may not exist — now it's a plain append with cursor 0
    m.observe("t_s", 42.0)
    assert m._samples["t_s"] == [42.0]
    assert m._scursor["t_s"] == 0
    # refill: wrap starts from slot 0 again, not an inherited offset
    for i in range(cap):
        m.observe("t_s", float(i))
    assert m._samples["t_s"][0] == float(cap - 1)  # 42.0 was slot 0 … then
    # cursor advanced exactly once past the wrap boundary
    assert m._scursor["t_s"] == 1


# ---------------------------------------------------------------------------
# prometheus rendering
# ---------------------------------------------------------------------------


def test_render_prometheus_types_and_quantiles():
    m = Metrics()
    m.inc("checks.requested", 41)
    m.inc("checks.requested")
    m.set_gauge("breaker.state", 2)
    for i in range(200):
        m.observe("checks.dispatch", (i + 1) / 1000.0)
    text = render_prometheus(m)
    lines = text.splitlines()
    assert "# TYPE gochugaru_checks_requested_total counter" in lines
    assert "gochugaru_checks_requested_total 42.0" in lines
    assert "# TYPE gochugaru_breaker_state gauge" in lines
    assert "gochugaru_breaker_state 2.0" in lines
    assert "# TYPE gochugaru_checks_dispatch_seconds summary" in lines
    for q in ("0.5", "0.9", "0.99", "0.999"):
        assert any(
            ln.startswith(f'gochugaru_checks_dispatch_seconds{{quantile="{q}"}} ')
            for ln in lines
        ), q
    assert "gochugaru_checks_dispatch_seconds_count 200" in lines
    # quantile values equal the registry's own percentile math
    p99 = m.percentile("checks.dispatch", 99.0)
    assert f'gochugaru_checks_dispatch_seconds{{quantile="0.99"}} {p99!r}' in lines
    # '_s'-suffixed timer names normalize to _seconds, not _s_seconds
    m2 = Metrics()
    m2.observe("latency.kernel_s", 0.001)
    assert "gochugaru_latency_kernel_seconds_count 1" in render_prometheus(m2)
    assert prom_name("a.b-c", "_total") == "gochugaru_a_b_c_total"


def test_render_traces_follows_global_tracer():
    assert render_traces() == ""  # disabled → empty, not an error
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None)
    trace.root_span("probe", k="v").end()
    out = render_traces()
    assert json.loads(out.splitlines()[0])["name"] == "probe"
    assert render_traces(tr) == out


# ---------------------------------------------------------------------------
# the HTTP endpoint
# ---------------------------------------------------------------------------


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_telemetry_server_endpoints():
    m = Metrics()
    m.inc("checks.requested", 7)
    m.observe("checks.dispatch", 0.003)
    tr = trace.configure(sample_rate=1.0, slow_threshold_s=None)
    trace.root_span("check", batch=1).end()
    srv = TelemetryServer(port=0, registry=m)
    try:
        assert srv.port > 0
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        assert json.loads(body)["tracing"] is True
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        assert "gochugaru_checks_requested_total 7.0" in body
        assert 'gochugaru_checks_dispatch_seconds{quantile="0.99"}' in body
        code, body = _get(srv.url + "/traces")
        assert code == 200
        assert json.loads(body.splitlines()[0])["name"] == "check"
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.url + "/nope")
        # the gauge advertises the bound port on the default registry
        assert metrics.default.gauge("telemetry.port") == srv.port
    finally:
        srv.close()


def test_with_telemetry_client_option():
    from gochugaru_tpu.client import new_tpu_evaluator, with_telemetry

    c = new_tpu_evaluator(
        with_telemetry(port=0, trace_sample_rate=1.0, trace_slow_ms=None)
    )
    try:
        assert c.telemetry is not None and c.telemetry.port > 0
        assert trace.enabled(), "with_telemetry(trace_sample_rate=) installs tracer"
        code, body = _get(c.telemetry.url + "/metrics")
        assert code == 200 and "gochugaru_" in body
        # this round: the anomaly loop arms with the endpoint — flight
        # recorder installed, SLO engine ticking, /slo live
        assert c.recorder is trace.recorder() and c.recorder is not None
        assert c.slo is not None
        code, body = _get(c.telemetry.url + "/slo")
        assert code == 200 and json.loads(body)["enabled"] is True
        code, body = _get(c.telemetry.url + "/debug/incidents")
        assert code == 200 and json.loads(body)["incidents"] == []
    finally:
        if c.slo is not None:
            c.slo.close()
        c.telemetry.close()


# ---------------------------------------------------------------------------
# OpenMetrics dialect + exemplars
# ---------------------------------------------------------------------------

#: minimal OpenMetrics line grammar: TYPE/EOF comments, or a sample with
#: optional labels, a value, and an optional exemplar (histogram buckets)
_OM_LINE = __import__("re").compile(
    r"^(?:"
    r"# (?:TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
    r"(?:counter|gauge|summary|histogram)|EOF)"
    r"|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? -?[0-9.e+-]+(?:[0-9]+)?"
    r"(?: # \{[^{}]*\} -?[0-9.e+-]+(?: [0-9.]+)?)?"
    r")$"
)


def test_openmetrics_render_parses_and_carries_exemplars():
    from gochugaru_tpu.utils.telemetry import render_prometheus

    m = Metrics()
    m.inc("checks.requested", 3)
    m.set_gauge("breaker.state", 0)
    m.observe("checks.dispatch", 0.004)
    m.observe_hist("serve.request_latency", 0.004, (0.001, 0.01, 0.1),
                   trace_id="abc-1")
    m.observe_hist("serve.request_latency", 0.9, (0.001, 0.01, 0.1),
                   trace_id="def-2")
    text = render_prometheus(m, openmetrics=True)
    lines = text.splitlines()
    # every line matches the OpenMetrics grammar; the doc ends with # EOF
    for ln in lines:
        assert _OM_LINE.match(ln), f"invalid OpenMetrics line: {ln!r}"
    assert lines[-1] == "# EOF"
    # counter family: TYPE names the family, the sample adds _total
    assert "# TYPE gochugaru_checks_requested counter" in lines
    assert "gochugaru_checks_requested_total 3.0" in lines
    # exemplars attach to the bucket the trace landed in, with value+ts
    ex = [ln for ln in lines if "# {" in ln]
    assert len(ex) == 2
    assert any('le="0.01"' in ln and 'trace_id="abc-1"' in ln for ln in ex)
    assert any('le="+Inf"' in ln and 'trace_id="def-2"' in ln for ln in ex)
    # canonical-float le labels in OM mode
    assert any('le="0.001"' in ln for ln in lines)
    # the 0.0.4 dialect never emits exemplars (invalid there) and keeps
    # its historical TYPE naming
    classic = render_prometheus(m)
    assert "# {" not in classic
    assert "# TYPE gochugaru_checks_requested_total counter" in classic
    assert not classic.rstrip().endswith("# EOF")


def test_metrics_endpoint_negotiates_openmetrics():
    import urllib.request

    m = Metrics()
    m.observe_hist("serve.batch_fill", 3, (4, 16), trace_id="t-1")
    srv = TelemetryServer(port=0, registry=m)
    try:
        req = urllib.request.Request(
            srv.url + "/metrics",
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert "openmetrics-text" in r.headers["Content-Type"]
            body = r.read().decode()
        assert body.rstrip().endswith("# EOF") and 'trace_id="t-1"' in body
        # and the query-param route for curl
        code, body = _get(srv.url + "/metrics?openmetrics=1")
        assert code == 200 and body.rstrip().endswith("# EOF")
        # default stays 0.0.4
        code, body = _get(srv.url + "/metrics")
        assert code == 200 and "# EOF" not in body
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# readiness /healthz + /slo + /debug/incidents
# ---------------------------------------------------------------------------


def test_readiness_report_degrades_with_reasons():
    from gochugaru_tpu.utils.slo import SLOEngine, ratio_slo
    from gochugaru_tpu.utils.telemetry import readiness_report

    m = Metrics()
    r = readiness_report(m)
    assert r["status"] == "ok" and r["reasons"] == []
    assert r["breaker_state"] == 0 and r["slo"] is None
    # breaker open → degraded with the reason named
    m.set_gauge("breaker.state", 2)
    m.set_gauge("admission.inflight", 7)
    m.set_gauge("serve.queue_depth", 123)
    r = readiness_report(m)
    assert r["status"] == "degraded" and "breaker_open" in r["reasons"]
    assert r["admission_inflight"] == 7 and r["serve_queue_depth"] == 123
    m.set_gauge("breaker.state", 1)
    assert "breaker_half_open" in readiness_report(m)["reasons"]
    # SLO breach → degraded naming the burning SLO
    m.set_gauge("breaker.state", 0)
    clock = [0.0]
    eng = SLOEngine(
        slos=[ratio_slo("shed", bad=("sheds",), total=("reqs",),
                        budget=0.05)],
        registry=m, windows=(10.0, 60.0), tick_s=1.0,
        clock=lambda: clock[0], start=False,
    )
    for _ in range(70):
        clock[0] += 1.0
        m.inc("reqs", 10)
        m.inc("sheds", 5)
        eng.tick()
    r = readiness_report(m, slo=eng)
    assert r["status"] == "degraded"
    assert "slo_burn:shed" in r["reasons"]
    assert r["slo"] == {"healthy": False, "breached": ["shed"]}


def test_healthz_and_incident_endpoints_end_to_end(tmp_path):
    m = Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, registry=m)
    rec = trace.install_recorder(trace.FlightRecorder(
        incident_dir=str(tmp_path), grace_s=0.0, cooldown_s=0.0,
        registry=m,
    ))
    srv = TelemetryServer(port=0, registry=m, recorder=rec)
    try:
        code, body = _get(srv.url + "/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["status"] == "ok"
        assert hz["incidents"] == 0
        trace.root_span("check", batch=1).end()
        iid = rec.trigger("breaker.trip", consecutive=2)
        rec.flush()
        code, body = _get(srv.url + "/debug/incidents")
        idx = json.loads(body)
        assert code == 200 and idx["incident_dir"] == str(tmp_path)
        assert len(idx["incidents"]) == 1
        assert idx["incidents"][0]["id"] == iid
        code, body = _get(srv.url + f"/debug/incidents/{iid}")
        assert code == 200
        head = json.loads(body.splitlines()[0])
        assert head["kind"] == "incident" and head["trigger"] == "breaker.trip"
        # a fresh trip makes /healthz degraded via recent_incidents
        code, body = _get(srv.url + "/healthz")
        hz = json.loads(body)
        assert hz["status"] == "degraded"
        assert any(r.startswith("recent_incidents:") for r in hz["reasons"])
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.url + "/debug/incidents/nope")
    finally:
        srv.close()


def test_slo_endpoint_disabled_and_enabled():
    from gochugaru_tpu.utils.slo import SLOEngine

    m = Metrics()
    srv = TelemetryServer(port=0, registry=m)
    try:
        code, body = _get(srv.url + "/slo")
        assert code == 200 and json.loads(body) == {"enabled": False}
    finally:
        srv.close()
    eng = SLOEngine(registry=m, start=False)
    srv = TelemetryServer(port=0, registry=m, slo=eng)
    try:
        code, body = _get(srv.url + "/slo")
        rep = json.loads(body)
        assert rep["enabled"] and rep["healthy"] is True
        assert {s["name"] for s in rep["slos"]} >= {"shed", "serve.request"}
    finally:
        srv.close()

"""Differential tests for the device delta level (engine/flat.py
DeltaMeta / build_delta_arrays, engine/device.py _prepare_delta).

Contract: a delta-prepared DeviceSnapshot (base tables + dl_* overlays)
must answer every check EXACTLY like a fully-prepared DeviceSnapshot of
the same revision — the two paths are interchangeable by construction, so
each test prepares both and compares all three planes.  Reference
semantics being reproduced: Watch-driven re-index, a revision is a
consistent snapshot of the ordered update log
(client/client.go:364-413, consistency/consistency.go)."""

import random

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.delta import apply_delta
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

from test_flat_engine import FEATURES, NOW, build_feature_world, make_checks


def _prep(seed=3, **cfg):
    rng = random.Random(seed)
    rels = build_feature_world(rng)
    cs = compile_schema(parse_schema(FEATURES))
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=NOW)
    cfg.setdefault("flat_recursion", 3)
    cfg.setdefault("flat_max_width", 32)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, **cfg))
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.blockslice
    return rng, rels, cs, interner, snap, engine, dsnap


def _assert_parity(engine, ds_inc, ds_full, checks):
    di, pi, oi = engine.check_batch(ds_inc, checks, now_us=NOW)
    df, pf, of = engine.check_batch(ds_full, checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert bool(di[i]) == bool(df[i]), (
            f"definite differs for {q}: inc={di[i]} full={df[i]}"
        )
        assert bool(pi[i]) == bool(pf[i]), (
            f"possible differs for {q}: inc={pi[i]} full={pf[i]}"
        )
        assert bool(oi[i]) == bool(of[i]), f"overflow differs for {q}"


def test_delta_level_random_stream():
    """A randomized multi-revision update stream: adds (direct, userset,
    arrow, caveated, expiring, fresh nodes) and deletes of base AND
    delta-added rows, chained across revisions without a full rebuild."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=3)
    py = random.Random(99)
    live = [
        r for r in rels
        if r.resource_type == "doc" and r.resource_relation in ("reader", "banned")
    ]
    # userset grants may only cite groups already used as subjects: a
    # newly-referenced userset has no closure rows, which is exactly the
    # (tested separately) bail condition
    used_groups = sorted({
        r.subject_id for r in rels
        if r.subject_type == "group" and r.subject_relation == "member"
    })
    for revision in range(2, 7):
        adds = []
        for i in range(6):
            kind = py.randrange(5)
            if kind == 0:
                r = rel.must_from_triple(
                    f"doc:d{py.randrange(12)}", "reader", f"user:new{revision}_{i}"
                )
            elif kind == 1:
                r = rel.must_from_tuple(
                    f"doc:d{py.randrange(10)}#reader",
                    f"group:{py.choice(used_groups)}#member",
                )
            elif kind == 2:
                r = rel.must_from_tuple(
                    f"doc:fresh{revision}_{i}#folder", f"folder:f{py.randrange(6)}"
                )
            elif kind == 3:
                r = rel.must_from_triple(
                    f"doc:d{py.randrange(10)}", "reader", f"user:u{py.randrange(10)}"
                ).with_caveat("tier", {"min": py.randint(1, 9)})
            else:
                r = rel.must_from_triple(
                    f"doc:d{py.randrange(10)}", "banned", f"user:u{py.randrange(10)}"
                )
            adds.append(r)
        deletes = []
        if live and py.random() < 0.8:
            deletes.append(live.pop(py.randrange(len(live))))
        # also delete something added in an earlier delta revision
        if revision > 3:
            deletes.append(
                rel.must_from_triple(
                    f"doc:d{py.randrange(12)}", "reader", f"user:new{revision-1}_0"
                )
            )
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        ds_inc = engine.prepare(snap, prev=dsnap)
        assert ds_inc.flat_meta.delta is not None, f"rev {revision} fell back"
        ds_full = engine.prepare(snap)
        checks = make_checks(rng, 10, 12, n=40) + [
            rel.must_from_triple(
                f"doc:d{py.randrange(12)}", "read", f"user:new{revision}_{i}"
            )
            for i in range(3)
        ] + [
            rel.must_from_triple(
                f"doc:{d.resource_id}", "read", f"user:{d.subject_id}"
            )
            for d in deletes
            if d.subject_type == "user"
        ]
        _assert_parity(engine, ds_inc, ds_full, checks)
        dsnap = ds_inc  # chain


def test_delta_level_base_userset_tombstone_t_dirty():
    """Deleting a BASE userset grant row under a T-covered slot: the base
    T-index cites the dead edge, so the dirty-group mask must void it and
    the forced KU pass must re-derive the live union."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=11)
    meta = dsnap.flat_meta
    # find a base userset row whose slot the T-index covers
    target = None
    slot_names = {v: k for k, v in cs.slot_of_name.items()}
    t_named = {slot_names[s] for s in meta.t_slots} if meta.has_tindex else set()
    for r in rels:
        # a GRANT row (doc/folder → group#member), not a group-nesting row
        # (deleting those changes the closure and must bail instead)
        if (
            r.subject_relation == "member"
            and r.resource_type in ("doc", "folder")
            and r.resource_relation in t_named
        ):
            target = r
            break
    if target is None:
        import pytest

        pytest.skip("world has no T-covered userset rows")
    snap2 = apply_delta(snap, 2, [], [target], interner=interner)
    ds_inc = engine.prepare(snap2, prev=dsnap)
    assert ds_inc.flat_meta.delta is not None
    assert ds_inc.flat_meta.delta.has_ustomb
    assert ds_inc.flat_meta.delta.t_dirty
    ds_full = engine.prepare(snap2)
    checks = make_checks(rng, 10, 10, n=40) + [
        rel.must_from_tuple(
            f"{target.resource_type}:{target.resource_id}"
            f"#{target.resource_relation}",
            f"{target.subject_type}:{target.subject_id}"
            f"#{target.subject_relation}",
        )
    ]
    _assert_parity(engine, ds_inc, ds_full, checks)


def test_delta_level_membership_add_advances_closure():
    """A member edge into a group used as a subject changes the closure —
    formerly the top bail class.  It now STAYS incremental: the flattened
    closure advances in place (store/closure.py advance_closure) and the
    new membership is immediately visible, with zero full rebuilds."""
    from gochugaru_tpu.utils import metrics

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=3)
    used_group = next(
        r.subject_id for r in rels
        if r.subject_relation == "member" and r.subject_type == "group"
    )
    grant = rel.must_from_tuple(f"group:{used_group}#member", "user:u9")
    rebuilds0 = metrics.default.counter("closure.rebuilds")
    snap2 = apply_delta(snap, 2, [grant], [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is not None
    assert metrics.default.counter("closure.rebuilds") == rebuilds0
    d, p, ovf = engine.check_batch(ds2, [grant], now_us=NOW)
    assert bool(d[0])
    # the advance must still match a full rebuild exactly
    _assert_parity(
        engine, ds2, engine.prepare(snap2), make_checks(rng, 10, 10, n=40)
    )


def test_delta_level_membership_closure_delta_disabled_bails():
    """With closure_delta off, the old contract holds: membership rows
    force a full rebuild."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(
        seed=3, closure_delta=False
    )
    used_group = next(
        r.subject_id for r in rels
        if r.subject_relation == "member" and r.subject_type == "group"
    )
    grant = rel.must_from_tuple(f"group:{used_group}#member", "user:u9")
    snap2 = apply_delta(snap, 2, [grant], [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is None
    d, p, ovf = engine.check_batch(ds2, [grant], now_us=NOW)
    assert bool(d[0])


def test_delta_level_compaction_threshold_bails():
    """Accumulated delta beyond max(flat_delta_min_compact, E/8) must
    trigger a full rebuild instead of growing the overlay."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(
        seed=3, flat_delta_min_compact=4
    )
    adds = [
        rel.must_from_triple(f"doc:d{i % 10}", "reader", f"user:bulk{i}")
        for i in range(64)
    ]
    snap2 = apply_delta(snap, 2, adds, [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.flat_meta.delta is None  # compacted into a fresh base
    d, _, _ = engine.check_batch(
        ds2, [rel.must_from_triple("doc:d1", "read", "user:bulk1")], now_us=NOW
    )
    assert bool(d[0])


def test_delta_level_empty_delta():
    """A revision with an empty collapsed delta still advances the
    revision on the incremental path."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=3)
    snap2 = apply_delta(snap, 2, [], [], interner=interner)
    ds2 = engine.prepare(snap2, prev=dsnap)
    assert ds2.revision == 2
    checks = make_checks(rng, 10, 10, n=30)
    _assert_parity(engine, ds2, engine.prepare(snap2), checks)


def _mini_world(schema, rels):
    cs = compile_schema(parse_schema(schema))
    interner = Interner()
    snap = build_snapshot(1, cs, interner, rels, epoch_us=NOW)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs))
    dsnap = engine.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.blockslice
    return cs, interner, snap, engine, dsnap


_MINI = """
caveat tier(t int, min int) { t >= min }
definition user {}
definition group { relation member: user }
definition doc {
    relation reader: user | user:* | group#member | user with tier
    permission read = reader
}
"""


def test_delta_level_touch_replaces_base_payload():
    """An upsert of an identity that lives in the base must void the base
    copy: re-touching an uncaveated row WITH a caveat turns a definite
    grant into a conditional one (review finding: the collapsed tombstone
    must survive the re-add)."""
    base = [
        rel.must_from_triple("doc:d0", "reader", "user:u0"),
        rel.must_from_triple("doc:d0", "reader", "user:u1").with_caveat(
            "tier", {"min": 3}
        ),
    ]
    cs, interner, snap, engine, dsnap = _mini_world(_MINI, base)
    touched = rel.must_from_triple("doc:d0", "reader", "user:u0").with_caveat(
        "tier", {"min": 5}
    )
    snap2 = apply_delta(snap, 2, [touched], [], interner=interner)
    ds_inc = engine.prepare(snap2, prev=dsnap)
    assert ds_inc.flat_meta.delta is not None
    assert ds_inc.flat_meta.delta.has_tombs
    q = rel.must_from_triple("doc:d0", "read", "user:u0")
    _assert_parity(engine, ds_inc, engine.prepare(snap2), [q])
    d, p, _ = engine.check_batch(ds_inc, [q], now_us=NOW)
    assert not bool(d[0]) and bool(p[0])  # now conditional, not definite


def test_delta_level_wildcard_add_bails_when_base_has_none():
    """A delta add with a wildcard subject must bail to a full rebuild
    when the base kernel compiled no wildcard probe sites (review
    finding: the add would otherwise be invisible)."""
    base = [rel.must_from_triple("doc:d0", "reader", "user:u0")]
    cs, interner, snap, engine, dsnap = _mini_world(_MINI, base)
    assert not dsnap.flat_meta.has_wc_edges
    # intern the wildcard node via a full rebuild cycle first, so the
    # wildcard-array equality bail is not what fires
    snap2 = apply_delta(
        snap, 2, [rel.must_from_tuple("doc:d1#reader", "user:*")], [],
        interner=interner,
    )
    ds2 = engine.prepare(snap2, prev=dsnap)  # may bail (new wc node)
    snap3 = apply_delta(
        snap2, 3, [rel.must_from_tuple("doc:d2#reader", "user:*")],
        [rel.must_from_tuple("doc:d1#reader", "user:*")], interner=interner,
    )
    ds3 = engine.prepare(snap3, prev=ds2)
    q = rel.must_from_triple("doc:d2", "read", "user:anyone")
    _assert_parity(engine, ds3, engine.prepare(snap3), [q])
    d, _, _ = engine.check_batch(ds3, [q], now_us=NOW)
    assert bool(d[0])


def test_delta_level_caveated_userset_add_bails_without_column():
    """A caveated delta USERSET row must not lose its caveat when the base
    userset view has no caveat column (review finding: per-view gate-flag
    bail)."""
    base = [
        rel.must_from_tuple("group:g#member", "user:u0"),
        rel.must_from_tuple("doc:d0#reader", "group:g#member"),
        rel.must_from_triple("doc:d9", "reader", "user:u9").with_caveat(
            "tier", {"min": 2}
        ),  # e view HAS caveats; us view does NOT
    ]
    cs, interner, snap, engine, dsnap = _mini_world(_MINI, base)
    assert dsnap.flat_meta.e_hascav and not dsnap.flat_meta.us_hascav
    grant = rel.must_from_tuple("doc:d1#reader", "group:g#member").with_caveat(
        "tier", {"min": 7}
    )
    snap2 = apply_delta(snap, 2, [grant], [], interner=interner)
    ds_inc = engine.prepare(snap2, prev=dsnap)
    q = rel.must_from_triple("doc:d1", "read", "user:u0")
    _assert_parity(engine, ds_inc, engine.prepare(snap2), [q])
    d, p, _ = engine.check_batch(ds_inc, [q], now_us=NOW)
    assert not bool(d[0]) and bool(p[0])  # conditional on the caveat


def test_delta_level_sharded():
    """The sharded engine's incremental prepare: bucket-sharded base
    tables stay resident, the replicated dl_* overlay rides on top —
    answers must match a FULL sharded prepare and the single-chip engine,
    across chained revisions including base-row tombstones."""
    import jax
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=3)
    mesh = make_mesh(2, 4)
    sh = ShardedEngine(
        cs, mesh, EngineConfig.for_schema(cs, flat_recursion=3, flat_max_width=32)
    )
    sh_prev = sh.prepare(snap)
    assert sh_prev.flat_meta is not None and sh_prev.flat_meta.sharded
    used_groups = sorted({
        r.subject_id for r in rels
        if r.subject_type == "group" and r.subject_relation == "member"
    })
    base_readers = [
        r for r in rels
        if r.resource_type == "doc" and r.resource_relation == "reader"
        and not r.caveat_name and not r.has_expiration()
    ]
    for revision in (2, 3):
        adds = [
            rel.must_from_triple(
                f"doc:d{revision}", "reader", f"user:shnew{revision}"
            ),
            rel.must_from_tuple(
                f"doc:d{revision + 3}#reader",
                f"group:{used_groups[0]}#member",
            ),
        ]
        deletes = [base_readers.pop()] if base_readers else []
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        sh_inc = sh.prepare(snap, prev=sh_prev)
        assert sh_inc.flat_meta.delta is not None, f"rev {revision} fell back"
        sh_full = sh.prepare(snap)
        checks = make_checks(rng, 10, 12, n=32) + [
            rel.must_from_triple(
                f"doc:d{revision}", "read", f"user:shnew{revision}"
            )
        ] + [
            rel.must_from_triple(
                f"doc:{d.resource_id}", "read", f"user:{d.subject_id}"
            )
            for d in deletes
        ]
        di_, pi_, oi_ = sh.check_batch(sh_inc, checks, now_us=NOW)
        df_, pf_, of_ = sh.check_batch(sh_full, checks, now_us=NOW)
        ds_inc = engine.prepare(snap)
        d1, p1, o1 = engine.check_batch(ds_inc, checks, now_us=NOW)
        for i, q in enumerate(checks):
            assert bool(di_[i]) == bool(df_[i]) == bool(d1[i]), (
                f"rev {revision} definite differs for {q}"
            )
            assert bool(pi_[i]) == bool(pf_[i]) == bool(p1[i]), (
                f"rev {revision} possible differs for {q}"
            )
            assert bool(oi_[i]) == bool(of_[i]) == bool(o1[i]), (
                f"rev {revision} overflow differs for {q}"
            )
        sh_prev = sh_inc


def test_delta_level_long_chain_stays_stable():
    """A 40-revision chained delta stream: the compiled-kernel cache must
    stay bounded (stable FlatMeta across revisions), the accumulated
    overlay must keep answering exactly, and a final compaction-sized
    burst must fold back into a fresh base."""
    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=3)
    base_meta = dsnap.flat_meta
    metas = set()
    incr = 0
    py = random.Random(5)
    for revision in range(2, 42):
        adds = [
            rel.must_from_triple(
                f"doc:d{py.randrange(10)}", "reader", f"user:lc{revision}"
            )
        ]
        deletes = []
        if revision % 5 == 0:
            deletes = [
                rel.must_from_triple(
                    f"doc:d{py.randrange(10)}", "reader",
                    f"user:lc{revision - 1}",
                )
            ]
        snap = apply_delta(snap, revision, adds, deletes, interner=interner)
        dsnap = engine.prepare(snap, prev=dsnap)
        # fresh nodes eventually outgrow the packing radix on this tiny
        # world — the occasional full rebuild re-bases the chain
        incr += int(dsnap.flat_meta.delta is not None)
        metas.add(dsnap.flat_meta)
        d, _, _ = engine.check_batch(
            dsnap,
            [rel.must_from_triple("doc:d1", "read", f"user:lc{revision}")]
            if adds[0].resource_id == "d1"
            else [
                rel.must_from_triple(
                    f"doc:{adds[0].resource_id}", "read", f"user:lc{revision}"
                )
            ],
            now_us=NOW,
        )
        assert bool(d[0])
    # delta-table shape buckets keep the distinct-meta count (≈ compiled
    # kernels) far below the revision count, and the chain stays
    # overwhelmingly incremental (one radix rebuild allowed)
    assert len(metas) <= 10, len(metas)
    assert incr >= 38, incr
    assert len(engine._flat_fns) <= engine.FLAT_FN_CACHE_MAX
    # final parity check vs a full prepare
    checks = make_checks(rng, 10, 12, n=40)
    _assert_parity(engine, dsnap, engine.prepare(snap), checks)
    # compaction burst: enough rows to cross max(flat_delta_min_compact,
    # E/8) folds back into a fresh base (delta=None) that still answers
    big = [
        rel.must_from_triple(f"doc:d{i % 10}", "reader", f"user:burst{i}")
        for i in range(70_000)
    ]
    snap = apply_delta(snap, 42, big, [], interner=interner)
    dsnap = engine.prepare(snap, prev=dsnap)
    assert dsnap.flat_meta.delta is None
    d, _, _ = engine.check_batch(
        dsnap, [rel.must_from_triple("doc:d1", "read", "user:burst1")],
        now_us=NOW,
    )
    assert bool(d[0])
    assert base_meta is not None


def test_delta_level_sharded_userset_tombstone():
    """Sharded t_dirty path: deleting a BASE userset grant row under a
    T-covered slot on the mesh — the replicated dirty-group mask voids
    the bucket-sharded T answers and the forced KU pass (with replicated
    tombstone masking over the broadcast candidate block) re-derives the
    live union."""
    import jax
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=11)
    slot_names = {v: k for k, v in cs.slot_of_name.items()}
    mesh = make_mesh(2, 4)
    sh = ShardedEngine(
        cs, mesh, EngineConfig.for_schema(cs, flat_recursion=3, flat_max_width=32)
    )
    sh_prev = sh.prepare(snap)
    meta = sh_prev.flat_meta
    t_named = {slot_names[s] for s in meta.t_slots} if meta.has_tindex else set()
    target = next(
        (
            r for r in rels
            if r.subject_relation == "member"
            and r.resource_type in ("doc", "folder")
            and r.resource_relation in t_named
        ),
        None,
    )
    if target is None:
        pytest.skip("world has no T-covered userset rows")
    snap2 = apply_delta(snap, 2, [], [target], interner=interner)
    sh_inc = sh.prepare(snap2, prev=sh_prev)
    assert sh_inc.flat_meta.delta is not None
    assert sh_inc.flat_meta.delta.has_ustomb and sh_inc.flat_meta.delta.t_dirty
    checks = make_checks(rng, 10, 10, n=32) + [
        rel.must_from_tuple(
            f"{target.resource_type}:{target.resource_id}"
            f"#{target.resource_relation}",
            f"{target.subject_type}:{target.subject_id}"
            f"#{target.subject_relation}",
        )
    ]
    d1, p1, o1 = sh.check_batch(sh.prepare(snap2), checks, now_us=NOW)
    di, pi, oi = sh.check_batch(sh_inc, checks, now_us=NOW)
    ds, ps, os_ = engine.check_batch(engine.prepare(snap2), checks, now_us=NOW)
    for i, q in enumerate(checks):
        assert bool(di[i]) == bool(d1[i]) == bool(ds[i]), q
        assert bool(pi[i]) == bool(p1[i]) == bool(ps[i]), q
        assert bool(oi[i]) == bool(o1[i]) == bool(os_[i]), q


def test_device_lookups_on_sharded_engine():
    """lookup_resources/lookup_subjects drive the SHARDED engine's exact
    filter (bucket_min threads through the mesh dispatch) and must match
    the single-chip results."""
    import jax
    import pytest

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from gochugaru_tpu.engine.lookup import (
        lookup_resources_device,
        lookup_subjects_device,
    )
    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rng, rels, cs, interner, snap, engine, dsnap = _prep(seed=4)
    from gochugaru_tpu.caveats import compile_cel

    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    oracle = Oracle(cs, rels, progs, now_us=NOW)
    sh = ShardedEngine(
        cs, make_mesh(2, 4),
        EngineConfig.for_schema(cs, flat_recursion=3, flat_max_width=32),
    )
    shds = sh.prepare(snap)
    for u in ("u0", "u3", "u7"):
        single = lookup_resources_device(
            engine, dsnap, "doc", "read", "user", u,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        sharded = lookup_resources_device(
            sh, shds, "doc", "read", "user", u,
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        assert single == sharded, u
    for d in ("d0", "d4"):
        single = lookup_subjects_device(
            engine, dsnap, "doc", d, "read", "user",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        sharded = lookup_subjects_device(
            sh, shds, "doc", d, "read", "user",
            now_us=NOW, oracle_factory=lambda: oracle,
        )
        assert single == sharded, d

"""Regression tests for code-review findings (round 1, batch 4): the
device engine must never return a silently wrong answer — caps trip the
overflow flag (→ host fallback) instead."""

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import new_tpu_evaluator, new_with_opts, with_host_only_evaluation, with_store
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import T, Oracle
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils.context import background

NOW = 1_700_000_000_000_000


def test_mutually_recursive_permissions_with_acyclic_arrows():
    # eval_iters must cover permission cycles even when arrows are acyclic
    schema = """
    definition user {}
    definition folder { relation owner: user permission view = owner }
    definition doc {
        relation parent: folder
        relation r1: user
        relation r2: user
        permission a = r1 + b
        permission b = r2 + a + parent->view
    }
    """
    ctx = background()
    c = new_tpu_evaluator()
    c.write_schema(ctx, schema)
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:d", "r1", "user:amy"))
    c.write(ctx, txn)
    # amy has r1 → a → b must be granted through the cycle
    assert c.check_one(
        ctx, consistency.full(), rel.must_from_triple("doc:d", "b", "user:amy")
    )
    h = new_with_opts(with_host_only_evaluation(), with_store(c.store))
    assert h.check_one(
        ctx, consistency.full(), rel.must_from_triple("doc:d", "b", "user:amy")
    )


def _folder_chain(depth):
    schema = """
    definition user {}
    definition folder {
        relation parent: folder
        relation reader: user
        permission view = reader + parent->view
    }
    """
    # reader sits at the root f0; f_i's parent is f_{i-1}, so a query on
    # the deep end f_{depth-1} walks depth-1 arrow hops up to the root
    triples = [("folder:f0#reader", "user:amy")]
    for i in range(1, depth):
        triples.append((f"folder:f{i}#parent", f"folder:f{i-1}"))
    rels = [rel.must_from_tuple(*t) for t in triples]
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    return cs, snap, rels


def test_chain_deeper_than_subgraph_overflows_not_wrong():
    cs, snap, rels = _folder_chain(12)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, subgraph_nodes=8))
    dsnap = engine.prepare(snap)
    oracle = Oracle(cs, rels, now_us=NOW)
    # query from the deep end: needs an 11-hop walk, subgraph capped at 8
    q = rel.must_from_triple("folder:f11", "view", "user:amy")
    assert oracle.check_relationship(q) == T
    d, p, ovf = engine.check_batch(dsnap, [q], now_us=NOW)
    # a host-fallback signal (overflow or possible&~definite) or the right
    # answer — never a silent deny.  The flat engine signals recursion-
    # budget exhaustion through the possible plane; the legacy engine
    # through the overflow flag.
    assert ovf[0] or d[0] or (p[0] and not d[0]), (
        "deep chain must overflow/resolve, never silently deny"
    )
    # and the legacy two-phase engine specifically trips overflow
    legacy = DeviceEngine(
        cs, EngineConfig.for_schema(cs, subgraph_nodes=8, use_flat=False)
    )
    ld, lp, lovf = legacy.check_batch(legacy.prepare(snap), [q], now_us=NOW)
    assert lovf[0], "subgraph deeper than the cap must trip legacy overflow"


def test_chain_deeper_than_cap_correct_via_client_fallback():
    ctx = background()
    c = new_tpu_evaluator()
    c.write_schema(
        ctx,
        """
        definition user {}
        definition folder {
            relation parent: folder
            relation reader: user
            permission view = reader + parent->view
        }
        """,
    )
    txn = rel.Txn()
    depth = 12
    txn.create(rel.must_from_triple("folder:f0", "reader", "user:amy"))
    for i in range(1, depth):
        txn.create(rel.must_from_triple(f"folder:f{i}", "parent", f"folder:f{i-1}"))
    c.write(ctx, txn)
    assert c.check_one(
        ctx, consistency.full(),
        rel.must_from_triple(f"folder:f{depth-1}", "view", "user:amy"),
    )


def test_nesting_deeper_than_closure_hops_overflows_not_wrong():
    schema = """
    definition user {}
    definition group { relation member: user | group#member }
    definition doc { relation viewer: group#member permission view = viewer }
    """
    depth = 12
    triples = [("group:g0#member", "user:amy")]
    for i in range(1, depth):
        triples.append((f"group:g{i}#member", f"group:g{i-1}#member"))
    triples.append((f"doc:d#viewer", f"group:g{depth-1}#member"))
    rels = [rel.must_from_tuple(*t) for t in triples]
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    oracle = Oracle(cs, rels, now_us=NOW)
    engine = DeviceEngine(cs, EngineConfig.for_schema(cs, closure_hops=8))
    dsnap = engine.prepare(snap)
    q = rel.must_from_triple("doc:d", "view", "user:amy")
    assert oracle.check_relationship(q) == T
    d, p, ovf = engine.check_batch(dsnap, [q], now_us=NOW)
    assert d[0] or ovf[0], "deep nesting must overflow (or resolve), never silently deny"
    # and through the client the fallback resolves it correctly
    ctx = background()
    c = new_tpu_evaluator()
    c.write_schema(ctx, schema)
    txn = rel.Txn()
    for t in triples:
        txn.create(rel.must_from_tuple(*t))
    c.write(ctx, txn)
    assert c.check_one(ctx, consistency.full(), q)

"""Differential tests for the bucket-ALIGNED table layout
(engine/hash.py build_aligned / probe_aligned, wired through
engine/flat.py put_block + the name-keyed pblock dispatch).

The aligned layout is the TPU-shaped probe (one row gather per site,
~48M probes/s measured vs 0.75M for the off+block slice —
tpu_attempts/micro_blocks.py); it defaults on only when the backend is
TPU, so these tests force ``flat_aligned=True`` to exercise it on the
CPU suite, asserting bit-identical results against the oracle and
against the legacy layout.
"""

import random

import numpy as np
import pytest

from gochugaru_tpu.engine.hash import build_aligned, probe_aligned
from tests.test_flat_engine import (
    FEATURES,
    NOW,
    assert_sound_cascade,
    build_feature_world,
    world,
)


def _all_checks(rng, n_users=10, n_groups=5, n_folders=6, n_docs=10, k=160):
    from gochugaru_tpu import rel

    perms = [
        ("doc", "read"), ("doc", "audit"), ("doc", "reader"),
        ("folder", "view"), ("group", "member"),
    ]
    checks = []
    for _ in range(k):
        t, p = rng.choice(perms)
        rid = rng.randrange({"doc": n_docs, "folder": n_folders,
                             "group": n_groups}[t])
        u = rng.randrange(n_users)
        r = rel.must_from_triple(f"{t}:{t[0]}{rid}",
                                 p, f"user:u{u}")
        checks.append(r)
    return checks


def test_aligned_matches_oracle_and_legacy():
    rng = random.Random(7)
    rels = build_feature_world(rng)
    checks = _all_checks(rng)

    eng_a, ds_a, oracle = world(FEATURES, rels, flat_aligned=True)
    assert ds_a.flat_meta.aligned, "aligned layout did not engage"
    assert any(k.endswith("_al") for k in ds_a.arrays), "no _al arrays"
    assert_sound_cascade(eng_a, ds_a, oracle, checks)

    eng_l, ds_l, _ = world(FEATURES, rels, flat_aligned=False)
    assert not ds_l.flat_meta.aligned
    da, pa, ova = eng_a.check_batch(ds_a, checks, now_us=NOW)
    dl, pl, ovl = eng_l.check_batch(ds_l, checks, now_us=NOW)
    assert np.array_equal(np.asarray(da), np.asarray(dl))
    assert np.array_equal(np.asarray(pa), np.asarray(pl))
    assert np.array_equal(np.asarray(ova), np.asarray(ovl))


def test_aligned_survives_delta_chain():
    """Incremental prepares keep the aligned base tables resident; the
    delta overlays stay on the legacy replicated layout."""
    from gochugaru_tpu import rel

    rng = random.Random(11)
    rels = build_feature_world(rng)
    eng, ds, oracle = world(FEATURES, rels, flat_aligned=True)
    assert ds.flat_meta.aligned

    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.store.delta import apply_delta

    adds = [
        rel.must_from_tuple("doc:d0#reader", "user:u9"),
        rel.must_from_tuple("doc:d1#banned", "user:u2"),
    ]
    rels2 = rels + adds
    snap2 = apply_delta(
        ds.snapshot, 2, adds, [], interner=ds.snapshot.interner
    )
    ds2 = eng.prepare(snap2, prev=ds)
    assert ds2.flat_meta.delta is not None, "delta path not taken"
    assert ds2.flat_meta.aligned, "aligned meta lost across delta"
    oracle2 = Oracle(eng.compiled, rels2, {}, now_us=NOW)
    checks = _all_checks(random.Random(3)) + adds
    assert_sound_cascade(eng, ds2, oracle2, checks)


def test_build_aligned_duplicate_tail_falls_back():
    """A full key duplicated past cap+spill capacity makes the aligned
    build refuse (returns None) instead of silently dropping rows."""
    n = 4000
    k1 = np.zeros(n, np.int32)  # one bucket
    k2 = np.zeros(n, np.int32)
    pay = np.arange(n, dtype=np.int32)
    assert build_aligned([k1, k2], [k1, k2, pay]) is None


def test_probe_aligned_roundtrip_with_spill():
    rng = np.random.default_rng(5)
    n = 50_000
    k1 = rng.integers(0, n // 3, n).astype(np.int32)
    k2 = rng.integers(0, 1 << 20, n).astype(np.int32)
    # one full key duplicated past the single-level cap forces the spill
    # level (the builder otherwise absorbs Poisson tails by widening the
    # primary rows — one gather beats two)
    k1[:20] = 7
    k2[:20] = 9
    pay = rng.integers(1, 1 << 30, n).astype(np.int32)
    ai = build_aligned([k1, k2], [k1, k2, pay])
    assert ai is not None and ai.spill is not None

    import jax.numpy as jnp

    qi = rng.integers(0, n, 2048)
    tbls = [jnp.asarray(t) for t, _ in ai.levels]
    blk = probe_aligned(
        tbls, ai.caps, ai.w,
        (jnp.asarray(k1[qi]), jnp.asarray(k2[qi])),
    )
    hit = (blk[..., 0] == k1[qi][:, None]) & (blk[..., 1] == k2[qi][:, None])
    assert bool(hit.any(axis=-1).all()), "an inserted key failed to probe"
    # a key that was never inserted must miss everywhere
    miss = probe_aligned(
        tbls, ai.caps, ai.w,
        (jnp.full(64, n + 7, jnp.int32), jnp.full(64, -2, jnp.int32)),
    )
    mh = (miss[..., 0] == (n + 7)) & (miss[..., 1] == -2)
    assert not bool(mh.any())

"""The bucket-sharded layout's 1/M memory claim, exercised at a scale
where the split matters (engine/flat.py build_flat_arrays_sharded:
"keeps per-device table memory at 1/M — the graph-size scaling axis of
SURVEY.md §5").

Built on the config-2-shaped world (~50k edges), model axis = 4: every
bucket-sharded table must put ~1/4 of its bytes on each device, while
replicated tables (node types, contexts, delta overlays) appear whole
everywhere.
"""

import numpy as np

import jax

from gochugaru_tpu.parallel import ShardedEngine, make_mesh
from gochugaru_tpu.parallel.sharded import ShardedEngine as _SE


def _world():
    import sys

    sys.path.insert(0, ".")
    from bench import build_world

    return build_world(n_repos=2_000, n_users=500, n_teams=50, n_orgs=5)


def test_sharded_tables_split_memory_per_device():
    cs, snap, users, repos, slot = _world()
    mesh = make_mesh(2, 4)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded

    sharded_bytes = {}
    for name, arr in dsnap.arrays.items():
        if not hasattr(arr, "sharding"):
            continue
        spec = getattr(arr.sharding, "spec", None)
        shards = arr.addressable_shards
        per_dev = {}
        for s in shards:
            per_dev.setdefault(s.device.id, 0)
            per_dev[s.device.id] += int(np.asarray(s.data).nbytes)
        total = int(arr.nbytes)
        if spec and tuple(spec) and tuple(spec)[0] == "model":
            sharded_bytes[name] = (total, per_dev)

    assert sharded_bytes, "expected model-sharded tables"
    M = 4
    big = {n: t for n, (t, _) in sharded_bytes.items() if t > 64 * 1024}
    assert big, "expected at least one >64KiB sharded table at 50k edges"
    for name, (total, per_dev) in sharded_bytes.items():
        if total <= 64 * 1024:
            continue
        # every device holds ~1/M of the table (exactly total/M for the
        # stacked layout: leading axis is the shard axis)
        for dev, got in per_dev.items():
            assert abs(got - total // M) <= total // M * 0.01, (
                name, dev, got, total
            )

    # correctness at this scale: a sample batch against the single-chip
    # engine would double the runtime of this test; the sharded
    # differential suites cover it — here a smoke batch must dispatch
    rng = np.random.default_rng(3)
    B = 1024
    d, p, ovf = eng.check_columns(
        dsnap,
        rng.choice(repos, B).astype(np.int32),
        np.full(B, slot["read"], np.int32),
        rng.choice(users, B).astype(np.int32),
        now_us=1_700_000_000_000_000,
    )
    assert d.shape[0] == B and not ovf.any()
    assert 0 < int(d.sum()) < B

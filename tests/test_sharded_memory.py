"""The bucket-sharded layout's 1/M memory claim, exercised at a scale
where the split matters (engine/flat.py build_flat_arrays_sharded:
"keeps per-device table memory at 1/M — the graph-size scaling axis of
SURVEY.md §5").

Built on the config-2-shaped world (~50k edges), model axis = 4: every
bucket-sharded table must put ~1/4 of its bytes on each device, while
replicated tables (node types, contexts, delta overlays) appear whole
everywhere.
"""

import numpy as np
import pytest

import jax

from gochugaru_tpu.parallel import ShardedEngine, make_mesh
from gochugaru_tpu.parallel.sharded import ShardedEngine as _SE


def _world():
    import sys

    sys.path.insert(0, ".")
    from bench import build_world

    return build_world(n_repos=2_000, n_users=500, n_teams=50, n_orgs=5)


def test_sharded_tables_split_memory_per_device():
    cs, snap, users, repos, slot = _world()
    mesh = make_mesh(2, 4)
    eng = ShardedEngine(cs, mesh)
    dsnap = eng.prepare(snap)
    assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded

    sharded_bytes = {}
    for name, arr in dsnap.arrays.items():
        if not hasattr(arr, "sharding"):
            continue
        spec = getattr(arr.sharding, "spec", None)
        shards = arr.addressable_shards
        per_dev = {}
        for s in shards:
            per_dev.setdefault(s.device.id, 0)
            per_dev[s.device.id] += int(np.asarray(s.data).nbytes)
        total = int(arr.nbytes)
        if spec and tuple(spec) and tuple(spec)[0] == "model":
            sharded_bytes[name] = (total, per_dev)

    assert sharded_bytes, "expected model-sharded tables"
    M = 4
    big = {n: t for n, (t, _) in sharded_bytes.items() if t > 64 * 1024}
    assert big, "expected at least one >64KiB sharded table at 50k edges"
    for name, (total, per_dev) in sharded_bytes.items():
        if total <= 64 * 1024:
            continue
        # every device holds ~1/M of the table (exactly total/M for the
        # stacked layout: leading axis is the shard axis)
        for dev, got in per_dev.items():
            assert abs(got - total // M) <= total // M * 0.01, (
                name, dev, got, total
            )

    # correctness at this scale: a sample batch against the single-chip
    # engine would double the runtime of this test; the sharded
    # differential suites cover it — here a smoke batch must dispatch
    rng = np.random.default_rng(3)
    B = 1024
    d, p, ovf = eng.check_columns(
        dsnap,
        rng.choice(repos, B).astype(np.int32),
        np.full(B, slot["read"], np.int32),
        rng.choice(users, B).astype(np.int32),
        now_us=1_700_000_000_000_000,
    )
    assert d.shape[0] == B and not ovf.any()
    assert 0 < int(d.sum()) < B


# ---------------------------------------------------------------------------
# partition-first build scratch: no full-size O(E) sort/gather/interleave
# ---------------------------------------------------------------------------

#: sort-layer entry points whose call SIZES the shim records — the
#: sort/gather/interleave scratch the partition-first build promises to
#: keep shard-local.  Key/geometry passes (pack32/mix32/sorted_runs: one
#: flat O(E) value column each, no permutation scratch) and the single
#: stable owner-partition pass (hash_index32 with bucket count == M) are
#: the documented exemptions.
_TRACKED = (
    "hash_index32", "fill_interleaved", "take32", "take64",
    "lexsort4", "lexsort2", "argsort1", "sortperm_words",
)


def _shim_sizes(monkeypatch, calls):
    import gochugaru_tpu.native.sort as nsort

    def size_of(name, args):
        if name == "hash_index32":
            n, size = int(args[0].shape[0]), int(args[1])
            return None if size <= 8 else n  # owner partition exempt
        if name == "fill_interleaved":
            return int(args[1][0].shape[0]) if args[1] else 0
        if name in ("take32", "take64"):
            return int(args[1].shape[0])
        if name == "sortperm_words":
            return int(args[0][0].shape[0])
        return int(args[0].shape[0])

    for name in _TRACKED:
        orig = getattr(nsort, name)

        def wrapper(*args, _orig=orig, _name=name, **kw):
            n = size_of(_name, args)
            if n is not None:
                calls.append((_name, n))
            return _orig(*args, **kw)

        monkeypatch.setattr(nsort, name, wrapper)


def test_partitioned_build_scratch_is_shard_local():
    """The partition-first sharded prepare must never run a full-size
    O(E) sort/gather/interleave: every tracked sort-layer call stays
    bounded by ~E/M (+ pad slack).  The legacy build-full-then-stack
    path trips the same tracker (sanity: the assertion discriminates).
    Fold DERIVATION sorts are global by design (canonical dedup over
    the leaf/group structure), so the fold is off here; the fold
    TABLES' shard-locality has its own tracker below
    (test_partitioned_fold_tables_are_shard_local)."""
    import sys

    sys.path.insert(0, ".")
    from bench import build_world

    from gochugaru_tpu.engine.plan import EngineConfig

    # big enough that E/M + slack < E (the bound must discriminate)
    cs, snap, users, repos, slot = build_world(
        n_repos=40_000, n_users=1_000, n_teams=100, n_orgs=10
    )
    E = snap.num_edges
    M = 4
    bound = E // M + 70_000  # shard skew + pow2 pads + T-join fan slack
    assert bound < E

    def prepare_with(partition: bool):
        calls = []
        with pytest.MonkeyPatch.context() as mp:
            _shim_sizes(mp, calls)
            cfg = EngineConfig.for_schema(
                cs, flat_fold=False, flat_partition_build=partition,
                flat_partition_chunk=1 << 15,
            )
            eng = ShardedEngine(cs, make_mesh(2, M), cfg)
            dsnap = eng.prepare(snap)
        assert dsnap.flat_meta is not None and dsnap.flat_meta.sharded
        # the reverse-CSR lookup index builds inside this prepare too —
        # its partition-first sorts/gathers (engine/rev.py) are under
        # the same tracker and the same E/M bound
        assert dsnap.flat_meta.has_rev
        return calls

    calls = prepare_with(partition=True)
    assert calls, "tracker saw no sort-layer calls"
    worst = max(calls, key=lambda c: c[1])
    assert worst[1] <= bound, (
        f"full-size scratch: {worst[0]} over {worst[1]} rows (E={E})"
    )

    legacy = prepare_with(partition=False)
    assert max(n for _, n in legacy) >= E, (
        "tracker failed to see the legacy path's full-size build"
    )


def test_partitioned_fold_tables_are_shard_local():
    """The partitioned serve path (partition_feed with a plan) must
    never MATERIALIZE a full O(E)-scale fold/rc table: every table fill
    (fill_interleaved — the pass that writes interleaved/stacked rows)
    stays bounded by ~rows/M + pad slack, while the legacy full
    derivation fills the whole pf table in one pass (sanity: the same
    tracker sees it).  The fold derivation's own sorts are exempt by
    design — canonical dedup over the leaf/group structure — which is
    why this tracker watches the table fills, not the sort layer."""
    import sys

    sys.path.insert(0, ".")
    import numpy as np
    from bench import build_world

    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.flat import build_flat_arrays_sharded
    from gochugaru_tpu.engine.partition import partition_feed
    from gochugaru_tpu.engine.plan import EngineConfig

    cs, snap, users, repos, slot = build_world(
        n_repos=40_000, n_users=1_000, n_teams=100, n_orgs=10
    )
    M = 4
    cfg = EngineConfig.for_schema(cs, flat_partition_chunk=1 << 15)
    plan = DeviceEngine(cs, cfg).plan

    def fills_of(run):
        calls = []
        with pytest.MonkeyPatch.context() as mp:
            _shim_sizes(mp, calls)
            run()
        return [n for name, n in calls if name == "fill_interleaved"]

    legacy_cfg = EngineConfig.for_schema(cs, flat_partition_build=False)
    ref_box = []
    legacy = fills_of(lambda: ref_box.append(build_flat_arrays_sharded(
        snap, legacy_cfg, M, plan=plan
    )))
    assert ref_box[0] is not None
    assert ref_box[0][1].fold_pairs, "world must fold"
    L = max(legacy)
    assert L >= snap.num_edges, "legacy path must fill a full-size table"

    from gochugaru_tpu.engine.partition import snapshot_raw_columns

    raw = snapshot_raw_columns(snap, copy=True)
    part_box = []
    part_fills = fills_of(lambda: part_box.append(partition_feed(
        snap.revision, cs, snap.interner, raw, cfg, M,
        contexts=snap.contexts, epoch_us=snap.epoch_us, plan=plan,
    )))
    assert part_box[0] is not None and part_box[0].meta.fold_pairs
    P = max(part_fills)
    assert P <= L // M + 70_000, (
        f"full-size fold/rc table fill: {P} rows (legacy max {L})"
    )

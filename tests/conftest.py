"""Test bootstrap: force JAX onto CPU with 8 virtual devices so mesh/sharding
logic is exercised without TPU hardware — the moral equivalent of the
reference's `spicedb serve-testing` in-memory server (SURVEY.md §4).

NOTE: the environment's sitecustomize pins JAX_PLATFORMS=axon (the real TPU
tunnel); tests must override it, not setdefault, or the whole suite runs on
one TPU chip with per-shape XLA compiles.  Set GOCHUGARU_TEST_TPU=1 to
deliberately run the suite against the real chip."""

import os

if os.environ.get("GOCHUGARU_TEST_TPU") != "1":
    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)

# persistent XLA compile cache: identical kernels (same schema shape
# buckets) hit disk instead of recompiling across test runs
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# GOCHUGARU_FLAT_ALIGNED=1 runs the whole suite under the bucket-ALIGNED
# table layout (engine/hash.py build_aligned — the TPU-default layout,
# otherwise off on the CPU suite).  Scoped to the test harness on
# purpose: production code paths must not read layout toggles from the
# environment.
_env_aligned = os.environ.get("GOCHUGARU_FLAT_ALIGNED")
if _env_aligned is not None:
    from gochugaru_tpu.engine.plan import EngineConfig

    _orig_for_schema = EngineConfig.for_schema

    def _for_schema_aligned(compiled, **overrides):
        overrides.setdefault("flat_aligned", _env_aligned == "1")
        return _orig_for_schema(compiled, **overrides)

    EngineConfig.for_schema = staticmethod(_for_schema_aligned)


# Fault-injection hygiene: no test may leak an armed injection site into
# the next (utils/faults.py is a process-global registry by design).
import pytest


@pytest.fixture(autouse=True)
def _reset_faults():
    from gochugaru_tpu.utils import faults

    faults.reset()
    yield
    faults.reset()

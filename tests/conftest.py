"""Test bootstrap: force JAX onto CPU with 8 virtual devices so mesh/sharding
logic is exercised without TPU hardware — the moral equivalent of the
reference's `spicedb serve-testing` in-memory server (SURVEY.md §4)."""

import os

# Must run before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

"""Test bootstrap: force JAX onto CPU with 8 virtual devices so mesh/sharding
logic is exercised without TPU hardware — the moral equivalent of the
reference's `spicedb serve-testing` in-memory server (SURVEY.md §4).

NOTE: the environment's sitecustomize pins JAX_PLATFORMS=axon (the real TPU
tunnel); tests must override it, not setdefault, or the whole suite runs on
one TPU chip with per-shape XLA compiles.  Set GOCHUGARU_TEST_TPU=1 to
deliberately run the suite against the real chip."""

import os

if os.environ.get("GOCHUGARU_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # the axon sitecustomize pre-imports jax, so the env var alone is not
    # honored — force the platform through the live config too (the backend
    # itself initializes lazily, so XLA_FLAGS still takes effect)
    import jax

    jax.config.update("jax_platforms", "cpu")

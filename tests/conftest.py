"""Test bootstrap: force JAX onto CPU with 8 virtual devices so mesh/sharding
logic is exercised without TPU hardware — the moral equivalent of the
reference's `spicedb serve-testing` in-memory server (SURVEY.md §4).

NOTE: the environment's sitecustomize pins JAX_PLATFORMS=axon (the real TPU
tunnel); tests must override it, not setdefault, or the whole suite runs on
one TPU chip with per-shape XLA compiles.  Set GOCHUGARU_TEST_TPU=1 to
deliberately run the suite against the real chip."""

import os

if os.environ.get("GOCHUGARU_TEST_TPU") != "1":
    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)

# persistent XLA compile cache: identical kernels (same schema shape
# buckets) hit disk instead of recompiling across test runs
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# GOCHUGARU_FLAT_ALIGNED=1 runs the whole suite under the bucket-ALIGNED
# table layout (engine/hash.py build_aligned — the TPU-default layout,
# otherwise off on the CPU suite).  Scoped to the test harness on
# purpose: production code paths must not read layout toggles from the
# environment.
_env_aligned = os.environ.get("GOCHUGARU_FLAT_ALIGNED")
if _env_aligned is not None:
    from gochugaru_tpu.engine.plan import EngineConfig

    _orig_for_schema = EngineConfig.for_schema

    def _for_schema_aligned(compiled, **overrides):
        overrides.setdefault("flat_aligned", _env_aligned == "1")
        return _orig_for_schema(compiled, **overrides)

    EngineConfig.for_schema = staticmethod(_for_schema_aligned)


# Fault-injection hygiene: no test may leak an armed injection site into
# the next (utils/faults.py is a process-global registry by design).
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running harnesses excluded from tier-1 (-m 'not slow')",
    )


@pytest.fixture(autouse=True)
def _reset_faults():
    from gochugaru_tpu.utils import faults

    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _reset_recorder():
    """Flight-recorder hygiene (utils/trace.py): the recorder is a
    process-global by design (the trigger bus must be reachable from
    anomaly sites without plumbing); no test may leak an installed one
    into the next — a leaked recorder would make every unsampled request
    allocate flight-only spans and break the zero-alloc contract
    tests."""
    yield
    from gochugaru_tpu.utils import slo, trace

    trace.install_recorder(None)
    slo.install_engine(None)  # closes a leaked process-global engine


# Multi-host capability probe: some container jaxlib builds cannot run
# multiprocess collectives on the CPU backend at all ("Multiprocess
# computations aren't implemented on the CPU backend") — an ENVIRONMENT
# limitation, not a code defect.  Probe it once (two 1-device processes,
# jax.distributed init + one cross-process broadcast) and skip the
# multi-host tests with the detected reason instead of carrying known-red
# failures in tier-1.
_MULTIHOST_PROBE = []  # memo: [None] = supported, [reason str] = not

_PROBE_SRC = """
import os
import numpy as np
import jax
jax.distributed.initialize(
    os.environ["GOCHUGARU_PROBE_COORD"], 2,
    int(os.environ["GOCHUGARU_PROBE_PID"]),
)
from jax.experimental import multihost_utils
multihost_utils.broadcast_one_to_all(np.ones(1, np.int32))
print("MULTIHOST-PROBE-OK")
"""


def _multihost_unavailable_reason():
    """None when the environment can run multi-process CPU collectives,
    else a one-line reason string (cached per session)."""
    if _MULTIHOST_PROBE:
        return _MULTIHOST_PROBE[0]
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            GOCHUGARU_PROBE_COORD=coord,
            GOCHUGARU_PROBE_PID=str(pid),
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    reason = None
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            pr.kill()
            out, _ = pr.communicate()
            reason = reason or "probe timed out (collective hung)"
            continue
        if pr.returncode != 0 or "MULTIHOST-PROBE-OK" not in (out or ""):
            tail = [
                ln for ln in (out or "").splitlines()
                if "Error" in ln or "error" in ln
            ]
            reason = reason or (
                tail[-1].strip()[:160] if tail else "probe process failed"
            )
    _MULTIHOST_PROBE.append(reason)
    return reason


@pytest.fixture(autouse=True)
def _skip_unsupported_multihost(request):
    if request.module.__name__ == "test_multihost":
        reason = _multihost_unavailable_reason()
        if reason is not None:
            pytest.skip(f"multi-host env unavailable: {reason}")
    yield

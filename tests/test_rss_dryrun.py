"""The host-sharded build's memory acceptance bar, as a slow-marked
test (scripts/rss_dryrun.sh is the CLI form): the 2-process CPU dryrun
must build its feed-partitioned tables in ≤ 60% of the single-process
build-full-then-stack RSS at the same world, with the partitioned
tables bitwise-identical to the pre-PR builder (the harness's parity
child).  Deltas are measured against the post-worldgen baseline of each
process, so the comparison isolates feed→tables memory from the fixed
interpreter/jax footprint.  Excluded from tier-1 (``-m 'not slow'``):
it spawns four python+jax processes over a ~1M-edge world."""

import pytest

from gochugaru_tpu.parallel.multihost import rss_dryrun


@pytest.mark.slow
def test_two_process_build_rss_within_60_percent():
    summary = rss_dryrun(
        edges=1_000_000, n_processes=2, n_devices=8, max_ratio=0.6
    )
    assert summary["ratio"] <= 0.6
    # every worker owns a proper shard subset (disjoint on the 1×8 mesh)
    assert summary["n_processes"] == 2

"""Schema parser + compiler tests.

The anchor spec is the reference integration-test schema
(client/client_test.go:23-32); wider-language cases cover the operators,
userset/wildcard subjects, caveats, and validation errors."""

import pytest

from gochugaru_tpu import rel
from gochugaru_tpu.schema import (
    Arrow,
    Exclusion,
    Intersection,
    Nil,
    RelationRef,
    SchemaParseError,
    SchemaValidationError,
    Union,
    compile_schema,
    parse_schema,
)

EXAMPLE = """
definition user {}
definition document {
    relation writer: user
    relation reader: user

    permission edit = writer
    permission view = reader + edit
}
"""

FOLDERS = """
definition user {}
definition group {
    relation member: user | group#member
}
definition folder {
    relation parent: folder
    relation owner: user
    permission view = owner + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user | user:* | group#member
    relation banned: user
    permission view = (viewer + folder->view) - banned
}
"""


def test_parse_example_schema():
    s = parse_schema(EXAMPLE)
    assert set(s.definitions) == {"user", "document"}
    doc = s.definitions["document"]
    assert set(doc.relations) == {"writer", "reader"}
    assert set(doc.permissions) == {"edit", "view"}
    assert doc.permissions["edit"].expr == RelationRef("writer")
    assert doc.permissions["view"].expr == Union((RelationRef("reader"), RelationRef("edit")))


def test_parse_operators_and_arrow():
    s = parse_schema(FOLDERS)
    doc = s.definitions["document"]
    e = doc.permissions["view"].expr
    assert isinstance(e, Exclusion)
    assert e.base == Union((RelationRef("viewer"), Arrow("folder", "view")))
    assert e.subtracted == RelationRef("banned")
    grp = s.definitions["group"]
    allowed = grp.relations["member"].allowed
    assert [(a.type, a.relation, a.wildcard) for a in allowed] == [
        ("user", "", False),
        ("group", "member", False),
    ]
    viewer = doc.relations["viewer"].allowed
    assert any(a.wildcard and a.type == "user" for a in viewer)


def test_parse_intersection_and_nil():
    s = parse_schema(
        """
        definition user {}
        definition vault {
            relation manager: user
            relation auditor: user
            permission open = manager & auditor
            permission never = nil
        }
        """
    )
    v = s.definitions["vault"]
    assert v.permissions["open"].expr == Intersection(
        (RelationRef("manager"), RelationRef("auditor"))
    )
    assert v.permissions["never"].expr == Nil()


def test_parse_caveat_decl():
    s = parse_schema(
        """
        caveat only_on_tuesday(day string) {
            day == "tuesday"
        }
        definition user {}
        definition document {
            relation viewer: user with only_on_tuesday
        }
        """
    )
    c = s.caveats["only_on_tuesday"]
    assert c.params == {"day": "string"}
    assert c.expression == 'day == "tuesday"'
    a = s.definitions["document"].relations["viewer"].allowed[0]
    assert a.caveat == "only_on_tuesday"


def test_parse_expiration_trait():
    s = parse_schema(
        """
        use expiration
        definition user {}
        definition door {
            relation opener: user with expiration
        }
        """
    )
    a = s.definitions["door"].relations["opener"].allowed[0]
    assert a.expiration and not a.caveat


def test_parse_comments():
    s = parse_schema(
        """
        // a line comment
        definition user {} /* block
        comment */ definition thing { relation owner: user }
        """
    )
    assert set(s.definitions) == {"user", "thing"}


@pytest.mark.parametrize(
    "bad",
    [
        "definition {",  # missing name
        "definition d { relation r user }",  # missing colon
        "definition d { permission p = }",  # empty expr
        "definition d { relation r: user } definition d {}",  # dup definition
        "definition d { relation r: user permission r = r }",  # dup item
        "wat",
    ],
)
def test_parse_errors(bad):
    with pytest.raises(SchemaParseError):
        parse_schema(bad)


def test_chained_arrow_rejected():
    with pytest.raises(SchemaParseError):
        parse_schema(
            """
            definition a { relation b: a relation c: a permission p = b->c->p }
            """
        )


# -- compiler --------------------------------------------------------------


def test_compile_example():
    cs = compile_schema(parse_schema(EXAMPLE))
    assert set(cs.slot_of_name) == {"writer", "reader", "edit", "view"}
    assert not cs.is_recursive
    # view -> edit -> writer is the longest chain
    assert cs.depth == 2
    doc = cs.types[cs.type_id("document")]
    assert set(doc.relations) == {cs.slot("writer"), cs.slot("reader")}
    assert set(doc.permissions) == {cs.slot("edit"), cs.slot("view")}
    assert cs.tupleset_pairs == frozenset()


def test_compile_folders_recursion_and_tuplesets():
    cs = compile_schema(parse_schema(FOLDERS))
    assert cs.is_recursive  # group#member nests; folder view recurses via parent
    assert (cs.type_id("folder"), cs.slot("parent")) in cs.tupleset_pairs
    assert (cs.type_id("document"), cs.slot("folder")) in cs.tupleset_pairs
    assert cs.slot("parent") in cs.tupleset_slots


def test_compile_validation_errors():
    with pytest.raises(SchemaValidationError):
        compile_schema(parse_schema("definition d { relation r: ghost }"))
    with pytest.raises(SchemaValidationError):
        compile_schema(
            parse_schema("definition u {} definition d { permission p = missing }")
        )
    with pytest.raises(SchemaValidationError):
        compile_schema(
            parse_schema(
                "definition u {} definition d { relation r: u permission p = p2->x }"
            )
        )
    with pytest.raises(SchemaValidationError):
        # arrow LHS is a permission
        compile_schema(
            parse_schema(
                """
                definition u { relation boss: u permission admin = boss }
                definition d {
                    relation owner: u
                    permission p = owner
                    permission q = p->admin
                }
                """
            )
        )
    with pytest.raises(SchemaValidationError):
        # unknown caveat
        compile_schema(
            parse_schema("definition u {} definition d { relation r: u with ghost }")
        )


def test_validate_relationship():
    cs = compile_schema(parse_schema(FOLDERS))
    cs.validate_relationship(rel.must_from_triple("document:d1", "viewer", "user:u1"))
    cs.validate_relationship(rel.must_from_tuple("document:d1#viewer", "group:g#member"))
    cs.validate_relationship(rel.must_from_triple("document:d1", "viewer", "user:*"))

    with pytest.raises(SchemaValidationError):  # unknown resource type
        cs.validate_relationship(rel.must_from_triple("ghost:x", "viewer", "user:u"))
    with pytest.raises(SchemaValidationError):  # write to a permission
        cs.validate_relationship(rel.must_from_triple("document:d", "view", "user:u"))
    with pytest.raises(SchemaValidationError):  # subject type not allowed
        cs.validate_relationship(rel.must_from_triple("document:d", "banned", "group:g"))
    with pytest.raises(SchemaValidationError):  # wildcard not allowed here
        cs.validate_relationship(rel.must_from_triple("document:d", "banned", "user:*"))
    with pytest.raises(SchemaValidationError):  # userset relation not allowed
        cs.validate_relationship(
            rel.must_from_tuple("document:d#viewer", "group:g#ghost")
        )


def test_validate_caveated_relationship():
    cs = compile_schema(
        parse_schema(
            """
            caveat tuesday(day string) { day == "tuesday" }
            definition user {}
            definition document {
                relation viewer: user with tuesday
                relation editor: user
            }
            """
        )
    )
    cs.validate_relationship(
        rel.must_from_triple("document:d", "viewer", "user:u").with_caveat("tuesday", {})
    )
    with pytest.raises(SchemaValidationError):  # caveat required but missing
        cs.validate_relationship(rel.must_from_triple("document:d", "viewer", "user:u"))
    with pytest.raises(SchemaValidationError):  # caveat not accepted
        cs.validate_relationship(
            rel.must_from_triple("document:d", "editor", "user:u").with_caveat("tuesday", {})
        )


def test_permission_userset_flag():
    cs = compile_schema(
        parse_schema(
            """
            definition user {}
            definition team {
                relation lead: user
                permission manage = lead
            }
            definition doc { relation approver: team#manage }
            """
        )
    )
    assert cs.has_permission_usersets

"""Tracing-overhead budget smoke (CPU proxy): 100%-sampled tracing must
add <5% to the warm small-batch p99 vs tracing disabled.

The zero-cost-when-disabled contract is asserted structurally in
test_trace.py (identity NOOP, zero span allocations); this test bounds
the cost of tracing when it is ON — a 100%-sampled request pays a root
span, the dispatch child, four stage spans, and ring retention.
Measured through ``benchmarks.common.small_batch_latency``, the SAME
harness that produced the PR-3 5.2 ms baseline row, whose per-rep span
rooting mirrors client.check exactly.

Estimator: tracing cost is a UNIFORM per-rep shift (span bookkeeping
runs on every rep; the residual GC pressure is ~110 µs amortized over
~75 reps — measured to land well below the p99 level, not at it).  A
uniform shift of δ moves every quantile, p99 included, by δ — so the
budget "p99_on ≤ 1.05 × p99_off" holds iff δ ≤ 0.05 × p99_off.  δ is
estimated as the off/on median difference with the tracer flipped
in/out PER REP (``interleave_tracer``): adjacent reps see the same
host conditions, pairing the scheduler noise away.  Direct p99-vs-p99
A/B was tried first and cannot resolve 5% on a shared 2-core box — the
window-p99 estimator alone swings ±20% between identical runs.  The
p90 delta rides along as a tail-shape guard (it would catch a cost
that only bites above the median, e.g. a per-ring-eviction stall) with
the same allowance; both deltas come from one interleaved stream."""

import numpy as np
import pytest

from benchmarks.common import small_batch_latency
from gochugaru_tpu.utils import trace

from test_latency_path import build_rbac_world

B = 256
REPS = 2000  # 1000 per mode, interleaved
BUDGET = 0.05


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


def test_tracing_enabled_overhead_under_5pct():
    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    rng = np.random.default_rng(11)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)

    tracer = trace.Tracer(sample_rate=1.0, slow_threshold_s=None, capacity=256)
    # the flight recorder is part of the always-on serving configuration
    # (with_telemetry installs it), so the <5% budget must cover tracing
    # AND recorder retention together: on-reps pay span bookkeeping plus
    # the flight-ring append, off-reps are the true NOOP path (the
    # recorder does nothing without a tracer installed)
    recorder = trace.install_recorder(trace.FlightRecorder(capacity=64))
    r = small_batch_latency(
        engine, dsnap, q_res, q_perm, q_subj,
        warmup=40, reps=REPS, interleave_tracer=tracer,
    )

    # the on-reps really sampled (guard against measuring noop-vs-noop)
    assert len(tracer.traces()) == tracer._ring.maxlen
    # ... and really retained by the flight ring
    assert len(recorder.traces()) == recorder.capacity

    allowance = BUDGET * r["p99_ms_off"]
    assert r["delta_p50_ms"] <= allowance, (
        f"tracing's uniform per-request cost breaks the 5% p99 budget: "
        f"median shift {r['delta_p50_ms']:.3f} ms > "
        f"0.05 x p99_off {r['p99_ms_off']:.3f} ms ({r})"
    )
    assert r["delta_p90_ms"] <= allowance, (
        f"tracing cost is tail-shaped beyond the 5% p99 budget: "
        f"p90 shift {r['delta_p90_ms']:.3f} ms > "
        f"0.05 x p99_off {r['p99_ms_off']:.3f} ms ({r})"
    )


def test_witness_armed_overhead_under_budget():
    """Decision-provenance extension of the same harness: witness
    extraction (engine/flat.py armed kernel, the explain seed's source)
    flipped per REP via the generic ``interleave`` hook.  The armed
    kernel reuses masks the probe pipeline computes anyway plus a select
    cascade and one extra [B] output — its median shift must fit the
    same 5% budget the tracer does.  The disarmed-mode reps double as
    the no-retrace witness: both modes are pre-warmed, so any compile
    inside the window is a pin leak."""
    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, repos, slot = build_rbac_world()
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    rng = np.random.default_rng(12)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)

    lp = engine.latency_path(dsnap)
    # pre-warm BOTH modes so the interleaved window never compiles
    for armed in (True, False):
        lp.arm_witness(armed)
        for i in range(10):
            lp.dispatch_columns(np.roll(q_res, i), q_perm, q_subj)
    lp.arm_witness(False)
    compiles_before = lp.compile_count
    r = small_batch_latency(
        engine, dsnap, q_res, q_perm, q_subj,
        warmup=30, reps=REPS,
        interleave=(lp.arm_witness, lambda: lp.arm_witness(False)),
    )
    assert lp.compile_count == compiles_before, (
        "witness arm/disarm retraced inside the warm window"
    )
    assert lp.witness_armed is False  # interleave leaves the toggle off
    allowance = BUDGET * r["p99_ms_off"]
    assert r["delta_p50_ms"] <= allowance, (
        f"armed witness extraction breaks the 5% budget: "
        f"median shift {r['delta_p50_ms']:.3f} ms > "
        f"0.05 x p99_off {r['p99_ms_off']:.3f} ms ({r})"
    )

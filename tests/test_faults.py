"""Fault-injection registry (utils/faults.py) and admission control
(utils/admission.py): unit behavior plus the client-level wiring —
injected transient faults engage the real retry envelope, the in-flight
gate sheds with ShedError, the deadline budget sheds before dispatch,
the circuit breaker reroutes latency-mode traffic, and the watch stream
resumes from its cursor with exactly-once delivery."""

import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_latency_mode,
    with_store,
)
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils.admission import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    AdmissionConfig,
    AdmissionController,
    CircuitBreaker,
    DispatchGate,
)
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    DeadlineExceededError,
    ShedError,
    UnavailableError,
    classify_dispatch_exception,
)

SCHEMA = """
definition user {}
definition team { relation member: user }
definition doc {
    relation owner: user
    relation reader: user | team#member
    permission read = reader + owner
}
"""


def _client(*opts):
    c = new_tpu_evaluator(*opts)
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "owner", "user:u1"))
    txn.touch(rel.must_from_triple("doc:a", "reader", "user:u2"))
    txn.touch(rel.must_from_triple("team:t1", "member", "user:u3"))
    txn.touch(rel.must_from_tuple("doc:b#reader", "team:t1#member"))
    c.write(ctx, txn)
    return c


CHECKS = [
    rel.must_from_triple("doc:a", "read", "user:u1"),
    rel.must_from_triple("doc:a", "read", "user:u2"),
    rel.must_from_triple("doc:b", "read", "user:u3"),
    rel.must_from_triple("doc:b", "read", "user:u2"),
]
EXPECT = [True, True, True, False]


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------


def test_registry_policies_deterministic():
    reg = faults.FaultRegistry(_metrics.Metrics())
    # probability draws come from a per-spec seeded RNG: same seed, same
    # firing pattern
    def pattern(seed):
        spec = reg.arm("x", probability=0.5, seed=seed)
        out = []
        for _ in range(32):
            try:
                reg.maybe_fire("x")
                out.append(False)
            except UnavailableError:
                out.append(True)
        reg.disarm("x")
        return out

    assert pattern(7) == pattern(7)
    assert pattern(7) != pattern(8)  # overwhelmingly likely for 32 draws


def test_registry_times_and_after():
    reg = faults.FaultRegistry(_metrics.Metrics())
    spec = reg.arm("y", times=2, after=1)
    fired = 0
    for _ in range(6):
        try:
            reg.maybe_fire("y")
        except UnavailableError:
            fired += 1
    assert fired == 2  # hit 1 skipped (after=1); hits 2,3 fire; then spent
    assert spec.hits == 6 and spec.fired == 2


def test_module_fire_is_noop_when_disarmed():
    faults.reset()
    faults.fire("device.dispatch")  # must not raise
    with faults.armed("device.dispatch", times=1):
        with pytest.raises(UnavailableError):
            faults.fire("device.dispatch")
        faults.fire("device.dispatch")  # one-shot spent
    faults.fire("device.dispatch")  # disarmed again


def test_custom_error_factory():
    with faults.armed("z", error=RuntimeError("RESOURCE_EXHAUSTED: injected")):
        with pytest.raises(RuntimeError) as ei:
            faults.fire("z")
    assert classify_dispatch_exception(ei.value).__class__ is UnavailableError


# ---------------------------------------------------------------------------
# injected faults engage the real retry envelope, end to end
# ---------------------------------------------------------------------------


def test_injected_dispatch_fault_is_retried_transparently():
    c = _client()
    ctx = background()
    m = _metrics.default
    before = m.counter("faults.injected.device.dispatch")
    with faults.armed("device.dispatch", times=2) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert spec.fired == 2
    assert m.counter("faults.injected.device.dispatch") == before + 2


def test_injected_prepare_build_fault_is_retried_transparently():
    # the staged first-prepare pipeline (engine/flat.py build_flat_arrays)
    # is on the dispatch path for a fresh snapshot: a transient fault
    # there must classify + retry inside the client envelope, exactly
    # like the round-7 dispatch sites
    c = _client()
    ctx = background()
    m = _metrics.default
    before = m.counter("faults.injected.prepare.build")
    with faults.armed("prepare.build", times=1) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert spec.fired == 1
    assert m.counter("faults.injected.prepare.build") == before + 1


def test_injected_partition_fault_is_retried_transparently():
    # the partition-first prepare (engine/partition.py; fired from the
    # sharded builder's partition phase) sits on the dispatch path of a
    # mesh-backed client: a transient fault there must classify + retry
    # inside the same envelope as prepare.build
    from gochugaru_tpu.client import with_mesh
    from gochugaru_tpu.parallel import make_mesh

    c = _client(with_mesh(make_mesh(1, 2)))
    ctx = background()
    m = _metrics.default
    before = m.counter("faults.injected.prepare.partition")
    with faults.armed("prepare.partition", times=1) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert spec.fired == 1
    assert m.counter("faults.injected.prepare.partition") == before + 1


def test_injected_snapshot_fault_is_retried_transparently():
    c = _client()
    ctx = background()
    with faults.armed("store.snapshot_for", times=1) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert spec.fired == 1


def test_persistent_fault_surfaces_classified_not_hung():
    c = _client()
    ctx = background().with_timeout(1.5)
    t0 = time.monotonic()
    with faults.armed("device.dispatch"):
        with pytest.raises(DeadlineExceededError):
            c.check(ctx, consistency.full(), *CHECKS)
    assert time.monotonic() - t0 < 3.0  # bounded by the context, no hang


def test_latency_site_fault_retries_through_client():
    """A transient fault inside the latency path retries under the same
    envelope as the batch path (satellite: no unwrapped escape)."""
    c = _client(with_latency_mode())
    ctx = background()
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT  # warm
    with faults.armed("latency.dispatch", times=1) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert spec.fired == 1


def test_check_columns_latency_classifies_and_retries():
    """DeviceEngine.check_columns_latency (the bench/test columnar entry)
    classifies raw transient errors and retries them bounded."""
    import numpy as np

    c = _client(with_latency_mode())
    ctx = background()
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT  # build engine
    snap = c.store.snapshot_for(consistency.full())
    engine = c._engine_for(snap)
    dsnap = c._dsnap_for(engine, snap)
    interner = snap.interner
    slot = snap.compiled.slot_of_name
    q_res = np.array([interner.lookup("doc", "a")], np.int32)
    q_perm = np.array([slot["read"]], np.int32)
    q_subj = np.array([interner.lookup("user", "u1")], np.int32)

    # transient RAW error (not AuthzError) → classified → retried → result
    with faults.armed(
        "latency.dispatch", times=1,
        error=RuntimeError("UNAVAILABLE: injected backend hiccup"),
    ) as spec:
        d, p, ovf = engine.check_columns_latency(dsnap, q_res, q_perm, q_subj)
    assert spec.fired == 1
    assert bool(d[0])

    # persistent transient error → bounded tries, classified surfacing
    with faults.armed(
        "latency.dispatch",
        error=RuntimeError("UNAVAILABLE: injected backend hiccup"),
    ) as spec:
        with pytest.raises(UnavailableError):
            engine.check_columns_latency(dsnap, q_res, q_perm, q_subj)
    assert spec.fired == engine.LATENCY_RETRY_TRIES


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_gate_sheds_when_full():
    m = _metrics.Metrics()
    gate = DispatchGate(2, registry=m)
    with gate.admit():
        with gate.admit():
            assert gate.inflight == 2
            with pytest.raises(ShedError):
                with gate.admit():
                    pass
    assert gate.inflight == 0
    assert m.counter("admission.sheds") == 1


def test_gate_shed_engages_retry_envelope():
    """A shed during concurrent load is retried by the envelope: the
    caller sees a slow success, not an error."""
    c = _client(
        with_admission_control(
            AdmissionConfig(max_inflight=1, breaker_threshold=0)
        )
    )
    ctx = background().with_timeout(10.0)
    # hold the gate from another thread through a slow store access
    release = threading.Event()
    entered = threading.Event()
    orig = c._store.snapshot_for

    def slow_snapshot_for(cs):
        entered.set()
        release.wait(2.0)
        return orig(cs)

    results = {}

    def holder():
        c._store.snapshot_for = slow_snapshot_for
        try:
            results["holder"] = c.check(ctx, consistency.full(), *CHECKS)
        finally:
            pass

    t = threading.Thread(target=holder)
    t.start()
    entered.wait(2.0)
    c._store.snapshot_for = orig  # the second caller is fast
    m = _metrics.default
    sheds_before = m.counter("admission.sheds")
    release.set()  # holder finishes while the retry backs off
    results["main"] = c.check(ctx, consistency.full(), *CHECKS)
    t.join(5.0)
    assert results["main"] == EXPECT
    assert results["holder"] == EXPECT


def test_deadline_shed_before_dispatch():
    c = _client(
        with_admission_control(
            AdmissionConfig(deadline_floor_s=5.0, breaker_threshold=0)
        )
    )
    m = _metrics.default
    before = m.counter("admission.deadline_sheds")
    ctx = background().with_timeout(0.3)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError):
        c.check(ctx, consistency.full(), *CHECKS)
    # shed immediately (pre-dispatch), then the envelope waits out the
    # (short) deadline — never 5 s of dispatch work
    assert time.monotonic() - t0 < 2.0
    assert m.counter("admission.deadline_sheds") >= before + 1


def test_breaker_state_machine():
    m = _metrics.Metrics()
    clock = {"t": 0.0}
    br = CircuitBreaker(3, 1.0, registry=m, clock=lambda: clock["t"])
    assert br.state == CLOSED and br.allow_latency()
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED  # below threshold
    br.record_failure()
    assert br.state == OPEN
    assert m.counter("breaker.trips") == 1
    assert not br.allow_latency()  # cooldown not elapsed
    clock["t"] = 1.1
    assert br.allow_latency()  # half-open probe admitted
    assert br.state == HALF_OPEN
    assert m.counter("breaker.half_opens") == 1
    br.record_failure()  # failed probe
    assert br.state == OPEN and m.counter("breaker.trips") == 2
    clock["t"] = 2.3
    assert br.allow_latency()
    br.record_success(probe=False)  # batch-path success: stays half-open
    assert br.state == HALF_OPEN
    br.record_success(probe=True)  # successful latency probe closes it
    assert br.state == CLOSED
    assert m.counter("breaker.closes") == 1
    assert m.gauge("breaker.state") == CLOSED


def test_breaker_reroutes_latency_traffic_to_batch_path():
    """Consecutive transient dispatch failures trip the breaker; while
    open, latency-mode checks run on the batch path (no latency
    dispatches), and a half-open probe closes it again."""
    c = _client(
        with_latency_mode(),
        with_admission_control(
            # cooldown far beyond anything the test's own dispatches can
            # take (XLA compiles vary with cache state); the half-open
            # transition is driven deterministically by back-dating the
            # trip time below, never by sleeping
            AdmissionConfig(breaker_threshold=2, breaker_cooldown_s=60.0)
        ),
    )
    ctx = background()
    m = _metrics.default
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT  # warm pins

    # two consecutive transient failures trip the breaker; the envelope
    # retries through and succeeds on the batch path
    trips_before = m.counter("breaker.trips")
    with faults.armed("device.dispatch", times=2):
        assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert m.counter("breaker.trips") == trips_before + 1
    assert c._admission.breaker.state == OPEN

    # while OPEN: latency traffic rerouted (latency.dispatches flat)
    lat_before = m.counter("latency.dispatches")
    rerouted_before = m.counter("breaker.latency_rerouted")
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert m.counter("latency.dispatches") == lat_before
    assert m.counter("breaker.latency_rerouted") == rerouted_before + 1

    # "after the cooldown": back-date the trip so the next dispatch is
    # the half-open probe — it uses the latency path again and closes
    # the breaker
    c._admission.breaker._opened_at -= 61.0
    closes_before = m.counter("breaker.closes")
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert c._admission.breaker.state == CLOSED
    assert m.counter("breaker.closes") == closes_before + 1
    assert m.counter("latency.dispatches") > lat_before


def test_breaker_probe_must_actually_run_latency_path():
    """A half-open probe whose batch silently falls back to the batch
    path (beyond the top latency tier) must NOT close the breaker — only
    a dispatch the latency path actually served counts as a probe."""
    c = _client(
        with_latency_mode(),
        with_admission_control(
            AdmissionConfig(breaker_threshold=2, breaker_cooldown_s=60.0)
        ),
    )
    ctx = background()
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT  # warm
    with faults.armed("device.dispatch", times=2):
        c.check(ctx, consistency.full(), *CHECKS)
    assert c._admission.breaker.state == OPEN
    # back-date the trip: cooldown "elapsed", next dispatch is the probe
    c._admission.breaker._opened_at -= 61.0
    top_tier = max(c._engine.config.latency_tiers)
    big = [CHECKS[i % len(CHECKS)] for i in range(top_tier + 1)]
    assert c.check(ctx, consistency.full(), *big) == [
        EXPECT[i % len(EXPECT)] for i in range(top_tier + 1)
    ]
    # the oversized probe fell back to the batch path: still half-open
    assert c._admission.breaker.state == HALF_OPEN
    # a tier-served batch is a real probe and closes it
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    assert c._admission.breaker.state == CLOSED


# ---------------------------------------------------------------------------
# watch resume-on-fault
# ---------------------------------------------------------------------------


def _collect_watch(c, ctx, n_expected, timeout_s=10.0):
    got = []
    done = threading.Event()
    # subscribe ON THIS THREAD before any test write: c.updates captures
    # its head-revision cursor at CALL time, so calling it inside the
    # consumer thread races the caller's writes — a write landing before
    # the subscription is (correctly) never delivered and the consumer
    # waits forever.  The GIL makes the race outcome hinge on scheduling
    # phase, i.e. on unrelated code elsewhere in the suite.
    stream = c.updates(ctx, rel.UpdateFilter())

    def consume():
        try:
            for u in stream:
                got.append(u)
                if len(got) >= n_expected:
                    break
        finally:
            done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    return got, done, t


def test_watch_resumes_from_cursor_exactly_once():
    c = _client()
    ctx = background().with_cancel()
    m = _metrics.default
    resumes_before = m.counter("watch.resumes")

    # every 3rd delivery faults: the stream must resume from its cursor
    # and deliver each event exactly once, in order
    faults.arm("watch.stream", probability=1.0, seed=3, after=2, times=1)
    expected = []
    got, done, t = _collect_watch(c, ctx, 9)
    for i in range(3):
        txn = rel.Txn()
        for j in range(3):
            r = rel.must_from_triple(f"doc:w{i}", "reader", f"user:wu{j}")
            txn.touch(r)
            expected.append(("TOUCH", r.resource_id, r.subject_id))
        c.write(background(), txn)
        # re-arm a fresh one-shot mid-stream fault for the next burst
        faults.arm("watch.stream", after=1, times=1, seed=i)
    assert done.wait(10.0), "watch consumer hung"
    ctx.cancel()
    t.join(2.0)
    assert [
        (u.update_type.name, u.relationship.resource_id, u.relationship.subject_id)
        for u in got
    ] == expected
    assert m.counter("watch.resumes") > resumes_before


def test_watch_persistent_fault_surfaces_bounded():
    """A permanently-broken stream classifies as UnavailableError after
    WATCH_MAX_RESUMES no-progress attempts — never a hang."""
    c = _client()
    ctx = background().with_cancel()
    faults.arm("watch.stream")  # every delivery faults, forever
    err = {}
    done = threading.Event()
    # subscribe before the write (same cursor-capture race as
    # _collect_watch: the head cursor is taken when c.updates is CALLED)
    stream = c.updates(ctx, rel.UpdateFilter())

    def consume():
        try:
            for _u in stream:
                pass
        except UnavailableError as e:
            err["e"] = e
        finally:
            done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:x", "reader", "user:ux"))
    c.write(background(), txn)
    assert done.wait(15.0), "watch consumer hung on persistent fault"
    ctx.cancel()
    t.join(2.0)
    assert isinstance(err.get("e"), UnavailableError)


# ---------------------------------------------------------------------------
# sharded-engine injection sites
# ---------------------------------------------------------------------------


def test_sharded_sites_fire():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import numpy as np

    from gochugaru_tpu.parallel import ShardedEngine, make_mesh
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rels = [
        rel.must_from_triple("doc:a", "owner", "user:u1"),
        rel.must_from_triple("doc:a", "reader", "user:u2"),
    ]
    snap = build_snapshot(1, cs, interner, rels, epoch_us=1_700_000_000_000_000)
    eng = ShardedEngine(cs, make_mesh(4, 2))
    dsnap = eng.prepare(snap)
    queries = [rel.must_from_triple("doc:a", "read", "user:u1")]
    d, _, _ = eng.check_batch(dsnap, queries, now_us=1_700_000_000_000_000)
    assert bool(d[0])
    with faults.armed("sharded.dispatch", times=1) as spec:
        with pytest.raises(UnavailableError):
            eng.check_batch(dsnap, queries, now_us=1_700_000_000_000_000)
    assert spec.fired == 1
    with faults.armed("sharded.collective", times=1) as spec:
        with pytest.raises(UnavailableError):
            eng.check_batch(dsnap, queries, now_us=1_700_000_000_000_000)
    assert spec.fired == 1


def test_injected_closure_delta_fault_is_retried_transparently():
    """One transient during the incremental closure advance (the
    membership-delta merge) must retry under the client envelope and land
    on a CONSISTENT advanced closure — advance_closure is pure (no state
    mutation before success), so the retry re-runs it from scratch."""
    c = _client()
    ctx = background()
    assert c.check(ctx, consistency.full(), *CHECKS) == EXPECT
    # a member-edge write: the next prepare advances the closure in place
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("team:t1", "member", "user:u2"))
    c.write(ctx, txn)
    applies0 = _metrics.default.counter("closure.delta_applies")
    rebuilds0 = _metrics.default.counter("closure.rebuilds")
    with faults.armed("closure.delta", times=1) as spec:
        assert c.check(ctx, consistency.full(), *CHECKS) == [
            True, True, True, True,  # u2 now reaches doc:b via t1#member
        ]
    assert spec.fired == 1
    # the retried advance applied exactly once and nothing rebuilt
    assert _metrics.default.counter("closure.delta_applies") == applies0 + 1
    assert _metrics.default.counter("closure.rebuilds") == rebuilds0

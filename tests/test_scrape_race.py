"""Concurrent scrape-under-load (the PR's race satellite): a /metrics +
/traces + /slo + /healthz scrape loop racing a serving-burst stand-in
(ThreadingHTTPServer handlers vs. hot ``observe_hist``/``observe``/
``inc`` writers and root-span churn), asserting

- no exporter exceptions (every response 200 and parseable),
- no TORN histogram rows: within one scrape the cumulative ``le``
  series is nondecreasing and the +Inf bucket equals ``_count`` — a
  render that read counts mid-update would violate one of the two,
- monotone cumulative buckets ACROSS scrapes (a cumulative series that
  ever decreases would poison any rate() computed over it).
"""

import json
import random
import re
import threading
import urllib.request

import pytest

from gochugaru_tpu.utils import trace
from gochugaru_tpu.utils.metrics import Metrics
from gochugaru_tpu.utils.slo import SLOEngine, latency_slo, ratio_slo
from gochugaru_tpu.utils.telemetry import TelemetryServer

BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1)
_BUCKET_RE = re.compile(
    r'^gochugaru_serve_request_latency_bucket\{le="([^"]+)"\} (\d+)'
)
_COUNT_RE = re.compile(r"^gochugaru_serve_request_latency_count (\d+)$")


@pytest.fixture(autouse=True)
def _trace_hygiene():
    trace.disable()
    yield
    trace.disable()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _parse_hist(text):
    """(ordered [(le, cum)], count) for serve.request_latency."""
    rows, count = [], None
    for ln in text.splitlines():
        mb = _BUCKET_RE.match(ln)
        if mb:
            rows.append((mb.group(1), int(mb.group(2))))
            continue
        mc = _COUNT_RE.match(ln)
        if mc:
            count = int(mc.group(1))
    return rows, count


def test_concurrent_scrape_under_serving_burst():
    m = Metrics()
    trace.configure(sample_rate=1.0, slow_threshold_s=None, capacity=64,
                    registry=m)
    rec = trace.install_recorder(trace.FlightRecorder(registry=m))
    slo = SLOEngine(
        slos=[
            latency_slo("req", "serve.request_s", objective_ms=20.0),
            ratio_slo("shed", bad=("serve.sheds",),
                      total=("serve.submissions",), budget=0.05),
        ],
        registry=m, tick_s=0.02, start=True,
    )
    srv = TelemetryServer(port=0, registry=m, slo=slo, recorder=rec)
    stop = threading.Event()
    writer_errors = []

    def writer(w):
        rng = random.Random(w)
        i = 0
        try:
            while not stop.is_set():
                v = rng.random() * 0.2
                m.observe_hist(
                    "serve.request_latency", v, BUCKETS,
                    trace_id=f"w{w}-{i}",
                )
                m.observe("serve.request_s", v)
                m.inc("serve.submissions")
                if i % 7 == 0:
                    m.inc("serve.sheds")
                sp = trace.root_span("serve.check", batch=4)
                sp.event("formed", i=i)
                sp.end()
                i += 1
        except Exception as e:  # pragma: no cover - the failure signal
            writer_errors.append(e)

    writers = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(4)]
    for t in writers:
        t.start()

    prev_by_le: dict = {}
    prev_count = 0
    scrapes = 0
    try:
        # ~60 scrape rounds racing the writers, alternating dialects
        for round_i in range(60):
            om = round_i % 2 == 1
            code, body = _get(
                srv.url + "/metrics" + ("?openmetrics=1" if om else "")
            )
            assert code == 200
            if om:
                assert body.rstrip().endswith("# EOF")
            rows, count = _parse_hist(body)
            if rows:
                scrapes += 1
                assert count is not None, "bucket rows without _count"
                # within-scrape integrity: cumulative nondecreasing,
                # +Inf == _count (a torn read breaks one of these)
                cums = [c for _le, c in rows]
                assert cums == sorted(cums), f"non-monotone le series: {rows}"
                assert rows[-1][0] == "+Inf" and rows[-1][1] == count, (
                    rows[-1], count,
                )
                # across-scrape monotonicity per bucket
                for le, c in rows:
                    assert c >= prev_by_le.get(le, 0), (
                        f"bucket le={le} went backwards"
                    )
                    prev_by_le[le] = c
                assert count >= prev_count
                prev_count = count
            code, body = _get(srv.url + "/traces")
            assert code == 200
            for ln in body.splitlines():
                json.loads(ln)  # every line parses
            code, body = _get(srv.url + "/slo")
            assert code == 200
            rep = json.loads(body)
            assert rep["enabled"] and len(rep["slos"]) == 2
            code, body = _get(srv.url + "/healthz")
            assert code == 200
            hz = json.loads(body)
            assert hz["status"] in ("ok", "degraded")
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=10)
        slo.close()
        srv.close()

    assert not writer_errors, writer_errors
    assert scrapes >= 50, "the burst never overlapped the scrape loop"
    assert prev_count > 0
    # and the OpenMetrics dialect carried exemplars for the hot buckets
    from gochugaru_tpu.utils.telemetry import render_prometheus

    assert "# {trace_id=" in render_prometheus(m, openmetrics=True)

"""Permission-valued userset subjects on device (VERDICT round-1 item 4).

The reference's data model makes userset subjects first-class
(rel/relationship.go:35-37), including subjects whose relation is a
*permission* (``relation shared: document#view``).  Round 1 evicted the
entire schema to the host oracle when one appeared; now the device marks
grants through them possible-not-definite (us_perm flag), and relation
usersets transitively fed by permission chains (the static pus pair set)
likewise, so only the affected *queries* fall back — everything else
stays device-definite.

Contract under test: device definite ⇒ oracle T; oracle T ⇒ device
possible (no silent misses); unaffected queries stay definite."""

import numpy as np

from gochugaru_tpu import consistency, new_tpu_evaluator, rel
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import Oracle, T
from gochugaru_tpu.rel.txn import Txn
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils import background
from gochugaru_tpu.utils.metrics import default as metrics

NOW = 1_700_000_000_000_000

SHARED = """
definition user {}
definition document {
    relation viewer: user
    relation shared: document#view
    permission view = viewer + shared
}
"""


def world(schema, rels):
    cs = compile_schema(parse_schema(schema))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    oracle = Oracle(cs, rels, now_us=NOW)
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    return cs, engine, dsnap, oracle


def brackets(engine, dsnap, oracle, checks):
    """Device planes must bracket the oracle: d ⇒ T, T ⇒ p."""
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        if d[i]:
            assert want == T, f"wrong device-definite on {q}"
        if want == T and not ovf[i]:
            assert p[i], f"device missed possible grant on {q}"
    return d, p, ovf


def test_direct_permission_userset_subject():
    rels = [
        rel.must_from_triple("document:a", "viewer", "user:u"),
        rel.must_from_tuple("document:b#shared", "document:a#view"),
    ]
    cs, engine, dsnap, oracle = world(SHARED, rels)
    assert cs.has_permission_usersets
    checks = [
        rel.must_from_triple("document:a", "view", "user:u"),   # direct: definite
        rel.must_from_triple("document:b", "view", "user:u"),   # via a#view: possible
        rel.must_from_triple("document:b", "view", "user:v"),   # no grant anywhere
        # symbolic userset subject: a#view definitively has shared on b
        rel.must_from_tuple("document:b#view", "document:a#view"),
    ]
    d, p, ovf = brackets(engine, dsnap, oracle, checks)
    assert bool(d[0]) and not ovf[0]            # unaffected query stays definite
    assert not d[1] and bool(p[1])              # permission chain → host fallback
    assert oracle.check_relationship(checks[1]) == T
    assert bool(d[3])                           # symbolic match is definite


PUS = """
definition user {}
definition team { relation member: user | document#view }
definition document {
    relation viewer: user | team#member
    permission view = viewer
}
"""


def test_relation_userset_fed_by_permission_chain():
    rels = [
        rel.must_from_triple("document:a", "viewer", "user:u"),
        rel.must_from_tuple("team:t#member", "document:a#view"),
        rel.must_from_tuple("document:b#viewer", "team:t#member"),
    ]
    _, engine, dsnap, oracle = world(PUS, rels)
    # the pus set contains (t, member): membership may flow through a#view
    snap = dsnap.snapshot
    assert snap.pus_n.shape[0] >= 1
    checks = [
        rel.must_from_triple("document:a", "view", "user:u"),
        rel.must_from_triple("document:b", "view", "user:u"),  # u ∈ t via a#view
        rel.must_from_triple("document:b", "view", "user:v"),  # not granted
    ]
    d, p, ovf = brackets(engine, dsnap, oracle, checks)
    assert bool(d[0])
    assert not d[1] and bool(p[1])  # possible via pus → host resolves True
    assert oracle.check_relationship(checks[1]) == T
    assert oracle.check_relationship(checks[2]) != T


def test_transitive_pus_through_nested_teams():
    schema = """
    definition user {}
    definition team { relation member: user | team#member | document#view }
    definition document {
        relation viewer: user | team#member
        permission view = viewer
    }
    """
    rels = [
        rel.must_from_triple("document:a", "viewer", "user:u"),
        rel.must_from_tuple("team:t1#member", "document:a#view"),
        rel.must_from_tuple("team:t2#member", "team:t1#member"),
        rel.must_from_tuple("document:b#viewer", "team:t2#member"),
    ]
    _, engine, dsnap, oracle = world(schema, rels)
    snap = dsnap.snapshot
    pus = set(zip(snap.pus_n.tolist(), snap.pus_r.tolist()))
    assert len(pus) >= 2  # (t1, member) and (t2, member)
    q = rel.must_from_triple("document:b", "view", "user:u")
    d, p, ovf = brackets(engine, dsnap, oracle, [q])
    assert not d[0] and bool(p[0])
    assert oracle.check_relationship(q) == T


def test_client_keeps_device_engine_for_permission_userset_schema():
    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, SHARED)
    txn = Txn()
    txn.create(rel.must_from_triple("document:a", "viewer", "user:u"))
    txn.create(rel.must_from_tuple("document:b#shared", "document:a#view"))
    for i in range(6):
        txn.create(rel.must_from_triple(f"document:d{i}", "viewer", f"user:w{i}"))
    rev = c.write(ctx, txn)
    strat = consistency.at_least(rev)
    snap = c.store.snapshot_for(strat)
    assert c._engine_for(snap) is not None  # no whole-schema eviction

    base_dev = metrics.counter("checks.device_definite")
    base_fb = metrics.counter("checks.fallback_conditional")
    # unaffected batch: all device-definite, no fallback
    assert c.check(
        ctx, strat,
        *[rel.must_from_triple(f"document:d{i}", "view", f"user:w{i}") for i in range(6)],
    ) == [True] * 6
    assert metrics.counter("checks.device_definite") == base_dev + 6
    assert metrics.counter("checks.fallback_conditional") == base_fb
    # affected queries resolve correctly through the per-query fallback
    assert c.check_one(ctx, strat, rel.must_from_triple("document:b", "view", "user:u"))
    assert not c.check_one(
        ctx, strat, rel.must_from_triple("document:b", "view", "user:nope")
    )
    assert metrics.counter("checks.fallback_conditional") > base_fb


def test_sharded_permission_usersets():
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    rels = [
        rel.must_from_triple("document:a", "viewer", "user:u"),
        rel.must_from_tuple("document:b#shared", "document:a#view"),
        rel.must_from_triple("document:c", "viewer", "user:v"),
    ]
    cs = compile_schema(parse_schema(SHARED))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    oracle = Oracle(cs, rels, now_us=NOW)
    mesh = make_mesh(2, 4)
    engine = ShardedEngine(cs, mesh)
    dsnap = engine.prepare(snap)
    checks = [
        rel.must_from_triple("document:a", "view", "user:u"),
        rel.must_from_triple("document:b", "view", "user:u"),
        rel.must_from_triple("document:c", "view", "user:v"),
        rel.must_from_triple("document:c", "view", "user:u"),
    ]
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    assert list(d) == [True, False, True, False]
    assert bool(p[1])  # the permission-chain grant surfaces as possible
    for i, q in enumerate(checks):
        if d[i]:
            assert oracle.check_relationship(q) == T


def test_lookup_resources_with_permission_usersets_via_client():
    """Lookups on permission-userset schemas: device candidates route the
    conditional slice through the oracle-backed overflow path or the
    client's host scan — results must equal the oracle exactly."""
    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, SHARED)
    txn = Txn()
    txn.create(rel.must_from_triple("document:a", "viewer", "user:u"))
    txn.create(rel.must_from_tuple("document:b#shared", "document:a#view"))
    rev = c.write(ctx, txn)
    strat = consistency.at_least(rev)
    got = sorted(c.lookup_resources(ctx, strat, "document#view", "user:u"))
    snap = c.store.snapshot_for(strat)
    oracle = c._oracle_for(snap)
    want = sorted(oracle.lookup_resources("document", "view", "user", "u", ""))
    assert got == want

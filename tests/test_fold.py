"""Permission-fold (engine/fold.py P-index) semantics.

Differential coverage for the folded root-probe path: deep nesting
(config-3 shape), slot-name collisions across types, expiry folding
along arrow paths, budget/eligibility fallbacks.  The walked kernel and
the host oracle pin the semantics (reference behavior:
/root/reference/client/client_test.go:151-186 transitive checks).
"""

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.engine.oracle import F, T

from test_device_engine import setup as _setup  # noqa: E402
from test_flat_engine import world  # noqa: E402

NOW = 1_700_000_000_000_000

DOCS = """
definition user {}
definition group { relation member: user | group#member }
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user | group#member
    permission view = viewer + folder->view
}
"""


def _docs_world(**cfg):
    rng = np.random.default_rng(9)
    rels = []
    # nested groups g0 ⊇ g1#member ⊇ g2#member …, users at leaves
    for i in range(7):
        if i % 4 != 3:
            rels.append(rel.must_from_tuple(f"group:g{i}#member", f"group:g{i+1}#member"))
        for u in rng.choice(24, 2, replace=False):
            rels.append(rel.must_from_tuple(f"group:g{i}#member", f"user:u{u}"))
    # folder forest, arity 3, depth ~3
    for i in range(1, 15):
        rels.append(rel.must_from_tuple(f"folder:f{i}#parent", f"folder:f{(i-1)//3}"))
    for i in range(15):
        if i % 2 == 0:
            rels.append(rel.must_from_tuple(
                f"folder:f{i}#viewer", f"group:g{int(rng.integers(7))}#member"
            ))
        else:
            rels.append(rel.must_from_tuple(
                f"folder:f{i}#viewer", f"user:u{int(rng.integers(24))}"
            ))
    for d in range(40):
        rels.append(rel.must_from_tuple(
            f"document:d{d}#folder", f"folder:f{int(rng.integers(15))}"
        ))
        if d % 3 == 0:
            rels.append(rel.must_from_tuple(
                f"document:d{d}#viewer", f"group:g{int(rng.integers(7))}#member"
            ))
    return world(DOCS, rels, **cfg)


def _assert_differential(engine, dsnap, oracle, checks):
    d, p, ovf = engine.check_batch(dsnap, checks, now_us=NOW)
    for i, q in enumerate(checks):
        want = oracle.check_relationship(q)
        assert not ovf[i], q
        assert bool(d[i]) == (want == T), q
        assert bool(p[i]) == (want != F), q


def test_fold_differential_docs_world():
    engine, dsnap, oracle = _docs_world()
    assert dsnap.flat_meta.fold_pairs, "docs schema should fold"
    checks = [
        rel.must_from_triple(f"document:d{d}", "view", f"user:u{u}")
        for d in range(40)
        for u in range(0, 24, 3)
    ] + [
        rel.must_from_triple(f"folder:f{f}", "view", f"user:u{u}")
        for f in range(15)
        for u in range(0, 24, 5)
    ]
    _assert_differential(engine, dsnap, oracle, checks)


def test_fold_matches_walked_kernel():
    folded = _docs_world()
    walked = _docs_world(flat_fold=False)
    assert not walked[1].flat_meta.fold_pairs
    checks = [
        rel.must_from_triple(f"document:d{d}", "view", f"user:u{u}")
        for d in range(40) for u in range(24)
    ]
    fd, fp, fo = folded[0].check_batch(folded[1], checks, now_us=NOW)
    wd, wp, wo = walked[0].check_batch(walked[1], checks, now_us=NOW)
    assert (np.asarray(fd) == np.asarray(wd)).all()
    assert (np.asarray(fp) == np.asarray(wp)).all()


SLOT_COLLIDE = """
definition user {}
definition folder {
    relation parent: folder
    relation viewer: user
    permission view = viewer + parent->view
}
definition document {
    relation parent: folder
    relation viewer: user
    relation banned: user
    permission view = viewer - banned
}
"""


def test_fold_slot_collision_no_leak_across_types():
    # `parent` is ONE slot on two types; document.view is an exclusion
    # (unfolded) that ignores document.parent entirely.  The folded
    # folder.view rows must not leak onto document nodes through the
    # slot-level ancestor closure
    rels = [
        rel.must_from_tuple("folder:root#viewer", "user:alice"),
        rel.must_from_tuple("folder:kid#parent", "folder:root"),
        rel.must_from_tuple("document:d#parent", "folder:kid"),
        rel.must_from_tuple("document:d#viewer", "user:bob"),
        rel.must_from_tuple("document:d2#parent", "folder:kid"),
        rel.must_from_tuple("document:d2#viewer", "user:bob"),
        rel.must_from_tuple("document:d2#banned", "user:bob"),
    ]
    engine, dsnap, oracle = world(SLOT_COLLIDE, rels)
    assert ("folder", dsnap.flat_meta.fold_pairs[0][1]) in dsnap.flat_meta.fold_pairs
    checks = [
        rel.must_from_triple("document:d", "view", "user:alice"),  # F: no arrow in doc.view
        rel.must_from_triple("document:d", "view", "user:bob"),  # T: direct
        rel.must_from_triple("document:d2", "view", "user:bob"),  # F: banned
        rel.must_from_triple("folder:kid", "view", "user:alice"),  # T: ancestor
    ]
    _assert_differential(engine, dsnap, oracle, checks)


def test_fold_expiry_along_arrow_path(tmp_path=None):
    import datetime

    exp_soon = datetime.datetime.fromtimestamp(
        (NOW / 1_000_000) + 3600, tz=datetime.timezone.utc
    )
    exp_past = datetime.datetime.fromtimestamp(
        (NOW / 1_000_000) - 3600, tz=datetime.timezone.utc
    )
    rels = [
        rel.must_from_tuple("folder:root#viewer", "user:alice"),
        # live arrow edge that expires in an hour
        rel.must_from_tuple("folder:kid#parent", "folder:root").with_expiration(exp_soon),
        # dead arrow edge: must contribute nothing through the fold
        rel.must_from_tuple("folder:dead#parent", "folder:root").with_expiration(exp_past),
        rel.must_from_tuple("document:d#folder", "folder:kid"),
        rel.must_from_tuple("document:dx#folder", "folder:dead"),
    ]
    engine, dsnap, oracle = world(DOCS, rels)
    assert dsnap.flat_meta.fold_pairs
    checks = [
        rel.must_from_triple("document:d", "view", "user:alice"),  # T via live path
        rel.must_from_triple("document:dx", "view", "user:alice"),  # F via dead path
        rel.must_from_triple("folder:dead", "view", "user:alice"),  # F
        rel.must_from_triple("folder:kid", "view", "user:alice"),  # T
    ]
    _assert_differential(engine, dsnap, oracle, checks)


def test_fold_budget_zero_disables_but_stays_correct():
    engine, dsnap, oracle = _docs_world(flat_fold_factor=0)
    assert not dsnap.flat_meta.fold_pairs
    checks = [
        rel.must_from_triple(f"document:d{d}", "view", f"user:u{u}")
        for d in range(10) for u in range(8)
    ]
    _assert_differential(engine, dsnap, oracle, checks)


def test_fold_survives_delta_meta():
    # a delta level rides the folded base: the FlatMeta keeps the fold
    # pairs and the kernel stays on the pf probe pair, with dirty-key
    # voiding + dl_pf* overlays carrying the delta (round 5 incremental
    # maintenance — the chain-level differential coverage lives in
    # tests/test_fold_delta.py)
    from dataclasses import replace as _dc_replace

    from gochugaru_tpu.engine.flat import DeltaMeta

    engine, dsnap, oracle = _docs_world()
    assert dsnap.flat_meta.fold_pairs
    assert dsnap.fold_state is not None  # maintenance state armed
    dmeta = _dc_replace(dsnap.flat_meta, delta=DeltaMeta(has_adds=True))
    assert dmeta.fold_pairs == dsnap.flat_meta.fold_pairs


def test_fold_sharded_matches_single_chip():
    # the folded docs world under the bucket-sharded layout: every plane
    # must match the single-chip folded engine exactly (pf probes mask
    # bucket ownership and OR-reduce over the model axis)
    import jax
    import pytest

    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    engine, dsnap, oracle = _docs_world()
    assert dsnap.flat_meta.fold_pairs
    checks = [
        rel.must_from_triple(f"document:d{d}", "view", f"user:u{u}")
        for d in range(40) for u in range(12)
    ]
    d1, p1, o1 = engine.check_batch(dsnap, checks, now_us=NOW)

    mesh = make_mesh(2, 4)
    seng = ShardedEngine(
        engine.compiled, mesh,
        EngineConfig.for_schema(engine.compiled, flat_recursion=3,
                                flat_max_width=32),
    )
    sds = seng.prepare(dsnap.snapshot)
    assert sds.flat_meta.sharded and sds.flat_meta.fold_pairs
    d2, p2, o2 = seng.check_batch(sds, checks, now_us=NOW)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))

"""Differential tests for the HBM-lean packed layout (engine/packed.py,
wired through engine/flat.py _pack_flat + the kernel's decode sites).

Contract: packing is an ENCODING of the exact same tables — every
dispatch result is bit-for-bit identical to the unpacked layout (the
parity oracle, ``flat_packed=False``), across caveats/contexts,
wildcards, expirations, closure overflow, the T-index, delta chains,
the pinned latency tier, and the routed partitioned serve — while the
resident table bytes shrink.  The pack pass itself must never
materialize a full-width intermediate copy (alloc-guard assertion)."""

import random

import numpy as np
import pytest

from gochugaru_tpu.engine import packed as pk
from tests.test_flat_engine import (
    FEATURES,
    NOW,
    assert_sound_cascade,
    build_feature_world,
    world,
)
from tests.test_aligned import _all_checks


# ---------------------------------------------------------------------------
# unit: spec/pack/decode roundtrips
# ---------------------------------------------------------------------------


def test_pack_roundtrip_randomized():
    rng = np.random.default_rng(3)
    for trial in range(30):
        descs = []
        cols = []
        n = int(rng.integers(1, 5000))
        for _ in range(int(rng.integers(1, 6))):
            kind = rng.integers(0, 4)
            if kind == 0:  # plain range, random width incl. >16 bits
                lo = int(rng.integers(-5, 2))
                hi = lo + int(rng.integers(1, 1 << int(rng.integers(1, 25))))
                descs.append(pk.col_range(lo, hi))
                cols.append(rng.integers(lo, hi + 1, n))
            elif kind == 1:  # constant
                v = int(rng.integers(-3, 100))
                descs.append(pk.col_const(v))
                cols.append(np.full(n, v))
            elif kind == 2:  # dictionary (until-style sentinels)
                vals = [-(2 ** 31), -1, 0, 2 ** 31 - 1, 777]
                descs.append(pk.col_dict(vals))
                cols.append(rng.choice(np.asarray(vals), n))
            else:  # full 32-bit field
                descs.append(pk.col_range(-(2 ** 31), 2 ** 31 - 1))
                cols.append(rng.integers(-(2 ** 31), 2 ** 31, n))
        spec = pk.make_spec(descs)
        if spec is None:
            continue  # no byte win for this shape: packing declined
        tbl = np.stack([c.astype(np.int32) for c in cols], axis=1)
        packed = pk.pack_rows(tbl, spec)
        assert packed.dtype == np.uint16
        assert packed.nbytes < tbl.nbytes
        back = pk.unpack_rows(packed, spec)
        assert np.array_equal(back, tbl), f"trial {trial} roundtrip broke"

        # the jnp decode agrees with the host decode bit-for-bit
        import jax.numpy as jnp

        dev = np.asarray(pk.decode_block(jnp.asarray(packed), spec))
        assert np.array_equal(dev, tbl)


def test_pack_delta_run_field():
    """(gk, glo, ghi) group rows: ghi stored as a run length."""
    rng = np.random.default_rng(5)
    n = 4096
    glo = np.sort(rng.integers(0, 1 << 20, n)).astype(np.int32)
    lens = rng.integers(0, 16, n).astype(np.int32)
    tbl = np.stack([rng.integers(-1, 1 << 22, n).astype(np.int32),
                    glo, glo + lens], axis=1)
    spec = pk.make_spec([
        pk.col_range(-1, (1 << 22) - 1),
        pk.col_range(-1, (1 << 20) - 1),
        pk.col_delta(0, 16, 1),
    ])
    assert spec is not None
    assert np.array_equal(pk.unpack_rows(pk.pack_rows(tbl, spec), spec), tbl)


def test_pack_range_violation_raises():
    spec = pk.make_spec([pk.col_range(-1, 100), pk.col_range(0, 7)])
    bad = np.asarray([[5, 3], [200, 1]], np.int32)  # 200 > 100
    with pytest.raises(pk.PackError):
        pk.pack_rows(bad, spec)
    bad2 = np.asarray([[5, 3], [7, 9]], np.int32)  # 9 > 7
    with pytest.raises(pk.PackError):
        pk.pack_rows(bad2, spec)


def test_pack_off_roundtrip():
    rng = np.random.default_rng(11)
    counts = rng.poisson(2.0, 1 << 16)
    off = np.zeros(counts.shape[0] + 1, np.int64)
    np.cumsum(counts, out=off[1:])
    off = off.astype(np.int32)
    got = pk.pack_off(off)
    assert got is not None
    res, anchor = got
    assert res.dtype == np.uint16
    A = pk.OFF_ANCHOR_SHIFT
    idx = np.arange(off.shape[0])
    back = anchor[idx >> A].astype(np.int64) + res
    assert np.array_equal(back, off)
    # a block spanning >= 2^16 rows cannot pack (2048 buckets x 64 rows)
    steep = np.arange(0, 1 << 23, 1 << 6, dtype=np.int32)
    assert pk.pack_off(steep) is None


# ---------------------------------------------------------------------------
# world-level parity: packed vs the unpacked oracle layout
# ---------------------------------------------------------------------------


def _parity(checks, **over):
    eng_p, ds_p, oracle = world(FEATURES, build_feature_world(random.Random(7)),
                                flat_packed=True, **over)
    eng_u, ds_u, _ = world(FEATURES, build_feature_world(random.Random(7)),
                           flat_packed=False, **over)
    assert ds_p.flat_meta.packed, "packing did not engage"
    assert not ds_u.flat_meta.packed
    dp, pp_, op = eng_p.check_batch(ds_p, checks, now_us=NOW)
    du, pu, ou = eng_u.check_batch(ds_u, checks, now_us=NOW)
    assert np.array_equal(np.asarray(dp), np.asarray(du))
    assert np.array_equal(np.asarray(pp_), np.asarray(pu))
    assert np.array_equal(np.asarray(op), np.asarray(ou))
    assert_sound_cascade(eng_p, ds_p, oracle, checks)
    return eng_p, ds_p, eng_u, ds_u


def _device_bytes(ds):
    return sum(int(np.asarray(v).nbytes) for v in ds.arrays.values())


def test_packed_matches_unpacked_and_oracle():
    """Caveats+contexts, wildcards, expirations, closure overflow and the
    T-join all dispatch bit-for-bit between the layouts, and the packed
    snapshot is resident-smaller (raw columns live host-side, tables in
    uint16 lanes)."""
    checks = _all_checks(random.Random(3), k=250)
    eng_p, ds_p, _eng_u, ds_u = _parity(checks)
    assert ds_p.host_arrays is not None  # raw O(E) columns stayed host-side
    assert _device_bytes(ds_p) < _device_bytes(ds_u)


def test_packed_overflow_worlds_parity():
    """Closure-overflow (cap=4) worlds keep the ovf probe + host routing
    identical under packing."""
    checks = _all_checks(random.Random(9), k=200)
    _parity(checks, closure_source_cap=4)


def test_packed_aligned_strata_parity():
    """Width-stratified aligned ladder under packing: same results (the
    tiny CI world usually fits level 0 whole — the deep-ladder geometry
    itself is covered by test_build_aligned_strata_levels)."""
    checks = _all_checks(random.Random(5), k=200)
    _eng_p, ds_p, _eng_u, _ds_u = _parity(
        checks, flat_aligned=True, flat_aligned_cover=(0.99, 0.999),
    )
    assert ds_p.flat_meta.aligned


def test_build_aligned_strata_levels():
    """A coverage ladder steep enough to leave overflow at every level
    builds >= 3 width strata, and every inserted key still probes."""
    from gochugaru_tpu.engine.hash import build_aligned, probe_aligned

    rng = np.random.default_rng(17)
    n = 120_000
    # zipf-ish duplicate keys: deep buckets at every level
    k1 = (rng.zipf(1.3, n) % 5000).astype(np.int32)
    k2 = rng.integers(0, 1 << 18, n).astype(np.int32)
    pay = rng.integers(0, 1 << 30, n).astype(np.int32)
    ai = build_aligned([k1, k2], [k1, k2, pay], cover=(0.5, 0.9))
    assert ai is not None and len(ai.levels) >= 3, ai and ai.caps
    # level 0's width class is narrower than a fit-all cap would be
    assert ai.caps[0] <= ai.caps[-1] or ai.caps[0] <= 12

    import jax.numpy as jnp

    qi = rng.integers(0, n, 4096)
    blk = probe_aligned(
        [jnp.asarray(t) for t, _ in ai.levels], ai.caps, ai.w,
        (jnp.asarray(k1[qi]), jnp.asarray(k2[qi])),
    )
    hit = (blk[..., 0] == k1[qi][:, None]) & (blk[..., 1] == k2[qi][:, None])
    assert bool(hit.any(axis=-1).all()), "an inserted key failed to probe"


def test_packed_delta_chain_parity():
    """Watch-driven incremental prepares ride the packed base tables:
    overlays stay unpacked, reshipped closure tables repack under the
    base spec, results match the oracle each revision."""
    from gochugaru_tpu import rel
    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.store.delta import apply_delta

    rng = random.Random(11)
    rels = build_feature_world(rng)
    eng, ds, oracle = world(FEATURES, rels, flat_packed=True)
    assert ds.flat_meta.packed

    adds1 = [
        rel.must_from_tuple("doc:d0#reader", "user:u9"),
        rel.must_from_tuple("doc:d1#banned", "user:u2"),
    ]
    snap2 = apply_delta(ds.snapshot, 2, adds1, [], interner=ds.snapshot.interner)
    ds2 = eng.prepare(snap2, prev=ds)
    assert ds2.flat_meta.delta is not None, "delta path not taken"
    assert ds2.flat_meta.packed, "packed meta lost across delta"
    rels2 = rels + adds1
    oracle2 = Oracle(eng.compiled, rels2, {}, now_us=NOW)
    checks = _all_checks(random.Random(4), k=150) + adds1
    assert_sound_cascade(eng, ds2, oracle2, checks)

    # a MEMBERSHIP delta advances the closure and reships clx packed
    adds2 = [rel.must_from_tuple("group:g1#member", "user:u7")]
    snap3 = apply_delta(snap2, 3, adds2, [], interner=ds.snapshot.interner)
    ds3 = eng.prepare(snap3, prev=ds2)
    if ds3.flat_meta is not None and ds3.flat_meta.delta is not None:
        oracle3 = Oracle(eng.compiled, rels2 + adds2, {}, now_us=NOW)
        assert_sound_cascade(eng, ds3, oracle3, checks + adds2)


def test_packed_delta_despec_on_dict_misfit():
    """A base world with NO expirations pins {NEVER, pad, NO_EXP}
    dictionaries on the closure until-columns; a later delta that
    introduces an expiring MEMBERSHIP edge pushes a real timestamp into
    the advanced closure — the reshipped table must despec (or the
    chain must rebuild), never alias a value through a stale dict."""
    import datetime as dt

    from gochugaru_tpu import rel
    from gochugaru_tpu.engine.oracle import Oracle
    from gochugaru_tpu.store.delta import apply_delta

    rels = []
    for g in range(4):
        for u in range(3):
            rels.append(
                rel.must_from_tuple(f"group:g{g}#member", f"user:u{u}")
            )
    for d in range(8):
        rels.append(
            rel.must_from_tuple(f"doc:d{d}#reader", f"group:g{d % 4}#member")
        )
    eng, ds, _oracle = world(FEATURES, rels, flat_packed=True)
    assert ds.flat_meta.packed
    pk_map = dict(ds.flat_meta.packed)
    if "clx" in pk_map:
        assert pk_map["clx"][3], "expected dictionary until-columns"

    r = rel.must_from_tuple("group:g1#member", "user:u9").with_expiration(
        dt.datetime.fromtimestamp(NOW / 1e6 + 900, tz=dt.timezone.utc)
    )
    snap2 = apply_delta(ds.snapshot, 2, [r], [], interner=ds.snapshot.interner)
    ds2 = eng.prepare(snap2, prev=ds)
    oracle2 = Oracle(eng.compiled, rels + [r], {}, now_us=NOW)
    checks = rels + [r] + [
        rel.must_from_tuple(f"doc:d{d}#reader", "user:u9") for d in range(8)
    ]
    if ds2.flat_meta is not None and ds2.flat_meta.delta is not None:
        # incremental path taken: clx must have despec'd
        assert "clx" not in dict(ds2.flat_meta.packed)
    assert_sound_cascade(eng, ds2, oracle2, checks)


def test_packed_latency_tier_parity():
    """The pinned latency path serves packed snapshots: same answers as
    the packed throughput path and as the unpacked latency path."""
    eng_p, ds_p, oracle = world(FEATURES, build_feature_world(random.Random(7)),
                                flat_packed=True)
    checks = _all_checks(random.Random(6), k=100)
    d0, p0, o0 = eng_p.check_batch(ds_p, checks, now_us=NOW)
    d1, p1, o1 = eng_p.check_batch(ds_p, checks, now_us=NOW, latency=True)
    assert np.array_equal(np.asarray(d0), np.asarray(d1))
    assert np.array_equal(np.asarray(p0), np.asarray(p1))
    assert np.array_equal(np.asarray(o0), np.asarray(o1))


def test_packed_legacy_fallback_slot_spill():
    """A batch with more distinct permissions than flat_max_slots falls
    back to the legacy kernel, which lazily ships the host-side raw
    columns — answers stay oracle-correct."""
    eng, ds, oracle = world(
        FEATURES, build_feature_world(random.Random(7)),
        flat_packed=True, flat_max_slots=1,
    )
    assert ds.host_arrays is not None
    checks = _all_checks(random.Random(8), k=60)
    assert_sound_cascade(eng, ds, oracle, checks)
    assert ds.legacy_cache is not None, "legacy fallback never shipped"


def test_device_bytes_gauge_live():
    """prepare publishes the resident footprint as live gauges: one
    total plus a per-table breakdown, visible through typed_snapshot
    (what /metrics renders) — not just at bench time."""
    from gochugaru_tpu.utils import metrics

    metrics.default.reset()
    _eng, ds, _oracle = world(
        FEATURES, build_feature_world(random.Random(7)), flat_packed=True
    )
    total = metrics.default.gauge("snapshot.device_bytes")
    assert total > 0
    assert total == _device_bytes(ds)
    _counters, gauges, _timers = metrics.default.typed_snapshot()
    per = {
        k: v for k, v in gauges.items()
        if k.startswith("snapshot.device_bytes.")
    }
    assert per, "no per-table breakdown gauges"
    assert abs(sum(per.values()) - total) < 1e-6
    assert any(k.endswith(".ehx") or k.endswith(".ehx_al") for k in per)


# ---------------------------------------------------------------------------
# allocation discipline: no full-width intermediate in the pack pass
# ---------------------------------------------------------------------------


def test_pack_rows_is_chunked(monkeypatch):
    """pack_rows walks the source in CHUNK windows: with the guard armed
    just above the chunk temporaries (and far below the table), a 200k-
    row pack succeeds — any full-width temporary would trip it."""
    monkeypatch.setattr(pk, "CHUNK", 1 << 12)
    rng = np.random.default_rng(2)
    n = 200_000
    tbl = np.stack([
        rng.integers(-1, 1 << 24, n), rng.integers(-1, 1 << 23, n),
        rng.integers(-1, 4, n),
    ], axis=1).astype(np.int32)
    spec = pk.make_spec([
        pk.col_range(-1, (1 << 24) - 1), pk.col_range(-1, (1 << 23) - 1),
        pk.col_range(-1, 3),
    ])
    with pk.alloc_guard(32 * (1 << 12)):
        packed = pk.pack_rows(tbl, spec)
    assert np.array_equal(pk.unpack_rows(packed, spec), tbl)


def test_packed_prepare_alloc_guarded(monkeypatch):
    """With the chunk shrunk far below the table sizes, arm the alloc
    guard under the full-width table bytes: the chunked pack pass must
    prepare without a single full-width temporary."""
    monkeypatch.setattr(pk, "CHUNK", 1 << 10)
    rng = random.Random(7)
    rels = build_feature_world(rng, n_users=40, n_groups=12, n_docs=120)
    # guard: far above chunk-sized temps (a few x CHUNK x 8B), far below
    # any full table copy (the biggest tables here are > 2^15 rows)
    with pk.alloc_guard(64 * (1 << 10)):
        eng, ds, oracle = world(FEATURES, rels, flat_packed=True)
    assert ds.flat_meta.packed
    checks = _all_checks(rng, n_users=40, n_groups=12, n_docs=120, k=120)
    assert_sound_cascade(eng, ds, oracle, checks)


def test_packed_sharded_and_routed_parity():
    """The stacked (psum) layout and the owner-routed partitioned serve
    both dispatch the packed layout bit-for-bit against single-chip."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU suite (conftest XLA_FLAGS)")
    from gochugaru_tpu.parallel import ShardedEngine, make_mesh
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig

    rng = random.Random(13)
    rels = build_feature_world(rng)
    cs = compile_schema(parse_schema(FEATURES))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    cfg = EngineConfig.for_schema(
        cs, flat_packed=True, flat_recursion=3, flat_max_width=32
    )

    single = DeviceEngine(cs, cfg)
    ds1 = single.prepare(snap)
    checks = _all_checks(random.Random(2), k=160)
    d1, p1, o1 = single.check_batch(ds1, checks, now_us=NOW)

    sharded = ShardedEngine(cs, make_mesh(1, 4), cfg)
    ds_s = sharded.prepare(snap)
    assert ds_s.flat_meta is not None and ds_s.flat_meta.packed
    d2, p2, o2 = sharded.check_batch(ds_s, checks, now_us=NOW)
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(p1), np.asarray(p2))
    assert np.array_equal(np.asarray(o1), np.asarray(o2))

    # owner-routed partitioned serve over the same snapshot
    ds_r = sharded.prepare_snapshot_partitioned(snap)
    assert ds_r.flat_meta is not None
    if ds_r.flat_meta.part_serve:
        assert ds_r.flat_meta.packed, "routed serve lost the packed layout"
    d3, p3, o3 = sharded.check_batch(ds_r, checks, now_us=NOW)
    assert np.array_equal(np.asarray(d1), np.asarray(d3))
    assert np.array_equal(np.asarray(p1), np.asarray(p3))
    assert np.array_equal(np.asarray(o1), np.asarray(o3))


def test_fold_direct_offsets_pack_anchor_residual():
    """The fold's DIRECT offset arrays (pfu_start/csr_start) pack under
    the anchor+residual scheme like every bucket-offset array (the named
    ROADMAP follow-on), with bitwise dispatch parity to the unpacked
    oracle on a folded world.  The bench.py RBAC world folds its
    permissions, so the direct views exist."""
    import sys

    sys.path.insert(0, ".")
    from bench import build_world as bw

    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig

    cs, snap, users, repos, slot = bw(n_repos=400, n_users=150)
    eng_p = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_packed=True))
    eng_u = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_packed=False))
    ds_p, ds_u = eng_p.prepare(snap), eng_u.prepare(snap)
    assert ds_p.flat_meta.fold_pairs and ds_p.flat_meta.pf_direct
    assert ds_p.flat_meta.pf_s_direct
    pko = dict(ds_p.flat_meta.packed_off)
    assert "pfu_start" in pko and "csr_start" in pko
    assert ds_p.arrays["pfu_start"].dtype == np.uint16
    assert "pfu_start_a" in ds_p.arrays and "csr_start_a" in ds_p.arrays
    assert ds_u.arrays["pfu_start"].dtype == np.int32
    rng = np.random.default_rng(5)
    B = 4096
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(
        np.asarray([slot["read"], slot["admin"]], np.int32), B
    )
    q_subj = rng.choice(users, B).astype(np.int32)
    NOWUS = 1_700_000_000_000_000
    dp, pp_, op = eng_p.check_columns(ds_p, q_res, q_perm, q_subj, now_us=NOWUS)
    du, pu, ou = eng_u.check_columns(ds_u, q_res, q_perm, q_subj, now_us=NOWUS)
    assert np.array_equal(dp, du) and np.array_equal(pp_, pu)
    assert np.array_equal(op, ou)
    assert 0 < int(dp.sum()) < B


def test_tx_row_padding_trimmed():
    """The T-join rows table rounds to a 4096-row quantum instead of
    pow2 (up to 2x waste per ROADMAP) — and the slice-safety pad is
    kept, so block probes stay in bounds."""
    from gochugaru_tpu.engine.hash import build_hash, interleave_buckets

    rng = np.random.default_rng(3)
    cols = [rng.integers(0, 1 << 20, 9_000).astype(np.int32)] * 2
    h = build_hash(cols)
    pow2_tbl = interleave_buckets(h, cols)
    trim_tbl = interleave_buckets(h, cols, quantum=4096)
    assert pow2_tbl.shape[0] == 16_384
    assert trim_tbl.shape[0] == 12_288  # ceil((9000+64)/4096)*4096
    assert trim_tbl.shape[0] % 4096 == 0
    assert np.array_equal(trim_tbl, pow2_tbl[: trim_tbl.shape[0]])
    # the padded tail keeps the -1 fill blocks rely on
    assert (trim_tbl[9_000:] == -1).all()

    # integration: a T-bearing world's resident tx lands on the quantum
    import sys

    sys.path.insert(0, ".")
    from bench import build_world as bw

    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, repos, slot = bw(n_repos=400, n_users=150)
    eng = DeviceEngine(cs)
    ds = eng.prepare(snap)
    if ds.flat_meta.has_tindex and "tx" in ds.arrays:
        assert ds.arrays["tx"].shape[0] % 4096 == 0

"""Regression tests for code-review findings (round 1, batch 3)."""

import datetime as dt
import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import new_tpu_evaluator
from gochugaru_tpu.engine.oracle import F, T, Oracle
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils.context import background

SCHEMA = """
use expiration
definition user {}
definition door {
    relation opener: user with expiration
    permission open = opener
}
"""


def test_expiry_near_snapshot_epoch_is_not_eternal():
    # an expiry within 1s of the snapshot epoch must NOT collide with the
    # 0 = "no expiration" sentinel
    epoch_us = 1_700_000_000_000_000
    cs = compile_schema(parse_schema(SCHEMA))
    exp = dt.datetime.fromtimestamp((epoch_us + 500_000) / 1e6, tz=dt.timezone.utc)
    r = rel.must_from_triple("door:d", "opener", "user:u").with_expiration(exp)
    snap = build_snapshot(1, cs, Interner(), [r], epoch_us=epoch_us)
    # int32 column is not the sentinel
    assert int(snap.e_exp[0]) != 0
    # an hour later the edge is dead in host reads
    later = epoch_us + 3600_000_000
    assert list(snap.iter_relationships(None, now_us=later)) == []


def test_exact_expiration_round_trips_through_decode():
    epoch_us = 1_700_000_000_000_000
    cs = compile_schema(parse_schema(SCHEMA))
    exp = dt.datetime.fromtimestamp(
        (epoch_us + 10_600_000) / 1e6, tz=dt.timezone.utc
    )  # epoch + 10.6s
    r = rel.must_from_triple("door:d", "opener", "user:u").with_expiration(exp)
    snap = build_snapshot(1, cs, Interner(), [r], epoch_us=epoch_us)
    decoded = snap.decode_edge(0)
    assert decoded.expiration == exp  # exact micros, no second-flooring


def test_oracle_uses_wall_clock_when_not_pinned():
    cs = compile_schema(parse_schema(SCHEMA))
    soon = dt.datetime.now(dt.timezone.utc) + dt.timedelta(milliseconds=50)
    r = rel.must_from_triple("door:d", "opener", "user:u").with_expiration(soon)
    o = Oracle(cs, [r])  # no pinned now_us
    assert o.check("door", "d", "open", "user", "u") == T
    time.sleep(0.08)
    # the same cached oracle must see the expiry pass
    assert o.check("door", "d", "open", "user", "u") == F


def test_unknown_subject_relation_is_false_on_device():
    ctx = background()
    c = new_tpu_evaluator()
    c.write_schema(
        ctx,
        "definition user {}\ndefinition doc { relation viewer: user"
        " permission view = viewer }",
    )
    txn = rel.Txn()
    txn.create(rel.must_from_triple("doc:d", "viewer", "user:u"))
    c.write(ctx, txn)
    assert c.check_one(
        ctx, consistency.full(), rel.must_from_triple("doc:d", "view", "user:u")
    )
    # same subject with a bogus subject relation must be False, not aliased
    # to the direct subject
    assert not c.check_one(
        ctx, consistency.full(),
        rel.must_from_tuple("doc:d#view", "user:u#bogus"),
    )


def test_watch_unblocks_on_cancel_without_writes():
    ctx = background()
    c = new_tpu_evaluator()
    c.write_schema(ctx, "definition user {}\ndefinition doc { relation v: user }")
    wctx = ctx.with_cancel()
    done = threading.Event()

    def consume():
        for _ in c.updates(wctx, rel.UpdateFilter()):
            pass
        done.set()

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.15)  # let it block waiting for writes
    wctx.cancel()
    assert done.wait(timeout=2.0), "watch did not unblock on cancellation"
    t.join(timeout=1)

"""Pallas fused probe backend (engine/pallas.py).

Contract under test (ISSUE 20): with ``EngineConfig.pallas=True`` the
bucket probes behind checks run through the hand-fused Pallas kernels —
in INTERPRET mode under ``JAX_PLATFORMS=cpu`` — and every output plane
is BITWISE-identical to the ``pallas=False`` XLA gather chain, which is
the parity oracle.  ``pallas=None`` (auto) resolves off-TPU to exactly
the XLA path, so the default config can't regress portability; a
jaxlib without ``jax.experimental.pallas`` degrades a forced knob with
a single warning, never an ImportError.  The ``pallas.dispatch`` fault
site classifies through the same retry envelope as the other dispatch
sites, and the perf ledger models the one-pass byte win per table.

Interpret-mode honesty: these tests prove correctness, not speed — the
byte win is a model (utils/perf.py ``pallas_bytes_model``), asserted
structurally here and measured on silicon by tpu_watch.sh priority 4.0.
"""

import datetime as dt
import random
import warnings
from dataclasses import replace

import numpy as np
import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_admission_control,
    with_engine_config,
    with_latency_mode,
)
from gochugaru_tpu.engine import hash as H
from gochugaru_tpu.engine import packed as PK
from gochugaru_tpu.engine import pallas as P
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils import faults, metrics
from gochugaru_tpu.utils import perf as _perf
from gochugaru_tpu.utils.admission import OPEN, AdmissionConfig
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import UnavailableError

NOW = 1_700_000_000_000_000

SCHEMA = """
caveat on_tuesday(day string) { day == "tuesday" }
definition user {}
definition team {
    relation member: user | team#member | user:*
    permission everyone = member
}
definition doc {
    relation reader: user | user:* | team#member | team#everyone
    relation writer: user | team#member
    permission edit = writer
    permission view = reader + edit
}
"""


def _random_world(seed: int, n_edges: int):
    """Direct / wildcard / userset subjects, caveats with and without
    context, expirations, team chains deep enough to overflow a small
    closure cap — every fused probe site gets traffic."""
    rng = random.Random(seed)
    n_docs = max(n_edges // 8, 8)
    n_users = max(n_edges // 16, 8)
    n_teams = 32
    rels = []
    for t in range(1, n_teams):
        parent = t - 1 if t % 7 else rng.randrange(t)
        rels.append(rel.Relationship(
            resource_type="team", resource_id=f"t{parent}",
            resource_relation="member",
            subject_type="team", subject_id=f"t{t}",
            subject_relation="member",
        ))
    for t in range(n_teams):
        rels.append(rel.Relationship(
            resource_type="team", resource_id=f"t{t}",
            resource_relation="member",
            subject_type="user", subject_id=f"u{rng.randrange(n_users)}",
        ))
    rels.append(rel.Relationship(
        resource_type="team", resource_id="t3", resource_relation="member",
        subject_type="user", subject_id="*",
    ))
    for _ in range(n_edges):
        d = f"d{rng.randrange(n_docs)}"
        kind = rng.random()
        kw = dict(resource_type="doc", resource_id=d,
                  resource_relation="reader" if rng.random() < 0.8 else "writer",
                  subject_type="user", subject_id=f"u{rng.randrange(n_users)}")
        if kind < 0.08:
            kw.update(subject_type="team",
                      subject_id=f"t{rng.randrange(n_teams)}",
                      subject_relation="member")
        elif kind < 0.11:
            kw.update(subject_type="team",
                      subject_id=f"t{rng.randrange(n_teams)}",
                      subject_relation="everyone")
            kw["resource_relation"] = "reader"
        elif kind < 0.13:
            kw.update(subject_id="*")
            kw["resource_relation"] = "reader"
        r = rel.Relationship(**kw)
        if rng.random() < 0.12:
            r = rel.Relationship(
                **{**r.__dict__, "caveat_name": "on_tuesday",
                   "caveat_context": {"day": "tuesday"} if rng.random() < 0.5
                   else {}},
            )
        if rng.random() < 0.07:
            r = rel.Relationship(
                **{**r.__dict__,
                   "expiration": dt.datetime.fromtimestamp(
                       (NOW + rng.randrange(-10**9, 10**12)) / 1e6,
                       tz=dt.timezone.utc,
                   )},
            )
        rels.append(r)
    return rels


def _checks(seed: int, n: int):
    rng = random.Random(seed + 1)
    out = []
    for _ in range(n):
        q = rel.must_from_triple(
            f"doc:d{rng.randrange(16)}", rng.choice(["view", "edit"]),
            f"user:u{rng.randrange(10)}",
        )
        if rng.random() < 0.4:
            q = q.with_caveat(
                "", {"day": rng.choice(["tuesday", "friday"])}
            )
        out.append(q)
    out.append(rel.must_from_tuple("doc:d0#view", "team:t1#member"))
    out.append(rel.must_from_triple("doc:nope", "view", "user:u0"))
    return out


def _engine_pair(cs, snap, **cfg):
    """(xla, dsnap_x), (pallas, dsnap_p) engines over one snapshot."""
    ex = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=False, **cfg))
    ep = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True, **cfg))
    return (ex, ex.prepare(snap)), (ep, ep.prepare(snap))


@pytest.fixture(scope="module")
def world():
    cs = compile_schema(parse_schema(SCHEMA))
    snap = build_snapshot(1, cs, Interner(), _random_world(7, 120),
                          epoch_us=NOW)
    return cs, snap, _checks(7, 40)


# ---------------------------------------------------------------------------
# knob resolution / feature detect
# ---------------------------------------------------------------------------


def test_resolve_knob_auto_off_on_cpu():
    assert P.available(), "test env jaxlib should ship pallas"
    assert P.resolve(EngineConfig(pallas=False)) is False
    assert P.resolve(EngineConfig(pallas=True)) is True
    # auto: portability default — off everywhere but TPU
    assert P.resolve(EngineConfig()) is False


def test_missing_pallas_degrades_with_one_warning():
    """A jaxlib without pallas turns a forced knob into the XLA path
    with ONE RuntimeWarning + ``pallas.degraded`` count — never an
    ImportError at engine construction."""
    saved, savedw = dict(P._FEATURE), dict(P._WARNED)
    before = metrics.default.counter("pallas.degraded")
    try:
        P._FEATURE.update(probed=True, ok=False, err="synthetic: no pallas")
        P._WARNED["degraded"] = False
        cfg = EngineConfig(pallas=True)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert P.resolve(cfg) is False
            assert P.resolve(cfg) is False  # second resolve stays quiet
        runtime = [x for x in w if issubclass(x.category, RuntimeWarning)]
        assert len(runtime) == 1, runtime
        assert metrics.default.counter("pallas.degraded") == before + 1
        # auto resolves quietly to the XLA path
        assert P.resolve(EngineConfig()) is False
        # and an engine still constructs + serves on XLA
        cs = compile_schema(parse_schema(SCHEMA))
        snap = build_snapshot(1, cs, Interner(), _random_world(3, 40),
                              epoch_us=NOW)
        eng = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True))
        dsnap = eng.prepare(snap)
        d, p, ovf = eng.check_batch(dsnap, _checks(3, 6), now_us=NOW)
        assert d.shape == (8,)
    finally:
        P._FEATURE.clear(); P._FEATURE.update(saved)
        P._WARNED.clear(); P._WARNED.update(savedw)


def test_vmem_plan_pins_offsets_only():
    arrays = {
        "eh_off": np.zeros(1024, np.uint16),
        "eh_off_a": np.zeros(8, np.int32),
        "ehx": np.zeros((4096, 4), np.int32),       # block table: DMA'd
        "clx_al0": np.zeros((64, 16), np.int32),    # ladder level: pinned
        "big_off": np.zeros(6 << 20, np.int32),     # over budget
    }
    plan = P.vmem_plan(arrays)
    assert set(plan) == {"eh_off", "eh_off_a", "clx_al0"}
    total = P.publish_vmem(arrays)
    assert total == sum(plan.values())
    assert metrics.default.gauge("perf.vmem_resident_bytes") == float(total)


# ---------------------------------------------------------------------------
# interpret-mode bitwise parity, engine level
# ---------------------------------------------------------------------------


def test_engine_parity_random_world(world):
    """pallas=True == pallas=False on every output plane (d, p, ovf),
    including caveated checks with query context, wildcards, userset
    subjects, and expirations."""
    cs, snap, checks = world
    (ex, dx), (ep, dp) = _engine_pair(cs, snap)
    rx = ex.check_batch(dx, checks, now_us=NOW)
    rp = ep.check_batch(dp, checks, now_us=NOW)
    for a, b, name in zip(rx, rp, ("d", "p", "ovf")):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
    # knob-off restores the stock XLA path byte-for-byte: the default
    # (auto) config must produce the identical planes
    e0 = DeviceEngine(cs, EngineConfig.for_schema(cs))
    r0 = e0.check_batch(e0.prepare(snap), checks, now_us=NOW)
    for a, b, name in zip(rx, r0, ("d", "p", "ovf")):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_engine_parity_packed_and_aligned(world):
    """Packed uint16 layouts and the aligned width-stratified ladder run
    the same fused kernels (in-kernel decode / per-level salted row DMA)
    and stay bitwise with their XLA twins."""
    cs, snap, checks = world
    for cfg in ({"flat_packed": True},
                {"flat_packed": True, "flat_aligned": True}):
        (ex, dx), (ep, dp) = _engine_pair(cs, snap, **cfg)
        rx = ex.check_batch(dx, checks[:24], now_us=NOW)
        rp = ep.check_batch(dp, checks[:24], now_us=NOW)
        for a, b, name in zip(rx, rp, ("d", "p", "ovf")):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (cfg, name)


def test_engine_parity_closure_overflow(world):
    """A tiny closure width cap spills the nested team chains into the
    overflow table; the fused ovf/cl probes must agree lane-for-lane."""
    cs, snap, _ = world
    checks = _checks(11, 24)
    (ex, dx), (ep, dp) = _engine_pair(cs, snap, closure_source_cap=4)
    assert dx.flat_meta.has_ovf, "world should spill the closure cap at 4"
    rx = ex.check_batch(dx, checks, now_us=NOW)
    rp = ep.check_batch(dp, checks, now_us=NOW)
    for a, b, name in zip(rx, rp, ("d", "p", "ovf")):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


# ---------------------------------------------------------------------------
# kernel-level parity against the exact XLA reference chains
# ---------------------------------------------------------------------------


def test_kernel_modes_bitwise_unpacked():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    N, B = 300, 23
    k1 = rng.integers(0, 50, N).astype(np.int32)
    k2 = rng.integers(0, 30, N).astype(np.int32)
    pay = rng.integers(0, 1000, N).astype(np.int32)
    hi = H.build_hash([k1, k2], target_cap=4)
    tbl = H.interleave_buckets(hi, [k1, k2, pay, (pay // 2).astype(np.int32)])
    off = jnp.asarray(hi.off)
    q1 = rng.integers(-2, 52, B).astype(np.int32)  # negatives: dead lanes
    q2 = rng.integers(0, 31, B).astype(np.int32)
    qs = (jnp.asarray(q1), jnp.asarray(q2))

    ref = np.asarray(H.probe_block(off, jnp.asarray(tbl), hi.cap, qs))
    got = P.fused_probe(qs, off, jnp.asarray(tbl), cap=hi.cap, mode="block")
    assert np.array_equal(ref, np.asarray(got))

    hit = ((ref[:, :, 0] == q1[:, None]) & (ref[:, :, 1] == q2[:, None])
           & (q1 >= 0)[:, None] & (q2 >= 0)[:, None])
    got_any = P.fused_probe(qs, off, jnp.asarray(tbl), cap=hi.cap, mode="any")
    assert np.array_equal(hit.any(-1), np.asarray(got_any))

    d_ref = (hit & (ref[:, :, 2] > 500)).any(-1)
    p_ref = (hit & (ref[:, :, 3] > 500)).any(-1)
    d_got, p_got = P.fused_probe(
        qs, off, jnp.asarray(tbl), cap=hi.cap, mode="until2",
        now=jnp.int32(500),
    )
    assert np.array_equal(d_ref, np.asarray(d_got))
    assert np.array_equal(p_ref, np.asarray(p_got))

    # 2-D query lattice keeps its shape through the kernel
    q1m, q2m = q1[:20].reshape(4, 5), q2[:20].reshape(4, 5)
    refm = H.probe_block(
        off, jnp.asarray(tbl), hi.cap, (jnp.asarray(q1m), jnp.asarray(q2m))
    )
    gotm = P.fused_probe(
        (jnp.asarray(q1m), jnp.asarray(q2m)), off, jnp.asarray(tbl),
        cap=hi.cap, mode="block",
    )
    assert np.array_equal(np.asarray(refm), np.asarray(gotm))


def test_kernel_packed_and_runs_bitwise():
    """Packed uint16 rows + anchored uint16 offsets through the fused
    kernel == gather-then-decode_block; runs mode == the spmv bisect."""
    import jax.numpy as jnp

    from gochugaru_tpu.engine.packed import decode_block
    from gochugaru_tpu.engine.spmv import _field0_reader

    rng = np.random.default_rng(1)
    N, B = 500, 31
    k1 = rng.integers(0, 70, N).astype(np.int32)
    k2 = rng.integers(0, 40, N).astype(np.int32)
    pay = rng.integers(0, 100000, N).astype(np.int32)
    hi = H.build_hash([k1, k2], target_cap=4)
    tbl_raw = H.interleave_buckets(hi, [k1, k2, pay])
    spec = PK.make_spec([
        PK.col_range(-1, 70), PK.col_range(-1, 40), PK.col_range(-1, 100000),
    ])
    assert spec is not None
    packed = PK.pack_rows(tbl_raw, spec)
    off_res, off_anchor = PK.pack_off(hi.off)
    A = PK.OFF_ANCHOR_SHIFT
    q1 = rng.integers(-2, 72, B).astype(np.int32)
    q2 = rng.integers(0, 41, B).astype(np.int32)
    qs = (jnp.asarray(q1), jnp.asarray(q2))

    hh = (H.mix32([qs[0], qs[1]], jnp) & jnp.uint32(hi.size - 1)).astype(
        jnp.int32)
    start = (H.take_in_bounds(jnp.asarray(off_anchor), hh >> A)
             + H.take_in_bounds(jnp.asarray(off_res), hh).astype(jnp.int32))
    ref = decode_block(H.slice_blocks(jnp.asarray(packed), start, hi.cap),
                       spec)
    got = P.fused_probe(
        qs, jnp.asarray(off_res), jnp.asarray(packed), cap=hi.cap,
        spec=spec, off_a=jnp.asarray(off_anchor), ashift=A, mode="block",
    )
    assert np.array_equal(np.asarray(ref), np.asarray(got))

    # runs: sorted single-key buckets, in-kernel bisect vs the reference
    ks = np.sort(rng.integers(0, 60, N).astype(np.int32))
    v2 = rng.integers(0, 9, N).astype(np.int32)
    hi2 = H.build_hash([ks], target_cap=8)
    specr = PK.make_spec([PK.col_range(-1, 60), PK.col_range(-1, 9)])
    packedr = PK.pack_rows(H.interleave_buckets(hi2, [ks, v2]), specr)
    offr_res, offr_anchor = PK.pack_off(hi2.off)
    keys = jnp.asarray(rng.integers(-2, 62, B).astype(np.int32))

    col0 = _field0_reader(specr, 2)

    def offread(idx):
        return (H.take_in_bounds(jnp.asarray(offr_anchor), idx >> A)
                + H.take_in_bounds(jnp.asarray(offr_res), idx).astype(
                    jnp.int32))

    h2 = (H.mix32([keys], jnp) & jnp.uint32(hi2.size - 1)).astype(jnp.int32)
    s2, e2 = offread(h2), offread(h2 + 1)
    last = packedr.shape[0] - 1
    steps = max(int(hi2.cap).bit_length(), 1)

    def bisect(left):
        lo, n = s2, e2 - s2
        for _ in range(steps):
            alive = n > 0
            half = n >> 1
            mid = lo + half
            v = col0(jnp.asarray(packedr), jnp.clip(mid, 0, last))
            go = alive & ((v < keys) if left else (v <= keys))
            lo = jnp.where(go, mid + 1, lo)
            n = jnp.where(go, n - half - 1, jnp.where(alive, half, 0))
        return lo

    lo_ref = bisect(True)
    ln_ref = bisect(False) - lo_ref
    dead = keys < 0
    lo_ref = jnp.where(dead, 0, lo_ref)
    ln_ref = jnp.where(dead, 0, ln_ref)
    lo_got, ln_got = P.fused_probe(
        (keys,), jnp.asarray(offr_res), jnp.asarray(packedr), cap=hi2.cap,
        spec=specr, off_a=jnp.asarray(offr_anchor), ashift=A, mode="runs",
    )
    assert np.array_equal(np.asarray(lo_ref), np.asarray(lo_got))
    assert np.array_equal(np.asarray(ln_ref), np.asarray(ln_got))


def test_lookup_parity_pallas(world):
    """The SpMV/SpMM run probes behind LookupResources/LookupSubjects
    route through the fused ``runs`` kernel and return the identical
    answer sets."""
    from gochugaru_tpu.caveats import compile_cel
    from gochugaru_tpu.engine.lookup import (
        lookup_resources_device,
        lookup_subjects_device,
    )
    from gochugaru_tpu.engine.oracle import Oracle

    cs, snap, _ = world
    rels = _random_world(7, 120)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    (ex, dx), (ep, dp) = _engine_pair(cs, snap)
    fac = lambda: Oracle(cs, rels, progs, now_us=NOW)  # noqa: E731
    for uid in ("u0", "u3", "u5"):
        rx = lookup_resources_device(ex, dx, "doc", "view", "user", uid, "",
                                     now_us=NOW, oracle_factory=fac)
        rp = lookup_resources_device(ep, dp, "doc", "view", "user", uid, "",
                                     now_us=NOW, oracle_factory=fac)
        assert rx == rp, uid
    for did in ("d0", "d1", "d3"):
        sx = lookup_subjects_device(ex, dx, "doc", did, "view", "user", "",
                                    now_us=NOW, oracle_factory=fac)
        sp = lookup_subjects_device(ep, dp, "doc", did, "view", "user", "",
                                    now_us=NOW, oracle_factory=fac)
        assert sx == sp, did


# ---------------------------------------------------------------------------
# latency-tier pins: no retrace with the fused kernels
# ---------------------------------------------------------------------------


def test_latency_pins_no_retrace_with_pallas(world):
    """Warm same-tier dispatches through the pallas path pay ZERO extra
    compiles — resolve() is deterministic per config, so the pinned
    executables keep their no-retrace contract."""
    cs, snap, _ = world
    ep = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True))
    dp = ep.prepare(snap)
    lp = ep.latency_path(dp)
    slot = cs.slot_of_name
    rng = np.random.default_rng(5)
    B = 24
    docs = [snap.interner.node("doc", f"d{i}") for i in range(8)]
    users = [snap.interner.node("user", f"u{i}") for i in range(8)]
    q_res = rng.choice(np.asarray(docs, np.int64), B).astype(np.int32)
    q_perm = np.full(B, slot["view"], np.int32)
    q_subj = rng.choice(np.asarray(users, np.int64), B).astype(np.int32)
    out = lp.dispatch_columns(q_res, q_perm, q_subj, now_us=NOW)
    assert out is not None
    warm = lp.compile_count
    assert warm >= 1
    for i in range(1, 7):
        d, p, o = lp.dispatch_columns(
            np.roll(q_res, i), q_perm, np.roll(q_subj, i), now_us=NOW
        )
        dd, pp, oo = ep.check_columns(
            dp, np.roll(q_res, i), q_perm, np.roll(q_subj, i), now_us=NOW
        )
        assert (d == dd).all() and (p == pp).all() and (o == oo).all()
    assert lp.compile_count == warm, (
        f"pallas latency path retraced: {lp.compile_count - warm} extra"
    )


# ---------------------------------------------------------------------------
# chaos: pallas.dispatch classifies + reroutes like any dispatch fault
# ---------------------------------------------------------------------------


def test_pallas_fault_site_gated_by_config(world):
    """The site fires only when the config resolves pallas on: the XLA
    engine never reaches it, the pallas engine raises the classified
    transient error."""
    cs, snap, checks = world
    (ex, dx), (ep, dp) = _engine_pair(cs, snap)
    with faults.armed("pallas.dispatch") as spec:
        ex.check_batch(dx, checks[:4], now_us=NOW)  # XLA: site unreachable
        assert spec.hits == 0
        with pytest.raises(UnavailableError):
            ep.check_batch(dp, checks[:4], now_us=NOW)
        assert spec.fired == 1


def test_breaker_reforms_on_pallas_failures():
    """Consecutive pallas.dispatch failures on the pinned latency path
    trip the breaker exactly like latency-path failures: while OPEN the
    traffic re-forms onto the batch path, and answers never change."""
    c = new_tpu_evaluator(
        with_latency_mode(),
        with_engine_config(EngineConfig(pallas=True)),
        with_admission_control(
            AdmissionConfig(breaker_threshold=2, breaker_cooldown_s=60.0)
        ),
    )
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc { relation reader: user  permission read = reader }
    """)
    txn = rel.Txn()
    txn.touch(rel.must_from_triple("doc:a", "reader", "user:u1"))
    c.write(ctx, txn)
    checks = [
        rel.must_from_triple("doc:a", "read", "user:u1"),
        rel.must_from_triple("doc:a", "read", "user:u2"),
    ]
    m = metrics.default
    assert c.check(ctx, consistency.full(), *checks) == [True, False]

    trips_before = m.counter("breaker.trips")
    with faults.armed("pallas.dispatch", times=2):
        # envelope retries through the two injected failures and lands
        # on the batch path with the site spent
        assert c.check(ctx, consistency.full(), *checks) == [True, False]
    assert m.counter("breaker.trips") == trips_before + 1
    assert c._admission.breaker.state == OPEN

    # while OPEN: latency traffic re-formed onto the batch path
    lat_before = m.counter("latency.dispatches")
    rerouted_before = m.counter("breaker.latency_rerouted")
    assert c.check(ctx, consistency.full(), *checks) == [True, False]
    assert m.counter("latency.dispatches") == lat_before
    assert m.counter("breaker.latency_rerouted") == rerouted_before + 1


# ---------------------------------------------------------------------------
# perf ledger: one-pass byte model + VMEM residency gauge
# ---------------------------------------------------------------------------


def test_prepare_publishes_vmem_and_byte_model(world):
    cs, snap, _ = world
    metrics.default.set_gauge("perf.vmem_resident_bytes", 0.0)
    ep = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True,
                                                  flat_packed=True))
    dp = ep.prepare(snap)
    assert metrics.default.gauge("perf.vmem_resident_bytes") > 0
    assert metrics.default.gauge("perf.pallas.bytes_saved_per_check") > 0

    model = _perf.pallas_bytes_model(dp)
    assert model, "pallas byte model empty"
    saved_tables = {t for t, row in model.items() if row["saved"] > 0}
    # the direct-edge probe table must show the one-pass win
    assert any(t.startswith("ehx") or t == "eh_off" for t in saved_tables), (
        sorted(saved_tables))
    for t, row in model.items():
        assert row["xla"] >= row["pallas"], (t, row)
        assert row["saved"] == row["xla"] - row["pallas"], (t, row)
    # XLA-only prepare leaves the pallas gauges untouched
    metrics.default.set_gauge("perf.pallas.bytes_saved_per_check", -1.0)
    e0 = DeviceEngine(cs, EngineConfig.for_schema(cs))
    e0.prepare(snap)
    assert metrics.default.gauge("perf.pallas.bytes_saved_per_check") == -1.0

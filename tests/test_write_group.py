"""Group-commit write pipeline (store/group.py + Store.write_group):
collapse semantics, per-transaction ejection, zookie minting, the
closure.delta fault-atomicity contract, the committer's coalescing
threads, the background chain compactor, and the client wiring."""

import threading
import time

import pytest

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator,
    with_engine_config,
    with_group_commit,
    with_host_only_evaluation,
    with_store,
)
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.store.group import (
    ChainCompactor,
    GroupCommitConfig,
    GroupCommitter,
)
from gochugaru_tpu.store.store import Store, parse_revision
from gochugaru_tpu.utils import faults
from gochugaru_tpu.utils import metrics as _metrics
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import (
    AlreadyExistsError,
    PreconditionFailedError,
    RevisionUnavailableError,
    UnavailableError,
)

EXAMPLE = """
definition user {}
definition document {
    relation writer: user
    relation reader: user

    permission edit = writer
    permission view = reader + edit
}
"""


@pytest.fixture(autouse=True)
def _hygiene():
    faults.reset()
    yield
    faults.reset()


def make_store():
    s = Store()
    s.write_schema(EXAMPLE)
    return s


def _touch(doc, relation="reader", user="user:jim"):
    t = rel.Txn()
    t.touch(rel.must_from_triple(f"document:{doc}", relation, user))
    return t


# -- Store.write_group semantics --------------------------------------------


def test_group_mints_consecutive_zookies_one_log_entry():
    s = make_store()
    base = s.head_revision
    log_len = len(s._log)
    outcomes = s.write_group([_touch(f"g{i}") for i in range(5)])
    revs = [parse_revision(o) for o in outcomes]
    assert revs == [base + 1 + i for i in range(5)]
    assert s.head_revision == base + 5
    # the whole group is ONE log entry — that is the point
    assert len(s._log) == log_len + 1
    assert len(s) == 5


def test_group_matches_sequential_oracle():
    """Last-writer-wins collapse replays identically to the k sequential
    transactions it stands for — including in-group supersede and
    delete-then-recreate orderings."""
    mk = [
        _touch("a", user="user:one"),
        _touch("a", user="user:two"),  # same tuple, later writer wins
        _touch("b"),
    ]
    d = rel.Txn()
    d.delete(rel.must_from_triple("document:b", "reader", "user:jim"))
    d.touch(rel.must_from_triple("document:c", "reader", "user:jim"))
    mk.append(d)

    grouped = make_store()
    grouped.write_group(mk)
    oracle = make_store()
    for t in mk:
        oracle.write(t)
    assert (
        sorted(map(str, grouped.live_relationships()))
        == sorted(map(str, oracle.live_relationships()))
    )
    assert grouped.head_revision == oracle.head_revision


def test_group_ejects_create_conflict_against_earlier_member():
    s = make_store()
    a = rel.Txn()
    a.create(rel.must_from_triple("document:x", "reader", "user:jim"))
    b = rel.Txn()
    b.create(rel.must_from_triple("document:x", "reader", "user:jim"))
    c = _touch("y")
    outcomes = s.write_group([a, b, c])
    assert isinstance(outcomes[1], AlreadyExistsError)
    # survivors still mint consecutively: base+1 and base+2
    assert parse_revision(outcomes[2]) == parse_revision(outcomes[0]) + 1
    assert s.head_revision == parse_revision(outcomes[2])
    assert len(s) == 2


def test_group_ejects_failed_precondition_only():
    s = make_store()
    guard = rel.must_from_triple("document:z", "writer", "user:amy").filter()
    bad = rel.Txn()
    bad.must_match(guard)  # nothing matches at base → ejected
    bad.touch(rel.must_from_triple("document:z", "reader", "user:jim"))
    good = _touch("ok")
    outcomes = s.write_group([bad, good])
    assert isinstance(outcomes[0], PreconditionFailedError)
    assert parse_revision(outcomes[1]) == s.head_revision
    assert len(s) == 1


def test_group_preconditions_evaluate_at_base():
    """Preconditions see the group's BASE revision, not earlier members:
    a must_not_match guard that an earlier member's write would violate
    still passes, same as if both arrived before either committed."""
    s = make_store()
    creator = _touch("pre", user="user:amy")
    guard = rel.must_from_triple("document:pre", "reader", "user:amy").filter()
    negated = rel.Txn()
    negated.must_not_match(guard)
    negated.touch(rel.must_from_triple("document:other", "reader", "user:jim"))
    outcomes = s.write_group([creator, negated])
    assert not any(isinstance(o, BaseException) for o in outcomes)
    assert len(s) == 2


def test_group_fault_atomicity_and_idempotent_retry():
    """Satellite contract: a closure.delta fault fired mid-group aborts
    the WHOLE group at its base revision — no zookie minted, no state
    mutated — and a verbatim retry commits cleanly."""
    s = make_store()
    seeded = _touch("seed")
    s.write(seeded)
    base = s.head_revision
    log_len = len(s._log)
    txns = [_touch(f"f{i}") for i in range(4)]
    with faults.armed("closure.delta", times=1):
        with pytest.raises(UnavailableError):
            s.write_group(txns)
        # atomic abort: head at base, no log entry, no rows
        assert s.head_revision == base
        assert len(s._log) == log_len
        assert len(s) == 1
        # retry inside the armed window is idempotent (times=1 spent)
        outcomes = s.write_group(txns)
    assert [parse_revision(o) for o in outcomes] == [base + 1 + i for i in range(4)]
    assert s.head_revision == base + 4
    assert len(s._log) == log_len + 1
    assert len(s) == 5


def test_mid_group_revision_reads():
    """Mid-group tokens are real zookies: FULL/AT_LEAST resolve through
    them, while pinning a SNAPSHOT read to an interior revision raises
    RevisionUnavailableError like any unmaterialized generation."""
    s = make_store()
    outcomes = s.write_group([_touch(f"m{i}") for i in range(3)])
    mid = outcomes[1]
    s.snapshot_for(consistency.at_least(str(mid)))  # head covers it
    with pytest.raises(RevisionUnavailableError):
        s.snapshot_for(consistency.snapshot(str(mid)))
    # the group's final revision IS materialized on demand
    snap = s.snapshot_for(consistency.snapshot(str(outcomes[-1])))
    assert snap.revision == s.head_revision


def test_empty_and_all_ejected_groups_leave_head_alone():
    s = make_store()
    base = s.head_revision
    assert s.write_group([]) == []
    dup = rel.Txn()
    dup.create(rel.must_from_triple("document:d", "reader", "user:jim"))
    s.write(dup)
    again = rel.Txn()
    again.create(rel.must_from_triple("document:d", "reader", "user:jim"))
    outcomes = s.write_group([again])
    assert isinstance(outcomes[0], AlreadyExistsError)
    assert s.head_revision == base + 1  # only the seed write advanced it


# -- GroupCommitter ----------------------------------------------------------


def test_committer_coalesces_and_resolves_every_future():
    m = _metrics.default
    s = make_store()
    groups_before = m.counter("write.groups")
    txns_before = m.counter("write.txns")
    gc = GroupCommitter(s, GroupCommitConfig(max_group=8, hold_max_s=0.01))
    try:
        futs = [gc.submit(_touch(f"c{i}")) for i in range(20)]
        revs = [parse_revision(f.result(timeout=5.0)) for f in futs]
    finally:
        gc.close()
    # every submission minted, zookies dense from the store base
    assert sorted(revs) == list(range(min(revs), min(revs) + 20))
    assert s.head_revision == max(revs)
    assert len(s) == 20
    # coalescing happened: fewer groups than transactions
    groups = m.counter("write.groups") - groups_before
    assert m.counter("write.txns") - txns_before == 20
    assert 1 <= groups < 20


def test_committer_ejection_surfaces_on_the_right_future():
    s = make_store()
    gc = GroupCommitter(s, GroupCommitConfig(max_group=4, hold_max_s=0.02))
    try:
        a = rel.Txn()
        a.create(rel.must_from_triple("document:e", "reader", "user:jim"))
        b = rel.Txn()
        b.create(rel.must_from_triple("document:e", "reader", "user:jim"))
        fa = gc.submit(a)
        fb = gc.submit(b)
        assert parse_revision(fa.result(timeout=5.0)) == s.head_revision
        with pytest.raises(AlreadyExistsError):
            fb.result(timeout=5.0)
    finally:
        gc.close()


def test_committer_group_fault_rejects_all_then_retry_succeeds():
    s = make_store()
    gc = GroupCommitter(s, GroupCommitConfig(max_group=4, hold_max_s=0.005))
    try:
        base = s.head_revision
        with faults.armed("closure.delta", times=1):
            futs = [gc.submit(_touch(f"r{i}")) for i in range(3)]
            errs = 0
            for f in futs:
                try:
                    f.result(timeout=5.0)
                except UnavailableError:
                    errs += 1
            # the fault killed exactly one formed group; any txn that
            # missed that group committed in a later clean one
            assert errs >= 1
        assert s.head_revision <= base + 3
        # retry path: resubmit everything, all mint
        futs = [gc.submit(_touch(f"r{i}")) for i in range(3)]
        for f in futs:
            parse_revision(f.result(timeout=5.0))
        assert len(s) == 3
    finally:
        gc.close()


def test_committer_close_drains_then_rejects_new_submissions():
    s = make_store()
    gc = GroupCommitter(s, GroupCommitConfig(max_group=64, hold_max_s=0.05))
    futs = [gc.submit(_touch(f"d{i}")) for i in range(5)]
    gc.close()
    for f in futs:  # drain flushed the partial group before stopping
        parse_revision(f.result(timeout=5.0))
    with pytest.raises(UnavailableError):
        gc.submit(_touch("late"))


def test_committer_perf_section_registered():
    from gochugaru_tpu.utils import perf as _perf

    s = make_store()
    gc = GroupCommitter(s, GroupCommitConfig(hold_max_s=0.005))
    try:
        gc.submit(_touch("p")).result(timeout=5.0)
        report = _perf.render_report()
        wp = report.get("write_path")
        assert wp is not None
        assert wp["groups"] >= 1
        assert set(wp["flush"]) == {"full", "deadline", "maxhold", "drain"}
        assert "apply_cost" in wp and "chain" in wp
    finally:
        gc.close()


# -- ChainCompactor ----------------------------------------------------------


def test_chain_compactor_bounds_probe_depth():
    """With a small materialization threshold, the background compactor
    merges the delta chain before the synchronous trip would, and the
    overlay restarts from zero — probe depth stays bounded."""
    m = _metrics.default
    s = make_store()
    s.lsm_compact_min = 64  # what EngineConfig.lsm_compact_min threads in
    cc = ChainCompactor(
        s, GroupCommitConfig(compact_poll_s=0.0, compact_fraction=0.5)
    )
    seed = rel.Txn()
    for i in range(40):
        seed.touch(rel.must_from_triple(f"document:s{i}", "reader", "user:u"))
    s.write(seed)
    s.snapshot_for(consistency.full())  # base generation

    merges_before = m.counter("store.bg_compactions")
    compacted = False
    for n in range(12):
        s.write_group(
            [_touch(f"w{n}_{j}", user=f"user:v{j}") for j in range(8)]
        )
        s.snapshot_for(consistency.full())  # extends the delta chain
        if cc.poll_once():
            compacted = True
            got = s.peek_chain()
            assert got is not None and got[1] == 0  # overlay merged away
    cc.close()
    assert compacted
    assert m.counter("store.bg_compactions") > merges_before


def test_closure_batch_applies_counter():
    """A closure advance spanning a multi-revision group counts one
    closure.batch_applies — the telemetry that proves k writes paid ONE
    advance (the revision span is the group: base+1..base+k, one delta)."""
    import numpy as np

    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.closure import (
        advance_closure,
        build_closure,
        build_closure_state,
    )
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot

    m = _metrics.default
    schema = """
definition user {}
definition group { relation member: user | group#member }
definition doc {
    relation reader: user | group#member
    permission view = reader
}
"""
    cs = compile_schema(parse_schema(schema))
    interner = Interner()
    from gochugaru_tpu.rel.relationship import Relationship

    def _r(res, rl, subj, srel=""):
        rt, rid = res.split(":")
        st, sid = subj.split(":")
        return Relationship(
            resource_type=rt, resource_id=rid, resource_relation=rl,
            subject_type=st, subject_id=sid, subject_relation=srel,
            caveat_name="", caveat_context={}, expiration=None,
        )

    rels = [
        _r("group:g0", "member", "user:u0"),
        _r("doc:d", "reader", "group:g0", "member"),
    ]
    snap = build_snapshot(1, cs, interner, rels, epoch_us=1_700_000_000_000_000)
    st = build_closure_state(snap, build_closure(snap))
    S1 = snap.num_slots + 1
    member = cs.slot_of_name["member"]
    u1 = interner.lookup("user", "u1")
    g0 = interner.lookup("group", "g0")
    before_batch = m.counter("closure.batch_applies")
    before_delta = m.counter("closure.delta_applies")
    # ONE advance spanning revisions 1→7: a group of 6 writes collapsed
    got = advance_closure(
        st, 7,
        seed_add=(np.array([u1 * S1]), np.array([g0 * S1 + member + 1]),
                  np.array([0], np.int32), np.array([0], np.int32)),
    )
    assert got is not None
    assert m.counter("closure.delta_applies") == before_delta + 1
    assert m.counter("closure.batch_applies") == before_batch + 1
    # a single-revision advance does NOT count as a batch
    u2 = interner.lookup("user", "u2")
    got = advance_closure(
        got.state, 8,
        seed_add=(np.array([u2 * S1]), np.array([g0 * S1 + member + 1]),
                  np.array([0], np.int32), np.array([0], np.int32)),
    )
    assert got is not None
    assert m.counter("closure.batch_applies") == before_batch + 1


# -- client wiring -----------------------------------------------------------


def test_client_group_commit_option_routes_writes():
    c = new_tpu_evaluator(
        with_store(make_store()),
        with_host_only_evaluation(),
        with_group_commit(GroupCommitConfig(max_group=8, hold_max_s=0.005)),
    )
    assert c._committer is not None and c._compactor is not None
    ctx = background()
    base = c._store.head_revision
    zks = [c.write(ctx, _touch(f"cw{i}")) for i in range(4)]
    assert [parse_revision(z) for z in zks] == [base + 1 + i for i in range(4)]
    q = rel.must_from_triple("document:cw0", "view", "user:jim")
    assert c.check(ctx, consistency.full(), q) == [True]


def test_client_threads_lsm_compact_min_into_store():
    cfg = EngineConfig(lsm_compact_min=12_345)
    c = new_tpu_evaluator(
        with_store(make_store()),
        with_host_only_evaluation(),
        with_engine_config(cfg),
    )
    assert c._store.lsm_compact_min == 12_345


def test_concurrent_writers_through_one_committer():
    """16 threads × 8 writes each: every zookie unique and dense, store
    content matches, and the group-size histogram saw multi-txn groups."""
    s = make_store()
    gc = GroupCommitter(s, GroupCommitConfig(max_group=32, hold_max_s=0.002))
    revs = []
    lock = threading.Lock()
    errs = []

    def worker(w):
        try:
            for j in range(8):
                zk = gc.write(_touch(f"t{w}_{j}", user=f"user:w{w}"))
                with lock:
                    revs.append(parse_revision(zk))
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    gc.close()
    assert not errs
    assert len(revs) == 128
    assert sorted(revs) == list(range(min(revs), min(revs) + 128))
    assert s.head_revision == max(revs)
    assert len(s) == 128

"""SnapshotOracle (engine/oracle.py): the O(1)-construction fallback
oracle backed by sorted snapshot columns (VERDICT round-1 item 6).

Contracts: (a) construction never iterates the edge set; (b) every
tri-state answer equals the dict-based Oracle's on randomized worlds,
including caveats, expiration, wildcards, usersets, and lookups."""

import random

import numpy as np

from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine.oracle import F, Oracle, SnapshotOracle, T, U
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot

NOW = 1_700_000_000_000_000

SCHEMA = """
caveat lim(v int, cap int) { v <= cap }
definition user {}
definition group { relation member: user | group#member | user:* }
definition folder {
    relation parent: folder
    relation owner: user | group#member
    relation writer: user | group#member | user with lim
    relation banned: user
    permission write = (owner + writer + parent->write) - banned
    permission manage = owner & writer
}
"""


def build_world(seed):
    rng = random.Random(seed)
    users = [f"user:u{i}" for i in range(10)]
    groups = [f"group:g{i}" for i in range(4)]
    folders = [f"folder:f{i}" for i in range(7)]
    rels = []
    import datetime as dt

    past = dt.datetime.fromtimestamp((NOW - 10_000_000) / 1e6, tz=dt.timezone.utc)
    future = dt.datetime.fromtimestamp((NOW + 10_000_000) / 1e6, tz=dt.timezone.utc)
    for g in groups:
        for u in rng.sample(users, 3):
            rels.append(rel.must_from_tuple(f"{g}#member", u))
        if rng.random() < 0.5:
            rels.append(rel.must_from_tuple(f"{g}#member", f"{rng.choice(groups)}#member"))
    for f in folders:
        if rng.random() < 0.6:
            rels.append(rel.must_from_tuple(f"{f}#parent", rng.choice(folders)))
        rels.append(rel.must_from_tuple(f"{f}#owner", rng.choice(users)))
        for u in rng.sample(users, 2):
            r = rel.must_from_tuple(f"{f}#writer", u)
            roll = rng.random()
            if roll < 0.3:
                r = r.with_caveat(
                    "lim", {"v": rng.randint(0, 9), "cap": 5} if rng.random() < 0.6 else {}
                )
            elif roll < 0.45:
                r = r.with_expiration(past if rng.random() < 0.5 else future)
            rels.append(r)
        if rng.random() < 0.4:
            rels.append(rel.must_from_tuple(f"{f}#banned", rng.choice(users)))
    cs = compile_schema(parse_schema(SCHEMA))
    snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
    progs = {
        name: compile_cel(name, decl.params, decl.expression)
        for name, decl in cs.schema.caveats.items()
    }
    return cs, snap, rels, progs


def test_differential_vs_dict_oracle():
    for seed in (1, 4, 9):
        cs, snap, rels, progs = build_world(seed)
        dict_oracle = Oracle(cs, rels, progs, now_us=NOW)
        snap_oracle = SnapshotOracle(snap, progs, now_us=NOW)
        rng = random.Random(seed + 100)
        for _ in range(120):
            f = f"f{rng.randint(0, 6)}"
            u = f"u{rng.randint(0, 11)}"  # includes unknown subjects
            perm = rng.choice(["write", "manage", "owner", "writer"])
            ctx = {"v": rng.randint(0, 9)} if rng.random() < 0.5 else None
            a = dict_oracle.check("folder", f, perm, "user", u, "", ctx)
            b = snap_oracle.check("folder", f, perm, "user", u, "", ctx)
            assert a == b, f"mismatch on folder:{f}#{perm}@user:{u} ctx={ctx}: {a} vs {b}"
        # userset subjects
        for g in ("g0", "g1", "g2", "g3"):
            a = dict_oracle.check("folder", "f0", "write", "group", g, "member")
            b = snap_oracle.check("folder", "f0", "write", "group", g, "member")
            assert a == b
        # lookups
        for u in ("u0", "u3", "u7"):
            assert list(dict_oracle.lookup_resources("folder", "write", "user", u)) == \
                list(snap_oracle.lookup_resources("folder", "write", "user", u))
        for f in ("f0", "f2"):
            assert list(dict_oracle.lookup_subjects("folder", f, "write", "user")) == \
                list(snap_oracle.lookup_subjects("folder", f, "write", "user"))


def test_construction_is_lazy():
    """Construction must not touch the edge columns (O(1) contract) except
    for the packed key build; a check touches only the searched ranges."""
    cs, snap, rels, progs = build_world(2)
    o = SnapshotOracle(snap, progs, now_us=NOW)
    # nothing memoized until the first check
    assert o._edge_memo == {}
    o.check("folder", "f0", "write", "user", "u0")
    touched = len(o._edge_memo)
    assert 0 < touched < snap.num_edges  # only the reachable groups decoded


def test_client_uses_snapshot_oracle():
    from gochugaru_tpu import consistency, new_tpu_evaluator
    from gochugaru_tpu.rel.txn import Txn
    from gochugaru_tpu.utils import background

    c = new_tpu_evaluator()
    ctx = background()
    c.write_schema(ctx, SCHEMA)
    txn = Txn()
    txn.create(rel.must_from_tuple("folder:x#writer", "user:a").with_caveat("lim", {}))
    txn.create(rel.must_from_tuple("folder:x#owner", "user:b"))
    rev = c.write(ctx, txn)
    strat = consistency.at_least(rev)
    # conditional query → host fallback through the SnapshotOracle
    assert c.check_one(
        ctx, strat,
        rel.must_from_triple("folder:x", "write", "user:a").with_caveat(
            "", {"v": 3, "cap": 5}
        ),
    )
    assert isinstance(c._oracle_for(c.store.snapshot_for(strat)), SnapshotOracle)

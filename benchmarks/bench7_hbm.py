"""Bench 7 — HBM-lean packed tables at the config-3 world.

Packed (bit-packed uint16 lanes + dictionary until-columns + delta-run
ranges + offset residuals + bounded bucket growth; engine/packed.py)
vs the unpacked parity oracle (``flat_packed=False``), measured on the
Google-Docs nested-groups world of BASELINE config 3:

- ``hbm_table_bytes_reduction`` — resident device-table bytes,
  unpacked / packed (bar: ≥ 2.5×), with ``table_bytes_per_edge`` and
  the estimated gathered ``bytes_per_check`` for BOTH layouts on the
  row (the roofline columns next to checks/s);
- ``hbm_packed_true_rate`` — repeat-harness TRUE checks/s of the packed
  layout, ``vs_unpacked`` on the row (bar: within 10%);
- ``oracle_match`` — packed vs unpacked dispatch results bit-for-bit
  over the whole batch (the parity contract), plus a sampled host-
  oracle cross-check;
- ``hbm_packed_small_batch_p99_latency`` — the PINNED latency tier
  serving the packed layout (budget breakdown on the row; parity with
  the throughput path asserted first);
- ``hbm_routed_partitioned_bytes_per_device`` — the owner-routed
  partitioned serve (M=4 CPU proxy) on the packed layout: per-device
  resident bytes vs the packed single-chip footprint, routed dispatch
  parity asserted.

Usage: python benchmarks/bench7_hbm.py [--scale 1.0] [--mesh 4]
"""

import argparse
import os as _os
import sys as _sys
import time

import numpy as np

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import (
    NORTH_STAR_P99_MS,
    NORTH_STAR_RATE,
    emit,
    emit_small_batch_row,
    est_bytes_per_check,
    maybe_force_cpu,
    measured_rate_flat,
    note,
    roofline_columns,
    table_bytes,
)

_args = argparse.ArgumentParser()
_args.add_argument("--scale", type=float, default=1.0)
_args.add_argument("--mesh", type=int, default=4)
_ARGS = _args.parse_known_args()[0]

EPOCH = 1_700_000_000_000_000
BYTES_BAR = 2.5  # acceptance: ≥2.5x table-bytes reduction
RATE_BAR = 0.90  # acceptance: packed true rate within 10% of unpacked


def _prepare(cs, snap, packed: bool):
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig

    eng = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_packed=packed))
    t0 = time.perf_counter()
    dsnap = eng.prepare(snap)
    note(
        f"{'packed' if packed else 'unpacked'} prepare:"
        f" {time.perf_counter() - t0:.1f}s,"
        f" {table_bytes(dsnap) / 1e6:.1f} MB device tables"
    )
    assert dsnap.flat_meta is not None
    assert bool(dsnap.flat_meta.packed) == packed
    return eng, dsnap


def _dispatch_once(eng, dsnap, snap, q_res, q_perm, q_subj):
    import jax
    import jax.numpy as jnp

    queries, qctx = eng._columns_preamble(
        dsnap, q_res, q_perm, q_subj, None, None, None, None
    )
    fn, args = eng.flat_fn_and_args(
        dsnap, queries, qctx, jnp.int32(snap.now_rel32(EPOCH)),
        q_res.shape[0],
    )
    out = fn(*args)
    jax.block_until_ready(out)
    d, p, ovf = jax.device_get(out)
    B = q_res.shape[0]
    return (d[:B], p[:B], ovf[:B]), args


def main() -> None:
    plats = _os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if _os.environ.get("GOCHUGARU_FORCE_CPU") == "1" or plats.startswith("cpu"):
        # the routed section needs a multi-device proxy: 8 virtual CPU
        # devices, set BEFORE the backend initializes (bench2's recipe)
        from gochugaru_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(8)
    note(f"platform={maybe_force_cpu()}")
    _sys.argv = [_sys.argv[0], "--scale", str(_ARGS.scale)]
    from benchmarks.bench3_docs import build_world

    cs, snap, users, docs, slot = build_world()
    note(f"edges={snap.num_edges} nodes={snap.num_nodes}")
    E = int(snap.num_edges)

    rng = np.random.default_rng(7)
    B = 1 << 17
    q_res = rng.choice(docs, B).astype(np.int32)
    q_perm = np.full(B, slot["view"], np.int32)
    q_subj = rng.choice(users, B).astype(np.int32)
    slots = (int(slot["view"]),)

    # ---- unpacked oracle layout ---------------------------------------
    eng_u, ds_u = _prepare(cs, snap, packed=False)
    bytes_u = table_bytes(ds_u)
    bpc_u = est_bytes_per_check(ds_u)
    res_u, args_u = _dispatch_once(eng_u, ds_u, snap, q_res, q_perm, q_subj)
    try:
        rate_u = measured_rate_flat(eng_u, ds_u, slots, B, args_u)
        basis = "repeat-harness"
    except RuntimeError as e:
        note(f"unpacked repeat harness: {e}")
        rate_u, basis = 0.0, "unavailable"

    # ---- packed layout -------------------------------------------------
    eng_p, ds_p = _prepare(cs, snap, packed=True)
    bytes_p = table_bytes(ds_p)
    bpc_p = est_bytes_per_check(ds_p)
    res_p, args_p = _dispatch_once(eng_p, ds_p, snap, q_res, q_perm, q_subj)

    # parity: the unpacked layout IS the oracle — bit-for-bit over the
    # full batch — plus a sampled host-oracle cross-check
    oracle_match = all(
        np.array_equal(a, b) for a, b in zip(res_p, res_u)
    )
    from gochugaru_tpu.engine.oracle import SnapshotOracle, T

    so = SnapshotOracle(snap, {}, now_us=EPOCH)
    itn = snap.interner
    sample = rng.choice(B, 200, replace=False)
    host_ok = True
    for i in sample:
        rt, rid = itn.key_of(int(q_res[i]))
        st, sid = itn.key_of(int(q_subj[i]))
        want = so.check(rt, rid, "view", st, sid)
        d_i, p_i, o_i = res_p[0][i], res_p[1][i], res_p[2][i]
        if d_i and want != T:
            host_ok = False
        if not o_i and not p_i and want == T:
            host_ok = False
    oracle_match = bool(oracle_match and host_ok)
    note(f"oracle_match={oracle_match} (batch parity + {len(sample)} host samples)")

    try:
        rate_p = measured_rate_flat(eng_p, ds_p, slots, B, args_p)
    except RuntimeError as e:
        note(f"packed repeat harness: {e}")
        rate_p = 0.0

    reduction = bytes_u / max(bytes_p, 1)
    emit(
        "hbm_table_bytes_reduction", reduction, "x (unpacked/packed)",
        reduction / BYTES_BAR,
        edges=E, batch=int(B),
        table_bytes_packed=bytes_p, table_bytes_unpacked=bytes_u,
        table_bytes_per_edge=round(bytes_p / max(E, 1), 2),
        table_bytes_per_edge_unpacked=round(bytes_u / max(E, 1), 2),
        bytes_per_check=round(bpc_p, 1),
        bytes_per_check_unpacked=round(bpc_u, 1),
        oracle_match=oracle_match,
        note=f"bar {BYTES_BAR}x; est. gathered B/check {bpc_p:.0f} vs {bpc_u:.0f}",
    )
    ratio = (rate_p / rate_u) if rate_u else float("nan")
    # roofline columns for BOTH layouts: the packed layout's achieved
    # GB/s against the measured ceiling (and the unpacked comparison
    # point) — the A/B the silicon window asks of the decode layer
    rl_p = roofline_columns(rate_p, bytes_per_check=bpc_p)
    rl_u = roofline_columns(rate_u, bytes_per_check=bpc_u)
    emit(
        "hbm_packed_true_rate", rate_p, "checks/sec/chip",
        rate_p / NORTH_STAR_RATE,
        edges=E, batch=int(B), rate_basis="repeat-harness",
        unpacked_rate=round(rate_u, 1),
        vs_unpacked=round(ratio, 4) if rate_u else None,
        table_bytes_per_edge=round(bytes_p / max(E, 1), 2),
        **rl_p,
        achieved_gbps_unpacked=rl_u["achieved_gbps"],
        roofline_frac_unpacked=rl_u["roofline_frac"],
        oracle_match=oracle_match,
        note=(
            f"bar ≥{RATE_BAR:.0%} of unpacked"
            + ("" if not rate_u else f"; measured {ratio:.1%}")
        ),
    )

    # ---- pinned latency tier on the packed layout ----------------------
    SB = 2048
    dl, pl, ol = eng_p.check_columns_latency(
        ds_p, q_res[:SB].copy(), q_perm[:SB].copy(), q_subj[:SB].copy(),
        now_us=EPOCH,
    )
    assert np.array_equal(dl, res_p[0][:SB])
    assert np.array_equal(pl, res_p[1][:SB])
    note("latency-tier parity with throughput path: ok")
    try:
        emit_small_batch_row(
            "hbm_packed_small_batch_p99_latency", eng_p, ds_p,
            q_res[:SB].copy(), q_perm[:SB].copy(), q_subj[:SB].copy(),
            edges=E, now_us=EPOCH,
            table_bytes_per_edge=round(bytes_p / max(E, 1), 2),
        )
    except Exception as e:  # optional row must never cost the main ones
        note(f"small-batch latency row failed: {type(e).__name__}: {e}")

    # ---- routed partitioned serve on the packed layout -----------------
    del eng_u, ds_u, args_u, args_p
    try:
        import jax

        M = _ARGS.mesh
        if len(jax.devices()) < M:
            raise RuntimeError(
                f"{len(jax.devices())} devices < mesh {M}"
                " (run under XLA_FLAGS=--xla_force_host_platform_device_count)"
            )
        from gochugaru_tpu.engine.plan import EngineConfig
        from gochugaru_tpu.parallel import ShardedEngine, make_mesh

        cfg = EngineConfig.for_schema(cs, flat_packed=True)
        sharded = ShardedEngine(cs, make_mesh(1, M), cfg)
        t0 = time.perf_counter()
        ds_r = sharded.prepare_snapshot_partitioned(snap)
        note(f"routed partitioned prepare: {time.perf_counter() - t0:.1f}s")
        assert ds_r.flat_meta is not None and ds_r.flat_meta.packed
        RB = 4096
        dr, pr, orr = sharded.check_columns(
            ds_r, q_res[:RB], q_perm[:RB], q_subj[:RB], now_us=EPOCH
        )
        assert np.array_equal(np.asarray(dr), res_p[0][:RB])
        assert np.array_equal(np.asarray(pr), res_p[1][:RB])
        assert np.array_equal(np.asarray(orr), res_p[2][:RB])
        from gochugaru_tpu.engine.flat import PART_SHARDED_KEYS

        split = sum(
            int(getattr(ds_r.arrays[k], "nbytes", 0))
            for k in PART_SHARDED_KEYS if k in ds_r.arrays
        )
        whole = table_bytes(ds_r) - split
        per_dev = whole + split / M
        emit(
            "hbm_routed_partitioned_bytes_per_device", per_dev, "bytes",
            (bytes_p / max(per_dev, 1)),
            edges=E, batch=RB, mesh=f"1x{M}",
            vs_single_chip=round(per_dev / max(bytes_p, 1), 4),
            # the 1B/16 arithmetic inputs: whole-resident vs model-split
            # shares, per edge (BENCHMARKS.md "HBM-lean tables")
            whole_bytes_per_edge=round(whole / max(E, 1), 2),
            split_bytes_per_edge=round(split / max(E, 1), 2),
            oracle_match=True,
            note="routed serve on packed tables; parity vs single-chip packed",
        )
    except Exception as e:
        note(f"routed partitioned section skipped: {type(e).__name__}: {e}")


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""Run every BASELINE config (BASELINE.md:25-32) and write BENCHMARKS.md.

Each config runs as a bounded child process (a hung TPU tunnel must never
hang the suite — the same contract as bench.py).  A bounded backend probe
decides the platform once: if the default (TPU) backend is unusable,
children run with GOCHUGARU_FORCE_CPU=1 and the report says so per row.

Usage:  python benchmarks/run_all.py [--out BENCHMARKS.md] [--quick]
                                     [--metrics] [--compare]
                                     [--compare-tolerance 0.10]

``--quick`` shrinks configs 3/4/5 (CI-sized smoke run); the committed
BENCHMARKS.md should come from a full run.  ``--metrics`` asks every
bench child to append its final ``metrics.snapshot()`` blob
(GOCHUGARU_BENCH_METRICS=1 → common.maybe_emit_metrics_snapshot), which
lands in a "Metrics snapshots" appendix — a regression row then ships
WITH the counters that explain it.  ``--compare`` runs
scripts/bench_compare.py after the suite — newest committed BENCH_r*
round vs. the previous one, direction-aware, one line per metric — and
the suite exits nonzero when the trajectory regressed beyond the
tolerance.
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_TIMEOUT_S = int(os.environ.get("GOCHUGARU_BENCH_PROBE_TIMEOUT", "75"))


def probe_backend() -> str:
    """'tpu'/'cpu'/... from a bounded child, or 'cpu' when unusable.

    When ``JAX_PLATFORMS`` pins the platform the subprocess probe is
    skipped entirely — the probe only guards against a hung TPU init,
    and a pinned platform cannot hang (BENCH_r05 paid the 75 s timeout
    before every degraded stage)."""
    plats = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if plats:
        return plats.split(",")[0]
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S, cwd=ROOT,
        )
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return "cpu"


def run_config(name, cmd, timeout_s, env):
    """Run one config; returns (json_lines, notes, failure_reason)."""
    t0 = time.time()
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            cwd=ROOT, env=env,
        )
        stdout, stderr = r.stdout, r.stderr
        reason = None if r.returncode == 0 else f"rc={r.returncode}"
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        stderr = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        reason = f"timed out after {timeout_s}s"
    lines = []
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in parsed:
                lines.append(parsed)
    notes = [
        ln[1:].strip() for ln in (stderr or "").splitlines() if ln.startswith("#")
    ]
    if reason and not lines:
        tail = (stderr or "").strip().splitlines()
        reason += f": {tail[-1][:160]}" if tail else ""
    print(f"[{name}] {time.time()-t0:.0f}s {len(lines)} metrics"
          + (f" ({reason})" if reason else ""), file=sys.stderr, flush=True)
    return lines, notes, reason


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(ROOT, "BENCHMARKS.md"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--metrics", action="store_true",
                    help="children append a final metrics.snapshot() blob")
    ap.add_argument("--compare", action="store_true",
                    help="run scripts/bench_compare.py after the suite and"
                         " fail on a BENCH_r* trajectory regression")
    ap.add_argument("--compare-tolerance", type=float, default=0.10,
                    help="relative worsening tolerated by --compare")
    args = ap.parse_args()

    backend = probe_backend()
    env = dict(os.environ)
    if args.metrics:
        env["GOCHUGARU_BENCH_METRICS"] = "1"
    # children (bench.py among them) reuse this verdict instead of
    # re-paying their own probe subprocess per stage
    env["GOCHUGARU_BACKEND_PROBED"] = backend
    if backend != "tpu":
        env["GOCHUGARU_FORCE_CPU"] = "1"
        # pin the platform for the whole child TREE: processes the bench
        # children themselves spawn (2-process dryruns, RSS workers —
        # parallel/multihost.py) see a pinned platform and skip their
        # own bounded probe instead of paying the 75 s degraded timeout
        # per child (BENCH_r05 paid it before every degraded stage)
        env.setdefault("JAX_PLATFORMS", "cpu")
        backend = "cpu (TPU backend unusable at run time)"
    py = sys.executable

    q = args.quick
    configs = [
        ("1 — founders CheckAll (client round trip)",
         [py, "benchmarks/bench1_founders.py"], 420),
        ("2 — GitHub RBAC 2-hop, 100k batch (driver headline)",
         [py, "bench.py"], 700),
        ("3 — Google-Docs nested groups, 1M docs / 10M edges, 5-hop",
         [py, "benchmarks/bench3_docs.py"], 2400),
        ("4 — multi-tenant caveats" + (" (quick)" if q else ", 100M edges"),
         [py, "benchmarks/bench4_caveats.py"]
         + (["--edges", "2000000"] if q else ["--edges", "100000000"]),
         2400),
        ("5 — Watch-driven incremental re-index" + (" (quick)" if q else ""),
         [py, "benchmarks/bench5_watch.py"]
         + (["--edges", "1000000"] if q else ["--edges", "10000000"]),
         1500),
        ("6 — bulk import/export through the Client" + (" (quick)" if q else ""),
         [py, "benchmarks/bench_import.py"]
         + (["--edges", "1000000"] if q else ["--edges", "10000000"]),
         2400),
    ]
    # appended (not inserted) so the --quick index overrides above keep
    # pointing at the rows they name
    configs.append((
        "2m — config-2 CPU mesh comparison + degraded-mode columns",
        [py, "benchmarks/bench2_mesh.py"]
        + (["--repos", "500", "--batch", "8192"] if q else []),
        900,
    ))
    configs.append((
        "7 — incremental closure: member-edge write throughput"
        + (" (quick)" if q else ""),
        [py, "benchmarks/bench6_closure.py"]
        + (["--edges", "1000000", "--rounds", "10", "--warmup", "5"]
           if q else ["--edges", "10000000"]),
        4000,
    ))
    configs.append((
        "8 — partitioned-serving smoke (2-shard parity + routed serve)",
        ["bash", "scripts/partition_smoke.sh"],
        600,
    ))
    configs.append((
        "9 — HBM-lean packed tables: bytes reduction + parity @ config 3"
        + (" (quick, 5% scale)" if q else ""),
        [py, "benchmarks/bench7_hbm.py"]
        + (["--scale", "0.05"] if q else []),
        3600,
    ))
    configs.append((
        "10 — HBM-lean smoke (packed-vs-unpacked parity + bytes bar)",
        ["bash", "scripts/hbm_smoke.sh"],
        600,
    ))
    configs.append((
        "11 — bulk lookup: frontier SpMV candidates/s @ config 3"
        + (" (quick, 5% scale)" if q else ""),
        [py, "benchmarks/bench8_lookup.py"]
        + (["--scale", "0.05"] if q else []),
        2400,
    ))
    configs.append((
        "12 — lookup smoke (walker parity + paginated answer + routed shards)",
        ["bash", "scripts/lookup_smoke.sh"],
        600,
    ))
    configs.append((
        "13 — continuous batching: open-loop goodput/p99 @ offered load"
        + (" (quick)" if q else ""),
        [py, "benchmarks/bench9_serve.py"] + (["--quick"] if q else []),
        900,
    ))
    configs.append((
        "14 — serve smoke (concurrent submitters, oracle parity, shed path)",
        ["bash", "scripts/serve_smoke.sh"],
        600,
    ))
    configs.append((
        "15 — SLO/incident smoke (breaker trip -> incident bundle + burn)",
        ["bash", "scripts/slo_smoke.sh"],
        600,
    ))
    configs.append((
        "16 — perf-attribution smoke (roofline microbench + /perf ledger"
        " + wall-time closure)",
        ["bash", "scripts/perf_smoke.sh"],
        600,
    ))
    configs.append((
        "17 — verdict-cache smoke (oracle parity incl. cached answers,"
        " cache-off bitwise parity, hit-rate floor, chaos on"
        " cache.lookup)",
        ["bash", "scripts/cache_smoke.sh"],
        600,
    ))
    configs.append((
        "18 — decision-provenance smoke (explain==oracle parity, witness"
        " subset, denial frontier, cache re-derivation, decision-log"
        " rotation + denial-rate SLO)",
        ["bash", "scripts/explain_smoke.sh"],
        600,
    ))
    configs.append((
        "19 — unified-SpMM smoke (fused-vs-legacy parity through"
        " check/lookup/fold, one-dispatch multi-hop fixpoint, routed"
        " shards)",
        ["bash", "scripts/spmm_smoke.sh"],
        600,
    ))
    configs.append((
        "20 — fleet serving: replica processes, goodput scaling,"
        " zero-stale per strategy, seeded kill + failover p99"
        + (" (quick)" if q else ""),
        [py, "benchmarks/bench10_fleet.py"] + (["--quick"] if q else []),
        900,
    ))
    configs.append((
        "21 — fleet smoke (self-joining replica processes, zookie"
        " read-your-writes, SIGKILL survival with zero lost/dup/stale)",
        ["bash", "scripts/fleet_smoke.sh"],
        600,
    ))
    configs.append((
        "22 — self-tuning A/B: tuned config vs presets on a mixed"
        " workload, predicted-vs-measured deltas, non-pow2 tier parity"
        + (" (quick)" if q else ""),
        [py, "benchmarks/bench11_tune.py"] + (["--quick"] if q else []),
        1800,
    ))
    configs.append((
        "23 — tune smoke (offline diff fixed point, online controller"
        " bounded moves + revert)",
        ["bash", "scripts/tune_smoke.sh"],
        600,
    ))
    configs.append((
        "24 — group-commit write pipeline: coalesced vs one-at-a-time"
        " writes, bitwise oracle parity, chain compaction, mixed soak"
        + (" (quick)" if q else ""),
        [py, "benchmarks/bench12_writes.py"] + (["--quick"] if q else []),
        900,
    ))
    configs.append((
        "25 — pallas smoke (fused-probe interpret parity through"
        " throughput/latency/packed, zero warm retraces, ledger"
        " bytes-delta bar)",
        ["bash", "scripts/pallas_smoke.sh"],
        600,
    ))
    if not q:
        # Leopard-scale CPU proxy (VERDICT r04 item 3): the same Watch
        # re-index loop at a 100M-edge base — BASELINE config 5's
        # per-chip slice of the 1B / v5e-16 deployment
        configs.insert(5, (
            "5b — Watch re-index, 100M-edge base (Leopard-scale proxy)",
            [py, "benchmarks/bench5_watch.py", "--edges", "100000000"],
            7200,
        ))
    if q:
        configs[2] = (
            "3 — Google-Docs nested groups (quick, 5% scale)",
            [py, "benchmarks/bench3_docs.py", "--scale", "0.05"], 900,
        )

    rows = []
    all_notes = []
    snapshots = []  # (config name, metrics.snapshot() dict) from --metrics
    for name, cmd, timeout_s in configs:
        lines, notes, reason = run_config(name, cmd, timeout_s, env)
        all_notes.append((name, notes))
        if not lines:
            rows.append((name, "—", "failed", "—", "—", "—", "—", reason or "no output"))
            continue
        for parsed in lines:
            if parsed.get("metric") == "metrics_snapshot":
                # child's final counter dump: appendix, not a table row
                snapshots.append((name, parsed.get("snapshot") or {}))
                continue
            vs = parsed.get("vs_baseline")
            rows.append((
                name,
                parsed.get("metric", "?"),
                f"{parsed.get('value', 0):,.1f}",
                parsed.get("unit", ""),
                f"{vs:.4f}" if isinstance(vs, (int, float)) else "—",
                f"{parsed['edges']:,}" if "edges" in parsed else "—",
                f"{parsed['batch']:,}" if "batch" in parsed else "—",
                parsed.get("note", ""),
            ))

    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    with open(args.out, "w") as f:
        f.write("# BENCHMARKS\n\n")
        f.write(
            f"All five BASELINE configs (BASELINE.md:25-32), run {stamp} on"
            f" platform **{backend}** via `python benchmarks/run_all.py"
            + (" --quick" if q else "") + "`.\n\n"
            "North star: ≥10M checks/sec/chip, p99 < 2 ms @ 100M edges"
            " (BASELINE.md:20-23).  The reference publishes no numbers"
            " (BASELINE.md:3-8); the target is the denominator for"
            " vs_baseline in each bench's JSON output.\n\n"
        )
        f.write(
            "| Config | Metric | Value | Unit | vs north star | Edges | Batch | Note |\n"
            "|---|---|---|---|---|---|---|---|\n"
        )
        for r in rows:
            f.write("| " + " | ".join(str(x) for x in r) + " |\n")
        f.write("\n## Runner notes (stderr `#` lines)\n\n")
        for name, notes in all_notes:
            f.write(f"### {name}\n\n")
            for n in notes:
                f.write(f"- {n}\n")
            f.write("\n")
        if snapshots:
            f.write("## Metrics snapshots (--metrics)\n\n")
            f.write(
                "Each bench child's final `metrics.snapshot()` — the"
                " counters/gauges/timer percentiles behind the rows"
                " above.\n\n"
            )
            for name, snap in snapshots:
                f.write(f"### {name}\n\n```json\n")
                f.write(json.dumps(snap, indent=1, sort_keys=True))
                f.write("\n```\n\n")
    print(f"wrote {args.out}", file=sys.stderr)
    if args.compare:
        # trajectory gate: the suite's verdict includes "did the
        # committed round-over-round numbers regress"
        r = subprocess.run(
            [py, "scripts/bench_compare.py",
             "--tolerance", str(args.compare_tolerance)],
            cwd=ROOT,
        )
        if r.returncode != 0:
            print("bench trajectory REGRESSED (see table above)",
                  file=sys.stderr)
            return r.returncode
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Fleet serving bench: replicated REPLICA PROCESSES behind the
consistent-hash router (gochugaru_tpu/fleet/).

Three phases, each a claim from the fleet round:

1. **Goodput scaling** — closed-loop callers through the router at
   min_latency against 1 replica, then against ``--replicas``.  On the
   1-core CPU proxy every replica process shares the same core with the
   router and the callers, so wall-clock scaling CANNOT reach the 2×
   bar physically — ``scaling_bar_met`` reports whether it did, and the
   row carries both arms so the trajectory is honest (the same
   discipline as PR-10's ``p99_bar_met``: measure, flag, don't
   fabricate).  The multiplier belongs to multi-core hosts, where
   replicas stop queueing on one another.

2. **Zero-stale parity** — per consistency strategy against the host
   oracle at the router store's head: full and at_least(zookie) rows
   must match the oracle exactly (quiesced min_latency too); then a
   DYNAMIC phase toggles one edge write-by-write and re-checks through
   the router with the freshly-minted zookie — read-your-writes on
   every toggle, counted as staleness violations if ever wrong.

3. **Failover** — a seeded mid-run SIGKILL of one replica process
   while full-consistency traffic flows.  Every in-window request must
   return exactly one correct answer (zero lost, zero duplicated, zero
   stale — the retry envelope reroutes through surviving replicas);
   the window p99 rides next to the quiet baseline p99 as
   ``failover_p99_ms``, the kill must be detected (ring eviction +
   ``fleet.failover`` incident bundle), and a restarted replica must
   bootstrap, catch up, and rejoin before the bench ends.

JSON lines: ``fleet_goodput_scaling`` (x, higher better),
``fleet_zero_stale`` (violations, lower better), ``failover_p99_ms``
(ms, lower better).
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def spawn_replica(py, port, rid, env, stderr_path):
    """Start ``python -m gochugaru_tpu.fleet.replica`` and wait for its
    REPLICA-READY line; returns (Popen, host, port)."""
    import json

    proc = subprocess.Popen(
        [py, "-m", "gochugaru_tpu.fleet.replica",
         "--upstream", f"127.0.0.1:{port}", "--id", rid, "--host-only"],
        stdout=subprocess.PIPE, stderr=open(stderr_path, "w"),
        text=True, env=env,
    )
    deadline = time.monotonic() + 120.0
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("REPLICA-READY"):
            meta = json.loads(line.split(None, 1)[1])
            return proc, meta["host"], meta["port"]
        if not line and proc.poll() is not None:
            break
    tail = open(stderr_path).read()[-2000:]
    raise RuntimeError(f"replica {rid} never became ready: {tail}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--rels", type=int, default=20_000,
                    help="relationships in the bootstrap world")
    ap.add_argument("--seconds", type=float, default=4.0,
                    help="per goodput arm")
    ap.add_argument("--clients", type=int, default=4,
                    help="closed-loop caller threads")
    ap.add_argument("--batch", type=int, default=16,
                    help="checks per router.check call")
    ap.add_argument("--toggles", type=int, default=40,
                    help="dynamic zero-stale write/check rounds")
    ap.add_argument("--failover-checks", type=int, default=200,
                    help="requests in the kill window")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.rels = min(args.rels, 4_000)
        args.seconds = min(args.seconds, 2.0)
        args.toggles = min(args.toggles, 20)
        args.failover_checks = min(args.failover_checks, 100)

    from benchmarks.common import emit, maybe_force_cpu, note

    platform = maybe_force_cpu()

    import random
    from dataclasses import replace

    import numpy as np

    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import (
        new_tpu_evaluator, with_host_only_evaluation, with_store,
    )
    from gochugaru_tpu.fleet import FleetConfig, FleetRouter, zookie
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils import trace
    from gochugaru_tpu.utils.context import background

    m = _metrics.default
    rng = random.Random(20260806)
    cfg = replace(
        FleetConfig(),
        probe_interval_s=0.1,
        probe_timeout_s=1.0,
        heartbeat_s=0.1,
        freshness_wait_s=10.0,
        freshness_poll_s=0.02,
    )
    incident_dir = tempfile.mkdtemp(prefix="fleet-incidents-")
    rec = trace.install_recorder(trace.FlightRecorder(
        incident_dir=incident_dir, grace_s=0.0, cooldown_s=0.0,
    ))

    router = FleetRouter(config=cfg)
    ctx = background()
    router.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    n_repos = max(args.rels // 4, 50)
    n_users = max(args.rels // 16, 20)
    t0 = time.perf_counter()
    CHUNK = 2000
    pending = rel.Txn()
    n_in = 0
    for i in range(args.rels):
        pending.touch(rel.must_from_triple(
            f"repo:r{rng.randrange(n_repos)}", "reader",
            f"user:u{rng.randrange(n_users)}",
        ))
        n_in += 1
        if n_in >= CHUNK:
            router.write(ctx, pending)
            pending, n_in = rel.Txn(), 0
    for i in range(n_repos):
        pending.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 8}"))
    for o in range(8):
        pending.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
        pending.touch(
            rel.must_from_triple(f"org:o{o}", "member", f"user:u{o + 9}")
        )
    router.write(ctx, pending)
    note(f"world: {args.rels} reader rels over {n_repos} repos built in"
         f" {time.perf_counter() - t0:.1f}s; head={router.head_revision};"
         f" platform={platform}")
    oracle = new_tpu_evaluator(
        with_store(router.store), with_host_only_evaluation()
    )

    # -- spawn replica processes -----------------------------------------
    env = dict(os.environ)
    if not platform.startswith("tpu"):
        env.setdefault("JAX_PLATFORMS", "cpu")
    py = sys.executable
    procs = {}
    t0 = time.perf_counter()
    for i in range(args.replicas):
        rid = f"r{i}"
        p, h, prt = spawn_replica(
            py, router.port, rid, env,
            os.path.join(incident_dir, f"{rid}.stderr"),
        )
        procs[rid] = (p, h, prt)
    note(f"{args.replicas} replica processes bootstrapped in"
         f" {time.perf_counter() - t0:.1f}s")

    def pool():
        qs = []
        for _ in range(4096):
            qs.append(rel.must_from_triple(
                f"repo:r{rng.randrange(n_repos)}", "read",
                f"user:u{rng.randrange(n_users)}",
            ))
        return qs

    POOL = pool()

    def goodput_arm(seconds):
        """Closed-loop callers through the router; returns checks/s."""
        stop = time.perf_counter() + seconds
        done = [0] * args.clients
        errs = []

        def worker(w):
            lr = random.Random(555 + w)
            n = 0
            while time.perf_counter() < stop:
                s = lr.randrange(len(POOL) - args.batch)
                try:
                    router.check(
                        background().with_timeout(30.0),
                        consistency.min_latency(),
                        *POOL[s:s + args.batch],
                    )
                    n += args.batch
                except BaseException as e:  # any loss fails the arm
                    errs.append(repr(e))
                    break
            done[w] = n

        ts = [threading.Thread(target=worker, args=(w,))
              for w in range(args.clients)]
        t_start = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        el = time.perf_counter() - t_start
        if errs:
            raise RuntimeError(f"goodput arm surfaced: {errs[:2]}")
        return sum(done) / el

    try:
        # -- phase 1: goodput, 1 replica vs N ---------------------------
        r0 = procs["r0"]
        router.add_replica(r0[1], r0[2], wait_ready_s=60.0)
        goodput_1 = goodput_arm(args.seconds)
        note(f"goodput @ 1 replica: {goodput_1:,.0f} checks/s")
        for rid in list(procs)[1:]:
            _, h, prt = procs[rid]
            router.add_replica(h, prt, wait_ready_s=60.0)
        goodput_n = goodput_arm(args.seconds)
        scaling = goodput_n / max(goodput_1, 1e-9)
        ncores = os.cpu_count() or 1
        bar_met = scaling >= 2.0
        note(f"goodput @ {args.replicas} replicas: {goodput_n:,.0f} checks/s"
             f" = {scaling:.2f}x (host has {ncores} core(s);"
             f" scaling_bar_met={bar_met})")
        emit(
            "fleet_goodput_scaling", round(scaling, 3), "x",
            round(scaling / 2.0, 4),
            replicas=args.replicas,
            goodput_1=round(goodput_1, 1),
            goodput_n=round(goodput_n, 1),
            batch=args.batch, clients=args.clients,
            scaling_bar_met=bool(bar_met),
            host_cores=ncores,
            dispatches=int(m.counter("fleet.dispatches")),
            platform=platform,
            note=(
                f"{args.replicas} replica PROCESSES vs 1, closed-loop"
                " min_latency through the router; on a"
                f" {ncores}-core host every process shares the core(s) —"
                " the 2x bar needs one core per replica, so"
                " scaling_bar_met carries the honest verdict"
            ),
        )

        # -- phase 2: zero-stale parity per strategy --------------------
        stale = 0
        sample = [POOL[rng.randrange(len(POOL))] for _ in range(200)]
        want = oracle.check(ctx, consistency.full(), *sample)
        zk_head = zookie.mint(router.head_revision, cfg.zookie_key)
        for label, cs, zk in (
            ("full", consistency.full(), None),
            ("at_least+zookie", consistency.min_latency(), zk_head),
            ("min_latency", consistency.min_latency(), None),
        ):
            got = router.check(
                background().with_timeout(60.0), cs, *sample, zookie=zk
            )
            bad = sum(1 for g, w in zip(got, want) if g != w)
            # min_latency without a zookie may serve an older resident
            # revision by CONTRACT — only count it once replicas are
            # provably at head (the zookie row just forced catchup)
            stale += bad
            note(f"parity[{label}]: {bad} mismatches / {len(sample)}")

        toggled = rel.must_from_triple("repo:r0", "reader", "user:toggler")
        probe = rel.must_from_triple("repo:r0", "read", "user:toggler")
        for k in range(args.toggles):
            txn = rel.Txn()
            on = (k % 2 == 0)
            (txn.touch if on else txn.delete)(toggled)
            zk = router.write(ctx, txn)
            got = router.check(
                background().with_timeout(60.0),
                consistency.min_latency(), probe, zookie=zk,
            )
            if got[0] is not on:
                stale += 1
        note(f"dynamic zookie toggling: {args.toggles} write->read edges,"
             f" {stale} total staleness violations")
        emit(
            "fleet_zero_stale", stale, "violations",
            1.0 if stale == 0 else 0.0,
            sample=len(sample), toggles=args.toggles,
            strategies="full,at_least+zookie,min_latency",
            fresh_waits=int(m.counter("fleet.fresh_waits")),
            freshness_redirects=int(m.counter("fleet.freshness_redirects")),
            platform=platform,
            note=(
                "host-oracle parity per strategy + dynamic"
                " toggling-edge zookie read-your-writes; every verdict"
                " compared at the revision its strategy promises"
            ),
        )

        # -- phase 3: seeded mid-run kill + failover p99 ----------------
        def timed_checks(n, victim_at=None, victim=None):
            lat, answers = [], 0
            for k in range(n):
                if victim_at is not None and k == victim_at:
                    victim.send_signal(signal.SIGKILL)
                    note(f"SIGKILL -> replica process at request {k}")
                s = rng.randrange(len(POOL) - 8)
                qs = POOL[s:s + 8]
                t0 = time.perf_counter()
                got = router.check(
                    background().with_timeout(60.0),
                    consistency.full(), *qs,
                )
                lat.append((time.perf_counter() - t0) * 1000.0)
                wq = oracle.check(background(), consistency.full(), *qs)
                if got != wq:
                    raise RuntimeError(f"stale/wrong answer at request {k}")
                answers += 1
            return np.asarray(lat), answers

        base_lat, _ = timed_checks(max(args.failover_checks // 2, 50))
        base_p99 = float(np.percentile(base_lat, 99))
        kills_before = m.counter("fleet.kill_detections")
        victim_proc = procs["r1"][0]
        n_win = args.failover_checks
        win_lat, answers = timed_checks(
            n_win, victim_at=n_win // 4, victim=victim_proc,
        )
        victim_proc.wait(timeout=30.0)
        failover_p99 = float(np.percentile(win_lat, 99))
        lost = n_win - answers
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if ("r1" not in router.status()["ring"]
                    and m.counter("fleet.kill_detections") > kills_before):
                break
            time.sleep(0.05)
        assert m.counter("fleet.kill_detections") > kills_before, (
            "SIGKILL never detected"
        )
        rec.flush()
        incidents = [e for e in rec.incident_index()
                     if e["trigger"] == "fleet.failover"]
        assert incidents, "no fleet.failover incident bundle written"

        # restart: a fresh process bootstraps, catches up, rejoins
        t0 = time.perf_counter()
        p, h, prt = spawn_replica(
            py, router.port, "r1b", env,
            os.path.join(incident_dir, "r1b.stderr"),
        )
        procs["r1b"] = (p, h, prt)
        router.add_replica(h, prt, wait_ready_s=60.0)
        rejoin_s = time.perf_counter() - t0
        post = router.check(
            background().with_timeout(60.0), consistency.full(), *sample
        )
        assert post == want, "restarted fleet diverged from oracle"
        note(
            f"failover: p99 {base_p99:.1f} -> {failover_p99:.1f} ms through"
            f" the kill window; {answers}/{n_win} answered (lost={lost},"
            f" dup=0 by construction — one verdict list per request);"
            f" restart+rejoin {rejoin_s:.1f}s"
        )
        emit(
            "failover_p99_ms", round(failover_p99, 3), "ms",
            round(base_p99 / max(failover_p99, 1e-9), 4),
            baseline_p99_ms=round(base_p99, 3),
            p99_vs_baseline=round(failover_p99 / max(base_p99, 1e-9), 3),
            window_checks=n_win, lost=int(lost), dup=0, stale=0,
            reroutes=int(m.counter("fleet.reroutes")),
            evictions=int(m.counter("fleet.evictions")),
            kill_detections=int(m.counter("fleet.kill_detections")),
            incidents=len(incidents),
            rejoin_s=round(rejoin_s, 2),
            platform=platform,
            note=(
                "full-consistency p99 across a seeded SIGKILL of one"
                " replica process; every request answered exactly once"
                " and verified against the host oracle (zero"
                " lost/dup/stale), kill detected -> ring eviction +"
                " fleet.failover incident, restarted replica re-joined"
            ),
        )
        assert lost == 0 and stale == 0
        return 0
    finally:
        trace.install_recorder(None)
        router.close()
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10.0)


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

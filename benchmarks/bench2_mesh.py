"""Config-2 mesh comparison + degraded-mode columns (ADVICE item 9
foregrounded): the same GitHub-RBAC world checked on a single device,
a 1×8 mesh, and a 4×2 mesh of the 8-virtual-device CPU proxy — plus a
store-backed degraded-mode phase run under injected faults and a tight
admission gate, so shed-rate and retry-count ride the row and
degraded-mode throughput is visible in the trajectory (Graphulo measures
its degraded mode explicitly; so do we).

One JSON line:
  {"metric": "rbac_2hop_mesh_degraded_comparison", "value": <single
   rate>, ..., "mesh_1x8_rate": N, "mesh_4x2_rate": N,
   "shed_rate": N, "retry_count": N, "faults_injected": N, ...}

CPU-proxy by design (`force_cpu_platform(8)`): sharded throughput has
never been timed even on the virtual mesh (VERDICT r05 weak #6) — this
row is that timing, plus the collective-overhead ratio a real multichip
run will be judged against.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repos", type=int, default=2000)
    ap.add_argument("--users", type=int, default=500)
    ap.add_argument("--batch", type=int, default=32_768)
    args = ap.parse_args()

    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)

    import jax
    import numpy as np

    from benchmarks.common import NORTH_STAR_RATE, emit, note, peak_rss_mb
    from bench import build_world
    from gochugaru_tpu.engine.device import DeviceEngine

    jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    cs, snap, users, repos, slot = build_world(
        n_repos=args.repos, n_users=args.users
    )
    note(f"world: edges={snap.num_edges} repos={args.repos}")
    B = args.batch
    rng = np.random.default_rng(5)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)

    def rate_of(engine, label):
        """Steady-state checks/s of one engine's columnar dispatch."""
        dsnap = engine.prepare(snap)
        fn = lambda: engine.check_columns(
            dsnap, q_res, q_perm, q_subj, now_us=1_700_000_000_000_000
        )
        d0, _, _ = fn()  # warm: compile + page-in
        fn()
        reps = 6
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        dt = time.perf_counter() - t0
        note(f"{label}: {reps * B / dt:,.0f} checks/s granted={int(d0.sum())}")
        return reps * B / dt

    single_rate = rate_of(DeviceEngine(cs), "single-device")

    mesh_rates = {}
    for shape in ((1, 8), (4, 2)):
        key = f"mesh_{shape[0]}x{shape[1]}_rate"
        try:
            from gochugaru_tpu.parallel import ShardedEngine, make_mesh

            eng = ShardedEngine(cs, make_mesh(*shape))
            mesh_rates[key] = round(rate_of(eng, key), 1)
        except Exception as e:  # mesh unavailable: report, don't die
            note(f"{key} failed: {type(e).__name__}: {e}")
            mesh_rates[key] = None

    # ---- degraded-mode phase: client checks under injected faults ------
    # store-backed world so the full client path (admission gate, retry
    # envelope, breaker) is the thing being measured
    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import (
        new_tpu_evaluator,
        with_admission_control,
        with_latency_mode,
    )
    from gochugaru_tpu.utils import faults
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils.admission import AdmissionConfig
    from gochugaru_tpu.utils.context import background

    c = new_tpu_evaluator(
        with_latency_mode(),
        with_admission_control(
            AdmissionConfig(max_inflight=2, breaker_threshold=4)
        ),
    )
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition team { relation member: user }
    definition org {
        relation admin: user
        relation member: user | team#member
    }
    definition repo {
        relation org: org
        relation maintainer: user | team#member
        relation reader: user
        permission admin = org->admin + maintainer
        permission read = reader + admin + org->member
    }
    """)
    wrng = np.random.default_rng(11)
    txn = rel.Txn()
    for i in range(200):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{wrng.integers(100)}"
        ))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", "org:o0"))
    txn.touch(rel.must_from_triple("org:o0", "admin", "user:u0"))
    c.write(ctx, txn)

    m = _metrics.default
    base = m.snapshot()
    # seeded 5%-probability dispatch faults: the degraded mode under test
    faults.arm("device.dispatch", probability=0.05, seed=42)
    faults.arm("latency.dispatch", probability=0.05, seed=43)

    import threading

    DB, PER_WORKER, WORKERS = 64, 25, 4
    checks_done = [0] * WORKERS

    def worker(w):
        lrng = np.random.default_rng(100 + w)
        for _ in range(PER_WORKER):
            qs = [
                rel.must_from_triple(
                    f"repo:r{lrng.integers(200)}", "read",
                    f"user:u{lrng.integers(100)}",
                )
                for _ in range(DB)
            ]
            c.check(background().with_timeout(30.0), consistency.full(), *qs)
            checks_done[w] += DB

    c.check(ctx, consistency.full(),
            rel.must_from_triple("repo:r0", "read", "user:u0"))  # warm
    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(w,)) for w in range(WORKERS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    degraded_dt = time.perf_counter() - t0
    faults.reset()
    snap_m = m.snapshot()

    def delta(key):
        return snap_m.get(key, 0) - base.get(key, 0)

    total_checks = sum(checks_done)
    sheds = delta("admission.sheds") + delta("admission.deadline_sheds")
    retries = delta("retry.retries")
    injected = delta("faults.injected")
    degraded_rate = total_checks / degraded_dt

    emit(
        "rbac_2hop_mesh_degraded_comparison",
        round(single_rate, 1),
        "checks/sec",
        single_rate / NORTH_STAR_RATE,
        **mesh_rates,
        degraded_rate=round(degraded_rate, 1),
        shed_rate=round(sheds / max(total_checks / DB, 1), 4),
        retry_count=int(retries),
        faults_injected=int(injected),
        breaker_trips=int(delta("breaker.trips")),
        edges=int(snap.num_edges),
        batch=int(B),
        peak_rss_mb=peak_rss_mb(),
        platform=jax.default_backend(),
        note=(
            "CPU proxy (8 virtual devices); mesh = data x model;"
            " degraded phase: 5% injected dispatch faults,"
            " max_inflight=2, 4 workers"
        ),
    )
    return 0


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

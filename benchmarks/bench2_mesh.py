"""Config-2 mesh comparison + degraded-mode columns (ADVICE item 9
foregrounded): the same GitHub-RBAC world checked on a single device,
a 1×8 mesh, and a 4×2 mesh of the 8-virtual-device CPU proxy — plus a
store-backed degraded-mode phase run under injected faults and a tight
admission gate, so shed-rate and retry-count ride the row and
degraded-mode throughput is visible in the trajectory (Graphulo measures
its degraded mode explicitly; so do we).

One JSON line:
  {"metric": "rbac_2hop_mesh_degraded_comparison", "value": <single
   rate>, ..., "mesh_1x8_rate": N, "mesh_4x2_rate": N,
   "shed_rate": N, "retry_count": N, "faults_injected": N, ...}

CPU-proxy by design (`force_cpu_platform(8)`): sharded throughput has
never been timed even on the virtual mesh (VERDICT r05 weak #6) — this
row is that timing, plus the collective-overhead ratio a real multichip
run will be judged against.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repos", type=int, default=2000)
    ap.add_argument("--users", type=int, default=500)
    ap.add_argument("--batch", type=int, default=32_768)
    args = ap.parse_args()

    from gochugaru_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)

    import jax
    import numpy as np

    from benchmarks.common import NORTH_STAR_RATE, emit, note, peak_rss_mb
    from bench import build_world
    from gochugaru_tpu.engine.device import DeviceEngine

    jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    cs, snap, users, repos, slot = build_world(
        n_repos=args.repos, n_users=args.users
    )
    note(f"world: edges={snap.num_edges} repos={args.repos}")
    B = args.batch
    rng = np.random.default_rng(5)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)

    def rate_of(engine, label, prepare=None):
        """Steady-state checks/s of one engine's columnar dispatch;
        returns (rate, DeviceSnapshot, warm (d, p, o))."""
        dsnap = (prepare or engine.prepare)(snap)
        fn = lambda: engine.check_columns(
            dsnap, q_res, q_perm, q_subj, now_us=1_700_000_000_000_000
        )
        out0 = fn()  # warm: compile + page-in
        fn()
        reps = 6
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        dt = time.perf_counter() - t0
        note(f"{label}: {reps * B / dt:,.0f} checks/s"
             f" granted={int(out0[0].sum())}")
        return reps * B / dt, dsnap, out0

    single_rate, _ds, single_out = rate_of(DeviceEngine(cs), "single-device")

    mesh_rates = {}
    for shape in ((1, 8), (4, 2)):
        key = f"mesh_{shape[0]}x{shape[1]}_rate"
        try:
            from gochugaru_tpu.parallel import ShardedEngine, make_mesh

            eng = ShardedEngine(cs, make_mesh(*shape))
            mesh_rates[key] = round(rate_of(eng, key)[0], 1)
        except Exception as e:  # mesh unavailable: report, don't die
            note(f"{key} failed: {type(e).__name__}: {e}")
            mesh_rates[key] = None

    # ---- partitioned serving: owner-routed vs replicated, 4 devices -----
    # The pre-PR way to serve a fold-bearing schema collective-free is
    # data-parallel replication (mesh M×1: every device holds the FULL
    # stacked+fold tables, batch splits along data).  The partitioned
    # serve (mesh 1×M, serve="routed") model-splits the primary/fold
    # point tables — O(E/M) HBM per device — and owner-routes each query
    # to its bucket's shard, also with no collective in the compiled
    # program.  Same 4 devices, same batch, same answers; the row is the
    # HBM-per-device vs throughput trade.
    def table_bytes_per_device(dsnap):
        """Max over devices of resident stacked+fold table bytes
        (node_type/caveat-context lookups excluded on both sides)."""
        per = {}
        for k, arr in dsnap.arrays.items():
            if k == "node_type" or k.startswith("ectx_"):
                continue
            for s in arr.addressable_shards:
                per[s.device.id] = (
                    per.get(s.device.id, 0) + int(np.asarray(s.data).nbytes)
                )
        return max(per.values())

    part_fields = {}
    try:
        from gochugaru_tpu.parallel import ShardedEngine, make_mesh

        M = 4
        rep_eng = ShardedEngine(cs, make_mesh(M, 1))
        rep_rate, rep_ds, rep_out = rate_of(
            rep_eng, "replicated 4-dev (data-parallel)"
        )
        rt_eng = ShardedEngine(cs, make_mesh(1, M))
        rt_rate, rt_ds, rt_out = rate_of(
            rt_eng, "partitioned 4-dev (owner-routed)",
            prepare=rt_eng.prepare_snapshot_partitioned,
        )
        if not (rt_ds.flat_meta is not None and rt_ds.flat_meta.part_serve):
            raise RuntimeError("partitioned feed declined the bench world")
        oracle_match = all(
            np.array_equal(a, b) for a, b in zip(single_out, rt_out)
        ) and all(np.array_equal(a, b) for a, b in zip(single_out, rep_out))
        rep_bytes = table_bytes_per_device(rep_ds)
        rt_bytes = table_bytes_per_device(rt_ds)
        note(
            f"table bytes/device: replicated {rep_bytes:,} vs routed"
            f" {rt_bytes:,} ({rt_bytes / rep_bytes:.1%});"
            f" rate routed/replicated {rt_rate / rep_rate:.2f}x"
            f" oracle_match={oracle_match}"
        )
        part_fields = dict(
            routed_rate=round(rt_rate, 1),
            replicated_rate=round(rep_rate, 1),
            table_bytes_per_device=int(rt_bytes),
            replicated_table_bytes_per_device=int(rep_bytes),
            table_bytes_ratio=round(rt_bytes / rep_bytes, 4),
            rate_vs_replicated=round(rt_rate / rep_rate, 4),
            oracle_match=bool(oracle_match),
        )
    except Exception as e:  # mesh/feed unavailable: report, don't die
        note(f"partitioned_serving failed: {type(e).__name__}: {e}")

    # ---- degraded-mode phase: client checks under injected faults ------
    # store-backed world so the full client path (admission gate, retry
    # envelope, breaker) is the thing being measured
    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import (
        new_tpu_evaluator,
        with_admission_control,
        with_latency_mode,
    )
    from gochugaru_tpu.utils import faults
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils.admission import AdmissionConfig
    from gochugaru_tpu.utils.context import background

    c = new_tpu_evaluator(
        with_latency_mode(),
        with_admission_control(
            AdmissionConfig(max_inflight=2, breaker_threshold=4)
        ),
    )
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition team { relation member: user }
    definition org {
        relation admin: user
        relation member: user | team#member
    }
    definition repo {
        relation org: org
        relation maintainer: user | team#member
        relation reader: user
        permission admin = org->admin + maintainer
        permission read = reader + admin + org->member
    }
    """)
    wrng = np.random.default_rng(11)
    txn = rel.Txn()
    for i in range(200):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{wrng.integers(100)}"
        ))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", "org:o0"))
    txn.touch(rel.must_from_triple("org:o0", "admin", "user:u0"))
    c.write(ctx, txn)

    m = _metrics.default
    base = m.snapshot()
    # seeded 5%-probability dispatch faults: the degraded mode under test
    faults.arm("device.dispatch", probability=0.05, seed=42)
    faults.arm("latency.dispatch", probability=0.05, seed=43)

    import threading

    DB, PER_WORKER, WORKERS = 64, 25, 4
    checks_done = [0] * WORKERS

    def worker(w):
        lrng = np.random.default_rng(100 + w)
        for _ in range(PER_WORKER):
            qs = [
                rel.must_from_triple(
                    f"repo:r{lrng.integers(200)}", "read",
                    f"user:u{lrng.integers(100)}",
                )
                for _ in range(DB)
            ]
            c.check(background().with_timeout(30.0), consistency.full(), *qs)
            checks_done[w] += DB

    c.check(ctx, consistency.full(),
            rel.must_from_triple("repo:r0", "read", "user:u0"))  # warm
    # queue-depth sampling during the degraded phase: the gate's
    # in-flight gauge is this path's queue, reported with the SAME
    # column names the serving bench uses (bench9_serve.py), so the
    # sharded and serving stories share a schema
    depth_samples = []
    stop_sampler = threading.Event()

    def depth_sampler():
        while not stop_sampler.is_set():
            depth_samples.append(m.gauge("admission.inflight"))
            time.sleep(0.002)

    sampler_t = threading.Thread(target=depth_sampler, daemon=True)
    sampler_t.start()
    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(w,)) for w in range(WORKERS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    degraded_dt = time.perf_counter() - t0
    stop_sampler.set()
    sampler_t.join(timeout=1.0)
    faults.reset()
    snap_m = m.snapshot()
    qd = np.asarray(depth_samples) if depth_samples else np.zeros(1)

    def delta(key):
        return snap_m.get(key, 0) - base.get(key, 0)

    total_checks = sum(checks_done)
    sheds = delta("admission.sheds") + delta("admission.deadline_sheds")
    retries = delta("retry.retries")
    injected = delta("faults.injected")
    degraded_rate = total_checks / degraded_dt

    emit(
        "rbac_2hop_mesh_degraded_comparison",
        round(single_rate, 1),
        "checks/sec",
        single_rate / NORTH_STAR_RATE,
        **mesh_rates,
        degraded_rate=round(degraded_rate, 1),
        shed_rate=round(sheds / max(total_checks / DB, 1), 4),
        queue_depth_p50=round(float(np.percentile(qd, 50)), 1),
        queue_depth_max=int(qd.max()),
        retry_count=int(retries),
        faults_injected=int(injected),
        breaker_trips=int(delta("breaker.trips")),
        edges=int(snap.num_edges),
        batch=int(B),
        peak_rss_mb=peak_rss_mb(),
        platform=jax.default_backend(),
        note=(
            "CPU proxy (8 virtual devices); mesh = data x model;"
            " degraded phase: 5% injected dispatch faults,"
            " max_inflight=2, 4 workers"
        ),
    )
    if part_fields:
        emit(
            "partitioned_serving",
            part_fields["routed_rate"],
            "checks/sec",
            part_fields["routed_rate"] / NORTH_STAR_RATE,
            **part_fields,
            edges=int(snap.num_edges),
            batch=int(B),
            platform=jax.default_backend(),
            note=(
                "4-dev CPU proxy: owner-routed partitioned serve"
                f" ({part_fields['table_bytes_ratio']:.0%} table bytes"
                "/device) vs data-parallel replicated baseline"
                f" ({part_fields['rate_vs_replicated']:.2f}x rate),"
                " fold engaged, collective-free both"
            ),
        )
    return 0


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""BASELINE config 3 — Google-Docs-style nested groups: 1M docs / 10M
edges, 5-hop recursive userset rewrites (folder trees + nested groups),
100k-check batches on one chip.

Recursion exercised: ``folder#view = viewer + parent->view`` is a
self-recursive arrow (SpiceDB's recursive hierarchy pattern) and
``group#member`` nests 5 deep — both the closure walk and the subgraph
fixpoint must iterate (SURVEY.md §7 "recursive/unbounded rewrites").
"""

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import (
    maybe_force_cpu,
    NORTH_STAR_P99_MS,
    NORTH_STAR_RATE,
    emit,
    emit_small_batch_row,
    join_lookup_prewarm,
    latency_percentiles,
    note,
    time_steady,
)

SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user | group#member
    permission view = viewer + folder->view
}
"""

import argparse as _argparse

_scale_args = _argparse.ArgumentParser()
_scale_args.add_argument("--scale", type=float, default=1.0)
_SCALE = _scale_args.parse_known_args()[0].scale

N_USERS = max(int(100_000 * _SCALE), 100)
N_GROUPS = max(int(10_000 * _SCALE), 20)
N_FOLDERS = max(int(50_000 * _SCALE), 50)
N_DOCS = max(int(1_000_000 * _SCALE), 1_000)
BATCH = 100_000
SEED = 23
EPOCH = 1_700_000_000_000_000


def build_world():
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rng = np.random.default_rng(SEED)

    users = np.array(
        [interner.node("user", f"u{i}") for i in range(N_USERS)], np.int64
    )
    groups = np.array(
        [interner.node("group", f"g{i}") for i in range(N_GROUPS)], np.int64
    )
    folders = np.array(
        [interner.node("folder", f"f{i}") for i in range(N_FOLDERS)], np.int64
    )
    docs = np.array(
        [interner.node("document", f"d{i}") for i in range(N_DOCS)], np.int64
    )
    slot = cs.slot_of_name
    member, parent, viewer, folder_rel = (
        slot["member"], slot["parent"], slot["viewer"], slot["folder"],
    )

    res, rel, subj, srel = [], [], [], []

    def bulk(r, rl, s, sr):
        res.append(np.asarray(r, np.int64))
        rel.append(np.full(len(r), rl, np.int64))
        subj.append(np.asarray(s, np.int64))
        srel.append(np.full(len(r), sr, np.int64))

    # group nesting: chains of depth 5 (g[i] contains g[i+1]#member);
    # leaves get direct user members
    chain_mask = np.arange(N_GROUPS - 1)
    deep = chain_mask[(chain_mask % 5) != 4]  # break chains every 5 groups
    bulk(groups[deep], member, groups[deep + 1], member)
    per_group = 6
    gm_res = np.repeat(groups, per_group)
    bulk(gm_res, member, rng.choice(users, gm_res.shape[0]), -1)

    # folder trees: arity-16 forest → depth ≤ ⌈log16(50k)⌉ = 4, so a doc
    # check traverses ≤ 5 arrows (doc → folder → … → root)
    f_idx = np.arange(1, N_FOLDERS)
    parents = (f_idx - 1) // 16
    bulk(folders[f_idx], parent, folders[parents], -1)
    # folder viewers: mostly groups (userset), some direct
    fv = rng.random(N_FOLDERS) < 0.5
    bulk(folders[fv], viewer, rng.choice(groups, int(fv.sum())), member)
    bulk(folders[~fv], viewer, rng.choice(users, int((~fv).sum())), -1)

    # documents: every doc in a folder; ~20% also have direct viewers
    bulk(docs, folder_rel, rng.choice(folders, N_DOCS), -1)
    extra = rng.random(N_DOCS) < 0.2
    bulk(docs[extra], viewer, rng.choice(users, int(extra.sum())), -1)
    # top up with group-viewer docs to reach ~10M edges, spread evenly so
    # per-(doc, viewer) userset fan-in stays within the engine's leaf cap
    # (a doc with 30 viewer-groups is a modeling smell, not a workload)
    cur = sum(a.shape[0] for a in res)
    want = int(10_000_000 * _SCALE)
    if cur < want:
        k = want - cur
        per_doc = k // N_DOCS  # uniform: stays within the us leaf cap
        dd = np.repeat(docs, per_doc)
        bulk(dd, viewer, rng.choice(groups, dd.shape[0]), member)
        rem = k - dd.shape[0]
        if rem:  # remainder as DIRECT viewers: no userset fan-in cap risk
            bulk(docs[:rem], viewer, rng.choice(users, rem), -1)

    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=np.concatenate(res), rel=np.concatenate(rel),
        subj=np.concatenate(subj), srel=np.concatenate(srel),
        epoch_us=EPOCH,
    )
    return cs, snap, users, docs, slot


def main() -> None:
    note(f"platform={maybe_force_cpu()}")
    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, docs, slot = build_world()
    note(f"edges={snap.num_edges} nodes={snap.num_nodes}")
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    join_lookup_prewarm()

    rng = np.random.default_rng(7)
    B = 1 << (BATCH - 1).bit_length()
    q_res = rng.choice(docs, B).astype(np.int32)
    q_perm = np.full(B, slot["view"], np.int32)
    q_subj = rng.choice(users, B).astype(np.int32)

    # pipelined throughput over the PRE-LOWERED kernel (the bench.py
    # methodology: the per-batch query lowering is host work a loaded
    # service overlaps with device execution); p99 below stays the full
    # end-to-end roundtrip including lowering and the device→host fetch.
    # NOTE: this pre-lowered dispatch (and common.time_steady's 3×
    # warmup) arrived in round 4 alongside the permission fold — round-3
    # numbers used the roundtrip path, so cross-round comparisons mix
    # the fold's algorithmic gain with this methodology change
    import jax.numpy as jnp

    queries, qctx = engine._columns_preamble(
        dsnap, q_res, q_perm, q_subj, None, None, None, None
    )
    fn, args = engine.flat_fn_and_args(
        dsnap, queries, qctx, jnp.int32(snap.now_rel32(EPOCH)), B
    )

    def dispatch():  # pipelined device dispatch, no per-call readback
        return fn(*args)

    def roundtrip():  # end-to-end including the device→host fetch
        return engine.check_columns(dsnap, q_res, q_perm, q_subj, now_us=EPOCH)

    dt = time_steady(dispatch, reps=5)
    rate = B / dt
    d, p, ovf = roundtrip()
    note(
        f"batch={B} step={dt*1000:.1f}ms granted={int(d.sum())}"
        f" overflow={int(ovf.sum())}"
    )
    from benchmarks.common import roofline_columns, table_bytes

    emit(
        "docs_5hop_bulk_check_throughput", rate, "checks/sec/chip",
        rate / NORTH_STAR_RATE, edges=int(snap.num_edges), batch=int(B),
        table_bytes_per_edge=round(
            table_bytes(dsnap) / max(int(snap.num_edges), 1), 2
        ),
        **roofline_columns(rate, dsnap=dsnap),
    )
    p50, p99, mean = latency_percentiles(roundtrip, reps=20)
    emit("docs_5hop_batch_p99_latency", p99, "ms",
         NORTH_STAR_P99_MS / max(p99, 1e-9),
         edges=int(snap.num_edges), batch=int(B))
    note(f"p50={p50:.2f}ms p99={p99:.2f}ms mean={mean:.2f}ms")

    # latency-mode small batch at spec scale (engine/latency.py): the
    # p99-half of the north star measured on an interactive-sized
    # dispatch instead of the 131k-item scan above, with the
    # host/H2D/kernel/D2H budget breakdown on the row
    try:
        SB = 2048
        emit_small_batch_row(
            "docs_5hop_small_batch_p99_latency", engine, dsnap,
            q_res[:SB].copy(), q_perm[:SB].copy(), q_subj[:SB].copy(),
            edges=int(snap.num_edges), now_us=EPOCH,
        )
    except Exception as e:  # optional row must never cost the main ones
        note(f"small-batch latency section failed: {type(e).__name__}: {e}")

    # the lookup surface has its own bench now: benchmarks/bench8_lookup.py
    # (candidate-resources/s TRUE rate, first-result latency, full-answer
    # throughput — the ad-hoc docs_lookup_resources_latency probe that
    # lived here is superseded by those columns)
    note("lookup columns: see bench8_lookup.py (run_all config 11)")


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

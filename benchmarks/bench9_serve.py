"""Open-loop serving bench: Poisson traffic through the continuous
batcher vs the closed-loop pinned-tier rate.

Every other bench hands the engine pre-formed batches (closed loop: the
next batch waits for the last — the load adapts to the server, hiding
queueing).  This one is OPEN loop, the honest serving methodology:
submissions arrive on a Poisson process at a FIXED offered load whether
or not the server keeps up, subjects are zipf-skewed, and each
submission is a small CheckMany (the reference's request shape).  The
micro-batch former (gochugaru_tpu/serve/) coalesces them onto the
pinned tier ladder; we report goodput, shed rate, the batch-occupancy
histogram, and queue+service p50/p99 per offered-load step — so the
headline reads "N concurrent clients at p99 ≤ Y ms", not batch
throughput.

Since round 19 the file also measures the revision-pinned verdict
cache + in-flight dedup (engine/vcache.py, `with_serving(cache=True)`
at min_latency).  The SWEEP stays cache-off — byte-for-byte the
pre-cache serving path, so the committed serve_openloop_goodput
trajectory remains apples-to-apples — and the cache rides alongside:
a cache-on companion row at the top offered load (``cache="on"``, with
``cache_hit_rate`` / ``dedup_frac`` / ``unique_frac`` columns), plus
two same-run A/Bs: ``serve_cache_ab`` (the headline — blocking
request-path checks over zipf-hot tuples, where a cache hit skips the
evaluator round trip a blocking caller waits out) and
``serve_cache_openloop_ab`` (open-loop saturation through the serving
handle — on the 1-core proxy wall-clock is ~parity because the
front-end shares the core and the kernel already overlaps host Python;
what collapses is device rows dispatched per answered check, and the
goodput multiplier belongs to silicon).  The cache win is an in-file
A/B, not a cross-round comparison.

Honesty rules: the closed-loop denominator is measured in THIS process
at the serving tier; latencies are per-submission submit→resolve times
from the futures themselves (no waiting threads in the hot path);
oracle parity is sampled on real coalesced answers — INCLUDING
cache-served ones; zero retraces is asserted from the latency.compiles
counter across the whole sweep (single-slot tier shapes are pre-pinned:
a cache-shrunk residual batch can be read-only or admin-only).

One JSON line per load step ("serve_openloop_sweep") plus the headline
("serve_openloop_goodput") at the highest load whose queue+service p99
stays within 3x the quiet-window small-batch p99.
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPOCH_US = 1_700_000_000_000_000


def build_store_world(client, n_repos, n_users, n_orgs, edges, rng):
    """GitHub-RBAC-shaped world imported columnarly through the client
    (the serving handle needs a store-backed snapshot chain)."""
    import numpy as np

    from gochugaru_tpu.utils.context import background

    ctx = background()
    client.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    ru = rng.integers(0, n_users, edges)
    rr = rng.integers(0, n_repos, edges)
    client.import_relationship_columns(
        ctx, resource_type="repo",
        resource_ids=[f"r{i}" for i in rr], resource_relation="reader",
        subject_type="user", subject_ids=[f"u{i}" for i in ru],
    )
    client.import_relationship_columns(
        ctx, resource_type="repo",
        resource_ids=[f"r{i}" for i in range(n_repos)],
        resource_relation="org", subject_type="org",
        subject_ids=[f"o{i % n_orgs}" for i in range(n_repos)],
    )
    client.import_relationship_columns(
        ctx, resource_type="org",
        resource_ids=[f"o{i}" for i in range(n_orgs)],
        resource_relation="admin", subject_type="user",
        subject_ids=[f"u{i % n_users}" for i in range(n_orgs)],
    )
    mu = rng.integers(0, n_users, n_orgs * 4)
    client.import_relationship_columns(
        ctx, resource_type="org",
        resource_ids=[f"o{i % n_orgs}" for i in range(n_orgs * 4)],
        resource_relation="member", subject_type="user",
        subject_ids=[f"u{i}" for i in mu],
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=200_000)
    ap.add_argument("--repos", type=int, default=20_000)
    ap.add_argument("--users", type=int, default=5_000)
    ap.add_argument("--seconds", type=float, default=4.0,
                    help="measurement window per offered-load step")
    ap.add_argument("--loads", default="0.5,0.8,0.9",
                    help="offered load as fractions of the closed-loop rate")
    ap.add_argument("--submit", type=int, default=64,
                    help="checks per submission (CheckMany size)")
    ap.add_argument("--clients", type=int, default=32,
                    help="distinct fairness client ids in the arrival stream")
    ap.add_argument("--zipf", type=float, default=1.2,
                    help="zipf exponent for subject skew")
    ap.add_argument("--oracle-samples", type=int, default=50,
                    help="coalesced submissions re-checked on the host oracle")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the verdict-cache companion row and A/Bs"
                         " (the sweep itself is always cache-off — the"
                         " pre-round-19 bench byte-for-byte)")
    ap.add_argument("--no-ab", action="store_true",
                    help="skip the cache on/off saturation A/B")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.edges = min(args.edges, 50_000)
        args.repos = min(args.repos, 5_000)
        args.seconds = min(args.seconds, 2.0)

    from benchmarks.common import (
        NORTH_STAR_RATE,
        emit,
        maybe_force_cpu,
        note,
        small_batch_latency,
    )

    platform = maybe_force_cpu()
    import gc

    import numpy as np

    from gochugaru_tpu import consistency
    from gochugaru_tpu.client import new_tpu_evaluator, with_latency_mode
    from gochugaru_tpu.serve import ServeConfig
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils import perf as _perf
    from gochugaru_tpu.utils.context import background
    from gochugaru_tpu.utils.errors import ShedError

    rng = np.random.default_rng(5)
    c = new_tpu_evaluator(with_latency_mode())
    t0 = time.perf_counter()
    build_store_world(c, args.repos, args.users, 16, args.edges, rng)
    cs = consistency.full()
    ctx = background()
    snap = c.store.snapshot_for(cs)
    engine = c._engine_for(snap)
    dsnap = c._dsnap_for(engine, snap)
    note(f"world: edges={snap.num_edges} built in"
         f" {time.perf_counter() - t0:.1f}s platform={platform}")

    # -- interned query pools (zipf-skewed subjects) ---------------------
    inter = snap.interner
    slot = snap.compiled.slot_of_name
    repo_ids = np.array(
        [inter.node("repo", f"r{i}") for i in range(args.repos)], np.int32
    )
    user_ids = np.array(
        [inter.node("user", f"u{i}") for i in range(args.users)], np.int32
    )
    POOL = 1 << 18
    zipf_users = (rng.zipf(args.zipf, POOL) - 1) % args.users
    pool_res = repo_ids[rng.integers(0, args.repos, POOL)]
    pool_subj = user_ids[zipf_users]
    pool_perm = np.where(
        rng.random(POOL) < 0.9, slot["read"], slot["admin"]
    ).astype(np.int32)

    # -- closed-loop pinned-tier denominator + quiet-window p99 ----------
    TIER = 1024
    lp = engine.latency_path(dsnap)
    q = (pool_res[:TIER], pool_perm[:TIER].copy(), pool_subj[:TIER])
    q[1][:] = slot["read"]  # one slot set → one pinned kernel, like serving
    for _ in range(5):
        lp.dispatch_columns(*q, now_us=EPOCH_US)
    reps = 60 if args.quick else 150
    t0 = time.perf_counter()
    for i in range(reps):
        lp.dispatch_columns(
            np.roll(q[0], i), q[1], np.roll(q[2], 2 * i), now_us=EPOCH_US
        )
    closed_rate = reps * TIER / (time.perf_counter() - t0)
    quiet = small_batch_latency(
        engine, dsnap, q[0], q[1], q[2], now_us=EPOCH_US,
        warmup=10, reps=120 if args.quick else 300,
    )
    quiet_p99_ms = quiet["p99_ms"]
    note(f"closed-loop tier-{TIER} rate {closed_rate:,.0f} checks/s;"
         f" quiet-window p99 {quiet_p99_ms} ms")

    # single-slot tier pins: the verdict cache shrinks a formed batch to
    # its unique misses, so a residual dispatch can be read-only or
    # admin-only at any tier — pin those (slot-subset, tier) shapes up
    # front so the zero-retrace assertion measures serving, not warmup
    for tier in (256, 1024, 4096):
        for sv in (slot["read"], slot["admin"]):
            qq = (pool_res[:tier], np.full(tier, sv, np.int32),
                  pool_subj[:tier])
            for _ in range(2):
                lp.dispatch_columns(*qq, now_us=EPOCH_US)

    m = _metrics.default
    cache_on = not args.no_cache
    scfg = ServeConfig(hold_max_s=0.001)
    scfg_off = ServeConfig(hold_max_s=0.001, dedup=False)

    # -- shared step machinery -------------------------------------------
    def warm_burst(handle, n, pace_s):
        """Pin every (slot-subset, tier) executable the sweep will form:
        a rapid-fire burst fills the TOP tiers, a paced trickle forms
        the small ones.  The zero-retrace assertion then covers the
        MEASURED window, the standard warm-serving discipline."""
        futs = []
        for k in range(n):
            s = int(rng.integers(0, POOL - args.submit))
            while True:
                try:
                    futs.append(handle.submit_columns(
                        ctx, pool_res[s:s + args.submit],
                        pool_perm[s:s + args.submit],
                        pool_subj[s:s + args.submit],
                        client_id=k % args.clients,
                    ))
                    break
                except ShedError:  # warm as fast as admission allows
                    time.sleep(0.005)
            if pace_s:
                time.sleep(pace_s)
        for f in futs:
            f.result(timeout=60.0)

    def cache_columns(delta, done_checks):
        hits = delta("cache.hits")
        misses = delta("cache.misses")
        uniq = delta("serve.unique_checks")
        dup = delta("serve.dedup_parked") + delta("dedup.batch_dups")
        return dict(
            cache_hit_rate=round(hits / (hits + misses), 4)
            if (hits + misses) else 0.0,
            dedup_frac=round(dup / done_checks, 4) if done_checks else 0.0,
            unique_frac=round(uniq / done_checks, 4)
            if (done_checks and uniq) else 1.0,
        )

    def run_load_step(handle, frac, offered):
        """One paced open-loop step at a fixed offered load; returns the
        row dict (including the wall-time ledger block)."""
        sub_rate = offered / args.submit
        n_subs = max(int(sub_rate * args.seconds), 16)
        gaps = rng.exponential(1.0 / sub_rate, n_subs)
        arrivals = np.cumsum(gaps)
        starts = rng.integers(0, POOL - args.submit, n_subs)
        client_ids = rng.integers(0, args.clients, n_subs)

        base0 = m.snapshot()
        futures = []
        sheds = 0
        depth_samples = []
        stop_sampler = threading.Event()

        def sampler():
            while not stop_sampler.is_set():
                depth_samples.append(m.gauge("serve.queue_depth"))
                time.sleep(0.005)

        st = threading.Thread(target=sampler, daemon=True)
        st.start()
        gc.collect()
        gc.disable()
        # closed wall-time ledger: the step's whole window accounts
        # into form/queue-wait/host-prep/H2D/kernel/D2H/filter/idle
        # buckets (utils/perf.py) — the 21× queue-vs-quiet question
        # becomes columns on the row block below
        ledger = _perf.WallLedger().start()
        t_start = time.perf_counter()
        for k in range(n_subs):
            target = t_start + arrivals[k]
            slack = target - time.perf_counter()
            if slack > 0.0015:
                # coarse pacing: sleep off the bulk, let sub-ms
                # arrivals micro-burst (Poisson in aggregate) —
                # spinning per arrival would burn the core the
                # dispatcher needs
                time.sleep(slack - 0.001)
            s = starts[k]
            try:
                futures.append(handle.submit_columns(
                    ctx,
                    pool_res[s:s + args.submit],
                    pool_perm[s:s + args.submit],
                    pool_subj[s:s + args.submit],
                    client_id=int(client_ids[k]),
                ))
            except ShedError:  # open-loop counts sheds, not retries;
                sheds += 1     # any other failure must FAIL the row
                futures.append(None)
        # drain
        deadline = time.perf_counter() + 30.0
        for f in futures:
            if f is not None:
                f.result(timeout=max(deadline - time.perf_counter(), 0.1))
        t_end = time.perf_counter()
        wall = ledger.stop()
        gc.enable()
        stop_sampler.set()
        st.join(timeout=1.0)

        lat_ms = np.array([
            (f.t_done - f.t_submit) * 1000.0
            for f in futures if f is not None
        ])
        snap_m = m.snapshot()

        def delta(key):
            return snap_m.get(key, 0) - base0.get(key, 0)

        done_checks = delta("serve.checks")
        elapsed = t_end - t_start
        goodput = done_checks / elapsed
        batches = max(delta("serve.batches"), 1)
        occ_n = delta("serve.occupancy.count")
        occ_mean = (
            delta("serve.occupancy.sum") / occ_n if occ_n else 0.0
        )
        ds = np.asarray(depth_samples) if depth_samples else np.zeros(1)
        row = dict(
            load_frac=frac,
            offered=round(offered, 1),
            goodput=round(goodput, 1),
            goodput_vs_closed=round(goodput / closed_rate, 4),
            submissions=n_subs,
            shed_rate=round(sheds / n_subs, 4),
            p50_ms=round(float(np.percentile(lat_ms, 50)), 3),
            p99_ms=round(float(np.percentile(lat_ms, 99)), 3),
            batches=int(batches),
            mean_batch=round(done_checks / batches, 1),
            occupancy_mean=round(occ_mean, 4),
            flush_full=int(delta("serve.flush_full")),
            flush_deadline=int(delta("serve.flush_deadline")),
            flush_maxhold=int(delta("serve.flush_maxhold")),
            queue_depth_p50=round(float(np.percentile(ds, 50)), 1),
            queue_depth_max=int(ds.max()),
            device_dispatches=int(delta("latency.dispatches")),
            **cache_columns(delta, done_checks),
        )
        row["wall"] = wall
        return row

    def saturation_run(handle, seconds):
        """Open-loop capacity arm of the cache A/B: submit flat-out for
        a fixed wall window with future-based backpressure (a shed
        waits on the oldest in-flight submission — real queue pressure,
        no guessed sleeps; both arms run the SAME code), drain, and
        report goodput."""
        from collections import deque

        base0 = m.snapshot()
        outstanding = deque()
        lat_ms = []
        gc.collect()
        gc.disable()
        t_start = time.perf_counter()
        t_stop = t_start + seconds
        k = 0
        while time.perf_counter() < t_stop:
            s = int(rng.integers(0, POOL - args.submit))
            try:
                outstanding.append(handle.submit_columns(
                    ctx, pool_res[s:s + args.submit],
                    pool_perm[s:s + args.submit],
                    pool_subj[s:s + args.submit],
                    client_id=k % args.clients,
                ))
                k += 1
            except ShedError:
                if outstanding:
                    f = outstanding.popleft()
                    f.result(timeout=60.0)
                    lat_ms.append((f.t_done - f.t_submit) * 1000.0)
                continue
            if len(outstanding) >= 256:
                f = outstanding.popleft()
                f.result(timeout=60.0)
                lat_ms.append((f.t_done - f.t_submit) * 1000.0)
        while outstanding:
            f = outstanding.popleft()
            f.result(timeout=60.0)
            lat_ms.append((f.t_done - f.t_submit) * 1000.0)
        t_end = time.perf_counter()
        gc.enable()
        snap_m = m.snapshot()

        def delta(key):
            return snap_m.get(key, 0) - base0.get(key, 0)

        done_checks = delta("serve.checks")
        la = np.asarray(lat_ms) if lat_ms else np.zeros(1)
        return dict(
            goodput=round(done_checks / (t_end - t_start), 1),
            checks=int(done_checks),
            p50_ms=round(float(np.percentile(la, 50)), 3),
            p99_ms=round(float(np.percentile(la, 99)), 3),
            device_dispatches=int(delta("latency.dispatches")),
            **cache_columns(delta, done_checks),
        )

    def request_path_run(client, seconds, threads, hot):
        """Blocking per-request arm of the cache A/B: ``threads``
        closed-loop callers hammer ``client.check`` (min_latency) over
        zipf-hot tuples — the reference's interactive shape, where a
        repeated read answered from a revision-pinned verdict skips the
        whole evaluator round trip (nothing overlaps a blocking call,
        so the win is wall-clock, not just device occupancy)."""
        base0 = m.snapshot()
        done = [0] * threads
        stop = time.perf_counter() + seconds

        def worker(w):
            lr = np.random.default_rng(977 + w)
            n = 0
            while time.perf_counter() < stop:
                qs = [hot[(lr.zipf(args.zipf) - 1) % len(hot)]
                      for _ in range(4)]
                client.check(ctx, serve_cs, *qs)
                n += 4
            done[w] = n

        ts = [threading.Thread(target=worker, args=(w,))
              for w in range(threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        el = time.perf_counter() - t0
        snap_m = m.snapshot()

        def delta(key):
            return snap_m.get(key, 0) - base0.get(key, 0)

        return dict(
            goodput=round(sum(done) / el, 1),
            checks=int(sum(done)),
            **cache_columns(delta, sum(done)),
        )

    def emit_sweep_row(row, cache_label, metric="serve_openloop_sweep"):
        # the cache-on companion emits under its OWN metric name:
        # bench_compare keys on the newest line per name, and the
        # companion must not shadow the sweep's trajectory row
        emit(
            metric, row["goodput"], "checks/sec",
            row["goodput"] / NORTH_STAR_RATE,
            edges=int(snap.num_edges), batch=args.submit,
            cache=cache_label,
            **{k: v for k, v in row.items() if k != "wall"},
        )

    # -- open-loop sweep: CACHE-OFF, byte-for-byte the pre-cache serving
    # path (cs=full, raw former, direct evaluate) — the committed
    # serve_openloop_goodput trajectory stays an apples-to-apples
    # comparison across rounds; the cache rows ride alongside below
    loads = [float(x) for x in args.loads.split(",")]
    serve_cs = consistency.min_latency()
    rows = []
    on_row = None
    handle = c.with_serving(cs=cs, config=scfg_off, cache=False)
    warm_burst(handle, 400, 0.0)   # saturates → full 4096-tier batches
    warm_burst(handle, 48, 0.003)  # trickle → 256/1024-tier batches
    compiles_sweep0 = m.counter("latency.compiles")
    # serving GC discipline: collections pause every thread and land
    # straight in the tail; collect between steps instead (the futures
    # are acyclic — nothing leaks while disabled)
    try:
        for frac in loads:
            row = run_load_step(handle, frac, frac * closed_rate)
            wall = row["wall"]
            rows.append(row)
            note(
                f"load {frac:.2f}: offered {row['offered']:,.0f} → goodput"
                f" {row['goodput']:,.0f} checks/s"
                f" ({row['goodput'] / closed_rate:.0%} of closed)"
                f" p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms"
                f" shed {row['shed_rate']:.1%} mean batch"
                f" {row['mean_batch']:.0f} depth_max {row['queue_depth_max']}"
                f" hit_rate {row['cache_hit_rate']:.1%}"
                f" dedup {row['dedup_frac']:.1%}"
            )
            note(
                "wall ledger: " + " ".join(
                    f"{b}={wall['fracs'][b]:.1%}"
                    for b in (*_perf.WALL_BUCKETS, "idle")
                    if wall["fracs"][b] > 0
                ) + f" closure={wall['closure_frac']:.1%}"
            )
            emit_sweep_row(row, "off")
            # the wall-time row block: one line per load step, every
            # bucket a column.  Closure holds by construction (idle is
            # the residual), so the teeth are elsewhere: zero dropped
            # intervals and the device stages actually reported.  A
            # fully cache-resident step may legitimately dispatch
            # nothing — the kernel tooth only bites when the device ran
            assert wall["closure_frac"] >= 0.95, wall
            assert wall["dropped"] == 0, wall
            if row["device_dispatches"] > 0:
                assert wall["seconds"]["kernel"] > 0, wall
            emit(
                "serve_wall_ledger", wall["closure_frac"], "frac",
                wall["closure_frac"],
                load_frac=frac, window_s=wall["window_s"],
                named_frac=wall["named_frac"],
                **{f"{b}_frac": wall["fracs"][b]
                   for b in (*_perf.WALL_BUCKETS, "idle")},
                **{f"{b}_s": wall["seconds"][b]
                   for b in (*_perf.WALL_BUCKETS, "idle")},
                intervals=wall["intervals"],
            )

        retraces = int(m.counter("latency.compiles") - compiles_sweep0)

        # -- cache+dedup companion row ------------------------------------
        # (same offered load as the sweep's FIRST row — sub-saturation,
        # so the row measures warm steady state and its promoted p99
        # stays a stable trajectory guard; the saturation behavior is
        # the open-loop A/B's job below)
        if cache_on:
            h_on = c.with_serving(cs=serve_cs, config=scfg, cache=True)
            try:
                warm_burst(h_on, 120 if args.quick else 400, 0.0)
                # cover the whole query pool once so the row measures
                # the warm steady state, not the cache-fill transient
                futs = []
                for s0 in range(0, POOL - args.submit, args.submit):
                    while True:
                        try:
                            futs.append(h_on.submit_columns(
                                ctx, pool_res[s0:s0 + args.submit],
                                pool_perm[s0:s0 + args.submit],
                                pool_subj[s0:s0 + args.submit],
                                client_id=s0 % args.clients,
                            ))
                            break
                        except ShedError:
                            time.sleep(0.002)
                for f in futs:
                    f.result(timeout=120.0)
                on_row = run_load_step(
                    h_on, loads[0], loads[0] * closed_rate
                )
            finally:
                h_on.close()
            emit_sweep_row(on_row, "on", metric="serve_openloop_cache_on")
            note(
                f"cache-on row @ load {loads[0]:.2f}: goodput"
                f" {on_row['goodput']:,.0f} checks/s p50"
                f" {on_row['p50_ms']}ms p99 {on_row['p99_ms']}ms hit_rate"
                f" {on_row['cache_hit_rate']:.1%} unique_frac"
                f" {on_row['unique_frac']:.2%}"
            )

        # -- cache on/off A/B ---------------------------------------------
        # Two arms, two truths.  (1) REQUEST PATH (the headline): for a
        # blocking caller nothing overlaps the evaluator round trip, so
        # a cache hit is a wall-clock win — the reference's "repeated
        # read answered from a revision-pinned result".  (2) OPEN-LOOP
        # capacity through the serving handle: on the 1-core proxy the
        # submission front-end shares the core with dispatch and the
        # device kernel already overlaps host Python, so removing
        # device work cannot raise goodput here — the honest outcome is
        # ~parity wall-clock with a collapse in device rows dispatched
        # per answered check (device_dispatches, unique_frac); the
        # goodput multiplier belongs to silicon, where the device is
        # the bottleneck (same split PR-10 documented for p99)
        ab = None
        ab_open = None
        if cache_on and not args.no_ab:
            from gochugaru_tpu import rel as _rel
            from gochugaru_tpu.client import (
                new_tpu_evaluator as _new, with_store as _wstore,
                with_latency_mode as _wlat, with_verdict_cache as _wvc,
            )

            ab_s = min(args.seconds, 2.0) if args.quick else args.seconds
            hot = [
                _rel.must_from_triple(
                    f"repo:r{rng.integers(args.repos)}", "read",
                    f"user:u{rng.integers(args.users)}",
                )
                for _ in range(4096)
            ]
            # symmetric fresh clients over the SAME store (`c` carries
            # the sweep's cache — it must not serve the off arm; fresh
            # engines warm identically, so neither arm rides the
            # other's pins)
            c_req_on = _new(_wlat(), _wvc(), _wstore(c.store))
            c_req_off = _new(_wlat(), _wstore(c.store))
            thr = 4 if args.quick else 8
            request_path_run(c_req_off, min(ab_s, 1.0), 2, hot)  # warm
            request_path_run(c_req_on, min(ab_s, 1.0), 2, hot)   # warm
            req_off = request_path_run(c_req_off, ab_s, thr, hot)
            req_on = request_path_run(c_req_on, ab_s, thr, hot)
            # parity: cached answers must equal the uncached evaluator's
            sample = hot[:256]
            got_on = c_req_on.check(ctx, serve_cs, *sample)
            got_off = c_req_off.check(ctx, serve_cs, *sample)
            req_match = got_on == got_off
            speedup = round(req_on["goodput"] / req_off["goodput"], 3)
            ab = dict(on=req_on, off=req_off, speedup=speedup,
                      match=req_match, threads=thr)
            note(
                f"cache A/B (request path, {thr} blocking threads,"
                f" {ab_s:.1f}s/arm): off {req_off['goodput']:,.0f} → on"
                f" {req_on['goodput']:,.0f} checks/s = {speedup}x,"
                f" hit_rate {req_on['cache_hit_rate']:.1%},"
                f" parity={req_match}"
            )
            open_off = saturation_run(handle, ab_s)  # the OFF sweep handle
            h_on2 = c.with_serving(cs=serve_cs, config=scfg, cache=True)
            try:
                saturation_run(h_on2, min(ab_s, 1.0))  # cache warm-up
                open_on = saturation_run(h_on2, ab_s)
            finally:
                h_on2.close()
            ab_open = dict(
                on=open_on, off=open_off,
                speedup=round(open_on["goodput"] / open_off["goodput"], 3),
            )
            note(
                f"cache A/B (open-loop saturation, {ab_s:.1f}s/arm): off"
                f" {open_off['goodput']:,.0f} → on"
                f" {open_on['goodput']:,.0f} checks/s"
                f" = {ab_open['speedup']}x wall-clock (front-end-bound"
                " on the 1-core proxy); device dispatches"
                f" {open_off['device_dispatches']} → "
                f"{open_on['device_dispatches']}, hit_rate"
                f" {open_on['cache_hit_rate']:.1%}"
            )

        # -- oracle parity on sampled coalesced answers -------------------
        # Two passes over the SAME sample offsets: the cache-off sweep
        # handle (the pre-PR check) and a cache-armed handle whose
        # cache is warm from the companion/A-B runs — so oracle_match
        # genuinely covers CACHE-SERVED coalesced answers, not just the
        # direct path
        oracle = c._oracle_for(snap)
        ns = args.oracle_samples
        oracle_match = True
        si = rng.integers(0, POOL - 4, ns)
        h_par = (
            c.with_serving(cs=serve_cs, config=scfg, cache=True)
            if cache_on else None
        )
        try:
            for s in si:
                want = np.fromiter(
                    (c._check_interned(oracle, snap, pool_res[s + j],
                                       pool_perm[s + j], pool_subj[s + j])
                     for j in range(4)),
                    bool, count=4,
                )
                # h_par twice: the second round is a guaranteed cache
                # HIT at the same revision — parity covers the hit path
                for hh in (handle, h_par, h_par):
                    if hh is None:
                        continue
                    got = np.asarray(hh.check_columns(
                        ctx, pool_res[s:s + 4], pool_perm[s:s + 4],
                        pool_subj[s:s + 4],
                    ))
                    if not (got == want).all():
                        oracle_match = False
                        note(f"ORACLE MISMATCH at pool offset {s}"
                             f" (cache={'on' if hh is h_par else 'off'})")
        finally:
            if h_par is not None:
                h_par.close()
    finally:
        handle.close()

    if ab is not None:
        emit(
            "serve_cache_ab", ab["speedup"], "x", ab["speedup"],
            edges=int(snap.num_edges), surface="request_path",
            threads=ab["threads"],
            goodput_on=ab["on"]["goodput"], goodput_off=ab["off"]["goodput"],
            hit_rate=ab["on"]["cache_hit_rate"],
            parity=bool(ab["match"]),
            oracle_match=bool(oracle_match),
            zipf=args.zipf, platform=platform,
            note=(
                "same-run A/B, blocking client.check at min_latency over"
                " zipf-hot tuples: a cache hit skips the evaluator round"
                " trip a blocking caller otherwise waits out; off ="
                " pre-cache path byte-for-byte"
            ),
        )
    if ab_open is not None:
        emit(
            "serve_cache_openloop_ab", ab_open["speedup"], "x",
            ab_open["speedup"],
            edges=int(snap.num_edges), batch=args.submit,
            goodput_on=ab_open["on"]["goodput"],
            goodput_off=ab_open["off"]["goodput"],
            p99_on_ms=ab_open["on"]["p99_ms"],
            p99_off_ms=ab_open["off"]["p99_ms"],
            device_dispatches_on=ab_open["on"]["device_dispatches"],
            device_dispatches_off=ab_open["off"]["device_dispatches"],
            hit_rate=ab_open["on"]["cache_hit_rate"],
            dedup_frac=ab_open["on"]["dedup_frac"],
            unique_frac=ab_open["on"]["unique_frac"],
            zipf=args.zipf, platform=platform,
            note=(
                "open-loop saturation through the serving handle: on the"
                " 1-core proxy the front-end shares the core and the"
                " kernel already overlaps host Python, so wall-clock is"
                " ~parity while device rows dispatched per answered"
                " check collapse — the goodput multiplier belongs to"
                " silicon, where the device is the bottleneck"
            ),
        )

    # -- headline: the highest load whose p99 holds the 3x bar; when no
    # row holds it (the 1-core CPU proxy shares the dispatch core with
    # the submission front-end, so queueing starts well below the
    # device's own capacity), the best sustained-goodput row with a
    # sub-2% shed rate carries the headline and p99_bar_met says so
    bar_ms = 3.0 * quiet_p99_ms
    ok_rows = [r for r in rows if r["p99_ms"] <= bar_ms and
               r["shed_rate"] < 0.01]
    if ok_rows:
        head = max(ok_rows, key=lambda r: r["goodput"])
    else:
        sustained = [r for r in rows if r["shed_rate"] < 0.02] or rows
        head = max(sustained, key=lambda r: r["goodput"])
    hw = head["wall"]
    emit(
        "serve_openloop_goodput", head["goodput"], "checks/sec",
        head["goodput"] / NORTH_STAR_RATE,
        edges=int(snap.num_edges), batch=args.submit,
        closed_rate=round(closed_rate, 1),
        goodput_vs_closed=head["goodput_vs_closed"],
        load_frac=head["load_frac"],
        p50_ms=head["p50_ms"], p99_ms=head["p99_ms"],
        quiet_p99_ms=quiet_p99_ms,
        p99_vs_quiet=round(head["p99_ms"] / max(quiet_p99_ms, 1e-9), 3),
        p99_bar_met=bool(ok_rows),
        shed_rate=head["shed_rate"],
        clients=args.clients, zipf=args.zipf,
        oracle_match=bool(oracle_match),
        retraces=retraces,
        queue_depth_p50=head["queue_depth_p50"],
        queue_depth_max=head["queue_depth_max"],
        # verdict-cache companions (the headline row itself is the
        # cache-OFF trajectory row; the cache-on numbers ride as
        # columns so the comparison lives in one emitted line)
        cache="off",
        cache_speedup=None if ab is None else ab["speedup"],
        cache_openloop_speedup=None if ab_open is None
        else ab_open["speedup"],
        cache_hit_rate=None if on_row is None else on_row["cache_hit_rate"],
        dedup_frac=None if on_row is None else on_row["dedup_frac"],
        unique_frac=None if on_row is None else on_row["unique_frac"],
        cache_on_load_frac=None if on_row is None else on_row["load_frac"],
        cache_on_goodput=None if on_row is None else on_row["goodput"],
        cache_on_p50_ms=None if on_row is None else on_row["p50_ms"],
        cache_on_p99_ms=None if on_row is None else on_row["p99_ms"],
        # measured-roofline columns (perf ledger: gathered bytes/check ×
        # goodput against the triad-microbench ceiling) + the headline
        # step's wall-time split — the 21× explanation as columns: on
        # the 1-core proxy host-side buckets dominate the window while
        # the kernel share stays small, which is exactly "queueing
        # starts below device capacity because the host core is shared"
        **_perf.roofline_columns(head["goodput"], dsnap=dsnap),
        wall_closure_frac=hw["closure_frac"],
        wall_kernel_frac=hw["fracs"]["kernel"],
        wall_host_frac=round(
            hw["fracs"]["host_prep"] + hw["fracs"]["filter"]
            + hw["fracs"]["form"] + hw["fracs"]["h2d"] + hw["fracs"]["d2h"],
            4,
        ),
        wall_queue_frac=hw["fracs"]["queue_wait"],
        wall_idle_frac=hw["fracs"]["idle"],
        pad_fraction=_perf.pad_stats()["pad_fraction"],
        platform=platform,
        note=(
            f"{args.clients} concurrent clients at p99 <="
            f" {head['p99_ms']} ms: open-loop Poisson arrivals,"
            f" zipf({args.zipf}) subjects, {args.submit}-check"
            " submissions coalesced onto the pinned tier ladder"
            " (cache-off trajectory row; cache_on_* columns carry the"
            " verdict-cache companion)"
        ),
    )
    assert retraces == 0, f"{retraces} retraces across the sweep"
    assert oracle_match, "coalesced answers diverged from the host oracle"
    if ab is not None:
        assert ab["match"], "cached request-path answers diverged"
        assert ab["speedup"] >= 1.3, (
            f"cache request-path speedup {ab['speedup']} < 1.3x"
        )
    return 0


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

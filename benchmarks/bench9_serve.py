"""Open-loop serving bench: Poisson traffic through the continuous
batcher vs the closed-loop pinned-tier rate.

Every other bench hands the engine pre-formed batches (closed loop: the
next batch waits for the last — the load adapts to the server, hiding
queueing).  This one is OPEN loop, the honest serving methodology:
submissions arrive on a Poisson process at a FIXED offered load whether
or not the server keeps up, subjects are zipf-skewed, and each
submission is a small CheckMany (the reference's request shape).  The
micro-batch former (gochugaru_tpu/serve/) coalesces them onto the
pinned tier ladder; we report goodput, shed rate, the batch-occupancy
histogram, and queue+service p50/p99 per offered-load step — so the
headline reads "N concurrent clients at p99 ≤ Y ms", not batch
throughput.

Honesty rules: the closed-loop denominator is measured in THIS process
at the serving tier; latencies are per-submission submit→resolve times
from the futures themselves (no waiting threads in the hot path);
oracle parity is sampled on real coalesced answers; zero retraces is
asserted from the latency.compiles counter across the whole sweep.

One JSON line per load step ("serve_openloop_sweep") plus the headline
("serve_openloop_goodput") at the highest load whose queue+service p99
stays within 3x the quiet-window small-batch p99.
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPOCH_US = 1_700_000_000_000_000


def build_store_world(client, n_repos, n_users, n_orgs, edges, rng):
    """GitHub-RBAC-shaped world imported columnarly through the client
    (the serving handle needs a store-backed snapshot chain)."""
    import numpy as np

    from gochugaru_tpu.utils.context import background

    ctx = background()
    client.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    ru = rng.integers(0, n_users, edges)
    rr = rng.integers(0, n_repos, edges)
    client.import_relationship_columns(
        ctx, resource_type="repo",
        resource_ids=[f"r{i}" for i in rr], resource_relation="reader",
        subject_type="user", subject_ids=[f"u{i}" for i in ru],
    )
    client.import_relationship_columns(
        ctx, resource_type="repo",
        resource_ids=[f"r{i}" for i in range(n_repos)],
        resource_relation="org", subject_type="org",
        subject_ids=[f"o{i % n_orgs}" for i in range(n_repos)],
    )
    client.import_relationship_columns(
        ctx, resource_type="org",
        resource_ids=[f"o{i}" for i in range(n_orgs)],
        resource_relation="admin", subject_type="user",
        subject_ids=[f"u{i % n_users}" for i in range(n_orgs)],
    )
    mu = rng.integers(0, n_users, n_orgs * 4)
    client.import_relationship_columns(
        ctx, resource_type="org",
        resource_ids=[f"o{i % n_orgs}" for i in range(n_orgs * 4)],
        resource_relation="member", subject_type="user",
        subject_ids=[f"u{i}" for i in mu],
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=200_000)
    ap.add_argument("--repos", type=int, default=20_000)
    ap.add_argument("--users", type=int, default=5_000)
    ap.add_argument("--seconds", type=float, default=4.0,
                    help="measurement window per offered-load step")
    ap.add_argument("--loads", default="0.5,0.8,0.9",
                    help="offered load as fractions of the closed-loop rate")
    ap.add_argument("--submit", type=int, default=64,
                    help="checks per submission (CheckMany size)")
    ap.add_argument("--clients", type=int, default=32,
                    help="distinct fairness client ids in the arrival stream")
    ap.add_argument("--zipf", type=float, default=1.2,
                    help="zipf exponent for subject skew")
    ap.add_argument("--oracle-samples", type=int, default=50,
                    help="coalesced submissions re-checked on the host oracle")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.edges = min(args.edges, 50_000)
        args.repos = min(args.repos, 5_000)
        args.seconds = min(args.seconds, 2.0)

    from benchmarks.common import (
        NORTH_STAR_RATE,
        emit,
        maybe_force_cpu,
        note,
        small_batch_latency,
    )

    platform = maybe_force_cpu()
    import numpy as np

    from gochugaru_tpu import consistency
    from gochugaru_tpu.client import new_tpu_evaluator, with_latency_mode
    from gochugaru_tpu.serve import ServeConfig
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils import perf as _perf
    from gochugaru_tpu.utils.context import background
    from gochugaru_tpu.utils.errors import ShedError

    rng = np.random.default_rng(5)
    c = new_tpu_evaluator(with_latency_mode())
    t0 = time.perf_counter()
    build_store_world(c, args.repos, args.users, 16, args.edges, rng)
    cs = consistency.full()
    ctx = background()
    snap = c.store.snapshot_for(cs)
    engine = c._engine_for(snap)
    dsnap = c._dsnap_for(engine, snap)
    note(f"world: edges={snap.num_edges} built in"
         f" {time.perf_counter() - t0:.1f}s platform={platform}")

    # -- interned query pools (zipf-skewed subjects) ---------------------
    inter = snap.interner
    slot = snap.compiled.slot_of_name
    repo_ids = np.array(
        [inter.node("repo", f"r{i}") for i in range(args.repos)], np.int32
    )
    user_ids = np.array(
        [inter.node("user", f"u{i}") for i in range(args.users)], np.int32
    )
    POOL = 1 << 18
    zipf_users = (rng.zipf(args.zipf, POOL) - 1) % args.users
    pool_res = repo_ids[rng.integers(0, args.repos, POOL)]
    pool_subj = user_ids[zipf_users]
    pool_perm = np.where(
        rng.random(POOL) < 0.9, slot["read"], slot["admin"]
    ).astype(np.int32)

    # -- closed-loop pinned-tier denominator + quiet-window p99 ----------
    TIER = 1024
    lp = engine.latency_path(dsnap)
    q = (pool_res[:TIER], pool_perm[:TIER].copy(), pool_subj[:TIER])
    q[1][:] = slot["read"]  # one slot set → one pinned kernel, like serving
    for _ in range(5):
        lp.dispatch_columns(*q, now_us=EPOCH_US)
    reps = 60 if args.quick else 150
    t0 = time.perf_counter()
    for i in range(reps):
        lp.dispatch_columns(
            np.roll(q[0], i), q[1], np.roll(q[2], 2 * i), now_us=EPOCH_US
        )
    closed_rate = reps * TIER / (time.perf_counter() - t0)
    quiet = small_batch_latency(
        engine, dsnap, q[0], q[1], q[2], now_us=EPOCH_US,
        warmup=10, reps=120 if args.quick else 300,
    )
    quiet_p99_ms = quiet["p99_ms"]
    note(f"closed-loop tier-{TIER} rate {closed_rate:,.0f} checks/s;"
         f" quiet-window p99 {quiet_p99_ms} ms")

    # -- open-loop sweep -------------------------------------------------
    m = _metrics.default
    rows = []
    handle = c.with_serving(cs=cs, config=ServeConfig(hold_max_s=0.001))
    # warm the serving pool: pin every (slot-subset, tier) executable
    # the sweep will form — a rapid-fire burst fills the TOP tiers, a
    # paced trickle forms the small ones.  The zero-retrace assertion
    # then covers the MEASURED window, the standard warm-serving
    # discipline (same as every latency row's warmup)
    def warm_burst(n, pace_s):
        futs = []
        for k in range(n):
            s = int(rng.integers(0, POOL - args.submit))
            while True:
                try:
                    futs.append(handle.submit_columns(
                        ctx, pool_res[s:s + args.submit],
                        pool_perm[s:s + args.submit],
                        pool_subj[s:s + args.submit],
                        client_id=k % args.clients,
                    ))
                    break
                except ShedError:  # warm as fast as admission allows
                    time.sleep(0.005)
            if pace_s:
                time.sleep(pace_s)
        for f in futs:
            f.result(timeout=60.0)

    warm_burst(400, 0.0)   # saturates → full 4096-tier batches
    warm_burst(48, 0.003)  # trickle → 256/1024-tier batches
    compiles_sweep0 = m.counter("latency.compiles")
    # serving GC discipline: collections pause every thread and land
    # straight in the tail; collect between steps instead (the futures
    # are acyclic — nothing leaks while disabled)
    import gc

    try:
        for frac in [float(x) for x in args.loads.split(",")]:
            offered = frac * closed_rate
            sub_rate = offered / args.submit
            n_subs = max(int(sub_rate * args.seconds), 16)
            gaps = rng.exponential(1.0 / sub_rate, n_subs)
            arrivals = np.cumsum(gaps)
            starts = rng.integers(0, POOL - args.submit, n_subs)
            client_ids = rng.integers(0, args.clients, n_subs)

            base0 = m.snapshot()
            futures = []
            sheds = 0
            depth_samples = []
            stop_sampler = threading.Event()

            def sampler():
                while not stop_sampler.is_set():
                    depth_samples.append(m.gauge("serve.queue_depth"))
                    time.sleep(0.005)

            st = threading.Thread(target=sampler, daemon=True)
            st.start()
            gc.collect()
            gc.disable()
            # closed wall-time ledger: the step's whole window accounts
            # into form/queue-wait/host-prep/H2D/kernel/D2H/filter/idle
            # buckets (utils/perf.py) — the 21× queue-vs-quiet question
            # becomes columns on the row block below
            ledger = _perf.WallLedger().start()
            t_start = time.perf_counter()
            for k in range(n_subs):
                target = t_start + arrivals[k]
                slack = target - time.perf_counter()
                if slack > 0.0015:
                    # coarse pacing: sleep off the bulk, let sub-ms
                    # arrivals micro-burst (Poisson in aggregate) —
                    # spinning per arrival would burn the core the
                    # dispatcher needs
                    time.sleep(slack - 0.001)
                s = starts[k]
                try:
                    futures.append(handle.submit_columns(
                        ctx,
                        pool_res[s:s + args.submit],
                        pool_perm[s:s + args.submit],
                        pool_subj[s:s + args.submit],
                        client_id=int(client_ids[k]),
                    ))
                except ShedError:  # open-loop counts sheds, not retries;
                    sheds += 1     # any other failure must FAIL the row
                    futures.append(None)
            # drain
            deadline = time.perf_counter() + 30.0
            for f in futures:
                if f is not None:
                    f.result(timeout=max(deadline - time.perf_counter(), 0.1))
            t_end = time.perf_counter()
            wall = ledger.stop()
            gc.enable()
            stop_sampler.set()
            st.join(timeout=1.0)

            lat_ms = np.array([
                (f.t_done - f.t_submit) * 1000.0
                for f in futures if f is not None
            ])
            snap_m = m.snapshot()

            def delta(key):
                return snap_m.get(key, 0) - base0.get(key, 0)

            done_checks = delta("serve.checks")
            elapsed = t_end - t_start
            goodput = done_checks / elapsed
            batches = max(delta("serve.batches"), 1)
            occ_n = delta("serve.occupancy.count")
            occ_mean = (
                delta("serve.occupancy.sum") / occ_n if occ_n else 0.0
            )
            ds = np.asarray(depth_samples) if depth_samples else np.zeros(1)
            row = dict(
                load_frac=frac,
                offered=round(offered, 1),
                goodput=round(goodput, 1),
                goodput_vs_closed=round(goodput / closed_rate, 4),
                submissions=n_subs,
                shed_rate=round(sheds / n_subs, 4),
                p50_ms=round(float(np.percentile(lat_ms, 50)), 3),
                p99_ms=round(float(np.percentile(lat_ms, 99)), 3),
                batches=int(batches),
                mean_batch=round(done_checks / batches, 1),
                occupancy_mean=round(occ_mean, 4),
                flush_full=int(delta("serve.flush_full")),
                flush_deadline=int(delta("serve.flush_deadline")),
                flush_maxhold=int(delta("serve.flush_maxhold")),
                queue_depth_p50=round(float(np.percentile(ds, 50)), 1),
                queue_depth_max=int(ds.max()),
            )
            row["wall"] = wall
            rows.append(row)
            note(
                f"load {frac:.2f}: offered {offered:,.0f} → goodput"
                f" {goodput:,.0f} checks/s ({goodput / closed_rate:.0%} of"
                f" closed) p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms"
                f" shed {row['shed_rate']:.1%} mean batch"
                f" {row['mean_batch']:.0f} depth_max {row['queue_depth_max']}"
            )
            note(
                "wall ledger: " + " ".join(
                    f"{b}={wall['fracs'][b]:.1%}"
                    for b in (*_perf.WALL_BUCKETS, "idle")
                    if wall["fracs"][b] > 0
                ) + f" closure={wall['closure_frac']:.1%}"
            )
            emit(
                "serve_openloop_sweep", row["goodput"], "checks/sec",
                row["goodput"] / NORTH_STAR_RATE,
                edges=int(snap.num_edges), batch=args.submit,
                **{k: v for k, v in row.items() if k != "wall"},
            )
            # the wall-time row block: one line per load step, every
            # bucket a column.  Closure holds by construction (idle is
            # the residual), so the teeth are elsewhere: zero dropped
            # intervals and the device stages actually reported — a
            # refactor that loses the stage stamps fails on kernel_s,
            # not on closure
            assert wall["closure_frac"] >= 0.95, wall
            assert wall["dropped"] == 0, wall
            assert wall["seconds"]["kernel"] > 0, wall
            emit(
                "serve_wall_ledger", wall["closure_frac"], "frac",
                wall["closure_frac"],
                load_frac=frac, window_s=wall["window_s"],
                named_frac=wall["named_frac"],
                **{f"{b}_frac": wall["fracs"][b]
                   for b in (*_perf.WALL_BUCKETS, "idle")},
                **{f"{b}_s": wall["seconds"][b]
                   for b in (*_perf.WALL_BUCKETS, "idle")},
                intervals=wall["intervals"],
            )

        retraces = int(m.counter("latency.compiles") - compiles_sweep0)

        # -- oracle parity on sampled coalesced answers -------------------
        oracle = c._oracle_for(snap)
        ns = args.oracle_samples
        oracle_match = True
        si = rng.integers(0, POOL - 4, ns)
        for s in si:
            got = np.asarray(handle.check_columns(
                ctx, pool_res[s:s + 4], pool_perm[s:s + 4],
                pool_subj[s:s + 4],
            ))
            want = np.fromiter(
                (c._check_interned(oracle, snap, pool_res[s + j],
                                   pool_perm[s + j], pool_subj[s + j])
                 for j in range(4)),
                bool, count=4,
            )
            if not (got == want).all():
                oracle_match = False
                note(f"ORACLE MISMATCH at pool offset {s}")
    finally:
        handle.close()

    # -- headline: the highest load whose p99 holds the 3x bar; when no
    # row holds it (the 1-core CPU proxy shares the dispatch core with
    # the submission front-end, so queueing starts well below the
    # device's own capacity), the best sustained-goodput row with a
    # sub-2% shed rate carries the headline and p99_bar_met says so
    bar_ms = 3.0 * quiet_p99_ms
    ok_rows = [r for r in rows if r["p99_ms"] <= bar_ms and
               r["shed_rate"] < 0.01]
    if ok_rows:
        head = max(ok_rows, key=lambda r: r["goodput"])
    else:
        sustained = [r for r in rows if r["shed_rate"] < 0.02] or rows
        head = max(sustained, key=lambda r: r["goodput"])
    hw = head["wall"]
    emit(
        "serve_openloop_goodput", head["goodput"], "checks/sec",
        head["goodput"] / NORTH_STAR_RATE,
        edges=int(snap.num_edges), batch=args.submit,
        closed_rate=round(closed_rate, 1),
        goodput_vs_closed=head["goodput_vs_closed"],
        load_frac=head["load_frac"],
        p50_ms=head["p50_ms"], p99_ms=head["p99_ms"],
        quiet_p99_ms=quiet_p99_ms,
        p99_vs_quiet=round(head["p99_ms"] / max(quiet_p99_ms, 1e-9), 3),
        p99_bar_met=bool(ok_rows),
        shed_rate=head["shed_rate"],
        clients=args.clients, zipf=args.zipf,
        oracle_match=bool(oracle_match),
        retraces=retraces,
        queue_depth_p50=head["queue_depth_p50"],
        queue_depth_max=head["queue_depth_max"],
        # measured-roofline columns (perf ledger: gathered bytes/check ×
        # goodput against the triad-microbench ceiling) + the headline
        # step's wall-time split — the 21× explanation as columns: on
        # the 1-core proxy host-side buckets dominate the window while
        # the kernel share stays small, which is exactly "queueing
        # starts below device capacity because the host core is shared"
        **_perf.roofline_columns(head["goodput"], dsnap=dsnap),
        wall_closure_frac=hw["closure_frac"],
        wall_kernel_frac=hw["fracs"]["kernel"],
        wall_host_frac=round(
            hw["fracs"]["host_prep"] + hw["fracs"]["filter"]
            + hw["fracs"]["form"] + hw["fracs"]["h2d"] + hw["fracs"]["d2h"],
            4,
        ),
        wall_queue_frac=hw["fracs"]["queue_wait"],
        wall_idle_frac=hw["fracs"]["idle"],
        pad_fraction=_perf.pad_stats()["pad_fraction"],
        platform=platform,
        note=(
            f"{args.clients} concurrent clients at p99 <="
            f" {head['p99_ms']} ms: open-loop Poisson arrivals,"
            f" zipf({args.zipf}) subjects, {args.submit}-check"
            " submissions coalesced onto the pinned tier ladder"
        ),
    )
    assert retraces == 0, f"{retraces} retraces across the sweep"
    return 0


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""BASELINE config 4 — multi-tenant SaaS with caveats at 100M edges:
on-device CEL caveat predicate evaluation (caveats/device.py).

Every grant edge carries a ``same_tenant`` caveat whose stored context
pins the edge's tenant; the query context supplies the caller's tenant.
The predicate (string equality + int tier comparison) runs inside the
jitted check — zero host fallbacks is part of the assertion.

Size note: 100M edges ≈ 3.4 GB of padded int32 columns on device.  Use
``--edges`` to scale down on small hosts; the driver-facing headline
(bench.py) stays config 2.
"""

import argparse

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import (
    maybe_force_cpu,
    NORTH_STAR_P99_MS,
    NORTH_STAR_RATE,
    emit,
    emit_small_batch_row,
    latency_percentiles,
    note,
    time_steady,
)

SCHEMA = """
caveat same_tenant(tenant string, edge_tenant string, tier int) {
    tenant == edge_tenant && tier >= 1
}
definition user {}
definition org { relation admin: user }
definition item {
    relation org: org
    relation holder: user with same_tenant
    permission access = holder + org->admin
}
"""

EPOCH = 1_700_000_000_000_000


def build_world(n_edges: int, n_tenants: int = 4096):
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rng = np.random.default_rng(31)

    n_users = 200_000
    n_items = max(n_edges // 10, 1000)
    n_orgs = 2000
    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    orgs = np.array([interner.node("org", f"o{i}") for i in range(n_orgs)], np.int64)
    items = np.array([interner.node("item", f"i{i}") for i in range(n_items)], np.int64)
    slot = cs.slot_of_name
    cid = cs.caveat_ids["same_tenant"]

    # shared stored-context rows: one per tenant (contexts are deduped by
    # construction — 100M edges share n_tenants dicts)
    contexts = [{"edge_tenant": f"t{t}", "tier": 2} for t in range(n_tenants)]

    n_holder = n_edges - n_items - n_orgs
    res = np.concatenate([
        rng.choice(items, n_holder),
        items,  # org edge per item
        orgs,  # admin per org
    ])
    rel = np.concatenate([
        np.full(n_holder, slot["holder"], np.int64),
        np.full(n_items, slot["org"], np.int64),
        np.full(n_orgs, slot["admin"], np.int64),
    ])
    subj = np.concatenate([
        rng.choice(users, n_holder),
        rng.choice(orgs, n_items),
        rng.choice(users, n_orgs),
    ])
    srel = np.full(res.shape[0], -1, np.int64)
    caveat = np.concatenate([
        np.full(n_holder, cid, np.int32),
        np.zeros(n_items + n_orgs, np.int32),
    ])
    ctx = np.concatenate([
        rng.integers(0, n_tenants, n_holder).astype(np.int32),
        np.full(n_items + n_orgs, -1, np.int32),
    ])

    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=res, rel=rel, subj=subj, srel=srel,
        caveat=caveat, ctx=ctx, contexts=contexts,
        epoch_us=EPOCH,
    )
    return cs, snap, users, items, slot, n_tenants


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=100_000_000)
    ap.add_argument("--batch", type=int, default=100_000)
    args = ap.parse_args()
    note(f"platform={maybe_force_cpu()}")

    from gochugaru_tpu.engine.device import DeviceEngine

    cs, snap, users, items, slot, n_tenants = build_world(args.edges)
    note(f"edges={snap.num_edges} contexts={len(snap.contexts)}")
    engine = DeviceEngine(cs)
    assert not engine.caveat_plan.host_only[cs.caveat_ids["same_tenant"]]
    dsnap = engine.prepare(snap)
    from benchmarks.common import join_lookup_prewarm

    join_lookup_prewarm(timeout=600)

    rng = np.random.default_rng(3)
    B = 1 << (args.batch - 1).bit_length()
    # half the queries target real holder edges (the caveat predicate must
    # actually run: right tenant → grant, wrong tenant → definite deny);
    # the other half are random misses
    holder_rows = np.nonzero(snap.e_rel == slot["holder"])[0]
    hit_rows = rng.choice(holder_rows, B // 2)
    q_res = np.concatenate([
        snap.e_res[hit_rows], rng.choice(items, B - B // 2).astype(np.int32),
    ])
    q_subj = np.concatenate([
        snap.e_subj[hit_rows], rng.choice(users, B - B // 2).astype(np.int32),
    ])
    q_perm = np.full(B, slot["access"], np.int32)
    # each query carries its caller's tenant + tier in request context;
    # for the edge-hitting half, 50% use the edge's own tenant (→ True)
    qctx_rows = [{"tenant": f"t{t}", "tier": 2} for t in range(n_tenants)]
    edge_tenant = snap.e_ctx[hit_rows].astype(np.int64)
    match = rng.random(B // 2) < 0.5
    hit_tenants = np.where(
        match, edge_tenant, (edge_tenant + 1) % n_tenants
    )
    q_ctx = np.concatenate([
        hit_tenants, rng.integers(0, n_tenants, B - B // 2),
    ]).astype(np.int32)

    def dispatch():  # pipelined device dispatch, no per-call readback
        return engine.check_columns(
            dsnap, q_res, q_perm, q_subj,
            q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=EPOCH, fetch=False,
        )

    def roundtrip():  # end-to-end including the device→host fetch
        return engine.check_columns(
            dsnap, q_res, q_perm, q_subj,
            q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=EPOCH,
        )

    dt = time_steady(dispatch, reps=5)
    rate = B / dt
    d, p, ovf = roundtrip()
    conditional = int((p & ~d).sum())
    note(
        f"batch={B} step={dt*1000:.1f}ms granted={int(d.sum())}"
        f" conditional(host-fallback)={conditional} overflow={int(ovf.sum())}"
    )
    emit(
        "caveated_100m_bulk_check_throughput", rate, "checks/sec/chip",
        rate / NORTH_STAR_RATE, edges=int(snap.num_edges), batch=int(B),
    )
    p50, p99, mean = latency_percentiles(roundtrip, reps=20)
    emit(
        "caveated_100m_batch_p99_latency", p99, "ms",
        NORTH_STAR_P99_MS / max(p99, 1e-9),
        edges=int(snap.num_edges), batch=int(B),
    )
    note(f"p50={p50:.2f}ms p99={p99:.2f}ms mean={mean:.2f}ms")

    # latency-mode small batch at spec scale (engine/latency.py), with
    # on-device caveat evaluation live: an interactive dispatch carries
    # its own (small) distinct-context slice, not the world's 4096 —
    # the per-dispatch qctx encode is honest host-lowering cost
    try:
        SB = 2048
        sb_tenants = 8
        sb_rows = [{"tenant": f"t{t}", "tier": 2} for t in range(sb_tenants)]
        emit_small_batch_row(
            "caveated_100m_small_batch_p99_latency", engine, dsnap,
            q_res[:SB].copy(), q_perm[:SB].copy(), q_subj[:SB].copy(),
            q_ctx=(q_ctx[:SB] % sb_tenants).astype(np.int32),
            qctx_rows=sb_rows, edges=int(snap.num_edges), now_us=EPOCH,
        )
    except Exception as e:  # optional row must never cost the main ones
        note(f"small-batch latency section failed: {type(e).__name__}: {e}")

    # sub-batch pipeline (VERDICT r04 item 8): the same B-item bulk
    # request dispatched as queued 32k sub-batches — per-sub-batch
    # completion latency is the tail a streaming consumer sees, and the
    # whole-request rate must hold
    import time as _t

    PB = engine._pipeline_batch() or 32_768
    def pipelined_once():
        lats = []
        t_start = _t.perf_counter()
        t_prev = t_start
        n = 0
        for lo, hi, d2, p2, o2 in engine.check_columns_pipelined(
            dsnap, q_res, q_perm, q_subj,
            q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=EPOCH, sub_batch=PB,
        ):
            t_now = _t.perf_counter()
            lats.append((t_now - t_prev) * 1000)
            t_prev = t_now
            n += hi - lo
        return (_t.perf_counter() - t_start), lats, n

    try:
        pipelined_once()  # warm the PB-bucket compilation
        all_lats = []
        total_s = 0.0
        total_n = 0
        for _ in range(6):
            dt2, lats, n = pipelined_once()
            all_lats += lats
            total_s += dt2
            total_n += n
        pl = np.asarray(all_lats)
        pp99 = float(np.percentile(pl, 99))
        prate = total_n / total_s
        emit(
            "caveated_100m_pipelined_subbatch_p99_latency", pp99, "ms",
            NORTH_STAR_P99_MS / max(pp99, 1e-9),
            edges=int(snap.num_edges), batch=int(PB),
        )
        emit(
            "caveated_100m_pipelined_throughput", prate, "checks/sec/chip",
            prate / NORTH_STAR_RATE, edges=int(snap.num_edges), batch=int(B),
        )
        note(
            f"pipelined PB={PB}: sub-batch p50={np.percentile(pl,50):.2f}ms "
            f"p99={pp99:.2f}ms rate={prate:,.0f}/s"
        )
    except Exception as e:  # optional metrics must never cost the main rows
        note(f"pipelined section failed: {type(e).__name__}: {e}")


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""BASELINE config 1 — the README "founders" CheckAll example
(/root/reference/README.md:64-89) through the full Client path.

This measures the *ergonomic* end-to-end surface (parse → intern → device
dispatch → reduction), not raw device throughput: the reference example is
3 direct-relation triples, so the interesting number is round-trip latency
of a tiny CheckAll — the reference's equivalent round-trips a gRPC
CheckBulkPermissions to a SpiceDB container.
"""

import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import (
    maybe_force_cpu,
    NORTH_STAR_P99_MS,
    emit,
    emit_small_batch_row,
    note,
)

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import new_tpu_evaluator
from gochugaru_tpu.rel.txn import Txn
from gochugaru_tpu.utils.context import background

SCHEMA = """
definition user {}
definition document {
    relation founder: user
    permission view = founder
}
"""


def main() -> None:
    note(f"platform={maybe_force_cpu()}")
    client = new_tpu_evaluator()
    ctx = background()
    client.write_schema(ctx, SCHEMA)
    txn = Txn()
    founders = []
    for name in ("jake", "joey", "jimmy"):
        r = rel.must_from_triple("document:readme", "founder", f"user:{name}")
        txn.touch(r)
        founders.append(rel.must_from_triple("document:readme", "view", f"user:{name}"))
    client.write(ctx, txn)

    cs = consistency.min_latency()
    assert client.check_all(ctx, cs, *founders)

    # warm, then time individual CheckAll round trips; frozen GC is the
    # standard latency-service tuning (collection pauses land in p99)
    import gc

    for _ in range(30):
        client.check_all(ctx, cs, *founders)
    gc.collect()
    gc.freeze()
    ts = []
    # 1000 samples: at n=200 the p99 is the 2nd-worst sample, and a
    # single ambient scheduler/daemon spike poisons it
    for _ in range(1000):
        t0 = time.perf_counter()
        client.check_all(ctx, cs, *founders)
        ts.append((time.perf_counter() - t0) * 1000)
    a = np.asarray(ts)
    p50, p99 = float(np.percentile(a, 50)), float(np.percentile(a, 99))
    emit("founders_checkall_p99_latency", p99, "ms", NORTH_STAR_P99_MS / max(p99, 1e-9))
    note(f"p50={p50:.3f}ms p99={p99:.3f}ms mean={a.mean():.3f}ms n=1000")

    # latency-mode small batch (engine/latency.py): a warm B=1024
    # dispatch on the founders world through the pinned-kernel path,
    # with the host/H2D/kernel/D2H budget breakdown on the row
    snap = client._store.snapshot_for(cs)
    engine = client._engine_for(snap)
    if engine is None:  # device unavailable: the CheckAll row above
        note("small-batch latency row skipped: no device engine")
        return
    dsnap = client._dsnap_for(engine, snap)
    slot = snap.compiled.slot_of_name
    B = 1024
    doc = snap.interner.lookup("document", "readme")
    subs = np.array(
        [snap.interner.lookup("user", n) for n in ("jake", "joey", "jimmy")]
        + [-1],  # a miss lane: unknown subjects stay definite-false
        np.int32,
    )
    q_res = np.full(B, doc, np.int32)
    q_perm = np.full(B, slot["view"], np.int32)
    q_subj = subs[np.arange(B) % subs.shape[0]]
    try:
        emit_small_batch_row(
            "founders_small_batch_p99_latency", engine, dsnap,
            q_res, q_perm, q_subj, edges=int(snap.num_edges),
        )
    except Exception as e:  # optional row must never cost the main one
        note(f"small-batch latency section failed: {type(e).__name__}: {e}")


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""Group-commit write pipeline: coalesced writes vs one-at-a-time.

Every committed revision pays fixed machinery regardless of how many
tuples it carries — a log entry, a delta-chain link, and (on the serving
path) a closure advance plus device reship.  The group-commit pipeline
(store/group.py) amortizes that machinery across a GROUP: one collapsed
delta, one log entry, one materialization per group, while every
transaction still mints its own zookie.  This bench prices that on the
CPU host proxy, closed-loop:

1. **group vs single** — W transactions committed one-at-a-time (write +
   per-revision snapshot materialization, the delta link every revision
   pays on the serving path) against the same W transactions in groups
   of G, with BITWISE oracle parity asserted on every post-group
   snapshot (lexsorted packed edge columns).  Emits ``writes_per_s``
   with the measured speedup; at G ≥ 64 the acceptance bar is ≥5×.
2. **committer closed-loop** — concurrent submitters through
   ``GroupCommitter`` (deadline-aware hold-back, formation overlapping
   application); emits ``committer_writes_per_s`` and the achieved
   ``group_size_p50`` from the store-side ``write.group_size``
   histogram.
3. **chain compaction** — a ≥2k-revision delta chain with the
   background ``ChainCompactor`` on: overlay probe depth must stay
   bounded (no writer ever pays the synchronous merge), emitted as
   ``probe_depth_after_compaction``.
4. **mixed soak** — read p99 through a host-only client while writer
   threads stream group commits, vs the write-free baseline; the
   acceptance bar is within 1.5×.  Emits ``read_p99_under_write_ms``.

The paper's write-side anchor (PAPER.md §3.2): ~10k writes/s sustained
while serving reads — ``vs_baseline`` for the write rates uses it as
the denominator.
"""

import argparse
import threading
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import bench_main, emit, note

WRITE_NORTH_STAR = 10_000  # writes/s sustained (PAPER.md §3.2)

SCHEMA = """
definition user {}
definition document {
    relation writer: user
    relation reader: user

    permission edit = writer
    permission view = reader + edit
}
"""


def _make_store():
    from gochugaru_tpu.store.store import Store

    s = Store()
    s.write_schema(SCHEMA)
    return s


def _txn(doc: str, user: str):
    from gochugaru_tpu import rel

    t = rel.Txn()
    t.touch(rel.must_from_triple(f"document:{doc}", "reader", f"user:{user}"))
    return t


def _txn_stream(n: int):
    """n single-touch transactions over a unique-doc keyspace with a
    sprinkle of repeat-doc touches (upserts across groups)."""
    txns = []
    for i in range(n):
        doc = f"d{i % max(n // 2, 1)}"  # second half revisits docs
        txns.append(_txn(doc, f"u{i % 97}"))
    return txns


def _canon(snap):
    """Lexsorted packed edge columns — the bitwise-comparable canonical
    form of a snapshot's world (touching e_* forces the LSM merge)."""
    cols = (snap.e_res, snap.e_rel, snap.e_subj, snap.e_srel1,
            snap.e_caveat, snap.e_exp)
    order = np.lexsort(cols[::-1])
    return tuple(c[order] for c in cols)


def _assert_bitwise(a, b, where: str) -> None:
    ca, cb = _canon(a), _canon(b)
    for i, (x, y) in enumerate(zip(ca, cb)):
        if x.shape != y.shape or not np.array_equal(x, y):
            raise SystemExit(
                f"BITWISE PARITY FAILED at {where}: column {i} differs "
                f"({x.shape} vs {y.shape})"
            )


def section_group_vs_single(W: int, G: int, quick: bool) -> None:
    from gochugaru_tpu import consistency

    txns = _txn_stream(W)
    single = _make_store()   # the one-at-a-time oracle AND baseline
    grouped = _make_store()

    t_single = 0.0
    t_group = 0.0
    n_groups = 0
    for g0 in range(0, W, G):
        chunk = txns[g0:g0 + G]
        t0 = time.perf_counter()
        outcomes = grouped.write_group(chunk)
        gsnap = grouped.snapshot_for(consistency.full())
        gsnap.e_rel.shape  # force the merge inside the timed region
        t_group += time.perf_counter() - t0
        n_groups += 1
        if any(isinstance(o, BaseException) for o in outcomes):
            raise SystemExit(f"group at {g0}: unexpected ejection")
        # baseline: same chunk one revision at a time, each paying its
        # own materialization — the per-revision machinery group commit
        # amortizes
        t0 = time.perf_counter()
        for t in chunk:
            single.write(t)
            ssnap = single.snapshot_for(consistency.full())
            ssnap.e_rel.shape
        t_single += time.perf_counter() - t0
        # every post-group snapshot must match the sequential oracle
        # bitwise (revisions align: base+k == k sequential writes)
        assert grouped.head_revision == single.head_revision
        _assert_bitwise(gsnap, ssnap, f"group {n_groups} (rev {gsnap.revision})")

    singles_per_s = W / max(t_single, 1e-9)
    group_per_s = W / max(t_group, 1e-9)
    speedup = group_per_s / max(singles_per_s, 1e-9)
    note(
        f"group vs single: W={W} G={G} | one-at-a-time "
        f"{singles_per_s:,.0f} w/s | grouped {group_per_s:,.0f} w/s | "
        f"speedup {speedup:.1f}x | parity bitwise on all {n_groups} groups"
    )
    emit(
        "writes_per_s", group_per_s, "writes/s",
        group_per_s / WRITE_NORTH_STAR,
        batch=G, group_speedup=round(speedup, 2),
        single_writes_per_s=round(singles_per_s, 1),
        groups=n_groups, txns=W,
    )
    if G >= 64 and speedup < 5.0:
        if quick:
            note(f"quick mode: speedup {speedup:.1f}x below the 5x full-run bar")
        else:
            raise SystemExit(
                f"ACCEPTANCE FAILED: {speedup:.1f}x < 5x at group size {G}"
            )


def _hist_delta(before, name: str):
    """(uppers, count deltas) of one histogram vs a prior snapshot."""
    from gochugaru_tpu.utils import metrics as _metrics

    now = _metrics.default.hist_snapshot().get(name)
    if now is None:
        return None
    uppers, counts, _, _, _ = now
    old = before.get(name)
    base = old[1] if old is not None else [0] * len(counts)
    return uppers, [int(c) - int(b) for c, b in zip(counts, base)]


def _hist_p50(uppers, counts) -> float:
    total = sum(counts)
    if total == 0:
        return 0.0
    acc = 0
    for u, c in zip(list(uppers) + [float("inf")], counts):
        acc += c
        if acc * 2 >= total:
            return float(u)
    return float("inf")


def section_committer(duration_s: float, writers: int) -> None:
    from gochugaru_tpu.store.group import GroupCommitConfig, GroupCommitter
    from gochugaru_tpu.utils import metrics as _metrics

    store = _make_store()
    hist_before = _metrics.default.hist_snapshot()
    gc = GroupCommitter(
        store, GroupCommitConfig(max_group=256, hold_max_s=0.001)
    )
    done = []
    stop = time.monotonic() + duration_s

    def worker(w):
        n = 0
        while time.monotonic() < stop:
            gc.write(_txn(f"c{w}_{n % 512}", f"w{w}"))
            n += 1
        done.append(n)

    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(writers)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    gc.close()
    total = sum(done)
    rate = total / max(wall, 1e-9)
    h = _hist_delta(hist_before, "write.group_size")
    p50 = _hist_p50(*h) if h else 0.0
    groups = sum(h[1]) if h else 0
    note(
        f"committer closed-loop: {writers} writers, {total} txns in "
        f"{wall:.2f}s -> {rate:,.0f} w/s over {groups} groups "
        f"(group_size_p50<={p50:g})"
    )
    emit(
        "committer_writes_per_s", rate, "writes/s", rate / WRITE_NORTH_STAR,
        batch=writers, txns=total, groups=groups,
    )
    emit(
        "group_size_p50", p50, "txns/group",
        p50 / max(writers, 1), writers=writers,
    )
    if groups >= total:
        raise SystemExit("no coalescing happened: one group per txn")


def section_chain(revisions: int, G: int) -> None:
    from gochugaru_tpu import consistency
    from gochugaru_tpu.store.group import ChainCompactor, GroupCommitConfig
    from gochugaru_tpu.utils import metrics as _metrics

    m = _metrics.default
    store = _make_store()
    store.lsm_compact_min = 1024  # rows: EngineConfig.lsm_compact_min proxy
    cc = ChainCompactor(
        store, GroupCommitConfig(compact_poll_s=0.0, compact_fraction=0.5)
    )
    merges_before = m.counter("store.bg_compactions")
    store.snapshot_for(consistency.full())  # base generation
    max_overlay = 0
    n_groups = revisions // G
    for g in range(n_groups):
        store.write_group([_txn(f"ch{g}_{j}", f"u{j}") for j in range(G)])
        store.snapshot_for(consistency.full())
        got = store.peek_chain()
        if got is not None:
            max_overlay = max(max_overlay, got[1])
        cc.poll_once()
    cc.close()
    got = store.peek_chain()
    depth = int(got[1]) if got is not None else 0
    merges = int(m.counter("store.bg_compactions") - merges_before)
    hard_trip = max(store.lsm_compact_min, 1)
    note(
        f"chain: {n_groups * G} revisions in {n_groups} groups | "
        f"bg compactions {merges} | max overlay {max_overlay} rows "
        f"(hard trip {hard_trip}) | final depth {depth} rows"
    )
    emit(
        "probe_depth_after_compaction", depth, "rows",
        0.0, revisions=n_groups * G, bg_compactions=merges,
        max_overlay_rows=max_overlay,
    )
    if merges < 1:
        raise SystemExit("background compactor never ran over a 2k-rev chain")
    if max_overlay > hard_trip:
        raise SystemExit(
            f"probe depth unbounded: overlay hit {max_overlay} rows, past "
            f"the {hard_trip}-row synchronous trip the compactor must beat"
        )


def section_mixed_soak(reps: int, writers: int, quick: bool) -> None:
    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import (
        new_tpu_evaluator,
        with_group_commit,
        with_host_only_evaluation,
        with_store,
    )
    from gochugaru_tpu.store.group import GroupCommitConfig
    from gochugaru_tpu.utils.context import background

    store = _make_store()
    seed = rel.Txn()
    for i in range(512):
        seed.touch(rel.must_from_triple(f"document:m{i}", "reader", f"user:r{i % 31}"))
    store.write(seed)
    client = new_tpu_evaluator(
        with_store(store),
        with_host_only_evaluation(),
        with_group_commit(GroupCommitConfig(max_group=128, hold_max_s=0.001)),
    )
    ctx = background()
    qs = [
        rel.must_from_triple(f"document:m{i % 512}", "view", f"user:r{i % 31}")
        for i in range(64)
    ]

    def read_p99(min_wall_s: float = 0.0) -> float:
        ts = []
        i = 0
        t_end = time.perf_counter() + min_wall_s
        while i < reps or time.perf_counter() < t_end:
            q = qs[i % len(qs)]
            t0 = time.perf_counter()
            client.check(ctx, consistency.min_latency(), q)
            ts.append((time.perf_counter() - t0) * 1000)
            i += 1
        return float(np.percentile(np.asarray(ts), 99))

    client.check(ctx, consistency.full(), qs[0])  # warm + materialize
    p99_quiet = read_p99()

    stop = threading.Event()
    wrote = []

    def writer(w):
        n = 0
        while not stop.is_set():
            client.write(ctx, _txn(f"soak{w}_{n % 256}", f"sw{w}"))
            n += 1
        wrote.append(n)

    threads = [
        threading.Thread(target=writer, args=(w,)) for w in range(writers)
    ]
    for t in threads:
        t.start()
    try:
        # hold the mixed window open long enough for the writers to
        # stream a real load (a reps-only window on a fast host closes
        # before the first groups even form)
        p99_under_write = read_p99(min_wall_s=1.0 if quick else 3.0)
    finally:
        stop.set()
        for t in threads:
            t.join()
    ratio = p99_under_write / max(p99_quiet, 1e-9)
    note(
        f"mixed soak: read p99 {p99_quiet:.3f}ms quiet -> "
        f"{p99_under_write:.3f}ms under {writers} group-commit writers "
        f"({sum(wrote)} writes) = {ratio:.2f}x"
    )
    emit(
        "read_p99_under_write_ms", p99_under_write, "ms",
        2.0 / max(p99_under_write, 1e-9),
        read_p99_quiet_ms=round(p99_quiet, 3),
        soak_ratio=round(ratio, 2), write_txns=sum(wrote),
    )
    if ratio > 1.5:
        if quick:
            note(f"quick mode: soak ratio {ratio:.2f}x above the 1.5x full bar")
        else:
            raise SystemExit(
                f"ACCEPTANCE FAILED: read p99 {ratio:.2f}x write-free "
                "baseline (bar: 1.5x)"
            )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--txns", type=int, default=None)
    ap.add_argument("--group", type=int, default=64)
    args = ap.parse_args()
    q = args.quick
    W = args.txns or (1024 if q else 8192)

    note(f"group-commit write pipeline (CPU host proxy), quick={q}")
    section_group_vs_single(W, args.group, q)
    section_committer(duration_s=1.0 if q else 3.0, writers=32)
    section_chain(revisions=2048, G=64)
    section_mixed_soak(reps=400 if q else 2000, writers=4, quick=q)


if __name__ == "__main__":
    bench_main(main)

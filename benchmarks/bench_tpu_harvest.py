"""TPU tunnel-window harvesters (VERDICT r05 "What's weak" #1): the
tunnel is the scarcest resource in this environment, so a live window
must be consumed maximally and unattended.  tpu_watch.sh runs these, in
priority order, right after a successful config-2 bench:

  --trace DIR   capture a jax.profiler trace of the aligned kernel —
                one big-batch (32k) and one latency-mode small-batch
                dispatch loop — into DIR (TensorBoard-loadable), and
                print a JSON line naming the capture;
  --ab          aligned-vs-legacy A/B on silicon: the SAME config-2
                world measured with flat_aligned=True and False, same
                timing recipe, one JSON line per arm — the measurement
                the round-5 kernel rebuild was made for and never got.

Every section is wrapped so a dying tunnel costs only the remaining
sections; JSON goes to stdout (one line per metric, same shape as the
benches), stages to stderr.
"""

import argparse
import json
import os as _os
import sys as _sys
import time

import numpy as np

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import maybe_force_cpu, note


def _world(flat_aligned=None):
    from bench import build_world

    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig

    cs, snap, users, repos, slot = build_world()
    cfg = None
    if flat_aligned is not None:
        cfg = EngineConfig.for_schema(cs)
        from dataclasses import replace

        cfg = replace(cfg, flat_aligned=flat_aligned)
    engine = DeviceEngine(cs, cfg)
    dsnap = engine.prepare(snap)
    return engine, dsnap, snap, users, repos, slot


def _queries(users, repos, slot, B, seed=5):
    rng = np.random.default_rng(seed)
    q_res = rng.choice(repos, B).astype(np.int32)
    q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
    q_subj = rng.choice(users, B).astype(np.int32)
    return q_res, q_perm, q_subj


def _flat_call(engine, dsnap, snap, q_res, q_perm, q_subj):
    import jax.numpy as jnp

    queries, qctx = engine._columns_preamble(
        dsnap, q_res, q_perm, q_subj, None, None, None, None
    )
    return engine.flat_fn_and_args(
        dsnap, queries, qctx,
        jnp.int32(snap.now_rel32(1_700_000_000_000_000)), q_res.shape[0],
    )


def _blocked_rate(fn, args, B, reps=10):
    import jax

    jax.block_until_ready(fn(*args))
    jax.device_get(fn(*args))  # force sync mode (common.time_steady note)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    med = float(np.median(ts))
    return B / med, med


def do_trace(trace_dir: str) -> None:
    import jax

    from gochugaru_tpu.utils import trace as _trace

    engine, dsnap, snap, users, repos, slot = _world()
    note(f"trace: world prepared, backend={jax.default_backend()}")
    B = 32_768
    got = _flat_call(engine, dsnap, snap, *_queries(users, repos, slot, B))
    assert got is not None
    fn, args = got
    jax.block_until_ready(fn(*args))  # compile OUTSIDE the trace
    lp = engine.latency_path(dsnap)
    q_res, q_perm, q_subj = _queries(users, repos, slot, 1024, seed=9)
    lp.dispatch_columns(q_res, q_perm, q_subj)  # pin outside the trace
    # request attribution: a 100%-sampled tracer + an active profiler
    # session (GOCHUGARU_TRACE_DIR) make every latency dispatch inside
    # the window carry a jax.profiler.TraceAnnotation named by its trace
    # id, and the matching request spans dump as JSONL next to the
    # profiler capture — the TensorBoard timeline and the request view
    # join on `gochugaru:<trace_id>`
    tracer = _trace.configure(sample_rate=1.0, slow_threshold_s=None)
    # flight recorder rides the harvest window: any anomaly inside it
    # (breaker trip, pinned-path recompile) dumps an incident bundle
    # under $GOCHUGARU_INCIDENT_DIR (tpu_watch.sh sets it and copies the
    # bundles next to this capture)
    _trace.install_recorder(_trace.FlightRecorder())
    spans = []
    with _trace.profiler_session(trace_dir), jax.profiler.trace(trace_dir):
        for _ in range(10):
            out = fn(*args)
        jax.block_until_ready(out)
        for i in range(10):
            sp = _trace.root_span("harvest.latency_dispatch", batch=1024, i=i)
            try:
                lp.dispatch_columns(
                    np.roll(q_res, i), q_perm, q_subj, span=sp
                )
            finally:
                sp.end()
                spans.append(sp.trace_id)
    jsonl_path = _os.path.join(trace_dir, "request_traces.jsonl")
    tracer.dump_jsonl(jsonl_path)
    rec = _trace.recorder()
    if rec is not None:
        rec.flush()  # land any in-flight incident bundles before teardown
    _trace.disable()
    print(json.dumps({
        "metric": "tpu_profile_trace", "value": 1.0, "unit": "capture",
        "vs_baseline": 0.0, "trace_dir": trace_dir,
        "platform": jax.default_backend(),
        "request_traces": jsonl_path,
        "annotated_dispatches": len(spans),
        "contents": "10x B=32768 aligned dispatches + 10x B=1024 latency-mode"
                    " (request-annotated)",
    }), flush=True)


def do_ab() -> None:
    import jax

    B = 32_768
    for aligned in (True, False):
        arm = "aligned" if aligned else "legacy-blocks"
        try:
            note(f"A/B arm: {arm}")
            engine, dsnap, snap, users, repos, slot = _world(flat_aligned=aligned)
            got = _flat_call(engine, dsnap, snap, *_queries(users, repos, slot, B))
            assert got is not None, "flat path unavailable"
            rate, med = _blocked_rate(*got, B)
            print(json.dumps({
                "metric": f"rbac_2hop_ab_{arm.replace('-', '_')}_rate",
                "value": round(rate, 1), "unit": "checks/sec/chip",
                "vs_baseline": round(rate / 10_000_000, 4),
                "batch": B, "blocked_ms": round(med * 1000, 2),
                "platform": jax.default_backend(),
            }), flush=True)
        except Exception as e:  # a dead arm must not cost the other
            note(f"A/B arm {arm} failed: {type(e).__name__}: {e}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", metavar="DIR", default=None)
    ap.add_argument("--ab", action="store_true")
    args = ap.parse_args()
    note(f"platform={maybe_force_cpu()}")
    if args.trace:
        do_trace(args.trace)
    if args.ab:
        do_ab()


if __name__ == "__main__":
    main()

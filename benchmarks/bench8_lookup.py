"""Bulk reverse reachability: LookupResources as masked frontier SpMV,
measured at the config-3 world (1M docs / 10M edges, 5-hop nested
groups + folder trees — benchmarks/bench3_docs.py's generator).

Three honest columns, separated on purpose:

- ``lookup_candidates_per_s`` — candidate resources/second through the
  device frontier expansion (engine/spmv.py over the reverse-CSR
  tables) for BULK subjects (group usersets viewing near-root folders:
  the ~1M-resource answers this surface exists for), TRUE-rate basis:
  total candidates divided by the median wall clock of full sequential
  drains — no pipelining, no per-subject best-of.  The bar is ≥1M/chip
  (vs_baseline's denominator here).  ``mixed_rate`` on the same row is
  the rate over 48 RANDOM users — small-reach lookups are dominated by
  the fixed per-hop dispatch cost (a ~1k-resource answer cannot
  amortize it), so the two numbers are kept separate instead of
  averaged into something misleading.
- ``lookup_first_result_latency`` — wall time to the FIRST page (1k
  results) of a cursored lookup, the streaming claim: answers start
  flowing before the fixpoint completes (measured on random users AND
  on a bulk subject whose full answer takes ~100x longer).
- ``lookup_full_answer_throughput`` — results/second for the complete
  bulk answer, INCLUDING the exact forward filter — what an
  export-everything caller sees.

``oracle_match`` on the headline row asserts the frontier answer equals
the host walker's (engine/lookup.py — the superseded O(E log E)
transposed-index path, kept as the parity oracle) for measured
subjects; the walker's index build time rides along as
``walker_index_build_s`` for contrast.

Every lookup row also carries ``device_dispatches`` — the number of
device program launches the measured phase actually made (read from the
``lookup.dispatches`` + ``spmm.dispatches`` counters, engine/spmv.py and
engine/spmm.py), so dispatch-floor claims are data, not prose.  The
``lookup_fused_vs_looped`` A/B row runs the SAME mixed-user sample
through the fused K-hop SpMM path (``EngineConfig.spmm`` on, one pinned
dispatch per lookup) and the looped per-hop path (off) on the SAME
prepared snapshot, promoting ``mixed_users_rate`` (higher-better) and
``dispatches_per_lookup`` (lower-better) for the trajectory guard.
"""

import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import (
    emit,
    join_lookup_prewarm,
    maybe_force_cpu,
    note,
)

#: the acceptance bar: candidate resources per second per chip
CANDIDATE_RATE_BAR = 1_000_000


def main() -> None:
    note(f"platform={maybe_force_cpu()}")
    from benchmarks.bench3_docs import EPOCH, build_world
    from gochugaru_tpu.engine import lookup as lm
    from gochugaru_tpu.engine import spmv
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.oracle import SnapshotOracle

    t0 = time.perf_counter()
    cs, snap, users, docs, slot = build_world()
    note(f"edges={snap.num_edges} nodes={snap.num_nodes} "
         f"worldgen={time.perf_counter()-t0:.0f}s")
    engine = DeviceEngine(cs)
    t0 = time.perf_counter()
    dsnap = engine.prepare(snap)
    join_lookup_prewarm()
    note(f"prepare={time.perf_counter()-t0:.0f}s "
         f"has_rev={dsnap.flat_meta.has_rev}")
    assert spmv.frontier_ok(engine, dsnap), "frontier path must serve"
    oracle = SnapshotOracle(snap, {})
    interner = snap.interner

    from gochugaru_tpu.utils.metrics import default as _mt

    def _disp() -> float:
        return _mt.counter("lookup.dispatches") + _mt.counter(
            "spmm.dispatches"
        )

    rng = np.random.default_rng(11)
    sample = [int(u) for u in rng.choice(users, 48, replace=False)]
    st = spmv.state_for(engine, dsnap)
    rtid = interner.type_lookup("document")
    member = cs.slot_of_name["member"]
    viewer = cs.slot_of_name["viewer"]
    gtid = interner.type_lookup("group")

    def drain_candidates(u: int, srel: int = -1, state=st) -> int:
        n = 0
        for blk in state.resource_candidates(rtid, u, srel, -1, EPOCH):
            n += blk.shape[0]
        return n

    # bulk subjects: the groups viewing the lowest-index folders (near
    # the roots of the arity-16 forest) — their member usersets reach
    # whole subtrees, the bulk-reverse-reachability workload
    bulk: list = []
    fnodes = np.asarray(
        [interner.lookup("folder", f"f{i}") for i in range(64)], np.int64
    )
    for f in fnodes:
        m = (snap.e_res == f) & (snap.e_rel == viewer) & (snap.e_srel1 > 0)
        for g in snap.e_subj[m]:
            if snap.node_type[int(g)] == gtid and int(g) not in bulk:
                bulk.append(int(g))
    bulk = bulk[:6]
    assert bulk, "no group views a near-root folder in this world"

    # ---- candidate expansion TRUE rate ---------------------------------
    mixed_of = {u: drain_candidates(u) for u in sample}  # warm (compiles)
    bulk_of = {g: drain_candidates(g, member) for g in bulk}

    def timed(subjects, srel, state=st):
        """(median wall s, device dispatches per drain) over 3 reps."""
        reps = []
        d0 = _disp()
        for _ in range(3):
            t0 = time.perf_counter()
            for s in subjects:
                drain_candidates(s, srel, state)
            reps.append(time.perf_counter() - t0)
        per_drain = (_disp() - d0) / (3 * max(len(subjects), 1))
        return float(np.median(reps)), per_drain

    mixed_dt, mixed_dpl = timed(sample, -1)
    bulk_dt, bulk_dpl = timed(bulk, member)
    mixed_rate = sum(mixed_of.values()) / mixed_dt
    total_cands = sum(bulk_of.values())
    cand_rate = total_cands / bulk_dt
    heavy = max(bulk, key=lambda g: bulk_of[g])
    heavy_id = interner.key_of(heavy)[1]
    note(
        f"bulk expansion: {len(bulk)} userset subjects, {total_cands} "
        f"candidates in {bulk_dt*1000:.0f}ms → {cand_rate/1e6:.2f}M cand/s"
        f" (heaviest: {bulk_of[heavy]}, {bulk_dpl:.1f} dispatches/drain); "
        f"mixed 48 random users: {sum(mixed_of.values())} candidates → "
        f"{mixed_rate/1e6:.2f}M/s at {mixed_dpl:.1f} dispatches/lookup"
    )

    # ---- fused vs looped A/B: same snapshot, same sample ---------------
    # the looped state serves through a spmm=False engine over the SAME
    # prepared tables — the pre-PR per-hop path, byte-for-byte
    import dataclasses as _dc

    from gochugaru_tpu.engine.device import DeviceEngine as _DE

    eng_off = _DE(cs, _dc.replace(engine.config, spmm=False))
    st_off = spmv.FrontierState(eng_off, dsnap)
    looped_of = {u: drain_candidates(u, -1, st_off) for u in sample}  # warm
    assert looped_of == mixed_of, "fused/looped candidate counts differ"
    looped_dt, looped_dpl = timed(sample, -1, st_off)
    looped_rate = sum(looped_of.values()) / looped_dt
    note(
        f"fused-vs-looped A/B (48 mixed users): fused "
        f"{mixed_rate/1e6:.2f}M cand/s @ {mixed_dpl:.1f} disp/lookup, "
        f"looped {looped_rate/1e6:.2f}M @ {looped_dpl:.1f} — "
        f"{mixed_rate/max(looped_rate,1e-9):.1f}x"
    )

    # ---- first-result latency (cursored page 1) ------------------------
    def first_page_ms(node: int, stype: str, srel: str) -> float:
        sid = interner.key_of(node)[1]
        # a fresh stream per timing: drop the continuation cache entry
        dsnap.__dict__.pop("_lookup_streams", None)
        t0 = time.perf_counter()
        lm.lookup_resources_page(
            engine, dsnap, "document", "view", stype, sid, srel,
            page_size=1_000, now_us=EPOCH,
            oracle_factory=lambda: oracle,
        )
        return (time.perf_counter() - t0) * 1000

    fp_d0 = _disp()
    fr = [first_page_ms(u, "user", "") for u in sample[:16]]
    fr_p50 = float(np.percentile(fr, 50))
    heavy_first = first_page_ms(heavy, "group", "member")
    fp_disp = _disp() - fp_d0

    # ---- full bulk answer (exact filter included) ----------------------
    fa_d0 = _disp()
    t0 = time.perf_counter()
    full = lm.lookup_resources_device(
        engine, dsnap, "document", "view", "group", heavy_id, "member",
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    full_dt = time.perf_counter() - t0
    full_rate = len(full) / max(full_dt, 1e-9)
    fa_disp = _disp() - fa_d0

    # ---- oracle parity vs the host walker ------------------------------
    t0 = time.perf_counter()
    match = True
    checks = [("group", interner.key_of(heavy)[1], "member")] + [
        ("user", interner.key_of(u)[1], "") for u in sample[:4]
    ]
    for stype, sid, srel in checks:
        names = ("document", "view", stype, sid, srel)
        resolved = lm._resolve_resources(dsnap, *names)
        if resolved is None:
            continue
        _rt, _p, srel_slot, subj_node, wc_node = resolved
        seen = lm._walk_resource_candidates(snap, subj_node, srel_slot,
                                            wc_node)
        wcand = seen[snap.node_type[seen] == rtid]
        filt, id_of = lm._res_filter(
            engine, dsnap, resolved, names, EPOCH, lambda: oracle,
        )
        walker_ids = sorted(id_of(int(g)) for g in filt(wcand))
        got = lm.lookup_resources_device(
            engine, dsnap, *names[:2], *names[2:],
            now_us=EPOCH, oracle_factory=lambda: oracle,
        )
        if got != walker_ids:
            match = False
            note(f"PARITY MISMATCH for {stype}:{sid}: "
                 f"{len(got)} vs walker {len(walker_ids)}")
    walker_s = time.perf_counter() - t0
    note(f"walker parity pass (incl. one-time transposed-index build): "
         f"{walker_s:.0f}s oracle_match={match}")

    emit(
        "lookup_candidates_per_s", cand_rate, "candidates/sec/chip",
        cand_rate / CANDIDATE_RATE_BAR,
        edges=int(snap.num_edges), batch=len(bulk),
        oracle_match=bool(match),
        total_candidates=int(total_cands),
        heavy_candidates=int(bulk_of[heavy]),
        mixed_rate=round(mixed_rate, 1),
        mixed_users_rate=round(mixed_rate, 1),
        mixed_candidates=int(sum(mixed_of.values())),
        device_dispatches=round(bulk_dpl * len(bulk), 1),
        dispatches_per_lookup=round(mixed_dpl, 2),
        hops=int(_mt.counter("lookup.hops")),
        note=f"bar {CANDIDATE_RATE_BAR/1e6:.0f}M cand/s; bulk userset "
             "subjects, TRUE-rate (sequential drains, median of 3); "
             "mixed_users_rate = 48 random users; device_dispatches = "
             "per bulk rep",
    )
    emit(
        "lookup_fused_vs_looped", mixed_rate / max(looped_rate, 1e-9), "x",
        mixed_rate / max(looped_rate, 1e-9),
        edges=int(snap.num_edges), batch=len(sample),
        oracle_match=bool(match),
        mixed_users_rate=round(mixed_rate, 1),
        looped_mixed_users_rate=round(looped_rate, 1),
        dispatches_per_lookup=round(mixed_dpl, 2),
        looped_dispatches_per_lookup=round(looped_dpl, 2),
        device_dispatches=round(mixed_dpl * len(sample), 1),
        note="same snapshot, same 48 mixed users: fused K-hop SpMM "
             "(EngineConfig.spmm on) vs looped per-hop SpMV (off); "
             "value = fused/looped candidate-rate ratio",
    )
    emit(
        "lookup_first_result_latency", fr_p50, "ms", 2.0 / max(fr_p50, 1e-9),
        edges=int(snap.num_edges), batch=1_000,
        bulk_first_ms=round(heavy_first, 1),
        bulk_full_ms=round(full_dt * 1000, 1),
        device_dispatches=int(fp_disp),
        note="time to first 1k-result page (cursored stream); bulk_* = "
             "the heavy userset subject",
    )
    emit(
        "lookup_full_answer_throughput", full_rate, "results/sec/chip",
        full_rate / CANDIDATE_RATE_BAR,
        edges=int(snap.num_edges), batch=len(full),
        full_answer_ms=round(full_dt * 1000, 1),
        walker_index_build_s=round(walker_s, 1),
        device_dispatches=int(fa_disp),
        note="heaviest bulk subject, exact forward filter included",
    )


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""Incremental closure maintenance — member-edge write throughput.

Measures the write hot path this repo's ROADMAP called the top bail
class: membership-subgraph deltas (user ∈ team edges, nested team ∈ team
edges) used to force a full flattened-closure rebuild per revision; they
now advance the closure in O(Δ·depth) host work (store/closure.py
advance_closure) and reship only the O(closure) clx/ovfx tables, with
the fold staying armed (its pf_u side is closure-independent — the
reachability-pruned fold T-join of engine/fold.py fold_userset_rows).

Emits ``closure_update_throughput`` (updates/s over 30 measured rounds
at a --edges base) and asserts ``closure.rebuilds == 0`` across the
measured window — the acceptance bar for the incremental closure engine.
A freshness probe per round asserts the just-written membership is
immediately visible through a FOLDED permission (read = reader +
maintainer), i.e. the whole write→closure→check pipeline, not just the
host index.
"""

import argparse
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import maybe_force_cpu, emit, note

SCHEMA = """
definition user {}
definition team { relation member: user | team#member }
definition repo {
    relation maintainer: user | team#member
    relation reader: user
    permission read = reader + maintainer
}
"""

EPOCH = 1_700_000_000_000_000


def build_base(n_edges: int):
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rng = np.random.default_rng(19)
    n_users = 100_000
    n_teams = 1000
    n_repos = max(n_edges // 20, 1000)
    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    teams = np.array([interner.node("team", f"t{i}") for i in range(n_teams)], np.int64)
    repos = np.array([interner.node("repo", f"r{i}") for i in range(n_repos)], np.int64)
    slot = cs.slot_of_name

    n_member = n_teams * 50
    # nesting: every 10th team also contains the next team's members —
    # member writes then propagate through pair-closure depth, not just
    # the seed level (the O(Δ·depth) term is real work)
    nest = np.arange(0, n_teams - 1, 10)
    n_maint = n_repos
    n_reader = n_edges - n_member - nest.shape[0] - n_maint
    res = np.concatenate([
        np.repeat(teams, 50), teams[nest], repos, rng.choice(repos, n_reader),
    ])
    rel_c = np.concatenate([
        np.full(n_member, slot["member"], np.int64),
        np.full(nest.shape[0], slot["member"], np.int64),
        np.full(n_maint, slot["maintainer"], np.int64),
        np.full(n_reader, slot["reader"], np.int64),
    ])
    subj = np.concatenate([
        rng.choice(users, n_member),
        teams[nest + 1],
        rng.choice(teams, n_maint),
        rng.choice(users, n_reader),
    ])
    srel = np.concatenate([
        np.full(n_member, -1, np.int64),
        np.full(nest.shape[0], slot["member"], np.int64),
        np.full(n_maint, slot["member"], np.int64),
        np.full(n_reader, -1, np.int64),
    ])
    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=res, rel=rel_c, subj=subj, srel=srel, epoch_us=EPOCH,
    )
    return cs, snap, interner, slot, users, teams, repos


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=10_000_000)
    ap.add_argument("--delta", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=30)
    # chain-growth warmup, same rationale as bench5: dl_* shape-band
    # retraces and the one-time t_off flip happen in the first revisions
    ap.add_argument("--warmup", type=int, default=20)
    args = ap.parse_args()
    note(f"platform={maybe_force_cpu()}")

    from gochugaru_tpu import rel as relmod
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.store.delta import apply_delta
    from gochugaru_tpu.utils import metrics

    cs, snap, interner, slot, users, teams, repos = build_base(args.edges)
    note(f"base edges={snap.num_edges}")
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)
    if dsnap.closure_state is None:
        raise SystemExit("closure state missing: closure_delta disabled?")
    cl = dsnap.closure_state.st.cl
    note(f"closure pairs={cl.num_pairs} fold_armed="
         f"{bool(dsnap.flat_meta and dsnap.flat_meta.fold_pairs)}")

    rng = np.random.default_rng(11)
    lat_mat, lat_overlay, lat_probe = [], [], []
    warm_ms = 0.0
    incremental = 0
    rebuilds0 = applies0 = None
    live_adds = []  # adds from prior rounds, eligible for deletion
    for rnd in range(args.warmup + args.rounds):
        if rnd == args.warmup:
            rebuilds0 = metrics.default.counter("closure.rebuilds")
            applies0 = metrics.default.counter("closure.delta_applies")
        # half fresh member grants, half revocations of earlier grants —
        # adds AND deletes both exercise the advance (deletes are the
        # hard half: subset recompute, no derivation counting)
        n_del = min(len(live_adds), args.delta // 2)
        deletes = [live_adds.pop(rng.integers(0, len(live_adds)))
                   for _ in range(n_del)]
        adds = [
            relmod.must_from_triple(
                f"team:t{rng.integers(0, 1000)}", "member",
                f"user:u{rng.integers(0, 100_000)}",
            )
            for _ in range(args.delta - n_del)
        ]
        t0 = time.perf_counter()
        snap = apply_delta(snap, snap.revision + 1, adds, deletes,
                           interner=interner)
        t1 = time.perf_counter()
        dsnap = engine.prepare(snap, prev=dsnap)
        t_ov = time.perf_counter()
        if dsnap.flat_meta is not None and dsnap.flat_meta.delta is not None:
            incremental += 1
        # freshness probe THROUGH the folded permission: the new member
        # must read every repo their team maintains — pick one such repo
        probe_team = adds[0].resource_id
        probe = relmod.must_from_triple(
            f"team:{probe_team}", "member", f"user:{adds[0].subject_id}",
        )
        d, p, ovf = engine.check_batch(dsnap, [probe], now_us=EPOCH)
        t2 = time.perf_counter()
        assert bool(d[0]), "freshness probe failed: member delta not visible"
        live_adds.extend(adds)
        if rnd < args.warmup:
            warm_ms += (t2 - t0) * 1000
            continue
        lat_mat.append((t1 - t0) * 1000)
        lat_overlay.append((t_ov - t1) * 1000)
        lat_probe.append((t2 - t_ov) * 1000)

    rebuilds = metrics.default.counter("closure.rebuilds") - rebuilds0
    applies = metrics.default.counter("closure.delta_applies") - applies0
    mat = np.asarray(lat_mat)
    overlay = np.asarray(lat_overlay)
    probe_t = np.asarray(lat_probe)
    total_ms = mat.mean() + overlay.mean() + probe_t.mean()
    rate = args.delta / (total_ms / 1000)
    emit(
        "closure_update_throughput", rate, "updates/sec", rate / 1_000_000,
        edges=int(args.edges), batch=int(args.delta),
        rounds=int(args.rounds),
        rebuilds=int(rebuilds), delta_applies=int(applies),
        materialize_ms=round(float(mat.mean()), 2),
        overlay_ms=round(float(overlay.mean()), 2),
        probe_ms=round(float(probe_t.mean()), 2),
    )
    note(
        f"member-edge writes: delta={args.delta} "
        f"materialize={mat.mean():.1f}ms closure+overlay={overlay.mean():.1f}ms "
        f"probe={probe_t.mean():.1f}ms total={total_ms:.1f}ms/delta "
        f"incremental={incremental}/{args.warmup + args.rounds} "
        f"rebuilds={rebuilds:.0f} delta_applies={applies:.0f}; "
        f"warmup {warm_ms:.0f}ms total, excluded"
    )
    if rebuilds:
        raise SystemExit(
            f"acceptance violated: {rebuilds:.0f} closure rebuilds in the "
            f"measured window (must be 0)"
        )


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

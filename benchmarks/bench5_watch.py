"""BASELINE config 5 — Leopard-scale Watch-driven incremental re-index.

Measures the path that keeps a live index fresh: a stream of relationship
updates (the Watch feed, client/client.go:364-413) is folded into the
current snapshot via O(E + D log E) delta materialization
(store/delta.py), and the DEVICE side advances incrementally — the base
revision's resident tables are reused and only small ``dl_*`` overlay
tables (delta adds + tombstones) ship per revision (engine/flat.py
DeltaMeta, engine/device.py _prepare_delta).  A check on the touched
edges must observe the new revision immediately (asserted every round).

Metrics: delta re-index latency (host materialize + device overlay) and
sustained updates/sec, at a base graph scaled by ``--edges`` (the full
config is 1B edges on v5e-16; one chip holds the 100M-class slice).

Multi-host status: ShardedEngine.prepare(prev=...) also advances
incrementally — bucket-sharded base tables stay resident per shard and
the delta-sized overlay ships replicated
(parallel/sharded.py _prepare_delta_sharded, tested on the CPU mesh in
test_delta_level.py) — so the per-revision device cost is O(delta) on
one chip AND on a mesh.  The remaining O(E) cost per revision is the
HOST-side column merge in apply_delta."""

import argparse
import time

import numpy as np

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import maybe_force_cpu, emit, note

SCHEMA = """
definition user {}
definition team { relation member: user }
definition repo {
    relation maintainer: user | team#member
    relation reader: user
    permission read = reader + maintainer
}
"""

EPOCH = 1_700_000_000_000_000


def build_base(n_edges: int):
    from gochugaru_tpu.schema import compile_schema, parse_schema
    from gochugaru_tpu.store.interner import Interner
    from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

    cs = compile_schema(parse_schema(SCHEMA))
    interner = Interner()
    rng = np.random.default_rng(17)
    n_users = 100_000
    n_teams = 1000
    n_repos = max(n_edges // 20, 1000)
    users = np.array([interner.node("user", f"u{i}") for i in range(n_users)], np.int64)
    teams = np.array([interner.node("team", f"t{i}") for i in range(n_teams)], np.int64)
    repos = np.array([interner.node("repo", f"r{i}") for i in range(n_repos)], np.int64)
    slot = cs.slot_of_name

    n_member = n_teams * 50
    n_maint = n_repos
    n_reader = n_edges - n_member - n_maint
    res = np.concatenate([
        np.repeat(teams, 50), repos, rng.choice(repos, n_reader),
    ])
    rel = np.concatenate([
        np.full(n_member, slot["member"], np.int64),
        np.full(n_maint, slot["maintainer"], np.int64),
        np.full(n_reader, slot["reader"], np.int64),
    ])
    subj = np.concatenate([
        rng.choice(users, n_member),
        rng.choice(teams, n_maint),
        rng.choice(users, n_reader),
    ])
    srel = np.concatenate([
        np.full(n_member, -1, np.int64),
        np.full(n_maint, slot["member"], np.int64),
        np.full(n_reader, -1, np.int64),
    ])
    snap = build_snapshot_from_columns(
        1, cs, interner,
        res=res, rel=rel, subj=subj, srel=srel, epoch_us=EPOCH,
    )
    return cs, snap, interner, slot, users, repos


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=10_000_000)
    ap.add_argument("--delta", type=int, default=1000)
    ap.add_argument("--rounds", type=int, default=10)
    # chain-growth warmup: the dl_* overlay tables step shapes in 4×
    # bands as the accumulated delta grows (16k → 65k → 262k → 1M rows;
    # each step retraces the chain kernel once, ~1s).  At --delta 1000
    # on a 10M-edge base the chain runs ~1250 revisions to compaction,
    # so those ~8 retraces amortize to <10 ms/rev — the measured window
    # starts past the dense early crossings to report the rate the
    # other ~95% of the chain sees (the excluded cost is printed)
    ap.add_argument("--warmup", type=int, default=20)
    args = ap.parse_args()
    note(f"platform={maybe_force_cpu()}")

    from gochugaru_tpu import rel as relmod
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.store.delta import apply_delta

    cs, snap, interner, slot, users, repos = build_base(args.edges)
    note(f"base edges={snap.num_edges}")
    engine = DeviceEngine(cs)
    dsnap = engine.prepare(snap)

    rng = np.random.default_rng(5)
    lat_mat, lat_overlay, lat_probe = [], [], []
    warm_ms = 0.0
    incremental = 0
    for rnd in range(args.warmup + args.rounds):
        adds = [
            relmod.must_from_triple(
                f"repo:r{rng.integers(0, 1000)}", "reader",
                f"user:fresh_{rnd}_{i}",
            )
            for i in range(args.delta)
        ]
        deletes = []
        t0 = time.perf_counter()
        snap = apply_delta(snap, snap.revision + 1, adds, deletes, interner=interner)
        t1 = time.perf_counter()
        dsnap = engine.prepare(snap, prev=dsnap)
        t_ov = time.perf_counter()
        if dsnap.flat_meta is not None and dsnap.flat_meta.delta is not None:
            incremental += 1
        # freshness probe: a just-added edge must be visible at the new
        # revision
        probe = relmod.must_from_triple(
            f"{adds[0].resource_type}:{adds[0].resource_id}",
            "read",
            f"{adds[0].subject_type}:{adds[0].subject_id}",
        )
        d, p, ovf = engine.check_batch(dsnap, [probe], now_us=EPOCH)
        t2 = time.perf_counter()
        assert bool(d[0]), "freshness probe failed: delta not visible"
        if rnd < args.warmup:
            warm_ms += (t2 - t0) * 1000
            continue
        lat_mat.append((t1 - t0) * 1000)
        lat_overlay.append((t_ov - t1) * 1000)
        lat_probe.append((t2 - t_ov) * 1000)

    # --warmup 0 keeps the old behavior of dropping the first sample
    # (it carries the one-time kernel trace); an empty window is an error
    drop = 1 if args.warmup == 0 and len(lat_mat) > 1 else 0
    mat = np.asarray(lat_mat[drop:])
    overlay = np.asarray(lat_overlay[drop:])
    probe_t = np.asarray(lat_probe[drop:])
    if mat.size == 0:
        raise SystemExit("no measured rounds: raise --rounds")
    total_ms = mat.mean() + overlay.mean() + probe_t.mean()
    rate = args.delta / (total_ms / 1000)
    # the per-stage breakdown rides ON the row (not just a stderr note)
    # so the 100M-edge (config 5b) run's in-suite vs solo spread is
    # decomposable from the recorded JSON: materialize is host column
    # merging (memory-pressure-sensitive), overlay is the device delta
    # prepare, probe is the freshness check dispatch
    emit("watch_reindex_updates_per_sec", rate, "updates/sec", rate / 1_000_000,
         edges=int(args.edges), batch=int(args.delta),
         materialize_ms=round(float(mat.mean()), 2),
         overlay_ms=round(float(overlay.mean()), 2),
         probe_ms=round(float(probe_t.mean()), 2))
    note(
        f"delta={args.delta} materialize={mat.mean():.1f}ms "
        f"device-overlay={overlay.mean():.1f}ms probe={probe_t.mean():.1f}ms "
        f"total={total_ms:.1f}ms/delta "
        f"incremental={incremental}/{args.warmup + args.rounds} rounds; "
        f"warmup ({args.warmup} revs incl. chain-growth retraces) "
        f"{warm_ms:.0f}ms total, excluded"
    )

    # folded-check throughput BETWEEN deltas: this schema's `read` folds
    # (union of relation leaves), and round-5 incremental maintenance
    # keeps the fold armed across the chain (engine/fold.py
    # fold_delta_update) — so steady-state checks on the delta-chained
    # snapshot must run at fold speed, not walked speed
    import jax
    import jax.numpy as jnp

    meta = dsnap.flat_meta
    fold_armed = bool(meta is not None and meta.fold_pairs)
    dm = meta.delta if meta is not None else None
    note(
        f"fold armed={fold_armed} delta_level={dm is not None} "
        f"pf_dirty={bool(dm and dm.pf_dirty)} "
        f"pf_ovl_e={bool(dm and dm.pf_ovl_e)}"
    )
    B = 131_072
    qr = rng.choice(repos, B).astype(np.int32)
    qp = np.full(B, slot["read"], np.int32)
    qs = rng.choice(users, B).astype(np.int32)
    queries, qctx = engine._columns_preamble(
        dsnap, qr, qp, qs, None, None, None, None
    )
    got = engine.flat_fn_and_args(
        dsnap, queries, qctx, jnp.int32(snap.now_rel32(EPOCH)), B
    )
    if got is not None:
        fn, fargs = got
        jax.block_until_ready(fn(*fargs))
        best = 0.0
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(4):
                out = fn(*fargs)
            jax.block_until_ready(out)
            best = max(best, 4 * B / (time.perf_counter() - t0))
        emit(
            "watch_folded_check_throughput", best, "checks/sec/chip",
            best / 10_000_000, edges=int(args.edges), batch=B,
        )


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

"""Self-tuning A/B: the offline tuner's proposed config vs static
presets on a mixed workload.

The tuner (gochugaru_tpu/tune/) closes the loop from the perf ledger to
EngineConfig: profile a workload under the default preset, capture one
telemetry snapshot (per-tier occupancy histograms, flush reasons,
dedup fractions, pad waste), ``propose()`` a config diff with predicted
deltas and per-knob measured evidence, ``apply_diff()``, and re-run.
This bench is the honesty check on that loop, in three parts:

1. **Mixed-workload sweep** — three profiles (interactive small-batch
   zipf arrivals, bulk CheckMany, lookup-heavy) each run under every
   static preset AND under the tuned config, scored on goodput×p99
   (score = goodput / p99_ms).  The tuned config must beat every
   preset on ≥2 of 3 profiles and regress none beyond tolerance —
   self-tuning that wins one workload by sacrificing another is a
   preset, not a tuner.
2. **Prediction audit** — for each applied knob whose predicted delta
   is measurable in this run (pad-waste for the tier ladder, p99 for
   the hold deadline), the measured delta must land within 2× of the
   prediction; both numbers ride the emitted JSON so the trajectory
   shows prediction quality, not just outcomes.
3. **Contract checks** — the tuned ladder is typically NON-pow2 (the
   occupancy rule quantizes to 64-lane multiples): zero
   ``latency.retraces`` across all arms and bitwise oracle parity on
   sampled coalesced answers prove the tuned ladder keeps the pinned
   no-retrace and correctness contracts.

Headline: ``tuned_vs_best_preset_goodput`` — the geometric mean over
profiles of tuned goodput vs the best static preset's goodput, with
``pad_waste_frac`` (tuned arm, lower-better) and the per-knob
prediction table as columns.
"""

import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EPOCH_US = 1_700_000_000_000_000


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=60_000)
    ap.add_argument("--repos", type=int, default=6_000)
    ap.add_argument("--users", type=int, default=2_000)
    ap.add_argument("--seconds", type=float, default=2.0,
                    help="interactive-profile window per arm")
    ap.add_argument("--rate", type=float, default=120.0,
                    help="interactive offered load, submissions/s"
                         " (sub-saturation on the 1-core proxy: p99 must"
                         " measure config, not queue depth)")
    ap.add_argument("--submit", type=int, default=9,
                    help="checks per interactive submission")
    ap.add_argument("--bulk-submit", type=int, default=300,
                    help="checks per bulk CheckMany submission")
    ap.add_argument("--bulk-rate", type=float, default=70.0,
                    help="bulk offered load, submissions/s (70×300 ="
                         " 21k checks/s keeps the proxy below"
                         " saturation so p99 measures config, not"
                         " queue growth)")
    ap.add_argument("--reps", type=int, default=2,
                    help="scored repetitions per (arm, profile); the"
                         " best rep by score counts — sheds one-off"
                         " ambient stalls on a shared-CPU proxy")
    ap.add_argument("--bulk-reps", type=int, default=120)
    ap.add_argument("--lookups", type=int, default=120)
    ap.add_argument("--zipf", type=float, default=1.2)
    ap.add_argument("--oracle-samples", type=int, default=40)
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="max allowed score regression on any profile")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.edges = min(args.edges, 30_000)
        args.repos = min(args.repos, 3_000)
        args.seconds = min(args.seconds, 1.2)
        args.bulk_reps = min(args.bulk_reps, 60)
        args.lookups = min(args.lookups, 90)

    from benchmarks.bench9_serve import build_store_world
    from benchmarks.common import emit, maybe_force_cpu, note

    platform = maybe_force_cpu()
    import numpy as np

    from gochugaru_tpu import consistency
    from gochugaru_tpu.client import (
        new_tpu_evaluator,
        with_engine_config,
        with_latency_mode,
        with_store,
    )
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.serve import ServeConfig
    from gochugaru_tpu.tune import TuneTarget, apply_diff, collect_snapshot, propose
    from gochugaru_tpu.utils import metrics as _metrics
    from gochugaru_tpu.utils.context import background
    from gochugaru_tpu.utils.errors import ShedError

    m = _metrics.default
    rng = np.random.default_rng(11)
    ctx = background()
    builder = new_tpu_evaluator(with_latency_mode())
    t0 = time.perf_counter()
    build_store_world(builder, args.repos, args.users, 8, args.edges, rng)
    store = builder.store
    cs = consistency.full()
    snap = store.snapshot_for(cs)
    note(f"world: edges={snap.num_edges} built in"
         f" {time.perf_counter() - t0:.1f}s platform={platform}")

    inter = snap.interner
    slot = snap.compiled.slot_of_name
    repo_ids = np.array(
        [inter.node("repo", f"r{i}") for i in range(args.repos)], np.int32
    )
    user_ids = np.array(
        [inter.node("user", f"u{i}") for i in range(args.users)], np.int32
    )
    POOL = 1 << 16
    zipf_users = (rng.zipf(args.zipf, POOL) - 1) % args.users
    pool_res = repo_ids[rng.integers(0, args.repos, POOL)]
    pool_subj = user_ids[zipf_users]
    pool_perm = np.where(
        rng.random(POOL) < 0.9, slot["read"], slot["admin"]
    ).astype(np.int32)

    # -- the arms --------------------------------------------------------
    # presets: the shipped default, a latency-biased preset, and a
    # throughput-biased preset — the static configs an operator would
    # plausibly pick without measurements
    DEFAULT_E = EngineConfig()
    PRESETS = {
        "default": (DEFAULT_E, ServeConfig()),
        "lowlat": (DEFAULT_E, ServeConfig(hold_max_s=0.001)),
        "bulk": (DEFAULT_E, ServeConfig(hold_max_s=0.004)),
    }

    def submit_span(h, s, n, client_id=0):
        while True:
            try:
                return h.submit_columns(
                    ctx, pool_res[s:s + n], pool_perm[s:s + n],
                    pool_subj[s:s + n], client_id=client_id,
                )
            except ShedError:
                time.sleep(0.002)

    # fixed per-profile schedules, drawn ONCE and replayed identically
    # by every arm — the A/B is paired, so arm deltas measure config,
    # not workload draw
    n_inter = max(int(args.rate * args.seconds), 32)
    SCHED_INTER = (
        np.cumsum(rng.exponential(1.0 / args.rate, n_inter)),
        rng.integers(0, POOL - args.submit, n_inter),
    )
    SCHED_BULK = (
        np.cumsum(rng.exponential(1.0 / args.bulk_rate, args.bulk_reps)),
        rng.integers(0, POOL - args.bulk_submit, args.bulk_reps),
    )
    LOOKUP_USERS = [
        int((rng.zipf(args.zipf) - 1) % args.users)
        for _ in range(args.lookups)
    ]

    def paced_run(h, sched, n_checks):
        """Open-loop Poisson arrivals from a fixed schedule of
        ``n_checks``-check submissions; per-submission latency from the
        futures themselves.  Both check profiles share this shape so
        their p99 measures config (hold wait + padded-dispatch cost),
        not the arrival discipline.  The first 10% of submissions are
        the profile's own warm transient and excluded from the stats;
        GC is off during the window (collections land in the tail)."""
        import gc

        arrivals, starts = sched
        n_subs = len(starts)
        futs = []
        base = m.snapshot()
        gc.collect()
        gc.disable()
        t_start = time.perf_counter()
        try:
            for k in range(n_subs):
                slack = t_start + arrivals[k] - time.perf_counter()
                if slack > 0.0015:
                    time.sleep(slack - 0.001)
                futs.append(submit_span(h, int(starts[k]), n_checks,
                                        client_id=k % 8))
            for f in futs:
                f.result(timeout=60.0)
        finally:
            gc.enable()
        el = time.perf_counter() - t_start
        trim = max(3, n_subs // 10)
        lat = np.array([(f.t_done - f.t_submit) * 1000.0
                        for f in futs[trim:]])
        done = m.snapshot().get("serve.checks", 0) - base.get("serve.checks", 0)
        return dict(
            goodput=round(done / el, 1),
            p50_ms=round(float(np.percentile(lat, 50)), 3),
            p99_ms=round(float(np.percentile(lat, 99)), 3),
        )

    def profile_interactive(h):
        return paced_run(h, SCHED_INTER, args.submit)

    def profile_bulk(h):
        return paced_run(h, SCHED_BULK, args.bulk_submit)

    def profile_lookup(c):
        """Lookup-heavy: cursored LookupResources pages for the FIXED
        zipf-hot subject sequence (identical across arms); goodput is
        resources returned per second."""
        import gc

        lat = []
        total = 0
        gc.collect()
        gc.disable()
        t_start = time.perf_counter()
        try:
            for u in LOOKUP_USERS:
                t0 = time.perf_counter()
                page = c.lookup_resources_page(
                    ctx, cs, "repo#read", f"user:u{u}", page_size=256
                )
                lat.append((time.perf_counter() - t0) * 1000.0)
                total += len(page.ids)
        finally:
            gc.enable()
        el = time.perf_counter() - t_start
        la = np.asarray(lat[max(2, len(lat) // 10):])
        return dict(
            goodput=round(total / el, 1),
            p50_ms=round(float(np.percentile(la, 50)), 3),
            p99_ms=round(float(np.percentile(la, 99)), 3),
        )

    oracle_failures = []

    def oracle_sample(c, h, snap_a):
        oracle = c._oracle_for(snap_a)
        for s in rng.integers(0, POOL - 4, args.oracle_samples):
            want = np.fromiter(
                (c._check_interned(oracle, snap_a, pool_res[s + j],
                                   pool_perm[s + j], pool_subj[s + j])
                 for j in range(4)),
                bool, count=4,
            )
            got = np.asarray(h.check_columns(
                ctx, pool_res[s:s + 4], pool_perm[s:s + 4],
                pool_subj[s:s + 4],
            ))
            if not (got == want).all():
                oracle_failures.append(int(s))

    def build_arm(ecfg, scfg):
        """Fresh client over the shared store + serving handle, every
        tier pin warmed SEQUENTIALLY (one submission sized to the tier
        itself — submitting several sizes at once lets the hold window
        coalesce them into a single top-tier batch, leaving lower pins
        cold so a profile dispatch pays the XLA compile
        mid-measurement)."""
        c = new_tpu_evaluator(
            with_latency_mode(), with_engine_config(ecfg), with_store(store)
        )
        h = c.with_serving(cs=cs, config=scfg, cache=False)
        for _ in range(2):
            for t in ecfg.latency_tiers:
                n = min(int(t), POOL - 1)
                submit_span(h, 0, n).result(timeout=120.0)
        c.lookup_resources_page(ctx, cs, "repo#read", "user:u0",
                                page_size=256)
        return c, h

    # -- 1. profiling pass: the default preset feeds the tuner ----------
    note("profiling pass (default preset) for the tuner")
    c0, h0 = build_arm(*PRESETS["default"])
    try:
        profile_interactive(h0)
        profile_bulk(h0)
        profile_lookup(c0)
    finally:
        h0.close()

    tsnap = collect_snapshot(
        m, engine_config=PRESETS["default"][0],
        serve_config=PRESETS["default"][1],
    )
    target = TuneTarget(engine=PRESETS["default"][0],
                        serve=PRESETS["default"][1], cache_bytes=None)
    occ_dbg = {
        t: dict(n=o["count"], mean=round(o["sum"] / max(o["count"], 1), 1))
        for t, o in sorted(tsnap["occupancy"].items(), key=lambda kv: int(kv[0]))
    }
    note(f"snapshot: flush={tsnap['flush']} occupancy={occ_dbg}")
    diff = propose(tsnap, target)
    note("tuner proposal:")
    for line in diff.render().splitlines():
        note("  " + line)
    tuned = apply_diff(target, diff)
    tuned_tiers = tuned.engine.latency_tiers
    nonpow2 = [t for t in tuned_tiers if t & (t - 1)]
    note(f"tuned ladder {tuned_tiers} (non-pow2 tiers: {nonpow2 or 'none'})"
         f" hold {tuned.serve.hold_max_s} dedup {tuned.serve.dedup}")

    # -- 2. scored pass: all arms interleaved profile-major -------------
    # Arms run back-to-back within each profile (and the whole sweep
    # repeats ``--reps`` times, best rep by score counting) so ambient
    # drift on a shared-CPU proxy lands on every arm alike instead of
    # on whichever arm happened to run last.
    ARMS = dict(PRESETS)
    ARMS["tuned"] = (tuned.engine, tuned.serve)
    arm_objs = {}
    for name, (ecfg, scfg) in ARMS.items():
        arm_objs[name] = build_arm(ecfg, scfg)
    pad_acc = {name: [0.0, 0.0] for name in ARMS}
    results = {name: {} for name in ARMS}

    def scored(p, r):
        # lookup is CLOSED-loop: its goodput and latency are one
        # measurement, so dividing one by the other double-counts the
        # same noise — goodput alone is the score there.  The check
        # profiles are open-loop (goodput pinned by the schedule) so
        # goodput×(1/p99) rewards meeting load at low tail.
        if p == "lookup":
            return r["goodput"]
        return r["goodput"] / max(r["p99_ms"], 1e-6)

    PROFILE_FNS = (
        ("interactive", profile_interactive, True),
        ("bulk", profile_bulk, True),
        ("lookup", profile_lookup, False),
    )
    arm_order = list(arm_objs.items())
    for rep in range(max(1, args.reps)):
        # alternate arm order so positional bias (allocator state, LLC
        # residency, ambient load ramps) lands on every arm alike
        order = arm_order if rep % 2 == 0 else arm_order[::-1]
        for p, fn, takes_handle in PROFILE_FNS:
            for name, (c, h) in order:
                l0 = m.counter("perf.pad.live_lanes")
                t0 = m.counter("perf.pad.total_lanes")
                r = fn(h if takes_handle else c)
                pad_acc[name][0] += m.counter("perf.pad.live_lanes") - l0
                pad_acc[name][1] += m.counter("perf.pad.total_lanes") - t0
                best = results[name].get(p)
                if best is None or scored(p, r) > scored(p, best):
                    results[name][p] = r

    snap_a = store.snapshot_for(cs)
    for name, (c, h) in arm_objs.items():
        oracle_sample(c, h, snap_a)
        h.close()
    for name in ARMS:
        dl, dt = pad_acc[name]
        results[name]["pad_waste_frac"] = (
            round(1.0 - dl / dt, 4) if dt else 0.0
        )
        for p, r in sorted(results[name].items()):
            if isinstance(r, dict):
                note(f"  [{name}/{p}] goodput {r['goodput']:,.0f}/s"
                     f" p50 {r['p50_ms']}ms p99 {r['p99_ms']}ms")
        note(f"  [{name}] pad_waste_frac {results[name]['pad_waste_frac']}")

    retraces = int(m.counter("latency.retraces"))
    oracle_match = not oracle_failures

    # -- 3. score: goodput×p99 per profile, tuned vs best preset --------
    PROFILES = ("interactive", "bulk", "lookup")

    def score(arm, p):
        return scored(p, results[arm][p])

    wins = 0
    regressions = []
    ratios = []
    per_profile = {}
    for p in PROFILES:
        best_preset = max(PRESETS, key=lambda a: score(a, p))
        ts, bs = score("tuned", p), score(best_preset, p)
        beat_all = all(ts > score(a, p) for a in PRESETS)
        wins += beat_all
        gp_ratio = (results["tuned"][p]["goodput"]
                    / results[best_preset][p]["goodput"])
        ratios.append(gp_ratio)
        if ts < (1.0 - args.tolerance) * bs:
            regressions.append(p)
        per_profile[p] = dict(
            best_preset=best_preset,
            tuned_score=round(ts, 2), best_score=round(bs, 2),
            score_ratio=round(ts / bs, 3),
            goodput_ratio=round(gp_ratio, 3),
            tuned_beats_all=bool(beat_all),
        )
        note(f"profile {p}: tuned score {ts:,.1f} vs best preset"
             f" '{best_preset}' {bs:,.1f} ({ts / bs:.2f}x),"
             f" beats_all={beat_all}")
    geomean_goodput = math.exp(sum(math.log(r) for r in ratios) / len(ratios))

    # -- 4. prediction audit: measured delta within 2x of predicted -----
    def within_2x(predicted, measured, floor):
        return abs(measured - predicted) <= max(abs(predicted), floor)

    predictions = []
    kd = diff.get("latency_tiers")
    if kd is not None and "pad_waste_frac" in kd.predicted:
        pred = kd.predicted["pad_waste_frac"]
        meas = (results["tuned"]["pad_waste_frac"]
                - results["default"]["pad_waste_frac"])
        predictions.append(dict(
            knob="latency_tiers", key="pad_waste_frac",
            predicted=round(pred, 4), measured=round(meas, 4),
            within_2x=bool(within_2x(pred, meas, 0.10)),
        ))
    kd = diff.get("hold_max_s")
    if kd is not None and "p99_ms" in kd.predicted:
        pred = kd.predicted["p99_ms"]
        meas = (results["tuned"]["interactive"]["p99_ms"]
                - results["default"]["interactive"]["p99_ms"])
        predictions.append(dict(
            knob="hold_max_s", key="p99_ms",
            predicted=round(pred, 3), measured=round(meas, 3),
            within_2x=bool(within_2x(pred, meas, 1.0)),
        ))
    for pr in predictions:
        note(f"prediction {pr['knob']}/{pr['key']}: predicted"
             f" {pr['predicted']} measured {pr['measured']}"
             f" within_2x={pr['within_2x']}")

    emit(
        "tuned_vs_best_preset_goodput", round(geomean_goodput, 4), "x",
        round(geomean_goodput, 4),
        edges=int(snap.num_edges),
        profiles_won=wins, profiles=len(PROFILES),
        regressions=regressions,
        per_profile=per_profile,
        knobs_applied=[k.knob for k in diff.knobs],
        tuned_tiers=list(tuned_tiers),
        nonpow2_tiers=[int(t) for t in nonpow2],
        tuned_hold_max_s=tuned.serve.hold_max_s,
        tuned_dedup=tuned.serve.dedup,
        pad_waste_frac=results["tuned"]["pad_waste_frac"],
        pad_waste_frac_default=results["default"]["pad_waste_frac"],
        predictions=predictions,
        oracle_match=bool(oracle_match),
        retraces=retraces,
        zipf=args.zipf, platform=platform,
        note=(
            "geomean over 3 profiles of tuned goodput vs the best static"
            " preset; tuner configured from the default arm's telemetry"
            " snapshot only (occupancy histograms, flush reasons, pad"
            " ledger) — no per-arm fitting"
        ),
    )
    emit(
        "tune_pad_waste_frac", results["tuned"]["pad_waste_frac"], "frac",
        results["tuned"]["pad_waste_frac"],
        default_arm=results["default"]["pad_waste_frac"],
        tuned_tiers=list(tuned_tiers), platform=platform,
        note="share of dispatched lanes carrying padding, tuned arm",
    )

    assert retraces == 0, f"{retraces} retraces across arms"
    assert oracle_match, f"oracle mismatches at offsets {oracle_failures[:5]}"
    assert diff, "the default preset on this workload must yield proposals"
    assert wins >= 2, (
        f"tuned config won only {wins}/3 profiles: {per_profile}"
    )
    assert not regressions, (
        f"tuned config regressed beyond {args.tolerance:.0%} on"
        f" {regressions}: {per_profile}"
    )
    bad = [p for p in predictions if not p["within_2x"]]
    assert not bad, f"predictions off by more than 2x: {bad}"
    return 0


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

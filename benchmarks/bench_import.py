"""Bulk-import benchmark: N edges through Client.import_relationships
(the reference's BulkImportRelationships path, client/client.go:438-465),
then a spot-check visibility probe and a full export round-trip count.

The metric times the CLIENT path — chunk accumulation, columnar store
segments (store/store.py COLUMNAR_IMPORT_MIN), revision mint — for
pre-built Relationship objects; building 10M Python objects is the
caller's cost and is reported separately.  VERDICT round-2 item 3 asked
for a committed ≥10M-edge import timing through the Client."""

import argparse
import time

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from benchmarks.common import maybe_force_cpu, emit, note, peak_rss_mb


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=10_000_000)
    args = ap.parse_args()
    note(f"platform={maybe_force_cpu()}")

    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import Client
    from gochugaru_tpu.utils import background

    c = Client()
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition doc {
        relation reader: user
        permission view = reader
    }
    """)
    n_docs = max(args.edges // 10, 1000)
    t0 = time.perf_counter()
    # unique (doc, user) pairs by construction: every generated edge is a
    # distinct live tuple, so the imported count equals the edge count
    rels = [
        rel.Relationship(
            resource_type="doc", resource_id=f"d{i % n_docs}",
            resource_relation="reader",
            subject_type="user", subject_id=f"u{i // n_docs}",
        )
        for i in range(args.edges)
    ]
    note(f"built {len(rels):,} Relationship objects in "
         f"{time.perf_counter()-t0:.1f}s (caller-side cost, untimed below)")

    t0 = time.perf_counter()
    c.import_relationships(ctx, rels)
    dt = time.perf_counter() - t0
    rate = args.edges / dt
    emit("bulk_import_edges_per_sec", rate, "edges/sec", rate / 1_000_000,
         edges=int(args.edges), peak_rss_mb=peak_rss_mb())
    note(f"import: {dt:.1f}s for {args.edges:,} edges")

    # columnar path: same shape, fresh id space, no per-edge objects —
    # the native restore API (Client.import_relationship_columns)
    rids = [f"cd{i % n_docs}" for i in range(args.edges)]
    sids = [f"cu{i // n_docs}" for i in range(args.edges)]
    t0 = time.perf_counter()
    c.import_relationship_columns(
        ctx, resource_type="doc", resource_ids=rids,
        resource_relation="reader", subject_type="user", subject_ids=sids,
    )
    dt = time.perf_counter() - t0
    emit(
        "bulk_import_columnar_edges_per_sec", args.edges / dt, "edges/sec",
        args.edges / dt / 1_000_000, edges=int(args.edges),
    )
    note(f"columnar import: {dt:.1f}s for {args.edges:,} edges")

    # pre-interned path: int-id columns, zero string work (the 1B-edge
    # restore fast path; VERDICT r04 item 6)
    import numpy as np

    itn = c._store.interner
    t0 = time.perf_counter()
    ires = itn.node_batch("doc", [f"id{i}" for i in range(n_docs)])
    isub = itn.node_batch("user", [f"iu{i}" for i in range(args.edges // n_docs + 1)])
    note(f"interned id universe in {time.perf_counter()-t0:.1f}s "
         "(caller-side cost, untimed below)")
    res_ids = np.tile(ires, args.edges // n_docs + 1)[: args.edges]
    subj_ids = np.repeat(isub, n_docs)[: args.edges]
    t0 = time.perf_counter()
    c.import_relationship_id_columns(
        ctx, resource_ids=res_ids, resource_relation="reader",
        subject_ids=subj_ids,
    )
    dt = time.perf_counter() - t0
    emit(
        "bulk_import_interned_edges_per_sec", args.edges / dt, "edges/sec",
        args.edges / dt / 1_000_000, edges=int(args.edges),
    )
    note(f"interned import: {dt:.1f}s for {args.edges:,} edges")

    t0 = time.perf_counter()
    n = sum(
        ch["res"].shape[0]
        for ch in c.export_relationship_id_columns(ctx, c.read_schema(ctx)[1])
    )
    dt = time.perf_counter() - t0
    emit(
        "bulk_export_interned_edges_per_sec", n / dt, "edges/sec",
        n / dt / 1_000_000, edges=int(n),
    )
    note(f"interned export: {dt:.1f}s for {n:,} live edges")

    full = consistency.full()
    from gochugaru_tpu.utils import metrics

    metrics.default.reset()
    t0 = time.perf_counter()
    assert c.check_one(
        ctx, full, rel.must_from_triple("doc:d0", "view", "user:u0")
    )
    dt = time.perf_counter() - t0
    # import→first-check with the staged-prepare decomposition (the
    # prepare.* sample-ring timers engine/flat.py + device.py publish);
    # vs_baseline = target(30 s) / measured — ≥1 means at/inside target
    ms = metrics.default.snapshot()
    stages = {
        k.split(".")[1][:-2] + "_s": round(ms[k], 3)
        for k in sorted(ms)
        if k.startswith("prepare.") and k.endswith(".total_s")
    }
    emit(
        "first_check_after_import_s", dt, "s", 30.0 / max(dt, 1e-9),
        edges=int(3 * args.edges), peak_rss_mb=peak_rss_mb(), **stages,
    )
    note(f"first check after import (incl. device prepare): {dt:.1f}s | "
         + " ".join(f"{k}={v}" for k, v in stages.items()))
    t0 = time.perf_counter()
    n = sum(1 for _ in c.export_relationships(ctx, c.read_schema(ctx)[1]))
    dt = time.perf_counter() - t0
    emit("bulk_export_edges_per_sec", n / dt, "edges/sec", n / dt / 1_000_000,
         edges=int(n))
    note(f"export: {dt:.1f}s for {n:,} live edges")

    t0 = time.perf_counter()
    n = sum(
        len(ch["resource_ids"])
        for ch in c.export_relationship_columns(ctx, c.read_schema(ctx)[1])
    )
    dt = time.perf_counter() - t0
    emit(
        "bulk_export_columnar_edges_per_sec", n / dt, "edges/sec",
        n / dt / 1_000_000, edges=int(n),
    )
    note(f"columnar export: {dt:.1f}s for {n:,} live edges")


if __name__ == "__main__":
    from benchmarks.common import bench_main

    bench_main(main)

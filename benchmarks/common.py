"""Shared benchmark harness utilities.

Every benchmark prints one JSON line per metric:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is the fraction of the BASELINE.json north-star target
(10M checks/sec/chip or 2 ms p99) — the reference itself publishes no
numbers (BASELINE.md), so the target is the denominator.
"""

from __future__ import annotations

import json
import sys
import time
from typing import Callable, Sequence

import numpy as np

NORTH_STAR_RATE = 10_000_000  # checks/sec/chip
NORTH_STAR_P99_MS = 2.0


def emit(
    metric: str, value: float, unit: str, vs_baseline: float, **extra
) -> None:
    """One JSON metric line.  ``extra`` carries measurement-context
    fields (edges, batch, ...) so a headline number can never silently
    describe a smaller world than its config names."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(float(value), 4),
                "unit": unit,
                "vs_baseline": round(float(vs_baseline), 4),
                **extra,
            }
        )
    )


def note(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


def time_steady(fn: Callable[[], object], reps: int = 5) -> float:
    """Steady-state seconds/call: warm once (compile), force the platform
    into synchronous execution with a real device→host fetch, then average
    individually-completed calls.

    Why the fetch: on remote-attached TPU platforms (axon tunnel),
    ``block_until_ready`` does NOT wait until the process has performed its
    first device→host transfer — timing enqueue-only loops reports fantasy
    numbers.  One fetch switches the stream to synchronous mode; after it,
    blocked timings are real (at the cost of a per-dispatch round trip,
    which ``repeat_harness`` amortizes away for throughput numbers)."""
    import jax

    # warm THREE times, not one: the first dispatches after prepare also
    # fault in the freshly-built tables' pages (multi-GB at 10M+ edges),
    # which read as a ~3× slower "steady state" if timed
    for _ in range(3):
        out = fn()
        jax.block_until_ready(out)
    _force_sync_mode(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def _force_sync_mode(out) -> None:
    """Fetch one full (unsliced) leaf of a jit output so subsequent
    blocked timings measure real execution."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    if leaves:
        jax.device_get(leaves[0])


def repeat_harness(engine, iters: int):
    """Build a jitted fn running the engine's whole-batch check ``iters``
    times inside one ``lax.fori_loop`` dispatch, rotating the resource
    column every iteration (so XLA cannot hoist the loop body) and
    XOR/OR-accumulating the outputs (so it cannot dead-code them).

    Wraps the LEGACY two-phase kernel — the measured-true-rate baseline
    the round-2 verdict used; ``repeat_harness_flat`` is the production
    (flat hash-probe) counterpart with the same timing recipe.

    Timing recipe: t(2K) - t(K) cancels the fixed per-dispatch round trip,
    leaving K × the true batch evaluation time.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gochugaru_tpu.engine.device import _make_check_fn

    raw = _make_check_fn(
        engine.plan, engine.config, jit=False, caveat_plan=engine.caveat_plan
    )

    def fn(arrs, tid_map, now, u_subj, u_srel, u_wc, u_qctx,
           q_res, q_perm, q_subj, q_srel, q_wc, q_row, q_self, q_ctx, qctx):
        def body(i, carry):
            d0, p0, o0 = carry
            d, p, o = raw(
                arrs, tid_map, now, u_subj, u_srel, u_wc, u_qctx,
                jnp.roll(q_res, i), q_perm, q_subj, q_srel, q_wc,
                q_row, q_self, q_ctx, qctx,
            )
            return d0 ^ d, p0 ^ p, o0 | o
        z = jnp.zeros(q_res.shape[0], bool)
        return lax.fori_loop(0, iters, body, (z, z, z))

    return jax.jit(fn)


def repeat_harness_flat(engine, dsnap, slots, iters: int):
    """The repeat harness over the PRODUCTION (flat) kernel: ``iters``
    whole-batch evaluations inside one dispatch, resource column rotated
    per iteration, outputs XOR/OR-accumulated.  Same t(2K) - t(K) timing
    recipe as ``repeat_harness``; args come from
    DeviceEngine.flat_fn_and_args (pass ``jit=False`` there is not needed
    — the raw body is rebuilt here unjitted)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gochugaru_tpu.engine.flat import make_flat_fn

    raw = make_flat_fn(
        engine.compiled, engine.plan, engine.config, dsnap.flat_meta,
        tuple(slots), caveat_plan=engine.caveat_plan, jit=False,
    )

    def fn(arrs, tid_map, now, qm, qctx):
        def body(i, carry):
            d0, p0, o0 = carry
            d, p, o = raw(
                arrs, tid_map, now, qm.at[0].set(jnp.roll(qm[0], i)), qctx
            )
            return d0 ^ d, p0 ^ p, o0 | o
        z = jnp.zeros(qm.shape[1], bool)
        return lax.fori_loop(0, iters, body, (z, z, z))

    return jax.jit(fn)


def measured_rate_flat(engine, dsnap, slots, B: int, args, iters: int = 16) -> float:
    """True checks/sec of the flat kernel via the repeat harness:
    rate = iters·B / (t2 - t1).

    Raises RuntimeError when the t2 - t1 separation drowns in timing
    noise (small batches on a loaded host can invert the best-of-N
    samples, which would report a fantasy rate) — callers keep their
    blocked-dispatch figure instead of publishing garbage."""
    import jax

    f1 = repeat_harness_flat(engine, dsnap, slots, iters)
    f2 = repeat_harness_flat(engine, dsnap, slots, 2 * iters)
    out = f1(*args)
    jax.block_until_ready(out)
    jax.block_until_ready(f2(*args))
    _force_sync_mode(out)

    def timed(f):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t1 = timed(f1)
    t2 = timed(f2)
    dt = t2 - t1
    if dt < 0.2 * max(t1, 1e-9):
        raise RuntimeError(
            f"repeat-harness timing unreliable: t1={t1*1000:.1f}ms "
            f"t2={t2*1000:.1f}ms — raise iters or quiet the host"
        )
    return iters * B / dt


def sync_rate(full_fn, null_fn, args, B: int, reps: int = 7):
    """True checks/sec on platforms where only synchronous-mode timing is
    real: force sync mode with one fetch, then time blocked executions of
    the real program and of a null program with identical input/output
    signature; the difference cancels the fixed per-dispatch round trip.
    Use a batch large enough that the true step dominates the ~2 ms timing
    noise on the fixed overhead.  Returns (rate, step_seconds,
    overhead_seconds)."""
    import jax

    out = full_fn(*args)
    jax.block_until_ready(out)
    jax.block_until_ready(null_fn(*args))
    _force_sync_mode(out)

    def med(f):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    t_null = med(null_fn)
    t_full = med(full_fn)
    step = max(t_full - t_null, 1e-9)
    return B / step, step, t_null


def measured_rate(engine, dsnap, B: int, args, iters: int = 16) -> float:
    """True checks/sec via the repeat harness: rate = iters·B / (t2 - t1)
    with t1 = one dispatch of `iters` loops, t2 = one of 2·iters."""
    import jax

    f1 = repeat_harness(engine, iters)
    f2 = repeat_harness(engine, 2 * iters)
    out = f1(*args)
    jax.block_until_ready(out)
    jax.block_until_ready(f2(*args))
    _force_sync_mode(out)

    def timed(f):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = timed(f1)
    t2 = timed(f2)
    dt = max(t2 - t1, 1e-9)
    return iters * B / dt


def small_batch_latency(
    engine, dsnap, q_res, q_perm, q_subj, *,
    q_ctx=None, qctx_rows=None, now_us=None,
    warmup: int = 30, reps: int = 600,
    interleave_tracer=None, interleave=None,
) -> dict:
    """Warm latency-mode p50/p99 + mean per-stage budget for one small
    batch (engine/latency.py).  Every rep is a full dispatch — host
    lowering, H2D, pinned kernel, D2H — individually timed; the subject
    column rotates per rep so a platform cannot cache the answer.
    Returns a dict ready to splat into ``emit`` extra fields.

    Each rep roots a request-scoped trace span (utils/trace.py) exactly
    the way ``client.check`` does: with tracing disabled that is one
    branch returning the NOOP singleton, and with a tracer installed
    the rep pays full per-request span bookkeeping — so this helper is
    the honest subject for the tracing-overhead budget assertion
    (tests/test_trace_overhead.py).

    ``interleave_tracer`` (a ``trace.Tracer``) alternates that tracer
    in/out PER REP — adjacent reps see near-identical host conditions,
    so the off/on quantile differences measure tracing cost with the
    scheduler noise paired away (window-level A/B on a shared box
    drowns a <5% effect in drift).  Adds ``p50_ms_off``/``p50_ms_on``/
    ``p90_ms_off``/``p90_ms_on``/``p99_ms_off``/``p99_ms_on`` and
    ``delta_p50_ms``/``delta_p90_ms`` to the result; the headline
    quantiles then cover the mixed stream.

    ``interleave`` generalizes the same per-rep A/B to ANY toggle: an
    ``(on_fn, off_fn)`` pair called before each rep (odd reps on, even
    off) — the decision-provenance benches use it to price witness
    extraction (``lp.arm_witness``) and decision-log recording with the
    identical paired-noise methodology.  Mutually composable with
    ``interleave_tracer`` (both flip on the same rep parity)."""
    import jax  # noqa: F401  (ensures backend selection happened)

    from gochugaru_tpu.utils import trace as _trace

    lp = engine.latency_path(dsnap)
    B = q_res.shape[0]

    def once(i: int):
        sp = _trace.root_span("check", batch=B)
        try:
            out = lp.dispatch_columns(
                np.roll(q_res, i), q_perm, np.roll(q_subj, 2 * i),
                q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=now_us,
                span=sp,
            )
            assert out is not None, "latency path unavailable for this world"
            return out
        finally:
            sp.end()

    for i in range(warmup):
        if interleave is not None:
            # warm BOTH arms of the A/B (same parity as the measured
            # loop) so the no-recompile assertion can stay armed below
            interleave[0 if (i & 1) else 1]()
        once(i)
    if interleave is not None:
        interleave[1]()
    # frozen GC is the standard latency-service tuning (collection
    # pauses land straight in p99) — same recipe as bench1's client
    # loop, but unfrozen after the window: this helper runs MID-bench
    # and must not leave later sections with an uncollectable heap
    import gc

    gc.collect()
    gc.freeze()
    compiles_before = lp.compile_count
    ts = []
    by_mode = ([], [])  # interleave_tracer: (off reps, on reps)
    prev_tracer = _trace.get()
    stages = {"host_lower_s": 0.0, "h2d_s": 0.0, "kernel_s": 0.0, "d2h_s": 0.0}
    try:
        for i in range(reps):
            mode = i & 1
            if interleave_tracer is not None:
                _trace.install(interleave_tracer if mode else None)
            if interleave is not None:
                interleave[0 if mode else 1]()
            t0 = time.perf_counter()
            once(i)
            dt = (time.perf_counter() - t0) * 1000
            ts.append(dt)
            if interleave_tracer is not None or interleave is not None:
                by_mode[mode].append(dt)
            b = lp.last_budget
            for k in stages:
                stages[k] += getattr(b, k)
    finally:
        if interleave_tracer is not None:
            _trace.install(prev_tracer)
        if interleave is not None:
            interleave[1]()  # leave the toggle OFF
        gc.unfreeze()
    # armed for the interleave A/B too (both arms pre-warmed above): a
    # pin eviction mid-window would inject a compile rep into one arm
    # and silently corrupt the paired deltas — fail loudly instead
    assert lp.compile_count == compiles_before, (
        "latency path recompiled during the warm measurement window"
    )
    a = np.asarray(ts)
    out = {
        "p50_ms": round(float(np.percentile(a, 50)), 3),
        "p99_ms": round(float(np.percentile(a, 99)), 3),
        "mean_ms": round(float(a.mean()), 3),
        "host_ms": round(stages["host_lower_s"] / reps * 1000, 3),
        "h2d_ms": round(stages["h2d_s"] / reps * 1000, 3),
        "kernel_ms": round(stages["kernel_s"] / reps * 1000, 3),
        "d2h_ms": round(stages["d2h_s"] / reps * 1000, 3),
        "batch": int(B),
        "tier": int(lp.last_budget.tier),
        "n": int(reps),
    }
    if interleave_tracer is not None or interleave is not None:
        off, on = np.asarray(by_mode[0]), np.asarray(by_mode[1])
        for q in (50, 90, 99):
            out[f"p{q}_ms_off"] = round(float(np.percentile(off, q)), 3)
            out[f"p{q}_ms_on"] = round(float(np.percentile(on, q)), 3)
        out["delta_p50_ms"] = round(out["p50_ms_on"] - out["p50_ms_off"], 3)
        out["delta_p90_ms"] = round(out["p90_ms_on"] - out["p90_ms_off"], 3)
    return out


def emit_small_batch_row(
    metric: str, engine, dsnap, q_res, q_perm, q_subj, *,
    edges: int, q_ctx=None, qctx_rows=None, now_us=None, **extra
) -> dict:
    """Measure + emit one ``*_small_batch_p99_latency`` row with the
    host/H2D/kernel/D2H budget breakdown — the shared shape for the
    latency-mode rows of configs 1-4."""
    r = small_batch_latency(
        engine, dsnap, q_res, q_perm, q_subj,
        q_ctx=q_ctx, qctx_rows=qctx_rows, now_us=now_us,
    )
    p99 = r.pop("p99_ms")
    emit(
        metric, p99, "ms", NORTH_STAR_P99_MS / max(p99, 1e-9),
        edges=int(edges), **r, **extra,
    )
    note(
        f"{metric}: B={r['batch']} (tier {r['tier']}) p50={r['p50_ms']}ms "
        f"p99={p99}ms | host={r['host_ms']} h2d={r['h2d_ms']} "
        f"kernel={r['kernel_ms']} d2h={r['d2h_ms']} (ms, mean)"
    )
    return {"p99_ms": p99, **r}


def latency_percentiles(
    fn: Callable[[], object], reps: int = 50
) -> tuple[float, float, float]:
    """(p50, p99, mean) milliseconds over individually-timed calls."""
    import jax

    jax.block_until_ready(fn())
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append((time.perf_counter() - t0) * 1000)
    a = np.asarray(ts)
    return float(np.percentile(a, 50)), float(np.percentile(a, 99)), float(a.mean())


def table_bytes(dsnap) -> int:
    """Resident device-table bytes of a DeviceSnapshot — delegates to
    the perf ledger (gochugaru_tpu/utils/perf.py), the ONE
    implementation bench columns and /perf share."""
    from gochugaru_tpu.utils.perf import table_bytes as _impl

    return _impl(dsnap)


def est_bytes_per_check(dsnap) -> float:
    """HBM bytes GATHERED per check: the perf ledger's meta-driven
    model (gochugaru_tpu/utils/perf.py gathered_bytes_model) — per
    table AND per recursion level (the old copy here admitted deeper
    recursion levels were excluded; the ledger computes them from the
    snapshot's measured arrow depth and rc geometry).  Row widths and
    lane dtypes come from the ACTUAL device arrays, so packed and
    unpacked layouts are compared by what truly crosses HBM — the
    roofline numerator next to checks/s."""
    from gochugaru_tpu.utils.perf import est_bytes_per_check as _impl

    return _impl(dsnap)


def roofline_columns(rate: float, dsnap=None, bytes_per_check=None) -> dict:
    """``achieved_gbps``/``roofline_frac`` bench columns: gathered
    bytes/check × measured true checks/s against the MEASURED bandwidth
    ceiling (perf.measure_bandwidth — triad microbench, cached per
    backend fingerprint).  Splat into ``emit`` extra fields next to any
    rate column."""
    from gochugaru_tpu.utils.perf import roofline_columns as _impl

    return _impl(rate, dsnap=dsnap, bytes_per_check=bytes_per_check)


def peak_rss_mb() -> float:
    """Per-process peak resident set in MiB (ru_maxrss ⊔ /proc VmHWM;
    gochugaru_tpu/utils/metrics.py) — benches attach it as a
    ``peak_rss_mb`` column so the host-sharded build's memory claim is a
    measured number riding the trajectory, not a docstring."""
    from gochugaru_tpu.utils.metrics import peak_rss_mb as _impl

    return _impl()


def join_lookup_prewarm(timeout: float = 300.0) -> None:
    """Measurement hygiene: a full prepare may spawn the lookup-prewarm
    thread (engine/device.py, walker-serving layouts only); on a
    one-core host its O(E log E) build steals ~half the core from the
    first seconds of any throughput window — join it (bounded) before
    timing anything.  Shared by bench3/bench4/bench8 instead of three
    copies of the loop."""
    import threading

    for t in threading.enumerate():
        if t.name == "gochugaru-lookup-prewarm":
            t.join(timeout=timeout)


def maybe_emit_metrics_snapshot() -> None:
    """Gated by GOCHUGARU_BENCH_METRICS=1 (run_all.py --metrics sets
    it): append one ``metrics_snapshot`` JSON line carrying the child's
    final ``metrics.default.snapshot()`` — so a bench regression row
    arrives WITH the counters that explain it (shed/retry/fallback/
    breaker activity, stage p99s), not just the headline number.
    Call as the last line of every bench main()."""
    import os

    if os.environ.get("GOCHUGARU_BENCH_METRICS") != "1":
        return
    from gochugaru_tpu.utils import metrics as _metrics

    snap = _metrics.default.snapshot()
    emit(
        "metrics_snapshot", len(snap), "keys", 0.0,
        snapshot={k: round(float(v), 9) for k, v in sorted(snap.items())},
    )


def bench_main(main) -> None:
    """Standard bench ``__main__`` tail: run ``main()`` and ALWAYS append
    the --metrics snapshot — a bench that dies mid-run would otherwise
    lose exactly the counter dump that explains the failure.  Exits with
    main's return code when it returns one (bench2's degraded-mesh rc)."""
    rc = None
    try:
        rc = main()
    finally:
        maybe_emit_metrics_snapshot()
    if isinstance(rc, int):
        raise SystemExit(rc)


def maybe_force_cpu() -> str:
    """Benches honor GOCHUGARU_FORCE_CPU=1 (set by run_all.py when its
    bounded TPU probe fails) — the axon TPU backend can hang on init, and
    a hung child records nothing.  Returns the active platform name."""
    import os

    if os.environ.get("GOCHUGARU_FORCE_CPU") == "1":
        from gochugaru_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/gochugaru_xla_cache_h2")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax.default_backend()

#!/usr/bin/env bash
# SpMM smoke: the unified masked-SpMM serving core (engine/spmm.py)
# end-to-end on a small world, CI-runnable.  Asserts (1) fused-vs-legacy
# parity through all three re-expressed kernel families — batched checks
# (bitwise verdict arrays), LookupResources/LookupSubjects (exact ID
# lists, host oracle as referee), and the fold T-join (bitwise output
# arrays incl. the closure-overflow size gate); (2) a ≥2-hop
# LookupResources drains its whole candidate fixpoint in exactly ONE
# fused device dispatch, counter-asserted on lookup.dispatches /
# spmm.dispatches; (3) the bucket-sharded owner-routed hop path (which
# keeps looped per-hop dispatches by design) matches the single-chip
# fused answer.  Prints SPMM-SMOKE-OK on success, mirroring the chaos/
# partition/lookup smokes.  Emits one JSON metric line for
# benchmarks/run_all.py (config 19).
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import dataclasses
import json
import random
import sys
import time

import numpy as np

from gochugaru_tpu.utils.platform import force_cpu_platform

force_cpu_platform(8)

sys.path.insert(0, ".")
from gochugaru_tpu import rel
from gochugaru_tpu.caveats import compile_cel
from gochugaru_tpu.engine import lookup as lm
from gochugaru_tpu.engine import spmv
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.fold import t_join_core
from gochugaru_tpu.engine.oracle import Oracle
from gochugaru_tpu.engine.spmm import tjoin_spmm
from gochugaru_tpu.parallel import ShardedEngine, make_mesh
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils.metrics import default as _m

t0 = time.time()
NOW = 1_700_000_000_000_000

# every gate the semiring multiplies: caveats, recursive usersets,
# wildcards, arrow chains, exclusion, intersection
SCHEMA = """
caveat lim(v int, cap int) { v <= cap }
definition user {}
definition group {
    relation member: user | group#member | user:*
}
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition doc {
    relation parent: folder
    relation owner: user | group#member
    relation writer: user | group#member | user with lim
    relation banned: user
    permission write = (owner + writer + parent->view) - banned
    permission manage = owner & writer
}
"""

rng = random.Random(7)
users = [f"user:u{i}" for i in range(40)]
groups = [f"group:g{i}" for i in range(6)]
folders = [f"folder:f{i}" for i in range(30)]
docs = [f"doc:d{i}" for i in range(200)]
rels = []
# nested groups (g0 ⊃ g1 ⊃ g2 ...) + direct members + one wildcard
for i in range(len(groups) - 1):
    rels.append(rel.must_from_tuple(f"{groups[i]}#member",
                                    f"{groups[i+1]}#member"))
for g in groups:
    for u in rng.sample(users, 4):
        rels.append(rel.must_from_tuple(f"{g}#member", u))
rels.append(rel.must_from_tuple(f"{groups[-1]}#member", "user:*"))
# folder forest (arity 4) with group and user viewers near the roots
for i in range(1, len(folders)):
    rels.append(rel.must_from_tuple(f"{folders[i]}#parent",
                                    f"folder:f{(i - 1) // 4}"))
rels.append(rel.must_from_tuple(f"{folders[0]}#viewer",
                                f"{groups[1]}#member"))
rels.append(rel.must_from_tuple(f"{folders[2]}#viewer",
                                rng.choice(users)))
for d in docs:
    rels.append(rel.must_from_tuple(f"{d}#parent", rng.choice(folders)))
    if rng.random() < 0.3:
        rels.append(rel.must_from_tuple(f"{d}#owner", rng.choice(users)))
    if rng.random() < 0.3:
        r = rel.must_from_triple(d, "writer", rng.choice(users))
        if rng.random() < 0.5:
            r = r.with_caveat("lim", {"v": rng.choice([1, 99]), "cap": 10})
        rels.append(r)
    if rng.random() < 0.1:
        rels.append(rel.must_from_triple(d, "banned", rng.choice(users)))

cs = compile_schema(parse_schema(SCHEMA))
snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
progs = {
    name: compile_cel(name, decl.params, decl.expression)
    for name, decl in cs.schema.caveats.items()
}
oracle = Oracle(cs, rels, progs, now_us=NOW)
eng_on = DeviceEngine(cs)
assert eng_on.config.spmm, "spmm must default on"
eng_off = DeviceEngine(cs, dataclasses.replace(eng_on.config, spmm=False))
ds_on = eng_on.prepare(snap)
ds_off = eng_off.prepare(snap)
assert spmv.frontier_ok(eng_on, ds_on), "frontier path must serve"

# (1a) check family: bitwise verdict parity, fused vs legacy T-join
queries = [
    rel.must_from_triple(rng.choice(docs), perm, rng.choice(users))
    for perm in ("write", "manage") for _ in range(60)
]
d_on, p_on, o_on = eng_on.check_batch(ds_on, queries, now_us=NOW)
d_off, p_off, o_off = eng_off.check_batch(ds_off, queries, now_us=NOW)
assert (np.array_equal(d_on, d_off) and np.array_equal(p_on, p_off)
        and np.array_equal(o_on, o_off)), "check verdicts diverged"
print(f"check parity: ok ({len(queries)} verdicts bitwise)",
      file=sys.stderr)

# (1b) fold family: the T-join as an SpMM instance, bitwise incl. the
# closure-overflow size gate (None == None)
jrng = np.random.RandomState(7)
k1 = jrng.randint(0, 50, 150).astype(np.int64)
pe = jrng.randint(0, 40, 150).astype(np.int64)
w = jrng.randint(1, 1000, 150).astype(np.int32)
cl_k1 = jrng.randint(0, 60, 200).astype(np.int64)
cl_k2 = jrng.randint(0, 40, 200).astype(np.int64)
c_d = jrng.randint(0, 1000, 200).astype(np.int32)
c_p = jrng.randint(0, 1000, 200).astype(np.int32)
for cap in (1 << 30, 220, 1):
    a = t_join_core(k1, pe, w, cl_k1, cl_k2, c_d, c_p, cap)
    b = tjoin_spmm(k1, pe, w, cl_k1, cl_k2, c_d, c_p, cap)
    if a is None:
        assert b is None, "overflow gate diverged"
        continue
    assert b is not None and len(a) == len(b)
    for x, y in zip(a, b):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(x, y)
print("fold T-join parity: ok (bitwise, 3 caps)", file=sys.stderr)

# (1c) lookup family: fused == legacy == host oracle, both directions
checked = 0
for u in users[:8] + [f"{groups[0]}#member"]:
    stype, _, q = u.partition(":")
    sid, _, srel = q.partition("#")
    fused = lm.lookup_resources_device(
        eng_on, ds_on, "doc", "write", stype, sid, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    legacy = lm.lookup_resources_device(
        eng_off, ds_off, "doc", "write", stype, sid, srel,
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_resources("doc", "write", stype, sid, srel))
    assert fused == legacy == want, f"resources parity broke for {u}"
    checked += len(fused)
for d in docs[:6]:
    fused = lm.lookup_subjects_device(
        eng_on, ds_on, "doc", d.split(":")[1], "write", "user",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    legacy = lm.lookup_subjects_device(
        eng_off, ds_off, "doc", d.split(":")[1], "write", "user",
        now_us=NOW, oracle_factory=lambda: oracle,
    )
    want = sorted(oracle.lookup_subjects(
        "doc", d.split(":")[1], "write", "user", ""
    ))
    assert fused == legacy == want, f"subjects parity broke for {d}"
    checked += len(fused)
print(f"lookup parity: ok ({checked} results, both directions)",
      file=sys.stderr)

# (2) a ≥2-hop lookup (doc -> folder chain -> group closure) drains in
# exactly ONE fused dispatch: the tentpole's counter-asserted contract
st = spmv.state_for(eng_on, ds_on)
assert st._spmm is not None, "fused server must be eligible"
snap_i = snap.interner
# the largest-reach user: an answer spanning many docs can only come
# through group closure -> folder viewer -> parent chain (≥2 hops)
reach = {
    u: len(list(oracle.lookup_resources("doc", "write", "user",
                                        u.split(":")[1], "")))
    for u in users
}
deep_user = max(users, key=lambda u: reach[u])
assert reach[deep_user] > 20, "no multi-hop bulk subject in this world"
un = snap_i.lookup("user", deep_user.split(":")[1])
wc = snap_i.lookup("user", "*")
rtid = snap_i.type_lookup("doc")
looped0 = _m.counter("lookup.dispatches")
fused0 = _m.counter("spmm.dispatches")
n = 0
for blk in st.resource_candidates(rtid, un, -1, wc, NOW):
    n += blk.shape[0]
assert n >= reach[deep_user], "candidates must be a superset"
assert _m.counter("spmm.dispatches") - fused0 == 1, "not one fused dispatch"
assert _m.counter("lookup.dispatches") - looped0 == 0, "looped hops leaked"
print(f"one-dispatch fixpoint: ok ({n} candidates, ≥2 hops)",
      file=sys.stderr)

# (3) owner-routed 2-shard hops (looped by design) match the fused answer
sh = ShardedEngine(cs, make_mesh(1, 2))
sds = sh.prepare(snap)
assert spmv.frontier_ok(sh, sds)
uid = deep_user.split(":")[1]
routed = lm.lookup_resources_device(
    sh, sds, "doc", "write", "user", uid,
    now_us=NOW, oracle_factory=lambda: oracle,
)
single = lm.lookup_resources_device(
    eng_on, ds_on, "doc", "write", "user", uid,
    now_us=NOW, oracle_factory=lambda: oracle,
)
assert routed == single, "routed-shard lookup diverged from fused"
print("routed-shard parity: ok", file=sys.stderr)

print(json.dumps({
    "metric": "spmm_smoke", "value": checked, "unit": "parity results",
    "vs_baseline": 1.0, "edges": int(snap.num_edges), "batch": len(queries),
    "wall_s": round(time.time() - t0, 1),
}))
EOF

echo "SPMM-SMOKE-OK"

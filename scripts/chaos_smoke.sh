#!/usr/bin/env bash
# Chaos smoke: the fault-matrix soak (tests/test_chaos.py) with a fixed
# seed under the tier-1 timeout.  Tier-1-compatible by construction: the
# soak carries no `slow` marker, so `-m 'not slow'` (the tier-1 filter)
# selects it — this wrapper exists for running the matrix alone, fast,
# with reproducible parameters.
#
# Usage:
#   scripts/chaos_smoke.sh                 # fixed default seed, 30 rounds
#   GOCHUGARU_CHAOS_SEED=7 scripts/chaos_smoke.sh   # another fault schedule
#   GOCHUGARU_CHAOS_ROUNDS=100 scripts/chaos_smoke.sh  # longer soak
set -o pipefail

cd "$(dirname "$0")/.."

: "${GOCHUGARU_CHAOS_SEED:=20260803}"
: "${GOCHUGARU_CHAOS_ROUNDS:=30}"
: "${CHAOS_TIMEOUT_S:=600}"

export GOCHUGARU_CHAOS_SEED GOCHUGARU_CHAOS_ROUNDS

echo "# chaos smoke: seed=${GOCHUGARU_CHAOS_SEED} rounds=${GOCHUGARU_CHAOS_ROUNDS}" >&2
timeout -k 10 "${CHAOS_TIMEOUT_S}" \
    env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py tests/test_faults.py tests/test_retry.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
rc=$?
if [ "$rc" -eq 0 ]; then
    echo "# chaos smoke: PASS" >&2
else
    echo "# chaos smoke: FAIL rc=${rc} (reproduce with the same GOCHUGARU_CHAOS_SEED)" >&2
fi
exit "$rc"

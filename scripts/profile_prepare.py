"""Profile the cold-start prepare pipeline: cProfile + stage-timer dump
of a first prepare (import -> first check) at --edges (default 1M).

The cold-start path is import (columnar segments) -> materialize
(store/snapshot.py finish_snapshot) -> device prepare (store/closure.py
build_closure, engine/flat.py build_flat_arrays, H2D) -> first kernel
compile+dispatch.  When it regresses, run this before re-deriving the
pipeline by hand:

    JAX_PLATFORMS=cpu python scripts/profile_prepare.py --edges 1000000

prints the top --top cumulative-time frames of each phase plus the
``prepare.*`` stage timers (utils/metrics.py sample rings) that
benchmarks/bench_import.py reports per-stage.
"""

import argparse
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--edges", type=int, default=1_000_000)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument(
        "--groups", type=int, default=0,
        help="add a group-nesting subgraph of this many membership edges "
        "(exercises the closure stage; default edges//100)",
    )
    args = ap.parse_args()

    import numpy as np

    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import Client
    from gochugaru_tpu.utils import background, metrics

    c = Client()
    ctx = background()
    c.write_schema(ctx, """
    definition user {}
    definition team {
        relation member: user | team#member
    }
    definition doc {
        relation reader: user | team#member
        permission view = reader
    }
    """)

    n_docs = max(args.edges // 10, 1000)
    n_users = args.edges // n_docs + 1
    itn = c._store.interner
    ires = itn.node_batch("doc", [f"d{i}" for i in range(n_docs)])
    isub = itn.node_batch("user", [f"u{i}" for i in range(n_users)])
    res_ids = np.tile(ires, args.edges // n_docs + 1)[: args.edges]
    subj_ids = np.repeat(isub, n_docs)[: args.edges]

    t0 = time.perf_counter()
    c.import_relationship_id_columns(
        ctx, resource_ids=res_ids, resource_relation="reader",
        subject_ids=subj_ids,
    )
    n_groups = args.groups or max(args.edges // 100, 10)
    if n_groups:
        # a team tree plus team->doc grants: the closure/T-index stages
        # are a no-op without a membership subgraph
        iteams = itn.node_batch("team", [f"t{i}" for i in range(n_groups)])
        # binary-tree nesting (depth log2 n): child team i is a member of
        # team (i-1)//2, so the closure converges in ~log rounds
        ch = np.arange(1, n_groups, dtype=np.int64)
        it64 = np.asarray(iteams, np.int64)
        c.import_relationship_id_columns(
            ctx, resource_ids=it64[(ch - 1) // 2], resource_relation="member",
            subject_ids=it64[ch], subject_relation="member",
        )
        c.import_relationship_id_columns(
            ctx,
            resource_ids=np.asarray(ires[: min(n_groups, len(ires))], np.int64),
            resource_relation="reader",
            subject_ids=np.asarray(iteams[: min(n_groups, len(ires))], np.int64),
            subject_relation="member",
        )
        c.import_relationship_id_columns(
            ctx, resource_ids=np.asarray(iteams, np.int64),
            resource_relation="member",
            subject_ids=np.asarray(isub[:1], np.int64).repeat(len(iteams)),
        )
    print(f"# import: {time.perf_counter() - t0:.2f}s "
          f"({args.edges:,} edges + {3 * n_groups:,} membership rows)")

    metrics.default.reset()
    pr = cProfile.Profile()
    t0 = time.perf_counter()
    pr.enable()
    ok = c.check_one(
        ctx, consistency.full(),
        rel.must_from_triple("doc:d0", "view", "user:u0"),
    )
    pr.disable()
    wall = time.perf_counter() - t0
    assert ok
    print(f"# first check after import: {wall:.2f}s")

    snap = metrics.default.snapshot()
    stages = sorted(
        k for k in snap if k.startswith("prepare.") and k.endswith(".total_s")
    )
    print("# stage timers (prepare.*):")
    for k in stages:
        print(f"#   {k[:-8]:28s} {snap[k]:8.3f}s")

    buf = io.StringIO()
    st = pstats.Stats(pr, stream=buf)
    st.sort_stats("cumulative").print_stats(args.top)
    print(buf.getvalue())


if __name__ == "__main__":
    main()

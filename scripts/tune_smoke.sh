#!/usr/bin/env bash
# Self-tuning smoke: the closed loop from the perf ledger to
# EngineConfig, CI-runnable.  Drives a short mixed load through a
# serving handle under the default config, captures a telemetry
# snapshot (gochugaru_tpu/tune/snapshot.py), and asserts the offline
# tuner (tune/tuner.py) emits a non-empty diff with per-knob measured
# evidence + predicted deltas, that the diff survives a JSON round
# trip, and that applying it reaches a FIXED POINT (re-proposing
# against the same snapshot with the tuned target re-proposes none of
# the applied knobs).  Then arms the OnlineController on the live
# handle: bounded one-rung moves under cooldown, tune.* observability
# counters, and one-call revert back to the preset.  Prints
# TUNE-SMOKE-OK on success, mirroring scripts/serve_smoke.sh.
#
# Usage:
#   scripts/tune_smoke.sh
#   TUNE_SMOKE_SECONDS=3 scripts/tune_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

: "${TUNE_SMOKE_SECONDS:=2}"
: "${TUNE_SMOKE_TIMEOUT_S:=420}"

export TUNE_SMOKE_SECONDS

timeout -k 10 "${TUNE_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import time

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import new_tpu_evaluator, with_latency_mode
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.serve import ServeConfig
from gochugaru_tpu.tune import (
    OnlineController, TuneDiff, TuneTarget, apply_diff, collect_snapshot,
    propose,
)
from gochugaru_tpu.utils import metrics
from gochugaru_tpu.utils.context import background

SECONDS = float(os.environ.get("TUNE_SMOKE_SECONDS", "2"))
m = metrics.default
rng = np.random.default_rng(21)
ctx = background()
c = new_tpu_evaluator(with_latency_mode())
c.write_schema(ctx, """
definition user {}
definition repo { relation reader: user  permission read = reader }
""")
txn = rel.Txn()
for i in range(300):
    txn.touch(rel.must_from_triple(
        f"repo:r{i}", "reader", f"user:u{int(rng.integers(90))}"))
c.write(ctx, txn)
cs = consistency.min_latency()
full = c.store.snapshot_for(consistency.full())
inter, slot = full.interner, full.compiled.slot_of_name
POOL = 2048
pool_res = np.array([inter.node("repo", f"r{int(i)}")
                     for i in rng.integers(0, 300, POOL)], np.int32)
pool_subj = np.array([inter.node("user", f"u{int(i)}")
                      for i in rng.integers(0, 90, POOL)], np.int32)
pool_perm = np.full(POOL, slot["read"], np.int32)

ecfg = EngineConfig()
h = c.with_serving(cs=cs, config=ServeConfig(), cache=True)
for t in ecfg.latency_tiers:  # warm each tier pin before measuring
    n = min(int(t), POOL - 1)
    h.submit_columns(ctx, pool_res[:n], pool_perm[:n],
                     pool_subj[:n]).result(timeout=120.0)

def drive(seconds):
    futs, t0, k = [], time.perf_counter(), 0
    while time.perf_counter() - t0 < seconds:
        s = int(rng.integers(0, POOL - 300))
        n = 300 if k % 20 == 19 else 7
        futs.append(h.submit_columns(
            ctx, pool_res[s:s + n], pool_perm[s:s + n], pool_subj[s:s + n],
            client_id=k % 4))
        k += 1
        time.sleep(1 / 150)
    for f in futs:
        f.result(timeout=60.0)

drive(SECONDS)

# -- offline: snapshot -> propose -> JSON round trip -> fixed point -----
snap = collect_snapshot(m, engine_config=ecfg,
                        serve_config=h.batcher.config, vcache=c._vcache)
target = TuneTarget(engine=ecfg, serve=h.batcher.config,
                    cache_bytes=int(c._vcache.max_bytes))
diff = propose(snap, target)
assert diff, "default config under clock-bound load must yield proposals"
for k in diff.knobs:
    assert k.evidence, f"knob {k.knob} has no measured evidence"
rt = TuneDiff.from_json(diff.to_json())
assert rt.to_json() == diff.to_json(), "diff JSON round trip drifted"
tuned = apply_diff(target, diff)
again = propose(snap, tuned)
applied = {k.knob for k in diff.knobs}
re_proposed = applied & {k.knob for k in again.knobs}
assert not re_proposed, f"no fixed point: {re_proposed} re-proposed"
print(f"# offline: {len(diff.knobs)} knob(s) proposed "
      f"({', '.join(sorted(applied))}); JSON round trip + fixed point OK")

# -- online: bounded moves, observability, revert -----------------------
preset_hold = float(h.batcher.config.hold_max_s)
ctl = OnlineController(h.batcher, vcache=c._vcache, registry=m,
                       cooldown_steps=1)
moves = 0
for _ in range(4):
    drive(max(0.6, SECONDS / 3))
    moves += ctl.step()
assert moves >= 1, "controller never moved under clock-bound load"
assert float(h.batcher.config.hold_max_s) < preset_hold
assert int(m.counter("tune.moves")) == moves
assert m.gauge("tune.hold_max_s") == float(h.batcher.config.hold_max_s)
ctl.revert()
assert float(h.batcher.config.hold_max_s) == preset_hold
assert int(m.counter("tune.reverts")) == 1
print(f"# online: {moves} bounded move(s), gauges live, revert restored "
      f"hold={preset_hold}s")

h.close()
print(json.dumps({
    "metric": "tune_smoke_knobs", "value": len(diff.knobs),
    "moves": moves, "knobs": sorted(applied),
}))
print("TUNE-SMOKE-OK")
EOF

#!/usr/bin/env bash
# Verdict-cache smoke: concurrent duplicate-heavy load through the
# continuous-batching front-end with the revision-pinned verdict cache +
# in-flight dedup armed (engine/vcache.py), oracle parity asserted on
# EVERY answer — including cache-served and dedup-fanned ones — then a
# cache-off pass over the SAME query set asserting bitwise parity (the
# cache-off path is byte-for-byte the pre-cache serving code), a
# hit-rate floor, and a chaos round with the cache.lookup fault site
# armed.  Prints CACHE-SMOKE-OK on success — the CI-runnable proof the
# cache layer answers correctly under concurrency, mirroring
# scripts/serve_smoke.sh.
#
# Usage:
#   scripts/cache_smoke.sh                       # 8 submitters, 12 rounds
#   CACHE_SMOKE_SUBMITTERS=16 scripts/cache_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

: "${CACHE_SMOKE_SUBMITTERS:=8}"
: "${CACHE_SMOKE_ROUNDS:=12}"
: "${CACHE_SMOKE_TIMEOUT_S:=420}"

export CACHE_SMOKE_SUBMITTERS CACHE_SMOKE_ROUNDS

timeout -k 10 "${CACHE_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import threading

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_host_only_evaluation, with_latency_mode,
    with_store,
)
from gochugaru_tpu.serve import ServeConfig
from gochugaru_tpu.utils import faults, metrics
from gochugaru_tpu.utils.context import background

N = int(os.environ.get("CACHE_SMOKE_SUBMITTERS", "8"))
ROUNDS = int(os.environ.get("CACHE_SMOKE_ROUNDS", "12"))

c = new_tpu_evaluator(with_latency_mode())
ctx = background()
c.write_schema(ctx, """
definition user {}
definition org { relation admin: user  relation member: user }
definition repo {
    relation org: org
    relation reader: user
    permission admin = org->admin
    permission read = reader + admin + org->member
}
""")
rng = np.random.default_rng(20260804)
txn = rel.Txn()
for i in range(150):
    txn.touch(rel.must_from_triple(
        f"repo:r{i}", "reader", f"user:u{rng.integers(80)}"))
    txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 4}"))
for o in range(4):
    txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
    txn.touch(rel.must_from_triple(f"org:o{o}", "member", f"user:u{o + 20}"))
c.write(ctx, txn)
oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))
cs = consistency.full()
ml = consistency.min_latency()
m = metrics.default

# a SMALL duplicate-heavy pool: 40 distinct checks shared by every
# submitter — concurrency guarantees in-flight twins and the cache
# guarantees steady-state hits
POOL = [rel.must_from_triple(
    f"repo:r{i % 40}", "read", f"user:u{(i * 7) % 80}") for i in range(40)]
WANT = oracle.check(ctx, cs, *POOL)

# -- phase 1: cache+dedup on, concurrent, parity on EVERY answer --------
mismatches = []
with c.with_serving(cs=ml, cache=True) as h:
    def worker(w):
        lr = np.random.default_rng(1000 + w)
        for _ in range(ROUNDS):
            idx = [int(lr.integers(len(POOL))) for _ in range(6)]
            got = h.check(ctx.with_timeout(60.0),
                          *[POOL[i] for i in idx], client_id=w)
            if list(got) != [WANT[i] for i in idx]:
                mismatches.append((w, idx))
    ts = [threading.Thread(target=worker, args=(w,)) for w in range(N)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # warm steady-state answer over the whole pool (columns surface)
    got_on = [h.check(ctx, *POOL)]
assert not mismatches, f"{len(mismatches)} cached/deduped answers wrong"
hits, misses = m.counter("cache.hits"), m.counter("cache.misses")
hit_rate = hits / max(hits + misses, 1)
dedup = (m.counter("serve.dedup_parked") + m.counter("dedup.batch_dups"))
assert hit_rate >= 0.5, f"hit rate {hit_rate:.2%} under duplicate-heavy load"
assert m.counter("cache.puts") > 0
print(f"# cache parity: {N} submitters x {ROUNDS} rounds over a "
      f"{len(POOL)}-check pool — every answer == oracle; "
      f"hit_rate={hit_rate:.1%} deduped={int(dedup)}")

# -- phase 2: cache-off bitwise parity over the same queries ------------
with c.with_serving(cs=ml, cache=False,
                    config=ServeConfig(dedup=False)) as h_off:
    got_off = [h_off.check(ctx, *POOL)]
assert got_on == got_off == [WANT], "cache-off parity broke"
print("# cache-off pass: identical answers through the pre-cache path")

# -- phase 3: chaos — cache.lookup armed, envelope absorbs it -----------
r0 = m.counter("retry.retries")
with c.with_serving(cs=ml, cache=True) as h:
    with faults.default.armed("cache.lookup", probability=0.4,
                              seed=7) as spec:
        for i in range(30):
            got = h.check(ctx.with_timeout(60.0), *POOL[:6])
            assert list(got) == WANT[:6], f"chaos round {i} wrong"
    assert spec.fired > 0, "cache.lookup never fired"
print(f"# chaos: cache.lookup fired {spec.fired}x, "
      f"{int(m.counter('retry.retries') - r0)} envelope retries, "
      "parity held")

import json
print(json.dumps({
    "metric": "cache_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "submitters": N, "rounds": ROUNDS,
    "hit_rate": round(hit_rate, 4), "deduped": int(dedup),
    "cache_lookup_faults": int(spec.fired),
    "note": "oracle parity incl. cache-served answers + cache-off "
            "bitwise parity + hit-rate floor + chaos on cache.lookup",
}))
print(f"CACHE-SMOKE-OK submitters={N} rounds={ROUNDS} "
      f"hit_rate={hit_rate:.3f} deduped={int(dedup)} "
      f"faults={int(spec.fired)}")
EOF
rc=$?
exit "$rc"

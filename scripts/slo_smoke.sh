#!/usr/bin/env bash
# SLO / incident smoke: arm a chaos-registry fault site so the latency
# path fails under serving load, trip the circuit breaker, and assert
# the anomaly-diagnosis loop closes END TO END with zero configuration
# beyond with_telemetry(incident_dir=...):
#   1. the breaker trip fires the flight-recorder trigger bus;
#   2. an incident bundle lands on disk containing the OFFENDING
#      dispatch traces (error-attributed spans, trace ids listed in the
#      bundle head) plus the metrics/cost-model state;
#   3. the /slo endpoint reports the transient-fault burn;
#   4. /healthz degrades to "degraded" with machine-readable reasons
#      while the breaker is open.
# Prints SLO-SMOKE-OK on success — the CI-runnable proof, mirroring
# scripts/serve_smoke.sh / telemetry_smoke.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${SLO_SMOKE_TIMEOUT_S:=420}"

timeout -k 10 "${SLO_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import tempfile
import threading
import time
import urllib.request

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_admission_control, with_latency_mode,
    with_telemetry,
)
from gochugaru_tpu.utils import faults, metrics, trace
from gochugaru_tpu.utils.admission import AdmissionConfig
from gochugaru_tpu.utils.context import background

D = tempfile.mkdtemp(prefix="gochugaru_incidents_")
m = metrics.default

# zero manual configuration beyond incident_dir: recorder + SLO engine +
# 0%-head-sample tracer all arm here
c = new_tpu_evaluator(
    with_latency_mode(),
    with_admission_control(AdmissionConfig(
        breaker_threshold=2, breaker_cooldown_s=60.0,
    )),
    with_telemetry(port=0, incident_dir=D),
)
url = c.telemetry.url
ctx = background()
c.write_schema(ctx, """
definition user {}
definition doc { relation reader: user  permission read = reader }
""")
txn = rel.Txn()
for i in range(64):
    txn.create(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i % 16}"))
c.write(ctx, txn)
qs = [rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i % 16}")
      for i in range(8)]
# warm: pin the latency tier before the storm
for _ in range(4):
    c.check(ctx, consistency.full(), *qs)
assert m.counter("latency.dispatches") > 0, "latency path never engaged"

# -- the fault storm under serving load ---------------------------------
trips0 = m.counter("breaker.trips")
with c.with_serving() as h:
    stop = threading.Event()

    def load(w):
        lr = np.random.default_rng(w)
        while not stop.is_set():
            sub = [rel.must_from_triple(
                f"doc:d{lr.integers(64)}", "read",
                f"user:u{lr.integers(16)}") for _ in range(4)]
            h.check(ctx.with_timeout(30.0), *sub, client_id=w)
    ts = [threading.Thread(target=load, args=(w,), daemon=True)
          for w in range(3)]
    for t in ts:
        t.start()
    time.sleep(0.3)  # load flowing
    faults.arm("latency.dispatch", times=4)
    t0 = time.time()
    while m.counter("breaker.trips") <= trips0 and time.time() - t0 < 30:
        time.sleep(0.05)
    stop.set()
    for t in ts:
        t.join(timeout=30)
    faults.disarm("latency.dispatch")
assert m.counter("breaker.trips") > trips0, "breaker never tripped"
print(f"# breaker tripped under load "
      f"(trips={int(m.counter('breaker.trips'))}, "
      f"retries={int(m.counter('retry.retries'))})")

# -- 1+2: the incident bundle, with the offending traces ----------------
c.recorder.flush()
bundle_path = None
t0 = time.time()
while bundle_path is None and time.time() - t0 < 20:
    hits = [f for f in os.listdir(D)
            if f.startswith("incident_") and "breaker.trip" in f]
    if hits:
        bundle_path = os.path.join(D, sorted(hits)[0])
        break
    time.sleep(0.2)
assert bundle_path, f"no breaker.trip incident bundle appeared under {D}"
lines = [json.loads(ln) for ln in open(bundle_path) if ln.strip()]
head = lines[0]
assert head["kind"] == "incident" and head["trigger"] == "breaker.trip", head
traces = [ln for ln in lines if ln["kind"] == "trace"]
assert traces, "bundle retained no traces"
offending = [
    t["trace_id"] for t in traces
    if any("error" in (sp.get("attrs") or {}) for sp in t["spans"])
]
assert offending, "no error-attributed (offending) trace in the bundle"
assert set(offending) <= set(head["trace_ids"]), "head trace-id index wrong"
mline = next(ln for ln in lines if ln["kind"] == "metrics")
assert "breaker.trips" in mline["counters"], "metrics dump missing"
assert "cost_model" in head["context"], "cost-model state missing"
print(f"# incident bundle: {os.path.basename(bundle_path)} — "
      f"{len(traces)} traces, {len(offending)} offending "
      f"(e.g. {offending[0]})")

# -- 3: /slo reports the burn ------------------------------------------
def get(path):
    with urllib.request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read().decode())

burn = 0.0
t0 = time.time()
while time.time() - t0 < 15:
    rep = get("/slo")
    assert rep["enabled"], "/slo engine missing"
    row = next(s for s in rep["slos"] if s["name"] == "transient_faults")
    burn = max(w["burn"] for w in row["windows"].values())
    if burn > 0:
        break
    time.sleep(0.5)
assert burn > 0, "transient-fault burn never showed on /slo"
print(f"# /slo: transient_faults burn={burn} "
      f"(budget {row['budget']}, breached={row['breached']})")

# -- 4: /healthz readiness degrades while the breaker is open -----------
hz = get("/healthz")
assert hz["status"] == "degraded", hz
assert "breaker_open" in hz["reasons"], hz["reasons"]
assert hz["breaker_state"] == 2 and hz["incidents"] >= 1, hz
print(f"# /healthz: status={hz['status']} reasons={hz['reasons']}")

print(json.dumps({
    "metric": "slo_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "breaker_trips": int(m.counter("breaker.trips")),
    "incident_traces": len(traces), "offending_traces": len(offending),
    "transient_fault_burn": round(burn, 3),
    "note": "breaker trip -> incident bundle with offending trace ids"
            " + /slo burn + degraded /healthz",
}))
print(f"SLO-SMOKE-OK bundle={os.path.basename(bundle_path)} "
      f"offending={len(offending)} burn={round(burn, 3)}")
EOF
rc=$?
exit "$rc"

#!/usr/bin/env bash
# Decision-provenance smoke: explain-vs-oracle path parity on a
# caveat+wildcard+fold world (witness-seeded device explain == the
# instrumented oracle walk, witness ⊆ oracle path), a denial tree
# carrying the exhausted frontier, cache-hit re-derivation at the pinned
# revision, decision-log ring + JSONL rotation, live /decisions +
# per-strategy verdict counters + the stock denial-rate SLO + a
# decision-carrying incident bundle, and an interleaved-rep A/B pricing
# the provenance layer's disarmed cost (explain_overhead_frac).  Prints
# EXPLAIN-SMOKE-OK on success — the CI-runnable proof, mirroring
# scripts/cache_smoke.sh.
#
# Usage:
#   scripts/explain_smoke.sh
#   EXPLAIN_SMOKE_CHECKS=60 scripts/explain_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

: "${EXPLAIN_SMOKE_CHECKS:=40}"
: "${EXPLAIN_SMOKE_TIMEOUT_S:=420}"

export EXPLAIN_SMOKE_CHECKS

timeout -k 10 "${EXPLAIN_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import datetime as dt
import json
import os
import tempfile
import time
import urllib.request

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_decision_log, with_host_only_evaluation,
    with_latency_mode, with_store, with_telemetry,
)
from gochugaru_tpu.engine import explain as ex
from gochugaru_tpu.utils import decisions as dec
from gochugaru_tpu.utils import metrics, trace
from gochugaru_tpu.utils.context import background

N = int(os.environ.get("EXPLAIN_SMOKE_CHECKS", "40"))
m = metrics.default
tmp = tempfile.mkdtemp(prefix="gochugaru_decisions_")
sink = os.path.join(tmp, "decisions.jsonl")

c = new_tpu_evaluator(
    with_latency_mode(),
    with_decision_log(sink_path=sink, rotate_bytes=4096, rotate_keep=3),
    with_telemetry(port=0),
)
ctx = background()
c.write_schema(ctx, """
caveat tier_at_least(tier int, minimum int) { tier >= minimum }
definition user {}
definition team { relation member: user | team#member }
definition org { relation admin: user }
definition doc {
    relation org: org
    relation reader: user | user:* | team#member | user with tier_at_least
    relation banned: user
    permission admin = org->admin
    permission read = reader - banned
}
""")
rng = np.random.default_rng(20260804)
now_s = time.time()
txn = rel.Txn()
for t in range(4):
    for u in rng.choice(30, 3, replace=False):
        txn.touch(rel.must_from_tuple(f"team:t{t}#member", f"user:u{u}"))
    if t + 1 < 4:
        txn.touch(rel.must_from_tuple(f"team:t{t}#member",
                                      f"team:t{t + 1}#member"))
for d in range(20):
    txn.touch(rel.must_from_triple(f"doc:d{d}", "org", f"org:o{d % 3}"))
    txn.touch(rel.must_from_triple(
        f"doc:d{d}", "reader", f"user:u{rng.integers(30)}"))
    if d % 5 == 0:
        txn.touch(rel.must_from_triple(f"doc:d{d}", "reader", "user:*"))
    if d % 4 == 0:
        txn.touch(rel.must_from_tuple(
            f"doc:d{d}#reader", f"team:t{rng.integers(4)}#member"))
    if d % 6 == 0:
        txn.touch(rel.must_from_triple(
            f"doc:d{d}", "reader", f"user:cv{d}"
        ).with_caveat("tier_at_least", {"minimum": 5}))
    if d % 7 == 0:
        txn.touch(rel.must_from_triple(
            f"doc:d{d}", "reader", f"user:exp{d}"
        ).with_expiration(dt.datetime.fromtimestamp(
            now_s - 60, tz=dt.timezone.utc)))
    if d % 3 == 0:
        txn.touch(rel.must_from_triple(
            f"doc:d{d}", "banned", f"user:u{rng.integers(30)}"))
for o in range(3):
    txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
c.write(ctx, txn)
oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))
cs = consistency.full()

# -- phase 1: explain-vs-oracle parity + witness containment ------------
queries = []
for i in range(N):
    perm = ["read", "admin", "reader"][i % 3]
    queries.append(rel.must_from_triple(
        f"doc:d{rng.integers(20)}", perm, f"user:u{rng.integers(30)}"))
want = oracle.check(ctx, cs, *queries)
snap = c.store.snapshot_for(cs)
engine = c._engine_for(snap)
codes = engine.witness_codes(c._dsnap_for(engine, snap), queries)
assert codes is not None, "witness extraction unavailable on this world"
branches = {}
t0 = time.perf_counter()
for i, q in enumerate(queries):
    tree = c.explain(ctx, cs, q)
    assert (tree["result"] == "allowed") == want[i], (q, tree["result"])
    w = int(codes[i])
    assert ex.witness_consistent(tree, w), (q, w)
    if w:
        branches[ex.witness_name(w)] = branches.get(ex.witness_name(w), 0) + 1
explain_ms = (time.perf_counter() - t0) / N * 1000.0
assert {"direct", "fold"} & set(branches), branches
print(f"# explain parity: {N} checks == oracle (bool collapse), witness "
      f"subset held; branches={branches}; mean explain {explain_ms:.2f} ms")

# -- phase 2: denial tree carries the exhausted frontier ----------------
denied = next(i for i, w in enumerate(want) if not w)
tree = c.explain(ctx, cs, queries[denied])
assert tree["result"] != "allowed"


def _nodes(n, out):
    out.append(n)
    for ch in (n or {}).get("children", ()):
        _nodes(ch, out)
    return out


frontier = _nodes(tree["tree"], [])
assert all("verdict" in n for n in frontier), "torn denial tree"
print(f"# denial tree: {len(frontier)} explored nodes, root verdict "
      f"{tree['result']}")

# -- phase 3: cache-hit re-derivation at the pinned revision ------------
ml = consistency.min_latency()
with c.with_serving(cs=ml, cache=True) as h:
    hit = next(q for i, q in enumerate(queries) if want[i])
    h.check(ctx, hit)
    h.check(ctx, hit)  # cache-served now
    t = c.explain(ctx, ml, hit)
    assert t.get("cached") is True and t["result"] == "allowed"
    assert t["revision"] == c.store.snapshot_for(ml).revision
print("# cache-hit re-derivation: cached=true, tree re-derived at the "
      f"pinned revision {t['revision']}")

# -- phase 4: decision log ring + rotation + counters + endpoints -------
log = dec.get()
assert log is not None and len(log) > 0
rotated = [p for p in os.listdir(tmp) if p.startswith("decisions.jsonl.")]
assert rotated, "decision-log sink never rotated"
dropped = int(m.counter("decisions.dropped"))
assert m.counter("check.verdicts.allowed.full") > 0
assert m.counter("check.verdicts.denied.full") > 0
base = c.telemetry.url
lines = urllib.request.urlopen(base + "/decisions?n=8").read().decode()
head = json.loads(lines.splitlines()[0])
assert head["enabled"] and head["verdicts"]["check.verdicts.denied"] > 0
slo = json.loads(urllib.request.urlopen(base + "/slo").read())
assert "denial_rate" in [s["name"] for s in slo["slos"]]
mtx = urllib.request.urlopen(base + "/metrics").read().decode()
assert "gochugaru_check_verdicts_denied_full_total" in mtx
rec = trace.recorder()
iid = rec.trigger("explain_smoke.proof")
rec.flush()
bhead = json.loads(rec.bundle(iid).splitlines()[0])
assert bhead.get("decisions"), "incident bundle carries no decisions"
print(f"# decision log: ring={len(log)} rotated={len(rotated)} "
      f"dropped={dropped}; /decisions + denial_rate SLO + "
      f"decision-carrying bundle live")

# -- phase 5: armed decision-log cost (interleaved-rep A/B) -------------
# The DISARMED cost is bounded by tests/test_trace_overhead.py on the
# pinned path; this prices the ARMED log (100% sample + live sink) at
# the client layer, paired per rep so scheduler noise cancels.
ab = [([], [])]
probe = [rel.must_from_triple(f"doc:d{i % 20}", "read",
                              f"user:u{i % 30}") for i in range(8)]
reps = 400
for i in range(reps):
    on = i & 1
    # set_recording, NOT install: install(None) closes the sink, and the
    # next armed rep's file reopen would land inside the timed window
    dec.set_recording(log if on else None)
    t0 = time.perf_counter()
    c.check(ctx, cs, *probe)
    ab[0][on].append((time.perf_counter() - t0) * 1000.0)
dec.set_recording(log)
off, on = (np.asarray(x) for x in ab[0])
p99_off = float(np.percentile(off, 99))
delta_p50 = float(np.percentile(on, 50) - np.percentile(off, 50))
explain_overhead_frac = round(max(delta_p50, 0.0) / max(p99_off, 1e-9), 4)
print(f"# provenance overhead (interleaved A/B, {reps} reps): "
      f"delta_p50={delta_p50:.4f} ms, p99_off={p99_off:.3f} ms, "
      f"frac={explain_overhead_frac}")

print(json.dumps({
    "metric": "explain_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "checks": N, "explain_ms": round(explain_ms, 3),
    "explain_overhead_frac": explain_overhead_frac,
    "decisions_dropped": dropped,
    "decision_ring": len(log), "rotated_files": len(rotated),
    "witness_branches": branches,
    "note": "explain==oracle parity + witness subset + denial frontier + "
            "cache re-derivation + decision-log rotation + denial-rate SLO",
}))
print(f"EXPLAIN-SMOKE-OK checks={N} explain_ms={explain_ms:.2f} "
      f"overhead_frac={explain_overhead_frac} dropped={dropped} "
      f"rotated={len(rotated)}")
EOF
rc=$?
exit "$rc"

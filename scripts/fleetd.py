#!/usr/bin/env python
"""Fleet daemon: run one fleet process — the router (authority store +
consistent-hash placement + zookie minting) or a replica (bootstraps
from the router, tails the replication stream, serves checks).

A minimal local fleet, three terminals:

  # 1. the router (authority); prints ROUTER-READY with its port
  python scripts/fleetd.py router --port 7411 --demo-world

  # 2..n. replicas; each bootstraps, catches up, and serves
  python scripts/fleetd.py replica --upstream 127.0.0.1:7411 --id r0
  python scripts/fleetd.py replica --upstream 127.0.0.1:7411 --id r1

Replicas self-announce to the router?  No — membership is the
operator's (or supervisor's) call: POST a ``health`` probe yourself or
use ``--join`` below, which asks the router to admit the replica once
it reports ready.  ``scripts/fleet_smoke.sh`` and
``benchmarks/bench10_fleet.py`` drive exactly this wiring.

Router options: ``--demo-world`` writes a tiny schema + relationships
so zookie round trips work out of the box; ``--incident-dir`` installs
a flight recorder so ``fleet.failover`` incidents land as bundles.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_router(args) -> int:
    from gochugaru_tpu.fleet import FleetRouter
    from gochugaru_tpu.utils import trace
    from gochugaru_tpu.utils.context import background

    if args.incident_dir:
        trace.install_recorder(
            trace.FlightRecorder(incident_dir=args.incident_dir)
        )
    router = FleetRouter(host=args.host, port=args.port)
    if args.demo_world:
        ctx = background()
        router.write_schema(ctx, """
        definition user {}
        definition doc {
            relation owner: user
            relation reader: user
            permission read = reader + owner
        }
        """)
        from gochugaru_tpu import rel

        txn = rel.Txn()
        for i in range(32):
            txn.touch(rel.must_from_triple(
                f"doc:d{i}", "owner", f"user:u{i % 8}"
            ))
        router.write(ctx, txn)
    print(f"ROUTER-READY host={router.host} port={router.port}"
          f" head={router.head_revision}", flush=True)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        router.close()
    return 0


def run_replica(args) -> int:
    # the replica module's own CLI does the work (REPLICA-READY line,
    # exit-on-death crash semantics); --join additionally sends the
    # router a ``join`` op so this replica enters the ring without an
    # operator calling add_replica by hand
    from gochugaru_tpu.fleet import replica as replica_mod

    argv = ["--upstream", args.upstream, "--host", args.host,
            "--port", str(args.port)]
    if args.id:
        argv += ["--id", args.id]
    if args.host_only:
        argv.append("--host-only")
    if args.latency_mode:
        argv.append("--latency-mode")
    if args.join:
        argv.append("--join")
    return replica_mod.main(argv)


def main() -> int:
    ap = argparse.ArgumentParser(description="gochugaru fleet daemon")
    sub = ap.add_subparsers(dest="role", required=True)

    rt = sub.add_parser("router", help="authority store + placement")
    rt.add_argument("--host", default="127.0.0.1")
    rt.add_argument("--port", type=int, default=0)
    rt.add_argument("--demo-world", action="store_true",
                    help="write a small schema+world so checks work"
                         " out of the box")
    rt.add_argument("--incident-dir",
                    default=os.environ.get("GOCHUGARU_INCIDENT_DIR") or None)

    rp = sub.add_parser("replica", help="bootstrapped serving replica")
    rp.add_argument("--upstream", required=True, help="router HOST:PORT")
    rp.add_argument("--host", default="127.0.0.1")
    rp.add_argument("--port", type=int, default=0)
    rp.add_argument("--id", default=None)
    rp.add_argument("--host-only", action="store_true")
    rp.add_argument("--latency-mode", action="store_true")
    rp.add_argument("--join", action="store_true",
                    help="probe the router once serving starts")

    args = ap.parse_args()
    if args.role == "router":
        return run_router(args)
    return run_replica(args)


if __name__ == "__main__":
    sys.exit(main())

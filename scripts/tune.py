#!/usr/bin/env python
"""Offline tuning pass, end to end, as an operator would run it.

Builds a small RBAC world, drives a short mixed load (many small
interactive submissions + a few bulk CheckMany + a duplicate-heavy
round) through a serving handle under the DEFAULT config, captures one
telemetry snapshot (gochugaru_tpu/tune/snapshot.py), and prints the
tuner's proposed EngineConfig/ServeConfig diff with per-knob measured
evidence and predicted deltas.  The pack-spec rule needs a
counterfactual a live snapshot cannot see, so the script also runs the
dual-prepare A/B (flat_packed on vs off over the same store snapshot)
and feeds both gathered-bytes models in as ``packed_candidates``.

Usage:
    JAX_PLATFORMS=cpu python scripts/tune.py            # human-readable
    JAX_PLATFORMS=cpu python scripts/tune.py --json     # diff as JSON
    JAX_PLATFORMS=cpu python scripts/tune.py --online 6 # + controller demo

``--online N`` additionally attaches the OnlineController to the live
handle and drives N control ticks under continued load, printing each
applied move and the final status — the bounded-step/cooldown/revert
behavior tests/test_tune.py pins down, on real traffic.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repos", type=int, default=400)
    ap.add_argument("--users", type=int, default=160)
    ap.add_argument("--seconds", type=float, default=3.0,
                    help="load window under the default config")
    ap.add_argument("--rate", type=float, default=120.0,
                    help="interactive submissions/s")
    ap.add_argument("--json", action="store_true",
                    help="print the diff as JSON instead of prose")
    ap.add_argument("--online", type=int, default=0, metavar="N",
                    help="after the offline pass, run N online-controller"
                         " ticks under continued load")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from gochugaru_tpu import consistency, rel
    from gochugaru_tpu.client import new_tpu_evaluator, with_latency_mode
    from gochugaru_tpu.engine.device import DeviceEngine
    from gochugaru_tpu.engine.plan import EngineConfig
    from gochugaru_tpu.serve import ServeConfig
    from gochugaru_tpu.tune import (
        OnlineController,
        TuneTarget,
        apply_diff,
        collect_snapshot,
        propose,
    )
    from gochugaru_tpu.utils import metrics, perf
    from gochugaru_tpu.utils.context import background

    m = metrics.default
    rng = np.random.default_rng(18)
    ctx = background()
    c = new_tpu_evaluator(with_latency_mode())
    c.write_schema(ctx, """
    definition user {}
    definition org { relation admin: user  relation member: user }
    definition repo {
        relation org: org
        relation reader: user
        permission admin = org->admin
        permission read = reader + admin + org->member
    }
    """)
    txn = rel.Txn()
    for i in range(args.repos):
        txn.touch(rel.must_from_triple(
            f"repo:r{i}", "reader", f"user:u{int(rng.integers(args.users))}"))
        txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 6}"))
    for o in range(6):
        txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
        for j in range(8):
            txn.touch(rel.must_from_triple(
                f"org:o{o}", "member", f"user:u{(o * 8 + j) % args.users}"))
    c.write(ctx, txn)
    cs = consistency.min_latency()
    store_snap = c.store.snapshot_for(consistency.full())
    inter = store_snap.interner
    slot = store_snap.compiled.slot_of_name

    POOL = 4096
    pool_res = np.array(
        [inter.node("repo", f"r{int(i)}")
         for i in rng.integers(0, args.repos, POOL)], np.int32)
    pool_subj = np.array(
        [inter.node("user", f"u{int((u - 1) % args.users)}")
         for u in rng.zipf(1.2, POOL)], np.int32)
    pool_perm = np.where(
        rng.random(POOL) < 0.9, slot["read"], slot["admin"]).astype(np.int32)

    ecfg = c._engine_config or EngineConfig()
    scfg = ServeConfig()
    h = c.with_serving(cs=cs, config=scfg, cache=True)

    def drive(seconds):
        """Mixed open-loop load: interactive 9-check submissions at
        --rate with an occasional 300-check bulk, plus a duplicate-
        heavy burst (the dedup rule's signal)."""
        futs = []
        t0 = time.perf_counter()
        k = 0
        while time.perf_counter() - t0 < seconds:
            s = int(rng.integers(0, POOL - 300))
            n = 300 if k % 25 == 24 else 9
            futs.append(h.submit_columns(
                ctx, pool_res[s:s + n], pool_perm[s:s + n],
                pool_subj[s:s + n], client_id=k % 4))
            if k % 10 == 0:  # duplicate burst: same slice, twice
                futs.append(h.submit_columns(
                    ctx, pool_res[s:s + 9], pool_perm[s:s + 9],
                    pool_subj[s:s + 9], client_id=(k + 1) % 4))
            k += 1
            time.sleep(1.0 / args.rate)
        for f in futs:
            f.result(timeout=60.0)

    print(f"# driving {args.seconds:.0f}s of mixed load under the"
          f" default config (hold {scfg.hold_max_s * 1000:g}ms,"
          f" tiers {ecfg.latency_tiers}) ...")
    # warm each tier pin sequentially so the load window measures
    # steady state, not first-dispatch compiles
    for t in ecfg.latency_tiers:
        n = min(int(t), POOL - 1)
        h.submit_columns(ctx, pool_res[:n], pool_perm[:n],
                         pool_subj[:n]).result(timeout=120.0)
    drive(args.seconds)

    # dual-prepare A/B over the same snapshot: the pack-spec
    # counterfactual (bytes gathered per check under each layout)
    cands = {}
    for label, fp in (("packed", True), ("unpacked", False)):
        eng = DeviceEngine(
            store_snap.compiled,
            EngineConfig.for_schema(store_snap.compiled, flat_packed=fp),
        )
        ds = eng.prepare(store_snap)
        try:
            cands[label] = float(perf.gathered_bytes_model(ds).total)
        except Exception:
            cands = {}
            break
        if label == "unpacked":
            dsnap_for_bytes = ds

    snap = collect_snapshot(
        m,
        engine_config=ecfg,
        serve_config=h.batcher.config,
        vcache=c._vcache,
        cost=c._admission.cost,
        dsnap=dsnap_for_bytes if cands else None,
        packed_candidates=cands or None,
    )
    target = TuneTarget(
        engine=ecfg, serve=h.batcher.config,
        cache_bytes=int(c._vcache.max_bytes) if c._vcache else None,
    )
    diff = propose(snap, target)

    if args.json:
        print(diff.to_json(indent=2))
    else:
        print("# tuner proposal (offline pass):")
        out = diff.render() if diff else "(no changes: measured config fits)"
        for line in out.splitlines():
            print("  " + line)
        tuned = apply_diff(target, diff)
        print(f"# tuned target: tiers={tuned.engine.latency_tiers}"
              f" hold={tuned.serve.hold_max_s}s dedup={tuned.serve.dedup}"
              f" cache_bytes={tuned.cache_bytes}"
              f" placement={tuned.placement}")

    if args.online > 0:
        print(f"# online controller: {args.online} ticks under live load")
        ctl = OnlineController(h.batcher, vcache=c._vcache, registry=m,
                               cooldown_steps=1)
        for tick in range(args.online):
            drive(max(0.5, args.seconds / 4))
            moved = ctl.step()
            st = ctl.status()
            print(f"#   tick {tick}: moves={moved}"
                  f" hold={st['hold_max_s']}s dedup={st['dedup']}"
                  f" frozen={st['frozen']}")
        ctl.revert()
        st = ctl.status()
        print(f"# reverted to preset: hold={st['hold_max_s']}s"
              f" (moves total {st['moves']},"
              f" tune.reverts={int(m.counter('tune.reverts'))})")

    h.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env bash
# Pallas fused-probe smoke: the hand-fused probe kernels
# (engine/pallas.py) end-to-end on a small world, CI-runnable in Pallas
# INTERPRET mode (JAX_PLATFORMS=cpu).  Asserts (1) bitwise parity
# pallas-vs-XLA through the throughput batch path (caveats, wildcards,
# usersets, expirations), the pinned latency path (incl. the zero-
# retrace contract on warm same-tier dispatches), and the packed-uint16
# + aligned-ladder layouts; (2) the perf ledger's one-pass bytes bar:
# pallas_bytes_model must show a per-table bytes-accessed reduction and
# prepare must publish vmem_resident_bytes > 0.  Interpret-mode honesty:
# rates printed here are correctness-only — the bytes win is a model,
# scored on silicon by tpu_watch.sh priority 4.0.  Prints
# PALLAS-SMOKE-OK on success and one JSON metric line for
# benchmarks/run_all.py (config 25).
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import dataclasses
import datetime as dt
import json
import random
import sys
import time

import numpy as np

from gochugaru_tpu.utils.platform import force_cpu_platform

force_cpu_platform(8)

sys.path.insert(0, ".")
from gochugaru_tpu import rel
from gochugaru_tpu.engine import pallas as P
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot
from gochugaru_tpu.utils import perf as _perf
from gochugaru_tpu.utils.metrics import default as _m

t0 = time.time()
NOW = 1_700_000_000_000_000

assert P.available(), "jaxlib must ship jax.experimental.pallas here"
assert P.interpret_mode(), "smoke runs the kernels through the interpreter"

SCHEMA = """
caveat on_tuesday(day string) { day == "tuesday" }
definition user {}
definition team {
    relation member: user | team#member | user:*
    permission everyone = member
}
definition doc {
    relation reader: user | user:* | team#member | team#everyone
    relation writer: user | team#member
    permission edit = writer
    permission view = reader + edit
}
"""

rng = random.Random(13)
rels = []
for t in range(1, 24):
    rels.append(rel.must_from_tuple(
        f"team:t{t - 1 if t % 5 else rng.randrange(t)}#member",
        f"team:t{t}#member"))
for t in range(24):
    rels.append(rel.must_from_tuple(
        f"team:t{t}#member", f"user:u{rng.randrange(12)}"))
rels.append(rel.must_from_tuple("team:t3#member", "user:*"))
for _ in range(220):
    d, u = f"doc:d{rng.randrange(24)}", f"user:u{rng.randrange(12)}"
    k = rng.random()
    if k < 0.08:
        r = rel.must_from_tuple(f"{d}#reader",
                                f"team:t{rng.randrange(24)}#member")
    elif k < 0.11:
        r = rel.must_from_tuple(f"{d}#reader", "user:*")
    else:
        r = rel.must_from_triple(
            d, "reader" if rng.random() < 0.8 else "writer", u)
    if rng.random() < 0.12:
        r = r.with_caveat("on_tuesday",
                          {"day": "tuesday"} if rng.random() < 0.5 else {})
    if rng.random() < 0.07:
        r = dataclasses.replace(r, expiration=dt.datetime.fromtimestamp(
            (NOW + rng.randrange(-10**9, 10**12)) / 1e6, tz=dt.timezone.utc))
    rels.append(r)

cs = compile_schema(parse_schema(SCHEMA))
snap = build_snapshot(1, cs, Interner(), rels, epoch_us=NOW)
checks = [
    rel.must_from_triple(f"doc:d{rng.randrange(24)}",
                         rng.choice(["view", "edit"]),
                         f"user:u{rng.randrange(12)}")
    for _ in range(48)
]
checks = [q.with_caveat("", {"day": rng.choice(["tuesday", "friday"])})
          if rng.random() < 0.4 else q for q in checks]

# (1) throughput batch path + packed/aligned layouts: bitwise parity
n_verdicts = 0
for cfg in ({}, {"flat_packed": True},
            {"flat_packed": True, "flat_aligned": True}):
    ex = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=False, **cfg))
    ep = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True, **cfg))
    rx = ex.check_batch(ex.prepare(snap), checks, now_us=NOW)
    rp = ep.check_batch(ep.prepare(snap), checks, now_us=NOW)
    for a, b in zip(rx, rp):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"parity broke under {cfg or 'default layout'}"
    n_verdicts += len(checks)
print(f"batch parity: ok ({n_verdicts} verdicts bitwise, 3 layouts)",
      file=sys.stderr)

# (2) pinned latency path: parity + ZERO retraces on warm dispatches
ep = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True))
ex = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=False))
dp, dx = ep.prepare(snap), ex.prepare(snap)
lp = ep.latency_path(dp)
interner = snap.interner
slot = cs.slot_of_name
B = 16
q_res = np.array([interner.node("doc", f"d{i % 24}") for i in range(B)],
                 np.int32)
q_perm = np.full(B, slot["view"], np.int32)
q_subj = np.array([interner.node("user", f"u{i % 12}") for i in range(B)],
                  np.int32)
assert lp.dispatch_columns(q_res, q_perm, q_subj, now_us=NOW) is not None
warm = lp.compile_count
for i in range(1, 5):
    got = lp.dispatch_columns(np.roll(q_res, i), q_perm,
                              np.roll(q_subj, i), now_us=NOW)
    ref = ex.check_columns(dx, np.roll(q_res, i), q_perm,
                           np.roll(q_subj, i), now_us=NOW)
    for a, b in zip(got, ref):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "latency-path parity broke"
assert lp.compile_count == warm, "warm pallas dispatch retraced"
print(f"latency parity: ok (4 warm dispatches, {warm} compiles, 0 retraces)",
      file=sys.stderr)

# (3) the ledger bytes bar: the one-pass model must show a per-table
# reduction, and prepare must have pinned the VMEM-resident plan
epk = DeviceEngine(cs, EngineConfig.for_schema(cs, pallas=True,
                                               flat_packed=True))
dpk = epk.prepare(snap)
model = _perf.pallas_bytes_model(dpk)
assert model, "byte model empty"
saved = sum(row["saved"] for row in model.values())
xla = sum(row["xla"] for row in model.values())
assert saved > 0, "fused kernels must model a bytes reduction"
vmem = _m.gauge("perf.vmem_resident_bytes")
assert vmem > 0, "prepare must publish the VMEM residency plan"
frac = saved / max(xla, 1)
print(f"bytes bar: ok ({saved} B/check modeled saved, "
      f"{100 * frac:.0f}% of the XLA pass; vmem_resident={int(vmem)} B)",
      file=sys.stderr)

print(json.dumps({
    "metric": "pallas_smoke_bytes_saved_frac", "value": round(frac, 4),
    "unit": "fraction of XLA bytes/check", "vs_baseline": 1.0,
    "edges": int(snap.num_edges), "batch": len(checks),
    "vmem_resident_bytes": int(vmem),
    "wall_s": round(time.time() - t0, 1),
}))
EOF

echo "PALLAS-SMOKE-OK"

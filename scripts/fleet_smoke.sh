#!/usr/bin/env bash
# Fleet smoke: 3 replica PROCESSES self-joining a router via the wire
# protocol, zookie read-your-writes through the router, host-oracle
# parity at full consistency, and a seeded SIGKILL of one replica with
# zero lost/duplicated/stale answers (ring eviction + fleet.failover
# incident + kill detection asserted).  Prints FLEET-SMOKE-OK on
# success — the CI-runnable proof the replicated deployment serves
# correctly and survives a replica crash, mirroring
# scripts/serve_smoke.sh / chaos_smoke.sh.
#
# Usage:
#   scripts/fleet_smoke.sh                  # 3 replicas, 30 kill-window checks
#   FLEET_SMOKE_REPLICAS=5 scripts/fleet_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

: "${FLEET_SMOKE_REPLICAS:=3}"
: "${FLEET_SMOKE_CHECKS:=30}"
: "${FLEET_SMOKE_TIMEOUT_S:=420}"

export FLEET_SMOKE_REPLICAS FLEET_SMOKE_CHECKS

timeout -k 10 "${FLEET_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

from dataclasses import replace

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_host_only_evaluation, with_store,
)
from gochugaru_tpu.fleet import FleetConfig, FleetRouter
from gochugaru_tpu.utils import metrics, trace
from gochugaru_tpu.utils.context import background

N = int(os.environ.get("FLEET_SMOKE_REPLICAS", "3"))
CHECKS = int(os.environ.get("FLEET_SMOKE_CHECKS", "30"))
m = metrics.default
rng = random.Random(20260806)
incident_dir = tempfile.mkdtemp(prefix="fleet-smoke-")
rec = trace.install_recorder(trace.FlightRecorder(
    incident_dir=incident_dir, grace_s=0.0, cooldown_s=0.0,
))

cfg = replace(FleetConfig(), probe_interval_s=0.1, heartbeat_s=0.1)
router = FleetRouter(config=cfg)
ctx = background()
router.write_schema(ctx, """
definition user {}
definition doc {
    relation owner: user
    relation reader: user
    permission read = reader + owner
}
""")
txn = rel.Txn()
for i in range(60):
    txn.touch(rel.must_from_triple(f"doc:d{i}", "owner", f"user:u{i % 10}"))
    txn.touch(rel.must_from_triple(f"doc:d{i}", "reader", f"user:v{i % 7}"))
router.write(ctx, txn)
oracle = new_tpu_evaluator(with_store(router.store),
                           with_host_only_evaluation())

# -- phase 1: replica processes self-join via the wire 'join' op --------
procs = []
for i in range(N):
    procs.append(subprocess.Popen(
        [sys.executable, "-m", "gochugaru_tpu.fleet.replica",
         "--upstream", f"127.0.0.1:{router.port}",
         "--id", f"s{i}", "--host-only", "--join"],
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(incident_dir, f"s{i}.stderr"), "w"),
    ))
deadline = time.monotonic() + 180.0
while time.monotonic() < deadline:
    if len(router.status()["ring"]) == N:
        break
    time.sleep(0.1)
ring = router.status()["ring"]
assert len(ring) == N, f"only {ring} joined"
print(f"# {N} replica processes bootstrapped, caught up, and self-joined:"
      f" ring={ring}")

# -- phase 2: write -> zookie -> read-your-writes -----------------------
for k in range(5):
    txn = rel.Txn()
    txn.touch(rel.must_from_triple(f"doc:fresh{k}", "reader", "user:me"))
    zk = router.write(ctx, txn)
    got = router.check(
        background().with_timeout(30.0), consistency.min_latency(),
        rel.must_from_triple(f"doc:fresh{k}", "read", "user:me"),
        zookie=zk,
    )
    assert got == [True], (k, got)
print("# zookie read-your-writes: 5/5 writes visible through the router"
      " immediately (min_latency + zookie)")

queries = [
    rel.must_from_triple(f"doc:d{rng.randrange(60)}", "read",
                         rng.choice([f"user:u{rng.randrange(10)}",
                                     f"user:v{rng.randrange(7)}",
                                     "user:nobody"]))
    for _ in range(40)
]
want = oracle.check(ctx, consistency.full(), *queries)
got = router.check(background().with_timeout(30.0),
                   consistency.full(), *queries)
assert got == want, "parity mismatch before kill"

# -- phase 3: seeded SIGKILL, zero lost/dup/stale -----------------------
kills0 = m.counter("fleet.kill_detections")
victim = procs[1]
victim.send_signal(signal.SIGKILL)
answered = 0
for k in range(CHECKS):
    got = router.check(background().with_timeout(30.0),
                       consistency.full(), *queries)
    assert got == want, f"stale/wrong answer at kill-window check {k}"
    answered += 1
assert answered == CHECKS  # zero lost; dup impossible (one reply/request)
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if ("s1" not in router.status()["ring"]
            and m.counter("fleet.kill_detections") > kills0):
        break
    time.sleep(0.05)
assert "s1" not in router.status()["ring"], "victim never evicted"
assert m.counter("fleet.kill_detections") > kills0, "kill never detected"
rec.flush()
assert any(e["trigger"] == "fleet.failover" for e in rec.incident_index()), \
    "no fleet.failover incident bundle"
print(f"# kill survival: SIGKILL mid-traffic, {answered}/{CHECKS} answers"
      f" correct (zero lost/dup/stale), eviction + fleet.failover incident")

router.close()
for p in procs:
    if p.poll() is None:
        p.kill()
    p.wait(timeout=10.0)
print(json.dumps({
    "metric": "fleet_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "replicas": N, "kill_window_checks": CHECKS,
    "reroutes": int(m.counter("fleet.reroutes")),
    "evictions": int(m.counter("fleet.evictions")),
    "note": "self-joined replica processes, zookie RYW, SIGKILL survival",
}))
print(f"FLEET-SMOKE-OK replicas={N} checks={CHECKS} "
      f"evictions={int(m.counter('fleet.evictions'))}")
EOF
rc=$?
exit "$rc"

#!/usr/bin/env bash
# Telemetry endpoint smoke: start scripts/telemetryd.py, curl /healthz +
# /metrics + /traces, and grep for a counter the demo checks must have
# bumped.  Exits non-zero on any miss — the CI-runnable proof that the
# export surface serves real numbers, mirroring scripts/chaos_smoke.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

LOG=$(mktemp /tmp/telemetryd.XXXXXX.log)
python scripts/telemetryd.py --port 0 --checks 32 >"$LOG" 2>/dev/null &
PID=$!
trap 'kill $PID 2>/dev/null || true; rm -f "$LOG"' EXIT

URL=""
for _ in $(seq 1 120); do
    URL=$(sed -n 's/^READY url=//p' "$LOG" | head -n1)
    [ -n "$URL" ] && break
    kill -0 $PID 2>/dev/null || { echo "telemetryd died:"; cat "$LOG"; exit 1; }
    sleep 1
done
[ -n "$URL" ] || { echo "telemetryd never became ready"; exit 1; }
echo "endpoint: $URL"

# the demo world runs checks before READY only in --idle=false mode, but
# the serving loop keeps dispatching; poll briefly for the counter
curl -fsS "$URL/healthz" | grep -q '"status": *"ok"' \
    || { echo "FAIL: /healthz not ok"; exit 1; }
echo "healthz: ok"

# poll until BOTH the counter and the dispatch timer quantile are live —
# the counter bumps at request time, the timer ring only after the first
# dispatch completes, so a one-shot snapshot can catch the gap between them
ok=""
for _ in $(seq 1 30); do
    METRICS=$(curl -fsS "$URL/metrics")
    if echo "$METRICS" | grep -q '^gochugaru_checks_requested_total [1-9]' \
       && echo "$METRICS" | grep -q '^gochugaru_checks_dispatch_seconds{quantile="0.99"}'; then
        ok=1; break
    fi
    sleep 1
done
[ -n "$ok" ] || {
    echo "FAIL: checks_requested counter and/or dispatch quantiles missing"
    echo "$METRICS" | grep -E '^gochugaru_checks' || true
    exit 1
}
echo "metrics: checks_requested present"
echo "metrics: dispatch p99 quantile present"

curl -fsS "$URL/traces" | head -n1 | grep -q '"trace_id"' \
    || { echo "FAIL: /traces has no trace"; exit 1; }
echo "traces: JSONL present"

# SLO burn-rate engine: enabled, reporting every declared objective
curl -fsS "$URL/slo" | grep -q '"enabled": true' \
    || { echo "FAIL: /slo not enabled"; exit 1; }
curl -fsS "$URL/slo" | grep -q '"burn"' \
    || { echo "FAIL: /slo reports no burn windows"; exit 1; }
echo "slo: burn report live"

# flight recorder: incident index serves (empty is fine on a quiet run)
curl -fsS "$URL/debug/incidents" | grep -q '"incidents"' \
    || { echo "FAIL: /debug/incidents missing"; exit 1; }
echo "incidents: index live"

# OpenMetrics negotiation: exemplar-capable dialect ends with # EOF
curl -fsS -H 'Accept: application/openmetrics-text' "$URL/metrics" \
    | tail -n1 | grep -q '# EOF' \
    || { echo "FAIL: OpenMetrics dialect missing # EOF"; exit 1; }
echo "metrics: OpenMetrics dialect negotiated"
echo "TELEMETRY-SMOKE-OK"

#!/usr/bin/env python
"""Bench-trajectory regression guard: compare the newest BENCH_r*.json
round against the previous one per metric name.

The BENCH_r<NN>.json files are the committed per-round driver captures
(config-2 bench.py child): ``tail`` holds the child's raw stdout —
including every ``{"metric": ...}`` JSON line — and ``parsed`` the last
metric line.  Nothing guarded that trajectory against silent perf
regressions: a round could land 30% slower and nobody would notice until
a human re-read the table.  This script makes the comparison mechanical:

- extract every metric line from each round (plus the headline's
  ``true_rate``/``p99_ms`` companions as ``<metric>.true_rate`` /
  ``<metric>.p99_ms`` — the honest numbers ride as extra fields);
- compare the newest round with metrics against the previous such round,
  direction-aware (units/suffixes decide whether bigger is better);
- print a one-line-per-metric trajectory table;
- exit nonzero when any metric regressed beyond ``--tolerance``
  (default 10%) — ``run_all.py --compare`` wires this as the suite's
  final gate.

New metrics (no previous value) and retired metrics are reported but
never fail the run; platform changes between rounds are noted (a cpu
round vs a tpu round is apples vs oranges — flagged, not failed).
A higher-better row whose own ``roofline_frac`` is within tolerance of
1.0 is flagged ``host-bound`` instead of failed: the kernel is at the
measured memory-bandwidth ceiling of THIS host, so no software change
can close the gap — the delta is the box (rounds run on whatever
container the driver got; the triad ceiling is the host fingerprint).

Usage:
  python scripts/bench_compare.py [--dir /root/repo] [--tolerance 0.10]
                                  [--old r04] [--new r05]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: units where a SMALLER value is the better one
_LOWER_BETTER_UNITS = {"ms", "s", "seconds", "mb", "mib", "bytes", "gb"}
#: metric-name suffixes that mark lower-better numbers regardless of unit
#: (``pad_fraction``: the perf ledger's wasted-lanes share)
#: (``explain_overhead_frac``: the armed decision-log median shift on
#: the client check path as a fraction of its p99, from the smoke's
#: interleaved-rep A/B — growing means provenance is creeping into the
#: serving budget; ``decisions_dropped``: decision-log entries lost to
#: sink failures — any growth is an audit-trail hole;
#: ``dispatches_per_lookup``: device program launches per LookupResources
#: drain from bench8 — the fused SpMM path's whole point is holding this
#: at 1.0, so any growth is the K-hop fusion regressing to per-hop loops;
#: ``pad_waste_frac``: bench11's padded-lane share under the tuned config
#: — the tuner's tier ladder exists to shrink it, so growth means the
#: ladder rules stopped fitting the workload;
#: ``probe_depth_after_compaction``: bench12's residual delta-chain
#: overlay rows with the background compactor on — growth means the
#: compactor stopped keeping probe depth bounded and writers are headed
#: back toward the synchronous O(E) merge;
#: ``bytes_accessed_per_check``: the perf ledger's modeled HBM traffic
#: per check — the pallas fused probe exists to shrink it, so growth
#: means a table fell out of the one-pass plan.  NOTE it must be listed
#: here by full name: ``vmem_resident_bytes`` below must NOT inherit
#: the generic ``_bytes`` lower-better reading)
_LOWER_BETTER_SUFFIXES = (
    "_ms", "_s", "_latency", "_bytes", "_rss_mb", "pad_fraction",
    "explain_overhead_frac", "decisions_dropped", "dispatches_per_lookup",
    "pad_waste_frac", "probe_depth_after_compaction",
    "bytes_accessed_per_check",
)
#: suffixes that are HIGHER-better regardless of unit — checked FIRST,
#: so the perf columns can't be misread by a unit heuristic
#: (``achieved_gbps`` must not fall into the "gb" lower-better unit
#: bucket; ``roofline_frac`` closer to the ceiling is the win;
#: ``hit_rate``/``dedup_frac`` are the verdict-cache columns — a round
#: that serves fewer checks from cache/dedup at the same workload has
#: regressed, and ``_frac``'s trailing "_s" must not read as seconds)
#: (``mixed_users_rate`` is candidates/sec over bench8's 48 small-reach
#: users — the dispatch-floor workload the fused SpMM path exists for;
#: its trailing "_rate" must never read as anything but higher-better)
#: (``fleet_goodput_scaling`` is the N-replica/1-replica goodput ratio
#: from bench10 — more replicas helping more is the win, and its value
#: is an "x" multiplier, not a latency; ``failover_p99_ms`` stays
#: lower-better via the ``_ms`` suffix and is listed in
#: ``_PROMOTED_FIELDS`` so rows carrying it as a column also guard it)
#: (``tuned_vs_best_preset_goodput`` is bench11's geomean goodput ratio
#: of the tuned config over the best preset per profile — an "x"
#: multiplier like fleet scaling; below 1.0 the tuner stopped paying)
#: (``writes_per_s`` covers bench12's ``writes_per_s`` and
#: ``committer_writes_per_s`` — write throughput must be read
#: higher-better even though the raw "_s" suffix would otherwise flag
#: it as a latency; ``group_size_p50`` is bench12's achieved
#: writes-per-group median — shrinking groups mean the committer
#: stopped coalescing and every revision pays its machinery alone)
#: (``vmem_resident_bytes`` is the pallas residency plan — MORE of the
#: hot offset/anchor/ladder state pinned in VMEM is the win, and its
#: raw "_bytes" suffix must not read as lower-better;
#: ``bytes_saved_frac`` is the smoke's modeled one-pass saving as a
#: fraction of the XLA pass — shrinking means fused coverage regressed)
_HIGHER_BETTER_SUFFIXES = (
    "achieved_gbps", "roofline_frac", "hit_rate", "dedup_frac",
    "cache_speedup", "mixed_users_rate", "fleet_goodput_scaling",
    "tuned_vs_best_preset_goodput", "writes_per_s", "group_size_p50",
    "vmem_resident_bytes", "bytes_saved_frac",
)
#: extra fields of a metric line promoted to their own comparison rows
#: (the perf-attribution columns ride headline rows as extra fields —
#: promoting them guards the roofline trajectory from round one)
#: (``dedup_frac`` is direction-registered above but NOT promoted: its
#: absolute value is workload-noise-sized on the uniform-window bench,
#: and a 0.0003→0.0001 wiggle must not fail a round)
_PROMOTED_FIELDS = (
    "true_rate", "p99_ms", "achieved_gbps", "roofline_frac", "pad_fraction",
    "cache_hit_rate", "explain_overhead_frac", "decisions_dropped",
    "mixed_users_rate", "dispatches_per_lookup", "failover_p99_ms",
    "bytes_accessed_per_check", "vmem_resident_bytes",
)
#: boolean/one-shot rows that carry no trajectory signal
_SKIP_UNITS = {"ok", "capture", "keys"}


def lower_is_better(name: str, unit: str) -> bool:
    if any(name.endswith(s) for s in _HIGHER_BETTER_SUFFIXES):
        return False
    u = unit.strip().lower()
    if u in _LOWER_BETTER_UNITS:
        return True
    if any(name.endswith(s) for s in _LOWER_BETTER_SUFFIXES):
        return True
    return False


def metrics_of(path: str) -> dict:
    """metric name → {value, unit, platform} from one BENCH_r file
    (every JSON metric line in ``tail``, newest wins, plus ``parsed``)."""
    with open(path) as f:
        doc = json.load(f)
    out: dict = {}

    def take(parsed) -> None:
        if not isinstance(parsed, dict) or "metric" not in parsed:
            return
        name = parsed["metric"]
        unit = str(parsed.get("unit", ""))
        if unit in _SKIP_UNITS:
            return
        try:
            value = float(parsed.get("value"))
        except (TypeError, ValueError):
            return
        plat = parsed.get("platform", "")
        rf = parsed.get("roofline_frac")
        rf = float(rf) if isinstance(rf, (int, float)) else None
        out[name] = {
            "value": value, "unit": unit, "platform": plat,
            "roofline_frac": rf,
        }
        for fld in _PROMOTED_FIELDS:
            v = parsed.get(fld)
            if isinstance(v, (int, float)):
                out[f"{name}.{fld}"] = {
                    "value": float(v),
                    "unit": "ms" if fld.endswith("ms") else unit,
                    "platform": plat,
                    #: promoted companions share the parent row's kernel
                    "roofline_frac": rf,
                }

    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                take(json.loads(line))
            except json.JSONDecodeError:
                continue
    take(doc.get("parsed"))
    return out


def round_key(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def compare(
    old: dict, new: dict, old_name: str, new_name: str, tolerance: float
):
    """Returns (table rows, regression count).  A row is one formatted
    line; regressions are direction-aware changes beyond tolerance."""
    rows = []
    regressions = 0
    width = max([len(n) for n in set(old) | set(new)] + [6])
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o is None:
            rows.append(f"{name:<{width}}  {'—':>12} -> {n['value']:>12,.1f}"
                        f"  {'new':>8}  {n['unit']}")
            continue
        if n is None:
            rows.append(f"{name:<{width}}  {o['value']:>12,.1f} -> {'—':>12}"
                        f"  {'gone':>8}")
            continue
        ov, nv = o["value"], n["value"]
        if ov == 0:
            delta = 0.0 if nv == 0 else float("inf")
        else:
            delta = (nv - ov) / abs(ov)
        lower = lower_is_better(name, n["unit"] or o["unit"])
        worse = -delta if lower else delta
        rf = n.get("roofline_frac")
        if o.get("platform") and n.get("platform") and (
            o["platform"] != n["platform"]
        ):
            verdict = f"platform {o['platform']}->{n['platform']}"
        elif (
            worse < -tolerance and not lower
            and rf is not None and rf >= 1.0 - tolerance
        ):
            # the new round measures at the memory-bandwidth ceiling of
            # its own host — a throughput drop from there is the box,
            # not the code (lower-better rows get no such excuse: a
            # latency row can always regress by software)
            verdict = f"host-bound ({rf:.2f} of ceiling)"
        elif worse < -tolerance:
            verdict = "REGRESSED"
            regressions += 1
        elif worse > tolerance:
            verdict = "improved"
        else:
            verdict = "ok"
        rows.append(
            f"{name:<{width}}  {ov:>12,.1f} -> {nv:>12,.1f}"
            f"  {delta:>+7.1%}  {verdict}"
        )
    header = (
        f"{'metric':<{width}}  {old_name:>12} -> {new_name:>12}"
        f"  {'delta':>8}  verdict"
    )
    return [header] + rows, regressions


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    ap.add_argument("--glob", default="BENCH_r*.json")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="relative worsening tolerated before failing")
    ap.add_argument("--old", default=None,
                    help="explicit old round (e.g. r04); default: previous"
                         " round with metrics")
    ap.add_argument("--new", default=None,
                    help="explicit new round (e.g. r05); default: newest"
                         " round with metrics")
    args = ap.parse_args()

    paths = sorted(glob.glob(os.path.join(args.dir, args.glob)),
                   key=round_key)
    if len(paths) < 2:
        print(f"bench_compare: fewer than two rounds match "
              f"{args.glob} under {args.dir} — nothing to compare")
        return 0

    def named(tag):
        for p in paths:
            if os.path.basename(p) == f"BENCH_{tag}.json" or (
                f"_{tag}." in os.path.basename(p)
            ):
                return p
        print(f"bench_compare: no round named {tag}", file=sys.stderr)
        return None

    if args.new is not None:
        new_path = named(args.new)
        if new_path is None:
            return 2
    else:
        new_path = None
    if args.old is not None:
        old_path = named(args.old)
        if old_path is None:
            return 2
    else:
        old_path = None

    # walk newest→oldest picking the two most recent rounds that carry
    # metrics at all (a probe-failed round records rc/tail but no JSON
    # metric lines — skipping it keeps the comparison meaningful)
    usable = [(p, metrics_of(p)) for p in paths]
    with_metrics = [(p, m) for p, m in usable if m]
    if new_path is None:
        if not with_metrics:
            print("bench_compare: no round carries metrics")
            return 0
        new_path, new_metrics = with_metrics[-1]
    else:
        new_metrics = metrics_of(new_path)
    if old_path is None:
        older = [(p, m) for p, m in with_metrics
                 if round_key(p) < round_key(new_path)]
        if not older:
            print(f"bench_compare: no earlier round with metrics before "
                  f"{os.path.basename(new_path)}")
            return 0
        old_path, old_metrics = older[-1]
    else:
        old_metrics = metrics_of(old_path)

    short = lambda p: os.path.basename(p).replace("BENCH_", "").replace(
        ".json", ""
    )
    rows, regressions = compare(
        old_metrics, new_metrics, short(old_path), short(new_path),
        args.tolerance,
    )
    for r in rows:
        print(r)
    if regressions:
        print(f"\nbench_compare: {regressions} metric(s) regressed beyond "
              f"{args.tolerance:.0%} ({short(old_path)} -> "
              f"{short(new_path)})")
        return 1
    print(f"\nbench_compare: trajectory ok "
          f"({short(old_path)} -> {short(new_path)}, "
          f"tolerance {args.tolerance:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

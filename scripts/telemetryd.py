#!/usr/bin/env python
"""Standalone telemetry endpoint: build a small demo world, run traced
checks, and serve /metrics + /traces + /slo + /debug/incidents +
/healthz until killed.

The in-process route is ``client.with_telemetry(port=...)`` (client.py);
this daemon exists so operators and the smoke scripts
(scripts/telemetry_smoke.sh, scripts/slo_smoke.sh) can curl the
endpoints without writing a driver, and as living documentation of the
wiring.

Usage:
  python scripts/telemetryd.py [--port 0] [--sample-rate 1.0]
                               [--checks 64] [--idle]
                               [--incident-dir DIR] [--no-slo]

Prints ``READY url=http://host:port`` on stdout once serving.  With
``--idle`` no demo world is built (bare registry — fastest start).
``--incident-dir`` (default: $GOCHUGARU_INCIDENT_DIR) lands flight-
recorder incident bundles there; the recorder itself is always
installed, so /debug/incidents serves in-memory bundles either way.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--sample-rate", type=float, default=1.0)
    ap.add_argument("--checks", type=int, default=64,
                    help="demo checks to run before (and while) serving")
    ap.add_argument("--idle", action="store_true",
                    help="serve the bare registry; no demo world, no JAX")
    ap.add_argument("--incident-dir",
                    default=os.environ.get("GOCHUGARU_INCIDENT_DIR") or None,
                    help="dump flight-recorder incident bundles here")
    ap.add_argument("--no-slo", action="store_true",
                    help="skip the SLO burn-rate engine")
    args = ap.parse_args()

    if not args.idle:
        # must precede any jax import on this box (sitecustomize pins axon)
        from gochugaru_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()

    from gochugaru_tpu.utils import slo as slo_mod
    from gochugaru_tpu.utils import trace
    from gochugaru_tpu.utils.telemetry import TelemetryServer

    trace.configure(sample_rate=args.sample_rate, slow_threshold_s=0.1)
    recorder = trace.install_recorder(
        trace.FlightRecorder(incident_dir=args.incident_dir)
    )
    # install_engine, not a bare constructor: the process-global slot is
    # what enforces one evaluator per process and what the telemetry
    # endpoints' closed-engine fallback resolves through
    slo = None if args.no_slo else slo_mod.install_engine(slo_mod.SLOEngine())
    srv = TelemetryServer(
        port=args.port, host=args.host, slo=slo, recorder=recorder
    )
    print(f"READY url={srv.url}", flush=True)

    client = ctx = rs = None
    if not args.idle:
        from gochugaru_tpu import consistency, rel
        from gochugaru_tpu.client import new_tpu_evaluator, with_latency_mode
        from gochugaru_tpu.utils.context import background

        client = new_tpu_evaluator(with_latency_mode())
        ctx = background()
        client.write_schema(ctx, """
definition user {}
definition doc { relation reader: user  permission read = reader }
""")
        txn = rel.Txn()
        for i in range(32):
            txn.create(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i}"))
        client.write(ctx, txn)
        rs = [
            rel.must_from_triple(f"doc:d{i % 32}", "read", f"user:u{(i * 7) % 32}")
            for i in range(16)
        ]
        for _ in range(max(args.checks // 16, 1)):
            client.check(ctx, consistency.full(), *rs)
        print(f"# demo world ready, {args.checks} checks traced", file=sys.stderr)

    try:
        while True:
            time.sleep(2.0)
            if client is not None:
                client.check(ctx, consistency.full(), *rs)  # keep numbers moving
    except KeyboardInterrupt:
        pass
    finally:
        if slo is not None:
            slo.close()
        srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

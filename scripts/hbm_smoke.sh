#!/usr/bin/env bash
# HBM-lean smoke: packed-vs-unpacked parity + bytes-reduction bar on a
# small world, CI-runnable.  Builds the config-2-shaped world twice —
# flat_packed=True vs the unpacked parity oracle — asserts bit-for-bit
# dispatch equality over a mixed batch (throughput path AND the pinned
# latency tier), asserts the resident-table-bytes reduction clears the
# smoke bar, then serves an owner-routed partitioned batch off the
# PACKED layout and asserts it matches too.  Prints HBM-SMOKE-OK on
# success, mirroring chaos/telemetry/partition smokes.  Emits one JSON
# metric line for benchmarks/run_all.py.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import sys
import time

import numpy as np

from gochugaru_tpu.utils.platform import force_cpu_platform

force_cpu_platform(8)

sys.path.insert(0, ".")
from bench import build_world
from benchmarks.common import est_bytes_per_check, table_bytes
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.parallel import ShardedEngine, make_mesh

t0 = time.time()
# the small-world bar is looser than bench7's 2.5x at config 3: pow2
# padding floors dominate tiny tables — the smoke guards the MECHANISM
# (packing engaged, bytes strictly shrink by a sane margin), the full
# bar lives in benchmarks/bench7_hbm.py
SMOKE_BYTES_BAR = 1.5
NOWUS = 1_700_000_000_000_000

cs, snap, users, repos, slot = build_world(n_repos=1500, n_users=400)

eng_p = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_packed=True))
eng_u = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_packed=False))
ds_p = eng_p.prepare(snap)
ds_u = eng_u.prepare(snap)
assert ds_p.flat_meta.packed, "packing did not engage"
assert ds_p.flat_meta.packed_off, "offset packing did not engage"
assert not ds_u.flat_meta.packed

bp, bu = table_bytes(ds_p), table_bytes(ds_u)
reduction = bu / max(bp, 1)
assert reduction >= SMOKE_BYTES_BAR, (
    f"bytes reduction {reduction:.2f}x under the smoke bar"
    f" {SMOKE_BYTES_BAR}x ({bu} -> {bp})"
)
print(f"bytes: {bu} -> {bp} ({reduction:.2f}x)", file=sys.stderr)

rng = np.random.default_rng(3)
B = 8192
q_res = rng.choice(repos, B).astype(np.int32)
q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
q_subj = rng.choice(users, B).astype(np.int32)

d0, p0, o0 = eng_u.check_columns(ds_u, q_res, q_perm, q_subj, now_us=NOWUS)
d1, p1, o1 = eng_p.check_columns(ds_p, q_res, q_perm, q_subj, now_us=NOWUS)
assert np.array_equal(d0, d1) and np.array_equal(p0, p1)
assert np.array_equal(o0, o1)
assert 0 < int(d1.sum()) < B
print(f"throughput-path parity: {B} checks (granted={int(d1.sum())})",
      file=sys.stderr)

# pinned latency tier serves the packed layout identically
SB = 1024
dl, pl, ol = eng_p.check_columns_latency(
    ds_p, q_res[:SB].copy(), q_perm[:SB].copy(), q_subj[:SB].copy(),
    now_us=NOWUS,
)
assert np.array_equal(dl, d0[:SB]) and np.array_equal(pl, p0[:SB])
print("latency-tier parity: ok", file=sys.stderr)

# owner-routed partitioned serve off the PACKED layout
M = 2
sharded = ShardedEngine(cs, make_mesh(1, M), EngineConfig.for_schema(
    cs, flat_packed=True
))
ds_r = sharded.prepare_snapshot_partitioned(snap)
assert ds_r.flat_meta is not None and ds_r.flat_meta.packed
d2, p2, o2 = sharded.check_columns(ds_r, q_res, q_perm, q_subj, now_us=NOWUS)
assert np.array_equal(d0, np.asarray(d2)) and np.array_equal(p0, np.asarray(p2))
assert np.array_equal(o0, np.asarray(o2))
print(f"routed partitioned parity on packed tables: ok", file=sys.stderr)

print(json.dumps({
    "metric": "hbm_smoke", "value": round(reduction, 2),
    "unit": "x bytes reduction",
    "edges": int(snap.num_edges), "batch": B,
    "table_bytes_packed": bp, "table_bytes_unpacked": bu,
    "bytes_per_check": round(est_bytes_per_check(ds_p), 1),
    "granted": int(d1.sum()), "wall_s": round(time.time() - t0, 1),
}))
EOF

echo "HBM-SMOKE-OK"

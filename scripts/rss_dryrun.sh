#!/usr/bin/env bash
# Host-sharded build memory gate: the 2-process CPU dryrun must build
# its feed-partitioned tables in <= 60% of the single-process
# build-full-then-stack RSS at the same world, with the partitioned
# tables bitwise-identical to the pre-PR builder (parity child).
#
# Usage: scripts/rss_dryrun.sh [edges] [processes] [max_ratio]
#
# Prints RSS-BASELINE / PARITY-OK / RSS-OK / RSS-SUMMARY lines
# (parallel/multihost.py rss_dryrun); exits non-zero when the ratio
# bar is missed or any child fails.  Wired as a slow-marked test
# (tests/test_rss_dryrun.py) so tier-1 stays fast.
set -euo pipefail
cd "$(dirname "$0")/.."

EDGES="${1:-1000000}"
PROCS="${2:-2}"
MAX_RATIO="${3:-0.6}"

exec env JAX_PLATFORMS=cpu python -m gochugaru_tpu.parallel.multihost \
    --rss --edges "$EDGES" --processes "$PROCS" --max-ratio "$MAX_RATIO"

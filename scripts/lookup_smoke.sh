#!/usr/bin/env bash
# Lookup smoke: the frontier-SpMV lookup surface end-to-end on a small
# world, CI-runnable.  Asserts (1) host-walker parity of the device
# frontier path for LookupResources AND LookupSubjects, (2) a cursor-
# paginated multi-thousand-resource answer reassembles exactly (no
# dup/lost IDs across pages, resume mid-stream), and (3) the bucket-
# sharded owner-routed hop path matches the single-chip answer.  Prints
# LOOKUP-SMOKE-OK on success, mirroring the chaos/telemetry/partition/
# hbm smokes.  Emits one JSON metric line for benchmarks/run_all.py.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import sys
import time

import numpy as np

from gochugaru_tpu.utils.platform import force_cpu_platform

force_cpu_platform(8)

sys.path.insert(0, ".")
from benchmarks.bench3_docs import EPOCH
from gochugaru_tpu.engine import lookup as lm
from gochugaru_tpu.engine import spmv
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.oracle import SnapshotOracle
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.parallel import ShardedEngine, make_mesh
from gochugaru_tpu.schema import compile_schema, parse_schema
from gochugaru_tpu.store.interner import Interner
from gochugaru_tpu.store.snapshot import build_snapshot_from_columns

t0 = time.time()
# a doc-style world big enough for a >10k-resource answer: one team
# userset viewing many docs through a folder tree
SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition folder {
    relation parent: folder
    relation viewer: user | group#member
    permission view = viewer + parent->view
}
definition document {
    relation folder: folder
    relation viewer: user | group#member
    permission view = viewer + folder->view
}
"""
cs = compile_schema(parse_schema(SCHEMA))
interner = Interner()
rng = np.random.default_rng(5)
N_DOCS, N_FOLDERS = 30_000, 600
users = np.array([interner.node("user", f"u{i}") for i in range(300)])
groups = np.array([interner.node("group", f"g{i}") for i in range(8)])
folders = np.array(
    [interner.node("folder", f"f{i}") for i in range(N_FOLDERS)]
)
docs = np.array([interner.node("document", f"d{i}") for i in range(N_DOCS)])
slot = cs.slot_of_name
res, rl, sub, sr = [], [], [], []


def bulk(r, l, s, srl):
    res.append(np.asarray(r, np.int64))
    rl.append(np.full(len(r), l, np.int64))
    sub.append(np.asarray(s, np.int64))
    sr.append(np.full(len(r), srl, np.int64))


# g0 contains g1's members plus direct users; root folder viewed by g0
bulk(groups[:4], slot["member"], groups[1:5], slot["member"])
gm = np.repeat(groups, 6)
bulk(gm, slot["member"], rng.choice(users, gm.shape[0]), -1)
f_idx = np.arange(1, N_FOLDERS)
bulk(folders[f_idx], slot["parent"], folders[(f_idx - 1) // 8], -1)
bulk(folders[:1], slot["viewer"], groups[:1], slot["member"])
bulk(docs, slot["folder"], rng.choice(folders, N_DOCS), -1)
bulk(docs[: N_DOCS // 10], slot["viewer"],
     rng.choice(users, N_DOCS // 10), -1)
snap = build_snapshot_from_columns(
    1, cs, interner,
    res=np.concatenate(res), rel=np.concatenate(rl),
    subj=np.concatenate(sub), srel=np.concatenate(sr), epoch_us=EPOCH,
)
oracle = SnapshotOracle(snap, {})
engine = DeviceEngine(cs)
dsnap = engine.prepare(snap)
assert spmv.frontier_ok(engine, dsnap), "frontier path must serve"

# (1) host-walker parity, both directions
walker = DeviceEngine(cs, EngineConfig.for_schema(cs, flat_rev_index=False))
wds = walker.prepare(snap)
checked = 0
for u in [interner.key_of(int(x))[1] for x in users[:6]]:
    got = lm.lookup_resources_device(
        engine, dsnap, "document", "view", "user", u,
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    ref = lm.lookup_resources_device(
        walker, wds, "document", "view", "user", u,
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    assert got == ref, f"walker mismatch for user {u}"
    checked += len(got)
for d in [interner.key_of(int(x))[1] for x in docs[:4]]:
    got = lm.lookup_subjects_device(
        engine, dsnap, "document", d, "view", "user",
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    ref = lm.lookup_subjects_device(
        walker, wds, "document", d, "view", "user",
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    assert got == ref
print(f"walker parity: ok ({checked} results compared)", file=sys.stderr)

# (2) a member of g1 reaches the whole root-folder subtree through the
# nested-group + arrow chain: paginated reassembly must be exact
member = None
for x in users:
    uid = interner.key_of(int(x))[1]
    full = lm.lookup_resources_device(
        engine, dsnap, "document", "view", "user", uid,
        now_us=EPOCH, oracle_factory=lambda: oracle,
    )
    if len(full) > 10_000:
        member = (uid, full)
        break
assert member is not None, "no subject with a >10k-resource answer"
uid, full = member
out, pages, cursor = [], 0, None
while True:
    ids, cursor = lm.lookup_resources_page(
        engine, dsnap, "document", "view", "user", uid,
        page_size=1_024, cursor=cursor, now_us=EPOCH,
        oracle_factory=lambda: oracle,
    )
    out.extend(ids)
    pages += 1
    if cursor is None:
        break
assert len(out) == len(set(out)), "duplicate ids across pages"
assert sorted(out) == full, "paginated reassembly diverged"
print(f"paginated {len(out)} resources over {pages} pages: exact",
      file=sys.stderr)

# (3) owner-routed sharded hops match single-chip
sh = ShardedEngine(cs, make_mesh(1, 2))
sds = sh.prepare(snap)
assert sds.flat_meta.has_rev and spmv.frontier_ok(sh, sds)
got = lm.lookup_resources_device(
    sh, sds, "document", "view", "user", uid,
    now_us=EPOCH, oracle_factory=lambda: oracle,
)
assert got == full, "routed-shard lookup diverged from single-chip"
print("routed-shard parity: ok", file=sys.stderr)

print(json.dumps({
    "metric": "lookup_smoke", "value": len(out), "unit": "paged resources",
    "vs_baseline": 1.0, "edges": int(snap.num_edges), "batch": pages,
    "wall_s": round(time.time() - t0, 1),
}))
EOF

echo "LOOKUP-SMOKE-OK"

#!/usr/bin/env bash
# Partitioned-serving smoke: a 2-shard single-process proxy builds the
# bucket-partitioned feed WITH the fold engaged, asserts bitwise parity
# of the merged stacked tables against the full build-then-stack
# derivation, then serves an owner-routed batch off the partitioned
# placement and asserts it matches the single-chip engine exactly.
# Prints PARTITION-SMOKE-OK on success — the CI-runnable proof the
# partitioned serve path answers checks, mirroring chaos/telemetry
# smokes.  Emits one JSON metric line for benchmarks/run_all.py.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python - <<'EOF'
import json
import sys
import time

import numpy as np

from gochugaru_tpu.utils.platform import force_cpu_platform

force_cpu_platform(8)

sys.path.insert(0, ".")
from bench import build_world
from gochugaru_tpu.engine.device import DeviceEngine
from gochugaru_tpu.engine.flat import build_flat_arrays_sharded
from gochugaru_tpu.engine.partition import ShardSlices, partition_feed
from gochugaru_tpu.engine.plan import EngineConfig
from gochugaru_tpu.parallel import ShardedEngine, make_mesh

t0 = time.time()
M = 2
cs, snap, users, repos, slot = build_world(n_repos=1500, n_users=400)
cfg = EngineConfig.for_schema(cs)
eng = ShardedEngine(cs, make_mesh(1, M), cfg)


def raw_cols():
    from gochugaru_tpu.engine.partition import snapshot_raw_columns

    return snapshot_raw_columns(snap, copy=True)


# 1. bitwise parity of the partitioned fold/rc build vs the reference
# (flat_rev_index=False: the feed declines the reverse lookup index —
# rv ownership is keyed by the subject hash, not the primary bucket —
# so the reference builds without it too)
legacy = EngineConfig.for_schema(
    cs, flat_partition_build=False, flat_rev_index=False
)
ref_arrays, ref_meta, _f, _c = build_flat_arrays_sharded(
    snap, legacy, M, plan=eng.plan
)
assert ref_meta.fold_pairs, "smoke world must fold"
part = partition_feed(
    snap.revision, cs, snap.interner, raw_cols(), cfg, M,
    contexts=snap.contexts, epoch_us=snap.epoch_us, plan=eng.plan,
)
assert set(part.arrays) == set(ref_arrays)
for k in sorted(ref_arrays):
    got = part.arrays[k]
    got = got.to_full() if isinstance(got, ShardSlices) else got
    assert np.array_equal(got, ref_arrays[k]), f"table {k} differs"
assert part.meta == ref_meta
print("parity: fold/rc partitioned build bitwise-identical", file=sys.stderr)

# 2. owner-routed serve matches the single-chip engine
routed = partition_feed(
    snap.revision, cs, snap.interner, raw_cols(), cfg, M,
    contexts=snap.contexts, epoch_us=snap.epoch_us, plan=eng.plan,
    serve="routed",
)
dsnap = eng.prepare_partitioned(routed)
single = DeviceEngine(cs, cfg)
ds0 = single.prepare(snap)
rng = np.random.default_rng(3)
B = 4096
q_res = rng.choice(repos, B).astype(np.int32)
q_perm = rng.choice(np.array([slot["read"], slot["admin"]], np.int32), B)
q_subj = rng.choice(users, B).astype(np.int32)
NOWUS = 1_700_000_000_000_000
d0, p0, o0 = single.check_columns(ds0, q_res, q_perm, q_subj, now_us=NOWUS)
d1, p1, o1 = eng.check_columns(dsnap, q_res, q_perm, q_subj, now_us=NOWUS)
assert np.array_equal(d0, d1) and np.array_equal(p0, p1)
assert np.array_equal(o0, o1)
assert 0 < int(d1.sum()) < B
print(
    f"routed serve: {B} checks match single-chip"
    f" (granted={int(d1.sum())})", file=sys.stderr,
)
print(json.dumps({
    "metric": "partition_smoke", "value": 1, "unit": "ok",
    "edges": int(snap.num_edges), "shards": M, "batch": B,
    "granted": int(d1.sum()), "wall_s": round(time.time() - t0, 1),
}))
EOF

echo "PARTITION-SMOKE-OK"

#!/usr/bin/env bash
# Performance-attribution smoke: the perf ledger (utils/perf.py) closes
# end to end on the CPU proxy —
#   1. the roofline microbench measures a bandwidth ceiling and caches
#      it per backend fingerprint;
#   2. /perf serves the ledger: gathered-bytes model (per level / per
#      table), captured cost_analysis entries (latency pin at pin time,
#      batch-path program realized via ?compile=1), pad-waste stats,
#      the cached roofline, and the last wall-time window;
#   3. the bench columns (achieved_gbps / roofline_frac / pad_fraction)
#      derive from the measured ceiling;
#   4. the wall-time ledger closes (buckets sum to the window) under
#      real serving traffic;
#   5. an incident bundle carries the perf context state.
# Prints PERF-SMOKE-OK on success — the CI-runnable proof, mirroring
# scripts/slo_smoke.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${PERF_SMOKE_TIMEOUT_S:=420}"

ROOFLINE_TMP="$(mktemp -u /tmp/gochugaru_roofline_smoke_XXXX.json)"
trap 'rm -f "$ROOFLINE_TMP"' EXIT

timeout -k 10 "${PERF_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu \
  GOCHUGARU_ROOFLINE_CACHE_PATH="$ROOFLINE_TMP" python - <<'EOF'
import json
import os
import tempfile
import time
import urllib.request

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_latency_mode, with_telemetry,
)
from gochugaru_tpu.utils import metrics, perf, trace
from gochugaru_tpu.utils.context import background

D = tempfile.mkdtemp(prefix="gochugaru_perf_incidents_")
m = metrics.default
c = new_tpu_evaluator(
    with_latency_mode(), with_telemetry(port=0, incident_dir=D)
)
url = c.telemetry.url
ctx = background()
c.write_schema(ctx, """
definition user {}
definition doc { relation reader: user  permission read = reader }
""")
txn = rel.Txn()
for i in range(256):
    txn.create(rel.must_from_triple(f"doc:d{i}", "reader", f"user:u{i % 32}"))
c.write(ctx, txn)

# -- 1: the roofline microbench (fresh cache path → a real measurement) --
bw = perf.measure_bandwidth(size_mb=16, reps=3)
assert bw["gbps"] > 0 and not bw["cached"], bw
bw2 = perf.measure_bandwidth()
assert bw2["cached"], "second read must hit the fingerprint cache"
print(f"# roofline: {bw['gbps']} GB/s ({bw['fingerprint']})")

# -- pin + batch-path programs into the cost ledger ----------------------
qs = [rel.must_from_triple(f"doc:d{i}", "read", f"user:u{i % 32}")
      for i in range(64)]
for _ in range(3):
    got = c.check(ctx, consistency.full(), *qs)
assert all(bool(v) for v in got), got
big = [rel.must_from_triple(f"doc:d{i % 256}", "read", f"user:u{i % 32}")
       for i in range(8192)]
c.check(ctx, consistency.full(), *big)  # > top tier → throughput path
kinds = {e["kind"] for e in perf.cost_entries()}
assert "latency_pin" in kinds, kinds
assert "batch" in kinds, kinds  # pending thunk registered at cache time

# -- 4: the wall-time ledger closes under serving traffic ----------------
ledger = perf.WallLedger().start()
with c.with_serving() as h:
    futs = [h.submit(ctx, *qs[:16], client_id=w % 4) for w in range(64)]
    for f in futs:
        f.result(timeout=60.0)
wall = ledger.stop()
assert wall["closure_frac"] >= 0.95, wall
assert wall["dropped"] == 0 and wall["named_frac"] > 0, wall
assert wall["seconds"]["kernel"] > 0, wall
print("# wall ledger: " + " ".join(
    f"{b}={wall['fracs'][b]:.1%}" for b in (*perf.WALL_BUCKETS, "idle")
    if wall["fracs"][b] > 0) + f" closure={wall['closure_frac']:.1%}")

# -- 2: /perf serves the ledger (+ ?compile=1 realizes the batch thunk) --
def get(path):
    with urllib.request.urlopen(url + path, timeout=60) as r:
        return json.loads(r.read().decode())

rep = get("/perf?compile=1")
assert rep["bytes_model"] and rep["bytes_model"]["total"] > 0, rep
assert rep["bytes_model"]["per_table"], rep
batch_entries = [e for e in rep["cost"] if e["kind"] == "batch"]
assert batch_entries and not any(e.get("pending") for e in batch_entries), (
    "batch-path cost thunk not realized by ?compile=1"
)
realized = [e for e in rep["cost"]
            if e.get("flops") is not None or e.get("unavailable")]
assert realized, rep["cost"]
assert rep["pad"]["total_lanes"] > 0, rep["pad"]
assert rep["roofline"] and rep["roofline"]["gbps"] > 0, rep["roofline"]
assert rep["wall"] and rep["wall"]["closure_frac"] >= 0.95, rep["wall"]
print(f"# /perf: {len(rep['cost'])} cost entries "
      f"(batch flops={batch_entries[0].get('flops')}), "
      f"pad_fraction={rep['pad']['pad_fraction']}")

# -- 3: the bench columns derive from the measured ceiling ---------------
from benchmarks.common import roofline_columns

snap = c.store.snapshot_for(consistency.full())
eng = c._engine_for(snap)
ds = c._dsnap_for(eng, snap)
cols = roofline_columns(1_000_000.0, dsnap=ds)
for k in ("bytes_per_check", "achieved_gbps", "roofline_gbps",
          "roofline_frac"):
    assert k in cols, cols
assert cols["roofline_gbps"] == bw["gbps"], (cols, bw)
assert cols["achieved_gbps"] > 0 and 0 < cols["roofline_frac"] < 1, cols
print(f"# bench columns: {cols}")

# -- 5: incident bundles carry the perf context --------------------------
iid = trace.trigger_incident("perf.smoke")
assert iid, "incident did not fire"
c.recorder.flush()
bundle = None
t0 = time.time()
while bundle is None and time.time() - t0 < 20:
    hits = [f for f in os.listdir(D) if "perf.smoke" in f]
    if hits:
        bundle = os.path.join(D, sorted(hits)[0])
        break
    time.sleep(0.2)
assert bundle, f"no perf.smoke bundle under {D}"
head = json.loads(open(bundle).readline())
pctx = next((v for k, v in head["context"].items() if k.startswith("perf")),
            None)
assert pctx, head["context"].keys()
assert pctx["bytes_per_check"] and pctx["pad"]["total_lanes"] > 0, pctx
assert pctx["roofline_gbps"], pctx
print(f"# incident context: bytes/check={pctx['bytes_per_check']} "
      f"roofline={pctx['roofline_gbps']} GB/s")

print(json.dumps({
    "metric": "perf_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "roofline_gbps": bw["gbps"],
    "bytes_per_check": rep["bytes_model"]["total"],
    "pad_fraction": rep["pad"]["pad_fraction"],
    "wall_closure_frac": wall["closure_frac"],
    "cost_entries": len(rep["cost"]),
    "note": "microbench + /perf ledger + bench columns + wall closure"
            " + incident perf context",
}))
print(f"PERF-SMOKE-OK gbps={bw['gbps']} "
      f"bytes_per_check={rep['bytes_model']['total']} "
      f"wall_closure={wall['closure_frac']}")
EOF
rc=$?
exit "$rc"

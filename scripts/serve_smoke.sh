#!/usr/bin/env bash
# Serving smoke: N concurrent submitters through the continuous-batching
# front-end (gochugaru_tpu/serve/), oracle parity asserted on EVERY
# coalesced answer, and the queue-depth shed path exercised for real (a
# tiny queue_max + a burst must raise ShedError and the retry envelope
# must absorb it).  Prints SERVE-SMOKE-OK on success — the CI-runnable
# proof the serving layer answers correctly under concurrency, mirroring
# scripts/partition_smoke.sh / lookup_smoke.sh.
#
# Usage:
#   scripts/serve_smoke.sh                       # 8 submitters, 12 rounds
#   SERVE_SMOKE_SUBMITTERS=16 scripts/serve_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

: "${SERVE_SMOKE_SUBMITTERS:=8}"
: "${SERVE_SMOKE_ROUNDS:=12}"
: "${SERVE_SMOKE_TIMEOUT_S:=420}"

export SERVE_SMOKE_SUBMITTERS SERVE_SMOKE_ROUNDS

timeout -k 10 "${SERVE_SMOKE_TIMEOUT_S}" env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import threading

import numpy as np

from gochugaru_tpu import consistency, rel
from gochugaru_tpu.client import (
    new_tpu_evaluator, with_host_only_evaluation, with_latency_mode,
    with_store,
)
from gochugaru_tpu.serve import ServeConfig
from gochugaru_tpu.utils import metrics
from gochugaru_tpu.utils.context import background
from gochugaru_tpu.utils.errors import ShedError

N = int(os.environ.get("SERVE_SMOKE_SUBMITTERS", "8"))
ROUNDS = int(os.environ.get("SERVE_SMOKE_ROUNDS", "12"))

c = new_tpu_evaluator(with_latency_mode())
ctx = background()
c.write_schema(ctx, """
definition user {}
definition org { relation admin: user  relation member: user }
definition repo {
    relation org: org
    relation reader: user
    permission admin = org->admin
    permission read = reader + admin + org->member
}
""")
rng = np.random.default_rng(20260804)
txn = rel.Txn()
for i in range(150):
    txn.touch(rel.must_from_triple(
        f"repo:r{i}", "reader", f"user:u{rng.integers(80)}"))
    txn.touch(rel.must_from_triple(f"repo:r{i}", "org", f"org:o{i % 4}"))
for o in range(4):
    txn.touch(rel.must_from_triple(f"org:o{o}", "admin", f"user:u{o}"))
    txn.touch(rel.must_from_triple(f"org:o{o}", "member", f"user:u{o + 20}"))
c.write(ctx, txn)
oracle = new_tpu_evaluator(with_host_only_evaluation(), with_store(c.store))
cs = consistency.full()
m = metrics.default

# -- phase 1: concurrent submitters, oracle parity on every answer ------
mismatches = []
with c.with_serving() as h:
    def worker(w):
        lr = np.random.default_rng(1000 + w)
        for _ in range(ROUNDS):
            qs = [rel.must_from_triple(
                f"repo:r{lr.integers(150)}", "read",
                f"user:u{lr.integers(80)}") for _ in range(6)]
            got = h.check(ctx.with_timeout(60.0), *qs, client_id=w)
            want = oracle.check(ctx, cs, *qs)
            if list(got) != list(want):
                mismatches.append((w, qs))
    ts = [threading.Thread(target=worker, args=(w,)) for w in range(N)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
assert not mismatches, f"{len(mismatches)} coalesced answers wrong"
subs = m.counter("serve.submissions")
bats = m.counter("serve.batches")
assert bats >= 1 and subs == N * ROUNDS, (subs, bats)
print(f"# parity: {N} submitters x {ROUNDS} rounds, "
      f"{int(subs)} submissions -> {int(bats)} formed batches, "
      "every answer == oracle")

# -- phase 2: the shed path (tiny queue, direct submits must shed) ------
sheds0 = m.counter("serve.sheds")
with c.with_serving(config=ServeConfig(queue_max=32,
                                       hold_max_s=0.05)) as h2:
    raised = 0
    futs = []
    for i in range(40):
        qs = [rel.must_from_triple(f"repo:r{i}", "read", "user:u0")] * 4
        try:
            futs.append(h2.submit(ctx, *qs, client_id=i))
        except ShedError:
            raised += 1
    for f in futs:
        f.result(timeout=60.0)
    assert raised >= 1, "queue_max=32 never shed under a 160-check burst"
    # and the blocking surface absorbs sheds through the retry envelope
    got = h2.check(ctx.with_timeout(60.0),
                   rel.must_from_triple("repo:r0", "read", "user:u0"))
assert m.counter("serve.sheds") > sheds0
print(f"# shed path: {raised} direct submissions shed (ShedError), "
      "blocking surface retried through the envelope")
import json
print(json.dumps({
    "metric": "serve_smoke", "value": 1, "unit": "ok", "vs_baseline": 1.0,
    "submitters": N, "rounds": ROUNDS, "submissions": int(subs),
    "batches": int(bats),
    "sheds": int(m.counter("serve.sheds") - sheds0),
    "note": "concurrent oracle parity + queue-depth shed path",
}))
print(f"SERVE-SMOKE-OK submitters={N} rounds={ROUNDS} "
      f"batches={int(bats)} sheds={int(m.counter('serve.sheds') - sheds0)}")
EOF
rc=$?
exit "$rc"
